"""CLI/programmatic engine arguments -> EngineConfig.

Reference: vllm/engine/arg_utils.py (``EngineArgs`` mirrors every config
field as a --kebab-case flag; the fork's TKNP flags at arg_utils.py:339).
"""

import argparse
from dataclasses import dataclass, fields
from typing import Optional

from vllm_distributed_tpu.config import (CacheConfig, DeviceConfig,
                                         EngineConfig,
                                         FaultToleranceConfig,
                                         KVEventsConfig,
                                         KVTransferConfig, LoadConfig,
                                         LoRAConfig, ModelConfig,
                                         ObservabilityConfig,
                                         ParallelConfig, SchedulerConfig,
                                         SpeculativeConfig)


@dataclass
class EngineArgs:
    model: str = "meta-llama/Meta-Llama-3-8B"
    tokenizer: Optional[str] = None
    skip_tokenizer_init: bool = False
    trust_remote_code: bool = False
    dtype: str = "bfloat16"
    quantization: Optional[str] = None
    seed: int = 0
    max_model_len: Optional[int] = None

    block_size: int = 16
    kv_cache_dtype: str = "auto"
    gpu_memory_utilization: float = 0.90
    num_gpu_blocks_override: Optional[int] = None
    enable_prefix_caching: bool = True
    swap_space: int = 0  # accepted for CLI parity; unused on TPU

    tensor_parallel_size: int = 1
    pipeline_parallel_size: int = 1
    data_parallel_size: int = 1
    data_parallel_mode: str = "engine"  # engine replicas | mesh axis
    data_parallel_coordinator: bool = False
    token_parallel_size: int = 1
    enable_expert_parallel: bool = False
    enable_sequence_parallel: bool = False
    num_redundant_experts: int = 0
    multiprocess_engine_core: bool = False
    # Multi-host SPMD: this engine process's place in the pod.
    num_hosts: int = 1
    host_rank: int = 0
    coordinator_address: Optional[str] = None

    # Multi-LoRA serving.
    enable_lora: bool = False
    max_loras: int = 4
    max_lora_rank: int = 16

    max_num_batched_tokens: int = 8192
    max_num_seqs: int = 256
    enable_chunked_prefill: bool = True
    long_prefill_token_threshold: int = 0
    scheduling_policy: str = "fcfs"
    num_scheduler_steps: int = 1
    encoder_cache_budget: int = 8192
    # Overlap host scheduling with device execution (depth-2 in-flight
    # batch pipeline; auto-off with spec decode / PP / multi-step /
    # KV connectors — see SchedulerConfig.async_scheduling).
    async_scheduling: bool = False

    device: str = "auto"
    load_format: str = "auto"
    sharded_state_path: Optional[str] = None

    speculative_method: Optional[str] = None
    num_speculative_tokens: int = 0
    speculative_model: Optional[str] = None
    speculative_draft_window: int = 32

    kv_connector: Optional[str] = None
    kv_role: Optional[str] = None
    kv_connector_extra_config: Optional[dict] = None

    otlp_traces_endpoint: Optional[str] = None

    # Fault tolerance: remote-KV watchdog + engine health monitor +
    # restart supervisor (0 attempts = death stays terminal).
    kv_pull_timeout_s: float = 120.0
    kv_pull_max_retries: int = 1
    heartbeat_interval_s: float = 1.0
    heartbeat_timeout_s: float = 300.0
    restart_max_attempts: int = 3
    restart_window_s: float = 300.0
    restart_backoff_base_s: float = 0.5
    restart_backoff_max_s: float = 30.0
    replica_probe_interval_s: float = 10.0

    # KV cache event publishing (external prefix-aware routers).
    enable_kv_cache_events: bool = False
    kv_events_endpoint: str = "tcp://127.0.0.1:5557"
    kv_events_replay_endpoint: Optional[str] = None

    def create_engine_config(self) -> EngineConfig:
        model_config = ModelConfig(
            model=self.model,
            tokenizer=self.tokenizer,
            skip_tokenizer_init=self.skip_tokenizer_init,
            trust_remote_code=self.trust_remote_code,
            dtype=self.dtype,
            quantization=self.quantization,
            seed=self.seed,
            max_model_len=self.max_model_len,
        )
        model_config.maybe_load_hf_config()
        return EngineConfig(
            model_config=model_config,
            cache_config=CacheConfig(
                block_size=self.block_size,
                gpu_memory_utilization=self.gpu_memory_utilization,
                num_gpu_blocks_override=self.num_gpu_blocks_override,
                enable_prefix_caching=self.enable_prefix_caching,
                cache_dtype=self.kv_cache_dtype,
            ),
            parallel_config=ParallelConfig(
                tensor_parallel_size=self.tensor_parallel_size,
                pipeline_parallel_size=self.pipeline_parallel_size,
                data_parallel_size=self.data_parallel_size,
                data_parallel_mode=self.data_parallel_mode,
                data_parallel_coordinator=self.data_parallel_coordinator,
                token_parallel_size=self.token_parallel_size,
                enable_expert_parallel=self.enable_expert_parallel,
                enable_sequence_parallel=self.enable_sequence_parallel,
                num_redundant_experts=self.num_redundant_experts,
                multiprocess_engine_core=self.multiprocess_engine_core,
                num_hosts=self.num_hosts,
                host_rank=self.host_rank,
                coordinator_address=self.coordinator_address,
            ),
            scheduler_config=SchedulerConfig(
                max_num_batched_tokens=self.max_num_batched_tokens,
                max_num_seqs=self.max_num_seqs,
                max_model_len=model_config.max_model_len or 8192,
                enable_chunked_prefill=self.enable_chunked_prefill,
                long_prefill_token_threshold=self.
                long_prefill_token_threshold,
                policy=self.scheduling_policy,
                num_scheduler_steps=self.num_scheduler_steps,
                encoder_cache_budget=self.encoder_cache_budget,
                async_scheduling=self.async_scheduling,
            ),
            device_config=DeviceConfig(device=self.device),
            load_config=LoadConfig(
                load_format=self.load_format,
                sharded_state_path=self.sharded_state_path),
            speculative_config=SpeculativeConfig(
                method=self.speculative_method,
                num_speculative_tokens=self.num_speculative_tokens,
                model=self.speculative_model,
                draft_window=self.speculative_draft_window,
            ),
            kv_transfer_config=KVTransferConfig(
                kv_connector=self.kv_connector,
                kv_role=self.kv_role,
                kv_connector_extra_config=(
                    self.kv_connector_extra_config or {}),
            ),
            lora_config=LoRAConfig(
                enable_lora=self.enable_lora,
                max_loras=self.max_loras,
                max_lora_rank=self.max_lora_rank,
            ),
            kv_events_config=KVEventsConfig(
                enable_kv_cache_events=self.enable_kv_cache_events,
                endpoint=self.kv_events_endpoint,
                replay_endpoint=self.kv_events_replay_endpoint,
            ),
            observability_config=ObservabilityConfig(
                otlp_traces_endpoint=self.otlp_traces_endpoint),
            fault_tolerance_config=FaultToleranceConfig(
                kv_pull_timeout_s=self.kv_pull_timeout_s,
                kv_pull_max_retries=self.kv_pull_max_retries,
                heartbeat_interval_s=self.heartbeat_interval_s,
                heartbeat_timeout_s=self.heartbeat_timeout_s,
                restart_max_attempts=self.restart_max_attempts,
                restart_window_s=self.restart_window_s,
                restart_backoff_base_s=self.restart_backoff_base_s,
                restart_backoff_max_s=self.restart_backoff_max_s,
                replica_probe_interval_s=self.replica_probe_interval_s,
            ),
        )

    # ------------------------------------------------------------------
    @staticmethod
    def add_cli_args(parser: argparse.ArgumentParser) -> argparse.ArgumentParser:
        for f in fields(EngineArgs):
            name = "--" + f.name.replace("_", "-")
            # f.type may be the annotation object or its string form
            # depending on `from __future__ import annotations`.
            ts = f.type if isinstance(f.type, str) else str(f.type)
            if ts in ("bool", str(bool)) or "bool" in ts:
                parser.add_argument(name,
                                    action=argparse.BooleanOptionalAction,
                                    default=f.default)
            else:
                typ = str
                if "int" in ts:
                    typ = int
                elif "float" in ts:
                    typ = float
                parser.add_argument(name, type=typ, default=f.default)
        return parser

    @classmethod
    def from_cli_args(cls, args: argparse.Namespace) -> "EngineArgs":
        attrs = {f.name for f in fields(cls)}
        return cls(**{k: v for k, v in vars(args).items() if k in attrs})
