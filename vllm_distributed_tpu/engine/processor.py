"""Request pre-processing: tokenize + validate -> EngineCoreRequest.

Reference: vllm/v1/engine/processor.py (tokenization, validation; runs in
the client process, never on the device path).
"""

import json as json_module
import time
from typing import Optional, Union

from functools import lru_cache

from vllm_distributed_tpu.config import EngineConfig
from vllm_distributed_tpu.request import EngineCoreRequest
from vllm_distributed_tpu.sampling_params import SamplingParams


@lru_cache(maxsize=64)
def _validate_lora_path(path: str, max_rank: int) -> None:
    """Admission-time adapter check (cached): a bad path or oversized
    rank must 400 at the front-end, never surface inside the engine
    core's step path."""
    import json
    import os
    cfg_file = os.path.join(path, "adapter_config.json")
    if not os.path.isfile(cfg_file):
        raise ValueError(f"no adapter_config.json under {path!r}")
    with open(cfg_file) as f:
        rank = int(json.load(f)["r"])
    if rank > max_rank:
        raise ValueError(
            f"adapter rank {rank} exceeds max_lora_rank {max_rank}")
    if not any(os.path.exists(os.path.join(path, fname))
               for fname in ("adapter_model.safetensors",
                             "adapter_model.bin")):
        raise ValueError(f"no adapter weights under {path!r}")


@lru_cache(maxsize=256)
def _validate_grammar(pattern: str) -> None:
    """Admission-time grammar check, cached by pattern so repeated
    requests with the same schema don't recompile the DFA the core's
    manager also caches."""
    from vllm_distributed_tpu.structured_output.fsm import compile_regex
    compile_regex(pattern)


class Processor:

    def __init__(self, config: EngineConfig, tokenizer) -> None:
        self.config = config
        self.tokenizer = tokenizer
        from vllm_distributed_tpu.models.loader import (
            resolve_encoder_limits, resolve_encoder_only)
        self.is_encoder_only = resolve_encoder_only(config.model_config)
        self.is_cross_encoder, self.encoder_token_limit = \
            resolve_encoder_limits(config.model_config)
        self._score_num_labels = 0
        if self.is_cross_encoder:
            hf = config.model_config.maybe_load_hf_config()
            self._score_num_labels = int(getattr(hf, "num_labels", 2))
        # Encoder-decoder checkpoints REQUIRE an encoder payload: a
        # plain text request would cross-attend to whatever audio/
        # document states the reused batch row last held (cross-request
        # leakage). Mirrors the reference, which refuses enc-dec
        # requests without encoder input.
        self.cross_modality = None
        try:
            from vllm_distributed_tpu.models.registry import \
                resolve_architecture
            cls = resolve_architecture(
                config.model_config.maybe_load_hf_config())
            self.cross_modality = getattr(cls, "CROSS_MODALITY", None)
        except Exception:  # noqa: BLE001 - tokenizer-free toy configs
            pass
        # Per-INSTANCE memo (a class-level dict would collide across
        # engines serving different checkpoints in one process).
        self._enc_text_cache: dict = {}
        # Cached once: process_inputs sits on the per-request hot path
        # and must not re-read the environment per call.
        from vllm_distributed_tpu import trace_plane
        from vllm_distributed_tpu.metrics import events as ev
        self.trace_enabled = ev.trace_plane_enabled()
        self._mint_trace_ctx = trace_plane.mint_trace_ctx
        self.eos_token_id: Optional[int] = None
        if tokenizer is not None:
            self.eos_token_id = tokenizer.eos_token_id
        if self.eos_token_id is None:
            # Tokenizer-free runs still stop on the model's EOS
            # (reference: processor reads generation_config/hf_config).
            hf = config.model_config.maybe_load_hf_config()
            eos = getattr(hf, "eos_token_id", None)
            if isinstance(eos, (list, tuple)):
                eos = eos[0] if eos else None
            self.eos_token_id = eos

    def process_inputs(
        self,
        request_id: str,
        prompt: Union[str, list[int]],
        sampling_params: SamplingParams,
        arrival_time: Optional[float] = None,
        priority: int = 0,
        kv_transfer_params: Optional[dict] = None,
        lora_request: Optional[dict] = None,
        pooling_params: Optional[dict] = None,
        multi_modal_data: Optional[dict] = None,
        tenant: Optional[str] = None,
    ) -> EngineCoreRequest:
        if isinstance(prompt, str):
            assert self.tokenizer is not None, \
                "string prompts require a tokenizer"
            prompt_token_ids = self.tokenizer.encode(prompt)
        else:
            prompt_token_ids = list(prompt)
        if not prompt_token_ids:
            raise ValueError("empty prompt")
        mm_inputs = None
        if multi_modal_data:
            mm_inputs, prompt_token_ids = self._process_mm(
                multi_modal_data, prompt_token_ids)
        if self.cross_modality is not None and not any(
                inp.offset < 0 for inp in (mm_inputs or ())):
            kind = ("'audio'/'input_features'"
                    if self.cross_modality == "audio"
                    else "'encoder_text'/'encoder_input_ids'")
            raise ValueError(
                f"this encoder-decoder model requires an encoder input "
                f"({kind} in multi_modal_data); decoder-only requests "
                f"are not admissible")
        if self.is_encoder_only and pooling_params is None:
            raise ValueError(
                "this model is encoder-only: it serves embedding/"
                "scoring requests (LLM.encode / LLM.score / "
                "/v1/embeddings), not generation")
        if pooling_params is not None:
            ptype = pooling_params.get("type",
                                       "cls" if self.is_encoder_only
                                       else "last")
            if self.is_encoder_only:
                # The dense encoder pools any variant on-device; score
                # must be refused HERE for plain embedding checkpoints —
                # a runner-side raise would kill the engine core.
                if ptype not in ("cls", "mean", "last", "score"):
                    raise ValueError(f"unknown pooling type {ptype!r}")
                if ptype == "score" and not self.is_cross_encoder:
                    raise ValueError(
                        "score pooling needs a classification "
                        "checkpoint (e.g. BertForSequenceClassification)"
                        "; this model only embeds")
                if ptype == "score" and self._score_num_labels > 2:
                    # Which class means "relevant" is undefined for
                    # multi-label heads; reject instead of silently
                    # scoring an arbitrary column.
                    raise ValueError(
                        f"score pooling needs a 1- or 2-label "
                        f"classification head, this checkpoint has "
                        f"{self._score_num_labels} labels")
                clean = {"type": ptype}
                tt = pooling_params.get("token_type_ids")
                if tt is not None:
                    if len(tt) > len(prompt_token_ids):
                        raise ValueError(
                            "token_type_ids longer than the prompt")
                    clean["token_type_ids"] = [int(x) for x in tt]
                pooling_params = clean
            else:
                # Decoder pooling rides the causal step: only the final
                # prompt position's hidden state is exact under chunked
                # prefill (mean needs per-chunk accumulation).
                if ptype != "last":
                    raise ValueError(
                        "only 'last' pooling is supported on decoder "
                        "models (cls/mean pooling needs an encoder-only "
                        "arch)")
                pooling_params = {"type": "last"}
            # A pooling request never decodes: clamp so the scheduler's
            # fused multi-step burst (which never pools) can't claim it.
            sampling_params.max_tokens = 1
        if lora_request is not None:
            if not self.config.lora_config.enable_lora:
                raise ValueError(
                    "request selects a LoRA adapter but the engine was "
                    "started without enable_lora")
            try:
                _validate_lora_path(
                    str(lora_request["path"]),
                    self.config.lora_config.max_lora_rank)
            except (KeyError, OSError, TypeError,
                    json_module.JSONDecodeError) as e:
                raise ValueError(f"invalid lora_request: {e}") from e
        if sampling_params.structured is not None:
            # Reject uncompilable grammars at admission (client-side
            # error) instead of inside the engine core's busy loop.
            from vllm_distributed_tpu.structured_output.manager import \
                spec_to_regex
            try:
                _validate_grammar(spec_to_regex(
                    sampling_params.structured))
            except ValueError as e:
                raise ValueError(f"invalid structured spec: {e}") from e
        max_len = self.config.scheduler_config.max_model_len
        # Pooling requests generate nothing, so a prompt may fill the
        # whole window; generation needs at least one free position.
        limit = max_len if pooling_params is not None else max_len - 1
        if len(prompt_token_ids) > limit:
            raise ValueError(
                f"prompt ({len(prompt_token_ids)} tokens) is longer than "
                f"the maximum model length of {max_len}")
        if self.is_encoder_only:
            budget = self.config.scheduler_config.max_num_batched_tokens
            if len(prompt_token_ids) > budget:
                raise ValueError(
                    f"encoder prompt ({len(prompt_token_ids)} tokens) "
                    f"exceeds max_num_batched_tokens ({budget}): a "
                    f"bidirectional layer needs the whole sequence in "
                    f"one step")
            if (self.encoder_token_limit is not None
                    and len(prompt_token_ids) > self.encoder_token_limit):
                # e.g. RoBERTa's 514-row table holds 512 tokens (offset
                # 2); admitting more would silently alias positions.
                raise ValueError(
                    f"encoder prompt ({len(prompt_token_ids)} tokens) "
                    f"exceeds the model's position capacity "
                    f"({self.encoder_token_limit})")
        return EngineCoreRequest(
            request_id=request_id,
            prompt_token_ids=prompt_token_ids,
            sampling_params=sampling_params,
            eos_token_id=self.eos_token_id,
            arrival_time=arrival_time or time.time(),  # wallclock-ok
            priority=priority,
            tenant=tenant,
            kv_transfer_params=kv_transfer_params,
            lora_request=lora_request,
            pooling_params=pooling_params,
            mm_inputs=mm_inputs,
            # Minted at admission so every downstream event (router,
            # scheduler, disagg handoff, replay) shares one trace id.
            trace_ctx=(self._mint_trace_ctx(request_id)
                       if self.trace_enabled else None),
        )

    def _process_audio(self, multi_modal_data: dict,
                       prompt_token_ids: list[int]):
        """Whisper-family audio: run the front-end audio encoder at
        admission; the [frames, H] hidden states ride the request
        (offset=-1 marks a cross-attention payload, not prompt-row
        substitution). Reference: the transcription input path of
        serving_transcription.py + models/whisper.py."""
        import numpy as np

        from vllm_distributed_tpu.models.registry import \
            resolve_architecture
        from vllm_distributed_tpu.multimodal import MultiModalInput
        hf = self.config.model_config.maybe_load_hf_config()
        cls = resolve_architecture(hf)
        if getattr(cls, "CROSS_MODALITY", None) != "audio":
            raise ValueError(
                "audio inputs need an encoder-decoder (Whisper-family) "
                "model")
        if "input_features" in multi_modal_data:
            feats = np.asarray(multi_modal_data["input_features"],
                               np.float32)
        else:
            feats = self._extract_audio_features(
                multi_modal_data["audio"])
        if feats.ndim == 3:
            feats = feats[0]
        mel = int(getattr(hf, "num_mel_bins", feats.shape[0]))
        frames = 2 * int(hf.max_source_positions)
        if feats.shape != (mel, frames):
            # A wrong shape would shape-mismatch inside the worker's
            # cross-state scatter mid-step, killing the engine —
            # refuse at admission instead.
            raise ValueError(
                f"input_features must be [{mel}, {frames}] "
                f"(num_mel_bins x 2*max_source_positions); got "
                f"{tuple(feats.shape)}")
        if self._audio_encoder is None:
            from vllm_distributed_tpu.multimodal.audio import \
                build_audio_encoder
            self._audio_encoder = build_audio_encoder(
                self.config.model_config.model, hf)
            if self._audio_encoder is None:
                raise ValueError(
                    "audio inputs need a local Whisper checkpoint "
                    "(the front-end encoder loads model.encoder.*)")
        hidden = self._audio_encoder.encode(feats)
        return [MultiModalInput(embeds=hidden, offset=-1)], \
            prompt_token_ids

    def _process_encoder_text(self, multi_modal_data: dict,
                              prompt_token_ids: list[int]):
        """Encoder-decoder TEXT (BART-family): run the front-end text
        encoder at admission; hidden states ride the request like audio
        (offset=-1 cross-attention payload). Reference: the
        encoder_prompt path of the reference's encoder-decoder serving
        (models/bart.py)."""
        from vllm_distributed_tpu.models.registry import \
            resolve_architecture
        from vllm_distributed_tpu.multimodal import MultiModalInput
        hf = self.config.model_config.maybe_load_hf_config()
        cls = resolve_architecture(hf)
        if getattr(cls, "CROSS_MODALITY", None) != "text":
            raise ValueError(
                "encoder inputs need an encoder-decoder (BART-family) "
                "model")
        if "encoder_input_ids" in multi_modal_data:
            ids = [int(t) for t in multi_modal_data["encoder_input_ids"]]
        else:
            assert self.tokenizer is not None, \
                "encoder_text requires a tokenizer"
            ids = self.tokenizer.encode(multi_modal_data["encoder_text"])
        if not ids:
            raise ValueError("empty encoder input")
        if self._text_encoder is None:
            from vllm_distributed_tpu.multimodal.text_encoder import \
                build_text_encoder
            self._text_encoder = build_text_encoder(
                self.config.model_config.model, hf)
            if self._text_encoder is None:
                raise ValueError(
                    "encoder inputs need a local BART checkpoint "
                    "(the front-end encoder loads model.encoder.*)")
        # Small memo so a fan-out (n > 1 / multi-prompt) or repeated
        # request encodes each source document once.
        key = tuple(ids)
        hidden = self._enc_text_cache.get(key)
        if hidden is None:
            hidden = self._text_encoder.encode(ids)
            if len(self._enc_text_cache) >= 32:
                self._enc_text_cache.pop(
                    next(iter(self._enc_text_cache)))
            self._enc_text_cache[key] = hidden
        return [MultiModalInput(embeds=hidden, offset=-1)], \
            prompt_token_ids

    _text_encoder = None

    def _extract_audio_features(self, audio) -> "np.ndarray":
        """Raw waveform -> log-mel features via the checkpoint's
        feature extractor (reference: the WhisperFeatureExtractor use
        of serving_transcription.py)."""
        import numpy as np
        if self._audio_fe is None:
            from transformers import WhisperFeatureExtractor
            self._audio_fe = WhisperFeatureExtractor.from_pretrained(
                self.config.model_config.model)
        out = self._audio_fe(np.asarray(audio, np.float32),
                             sampling_rate=16000, return_tensors="np")
        return out["input_features"][0]

    _audio_encoder = None
    _audio_fe = None

    def _encode_pixels(self, pixel_values) -> list:
        """Run the in-engine vision tower at admission (reference: the
        encoder pass of gpu_model_runner._execute_mm_encoder; here the
        tower runs client-side once per request, feeding the same
        embedding path the scheduler budgets)."""
        import numpy as np
        if not hasattr(self, "_vision_encoder"):
            from vllm_distributed_tpu.multimodal.vision import \
                build_vision_encoder
            # build_vision_encoder raises ValueError for every
            # admission-level failure (missing tensors, unsupported
            # activations) — the contract of this path.
            self._vision_encoder = build_vision_encoder(
                self.config.model_config.model,
                self.config.model_config.maybe_load_hf_config())
        if self._vision_encoder is None:
            raise ValueError(
                "this model has no supported vision tower; pass "
                "pre-computed image_embeds instead")
        if isinstance(pixel_values, (list, tuple)):
            pixel_values = np.stack([np.asarray(p) for p in pixel_values])
        return self._vision_encoder.encode(pixel_values)

    def _process_mm(self, multi_modal_data: dict,
                    prompt_token_ids: list[int]):
        """Validate image embeddings and expand prompt placeholders
        (reference: the multimodal input processing of
        v1/engine/processor.py + vllm/multimodal/processing.py; this
        slice takes PRE-COMPUTED embeddings — projector outputs — and
        leaves the in-engine vision tower as follow-up)."""
        import numpy as np

        from vllm_distributed_tpu.multimodal import \
            expand_image_placeholders
        if self.config.parallel_config.pipeline_parallel_size > 1:
            raise ValueError(
                "image inputs under pipeline parallelism are not wired "
                "yet (the staged embed path does not apply embedding "
                "overrides); disable one")
        if ("audio" in multi_modal_data
                or "input_features" in multi_modal_data):
            return self._process_audio(multi_modal_data,
                                       prompt_token_ids)
        if ("encoder_input_ids" in multi_modal_data
                or "encoder_text" in multi_modal_data):
            return self._process_encoder_text(multi_modal_data,
                                              prompt_token_ids)
        if ("image_grid_thw" in multi_modal_data
                or "pixel_values_videos" in multi_modal_data
                or "video_grid_thw" in multi_modal_data):
            return self._process_qwen2_vl(multi_modal_data,
                                          prompt_token_ids)
        unknown = set(multi_modal_data) - {"image_embeds", "pixel_values"}
        if unknown:
            raise ValueError(
                f"unsupported multi_modal_data keys {sorted(unknown)}; "
                "this engine accepts 'image_embeds' (pre-computed), "
                "'pixel_values' (+ 'image_grid_thw' for Qwen2-VL), "
                "'pixel_values_videos'/'video_grid_thw' (video), or "
                "'audio'/'input_features' (Whisper-family models)")
        if "pixel_values" in multi_modal_data:
            if "image_embeds" in multi_modal_data:
                raise ValueError(
                    "pass either pixel_values or image_embeds, not both")
            images = self._encode_pixels(multi_modal_data["pixel_values"])
        else:
            images = multi_modal_data["image_embeds"]
        if isinstance(images, (list, tuple)):
            images = [np.asarray(im) for im in images]
        else:
            images = [np.asarray(images)]
        hf = self.config.model_config.maybe_load_hf_config()
        image_token = getattr(hf, "image_token_index",
                              getattr(hf, "image_token_id", None))
        if image_token is None:
            raise ValueError(
                "model config has no image_token_index; this model "
                "cannot take image inputs")
        text_cfg = getattr(hf, "text_config", hf)
        H = text_cfg.hidden_size
        for im in images:
            if im.ndim != 2 or im.shape[1] != H:
                raise ValueError(
                    f"image embeddings must be [n_tokens, {H}]; got "
                    f"{im.shape}")
        expanded, mm_inputs = expand_image_placeholders(
            prompt_token_ids, int(image_token), images)
        budget = self.config.scheduler_config.encoder_cache_budget
        n_enc = sum(m.num_tokens for m in mm_inputs)
        if n_enc > budget:
            raise ValueError(
                f"request needs {n_enc} encoder tokens; the engine's "
                f"encoder_cache_budget is {budget}")
        return mm_inputs, expanded

    _qwen_vision = None

    def _process_qwen2_vl(self, multi_modal_data: dict,
                          prompt_token_ids: list[int]):
        """Qwen2-VL images AND videos: HF-image-processor-style inputs
        (flattened patches + grid_thw per input) run the dynamic-
        resolution tower at admission; each placeholder expands to its
        merged-token count and carries its (t, h', w') grid for M-RoPE
        (reference: the qwen2_vl multimodal processor +
        get_rope_index)."""
        import numpy as np

        from vllm_distributed_tpu.multimodal import MultiModalInput
        hf = self.config.model_config.maybe_load_hf_config()
        from vllm_distributed_tpu.models.registry import \
            resolve_architecture
        cls = resolve_architecture(hf)
        if getattr(cls, "VISION_STYLE", None) != "qwen2_vl":
            raise ValueError(
                "grid_thw-style vision inputs need a Qwen2-VL-family "
                "model")
        if self._qwen_vision is None:
            from vllm_distributed_tpu.multimodal.qwen2_vision import \
                build_qwen2_vision_encoder
            self._qwen_vision = build_qwen2_vision_encoder(
                self.config.model_config.model, hf)
            if self._qwen_vision is None:
                raise ValueError(
                    "qwen2-vl vision inputs need a local checkpoint "
                    "with the visual.* tower tensors")
        enc = self._qwen_vision
        m = enc.merge

        def encode(pix_key, grid_key):
            pix = multi_modal_data.get(pix_key)
            if pix is None:
                return []
            grids = multi_modal_data.get(grid_key)
            if grids is None:
                raise ValueError(f"{pix_key} needs {grid_key}")
            grids = [tuple(int(v) for v in g) for g in np.asarray(grids)]
            embeds = enc.encode(np.asarray(pix, np.float32), grids)
            return [(e, (t, h // m, w // m))
                    for e, (t, h, w) in zip(embeds, grids)]

        images = encode("pixel_values", "image_grid_thw")
        videos = encode("pixel_values_videos", "video_grid_thw")

        image_tok = int(getattr(hf, "image_token_id", -1))
        video_tok = int(getattr(hf, "video_token_id", -2))
        n_img = sum(1 for t in prompt_token_ids if t == image_tok)
        n_vid = sum(1 for t in prompt_token_ids if t == video_tok)
        if n_img != len(images) or n_vid != len(videos):
            raise ValueError(
                f"prompt has {n_img} image / {n_vid} video placeholder "
                f"tokens but {len(images)} images / {len(videos)} "
                f"videos were provided")
        queues = {image_tok: list(images), video_tok: list(videos)}
        out: list[int] = []
        mm_inputs: list[MultiModalInput] = []
        for t in prompt_token_ids:
            q = queues.get(t)
            if q:
                embeds, grid = q.pop(0)
                mm_inputs.append(MultiModalInput(
                    embeds=embeds, offset=len(out), grid=grid))
                out.extend([t] * embeds.shape[0])
            else:
                out.append(t)
        leftover = sum(len(q) for q in queues.values())
        if leftover:
            raise ValueError(
                f"{leftover} image/video inputs had no matching "
                f"placeholder token in the prompt")
        budget = self.config.scheduler_config.encoder_cache_budget
        n_enc = sum(mi.num_tokens for mi in mm_inputs)
        if n_enc > budget:
            raise ValueError(
                f"request needs {n_enc} encoder tokens; the engine's "
                f"encoder_cache_budget is {budget}")
        return mm_inputs, out
