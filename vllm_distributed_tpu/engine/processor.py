"""Request pre-processing: tokenize + validate -> EngineCoreRequest.

Reference: vllm/v1/engine/processor.py (tokenization, validation; runs in
the client process, never on the device path).
"""

import time
from typing import Optional, Union

from vllm_distributed_tpu.config import EngineConfig
from vllm_distributed_tpu.request import EngineCoreRequest
from vllm_distributed_tpu.sampling_params import SamplingParams


class Processor:

    def __init__(self, config: EngineConfig, tokenizer) -> None:
        self.config = config
        self.tokenizer = tokenizer
        self.eos_token_id: Optional[int] = None
        if tokenizer is not None:
            self.eos_token_id = tokenizer.eos_token_id
        if self.eos_token_id is None:
            # Tokenizer-free runs still stop on the model's EOS
            # (reference: processor reads generation_config/hf_config).
            hf = config.model_config.maybe_load_hf_config()
            eos = getattr(hf, "eos_token_id", None)
            if isinstance(eos, (list, tuple)):
                eos = eos[0] if eos else None
            self.eos_token_id = eos

    def process_inputs(
        self,
        request_id: str,
        prompt: Union[str, list[int]],
        sampling_params: SamplingParams,
        arrival_time: Optional[float] = None,
        priority: int = 0,
        kv_transfer_params: Optional[dict] = None,
    ) -> EngineCoreRequest:
        if isinstance(prompt, str):
            assert self.tokenizer is not None, \
                "string prompts require a tokenizer"
            prompt_token_ids = self.tokenizer.encode(prompt)
        else:
            prompt_token_ids = list(prompt)
        if not prompt_token_ids:
            raise ValueError("empty prompt")
        max_len = self.config.scheduler_config.max_model_len
        if len(prompt_token_ids) >= max_len:
            raise ValueError(
                f"prompt ({len(prompt_token_ids)} tokens) is longer than "
                f"the maximum model length of {max_len}")
        return EngineCoreRequest(
            request_id=request_id,
            prompt_token_ids=prompt_token_ids,
            sampling_params=sampling_params,
            eos_token_id=self.eos_token_id,
            arrival_time=arrival_time or time.time(),
            priority=priority,
            kv_transfer_params=kv_transfer_params,
        )
