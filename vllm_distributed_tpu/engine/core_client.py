"""Client side of the engine-core transport.

Reference: vllm/v1/engine/core_client.py:44 (``EngineCoreClient.make_client``
:56 choosing InprocClient :219 / SyncMPClient / AsyncMPClient) and
v1/engine/exceptions.py (EngineDeadError). The multiprocess client spawns
``core_proc.run_engine_core`` and speaks msgpack over ZMQ ipc sockets; the
in-process client wraps EngineCore directly (CPU tests, offline runs).
"""

import os
import tempfile
import time
import uuid
from typing import Optional

from vllm_distributed_tpu.config import EngineConfig
from vllm_distributed_tpu.core.sched.scheduler import EngineCoreOutput
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.request import EngineCoreRequest

logger = init_logger(__name__)


class EngineDeadError(RuntimeError):
    """The engine core died or stopped responding (reference:
    v1/engine/exceptions.py EngineDeadError). Structured: ``reason``
    carries the detection detail and ``replica`` the DP rank it came
    from (None for a single-core engine), so the OpenAI server can
    surface both in its 503 body."""

    def __init__(self, reason: str = "engine core is dead",
                 replica: Optional[int] = None) -> None:
        self.reason = reason
        self.replica = replica
        detail = (f"[dp replica {replica}] {reason}"
                  if replica is not None else reason)
        super().__init__(detail)


class RestartSupervisor:
    """Restart budget + backoff policy for a dead engine core.

    The recovery ladder's "respawn" rung: each death asks
    ``next_delay()``; the supervisor grants at most ``max_attempts``
    restarts inside a sliding ``window_s`` window, with exponential
    backoff between grants, and returns None once the budget is burnt —
    the caller then circuit-breaks to the terminal EngineDeadError
    (reference analogue: the crash-loop backoff any production
    supervisor, e.g. systemd's StartLimitIntervalSec, applies)."""

    def __init__(self, max_attempts: int, window_s: float,
                 backoff_base_s: float, backoff_max_s: float) -> None:
        self.max_attempts = max_attempts
        self.window_s = window_s
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self._attempts: list[float] = []  # monotonic grant times

    @classmethod
    def from_config(cls, config: EngineConfig) -> "RestartSupervisor":
        ft = config.fault_tolerance_config
        return cls(ft.restart_max_attempts, ft.restart_window_s,
                   ft.restart_backoff_base_s, ft.restart_backoff_max_s)

    @property
    def exhausted(self) -> bool:
        self._expire()
        return len(self._attempts) >= self.max_attempts

    def _expire(self) -> None:
        cutoff = time.monotonic() - self.window_s
        self._attempts = [t for t in self._attempts if t > cutoff]

    def peek(self) -> tuple[int, bool]:
        """Read-only ``(attempts_in_window, exhausted)`` for debug
        surfaces: computed from a C-level (GIL-atomic) copy so a poll
        from another thread never rebuilds ``_attempts`` under a
        concurrent ``next_delay()``."""
        cutoff = time.monotonic() - self.window_s
        in_window = sum(1 for t in list(self._attempts) if t > cutoff)
        return in_window, in_window >= self.max_attempts

    def next_delay(self) -> Optional[float]:
        """Grant one restart attempt: the backoff to sleep before it,
        or None when the budget inside the window is exhausted (the
        circuit breaker). max_attempts=0 always refuses (recovery
        disabled)."""
        self._expire()
        if len(self._attempts) >= self.max_attempts:
            return None
        delay = min(self.backoff_base_s * (2 ** len(self._attempts)),
                    self.backoff_max_s)
        self._attempts.append(time.monotonic())
        return delay


class EngineCoreClient:

    @staticmethod
    def make_client(config: EngineConfig) -> "EngineCoreClient":
        from vllm_distributed_tpu import envs
        pc = config.parallel_config
        if pc.data_parallel_size > 1 and pc.data_parallel_mode == "engine":
            from vllm_distributed_tpu.engine.dp_client import DPEngineClient
            return DPEngineClient(config)
        if pc.multiprocess_engine_core or envs.VDT_ENABLE_MP_ENGINE:
            return SyncMPClient(config)
        return InprocClient(config)

    # Interface ---------------------------------------------------------
    def add_request(self, request: EngineCoreRequest) -> None:
        raise NotImplementedError

    def abort_requests(self, request_ids: list[str]) -> None:
        raise NotImplementedError

    def get_output(self) -> list[EngineCoreOutput]:
        """Next batch of per-request output deltas (blocking when work is
        in flight)."""
        raise NotImplementedError

    def has_unfinished_requests(self) -> bool:
        raise NotImplementedError

    def get_stats(self) -> dict:
        raise NotImplementedError

    def call_utility(self, method: str, *args):
        """Generic core RPC (sleep/wake_up/profile/...)."""
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


class InprocClient(EngineCoreClient):
    """Reference: core_client.py:219 InprocClient — step() inline."""

    def __init__(self, config: EngineConfig) -> None:
        from vllm_distributed_tpu.engine.core import EngineCore
        from vllm_distributed_tpu.utils import fault_injection
        fault_injection.fire_or_raise("core_proc.spawn_fail")
        self.config = config
        self.engine_core = EngineCore(config)

    def restart(self) -> None:
        """Rebuild the in-process core (DP resurrection probe). The old
        core's requests are gone — the caller replays its journal."""
        from vllm_distributed_tpu.engine.core import EngineCore
        from vllm_distributed_tpu.utils import fault_injection
        fault_injection.fire_or_raise("core_proc.spawn_fail")
        try:
            self.engine_core.shutdown()
        except Exception:  # noqa: BLE001 - the dead core may be torn
            pass
        self.engine_core = EngineCore(self.config)

    def add_request(self, request: EngineCoreRequest) -> None:
        self.engine_core.add_request(request)

    def abort_requests(self, request_ids: list[str]) -> None:
        self.engine_core.abort_requests(request_ids)

    def get_output(self) -> list[EngineCoreOutput]:
        return self.engine_core.step()

    def has_unfinished_requests(self) -> bool:
        return self.engine_core.has_unfinished_requests()

    def get_stats(self) -> dict:
        return self.engine_core.get_stats()

    def call_utility(self, method: str, *args):
        return getattr(self.engine_core, method)(*args)

    def shutdown(self) -> None:
        self.engine_core.shutdown()

    # Introspection conveniences for tests/tools (in-proc only).
    @property
    def scheduler(self):
        return self.engine_core.scheduler

    @property
    def executor(self):
        return self.engine_core.executor


class SyncMPClient(EngineCoreClient):
    """Engine core in a spawned subprocess, msgpack over ZMQ ipc.

    reference: core_client.py SyncMPClient + MPClient (ready handshake,
    output queue, engine-dead sentinel, startup timeout).
    """

    def __init__(self, config: EngineConfig) -> None:
        import zmq

        from vllm_distributed_tpu.engine import serial
        self._serial = serial
        self.config = config

        self._sock_dir = tempfile.mkdtemp(prefix="vdt-zmq-")
        self.ctx = zmq.Context()
        self.input_sock = None
        self.output_sock = None
        self.proc = None

        # Live request ids (NOT a counter: a client-side stop abort can
        # race a core-side finish for the same request; set-discard makes
        # the accounting idempotent).
        self._live: set[str] = set()
        self._call_id = 0
        self._pending_outputs: list[list[EngineCoreOutput]] = []
        # Utility-RPC results stashed by recv_outputs (async/pump mode).
        self._results: dict[int, object] = {}
        # Health monitor: every received message (including the core's
        # dedicated heartbeat beats) refreshes liveness; a stale window
        # with work in flight means the core process is wedged even
        # though the OS still reports it alive.
        self.heartbeat_timeout_s = \
            config.fault_tolerance_config.heartbeat_timeout_s
        self._last_alive = time.monotonic()

        self._spawn()

    def _spawn(self) -> None:
        """Spawn the core subprocess and run the ready handshake. Each
        incarnation gets FRESH ipc endpoints: messages buffered toward
        (or from) a dead incarnation must never reach its replacement —
        the journal replay, not the socket backlog, is the source of
        truth after a restart."""
        import multiprocessing

        import zmq

        from vllm_distributed_tpu import envs
        from vllm_distributed_tpu.utils import fault_injection
        fault_injection.fire_or_raise("core_proc.spawn_fail")

        for sock in (self.input_sock, self.output_sock):
            if sock is not None:
                sock.close(linger=0)
        rid = uuid.uuid4().hex[:8]
        input_addr = f"ipc://{self._sock_dir}/input-{rid}"
        output_addr = f"ipc://{self._sock_dir}/output-{rid}"
        self.input_sock = self.ctx.socket(zmq.PUSH)
        self.input_sock.bind(input_addr)
        self.output_sock = self.ctx.socket(zmq.PULL)
        self.output_sock.bind(output_addr)

        # spawn (not fork): the child must initialize its own JAX backend.
        mp_ctx = multiprocessing.get_context("spawn")
        from vllm_distributed_tpu.engine.core_proc import run_engine_core
        self.proc = mp_ctx.Process(
            target=run_engine_core,
            args=(self.config, input_addr, output_addr),
            daemon=True, name="vdt-engine-core")
        self.proc.start()

        # Ready handshake (the child compiles/loads weights first).
        timeout_ms = int(envs.VDT_RPC_TIMEOUT * 1000)
        if not self.output_sock.poll(timeout_ms):
            self._kill()
            raise EngineDeadError(
                f"engine core did not become ready in {timeout_ms} ms")
        msg = self._serial.unpack(self.output_sock.recv())
        if msg.get("t") != "ready":
            self._kill()
            raise EngineDeadError(f"bad handshake: {msg}")
        self.config.cache_config.num_gpu_blocks = msg.get("num_pages")
        self._last_alive = time.monotonic()
        logger.info("engine core proc ready (pid %d)", self.proc.pid)

    def restart(self) -> None:
        """Respawn a dead core subprocess. In-flight state is gone —
        the caller (AsyncLLM's supervisor / the DP failover path)
        replays its journal afterwards."""
        self._kill()
        self._live.clear()
        self._pending_outputs.clear()
        self._results.clear()
        self._spawn()

    # ------------------------------------------------------------------
    def _kill(self) -> None:
        if self.proc is not None and self.proc.is_alive():
            self.proc.terminate()
            self.proc.join(timeout=5)

    def _send(self, msg: dict) -> None:
        if not self.proc.is_alive():
            raise EngineDeadError("engine core process is not alive")
        self.input_sock.send(self._serial.pack(msg))

    def _recv(self, timeout_ms: int) -> Optional[dict]:
        import zmq
        deadline_poll = timeout_ms
        while True:
            if not self.output_sock.poll(deadline_poll):
                if not self.proc.is_alive():
                    raise EngineDeadError("engine core process died")
                self._check_heartbeat()
                return None
            msg = self._serial.unpack(self.output_sock.recv(zmq.NOBLOCK))
            self._last_alive = time.monotonic()
            if msg.get("t") == "dead":
                raise EngineDeadError(msg.get("error", "engine core died"))
            if msg.get("t") == "hb":
                # Liveness beat only; nothing for the caller.
                return None
            return msg

    def _check_heartbeat(self) -> None:
        """Wedged-process detection: the core's heartbeat thread beats
        through long compiles, so staleness past the window with work in
        flight means the process is hung, not slow."""
        if self.heartbeat_timeout_s <= 0 or not self._live:
            return
        stale = time.monotonic() - self._last_alive
        if stale > self.heartbeat_timeout_s:
            raise EngineDeadError(
                f"engine core unresponsive for {stale:.1f}s (heartbeat "
                f"window {self.heartbeat_timeout_s:.1f}s) with requests "
                f"in flight")

    # ------------------------------------------------------------------
    def _mark_finished(self, outs: list[EngineCoreOutput]) -> None:
        for o in outs:
            if o.finished:
                self._live.discard(o.req_id)

    def add_request(self, request: EngineCoreRequest) -> None:
        self._send({"t": "add", "req": self._serial.encode_request(request)})
        self._live.add(request.request_id)

    def abort_requests(self, request_ids: list[str]) -> None:
        if not request_ids:
            return
        self._send({"t": "abort", "ids": request_ids})
        for rid in request_ids:
            self._live.discard(rid)

    def get_output(self) -> list[EngineCoreOutput]:
        if self._pending_outputs:
            return self._pending_outputs.pop(0)
        if not self._live:
            return []
        while True:
            msg = self._recv(timeout_ms=200)
            if msg is None:
                continue  # core is busy compiling/stepping; keep waiting
            if msg["t"] == "outputs":
                outs = [self._serial.decode_output(v) for v in msg["outs"]]
                self._mark_finished(outs)
                return outs
            # Utility results arriving out of band are queued by caller.
            logger.debug("ignoring out-of-band message %s", msg["t"])

    def recv_outputs(
            self, timeout_ms: int) -> Optional[list[EngineCoreOutput]]:
        """Pump-thread receive (AsyncLLM): next output batch or None on
        timeout; utility results are stashed for fetch_result(). All
        receives must come from ONE thread — zmq sockets are not
        thread-safe."""
        msg = self._recv(timeout_ms)
        if msg is None:
            return None
        if msg["t"] == "outputs":
            outs = [self._serial.decode_output(v) for v in msg["outs"]]
            self._mark_finished(outs)
            return outs
        if msg["t"] == "result":
            if msg.get("error") is not None:
                self._results[msg["call_id"]] = RuntimeError(msg["error"])
            else:
                self._results[msg["call_id"]] = msg["value"]
        return None

    def send_utility(self, method: str, *args) -> int:
        """Fire a utility RPC; the result lands in fetch_result() once the
        receive thread pumps it."""
        self._call_id += 1
        self._send({"t": "call", "method": method, "args": list(args),
                    "call_id": self._call_id})
        return self._call_id

    def fetch_result(self, call_id: int, default=None):
        return self._results.pop(call_id, default)

    def has_unfinished_requests(self) -> bool:
        return bool(self._live)

    def get_stats(self) -> dict:
        return self.call_utility("get_stats")

    def call_utility(self, method: str, *args):
        from vllm_distributed_tpu import envs
        self._call_id += 1
        call_id = self._call_id
        self._send({"t": "call", "method": method, "args": list(args),
                    "call_id": call_id})
        deadline = time.monotonic() + envs.VDT_RPC_TIMEOUT
        while True:
            remaining_ms = int((deadline - time.monotonic()) * 1000)
            if remaining_ms <= 0:
                raise EngineDeadError(f"RPC {method} timed out")
            # Bounded polls: heartbeat beats and output batches arrive
            # between polls without consuming the whole RPC budget.
            msg = self._recv(timeout_ms=min(remaining_ms, 1000))
            if msg is None:
                continue
            if msg["t"] == "result" and msg["call_id"] == call_id:
                if msg.get("error") is not None:
                    raise RuntimeError(
                        f"RPC {method} failed in core: {msg['error']}")
                return msg["value"]
            if msg["t"] == "outputs":
                outs = [self._serial.decode_output(v) for v in msg["outs"]]
                self._mark_finished(outs)
                self._pending_outputs.append(outs)

    def shutdown(self) -> None:
        try:
            if self.proc is not None and self.proc.is_alive():
                self.input_sock.send(self._serial.pack({"t": "shutdown"}))
                self.proc.join(timeout=10)
        except Exception:
            pass
        self._kill()
        self.input_sock.close(linger=0)
        self.output_sock.close(linger=0)
        self.ctx.term()
        try:
            import shutil
            shutil.rmtree(self._sock_dir, ignore_errors=True)
        except Exception:
            pass
