"""Data-parallel coordinator process.

Reference: vllm/v1/engine/coordinator.py:21 ``DPCoordinator`` — a
separate process that aggregates per-engine request counts and serves
them to the front-end balancer(s), so routing state lives outside any
single API server. This implementation keeps the reference's
architecture at TPU-appropriate scope: a ZMQ REP loop owning the
count table; front-ends report +/- deltas on admission/finish and ask
``route`` for the least-loaded engine. One front-end uses it as an
out-of-process routing brain (enabled by
``ParallelConfig.data_parallel_coordinator``); multiple front-ends
sharing engine procs plug into the same protocol (the counts are
already globally aggregated — the remaining work is shared engine
endpoints, not coordination).

The reference's wave-lockstep dummy batches (core.py:929-969) remain
unnecessary here by construction: expert parallelism spans the model
mesh axis INSIDE a replica, so an idle replica participates in no
collective. The coordinator still tracks an ``engines_running`` view
(count > 0) mirroring the reference's wave state for observability.
"""

import tempfile
import threading
import uuid
from typing import Optional

from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)


def _coordinator_loop(addr: str, num_engines: int) -> None:
    import time

    import zmq

    from vllm_distributed_tpu.engine.serial import pack, unpack
    ctx = zmq.Context()
    sock = ctx.socket(zmq.REP)
    sock.bind(addr)
    counts = [0] * num_engines
    healthy = [True] * num_engines
    # Fleet-controller lease (engine/control_plane.py): exactly one
    # front-end controller holds the TTL lease and actuates; the epoch
    # increments on every holder change so a paused-then-resumed
    # ex-leader's commands are recognizably stale (fencing). Monotonic
    # server clock — wall-clock jumps cannot expire or extend a lease.
    lease_holder: Optional[str] = None
    lease_epoch = 0
    lease_deadline = 0.0
    lease_transitions = 0
    try:
        while True:
            raw = sock.recv()
            # A malformed message must produce an error REPLY, never
            # kill the loop — a dead REP socket strands every client.
            try:
                msg = unpack(raw)
                op = msg.get("op")
                if op == "report":
                    engine = int(msg["engine"])
                    if not 0 <= engine < num_engines:
                        raise ValueError(f"engine {engine} out of range")
                    counts[engine] += int(msg["delta"])
                    reply = {"ok": True}
                elif op == "route":
                    live = [i for i in range(num_engines) if healthy[i]]
                    if not live:
                        raise ValueError("no healthy engines to route to")
                    # The routing tier's placement (prefix affinity /
                    # SLO scoring happens front-end-side) rides along
                    # as a preference, honored while that engine is
                    # healthy; the coordinator stays the single owner
                    # of the cross-front-end admission counts.
                    prefer = msg.get("prefer")
                    if (prefer is not None and 0 <= int(prefer) <
                            num_engines and healthy[int(prefer)]):
                        engine = int(prefer)
                    else:
                        engine = min(live, key=counts.__getitem__)
                    counts[engine] += 1  # route implies one admission
                    reply = {"engine": engine}
                elif op == "health":
                    # DP failover/resurrection: a downed engine leaves
                    # the routing set, and clearing its count unwinds
                    # the admissions its death stranded (the front-end
                    # re-routes that load, which re-reports it).
                    engine = int(msg["engine"])
                    if not 0 <= engine < num_engines:
                        raise ValueError(f"engine {engine} out of range")
                    healthy[engine] = bool(msg["up"])
                    if msg.get("clear"):
                        counts[engine] = 0
                    reply = {"ok": True}
                elif op == "resize":
                    # Elastic scale-out (engine/fleet.py): grow the
                    # count table for appended engines. New slots start
                    # healthy with zero admissions. Shrink is refused —
                    # retirement keeps its slot and leaves via the
                    # health op, so indices stay stable fleet-wide.
                    n = int(msg["num_engines"])
                    if n < num_engines:
                        raise ValueError(
                            f"cannot shrink {num_engines} -> {n}")
                    counts.extend([0] * (n - num_engines))
                    healthy.extend([True] * (n - num_engines))
                    num_engines = n
                    reply = {"ok": True}
                elif op == "lease":
                    # Acquire/renew the controller lease. Grants when
                    # the lease is free, expired, or already held by
                    # this holder (renewal); the epoch bumps only on a
                    # holder CHANGE, so renewals keep in-flight fenced
                    # actions valid. "release" relinquishes voluntarily
                    # (clean shutdown) without burning an epoch — the
                    # next grant increments it.
                    holder = str(msg["holder"])
                    now = time.monotonic()
                    if msg.get("release"):
                        if lease_holder == holder:
                            lease_holder = None
                            lease_deadline = 0.0
                        reply = {"granted": False, "epoch": lease_epoch,
                                 "holder": lease_holder,
                                 "transitions": lease_transitions}
                    else:
                        ttl_s = float(msg["ttl_s"])
                        free = (lease_holder is None
                                or now >= lease_deadline)
                        if free or lease_holder == holder:
                            if lease_holder != holder:
                                lease_epoch += 1
                                lease_transitions += 1
                                lease_holder = holder
                            lease_deadline = now + ttl_s
                            granted = True
                        else:
                            granted = False
                        reply = {"granted": granted,
                                 "epoch": lease_epoch,
                                 "holder": lease_holder,
                                 "transitions": lease_transitions}
                elif op == "fence":
                    # Epoch check for an actuation: valid iff the epoch
                    # is CURRENT and the lease unexpired. A stale epoch
                    # is a normal reply (ok=False), not an error — the
                    # caller counts the rejection and moves on; fencing
                    # must never raise into the serving path.
                    now = time.monotonic()
                    ok = (int(msg["epoch"]) == lease_epoch
                          and lease_holder is not None
                          and now < lease_deadline)
                    reply = {"ok": bool(ok), "epoch": lease_epoch}
                elif op == "lease_info":
                    now = time.monotonic()
                    live = (lease_holder is not None
                            and now < lease_deadline)
                    reply = {"holder": lease_holder if live else None,
                             "epoch": lease_epoch,
                             "ttl_remaining_s":
                             max(0.0, lease_deadline - now),
                             "transitions": lease_transitions}
                elif op == "counts":
                    reply = {"counts": list(counts),
                             "engines_running": [c > 0 for c in counts],
                             "healthy": list(healthy)}
                elif op == "shutdown":
                    sock.send(pack({"ok": True}))
                    break
                else:
                    reply = {"error": f"unknown op {op!r}"}
            except Exception as e:  # noqa: BLE001 - reply, keep serving
                reply = {"error": f"{type(e).__name__}: {e}"}
            sock.send(pack(reply))
    finally:
        sock.close(0)
        ctx.term()


class DPCoordinatorClient:
    """Front-end handle to the coordinator (REQ socket; one in-flight
    request at a time per client, matching the balancer's call sites)."""

    TIMEOUT_MS = 10_000

    def __init__(self, addr: str) -> None:
        import zmq

        from vllm_distributed_tpu.engine import serial
        self._serial = serial
        self.ctx = zmq.Context()
        self.sock = self.ctx.socket(zmq.REQ)
        # Bounded waits: a dead coordinator must FAIL the front-end,
        # not wedge it (REQ_RELAXED lets the socket recover after a
        # timed-out request).
        self.sock.setsockopt(zmq.RCVTIMEO, self.TIMEOUT_MS)
        self.sock.setsockopt(zmq.SNDTIMEO, self.TIMEOUT_MS)
        self.sock.setsockopt(zmq.REQ_RELAXED, 1)
        self.sock.setsockopt(zmq.REQ_CORRELATE, 1)
        self.sock.connect(addr)
        self._lock = threading.Lock()

    def _call(self, **msg) -> dict:
        import zmq

        from vllm_distributed_tpu.utils import fault_injection
        if fault_injection.should_fire("coordinator.partition"):
            # Drill: the control plane is unreachable from THIS
            # front-end (network partition). Callers degrade — routing
            # falls back to local least-loaded, the HA controller
            # freezes placement — and nothing raises into serving.
            raise RuntimeError(
                "DP coordinator unreachable (injected partition)")
        with self._lock:
            try:
                self.sock.send(self._serial.pack(msg))
                reply = self._serial.unpack(self.sock.recv())
            except zmq.error.Again as e:
                raise RuntimeError(
                    "DP coordinator did not respond within "
                    f"{self.TIMEOUT_MS} ms (dead process?)") from e
        if "error" in reply:
            raise RuntimeError(f"DP coordinator: {reply['error']}")
        return reply

    def route(self, prefer: Optional[int] = None) -> int:
        """Least-loaded healthy engine, or ``prefer`` (the front-end
        routing tier's pick) while that engine is healthy."""
        msg = {"op": "route"}
        if prefer is not None:
            msg["prefer"] = int(prefer)
        return int(self._call(**msg)["engine"])

    def report(self, engine: int, delta: int) -> None:
        self._call(op="report", engine=engine, delta=delta)

    def set_health(self, engine: int, up: bool, *,
                   clear: bool = False) -> None:
        """Take an engine out of (or return it to) the routing set;
        ``clear`` zeroes its admission count (failover migrates the
        load, re-reporting it against the replicas that absorb it)."""
        self._call(op="health", engine=engine, up=up, clear=clear)

    def resize(self, num_engines: int) -> None:
        """Grow the coordinator's engine table (elastic scale-out).
        New slots start healthy with zero admissions."""
        self._call(op="resize", num_engines=num_engines)

    def acquire_lease(self, holder: str, ttl_s: float) -> dict:
        """Acquire or renew the fleet-controller lease. Returns the
        coordinator's view: ``{"granted", "epoch", "holder",
        "transitions"}`` — a renewal by the current holder keeps the
        epoch, a takeover bumps it."""
        return self._call(op="lease", holder=holder, ttl_s=ttl_s)

    def release_lease(self, holder: str) -> None:
        """Voluntarily relinquish the lease (clean shutdown); a no-op
        unless ``holder`` currently holds it."""
        self._call(op="lease", holder=holder, release=True)

    def fence(self, epoch: int, action: str) -> bool:
        """True iff an actuation stamped with ``epoch`` may proceed
        (epoch current AND lease unexpired). ``action`` rides along
        for the coordinator's logs; a False return is the stale-epoch
        rejection path — count it, never raise it."""
        return bool(self._call(op="fence", epoch=epoch,
                               action=action)["ok"])

    def lease_info(self) -> dict:
        """Observability snapshot: ``{"holder", "epoch",
        "ttl_remaining_s", "transitions"}`` (holder None if expired)."""
        return self._call(op="lease_info")

    def healthy(self) -> list[bool]:
        return list(self._call(op="counts")["healthy"])

    def counts(self) -> list[int]:
        return list(self._call(op="counts")["counts"])

    def engines_running(self) -> list[bool]:
        return list(self._call(op="counts")["engines_running"])

    def shutdown_coordinator(self) -> None:
        try:
            self._call(op="shutdown")
        except Exception:  # noqa: BLE001 - already gone
            pass

    def close(self) -> None:
        self.sock.close(0)
        self.ctx.term()


def spawn_coordinator(num_engines: int,
                      addr: Optional[str] = None):
    """Start the coordinator in its own process; returns (proc, addr).
    The process is daemonic and exits with a 'shutdown' op."""
    import multiprocessing
    if addr is None:
        d = tempfile.mkdtemp(prefix="vdt-coord-")
        addr = f"ipc://{d}/coord-{uuid.uuid4().hex[:8]}"
    mp_ctx = multiprocessing.get_context("spawn")
    proc = mp_ctx.Process(target=_coordinator_loop,
                          args=(addr, num_engines), daemon=True,
                          name="vdt-dp-coordinator")
    proc.start()
    return proc, addr


def cleanup_socket_dir(addr: str) -> None:
    """Remove the ipc socket directory spawn_coordinator created
    (mirrors SyncMPClient's vdt-zmq-* cleanup)."""
    import os
    import shutil
    if addr.startswith("ipc://"):
        d = os.path.dirname(addr[len("ipc://"):])
        if os.path.basename(d).startswith("vdt-coord-"):
            shutil.rmtree(d, ignore_errors=True)
