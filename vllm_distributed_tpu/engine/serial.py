"""msgpack serialization for the client <-> engine-core boundary.

Reference: vllm/v1/serial_utils.py (MsgpackEncoder/Decoder over msgspec).
msgspec is not in this image, so the wire format is plain msgpack with
explicit to/from-dict converters for the two dataclasses that cross the
process boundary (EngineCoreRequest in, EngineCoreOutput out). Tensors
never cross this boundary — token ids and logprobs are plain ints/floats.
"""

from dataclasses import asdict
from typing import Any

import msgpack
import numpy as np

from vllm_distributed_tpu.core.sched.scheduler import EngineCoreOutput
from vllm_distributed_tpu.multimodal import MultiModalInput
from vllm_distributed_tpu.request import EngineCoreRequest
from vllm_distributed_tpu.sampling_params import SamplingParams


def pack(obj: Any) -> bytes:
    return msgpack.packb(obj, use_bin_type=True)


def unpack(data: bytes) -> Any:
    # strict_map_key=False: logprob maps are keyed by int token ids.
    return msgpack.unpackb(data, raw=False, strict_map_key=False)


# ---------------------------------------------------------------------------
def encode_request(req: EngineCoreRequest) -> dict:
    sp = asdict(req.sampling_params)
    sp.pop("_all_stop_token_ids", None)
    d = {
        "request_id": req.request_id,
        "prompt_token_ids": req.prompt_token_ids,
        "sampling_params": sp,
        "eos_token_id": req.eos_token_id,
        "arrival_time": req.arrival_time,
        "priority": req.priority,
        "tenant": req.tenant,
        "kv_transfer_params": req.kv_transfer_params,
        "lora_request": req.lora_request,
        "pooling_params": req.pooling_params,
        "mm_inputs": ([{
            "embeds": np.ascontiguousarray(m.embeds).tobytes(),
            "shape": list(m.embeds.shape),
            "dtype": str(m.embeds.dtype),
            "offset": m.offset,
        } for m in req.mm_inputs] if req.mm_inputs else None),
    }
    # Additive wire key, emitted ONLY when a trace context exists:
    # with VDT_TRACE_PLANE=0 nothing mints one, so the encoded map (and
    # its msgpack bytes) are byte-identical to the pre-trace-plane
    # wire. Old decoders construct from known keys and ignore extras,
    # so a trace-stamped request is also accepted by a pre-trace-plane
    # peer (tolerance pinned by tests/engine/test_serial_trace.py).
    if req.trace_ctx is not None:
        d["trace_ctx"] = req.trace_ctx
    return d


def decode_request(d: dict) -> EngineCoreRequest:
    return EngineCoreRequest(
        request_id=d["request_id"],
        prompt_token_ids=list(d["prompt_token_ids"]),
        sampling_params=SamplingParams(**d["sampling_params"]),
        eos_token_id=d["eos_token_id"],
        arrival_time=d["arrival_time"],
        priority=d["priority"],
        tenant=d.get("tenant"),
        kv_transfer_params=d["kv_transfer_params"],
        lora_request=d.get("lora_request"),
        pooling_params=d.get("pooling_params"),
        # .get(): absent on the pre-trace-plane wire (old peer).
        trace_ctx=d.get("trace_ctx"),
        mm_inputs=([
            MultiModalInput(
                embeds=np.frombuffer(m["embeds"],
                                     dtype=m["dtype"]).reshape(
                                         m["shape"]),
                offset=m["offset"]) for m in d["mm_inputs"]
        ] if d.get("mm_inputs") else None),
    )


def encode_output(out: EngineCoreOutput) -> list:
    return [out.req_id, out.new_token_ids, out.finish_reason,
            out.stop_reason, out.num_cached_tokens, out.logprobs,
            out.kv_transfer_params, out.pooled, out.prompt_logprobs,
            ([list(e) for e in out.events] if out.events else None)]


def decode_output(v: list) -> EngineCoreOutput:
    (req_id, new_token_ids, finish_reason, stop_reason, cached, lps,
     kv_params, pooled, prompt_lps, events) = v
    return EngineCoreOutput(
        req_id=req_id,
        new_token_ids=list(new_token_ids),
        finish_reason=finish_reason,
        stop_reason=stop_reason,
        num_cached_tokens=cached,
        logprobs=lps,
        kv_transfer_params=kv_params,
        pooled=pooled,
        prompt_logprobs=prompt_lps,
        events=([tuple(e) for e in events] if events else None),
    )
