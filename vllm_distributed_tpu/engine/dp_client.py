"""Data-parallel engine replication: N engine cores behind one balancer.

Reference: one ``DPEngineCoreProc`` per DP rank plus a ``DPCoordinator``
process that publishes per-engine request counts to the front-end
balancer (vllm/v1/engine/core.py:812, coordinator.py:21). TPU-native
redesign: each replica is a full engine core (scheduler + KV pool) on
its own contiguous device slice of the host mesh; the front-end client
routes by live request count (the coordinator's queue-length publishing
collapses into client-side accounting because one front-end owns all
replicas — a separate coordinator process only pays off with multiple
API servers, which multi-host serving adds later). The reference's
lockstep dummy batches / wave sync (core.py:929-969) are unnecessary
here by construction: expert parallelism spans the ``model`` mesh axis
INSIDE a replica, so idle replicas participate in no collective and can
simply sleep.

Transport per replica follows the parent config: in-process cores for
offline/sync use (each replica's worker re-asserts its own global mesh
per call), or one ZMQ subprocess per replica for serving — the
subprocess layout is what actually overlaps replica compute on CPU
hosts and keeps replicas isolated on TPU hosts.
"""

import copy
from typing import Optional

from vllm_distributed_tpu.config import EngineConfig
from vllm_distributed_tpu.core.sched.scheduler import EngineCoreOutput
from vllm_distributed_tpu.engine.core_client import (EngineCoreClient,
                                                     EngineDeadError,
                                                     InprocClient,
                                                     SyncMPClient)
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.request import EngineCoreRequest

logger = init_logger(__name__)


def _tag_replica(e: EngineDeadError, rank: int) -> EngineDeadError:
    """Re-raise a child client's death with its DP rank attached so the
    front-end (and the server's 503 body) can say WHICH replica died."""
    if e.replica is not None:
        return e
    return EngineDeadError(getattr(e, "reason", str(e)), replica=rank)


def make_replica_config(config: EngineConfig, rank: int) -> EngineConfig:
    """A deep copy of the engine config describing ONE replica: dp size 1
    at dp rank ``rank`` (the worker slices its devices from the rank)."""
    rc = copy.deepcopy(config)
    rc.parallel_config.data_parallel_size = 1
    rc.parallel_config.data_parallel_rank = rank
    return rc


class DPEngineClient(EngineCoreClient):
    """Balancing front-end over data_parallel_size engine replicas."""

    def __init__(self, config: EngineConfig, *,
                 force_mp: Optional[bool] = None) -> None:
        from vllm_distributed_tpu import envs
        n = config.parallel_config.data_parallel_size
        assert n > 1, "DPEngineClient requires data_parallel_size > 1"
        if force_mp is None:
            force_mp = (config.parallel_config.multiprocess_engine_core
                        or envs.VDT_ENABLE_MP_ENGINE)
        self.is_mp = bool(force_mp)
        self.clients: list[EngineCoreClient] = []
        for rank in range(n):
            rc = make_replica_config(config, rank)
            client = SyncMPClient(rc) if self.is_mp else InprocClient(rc)
            self.clients.append(client)
            # Propagate the replica-profiled KV pool size so the parent
            # config reflects reality (replicas are symmetric).
            if rc.cache_config.num_gpu_blocks:
                config.cache_config.num_gpu_blocks = \
                    rc.cache_config.num_gpu_blocks
        logger.info("DP front-end: %d engine replicas (%s)", n,
                    "subprocess" if self.is_mp else "in-process")
        # Optional out-of-process routing brain (reference:
        # coordinator.py DPCoordinator): admission/finish deltas report
        # to it and routing asks it, so multiple front-ends could share
        # the aggregated view.
        self.coordinator = None
        self._coord_proc = None
        if config.parallel_config.data_parallel_coordinator:
            from vllm_distributed_tpu.engine.coordinator import (
                DPCoordinatorClient, spawn_coordinator)
            self._coord_proc, addr = spawn_coordinator(n)
            self._coord_addr = addr
            self.coordinator = DPCoordinatorClient(addr)
            logger.info("DP coordinator process at %s", addr)
        # Balancer state: request ownership + live counts per replica
        # (the coordinator's published queue lengths, client-side).
        self._owner: dict[str, int] = {}
        self._live: list[set[str]] = [set() for _ in range(n)]
        self._rr = 0  # round-robin tiebreak cursor
        # Fan-out utility RPC bookkeeping (async/pump mode).
        self._util_id = 0
        self._pending_util: dict[int, list[tuple]] = {}
        self._util_partial: dict[int, dict[int, object]] = {}

    # ------------------------------------------------------------------
    def _pick_replica(self) -> int:
        if self.coordinator is not None:
            # The coordinator's route() already accounts the admission.
            return self.coordinator.route()
        n = len(self.clients)
        best, best_load = None, None
        for off in range(n):
            i = (self._rr + off) % n
            load = len(self._live[i])
            if best_load is None or load < best_load:
                best, best_load = i, load
        self._rr = (best + 1) % n
        return best

    def add_request(self, request: EngineCoreRequest) -> None:
        i = self._pick_replica()
        self._owner[request.request_id] = i
        self._live[i].add(request.request_id)
        try:
            self.clients[i].add_request(request)
        except Exception as e:
            # Unwind the admission accounting (route() already
            # incremented the coordinator's count).
            self._owner.pop(request.request_id, None)
            self._live[i].discard(request.request_id)
            if self.coordinator is not None:
                self.coordinator.report(i, -1)
            if isinstance(e, EngineDeadError):
                raise _tag_replica(e, i) from e
            raise

    def abort_requests(self, request_ids: list[str]) -> None:
        by_replica: dict[int, list[str]] = {}
        for rid in request_ids:
            i = self._owner.pop(rid, None)
            if i is not None:
                self._live[i].discard(rid)
                by_replica.setdefault(i, []).append(rid)
        for i, rids in by_replica.items():
            self.clients[i].abort_requests(rids)
            if self.coordinator is not None:
                self.coordinator.report(i, -len(rids))

    def _mark_finished(self, outs: list[EngineCoreOutput]) -> None:
        finished_per: dict[int, int] = {}
        for o in outs:
            if o.finished:
                i = self._owner.pop(o.req_id, None)
                if i is not None:
                    self._live[i].discard(o.req_id)
                    finished_per[i] = finished_per.get(i, 0) + 1
        if self.coordinator is not None:
            # One batched delta per replica (output hot path).
            for i, k in finished_per.items():
                self.coordinator.report(i, -k)

    # ------------------------------------------------------------------
    def get_output(self) -> list[EngineCoreOutput]:
        """Merged next outputs across replicas.

        In-process replicas are stepped inline (each busy replica once);
        subprocess replicas are polled, blocking until at least one batch
        arrives while any request is live."""
        outs: list[EngineCoreOutput] = []
        if not self.is_mp:
            for i, client in enumerate(self.clients):
                if self._live[i] or self._has_kv_work(client):
                    # KV-transfer work (deferred sends, held pulls)
                    # needs step-polls even with no live requests.
                    outs.extend(client.get_output())
            self._mark_finished(outs)
            return outs
        while any(self._live):
            for i, client in enumerate(self.clients):
                if not self._live[i]:
                    continue
                try:
                    batch = client.recv_outputs(timeout_ms=20)
                except EngineDeadError as e:
                    raise _tag_replica(e, i) from e
                if batch:
                    outs.extend(batch)
            if outs:
                break
        self._mark_finished(outs)
        return outs

    def recv_outputs(
            self, timeout_ms: int) -> Optional[list[EngineCoreOutput]]:
        """Pump-thread receive (AsyncLLM): poll every replica once within
        the timeout budget; None when nothing arrived."""
        assert self.is_mp, "recv_outputs requires subprocess replicas"
        per = max(timeout_ms // len(self.clients), 1)
        outs: list[EngineCoreOutput] = []
        for i, client in enumerate(self.clients):
            try:
                batch = client.recv_outputs(timeout_ms=per)
            except EngineDeadError as e:
                raise _tag_replica(e, i) from e
            if batch:
                outs.extend(batch)
        self._mark_finished(outs)
        return outs or None

    # ------------------------------------------------------------------
    def send_utility(self, method: str, *args) -> int:
        """Fan a utility RPC out to every replica; the combined result
        lands in fetch_result() once the pump thread drains each child
        (AsyncLLM's thread-safe stats path)."""
        assert self.is_mp
        self._util_id += 1
        self._pending_util[self._util_id] = [
            (idx, c, c.send_utility(method, *args))
            for idx, c in enumerate(self.clients)
        ]
        self._util_partial[self._util_id] = {}
        return self._util_id

    def fetch_result(self, call_id: int, default=None):
        pending = self._pending_util.get(call_id)
        if pending is None:
            return default
        partial = self._util_partial[call_id]
        sentinel = object()
        for idx, client, child_id in pending:
            if idx in partial:
                continue
            value = client.fetch_result(child_id, sentinel)
            if value is not sentinel:
                partial[idx] = value
        if len(partial) < len(pending):
            return default
        del self._pending_util[call_id]
        by_idx = self._util_partial.pop(call_id)
        values = [by_idx[i] for i in range(len(pending))]
        for v in values:
            if isinstance(v, Exception):
                return v
        if all(isinstance(v, dict) for v in values):
            return self._aggregate_stats(values)
        return values

    @staticmethod
    def _has_kv_work(client) -> bool:
        core = getattr(client, "engine_core", None)
        return core is not None and core.has_kv_transfer_work()

    def has_unfinished_requests(self) -> bool:
        return any(self._live)

    def call_utility(self, method: str, *args):
        """Blocking fan-out RPC (sleep/wake_up/profile/...): every
        replica runs it; dict results aggregate, others come back as a
        per-replica list."""
        values = [c.call_utility(method, *args) for c in self.clients]
        if all(isinstance(v, dict) for v in values):
            return self._aggregate_stats(values)
        return values

    def request_counts(self) -> list[int]:
        """Per-replica live request counts (the coordinator's published
        load snapshot; exposed for /metrics and tests)."""
        return [len(s) for s in self._live]

    def _aggregate_stats(self, per: list[dict]) -> dict:
        agg: dict = {"dp_size": len(self.clients),
                     "dp_request_counts": self.request_counts(),
                     "dp_replicas": per}
        # Sum numeric leaves across replicas for the headline counters.
        for stats in per:
            for k, v in stats.items():
                if isinstance(v, (int, float)):
                    agg[k] = agg.get(k, 0) + v
        return agg

    def get_stats(self) -> dict:
        return self._aggregate_stats([c.get_stats() for c in self.clients])

    def shutdown(self) -> None:
        if self.coordinator is not None:
            self.coordinator.shutdown_coordinator()
            self.coordinator.close()
            if self._coord_proc is not None:
                self._coord_proc.join(timeout=5)
            from vllm_distributed_tpu.engine.coordinator import \
                cleanup_socket_dir
            cleanup_socket_dir(self._coord_addr)
        for c in self.clients:
            try:
                c.shutdown()
            except Exception:  # noqa: BLE001 - best-effort teardown
                pass
