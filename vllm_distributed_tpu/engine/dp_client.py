"""Data-parallel engine replication: N engine cores behind one balancer.

Reference: one ``DPEngineCoreProc`` per DP rank plus a ``DPCoordinator``
process that publishes per-engine request counts to the front-end
balancer (vllm/v1/engine/core.py:812, coordinator.py:21). TPU-native
redesign: each replica is a full engine core (scheduler + KV pool) on
its own contiguous device slice of the host mesh; the front-end client
routes by live request count (the coordinator's queue-length publishing
collapses into client-side accounting because one front-end owns all
replicas — a separate coordinator process only pays off with multiple
API servers, which multi-host serving adds later). The reference's
lockstep dummy batches / wave sync (core.py:929-969) are unnecessary
here by construction: expert parallelism spans the ``model`` mesh axis
INSIDE a replica, so idle replicas participate in no collective and can
simply sleep.

Transport per replica follows the parent config: in-process cores for
offline/sync use (each replica's worker re-asserts its own global mesh
per call), or one ZMQ subprocess per replica for serving — the
subprocess layout is what actually overlaps replica compute on CPU
hosts and keeps replicas isolated on TPU hosts.
"""

import copy
import os
import queue
import threading
import time
from typing import Optional

from vllm_distributed_tpu.config import EngineConfig
from vllm_distributed_tpu.core.sched.scheduler import EngineCoreOutput
from vllm_distributed_tpu.engine.core_client import (EngineCoreClient,
                                                     EngineDeadError,
                                                     InprocClient,
                                                     RestartSupervisor,
                                                     SyncMPClient)
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.metrics import events as ev
from vllm_distributed_tpu.request import (EngineCoreRequest,
                                          continuation_request)

logger = init_logger(__name__)


def _tag_replica(e: EngineDeadError, rank: int) -> EngineDeadError:
    """Re-raise a child client's death with its DP rank attached so the
    front-end (and the server's 503 body) can say WHICH replica died."""
    if e.replica is not None:
        return e
    return EngineDeadError(getattr(e, "reason", str(e)), replica=rank)


def make_replica_config(config: EngineConfig, rank: int) -> EngineConfig:
    """A deep copy of the engine config describing ONE replica: dp size 1
    at dp rank ``rank`` (the worker slices its devices from the rank)."""
    rc = copy.deepcopy(config)
    rc.parallel_config.data_parallel_size = 1
    rc.parallel_config.data_parallel_rank = rank
    return rc


class DPEngineClient(EngineCoreClient):
    """Balancing front-end over data_parallel_size engine replicas."""

    def __init__(self, config: EngineConfig, *,
                 force_mp: Optional[bool] = None) -> None:
        from vllm_distributed_tpu import envs
        self.config = config
        n = config.parallel_config.data_parallel_size
        assert n > 1, "DPEngineClient requires data_parallel_size > 1"
        if force_mp is None:
            force_mp = (config.parallel_config.multiprocess_engine_core
                        or envs.VDT_ENABLE_MP_ENGINE)
        self.is_mp = bool(force_mp)
        # Disaggregated serving tier (engine/disagg.py): when VDT_DISAGG
        # is set, the fleet splits into a prefill pool and a decode pool
        # — the plan is computed BEFORE replica construction so each
        # replica's config is specialized for its role (connector side,
        # token budget, precompile lattice, device offset).
        disagg_plan = None
        if envs.VDT_DISAGG:
            from vllm_distributed_tpu.engine.disagg import (
                DisaggCoordinator, specialize_replica_config)
            disagg_plan = DisaggCoordinator.plan_replicas(config)
        self.clients: list[EngineCoreClient] = []
        for rank in range(n):
            rc = make_replica_config(config, rank)
            if disagg_plan is not None:
                role, offset = disagg_plan[rank]
                specialize_replica_config(rc, role, offset)
            client = SyncMPClient(rc) if self.is_mp else InprocClient(rc)
            self.clients.append(client)
            # Propagate the replica-profiled KV pool size so the parent
            # config reflects reality (replicas are symmetric).
            if rc.cache_config.num_gpu_blocks:
                config.cache_config.num_gpu_blocks = \
                    rc.cache_config.num_gpu_blocks
        logger.info("DP front-end: %d engine replicas (%s)", n,
                    "subprocess" if self.is_mp else "in-process")
        # Optional out-of-process routing brain (reference:
        # coordinator.py DPCoordinator): admission/finish deltas report
        # to it and routing asks it, so multiple front-ends could share
        # the aggregated view.
        self.coordinator = None
        self._coord_proc = None
        # _coord_control_only: set when the ONLY coordinator is the one
        # spawned below for VDT_FLEET_CONTROLLER (routing didn't ask
        # for one) — it carries lease/fence/journal ops, never
        # admission accounting, so placement stays byte-identical.
        self._coord_control_only = False
        if config.parallel_config.data_parallel_coordinator:
            from vllm_distributed_tpu.engine.coordinator import (
                DPCoordinatorClient, spawn_coordinator)
            self._coord_proc, addr = spawn_coordinator(n)
            self._coord_addr = addr
            self.coordinator = DPCoordinatorClient(addr)
            logger.info("DP coordinator process at %s", addr)
        # Routing tier (engine/router.py): prefix-affinity + SLO-aware
        # placement over the alive replicas. VDT_ROUTER=0 removes it,
        # reverting placement to the live-count round-robin below. With
        # a coordinator, the router computes the preferred replica and
        # the coordinator keeps the (multi-front-end) admission counts.
        self.router = None
        if envs.VDT_ROUTER:
            from vllm_distributed_tpu.engine.router import ReplicaRouter
            self.router = ReplicaRouter(n, config)
        # Disagg handoff state machine: placement goes two-stage (least-
        # loaded prefill admission, affinity-scored decode home at
        # handoff) and finished prefills re-admit as pull continuations.
        self.disagg = None
        if disagg_plan is not None:
            self.disagg = DisaggCoordinator(self, config)
        # Front-end lifecycle recorder (metrics/events.py): placement
        # decisions and disagg handoffs, drained into the fleet-wide
        # timeline merge next to the per-core rings and fleet events.
        self.events = ev.EventRecorder()
        # Trace plane: replica-tag + clock-rebase drained core rings in
        # _aggregate_stats so the front-end assembler can stitch them.
        # Cached once (the envs registry re-reads os.environ); off
        # leaves every drained event byte-identical.
        self.trace_enabled = ev.trace_plane_enabled()
        # Per-replica monotonic clock offsets (front-end epoch −
        # replica epoch), estimated from the clock_mono reading riding
        # each get_stats response. In-process replicas share the clock
        # (offset ≈ the aggregation delay); subprocess offsets are
        # upper-bounded by one RPC latency.
        self._clock_offsets: dict[int, float] = {}
        # Balancer state: request ownership + live counts per replica
        # (the coordinator's published queue lengths, client-side).
        self._owner: dict[str, int] = {}
        self._live: list[set[str]] = [set() for _ in range(n)]
        self._rr = 0  # round-robin tiebreak cursor
        # Fan-out utility RPC bookkeeping (async/pump mode).
        self._util_id = 0
        self._pending_util: dict[int, list[tuple]] = {}
        self._util_partial: dict[int, dict[int, object]] = {}
        # Balancer-state lock: admissions arrive from AsyncLLM executor
        # threads while failover/finish bookkeeping runs on the pump
        # thread — every _owner/_live/_down/journal mutation (and the
        # client add_request sends they guard) happens under this RLock
        # (reentrant: _admit and _failover call each other). Output
        # POLLS stay outside it so admissions never wait on a poll.
        self._lock = threading.RLock()
        # Failover state: per-request journal (original request +
        # tokens delivered so far) for continuation-prefill migration,
        # replicas currently out of rotation, and a per-replica restart
        # budget gating the resurrection probe.
        self._requests: dict[str, EngineCoreRequest] = {}
        self._progress: dict[str, list[int]] = {}
        self._down: set[int] = set()
        self._supervisors = [RestartSupervisor.from_config(config)
                             for _ in range(n)]
        self._probe_interval = \
            config.fault_tolerance_config.replica_probe_interval_s
        self._next_probe: dict[int, float] = {}
        # In-flight resurrection probes (restart runs on a thread; the
        # result queue hands completion back to the caller's thread).
        self._probing: set[int] = set()
        self._probe_results: "queue.Queue[tuple[int, bool]]" = \
            queue.Queue()
        self.replica_failovers = 0
        self.replica_resurrections = 0
        # Elastic-fleet state (engine/fleet.py). Retired slots keep
        # their index (stable fleet-wide addressing) but leave rotation
        # permanently unless scale-out reuses them; _no_place holds
        # DRAINING replicas — excluded from placement, still polled.
        # Both stay empty with the fleet off, so every membership check
        # below reduces to the pre-fleet behavior.
        self._retired: set[int] = set()
        self._no_place: set[int] = set()
        self.fleet = None
        if envs.VDT_FLEET:
            if envs.VDT_FLEET_CONTROLLER:
                # HA control plane (engine/control_plane.py): lease/
                # fence/journal ops ride the coordinator socket, so
                # spawn one for the control plane if routing didn't —
                # _coord_routes stays False, placement untouched.
                if self.coordinator is None:
                    from vllm_distributed_tpu.engine.coordinator import (
                        DPCoordinatorClient, spawn_coordinator)
                    self._coord_proc, addr = spawn_coordinator(n)
                    self._coord_addr = addr
                    self.coordinator = DPCoordinatorClient(addr)
                    self._coord_control_only = True
                    logger.info("DP coordinator (control plane only) "
                                "at %s", addr)
                from vllm_distributed_tpu.engine.control_plane import \
                    HAFleetController
                self.fleet = HAFleetController(self, config)
            else:
                from vllm_distributed_tpu.engine.fleet import \
                    FleetController
                self.fleet = FleetController(self, config)
        # Correctness sentinel (correctness_plane.py): canary rounds,
        # reference journal, numerics drift, quarantine hints. None by
        # default — VDT_CORRECTNESS=0 constructs nothing and every hook
        # below is a `is not None` short-circuit.
        self.correctness = None
        if envs.VDT_CORRECTNESS:
            from vllm_distributed_tpu.correctness_plane import \
                CorrectnessPlane
            self.correctness = CorrectnessPlane(events=self.events)

    # ------------------------------------------------------------------
    def _pick_replica(self, request: Optional[EngineCoreRequest] = None,
                      count_fallbacks: bool = True) -> int:
        if len(self._down) == len(self.clients):
            raise EngineDeadError("all DP replicas are dead")
        # Draining replicas (fleet retire/convert) leave PLACEMENT but
        # keep serving their live requests; the union is only built
        # when the fleet actually has a drain in flight.
        blocked = (self._down | self._no_place if self._no_place
                   else self._down)
        pool, least_loaded = None, False
        if self.disagg is not None and request is not None:
            # Two-stage disagg placement: fresh requests go to the
            # prefill pool (least-loaded), handoff continuations to the
            # decode pool (affinity + load). An entirely-down pool
            # degrades to any-alive placement (counted once per
            # admission — retries after a failover don't re-count).
            pool = self.disagg.usable_pool(
                self.disagg.target_pool(request), blocked,
                count=count_fallbacks)
            least_loaded = (pool is not None and
                            self.disagg.prefill_least_loaded(request))
        prefer = None
        if self.router is not None:
            self.router.maybe_refresh(self.clients, self._down)
            prefer = self.router.route(request, self.request_counts(),
                                       blocked, pool=pool,
                                       least_loaded=least_loaded)
        if self.coordinator is not None and self._coord_routes:
            try:
                if pool is None:
                    # The coordinator's route() already accounts the
                    # admission (and skips replicas reported down via
                    # set_health); the router's pick rides along as a
                    # preference it honors while that replica is
                    # healthy.
                    return self.coordinator.route(prefer=prefer)
                # Disagg: the coordinator's fleet-wide least-loaded
                # pick (and its healthy-override of `prefer`) cannot
                # honor the pool restriction, so the pick stays local
                # and the admission is accounted to it explicitly —
                # keeping the invariant _admit's unwind relies on
                # (route() would have +1'd the same way).
                pick = (prefer if prefer is not None
                        else self._local_least_loaded(set(pool)))
                self.coordinator.report(pick, 1)
                return pick
            except RuntimeError:
                # Coordinator unreachable. With the HA control plane
                # on this is the coordinator.partition degradation:
                # keep serving with FROZEN placement (local least-
                # loaded below, counted on the freeze ladder). Without
                # it the failure surfaces as before.
                if not self._coord_partition_degraded():
                    raise
        if prefer is not None:
            return prefer
        return self._local_least_loaded(
            set(pool) if pool is not None else None)

    @property
    def _coord_routes(self) -> bool:
        """Whether routing/admission accounting rides the coordinator.
        A property (not an init-time snapshot) so a coordinator
        installed after construction — multi-front-end wiring, test
        stubs — gets the accounting exactly as before the HA plane."""
        return self.coordinator is not None \
            and not self._coord_control_only

    def _coord_partition_degraded(self) -> bool:
        """True iff a coordinator RPC failure should degrade to local
        routing instead of raising: only under the HA control plane,
        whose freeze ladder counts the partition."""
        fleet = self.fleet
        if fleet is None or not getattr(fleet, "ha", False):
            return False
        from vllm_distributed_tpu.engine.fleet import FREEZE_PARTITION
        fleet._freeze(FREEZE_PARTITION)
        return True

    def _coord_report_safe(self, engine: int, delta: int) -> None:
        """Admission-count delta to the coordinator, partition-tolerant:
        under the HA control plane a failed RPC degrades (counted on
        the freeze ladder) instead of raising into the serving path."""
        try:
            self.coordinator.report(engine, delta)
        except RuntimeError:
            if not self._coord_partition_degraded():
                raise

    def _local_least_loaded(self, members: Optional[set]) -> int:
        """Least-live-count replica with rotation tie-break, optionally
        restricted to a member subset (the disagg pool)."""
        n = len(self.clients)
        best, best_load = None, None
        for off in range(n):
            i = (self._rr + off) % n
            if i in self._down or i in self._no_place or (
                    members is not None and i not in members):
                continue
            load = len(self._live[i])
            if best_load is None or load < best_load:
                best, best_load = i, load
        if best is None:
            raise EngineDeadError("all DP replicas are dead")
        self._rr = (best + 1) % n
        return best

    def add_request(self, request: EngineCoreRequest) -> None:
        with self._lock:
            self._requests[request.request_id] = request
            admit_req = request
            if self.disagg is not None:
                # Handoff-eligible requests enter as their one-token
                # prefill-stage copy; the journal keeps the ORIGINAL
                # (the decode-home continuation and any failover replay
                # derive from it).
                admit_req = self.disagg.on_new_request(request)
            try:
                self._admit(admit_req)
            except Exception:
                self._requests.pop(request.request_id, None)
                self._progress.pop(request.request_id, None)
                if self.disagg is not None:
                    self.disagg.forget(request.request_id)
                raise

    def _admit(self, request: EngineCoreRequest) -> None:
        """Place a request on a healthy replica, failing over any
        replica found dead at admission time (its own journaled load
        migrates too), until the request lands or no replica is left."""
        first_pick = True
        while True:
            i = self._pick_replica(request, count_fallbacks=first_pick)
            first_pick = False
            try:
                self.clients[i].add_request(request)
            except Exception as e:
                # Unwind the admission accounting (route() already
                # incremented the coordinator's count).
                if self.coordinator is not None and self._coord_routes:
                    self._coord_report_safe(i, -1)
                if isinstance(e, EngineDeadError):
                    # Dead replica discovered at admission: take it out
                    # of rotation, migrate its load, then retry THIS
                    # request on whatever remains.
                    self._failover(i, e)
                    continue
                raise
            self._owner[request.request_id] = i
            self._live[i].add(request.request_id)
            if self.events.enabled:
                # The routing decision, on the request's causal trace:
                # which replica (and disagg stage, when split) this hop
                # landed on.
                detail: dict = {"replica": i}
                if self.disagg is not None:
                    stage = self.disagg._stage.get(request.request_id)
                    if stage is not None:
                        detail["pool"] = stage
                if self.trace_enabled:
                    detail = ev.stamp_trace(detail, request.trace_ctx)
                self.events.record(request.request_id, ev.ROUTER_PICK,
                                   detail)
            if self.router is not None:
                # Residency bookkeeping: the request's prompt pages will
                # live (and prefix-cache) on this replica. Migrated
                # continuations pass through here too — that re-admit IS
                # the affinity re-homing after a failover.
                self.router.on_admit(request, i)
            return

    def abort_requests(self, request_ids: list[str]) -> None:
        with self._lock:
            by_replica: dict[int, list[str]] = {}
            for rid in request_ids:
                self._requests.pop(rid, None)
                self._progress.pop(rid, None)
                if self.disagg is not None:
                    self.disagg.forget(rid)
                i = self._owner.pop(rid, None)
                if i is not None:
                    self._live[i].discard(rid)
                    by_replica.setdefault(i, []).append(rid)
            for i, rids in by_replica.items():
                try:
                    self.clients[i].abort_requests(rids)
                except Exception:  # noqa: BLE001 - replica dead; its
                    # journal entries are gone, so failover skips them.
                    pass
                if self.coordinator is not None and self._coord_routes:
                    self._coord_report_safe(i, -len(rids))

    def _mark_finished(
            self,
            outs: list[EngineCoreOutput]) -> list[EngineCoreOutput]:
        with self._lock:
            return self._mark_finished_locked(outs)

    def _mark_finished_locked(
            self,
            outs: list[EngineCoreOutput]) -> list[EngineCoreOutput]:
        if self.correctness is not None:
            # Canary absorption FIRST — before disagg interception and
            # before any journal/owner/coordinator bookkeeping. Canary
            # outputs never leave the DP client (that is what keeps
            # probes out of SLO scoring and the output processor), and
            # canaries were never +1'd at the coordinator, so they must
            # not reach the finished_per negative-delta loop either.
            kept = []
            for o in outs:
                if not self.correctness.owns(o.req_id):
                    kept.append(o)
                    continue
                self.correctness.on_output(o)
                if o.finished:
                    i = self._owner.pop(o.req_id, None)
                    if i is not None:
                        self._live[i].discard(o.req_id)
            outs = kept
        if self.disagg is not None:
            # Disagg interception BEFORE any journal/owner bookkeeping:
            # a finished prefill-stage output is swallowed (its sampled
            # token is regenerated by the decode home) and re-admitted
            # to the decode pool with the producer's pull coordinates.
            # Crediting it here instead would register prompt+generated
            # residency against the PREFILL replica — whose pages leave
            # with the pull — so next-turn affinity would route to a
            # replica that holds nothing (the decode-home registration
            # fix).
            outs = self.disagg.intercept(outs)
        finished_per: dict[int, int] = {}
        for o in outs:
            if o.new_token_ids and o.req_id in self._requests:
                # Failover journal: tokens already delivered downstream
                # (a migrated continuation must not regenerate them).
                self._progress.setdefault(o.req_id,
                                          []).extend(o.new_token_ids)
            if o.finished:
                orig = self._requests.pop(o.req_id, None)
                progress = self._progress.pop(o.req_id, None)
                i = self._owner.pop(o.req_id, None)
                if i is not None:
                    self._live[i].discard(o.req_id)
                    finished_per[i] = finished_per.get(i, 0) + 1
                    if self.router is not None and orig is not None:
                        # The finished sequence stays prefix-cached on
                        # its replica: index prompt+generated so the
                        # session's NEXT turn routes home page-exactly.
                        self.router.on_finish(orig, progress or [], i)
        if self.coordinator is not None and self._coord_routes:
            # One batched delta per replica (output hot path).
            for i, k in finished_per.items():
                self._coord_report_safe(i, -k)
        return outs

    # ------------------------------------------------------------------
    # Replica failover + resurrection
    # ------------------------------------------------------------------
    def _failover(self, i: int, err: Exception) -> None:
        """Take replica ``i`` out of rotation and migrate its journaled
        requests to healthy replicas as continuation prefills. Raises
        (tagged) only when no healthy replica remains."""
        with self._lock:
            self._failover_locked(i, err)

    def _failover_locked(self, i: int, err: Exception) -> None:
        if i in self._down:
            return
        self._down.add(i)
        self.replica_failovers += 1
        if self.correctness is not None:
            # The replica's suspicion history (and any in-flight canary)
            # died with it; a respawn starts clean.
            self.correctness.forget_replica(i)
        if self.router is not None:
            # The dead replica's KV pool died with it: drop every
            # affinity hint pointing there. Migrated requests re-home
            # as their continuation re-admits register the new owner.
            self.router.on_replica_down(i)
        self._next_probe[i] = time.monotonic() + self._probe_interval
        stranded = [rid for rid, owner in self._owner.items()
                    if owner == i]
        logger.error(
            "DP replica %d died (%s); failing over %d in-flight "
            "request(s)", i, err, len(stranded))
        if self.coordinator is not None and self._coord_routes:
            # Out of the routing set; clearing the count unwinds the
            # stranded admissions (migration re-reports them against
            # the replicas that absorb the load).
            try:
                self.coordinator.set_health(i, False, clear=True)
            except RuntimeError:
                if not self._coord_partition_degraded():
                    raise
        for rid in stranded:
            self._owner.pop(rid, None)
            self._live[i].discard(rid)
        for rid in stranded:
            orig = self._requests.get(rid)
            if orig is None:
                continue
            req = None
            if self.disagg is not None:
                # A prefill-stage casualty re-enters as a fresh
                # prefill-stage copy (nothing was delivered yet); a
                # decode-stage casualty takes the normal continuation
                # below and stays homed to the decode pool. Both are
                # counted as disagg fallbacks by cause.
                req = self.disagg.readmission_for(
                    rid, orig, self._progress.get(rid, []))
            if req is None:
                req = continuation_request(orig,
                                           self._progress.get(rid, []))
            try:
                self._admit(req)
            except EngineDeadError:
                # No healthy replica absorbed it: every replica is down.
                raise
            logger.info("migrated request %s to replica %d", rid,
                        self._owner[rid])

    def _check_any_alive(self) -> None:
        """Terminal check: with EVERY replica out of rotation the output
        paths would otherwise poll nothing forever — surface the
        deployment-wide death so the upstream supervisor (AsyncLLM) can
        attempt a full-fleet restart, or fail pending requests. Held
        back while a resurrection probe is in flight: a fleet restart
        would race the probe thread's exclusive use of that replica's
        transport."""
        if len(self._down) == len(self.clients) and not self._probing:
            raise EngineDeadError("all DP replicas are dead")

    def _maybe_resurrect(self) -> None:
        """Periodic probe: try to restart downed replicas, budgeted by
        their per-replica supervisor. The restart itself (spawn +
        ready handshake — minutes for a real core) runs on a probe
        THREAD so the output path keeps pumping healthy replicas;
        results apply here, on the caller's thread. A downed replica's
        sockets are untouched by the output path (it is skipped while
        in _down), so the probe thread has exclusive access."""
        with self._lock:
            while True:  # apply finished probe results first
                try:
                    i, ok = self._probe_results.get_nowait()
                except queue.Empty:
                    break
                self._probing.discard(i)
                if not ok:
                    continue
                self._down.discard(i)
                self.replica_resurrections += 1
                if self.coordinator is not None:
                    self.coordinator.set_health(i, True)
                logger.info("DP replica %d resurrected; back in "
                            "rotation", i)
            if not self._down or self._probe_interval <= 0:
                return
            now = time.monotonic()
            for i in sorted(self._down):
                if i in self._probing or now < self._next_probe.get(i, 0):
                    continue
                self._next_probe[i] = now + self._probe_interval
                if self._supervisors[i].next_delay() is None:
                    continue  # budget burnt until the window slides
                self._probing.add(i)
                threading.Thread(target=self._probe_restart, args=(i,),
                                 name=f"dp-resurrect-{i}",
                                 daemon=True).start()

    def _probe_restart(self, i: int) -> None:
        try:
            self.clients[i].restart()
        except Exception as e:  # noqa: BLE001 - still dead
            logger.warning("DP replica %d resurrection failed: %s", i, e)
            self._probe_results.put((i, False))
            return
        self._probe_results.put((i, True))

    def _probe_restart_verified(self, i: int) -> None:
        """Fleet-managed resurrection probe (engine/fleet.py): restart
        PLUS a health verification — a replica that reconnects but
        cannot answer a basic stats probe (its warm start failed)
        reports still-down, so ``replica_resurrections`` only counts
        replicas that actually came back."""
        try:
            self.clients[i].restart()
            self.clients[i].get_stats()
        except Exception as e:  # noqa: BLE001 - still dead (or alive
            # but not serving — same thing to the rotation).
            logger.warning("DP replica %d resurrection failed: %s", i, e)
            self._probe_results.put((i, False))
            return
        self._probe_results.put((i, True))

    def _tick(self) -> None:
        """Periodic maintenance hook on the output paths: the fleet
        controller's loop when VDT_FLEET=1 (which subsumes the
        resurrection probe), the legacy probe otherwise. The
        correctness sentinel's canary injector rides the same tick —
        its quarantine hints land BEFORE fleet.tick() so a hint emitted
        this tick can actuate this tick."""
        if self.correctness is not None:
            self._canary_tick()
        if self.fleet is not None:
            self.fleet.tick()
        else:
            self._maybe_resurrect()

    def _canary_tick(self) -> None:
        """Inject due canary probes and forward quarantine hints. A
        canary bypasses add_request/_admit entirely: no failover
        journal entry (a probe must pin to — and die with — its
        replica), no router residency, no coordinator admission (it is
        admission-exempt by construction). It IS registered in
        _owner/_live so the output paths keep stepping and polling its
        replica like any in-flight request."""
        plane = self.correctness
        with self._lock:
            targets = [i for i in range(len(self.clients))
                       if i not in self._down and i not in self._retired
                       and i not in self._no_place]
            for i, req in plane.due_probes(targets):
                try:
                    self.clients[i].add_request(req)
                except Exception as e:  # noqa: BLE001 - replica dying
                    # mid-probe; its health is the failover ladder's
                    # job, the round just proceeds without it.
                    logger.warning(
                        "canary submit to replica %d failed: %s", i, e)
                    plane.on_submit_failed(req.request_id)
                    continue
                self._owner[req.request_id] = i
                self._live[i].add(req.request_id)
            if self.fleet is not None:
                hints = plane.quarantine_hints()
                if hints:
                    self.fleet.observe_quarantine(hints)

    # ------------------------------------------------------------------
    # Elastic-fleet primitives (engine/fleet.py; balancer lock held)
    # ------------------------------------------------------------------
    def _drain_migrate_locked(self, i: int, report: bool = True) -> None:
        """Journal-migrate replica ``i``'s unfinished requests to the
        rest of the fleet as continuations (token-identical under
        greedy). This is PLANNED movement — a fleet drain deadline or a
        wedge cycle — so unlike _failover_locked nothing here counts as
        a failover or a disagg death fallback. ``report=False`` skips
        the coordinator's negative delta (the wedge path already
        cleared the replica's count wholesale)."""
        stranded = [rid for rid, owner in self._owner.items()
                    if owner == i]
        if not stranded:
            return
        for rid in stranded:
            self._owner.pop(rid, None)
            self._live[i].discard(rid)
        try:
            self.clients[i].abort_requests(stranded)
        except Exception:  # noqa: BLE001 - replica unresponsive; its
            # engine restarts (wedge) or shuts down (retire) anyway.
            pass
        if report and self.coordinator is not None:
            self.coordinator.report(i, -len(stranded))
        logger.info("fleet drain: migrating %d request(s) off "
                    "replica %d", len(stranded), i)
        for rid in stranded:
            orig = self._requests.get(rid)
            if orig is None:
                continue
            req = None
            if self.disagg is not None:
                from vllm_distributed_tpu.engine.disagg import (
                    PREFILL_POOL, prefill_stage_request)
                if self.disagg._stage.get(rid) == PREFILL_POOL:
                    # Prefill-stage work re-enters as a fresh one-token
                    # copy (nothing was delivered yet).
                    req = prefill_stage_request(orig)
            if req is None:
                req = continuation_request(orig,
                                           self._progress.get(rid, []))
            self._admit(req)

    def _spawn_replica(self, i: int,
                       role: Optional[str]) -> EngineCoreClient:
        """Build the engine client for slot ``i`` (fleet scale-out or a
        role conversion), specialized for its disagg role when the
        fleet is disaggregated. Blocking — the fleet controller budgets
        and rate-limits the call."""
        rc = make_replica_config(self.config, i)
        if self.disagg is not None and role is not None:
            from vllm_distributed_tpu.engine.disagg import \
                specialize_replica_config
            offset = self.disagg.device_offset_of(i)
            if offset is None:
                offset = self.disagg.next_device_offset()
            specialize_replica_config(rc, role, offset)
        return SyncMPClient(rc) if self.is_mp else InprocClient(rc)

    def _enter_replica(self, i: int, client: EngineCoreClient,
                       role: Optional[str]) -> None:
        """Wire a freshly spawned replica into rotation at slot ``i``
        — either reusing a retired slot or appending a new rank (which
        grows the router, the coordinator's count table, and the
        per-replica balancer state)."""
        if i == len(self.clients):
            self.clients.append(client)
            self._live.append(set())
            self._supervisors.append(
                RestartSupervisor.from_config(self.config))
            if self.router is not None:
                self.router.grow(1)
            if self.coordinator is not None:
                self.coordinator.resize(len(self.clients))
            if self.disagg is not None:
                self.disagg.add_replica(
                    i, role,
                    device_offset=self.disagg.next_device_offset())
        else:
            self.clients[i] = client
            self._retired.discard(i)
            self._down.discard(i)
            if self.correctness is not None:
                # A reused slot is a NEW engine to the sentinel too.
                self.correctness.forget_replica(i)
            # A reused slot is a NEW engine: fresh restart budget,
            # clean router state (on_replica_down also covers the
            # stale-residency case of a long-retired slot).
            self._supervisors[i] = \
                RestartSupervisor.from_config(self.config)
            if self.router is not None:
                self.router.on_replica_down(i)
            if self.coordinator is not None:
                self.coordinator.set_health(i, True, clear=True)
            if self.disagg is not None and role is not None:
                self.disagg.add_replica(i, role)

    def restart(self) -> None:
        """Full-fleet restart (AsyncLLM's supervisor calls this once
        every replica is dead): every replica respawns and all balancer
        state clears — the upstream journal replays the load."""
        with self._lock:
            for i, client in enumerate(self.clients):
                if i in self._retired:
                    continue  # fleet-retired: already shut down, its
                    # slot only rejoins via a scale-out reuse.
                client.restart()
                if self.coordinator is not None:
                    self.coordinator.set_health(i, True, clear=True)
            self._owner.clear()
            self._requests.clear()
            self._progress.clear()
            self._down.clear()
            self._down.update(self._retired)
            self._no_place.clear()
            self._next_probe.clear()
            for live in self._live:
                live.clear()
            if self.router is not None:
                self.router.reset()
            if self.disagg is not None:
                self.disagg.reset()
            if self.fleet is not None:
                self.fleet.reset()
            if self.correctness is not None:
                for i in range(len(self.clients)):
                    self.correctness.forget_replica(i)

    # ------------------------------------------------------------------
    def get_output(self) -> list[EngineCoreOutput]:
        """Merged next outputs across replicas.

        In-process replicas are stepped inline (each busy replica once);
        subprocess replicas are polled, blocking until at least one batch
        arrives while any request is live."""
        self._tick()
        self._check_any_alive()
        outs: list[EngineCoreOutput] = []
        if not self.is_mp:
            for i, client in enumerate(self.clients):
                if i in self._down:
                    continue
                if self._live[i] or self._has_kv_work(client):
                    # KV-transfer work (deferred sends, held pulls)
                    # needs step-polls even with no live requests.
                    try:
                        outs.extend(client.get_output())
                    except Exception as e:  # noqa: BLE001 - one
                        # replica's step failure is that replica's
                        # death, not the deployment's: fail over.
                        self._failover(i, e)
            return self._mark_finished(outs)
        while any(self._live):
            polled = False
            for i, client in enumerate(self.clients):
                if not self._live[i] or i in self._down:
                    continue
                polled = True
                try:
                    batch = client.recv_outputs(timeout_ms=20)
                except EngineDeadError as e:
                    self._failover(i, _tag_replica(e, i))
                    continue
                if batch:
                    outs.extend(batch)
            if outs:
                break
            if not polled:
                # All live work sits on downed replicas (probe in
                # flight): pace the loop instead of spinning.
                time.sleep(0.02)
                self._tick()
                self._check_any_alive()
        return self._mark_finished(outs)

    def recv_outputs(
            self, timeout_ms: int) -> Optional[list[EngineCoreOutput]]:
        """Pump-thread receive (AsyncLLM): poll every replica once within
        the timeout budget; None when nothing arrived."""
        assert self.is_mp, "recv_outputs requires subprocess replicas"
        self._tick()
        self._check_any_alive()
        per = max(timeout_ms // len(self.clients), 1)
        outs: list[EngineCoreOutput] = []
        polled = False
        for i, client in enumerate(self.clients):
            if i in self._down:
                continue
            polled = True
            try:
                batch = client.recv_outputs(timeout_ms=per)
            except EngineDeadError as e:
                self._failover(i, _tag_replica(e, i))
                continue
            if batch:
                outs.extend(batch)
        if not polled:
            # Every replica is down (resurrection probe in flight):
            # honor the caller's poll budget instead of busy-spinning
            # the pump thread for the probe's whole duration.
            time.sleep(timeout_ms / 1000)
            return None
        outs = self._mark_finished(outs)
        return outs or None

    # ------------------------------------------------------------------
    def send_utility(self, method: str, *args) -> int:
        """Fan a utility RPC out to every replica; the combined result
        lands in fetch_result() once the pump thread drains each child
        (AsyncLLM's thread-safe stats path)."""
        assert self.is_mp
        self._util_id += 1
        self._pending_util[self._util_id] = [
            (idx, c, c.send_utility(method, *args))
            for idx, c in enumerate(self.clients)
            if idx not in self._down
        ]
        self._util_partial[self._util_id] = {}
        return self._util_id

    def fetch_result(self, call_id: int, default=None):
        pending = self._pending_util.get(call_id)
        if pending is None:
            return default
        partial = self._util_partial[call_id]
        sentinel = object()
        for idx, client, child_id in pending:
            if idx in partial:
                continue
            value = client.fetch_result(child_id, sentinel)
            if value is not sentinel:
                partial[idx] = value
        if len(partial) < len(pending):
            return default
        del self._pending_util[call_id]
        by_idx = self._util_partial.pop(call_id)
        # Key by the REPLICA index recorded at send time: with a
        # replica down, positions and replica indices diverge.
        indices = [idx for idx, _, _ in pending]
        values = [by_idx[idx] for idx in indices]
        for v in values:
            if isinstance(v, Exception):
                return v
        if all(isinstance(v, dict) for v in values):
            return self._aggregate_stats(values, indices=indices)
        return values

    @staticmethod
    def _has_kv_work(client) -> bool:
        core = getattr(client, "engine_core", None)
        return core is not None and core.has_kv_transfer_work()

    def has_unfinished_requests(self) -> bool:
        return any(self._live)

    def call_utility(self, method: str, *args):
        """Blocking fan-out RPC (sleep/wake_up/profile/...): every
        replica runs it; dict results aggregate, others come back as a
        per-replica list."""
        alive = [i for i in range(len(self.clients))
                 if i not in self._down]
        values = [self.clients[i].call_utility(method, *args)
                  for i in alive]
        if method == "get_debug_state":
            # Introspection dicts must NOT be stats-aggregated: summing
            # per-replica config/bool fields (async_scheduling,
            # batch_queue_size, ...) fabricates values. Hand back the
            # raw per-replica states under the key _core_debug_states
            # already consumes.
            return {"dp_replicas": values}
        if values and all(isinstance(v, dict) for v in values):
            return self._aggregate_stats(values, indices=alive)
        return values

    def request_counts(self) -> list[int]:
        """Per-replica live request counts (the coordinator's published
        load snapshot; exposed for /metrics and tests)."""
        return [len(s) for s in self._live]

    def _aggregate_stats(self, per: list[dict],
                         indices: Optional[list[int]] = None) -> dict:
        # getattr: stats-aggregation tests build this client via
        # __new__ with only the balancer fields they exercise.
        router = getattr(self, "router", None)
        fleet = getattr(self, "fleet", None)
        if router is not None and indices is not None:
            # Passive routing-signal feed: every stats poll that already
            # flows through here (the /metrics scrape, the admission
            # gate's KV sampler) refreshes the router's per-replica load
            # snapshots — the "existing get_stats RPC" channel.
            for i, stats in zip(indices, per):
                router.observe_stats(i, stats)
        if fleet is not None and indices is not None:
            # Same passive channel feeds the fleet controller's
            # occupancy/step-heartbeat signals (subprocess replicas are
            # never polled by the control loop itself).
            for i, stats in zip(indices, per):
                fleet.observe_stats(i, stats)
        if getattr(self, "trace_enabled", False) and indices is not None:
            # Cross-process clock alignment: each replica's clock_mono
            # reading pairs with the front-end clock sampled here. The
            # estimate over-corrects by up to one RPC latency
            # (in-process replicas share the clock, so ~0); drained
            # ring events re-base into the front-end epoch and are
            # replica-tagged so the trace assembler knows which pid
            # lane each span belongs to.
            offsets = getattr(self, "_clock_offsets", {})
            now = time.monotonic()
            for i, stats in zip(indices, per):
                cm = stats.get("clock_mono")
                if isinstance(cm, (int, float)):
                    offsets[i] = now - cm
                evs = stats.get("timeline_events")
                if evs:
                    off = offsets.get(i, 0.0)
                    stats["timeline_events"] = [
                        [e[0] + off, e[1], e[2],
                         {**(e[3] if isinstance(e[3], dict) else {}),
                          ev.REPLICA_KEY: i}]
                        for e in evs]
        agg: dict = {"dp_size": len(self.clients),
                     "dp_request_counts": self.request_counts(),
                     "dp_replicas": per,
                     "dp_replicas_down": sorted(self._down),
                     "replica_failovers": self.replica_failovers,
                     "replica_resurrections":
                         self.replica_resurrections}
        # Sum numeric leaves across replicas for the headline counters
        # (this loop is also what merges the flat vdt:ssm_* state-cache
        # families — hits/queries/evictions/checkpoints sum, and
        # bytes_held sums to the fleet's snapshot footprint);
        # ratio gauges average instead (a 4-replica deployment at 25%
        # KV usage is at 25%, not 100% — the admission gate's KV shed
        # reads this value), and peak gauges take the max (summing
        # per-replica peaks would fabricate overlap that never
        # happened: 4 sync replicas at depth 1 are depth 1, not 4).
        ratio_gauges = ("kv_cache_usage", "spec_acceptance_rate",
                        "decode_overlap_frac")
        max_gauges = ("max_concurrent_batches", )
        for stats in per:
            for k, v in stats.items():
                if k == "clock_mono":
                    continue  # per-process clock reading, not a stat
                if k in max_gauges:
                    agg[k] = max(agg.get(k, 0), v)
                elif isinstance(v, (int, float)):
                    agg[k] = agg.get(k, 0) + v
        for k in ratio_gauges:
            if k in agg and per:
                agg[k] = agg[k] / len(per)
        # Histogram-shaped entries merge element-wise so DP /metrics
        # renders the fleet histogram instead of silently dropping it.
        from vllm_distributed_tpu.metrics.stats import \
            merge_histogram_dicts
        merged_gap = merge_histogram_dicts(
            [s.get("step_host_gap_seconds") for s in per])
        if merged_gap is not None:
            agg["step_host_gap_seconds"] = merged_gap
        # Attention kernel dispatch counts: {kernel: steps}, summed per
        # kernel label across replicas (a dict, so the flat numeric-sum
        # loop above skipped it).
        call_maps = [s["attn_kernel_calls"] for s in per
                     if isinstance(s.get("attn_kernel_calls"), dict)]
        if call_maps:
            merged_calls: dict = {}
            for m in call_maps:
                for k, v in m.items():
                    merged_calls[k] = merged_calls.get(k, 0) + int(v)
            agg["attn_kernel_calls"] = merged_calls
        # Fused-block fallback reasons: {reason: steps}, summed like the
        # kernel dispatch map (block_fusion_calls itself is a flat
        # numeric and already summed above).
        fb_maps = [s["block_fusion_fallbacks"] for s in per
                   if isinstance(s.get("block_fusion_fallbacks"), dict)]
        if fb_maps:
            merged_fb: dict = {}
            for m in fb_maps:
                for k, v in m.items():
                    merged_fb[k] = merged_fb.get(k, 0) + int(v)
            agg["block_fusion_fallbacks"] = merged_fb
        # Per-tenant QoS families: {tenant: {granted_tokens, kv_blocks,
        # preemptions}}, summed per tenant per leaf across replicas
        # (every scheduler buckets through qos.bucket_tenant so each
        # replica's key space is bounded; note the first-come tracked
        # set is per replica, so past VDT_QOS_MAX_TRACKED_TENANTS a
        # tenant routed to several replicas may appear tracked-by-name
        # on one and as an overflow "~n" bucket on another — the merge
        # stays bounded but such a tenant's series split across the
        # two labels. Counters and the kv_blocks gauge both sum — a
        # tenant's fleet page footprint is the sum of its per-replica
        # footprints).
        tenant_maps = [s["tenants"] for s in per
                       if isinstance(s.get("tenants"), dict)]
        if tenant_maps:
            merged_tenants: dict = {}
            for m in tenant_maps:
                for t, leaves in m.items():
                    if not isinstance(leaves, dict):
                        continue
                    dst = merged_tenants.setdefault(t, {})
                    for k, v in leaves.items():
                        dst[k] = dst.get(k, 0) + int(v)
            agg["tenants"] = merged_tenants
        # Performance-attribution plane: nested numeric maps summed per
        # label across replicas — hbm_bytes {kind: bytes}, perf_attrib
        # {key: {device_seconds, flops, bytes, dispatches}} and
        # perf_phases {phase: {...}} (model_flops is flat and already
        # summed above; mfu/mbu are per-worker ratios riding the
        # workers map union below, never summed). Peaks take the max —
        # replicas share identical hardware, and summing a peak would
        # fabricate a fleet-wide roofline no chip has.
        for perf_key in ("hbm_bytes", "perf_attrib", "perf_phases"):
            maps = [s[perf_key] for s in per
                    if isinstance(s.get(perf_key), dict)]
            if not maps:
                continue
            merged_perf: dict = {}
            for m in maps:
                for k, v in m.items():
                    if isinstance(v, dict):
                        dst = merged_perf.setdefault(k, {})
                        for leaf, n in v.items():
                            dst[leaf] = dst.get(leaf, 0) + n
                    elif isinstance(v, (int, float)):
                        merged_perf[k] = merged_perf.get(k, 0) + v
            agg[perf_key] = merged_perf
        peak_maps = [s["perf_peaks"] for s in per
                     if isinstance(s.get("perf_peaks"), dict)]
        if peak_maps:
            agg["perf_peaks"] = {
                k: max(float(p.get(k, 0.0)) for p in peak_maps)
                for k in {k for p in peak_maps for k in p}}
        # Step-phase family: {phase -> histogram dict}, merged per phase.
        phase_maps = [s["step_phase_seconds"] for s in per
                      if isinstance(s.get("step_phase_seconds"), dict)]
        if phase_maps:
            merged_phases = {}
            for phase in sorted({p for m in phase_maps for p in m}):
                h = merge_histogram_dicts(
                    [m.get(phase) for m in phase_maps])
                if h is not None:
                    merged_phases[phase] = h
            agg["step_phase_seconds"] = merged_phases
        # Telemetry plane: per-worker maps union (labels are
        # fleet-unique, so no counter is ever summed twice), transport
        # snapshots merge per connector/side label, block-pool stats
        # sum counts / average ratios. None of these ride the flat
        # numeric-sum loop above — summing a peak HBM gauge or a
        # replica's inflight map would fabricate fleet state.
        from vllm_distributed_tpu.metrics import telemetry
        workers = telemetry.merge_worker_telemetry(
            [s.get("workers") for s in per])
        if workers:
            agg["workers"] = workers
        transport = telemetry.merge_transport_snapshots(
            [s.get("transport") for s in per])
        if transport is not None:
            agg["transport"] = transport
        kv_cache = telemetry.merge_kv_cache_stats(
            [s.get("kv_cache") for s in per])
        if kv_cache is not None:
            agg["kv_cache"] = kv_cache
        # Hierarchical KV tiering: {pages/bytes/demotions/promotions/
        # misses: {tier: n}} sum per tier per leaf, the promotion
        # histogram merges element-wise, and the (destructively
        # drained) router transition feed was already consumed by
        # router.observe_stats above — it never reaches the merged
        # view.
        tier_maps = [s["kv_tier"] for s in per
                     if isinstance(s.get("kv_tier"), dict)]
        if tier_maps:
            merged_tier: dict = {}
            for m in tier_maps:
                for k, v in m.items():
                    if k in ("transitions", "promotion_seconds"):
                        continue
                    if isinstance(v, dict):
                        dst = merged_tier.setdefault(k, {})
                        for tier_name, n in v.items():
                            if isinstance(n, (int, float)):
                                dst[tier_name] = \
                                    dst.get(tier_name, 0) + n
                    elif isinstance(v, (int, float)):
                        merged_tier[k] = merged_tier.get(k, 0) + v
            promo = merge_histogram_dicts(
                [m.get("promotion_seconds") for m in tier_maps])
            if promo is not None:
                merged_tier["promotion_seconds"] = promo
            agg["kv_tier"] = merged_tier
        # Follower-process counter snapshots (pid-tagged by each core's
        # get_stats): merge once per distinct follower pid, excluding
        # this process — in-process cores share the front-end's
        # process-global registries, so summing them would double-count
        # what render_fault_injections / merged_qcomm_view already read
        # locally. The merged remote view makes /metrics fleet-exact.
        merged_fi: dict = {}
        merged_qc: dict = {"bytes_saved": {}, "fallbacks": {}}
        seen_fi = {os.getpid()}
        seen_qc = {os.getpid()}
        for s in per:
            snap = s.get("fault_injection_counts")
            if (isinstance(snap, dict) and snap.get("pid") not in seen_fi
                    and isinstance(snap.get("counts"), dict)):
                seen_fi.add(snap["pid"])
                for k, v in snap["counts"].items():
                    merged_fi[k] = merged_fi.get(k, 0) + int(v)
            snap = s.get("qcomm_traced")
            if isinstance(snap, dict) and snap.get("pid") not in seen_qc:
                seen_qc.add(snap.get("pid"))
                for fam in ("bytes_saved", "fallbacks"):
                    for k, v in (snap.get(fam) or {}).items():
                        dst = merged_qc[fam]
                        dst[k] = dst.get(k, 0) + int(v)
        if merged_fi:
            agg["fault_injection_counts_remote"] = merged_fi
        if merged_qc["bytes_saved"] or merged_qc["fallbacks"]:
            agg["qcomm_traced_remote"] = merged_qc
        # Lifecycle timelines: one fleet-wide event stream, time-sorted
        # (per-core rings, the fleet controller's actuations, and the
        # front-end's own placement/handoff ring).
        from vllm_distributed_tpu.metrics.events import merge_event_lists
        events = merge_event_lists(
            *(s.get("timeline_events") or [] for s in per),
            *([fleet.drain_events()] if fleet is not None else []),
            *([getattr(self, "events", None).drain()]
              if getattr(self, "events", None) is not None else []))
        if events:
            agg["timeline_events"] = events
        # Routing tier: ONE router instance owns the whole fleet's
        # placement, so its counters attach exactly — nothing to merge.
        if router is not None:
            agg["router"] = router.get_stats()
        # Disagg serving tier: one coordinator owns every handoff, so
        # its counters/histogram attach exactly too.
        disagg = getattr(self, "disagg", None)
        if disagg is not None:
            agg["disagg"] = disagg.get_stats(self.request_counts())
        # Elastic-fleet controller: one loop owns the whole fleet's
        # shape, so its counters attach exactly too.
        if fleet is not None:
            agg["fleet"] = fleet.get_stats()
        # Correctness sentinel: the runners' numerics snapshots are
        # PER-REPLICA by construction (entropy means, NaN counters,
        # histograms — a cross-replica numeric sum would erase exactly
        # the per-replica drift the sentinel exists to see), so they
        # re-key by replica index here; dead replicas fell out of
        # ``per`` at the get_stats poll, so a mid-scrape death is
        # excluded naturally. The same poll drives the plane's
        # NaN-delta / drift ladders, and the plane's own counters
        # attach exactly (one plane owns the fleet's canaries).
        plane = getattr(self, "correctness", None)
        if indices is not None:
            numerics = {i: s["numerics"] for i, s in zip(indices, per)
                        if isinstance(s.get("numerics"), dict)}
            if numerics:
                agg["numerics"] = numerics
                if plane is not None:
                    plane.observe_numerics(numerics)
        if plane is not None:
            agg["correctness"] = plane.get_stats()
        return agg

    def get_stats(self) -> dict:
        alive = [i for i in range(len(self.clients))
                 if i not in self._down]
        return self._aggregate_stats(
            [self.clients[i].get_stats() for i in alive], indices=alive)

    def observe_goodput(self, fracs: dict,
                        degraded: bool = False) -> None:
        """Per-tenant goodput feed (metrics/stats.py FrontendStats SLO
        scoring, wired from the entrypoints' stats path) into the
        fleet's VDT_FLEET_SIGNALS scale decision. ``degraded`` is the
        burn-rate watchdog's sustained-burn flag, offered as scale-up
        pressure on the same channel. No-op without a fleet
        controller."""
        if self.fleet is not None and isinstance(fracs, dict):
            self.fleet.observe_goodput(fracs, degraded=degraded)

    def shutdown(self) -> None:
        if self.fleet is not None:
            try:
                self.fleet.close()  # HA: relinquish the lease cleanly
            except Exception:  # noqa: BLE001 - best-effort teardown
                pass
        if self.coordinator is not None:
            self.coordinator.shutdown_coordinator()
            self.coordinator.close()
            if self._coord_proc is not None:
                self._coord_proc.join(timeout=5)
            from vllm_distributed_tpu.engine.coordinator import \
                cleanup_socket_dir
            cleanup_socket_dir(self._coord_addr)
        for c in self.clients:
            try:
                c.shutdown()
            except Exception:  # noqa: BLE001 - best-effort teardown
                pass
