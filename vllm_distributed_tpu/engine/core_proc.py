"""Engine core as a subprocess: ZMQ transport + busy loop.

Reference: vllm/v1/engine/core.py:362 (``EngineCoreProc``: run_busy_loop
:598, _process_input_queue :608, _send_engine_dead :679). The TPU variant
keeps the same actor shape — requests in over one socket, outputs out over
another, a ready handshake, and a dead sentinel — with msgpack instead of
msgspec and a single-threaded poll loop (the GIL-heavy input/output
threads of the reference buy nothing under an in-process XLA dispatch).
"""

import queue
import signal
import threading
import time
import traceback

import zmq

from vllm_distributed_tpu.engine import serial
from vllm_distributed_tpu.engine.core import EngineCore
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.utils import fault_injection

logger = init_logger(__name__)

# Poll interval while idle (ms); while busy the input queue is drained
# without blocking between steps.
_IDLE_POLL_MS = 100


def run_engine_core(config, input_addr: str, output_addr: str) -> None:
    """Subprocess entry: build the core, handshake, busy-loop until a
    shutdown message (or parent death) arrives."""
    # Die cleanly with the parent instead of leaking the device.
    signal.signal(signal.SIGTERM, lambda *_: _raise_shutdown())

    ctx = zmq.Context()
    inp = ctx.socket(zmq.PULL)
    inp.connect(input_addr)
    out = ctx.socket(zmq.PUSH)
    out.connect(output_addr)

    core = None
    hb_stop = threading.Event()
    try:
        core = EngineCore(config)
        out.send(serial.pack({
            "t": "ready",
            "num_pages": config.cache_config.num_gpu_blocks,
        }))
        # Liveness heartbeat on its own thread + its own PUSH socket
        # (zmq sockets are not thread-safe; multiple PUSH sockets may
        # connect to one PULL endpoint). It keeps beating through long
        # compiles — XLA releases the GIL — so the client's staleness
        # window only fires when the whole process is wedged or dead.
        interval = config.fault_tolerance_config.heartbeat_interval_s
        if interval > 0:
            threading.Thread(target=_heartbeat_loop,
                             args=(ctx, output_addr, interval, hb_stop),
                             name="engine-core-heartbeat",
                             daemon=True).start()
        _busy_loop(core, inp, out)
    except _Shutdown:
        pass
    except Exception as e:  # noqa: BLE001 - report then die
        logger.error("engine core died: %s", e)
        traceback.print_exc()
        try:
            out.send(serial.pack({
                "t": "dead",
                "error": f"{type(e).__name__}: {e}",
            }))
            time.sleep(0.2)  # let the sentinel flush
        except Exception:
            pass
    finally:
        hb_stop.set()
        if core is not None:
            core.shutdown()
        inp.close(linger=0)
        out.close(linger=0)
        ctx.term()


def _heartbeat_loop(ctx: zmq.Context, output_addr: str, interval: float,
                    stop: threading.Event) -> None:
    """Liveness beats to the client (reference analogue: the reference
    core's EngineCoreProc monitor thread / process liveness checks)."""
    sock = ctx.socket(zmq.PUSH)
    # Bounded send: a PUSH with no live peer blocks forever by default,
    # which would wedge this thread (and ctx.term) after parent death.
    sock.setsockopt(zmq.SNDTIMEO, 1000)
    sock.connect(output_addr)
    try:
        while not stop.wait(interval):
            if fault_injection.should_fire("heartbeat.stall"):
                continue  # injected stall: skip this beat
            try:
                sock.send(serial.pack(  # wallclock-ok: informational beat ts
                    {"t": "hb", "ts": time.time()}))
            except zmq.Again:
                # Transient: the client hasn't drained in a while (idle
                # sync user) and the HWM is full. Keep beating — exiting
                # here would later declare a HEALTHY core dead on its
                # first legitimate long stall.
                continue
            except zmq.ZMQError:
                return  # terminal (ctx terminated / socket closed)
    finally:
        try:
            sock.close(linger=0)
        except Exception:  # noqa: BLE001 - teardown race with ctx.term
            pass


def _try_add(core: EngineCore, req):
    """Add a request; a rejectable failure (e.g. a grammar the
    front-end validator missed) must not take the busy loop down — it
    bounces back as an aborted output so the client unblocks. Returns
    the synthetic output, or None on success."""
    try:
        core.add_request(req)
        return None
    except Exception as e:  # noqa: BLE001 - any admission failure is
        # rejectable (grammar compile, tokenizer load, bad params);
        # request state hasn't entered the scheduler yet, so bouncing is
        # always safe and beats killing every in-flight request.
        logger.warning("rejected request %s: %s", req.request_id, e)
        from vllm_distributed_tpu.core.sched.scheduler import \
            EngineCoreOutput
        return EngineCoreOutput(req_id=req.request_id, new_token_ids=[],
                                finish_reason="abort")


class _Shutdown(Exception):
    pass


def _raise_shutdown() -> None:
    raise _Shutdown()


def _handle_msg(core: EngineCore, out: zmq.Socket, msg: dict) -> None:
    t = msg["t"]
    if t == "add":
        req = serial.decode_request(msg["req"])
        rejected = _try_add(core, req)
        if rejected is not None:
            out.send(serial.pack({
                "t": "outputs",
                "outs": [serial.encode_output(rejected)],
            }))
    elif t == "abort":
        core.abort_requests(list(msg["ids"]))
    elif t == "call":
        # Generic utility RPC (get_stats, profiling hooks, ...). A bad
        # RPC must not take the core (and every in-flight request) down:
        # failures travel back as an error result.
        try:
            value = getattr(core, msg["method"])(*msg.get("args", ()))
            reply = {"t": "result", "call_id": msg["call_id"],
                     "value": value}
            out.send(serial.pack(reply))
        except Exception as e:  # noqa: BLE001 - reported to caller
            logger.warning("utility RPC %s failed: %s", msg["method"], e)
            out.send(serial.pack({
                "t": "result", "call_id": msg["call_id"], "value": None,
                "error": f"{type(e).__name__}: {e}",
            }))
    elif t == "shutdown":
        raise _Shutdown()
    else:  # pragma: no cover - protocol error
        raise ValueError(f"unknown message type {t!r}")


def _busy_loop(core: EngineCore, inp: zmq.Socket, out: zmq.Socket) -> None:
    """reference: core.py:598 run_busy_loop — block on input when idle,
    otherwise drain input without blocking and step."""
    poller = zmq.Poller()
    poller.register(inp, zmq.POLLIN)
    while True:
        fault_injection.fire_or_raise("engine_core.die")
        busy = (core.has_unfinished_requests()
                or core.has_kv_transfer_work())
        timeout = 0 if busy else _IDLE_POLL_MS
        while poller.poll(timeout):
            _handle_msg(core, out, serial.unpack(inp.recv()))
            timeout = 0
        if not (core.has_unfinished_requests()
                or core.has_kv_transfer_work()):
            continue
        outputs = core.step()
        if outputs:
            out.send(serial.pack({
                "t": "outputs",
                "outs": [serial.encode_output(o) for o in outputs],
            }))
        elif (not core.last_step_scheduled
              and not core.has_inflight_batches()):
            # Nothing ran on device (all requests held on async KV
            # transfers / deferred sends): each step is a host-only
            # poll, so pace it instead of busy-spinning a core for the
            # transfer's duration. Never pace while a dispatched batch
            # awaits its wait_model — sleeping there would park the
            # retire (and the next dispatch) behind the sleep quantum.
            time.sleep(0.005)


# ---------------------------------------------------------------------------
# In-process background core (thread) — used by AsyncLLM when a subprocess
# is unnecessary; shares run-loop semantics with the proc variant.
# ---------------------------------------------------------------------------


class BackgroundEngineCore:
    """EngineCore driven by a daemon thread with queue transport.

    Same contract as the ZMQ proc (add/abort in, output batches out) for
    single-process async serving; reference analogue: the in-process
    core_client InprocClient paired with AsyncLLM's output handler.
    """

    def __init__(self, config) -> None:
        fault_injection.fire_or_raise("core_proc.spawn_fail")
        self.config = config
        self.core = EngineCore(config)
        self.input_queue: "queue.Queue[tuple]" = queue.Queue()
        self.output_queue: "queue.Queue[object]" = queue.Queue()
        self._dead = False
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="engine-core")
        self._thread.start()

    def restart(self) -> None:
        """Rebuild the core + run thread after a death. The queue
        OBJECTS survive (an add_request racing the restart lands in the
        same input queue the fresh thread drains); stale items queued
        before the restart are discarded first. In-flight request state
        is gone — the caller replays its journal."""
        fault_injection.fire_or_raise("core_proc.spawn_fail")
        try:
            self.core.shutdown()
        except Exception:  # noqa: BLE001 - dead core teardown
            pass
        for q in (self.input_queue, self.output_queue):
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break
        self.core = EngineCore(self.config)
        self._dead = False
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="engine-core")
        self._thread.start()

    def check_health(self) -> None:
        """Raise EngineDeadError when the core thread died without
        reporting its error (reference: v1 core_client engine-dead
        detection; here the thread-transport analogue). No staleness
        window for the thread transport: in-process, a wedged step is
        indistinguishable from a legitimate long first compile (the
        subprocess transport gets stall detection from its dedicated
        heartbeat thread, which keeps beating through compiles)."""
        from vllm_distributed_tpu.engine.core_client import EngineDeadError
        if self._dead:
            return  # the terminal error is already in the output queue
        if not self._thread.is_alive():
            raise EngineDeadError(
                "engine core thread exited without reporting")

    def _run(self) -> None:
        try:
            has_kv_connector = \
                self.core.scheduler.kv_connector is not None
            while True:
                fault_injection.fire_or_raise("engine_core.die")
                busy = (self.core.has_unfinished_requests()
                        or self.core.has_kv_transfer_work())
                block = not busy
                # Bounded block only when a KV connector exists: async
                # work can then arrive from a peer's socket with no local
                # input message. Without one, idle blocks indefinitely.
                idle_timeout = 0.05 if has_kv_connector else None
                try:
                    while True:
                        kind, payload = self.input_queue.get(
                            block=block,
                            timeout=idle_timeout if block else 0)
                        if kind == "add":
                            rejected = _try_add(self.core, payload)
                            if rejected is not None:
                                self.output_queue.put([rejected])
                        elif kind == "abort":
                            self.core.abort_requests(payload)
                        elif kind == "shutdown":
                            return
                        block = False
                except queue.Empty:
                    pass
                outputs = self.core.step()
                if outputs:
                    self.output_queue.put(outputs)
                elif (busy and not self.core.last_step_scheduled
                      and not self.core.has_inflight_batches()):
                    # Host-only poll step (async KV transfer in
                    # flight): pace instead of spinning. A pending
                    # wait_model is NOT paced — the retire must chase
                    # the device, not a sleep quantum.
                    time.sleep(0.005)
        except Exception as e:  # noqa: BLE001
            logger.error("background engine core died: %s", e)
            traceback.print_exc()
            self._dead = True
            self.output_queue.put(e)

    @property
    def is_alive(self) -> bool:
        return self._thread.is_alive() and not self._dead

    def add_request(self, req) -> None:
        self.input_queue.put(("add", req))

    def abort_requests(self, ids: list[str]) -> None:
        self.input_queue.put(("abort", ids))

    def shutdown(self) -> None:
        self.input_queue.put(("shutdown", None))
        self._thread.join(timeout=5)
        self.core.shutdown()
