"""Disaggregated prefill/decode serving tier: two pools, routed handoff.

The paper's own framing (PAPERS.md "TPLA ... for Efficient Disaggregated
Prefill and Decode Inference"; reference architecture: vLLM's NIXL
connector + P/D proxy) separates the two phases of a generation onto
replicas SPECIALIZED for them, because they want opposite things:

* **prefill** is compute-bound and wants big token buckets and chunked
  prefill — and fleet-wide, a long prompt admitted to a mixed replica
  steals decode steps from every interactive stream on it (each mixed
  wave pads to the large token bucket the prefill chunk forces);
* **decode** is bandwidth-bound and wants deep pure-decode batches,
  TPLA/block-fusion-shaped kernels, and a SMALL compiled lattice.

``DisaggCoordinator`` composes the pieces previous PRs built — the
versioned standard/latent KV wire formats that re-slice across
asymmetric TP meshes, the quantized payload codec, the dcn_pull
connector with its deferred-free / watchdog / local-recompute recovery
ladder, and the prefix/SLO-aware ``ReplicaRouter`` — into that topology
behind ``DPEngineClient``:

1. **Admission** — a fresh request is placed on the least-loaded
   *prefill-pool* replica as a one-token *prefill-stage* copy
   (``max_tokens=1``: the prefill replica computes the whole prompt's
   KV, samples once, and finishes — it never decodes).
2. **Handoff** — the prefill replica's final output carries the
   producer's ``kv_transfer_params`` (deferred pages + pull
   coordinates). The coordinator intercepts that finish BEFORE any
   balancer bookkeeping (its sampled token is never delivered — the
   decode home regenerates it, token-identically under greedy), picks
   the *decode home* by prefix affinity + load among the decode pool,
   and re-admits the ORIGINAL request there with the pull coordinates
   attached. The decode home pulls the prompt pages over the existing
   connector (quantized codec + latent wire format, so asymmetric
   prefill-TP <-> decode-TP meshes work), computes only the prompt
   tail, and serves the whole decode.
3. **Recovery** — the PR 1/2 ladder holds end to end: a handoff pull
   that times out, is rejected, or CRC-fails degrades through bounded
   pull retries to LOCAL re-prefill on the decode home (the decode
   pool keeps chunked prefill exactly for this, with chunks capped at
   its small token budget); a prefill replica that dies mid-handoff
   has its stranded prefill-stage requests re-admitted to the
   surviving prefill pool; a decode home that dies re-admits its
   continuations (prompt + delivered tokens) inside the decode pool.
   Every fallback is counted by reason.

Kill switch: ``VDT_DISAGG`` (default 0) — off, ``DPEngineClient`` is
byte-identical to the monolithic balancer. Telemetry:
``vdt:disagg_handoffs_total``, ``vdt:disagg_handoff_seconds``,
``vdt:disagg_fallbacks_total{reason}``, ``vdt:pool_occupancy{pool}``.
"""

import copy
import time
from typing import Optional

from vllm_distributed_tpu.config import EngineConfig
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.metrics import events as ev
from vllm_distributed_tpu.metrics.stats import TTFT_BUCKETS, Histogram
from vllm_distributed_tpu.request import EngineCoreRequest
from vllm_distributed_tpu.utils import fault_injection

logger = init_logger(__name__)

PREFILL_POOL = "prefill"
DECODE_POOL = "decode"

# Fallback reasons surfaced as vdt:disagg_fallbacks_total{reason}.
FALLBACK_LOCAL_REPREFILL = "local_reprefill"  # pull failed -> recompute
FALLBACK_PULL_RETRY = "pull_retry"  # pull failed -> bounded re-pull
FALLBACK_PREFILL_DEATH = "prefill_death"  # producer died mid-handoff
FALLBACK_DECODE_DEATH = "decode_death"  # decode home died mid-stream
FALLBACK_POOL_DOWN = "pool_down"  # target pool empty; any-alive placement
FALLBACK_NO_PULL_COORDS = "no_pull_coords"  # producer had no full pages


def plan_pools(n: int) -> tuple[list[int], list[int]]:
    """Split ``n`` DP ranks into (prefill ranks, decode ranks): the
    first ``VDT_DISAGG_PREFILL_REPLICAS`` (auto: half) prefill, the
    rest decode — always at least one of each."""
    from vllm_distributed_tpu import envs
    assert n >= 2, "disagg needs at least one replica per pool"
    k = envs.VDT_DISAGG_PREFILL_REPLICAS or n // 2
    k = max(1, min(k, n - 1))
    return list(range(k)), list(range(k, n))


def specialize_replica_config(rc: EngineConfig, role: str,
                              device_offset: Optional[int] = None) -> None:
    """Mutate one replica's (already deep-copied) config for its pool.

    Applied AFTER dataclass __post_init__ ran on the parent, so the
    connector-incompatible modes the aggregate config would have
    rejected (multi-step bursts, async scheduling) are forced off here
    explicitly."""
    from vllm_distributed_tpu import envs
    kv = rc.kv_transfer_config
    if not kv.kv_connector:
        kv.kv_connector = "DCNPullConnector"
    kv.kv_role = "kv_producer" if role == PREFILL_POOL else "kv_consumer"
    kv.pool_role = role
    extra = dict(kv.kv_connector_extra_config or {})
    extra.setdefault("pull_host", "127.0.0.1")
    # Every producer binds its own side-channel port (0 = auto); the
    # actual port travels in each handoff's kv_transfer_params.
    extra["pull_port"] = 0
    kv.kv_connector_extra_config = extra
    sched = rc.scheduler_config
    # Connector hooks run at step boundaries: the fused multi-step burst
    # and async run-ahead grants would silently skip them (same gates
    # EngineConfig.__post_init__ applies when a connector is configured
    # up front).
    sched.num_scheduler_steps = 1
    sched.async_scheduling = False
    tp = (envs.VDT_DISAGG_PREFILL_TP if role == PREFILL_POOL
          else envs.VDT_DISAGG_DECODE_TP)
    if tp:
        rc.parallel_config.tensor_parallel_size = tp
    if device_offset is not None:
        rc.parallel_config.data_parallel_device_offset = device_offset
    if role == DECODE_POOL:
        # Deep decode batches, small compiled lattice: the token budget
        # caps both the decode wave depth and the chunk size of the
        # local re-prefill fallback — the decode pool's token-bucket
        # ladder (and with it the precompile lattice) shrinks to this
        # budget instead of the parent's prefill-sized one.
        budget = envs.VDT_DISAGG_DECODE_TOKENS or max(
            sched.max_num_seqs, 2 * rc.cache_config.block_size)
        budget = min(budget, sched.max_num_batched_tokens)
        sched.max_num_batched_tokens = budget
        sched.enable_chunked_prefill = True
        if (sched.long_prefill_token_threshold <= 0
                or sched.long_prefill_token_threshold > budget):
            sched.long_prefill_token_threshold = budget


def prefill_stage_request(orig: EngineCoreRequest) -> EngineCoreRequest:
    """The one-token copy a prefill replica serves: full prompt KV is
    computed and one token sampled (discarded — the decode home
    regenerates it), then the producer's request_finished hook defers
    the pages and hands back pull coordinates."""
    # Shallow copy: the prompt list is never mutated downstream (the
    # core's Request copies it into _all_token_ids and deep-copies
    # sampling_params itself), so only the fields this function changes
    # need their own objects — a 100k-token prompt is not re-copied
    # under the balancer lock.
    req = copy.copy(orig)
    req.kv_transfer_params = None  # the prefill side never pulls
    sp = copy.deepcopy(orig.sampling_params)
    sp.max_tokens = 1
    if getattr(sp, "min_tokens", 0):
        sp.min_tokens = 0
    req.sampling_params = sp
    return req


class DisaggCoordinator:
    """Handoff state machine riding ``DPEngineClient``'s balancer lock.

    Every method is called with the balancer RLock held (admission,
    output marking and failover already serialize on it), so plain
    dict/counter state needs no further locking."""

    def __init__(self, client, config: EngineConfig) -> None:
        self.client = client
        n = len(client.clients)
        self.prefill_pool, self.decode_pool = plan_pools(n)
        self._prefill_set = set(self.prefill_pool)
        self._decode_set = set(self.decode_pool)
        # Per-slot device offsets + per-role world sizes: the live
        # re-planning surface (fleet scale-out / role conversion) needs
        # to know which device slice a slot owns and whether the two
        # roles are device-footprint-compatible.
        self._sizes = self.role_world_sizes(config)
        self._device_offsets = [off for _, off
                                in self.plan_replicas(config)]
        self.resplits = 0
        # rid -> pool stage: PREFILL_POOL while the prefill-stage copy
        # is in flight, DECODE_POOL from handoff admission to finish.
        self._stage: dict[str, str] = {}
        # rid -> handoff start (monotonic); observed into the handoff
        # histogram at the first decode-home output for the request.
        self._t0: dict[str, float] = {}
        self.handoffs = 0
        self.fallbacks: dict[str, int] = {}
        self.handoff_seconds = Histogram(TTFT_BUCKETS)
        # Pull-based connectors (dcn_pull / p2p) ship coordinates in
        # kv_transfer_params; SharedStorageConnector is content-hash
        # addressed — its handoffs legitimately carry no params (the
        # decode home hits the page files by hash), so a missing-params
        # handoff only counts as a fallback on pull-based fleets.
        conn = (client.clients[self.prefill_pool[0]]
                .config.kv_transfer_config.kv_connector)
        self._params_expected = conn != "SharedStorageConnector"
        logger.info(
            "disagg serving tier: prefill pool %s, decode pool %s "
            "(handoff connector %s)",
            self.prefill_pool, self.decode_pool, conn)

    # ------------------------------------------------------------------
    # Pool planning helpers (used at replica construction)
    # ------------------------------------------------------------------
    @staticmethod
    def role_world_sizes(config: EngineConfig) -> dict:
        """One replica world size per ROLE (world_size is a derived
        property, so evaluate it on a scratch copy with the pool's TP
        degree applied rather than re-deriving its formula here)."""
        from vllm_distributed_tpu import envs
        sizes: dict[str, int] = {}
        for role, tp in ((PREFILL_POOL, envs.VDT_DISAGG_PREFILL_TP),
                         (DECODE_POOL, envs.VDT_DISAGG_DECODE_TP)):
            per = copy.deepcopy(config.parallel_config)
            per.data_parallel_size = 1
            if tp:
                per.tensor_parallel_size = tp
            sizes[role] = per.world_size
        return sizes

    @staticmethod
    def plan_replicas(config: EngineConfig) -> list[tuple[str, int]]:
        """(role, device_offset) per DP rank. Offsets are cumulative
        because pools may run asymmetric TP degrees (different replica
        world sizes), where rank * world_size stops addressing the
        right device slice."""
        n = config.parallel_config.data_parallel_size
        prefill, _decode = plan_pools(n)
        prefill_set = set(prefill)
        sizes = DisaggCoordinator.role_world_sizes(config)
        out: list[tuple[str, int]] = []
        offset = 0
        for rank in range(n):
            role = PREFILL_POOL if rank in prefill_set else DECODE_POOL
            out.append((role, offset))
            offset += sizes[role]
        return out

    def role_of(self, replica: int) -> str:
        return (PREFILL_POOL if replica in self._prefill_set
                else DECODE_POOL)

    # ------------------------------------------------------------------
    # Live pool re-planning (engine/fleet.py; balancer lock held)
    # ------------------------------------------------------------------
    def symmetric_roles(self) -> bool:
        """True when both pools run the same replica world size, so a
        replica's device slice stays valid across a role conversion
        (an asymmetric fleet would need a different device footprint —
        the fleet controller skips conversions there)."""
        return self._sizes[PREFILL_POOL] == self._sizes[DECODE_POOL]

    def device_offset_of(self, replica: int) -> Optional[int]:
        """The device offset the replica was constructed with (slot
        reuse and role conversions keep it — same devices, new role)."""
        if replica < len(self._device_offsets):
            return self._device_offsets[replica]
        return None

    def next_device_offset(self) -> int:
        """Device offset for an APPENDED replica: past every existing
        slot's slice (retired slots keep their reservation — their
        devices come back via slot reuse, not re-planning)."""
        ends = [self._device_offsets[i] + self._sizes[self.role_of(i)]
                for i in range(len(self._device_offsets))]
        return max(ends, default=0)

    def set_role(self, replica: int, role: str) -> None:
        """Move a (drained) replica between pools — the live re-split.
        The caller has already rebuilt the replica's engine with the
        role-specialized config; this just re-plans membership."""
        if self.role_of(replica) == role:
            return
        self._prefill_set.discard(replica)
        self._decode_set.discard(replica)
        for pool in (self.prefill_pool, self.decode_pool):
            if replica in pool:
                pool.remove(replica)
        (self._prefill_set if role == PREFILL_POOL
         else self._decode_set).add(replica)
        target = (self.prefill_pool if role == PREFILL_POOL
                  else self.decode_pool)
        target.append(replica)
        target.sort()
        self.resplits += 1
        logger.info("disagg re-split: replica %d -> %s pool "
                    "(prefill %s, decode %s)", replica, role,
                    self.prefill_pool, self.decode_pool)

    def add_replica(self, replica: int, role: str,
                    device_offset: Optional[int] = None) -> None:
        """Enter a new (or slot-reused) replica into a pool."""
        if replica >= len(self._device_offsets):
            self._device_offsets.extend(
                [0] * (replica + 1 - len(self._device_offsets)))
        if device_offset is not None:
            self._device_offsets[replica] = device_offset
        self._prefill_set.discard(replica)
        self._decode_set.discard(replica)
        for pool in (self.prefill_pool, self.decode_pool):
            if replica in pool:
                pool.remove(replica)
        (self._prefill_set if role == PREFILL_POOL
         else self._decode_set).add(replica)
        target = (self.prefill_pool if role == PREFILL_POOL
                  else self.decode_pool)
        target.append(replica)
        target.sort()

    def remove_replica(self, replica: int) -> None:
        """Retire a replica from its pool (its slot index stays
        reserved fleet-wide; only pool membership changes)."""
        self._prefill_set.discard(replica)
        self._decode_set.discard(replica)
        for pool in (self.prefill_pool, self.decode_pool):
            if replica in pool:
                pool.remove(replica)

    # ------------------------------------------------------------------
    # Admission
    # ------------------------------------------------------------------
    def on_new_request(self,
                       request: EngineCoreRequest) -> EngineCoreRequest:
        """Stage a fresh admission. Returns the request object to admit
        (the one-token prefill-stage copy for handoff-eligible
        requests; the original otherwise)."""
        if request.kv_transfer_params:
            # Externally prefilled (a disagg proxy upstream): straight
            # to the decode pool, no staging of our own.
            self._stage[request.request_id] = DECODE_POOL
            return request
        sp = request.sampling_params
        if (request.pooling_params is not None
                or sp.prompt_logprobs is not None
                or (sp.max_tokens is not None and sp.max_tokens <= 1)):
            # Prefill-only work (embeddings, one-token generations) and
            # prompt_logprobs (externally-loaded positions can never be
            # scored, so the pull would be skipped anyway) serve
            # monolithically on the prefill pool: untracked, their
            # outputs flow through unintercepted.
            return request
        self._stage[request.request_id] = PREFILL_POOL
        return prefill_stage_request(request)

    def target_pool(self, request: EngineCoreRequest) -> list[int]:
        """Replica candidates for this admission (or re-admission)."""
        stage = self._stage.get(request.request_id)
        if stage == DECODE_POOL or request.kv_transfer_params:
            return self.decode_pool
        return self.prefill_pool

    def usable_pool(self, pool: list[int], down: set,
                    count: bool = True) -> Optional[list[int]]:
        """The pool minus downed replicas; None (= place anywhere
        alive, counted as a pool_down fallback) when the whole pool is
        out of rotation — availability beats pool purity. ``count=False``
        on _admit's failover-retry re-picks keeps the counter at one
        per degraded ADMISSION, not one per pick attempt."""
        alive = [i for i in pool if i not in down]
        if alive:
            return alive
        if count:
            self._count(FALLBACK_POOL_DOWN)
        logger.warning("disagg: pool %s entirely down; placing on any "
                       "alive replica", pool)
        return None

    def prefill_least_loaded(self, request: EngineCoreRequest) -> bool:
        """Prefill-pool admissions place least-loaded (the two-stage
        scheme's first stage): prefix affinity buys nothing there —
        the produced pages leave with the pull."""
        return self._stage.get(request.request_id) == PREFILL_POOL

    # ------------------------------------------------------------------
    # Output interception (the handoff itself)
    # ------------------------------------------------------------------
    def intercept(self, outs: list) -> list:
        """Filter one output batch under the balancer lock, BEFORE any
        journal/owner bookkeeping runs. Prefill-stage outputs are
        swallowed (their sampled token is regenerated by the decode
        home) and finished ones trigger the handoff; decode-stage
        outputs pass through after fallback/latency accounting."""
        kept = []
        for o in outs:
            stage = self._stage.get(o.req_id)
            if stage == PREFILL_POOL:
                if o.finished:
                    self._handoff(o)
                continue
            if stage == DECODE_POOL:
                self._observe_decode_output(o)
            kept.append(o)
        return kept

    def _handoff(self, out) -> None:
        """One finished prefill-stage request -> its decode home."""
        rid = out.req_id
        client = self.client
        # Unwind the prefill placement by hand: this output never
        # reaches the normal finish bookkeeping (and must NOT — the
        # router would credit prompt+generated residency to the
        # prefill replica, whose pages leave with the pull; the decode
        # home's on_admit/on_finish do the honest registration).
        owner = client._owner.pop(rid, None)
        if owner is not None:
            client._live[owner].discard(rid)
            if client.coordinator is not None:
                client.coordinator.report(owner, -1)
        orig = client._requests.get(rid)
        if orig is None:
            # Aborted while the finish was in flight; the producer's
            # deferred pages expire on their own send timeout.
            self._stage.pop(rid, None)
            return
        params = out.kv_transfer_params
        if params is None:
            # Pull-based fleet with a prompt shorter than one full
            # page: nothing to pull, the decode home prefills the
            # (tiny) prompt locally. Hash-addressed (shared_storage)
            # fleets never carry params — their decode homes hit the
            # page files by content hash, so nothing is counted.
            if self._params_expected:
                self._count(FALLBACK_NO_PULL_COORDS)
        elif fault_injection.should_fire("disagg.handoff_stall"):
            # Drill: break the pull coordinates so the decode home's
            # pull can never complete and the scheduler's recovery
            # ladder (bounded retries -> local re-prefill) must carry
            # the request instead.
            params = dict(params)
            params["remote_req_id"] = \
                str(params.get("remote_req_id", rid)) + "#stalled"
        req = copy.copy(orig)  # shallow: only the params field changes
        req.kv_transfer_params = params
        self._stage[rid] = DECODE_POOL
        self._t0[rid] = time.monotonic()
        self.handoffs += 1
        recorder = getattr(client, "events", None)
        if recorder is not None and recorder.enabled:
            # The producer->consumer causal link: this event (stamped
            # with the request's trace id, tagged with the producer
            # replica) is where the Perfetto export opens its flow
            # arrow; the decode home's kv_pull span closes it.
            detail: dict = {"from_replica": owner,
                            "pull": params is not None}
            if getattr(client, "trace_enabled", False):
                detail = ev.stamp_trace(detail, orig.trace_ctx)
            recorder.record(rid, ev.DISAGG_HANDOFF, detail)
        client._admit(req)

    def _observe_decode_output(self, out) -> None:
        t0 = self._t0.get(out.req_id)
        if t0 is not None and (out.new_token_ids or out.finished):
            # Handoff latency: interception -> the decode home's first
            # token back at the front end (covers routing, the pull or
            # its fallback, requeue, and the first decode step).
            self.handoff_seconds.observe(time.monotonic() - t0)
            self._t0.pop(out.req_id, None)
        for event in (out.events or ()):
            name = event[1] if len(event) > 1 else None
            if name == ev.KV_PULL_LOCAL:
                self._count(FALLBACK_LOCAL_REPREFILL)
            elif name == ev.KV_PULL_RETRY:
                self._count(FALLBACK_PULL_RETRY)
        if out.finished:
            self._stage.pop(out.req_id, None)
            self._t0.pop(out.req_id, None)

    # ------------------------------------------------------------------
    # Failure handling
    # ------------------------------------------------------------------
    def readmission_for(self, rid: str, orig: EngineCoreRequest,
                        generated: list[int]) -> Optional[EngineCoreRequest]:
        """Replacement request for one stranded rid during a replica
        failover, or None to use the default continuation path. A
        prefill-stage casualty re-enters as a fresh prefill-stage copy
        (nothing was delivered, so there is nothing to continue); a
        decode-stage casualty uses the normal continuation (the caller
        builds it) and stays homed to the decode pool."""
        stage = self._stage.get(rid)
        if stage == PREFILL_POOL:
            self._count(FALLBACK_PREFILL_DEATH)
            return prefill_stage_request(orig)
        if stage == DECODE_POOL:
            self._count(FALLBACK_DECODE_DEATH)
        return None

    def forget(self, rid: str) -> None:
        self._stage.pop(rid, None)
        self._t0.pop(rid, None)

    def reset(self) -> None:
        self._stage.clear()
        self._t0.clear()

    def _count(self, reason: str) -> None:
        self.fallbacks[reason] = self.fallbacks.get(reason, 0) + 1

    # ------------------------------------------------------------------
    def get_stats(self, live_counts: list[int]) -> dict:
        """The ``disagg`` entry of the DP stats aggregation, rendered
        as the vdt:disagg_* / vdt:pool_occupancy families."""
        return {
            "handoffs": self.handoffs,
            "fallbacks": dict(self.fallbacks),
            "handoff_seconds": self.handoff_seconds.to_dict(),
            "pool_occupancy": {
                PREFILL_POOL:
                    sum(live_counts[i] for i in self.prefill_pool),
                DECODE_POOL:
                    sum(live_counts[i] for i in self.decode_pool),
            },
            "pools": {PREFILL_POOL: list(self.prefill_pool),
                      DECODE_POOL: list(self.decode_pool)},
        }
