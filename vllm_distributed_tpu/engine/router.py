"""Cluster routing tier: prefix-affinity + SLO-aware replica placement.

The DP front end (``engine/dp_client.py``) historically placed every
admission on the replica with the fewest live requests. That is blind to
the two signals that dominate chat-serving economics at scale:

* **where a conversation's KV already lives** — a session turn placed on
  the replica that prefix-cached the previous turns skips most of its
  prefill (multi-replica prefix reuse, ROADMAP item 3), and
* **how pressured each replica actually is** — queue depth alone misses
  a KV-saturated or latency-degraded replica until it sheds.

``ReplicaRouter`` scores every admission across the alive replicas:

``affinity(req, r)``
    Fraction of the request's leading prompt pages whose chained
    ``BlockHash`` (same sha256 page-chain scheme as
    ``core/block_pool.py``, so equal hashes imply equal full prefixes)
    is present in replica ``r``'s *prefix-residency index* — a bounded
    per-replica LRU of page hashes fed by the owner bookkeeping the
    balancer already maintains (registered at admission, extended with
    the generated tokens at finish, dropped wholesale on failover,
    halved under replica eviction pressure, TTL-expired otherwise).
    With hierarchical KV tiering on (core/kv_tier.py), entries are
    additionally TAGGED BY TIER — the replica's drained tier-transition
    stream rides its existing kv_tier stats entry through
    ``observe_stats`` — and each matched page scores its restore-cost
    credit (``TIER_CREDITS``: HBM 1.0, host RAM 0.8, disk 0.55), so a
    returning session routes to the replica that can *restore* its
    prefix cheapest, not only one still holding it in HBM.
    The index is a HINT: a false positive only costs the prefill the
    old balancer would have paid anyway — each replica's own block
    pool re-verifies every page hash before reuse.

``cost(r) = 0.5*queue(r) + 0.3*kv(r) + 0.2*wait(r) - affinity(req, r)``
    ``queue`` is the live front-end request count plus the replica's
    scheduler waiting queue, normalized by ``max_num_seqs``; ``kv`` is
    the replica's block-pool usage fraction; ``wait`` is the mean
    device-wait step phase (the PR5 step-phase profiler) normalized to
    a 0.5 s ceiling. The load terms come from the replica's existing
    ``get_stats`` RPC on a short TTL (``VDT_ROUTER_STATS_TTL_S``):
    in-process replicas refresh synchronously on the admission path,
    subprocess replicas are fed passively by the server's periodic
    stats polls — the router never opens a new channel.

Guard rails:

* **Spillover** — a replica whose blended pressure
  ``max(kv, min(queue, 1))`` exceeds ``VDT_ROUTER_SPILL_PRESSURE``
  forfeits its affinity credit, so a hot home replica spills session
  turns to the least-cost healthy replica instead of melting down.
* **Stale-stats degradation** — when every alive replica's snapshot is
  older than ``VDT_ROUTER_STALE_S`` (or the ``router.stale_stats``
  fault point is armed), the router ignores affinity AND the stale
  load terms and falls back to pure least-live-count balancing:
  affinity on blind load signals would herd a session-heavy workload
  onto one replica with nothing to push back.
* **Kill switch** — ``VDT_ROUTER=0`` removes the router entirely;
  the balancer reverts to the pre-router round-robin heuristic.

Telemetry rides the DP stats aggregation as the ``router`` entry
(rendered as the ``vdt:router_*`` families): routed / affinity-hit /
spillover / stale-degradation counters plus per-replica residency-index
occupancy.
"""

import time
from collections import OrderedDict
from typing import Optional

from vllm_distributed_tpu.core.kv_cache_utils import hash_block_tokens
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.request import EngineCoreRequest
from vllm_distributed_tpu.utils import fault_injection

logger = init_logger(__name__)

# Pressure above which a replica's residency index is halved: the
# replica's block pool is evicting prefix pages, so half our hints there
# are already dead weight.
_EVICTION_PRESSURE = 0.95
# Tier-aware affinity credit per matched page (core/kv_tier.py tier
# codes): a prefix the replica still holds in HBM is free to reuse, a
# host-RAM-tiered page costs one PCIe scatter, a disk-tiered page a
# file read + decode + scatter — all far cheaper than recomputing the
# prefill, which is what a miss costs. The credits ARE the restore-cost
# model: a returning session routes to the replica that can restore its
# prefix cheapest, not only one still holding it in HBM.
TIER_CREDITS = {0: 1.0, 1: 0.8, 2: 0.55}
# Normalization ceiling (seconds) for the mean device-wait step phase.
_WAIT_CEILING_S = 0.5
# Cost margin below which two replicas tie and the rotation cursor
# decides (keeps placement fair when signals are indistinguishable).
_TIE_EPS = 1e-9


class ReplicaRouter:
    """Placement brain for ``DPEngineClient``. Routing/bookkeeping
    calls run under the balancer's RLock; ``observe_stats`` may arrive
    from the stats-poll thread instead, so it sticks to GIL-atomic
    container operations (plain assignments, OrderedDict get/pop/
    popitem — never iteration over a live index), which is why no
    internal lock is needed."""

    def __init__(self, num_replicas: int, config) -> None:
        from vllm_distributed_tpu import envs
        self.n = num_replicas
        self.block_size = config.cache_config.block_size
        self.max_num_seqs = max(1, config.scheduler_config.max_num_seqs)
        self.stats_ttl_s = envs.VDT_ROUTER_STATS_TTL_S
        self.stale_s = envs.VDT_ROUTER_STALE_S
        self.prefix_pages = envs.VDT_ROUTER_PREFIX_PAGES
        self.prefix_capacity = envs.VDT_ROUTER_PREFIX_CAPACITY
        self.prefix_ttl_s = envs.VDT_ROUTER_PREFIX_TTL_S
        self.spill_pressure = envs.VDT_ROUTER_SPILL_PRESSURE
        # Per-replica prefix-residency index: page hash -> (last touch
        # (monotonic), tier code 0=device/1=host/2=disk). OrderedDict
        # in LRU order (oldest first).
        self._residency: list["OrderedDict[bytes, tuple]"] = [
            OrderedDict() for _ in range(num_replicas)
        ]
        # Per-replica load snapshot + fetch instant (monotonic).
        self._stats: list[dict] = [{} for _ in range(num_replicas)]
        self._stats_at: list[float] = [float("-inf")] * num_replicas
        # Device-wait latency signal, computed as the INTERVAL mean
        # between consecutive snapshots of the cumulative step-phase
        # histogram: the lifetime mean of a long-lived replica barely
        # moves when a slowdown starts, the interval mean tracks it.
        self._wait_prev: list[tuple[float, int]] = \
            [(0.0, 0)] * num_replicas
        self._wait_interval_s: list[float] = [0.0] * num_replicas
        self._rr = 0  # tie-break rotation cursor
        # Decision record of the last route() call (request id, hashes,
        # affinity home, degraded flag), consumed by the on_admit()
        # that follows under the same balancer lock: the counters
        # commit against the replica the request ACTUALLY landed on
        # (a failover retry re-routes, a coordinator can override the
        # pick), and the page-chain sha256 is never paid twice.
        self._pending_route: Optional[dict] = None
        # Counters surfaced as vdt:router_* (exact values — one router
        # instance owns the whole fleet's placement, nothing to merge).
        self.requests_routed = 0
        self.affinity_hits = 0
        self.spillovers = 0
        self.stale_degradations = 0

    # ------------------------------------------------------------------
    # Prefix hashing (same page-chain scheme as the block pool)
    # ------------------------------------------------------------------
    def _page_hashes(self, token_ids: list[int]) -> list[bytes]:
        """Chained page hashes of the leading ``prefix_pages`` full
        pages of ``token_ids`` (page granularity = cache block size)."""
        hashes: list[bytes] = []
        parent: Optional[bytes] = None
        limit = min(len(token_ids) // self.block_size, self.prefix_pages)
        for p in range(limit):
            chunk = tuple(
                token_ids[p * self.block_size:(p + 1) * self.block_size])
            parent = hash_block_tokens(parent, chunk).hash_value
            hashes.append(parent)
        return hashes

    def request_hashes(self, request: EngineCoreRequest) -> list[bytes]:
        """Affinity key for one admission. Multimodal prompts are
        skipped: their block hashes are salted with the image content
        hash scheduler-side, and recomputing that salt at the front end
        would hash the full embeds per admission — the affinity hint is
        not worth that cost."""
        if request.mm_inputs:
            return []
        return self._page_hashes(request.prompt_token_ids)

    # ------------------------------------------------------------------
    # Residency index bookkeeping (fed by the balancer's owner state)
    # ------------------------------------------------------------------
    def _register(self, replica: int, hashes: list[bytes],
                  tier: int = 0) -> None:
        if not hashes:
            return
        index = self._residency[replica]
        now = time.monotonic()
        for h in hashes:
            index.pop(h, None)
            index[h] = (now, tier)  # most-recently-used position
        while len(index) > self.prefix_capacity:
            index.popitem(last=False)

    # -- Tier transitions (core/kv_tier.py feed via observe_stats) -----
    def on_demote(self, replica: int, hashes: list[bytes],
                  tier: int) -> None:
        """Pages left the replica's device pool for a spill tier (or
        came back: tier 0 = promoted to HBM). Entries we track retag
        in place — keeping their recency — so affinity scores the
        RESTORE cost instead of pretending the prefix is still free
        (or gone). Hashes we never indexed are ignored: the feed is a
        hint stream, not an index bootstrap."""
        index = self._residency[replica]
        for h in hashes:
            at = index.get(h)
            if at is not None:
                index[h] = (at[0], tier)

    def on_evict(self, replica: int, hashes: list[bytes]) -> None:
        """Pages fell off the replica's last tier: drop the hints."""
        index = self._residency[replica]
        for h in hashes:
            index.pop(h, None)

    def observe_tier_transitions(self, replica: int,
                                 transitions) -> None:
        """Apply one drained (hash hex, tier code) transition stream
        from the replica's kv_tier stats entry (rides the existing
        get_stats feed — see observe_stats)."""
        if not transitions:
            return
        for entry in transitions:
            try:
                hex_key, code = entry
                key = bytes.fromhex(hex_key)
            except (TypeError, ValueError):
                continue
            if code < 0:
                self.on_evict(replica, [key])
            else:
                self.on_demote(replica, [key], int(code))

    def on_admit(self, request: EngineCoreRequest, replica: int,
                 hashes: Optional[list[bytes]] = None) -> None:
        """The request landed on ``replica``: its prompt pages will be
        resident there (written during prefill, prefix-cached after).
        Commits the pending route() decision's counters against the
        LANDING replica — exactly once per admission however many
        route() retries a failover cost, and honestly when a
        coordinator overrode the pick — and reuses its hashes instead
        of paying the page chain twice."""
        pend = self._pending_route
        if (pend is not None
                and pend["rid"] == request.request_id):
            self._pending_route = None
            if hashes is None:
                hashes = pend["hashes"]
            self.requests_routed += 1
            if pend["degraded"]:
                self.stale_degradations += 1
            elif self._affinity(replica, hashes) > 0.0:
                self.affinity_hits += 1
            elif (pend["home"] is not None
                  and pend["home"] != replica
                  and pend["home_pressured"]):
                # The guard rail fired: a home held this prefix but
                # its pressure forfeited the credit. (A home merely
                # losing on cost is ordinary placement, not spillover.)
                self.spillovers += 1
        if hashes is None:
            hashes = self.request_hashes(request)
        self._register(replica, hashes)

    def on_finish(self, request: EngineCoreRequest,
                  generated: list[int], replica: int) -> None:
        """A finished request leaves its FULL sequence prefix-cached on
        its replica — the next session turn's prompt extends it, so
        indexing prompt+generated gives that turn page-exact affinity."""
        if request.mm_inputs:
            return
        tokens = list(request.prompt_token_ids) + list(generated or [])
        self._register(replica, self._page_hashes(tokens))

    def on_replica_down(self, replica: int) -> None:
        """Failover: the replica's KV pool is gone with it; journaled
        sessions re-home as their migrated continuations re-admit."""
        self._residency[replica].clear()
        self._stats[replica] = {}
        self._stats_at[replica] = float("-inf")
        self._wait_prev[replica] = (0.0, 0)
        self._wait_interval_s[replica] = 0.0

    def reset(self) -> None:
        """Full-fleet restart: every pool respawned empty."""
        for i in range(self.n):
            self.on_replica_down(i)

    def grow(self, count: int = 1) -> None:
        """Elastic scale-out (engine/fleet.py): extend every per-replica
        array with empty state for ``count`` appended replicas. The new
        slots start cold (no residency, stats at -inf), exactly like a
        replica that just came back from on_replica_down."""
        for _ in range(count):
            self._residency.append(OrderedDict())
            self._stats.append({})
            self._stats_at.append(float("-inf"))
            self._wait_prev.append((0.0, 0))
            self._wait_interval_s.append(0.0)
        self.n += count

    def _affinity(self, replica: int, hashes: list[bytes]) -> float:
        """Tier-weighted matched leading pages / hashed pages, honoring
        the entry TTL (expired entries are pruned as they are seen). A
        device-resident page scores full credit; host/disk-tiered
        pages score their restore-cost discount (TIER_CREDITS), so two
        replicas holding the same prefix in different tiers rank by
        how cheaply each can actually serve it."""
        if not hashes:
            return 0.0
        index = self._residency[replica]
        now = time.monotonic()
        credit = 0.0
        for h in hashes:
            at = index.get(h)
            if at is None:
                break
            ts, tier = at
            if now - ts > self.prefix_ttl_s:
                index.pop(h, None)
                break
            credit += TIER_CREDITS.get(tier, TIER_CREDITS[2])
        return credit / len(hashes)

    # ------------------------------------------------------------------
    # Load snapshots (existing get_stats RPC, short TTL)
    # ------------------------------------------------------------------
    def observe_stats(self, replica: int, stats: dict) -> None:
        """Feed one replica's stats dict (passively from the server's
        periodic polls, or from a synchronous in-process refresh)."""
        if not isinstance(stats, dict):
            return
        if ("num_running_reqs" not in stats
                and "kv_cache_usage" not in stats):
            # Not a scheduler stats dict (the generic utility fan-out
            # aggregates other dict-shaped RPC results through the same
            # path): don't let it overwrite a real load snapshot.
            return
        self._stats[replica] = stats
        self._stats_at[replica] = time.monotonic()
        phases = stats.get("step_phase_seconds")
        h = phases.get("wait") if isinstance(phases, dict) else None
        if isinstance(h, dict) and h.get("count"):
            s, c = float(h.get("sum", 0.0)), int(h["count"])
            ps, pc = self._wait_prev[replica]
            if c > pc:
                self._wait_interval_s[replica] = (s - ps) / (c - pc)
            elif c < pc:
                # Counter went backwards: the replica restarted with a
                # fresh histogram — restart the interval baseline.
                self._wait_interval_s[replica] = 0.0
            self._wait_prev[replica] = (s, c)
        # Tier transitions (hierarchical KV memory): the replica's
        # kv_tier stats entry carries a drained (hash, tier) stream —
        # demoted pages retag to their restore-cost tier, tier-evicted
        # pages drop, promoted pages regain full device credit.
        kv_tier = stats.get("kv_tier")
        if isinstance(kv_tier, dict):
            self.observe_tier_transitions(
                replica, kv_tier.get("transitions"))
        if (float(stats.get("kv_cache_usage", 0.0)) >= _EVICTION_PRESSURE
                and self._residency[replica]):
            # The replica is evicting prefix pages; drop the oldest half
            # of our hints about it instead of advertising dead pages.
            index = self._residency[replica]
            for _ in range(len(index) // 2):
                try:
                    index.popitem(last=False)
                except KeyError:  # raced a TTL prune on the route path
                    break

    def maybe_refresh(self, clients: list, down: set) -> None:
        """Refresh expired snapshots where it costs nothing: in-process
        replicas answer get_stats inline (a dict build). Subprocess
        replicas are never polled here — their snapshots arrive via
        observe_stats from the pump-thread stats path."""
        if fault_injection.should_fire("router.stale_stats"):
            return  # drill: signals stay frozen until they expire
        now = time.monotonic()
        for i, client in enumerate(clients):
            if i in down or now - self._stats_at[i] < self.stats_ttl_s:
                continue
            if getattr(client, "engine_core", None) is None:
                continue  # subprocess replica: passive feed only
            try:
                # include_events=False: the event-ring drain is
                # destructive and belongs to the real stats poll.
                self.observe_stats(
                    i, client.call_utility("get_stats", False))
            except Exception:  # noqa: BLE001 - replica busy/dying; the
                # snapshot stays stale and the scoring degrades.
                pass

    def _stale(self, alive: list[int]) -> bool:
        now = time.monotonic()
        return all(now - self._stats_at[i] > self.stale_s for i in alive)

    # ------------------------------------------------------------------
    # Scoring
    # ------------------------------------------------------------------
    def _load_terms(self, i: int,
                    live_counts: list[int]) -> tuple[float, float, float]:
        stats = self._stats[i]
        queue = ((live_counts[i]
                  + float(stats.get("num_waiting_reqs", 0.0)))
                 / self.max_num_seqs)
        kv = float(stats.get("kv_cache_usage", 0.0))
        # Interval mean (maintained by observe_stats), not the lifetime
        # histogram mean: a slowdown that starts after hours of serving
        # must still move the signal.
        wait = min(1.0, self._wait_interval_s[i] / _WAIT_CEILING_S)
        return queue, kv, wait

    def pressure(self, i: int, live_counts: list[int]) -> float:
        queue, kv, _ = self._load_terms(i, live_counts)
        return max(kv, min(queue, 1.0))

    def route(self, request: Optional[EngineCoreRequest],
              live_counts: list[int], down: set,
              pool: Optional[list[int]] = None,
              least_loaded: bool = False) -> int:
        """Pick the replica with the best expected outcome for this
        admission. Caller guarantees at least one replica is alive.
        Counters do NOT move here — the decision record is stashed and
        committed by on_admit() against the landing replica (a failover
        retry re-enters here; a coordinator may override the pick).

        ``pool`` restricts candidates to a replica subset — the disagg
        tier's two-stage placement (engine/disagg.py): prefill-pool
        admissions additionally pass ``least_loaded=True`` (affinity
        buys nothing on a pool whose pages leave with the pull), while
        the decode-home pick at handoff time scores the decode pool
        with the full prefix-affinity + load blend."""
        members = (set(pool) if pool is not None
                   else set(range(self.n)))
        alive = [i for i in range(self.n)
                 if i not in down and i in members]
        assert alive, "route() with every candidate replica down"
        rid = request.request_id if request is not None else None
        if least_loaded or self._stale(alive):
            # Least-live-count with rotation tie-break: the explicit
            # two-stage prefill placement, or the degraded stale-stats
            # mode (identical to the pre-router balancer).
            best = self._least_loaded(alive, live_counts)
            self._rr = (best + 1) % self.n
            self._pending_route = {"rid": rid, "hashes": [],
                                   "degraded": not least_loaded,
                                   "home": None,
                                   "home_pressured": False}
            return best
        hashes = (self.request_hashes(request)
                  if request is not None else [])
        best, best_cost = None, None
        home, home_aff, home_pressured = None, 0.0, False
        for off in range(self.n):
            i = (self._rr + off) % self.n
            if i in down or i not in members:
                continue
            queue, kv, wait = self._load_terms(i, live_counts)
            affinity = self._affinity(i, hashes)
            pressured = max(kv, min(queue, 1.0)) > self.spill_pressure
            if affinity > home_aff:
                home, home_aff, home_pressured = i, affinity, pressured
            if pressured:
                # Pressured replicas forfeit their affinity credit so a
                # hot home spills instead of melting down.
                affinity = 0.0
            cost = 0.5 * queue + 0.3 * kv + 0.2 * wait - affinity
            if best_cost is None or cost < best_cost - _TIE_EPS:
                best, best_cost = i, cost
        self._rr = (best + 1) % self.n
        self._pending_route = {"rid": rid, "hashes": hashes,
                               "degraded": False, "home": home,
                               "home_pressured": home_pressured}
        return best

    def _least_loaded(self, alive: list[int],
                      live_counts: list[int]) -> int:
        best, best_load = None, None
        for off in range(self.n):
            i = (self._rr + off) % self.n
            if i not in alive:
                continue
            if best_load is None or live_counts[i] < best_load:
                best, best_load = i, live_counts[i]
        return best

    # ------------------------------------------------------------------
    def get_stats(self) -> dict:
        """Telemetry entry attached to the DP stats aggregation and
        rendered as the vdt:router_* families."""
        return {
            "requests_routed": self.requests_routed,
            "affinity_hits": self.affinity_hits,
            "spillovers": self.spillovers,
            "stale_degradations": self.stale_degradations,
            "prefix_index_entries": [len(x) for x in self._residency],
        }
