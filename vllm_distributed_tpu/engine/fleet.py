"""Self-managing elastic fleet: closed-loop autoscaling + live re-splits.

ROADMAP item 2. Every signal and actuator this loop needs already
exists as a disconnected piece — per-replica scheduler stats and
step-dispatch counters, disagg pool occupancy (engine/disagg.py), the
restart/failover ladder with its per-replica supervisor budget
(engine/core_client.py), journaled continuation migration
(engine/dp_client.py), and the shared tier-2 spill namespace that lets
a fresh engine warm-start its prefix cache (core/kv_tier.py).
``FleetController`` closes the loop:

* **scale-out** — sustained fleet occupancy above
  ``VDT_FLEET_HIGH_WATERMARK`` adds a DP replica: the lowest retired
  slot is reused (its device slice is reserved for exactly this),
  otherwise a new rank is appended (router ``grow``, coordinator
  ``resize``, disagg ``add_replica``). The new engine warm-starts from
  the shared T2 spill directory; restored pages are counted.
* **scale-in** — sustained occupancy below ``VDT_FLEET_LOW_WATERMARK``
  retires the least-loaded replica via drain (out of placement, keeps
  serving) -> journal-migrate whatever outlives ``VDT_FLEET_DRAIN_S``
  as continuations (token-identical under greedy, NOT counted as a
  failover — this is scheduled maintenance, not a death) -> remove
  from rotation. Zero requests lost.
* **live re-split** — a sustained prefill<->decode pool-pressure
  imbalance (``VDT_FLEET_RESPLIT_RATIO``) converts one replica: drain
  in the old role, rebuild the engine with the role-specialized config
  (role-appropriate token buckets and precompile lattice), re-enter
  the other pool. Gated to symmetric per-role world sizes — the
  replica keeps its device slice across the conversion.
* **wedge cycling** — a replica with live requests whose
  ``steps_dispatched`` has not advanced for ``VDT_FLEET_WEDGE_S`` is
  alive-but-not-stepping: its journaled requests migrate off and it is
  force-cycled through the PR-2 per-replica restart budget, counted on
  exactly the ``vdt:fleet_wedge_cycles_total`` rung.
* **graceful degradation** — a stale or missing stats snapshot for any
  in-rotation replica freezes ALL actuation (the router ``stale_stats``
  idiom: never reshape the fleet on blind signals); an exhausted
  action budget (``VDT_FLEET_ACTIONS`` per rolling window, a
  ``RestartSupervisor``) freezes it too, so an oscillating signal
  cannot thrash the fleet. Hysteresis (``VDT_FLEET_EVAL_TICKS``
  consecutive ticks) is the other anti-thrash half.

The controller has NO thread of its own: ``tick()`` rides the DP
client's output paths next to the resurrection probe it subsumes
(when ``VDT_FLEET=1`` the periodic probe folds into this loop — one
actuator, one budget — with restart health VERIFIED before a
resurrection is counted). ``VDT_FLEET=0`` constructs nothing and the
legacy probe path runs untouched.

Drills: ``fleet.scale_stall`` (replica construction stalls — counted,
budgeted, fleet intact) and ``fleet.replica_wedge`` (forces the wedge
detector). Telemetry: the ``fleet`` entry of the DP stats aggregation,
rendered as the ``vdt:fleet_*`` families, plus ``fleet_*`` timeline
events.
"""

import queue
import threading
import time
from typing import Optional

from vllm_distributed_tpu.config import EngineConfig
from vllm_distributed_tpu.engine.core_client import RestartSupervisor
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.metrics import events as ev
from vllm_distributed_tpu.metrics.events import EventRecorder
from vllm_distributed_tpu.utils import fault_injection

logger = init_logger(__name__)

# Freeze reasons surfaced as vdt:fleet_freezes_total{reason}. A freeze
# is one SKIPPED actuation opportunity (counted per frozen tick /
# blocked action, not per incident — a long stale window counts every
# tick it suppresses).
FREEZE_STALE_STATS = "stale_stats"  # snapshot missing/expired
FREEZE_BUDGET = "budget"  # action budget exhausted this window
FREEZE_SCALE_STALL = "scale_stall"  # replica construction failed
FREEZE_AT_MAX = "at_max"  # pressure with no slot/devices to grow into
FREEZE_ASYM_TP = "asym_tp"  # re-split blocked by asymmetric role TP
FREEZE_PARTITION = "partition"  # control plane unreachable (HA mode)


class FleetController:
    """Control loop over ``DPEngineClient``'s replica set. Every entry
    point runs under the balancer RLock (tick() takes it; observe_stats
    sticks to GIL-atomic dict assignment like the router's feed)."""

    def __init__(self, client, config: EngineConfig) -> None:
        from vllm_distributed_tpu import envs
        self.client = client
        self.config = config
        self.min_replicas = envs.VDT_FLEET_MIN_REPLICAS
        self.max_replicas = (envs.VDT_FLEET_MAX_REPLICAS
                             or len(client.clients))
        self.tick_s = envs.VDT_FLEET_TICK_S
        self.high_wm = envs.VDT_FLEET_HIGH_WATERMARK
        self.low_wm = envs.VDT_FLEET_LOW_WATERMARK
        self.eval_ticks = envs.VDT_FLEET_EVAL_TICKS
        self.stale_s = envs.VDT_FLEET_STALE_S
        self.wedge_s = envs.VDT_FLEET_WEDGE_S
        self.drain_s = envs.VDT_FLEET_DRAIN_S
        self.resplit_ratio = envs.VDT_FLEET_RESPLIT_RATIO
        # Richer scaling signals (VDT_FLEET_SIGNALS): the roofline
        # phase inflates occupancy for a memory-bound fleet; a tenant
        # under its goodput floor is scale-out pressure and a scale-in
        # veto. Off (default) the decision is occupancy-only.
        self.signals = envs.VDT_FLEET_SIGNALS
        self.roofline_weight = envs.VDT_FLEET_ROOFLINE_WEIGHT
        self.goodput_floor = envs.VDT_FLEET_GOODPUT_FLOOR
        self._goodput: dict[str, float] = {}
        self.max_num_seqs = max(1, config.scheduler_config.max_num_seqs)
        # Supervisor-style ACTION budget (shared across every fleet
        # action): next_delay() consumes one attempt, None = exhausted
        # until the rolling window slides — same machinery as the PR-2
        # restart budget, zero backoff (pacing is the tick's job).
        self.supervisor = RestartSupervisor(
            max_attempts=envs.VDT_FLEET_ACTIONS,
            window_s=envs.VDT_FLEET_ACTION_WINDOW_S,
            backoff_base_s=0.0, backoff_max_s=0.0)
        self.events = EventRecorder()
        # Counters (vdt:fleet_*; exact values — one controller owns the
        # whole fleet, nothing to merge).
        self.scale_outs = 0
        self.scale_ins = 0
        self.wedge_cycles = 0
        self.warm_start_pages = 0
        self.quarantines = 0
        self.freezes: dict[str, int] = {}
        # Correctness-sentinel quarantine hints (observe_quarantine):
        # replica -> cause, consumed by _check_quarantine on the next
        # tick. Only populated under VDT_FLEET_SIGNALS.
        self._quarantine_hints: dict[int, str] = {}
        # Per-replica stats snapshot + receipt instant (monotonic);
        # in-process replicas refresh synchronously each tick,
        # subprocess replicas are fed passively by the stats polls that
        # already flow through _aggregate_stats.
        self._snap: dict[int, tuple[dict, float]] = {}
        # Step-phase heartbeat: replica -> (last steps_dispatched seen,
        # instant it last ADVANCED). The wedge detector reads the age.
        self._step_marks: dict[int, tuple[int, float]] = {}
        # Replicas mid-drain: i -> {"mode": "retire"|"convert",
        # "role": new role or None, "deadline": monotonic}.
        self._draining: dict[int, dict] = {}
        self._high_ticks = 0
        self._low_ticks = 0
        self._resplit_dir: Optional[str] = None
        self._resplit_ticks = 0
        self._last_tick = float("-inf")
        logger.info(
            "fleet controller: replicas [%d, %d], watermarks "
            "[%.2f, %.2f], %d-tick hysteresis, budget %d/%.0fs",
            self.min_replicas, self.max_replicas, self.low_wm,
            self.high_wm, self.eval_ticks, self.supervisor.max_attempts,
            self.supervisor.window_s)

    # ------------------------------------------------------------------
    # Membership views
    # ------------------------------------------------------------------
    def _active(self) -> list[int]:
        """Replicas in rotation (serving): not down, not retired.
        Draining replicas still count — they hold live work."""
        c = self.client
        return [i for i in range(len(c.clients))
                if i not in c._down and i not in c._retired]

    def _placeable(self) -> list[int]:
        c = self.client
        return [i for i in self._active() if i not in c._no_place]

    # ------------------------------------------------------------------
    # Signal feed
    # ------------------------------------------------------------------
    def observe_stats(self, replica: int, stats: dict) -> None:
        """Feed one replica's stats dict (the same passive channel the
        router rides: every stats poll through _aggregate_stats)."""
        if not isinstance(stats, dict):
            return
        if ("num_running_reqs" not in stats
                and "steps_dispatched" not in stats):
            return  # not a scheduler stats dict
        now = time.monotonic()
        self._snap[replica] = (stats, now)
        steps = stats.get("steps_dispatched")
        if isinstance(steps, (int, float)):
            mark = self._step_marks.get(replica)
            if mark is None or steps != mark[0]:
                self._step_marks[replica] = (int(steps), now)

    def _refresh_snapshots(self) -> None:
        """In-process replicas answer get_stats inline (a dict build);
        subprocess replicas are never polled here — passive feed only
        (the router's maybe_refresh discipline)."""
        c = self.client
        for i in self._active():
            if getattr(c.clients[i], "engine_core", None) is None:
                continue
            try:
                self.observe_stats(i, c.clients[i].get_stats())
            except Exception:  # noqa: BLE001 - replica mid-death; the
                # output path's own poll surfaces it for failover.
                pass

    def observe_goodput(self, fracs: dict,
                        degraded: bool = False) -> None:
        """Per-tenant goodput fractions (metrics/stats.py FrontendStats
        SLO scoring, fed through the entrypoints' stats path). Only
        consulted when VDT_FLEET_SIGNALS is on. ``degraded`` — the SLO
        burn-rate watchdog's sustained-burn flag — registers as a
        zero-goodput pseudo-tenant, so under VDT_FLEET_SIGNALS with a
        goodput floor it counts as scale-out pressure and a scale-in
        veto exactly like a starved tenant; it clears as soon as the
        burn subsides."""
        if isinstance(fracs, dict):
            for tenant, frac in fracs.items():
                if isinstance(frac, (int, float)):
                    self._goodput[str(tenant)] = float(frac)
        if degraded:
            self._goodput["_slo_burn"] = 0.0
        else:
            self._goodput.pop("_slo_burn", None)

    def observe_quarantine(self, hints: dict) -> None:
        """Replica-quarantine hints from the correctness sentinel
        ({replica: cause} — sustained canary divergence or numerics
        strikes). Gated on VDT_FLEET_SIGNALS like the goodput feed: a
        hint is a SIGNAL into the existing actuator, never a new
        actuation path — _check_quarantine drains it through the same
        force-cycle rung (budget, fence, drain-migrate, probed respawn)
        the wedge detector uses."""
        if not self.signals or not isinstance(hints, dict):
            return
        for i, cause in hints.items():
            if isinstance(i, int):
                self._quarantine_hints[i] = str(cause)

    def _freeze(self, reason: str) -> None:
        self.freezes[reason] = self.freezes.get(reason, 0) + 1
        self.events.record("", ev.FLEET_FREEZE, {"reason": reason})

    # ------------------------------------------------------------------
    # HA control-plane hooks (engine/control_plane.py overrides these;
    # the in-process controller is its own single owner, so the base
    # fence always passes and the journal is a no-op).
    # ------------------------------------------------------------------
    def _fence(self, action: str) -> bool:
        """Epoch check before an actuation. Base: always allowed."""
        return True

    def _journal_begin(self, i: int, mode: str,
                       role: Optional[str]) -> None:
        """Write the intent record for a multi-step action's next rung
        BEFORE actuating it. Base: no journal."""

    def _journal_end(self, i: int) -> None:
        """The multi-step action on replica ``i`` reached a terminal
        state; drop its intent record. Base: no journal."""

    def close(self) -> None:
        """Release control-plane state (HA override relinquishes the
        lease); nothing to do in-process."""

    def _actuation_allowed(self, now: float) -> bool:
        """Stale/missing stats for ANY in-rotation replica freeze all
        actuation: the controller never reshapes the fleet on blind
        signals (scale decisions and the wedge detector both read the
        snapshots this check guards)."""
        if self.stale_s <= 0:
            return True
        for i in self._active():
            snap = self._snap.get(i)
            if snap is None or now - snap[1] > self.stale_s:
                self._freeze(FREEZE_STALE_STATS)
                return False
        return True

    def _budget_ok(self) -> bool:
        if self.supervisor.next_delay() is None:
            self._freeze(FREEZE_BUDGET)
            return False
        return True

    # ------------------------------------------------------------------
    # The loop
    # ------------------------------------------------------------------
    def tick(self) -> None:
        """One control-loop evaluation; called from the DP client's
        output paths (where the legacy resurrection probe ran). Probe
        results apply on every call; the control logic rate-limits
        itself to VDT_FLEET_TICK_S."""
        c = self.client
        with c._lock:
            self._apply_probe_results()
            now = time.monotonic()
            if now - self._last_tick < self.tick_s:
                return
            self._last_tick = now
            self._refresh_snapshots()
            self._progress_drains(now)
            self._schedule_probes(now)
            if not self._actuation_allowed(now):
                return
            self._check_wedges(now)
            self._check_quarantine(now)
            if not self._draining:
                # One structural action in flight at a time: scale and
                # re-split decisions wait for the drain to land.
                self._evaluate_scaling(now)
                self._evaluate_resplit(now)

    # -- Folded resurrection probe (satellite: one actuator, one budget)
    def _apply_probe_results(self) -> None:
        c = self.client
        while True:
            try:
                i, ok = c._probe_results.get_nowait()
            except queue.Empty:
                break
            c._probing.discard(i)
            if not ok:
                continue
            c._down.discard(i)
            c.replica_resurrections += 1
            if c.coordinator is not None:
                try:
                    c.coordinator.set_health(i, True)
                except RuntimeError:
                    # Partitioned from the control plane mid-apply: the
                    # replica serves locally; the coordinator relearns
                    # its health from the next successful RPC epoch.
                    if not c._coord_partition_degraded():
                        raise
            # Fresh engine: restart the step-phase heartbeat and give
            # the stale-stats check a grace window.
            self._mark_fresh(i)
            logger.info("DP replica %d resurrected; back in rotation", i)

    def _schedule_probes(self, now: float) -> None:
        """The legacy _maybe_resurrect scheduling, minus retired slots,
        with restart HEALTH VERIFICATION (a probe that reconnects but
        fails its warm-start stats probe reports still-down and does
        not count as a resurrection)."""
        c = self.client
        down = c._down - c._retired
        if not down or c._probe_interval <= 0:
            return
        for i in sorted(down):
            if i in c._probing or now < c._next_probe.get(i, 0):
                continue
            c._next_probe[i] = now + c._probe_interval
            if c._supervisors[i].next_delay() is None:
                continue  # replica restart budget burnt
            c._probing.add(i)
            threading.Thread(target=c._probe_restart_verified,
                             args=(i, ), name=f"dp-resurrect-{i}",
                             daemon=True).start()

    def _mark_fresh(self, i: int) -> None:
        now = time.monotonic()
        self._snap[i] = (self._snap.get(i, ({}, 0.0))[0], now)
        mark = self._step_marks.get(i)
        self._step_marks[i] = ((mark[0] if mark else 0), now)

    # -- Wedge detection ------------------------------------------------
    def _check_wedges(self, now: float) -> None:
        if self.wedge_s <= 0:
            return
        c = self.client
        for i in self._active():
            if i in self._draining or not c._live[i]:
                continue
            mark = self._step_marks.get(i)
            wedged = (mark is not None
                      and now - mark[1] > self.wedge_s)
            if fault_injection.should_fire("fleet.replica_wedge"):
                wedged = True  # drill: force the detector
            if wedged:
                self._cycle_wedged(i, now)

    def _cycle_wedged(self, i: int, now: float) -> None:
        """Force-cycle an alive-but-not-stepping replica: migrate its
        journaled requests (uncounted — the replica never died, so the
        only rung this degradation lands on is wedge_cycles), take it
        out of rotation, and let the folded probe restart it through
        its PR-2 restart budget."""
        c = self.client
        logger.error(
            "fleet: replica %d WEDGED (steps stalled > %.1fs with %d "
            "live request(s)); force-cycling", i, self.wedge_s,
            len(c._live[i]))
        if self._cycle_out(i, now):
            self.wedge_cycles += 1
            self.events.record("", ev.FLEET_WEDGE_CYCLE, {"replica": i})

    def _cycle_out(self, i: int, now: float) -> bool:
        """The shared force-cycle actuation rung (wedge detector and
        correctness quarantine): budget, fence, out of rotation,
        journal-migrate, immediate probed respawn. True when actuated
        (the caller owns the cause-specific counter/event)."""
        if not self._budget_ok():
            return False
        if not self._fence("force_cycle"):
            return False
        c = self.client
        c._down.add(i)
        if c.router is not None:
            c.router.on_replica_down(i)
        if c.coordinator is not None:
            c.coordinator.set_health(i, False, clear=True)
        c._drain_migrate_locked(i, report=False)
        c._next_probe[i] = now  # probe immediately, through the budget
        return True

    def _check_quarantine(self, now: float) -> None:
        """Drain the correctness sentinel's quarantine hints through
        the force-cycle rung: drain + respawn via the PR-16 machinery,
        never a new actuation path. A hint for a replica already out of
        rotation (or mid-drain) is dropped — its cycle is in flight."""
        if not self._quarantine_hints:
            return
        hints, self._quarantine_hints = self._quarantine_hints, {}
        c = self.client
        active = set(self._active())
        for i, cause in sorted(hints.items()):
            if i not in active or i in self._draining or i in c._down:
                continue
            logger.error(
                "fleet: replica %d QUARANTINED by correctness sentinel "
                "(%s); force-cycling", i, cause)
            if self._cycle_out(i, now):
                self.quarantines += 1
                self.events.record("", ev.FLEET_QUARANTINE,
                                   {"replica": i, "cause": cause})
                if getattr(c, "correctness", None) is not None:
                    # The slot respawns as a fresh engine: clear its
                    # suspicion so the new replica starts clean.
                    c.correctness.forget_replica(i)

    # -- Scaling --------------------------------------------------------
    def _occupancy(self, members: list[int]) -> float:
        c = self.client
        cap = len(members) * self.max_num_seqs
        if cap <= 0:
            return 1.0
        live = sum(len(c._live[i]) for i in members)
        waiting = sum(
            float(self._snap.get(i, ({}, 0.0))[0]
                  .get("num_waiting_reqs", 0)) for i in members)
        return (live + waiting) / cap

    def _memory_bound_frac(self, members: list[int]) -> float:
        """Device-time fraction of the fleet's attributed phases that
        sit on the bandwidth roof (PR 14's classifier over the
        per-replica perf_phases/perf_peaks riding the stats feed)."""
        from vllm_distributed_tpu.metrics.costmodel import \
            classify_roofline
        total = bound = 0.0
        for i in members:
            stats = self._snap.get(i, ({}, 0.0))[0]
            phases = stats.get("perf_phases")
            peaks = stats.get("perf_peaks")
            if not isinstance(phases, dict) or not isinstance(peaks,
                                                              dict):
                continue
            for entry in phases.values():
                if not isinstance(entry, dict):
                    continue
                dev_s = float(entry.get("device_seconds", 0.0) or 0.0)
                if dev_s <= 0.0:
                    continue
                total += dev_s
                if classify_roofline(entry, peaks) == "bandwidth":
                    bound += dev_s
        return bound / total if total > 0.0 else 0.0

    def _evaluate_scaling(self, now: float) -> None:
        active = self._active()
        occ = self._occupancy(active)
        starved = False
        if self.signals:
            # Memory-bound waves gain little from batching deeper on
            # the same replicas — inflate effective occupancy so the
            # fleet scales out earlier and resists scale-in.
            occ *= 1.0 + self.roofline_weight \
                * self._memory_bound_frac(active)
            # An SLO-starved tenant is scale-out pressure regardless
            # of occupancy, and vetoes scale-in.
            if self.goodput_floor > 0 and self._goodput:
                starved = (min(self._goodput.values())
                           < self.goodput_floor)
        self._high_ticks = self._high_ticks + 1 \
            if occ >= self.high_wm or starved else 0
        self._low_ticks = self._low_ticks + 1 \
            if occ <= self.low_wm and not starved else 0
        if self._high_ticks >= self.eval_ticks:
            self._high_ticks = 0
            self._scale_out(now)
        elif (self._low_ticks >= self.eval_ticks
              and len(active) > self.min_replicas):
            self._low_ticks = 0
            self._begin_retire(now)

    def _scale_out(self, now: float) -> None:
        c = self.client
        if len(self._active()) >= self.max_replicas:
            self._freeze(FREEZE_AT_MAX)
            return
        # Reuse the lowest retired slot (its device slice is reserved);
        # append a fresh rank only past that.
        reuse = min(c._retired) if c._retired else None
        slot = reuse if reuse is not None else len(c.clients)
        role = None
        if c.disagg is not None:
            # Grow the pressured pool (ties grow prefill: admission
            # pressure lands there first).
            pp = self._pool_occupancy("prefill")
            dp = self._pool_occupancy("decode")
            from vllm_distributed_tpu.engine.disagg import (DECODE_POOL,
                                                            PREFILL_POOL)
            role = DECODE_POOL if dp > pp else PREFILL_POOL
        if not self._budget_ok():
            return
        if not self._fence("scale_out"):
            return
        try:
            fault_injection.fire_or_raise("fleet.scale_stall")
            newc = c._spawn_replica(slot, role)
        except Exception as e:  # noqa: BLE001 - provisioning failed;
            # the action budget was consumed, so a wedged provisioner
            # converges to frozen, not thrashing.
            logger.error("fleet: scale-out of replica %d stalled: %s",
                         slot, e)
            self._freeze(FREEZE_SCALE_STALL)
            return
        c._enter_replica(slot, newc, role)
        self.scale_outs += 1
        self._count_warm_start(slot)
        self._mark_fresh(slot)
        self.events.record("", ev.FLEET_SCALE_OUT,
                           {"replica": slot, "role": role,
                            "reused": reuse is not None})
        logger.info("fleet: scaled OUT to %d replicas (replica %d%s)",
                    len(self._active()), slot,
                    f", role {role}" if role else "")

    def _begin_retire(self, now: float) -> None:
        c = self.client
        victims = [i for i in self._active() if i not in self._draining]
        if c.disagg is not None:
            # Never retire a pool's last member: disagg needs >= 1 of
            # each role to serve at all.
            victims = [i for i in victims
                       if self._pool_members(c.disagg.role_of(i),
                                             victims) != [i]]
        if not victims:
            return
        if not self._budget_ok():
            return
        if not self._fence("scale_in"):
            return
        victim = min(victims, key=lambda i: (len(c._live[i]), -i))
        self._start_drain(victim, "retire", None, now)
        logger.info("fleet: retiring replica %d (drain deadline %.1fs)",
                    victim, self.drain_s)

    def _start_drain(self, i: int, mode: str, role: Optional[str],
                     now: float) -> None:
        c = self.client
        # Intent BEFORE actuation: a leader that dies between here and
        # _finish_* leaves a record a successor replays to completion.
        self._journal_begin(i, mode, role)
        c._no_place.add(i)
        if c.coordinator is not None:
            # Out of the routing set, counts kept: the drain migration
            # reports its own deltas as requests move off.
            c.coordinator.set_health(i, False)
        self._draining[i] = {"mode": mode, "role": role,
                             "deadline": now + self.drain_s}

    def _progress_drains(self, now: float) -> None:
        c = self.client
        for i in list(self._draining):
            d = self._draining[i]
            if c._live[i] and now < d["deadline"]:
                continue
            if not self._fence(d["mode"]):
                # Deposed mid-drain: abandon the LOCAL record without
                # touching fleet state — the new leaseholder owns
                # completion through the journal.
                self._draining.pop(i)
                continue
            if c._live[i]:
                # Past the deadline: journal-migrate the stragglers as
                # continuations — token-identical under greedy, zero
                # loss, no failover counted.
                c._drain_migrate_locked(i)
            self._draining.pop(i)
            if d["mode"] == "retire":
                self._finish_retire(i)
            else:
                self._finish_convert(i, d["role"])

    def _finish_retire(self, i: int) -> None:
        c = self.client
        try:
            c.clients[i].shutdown()
        except Exception:  # noqa: BLE001 - best-effort teardown
            pass
        c._no_place.discard(i)
        c._retired.add(i)
        c._down.add(i)
        if c.router is not None:
            c.router.on_replica_down(i)
        if c.coordinator is not None:
            c.coordinator.set_health(i, False, clear=True)
        if c.disagg is not None:
            c.disagg.remove_replica(i)
        self._snap.pop(i, None)
        self._step_marks.pop(i, None)
        self.scale_ins += 1
        self._journal_end(i)
        self.events.record("", ev.FLEET_SCALE_IN, {"replica": i})
        logger.info("fleet: scaled IN to %d replicas (replica %d "
                    "retired; zero requests lost)",
                    len(self._active()), i)

    def _finish_convert(self, i: int, role: str) -> None:
        """Drained converted replica: rebuild its engine with the new
        role's specialized config (role-appropriate token buckets and
        precompile lattice) and re-enter it in the other pool."""
        c = self.client
        try:
            c.clients[i].shutdown()
        except Exception:  # noqa: BLE001 - best-effort teardown
            pass
        try:
            newc = c._spawn_replica(i, role)
        except Exception as e:  # noqa: BLE001 - conversion spawn
            # failed: the slot degrades to DOWN and the folded probe
            # owns its recovery (in the old role) through the replica's
            # restart budget.
            logger.error("fleet: re-split rebuild of replica %d "
                         "failed: %s", i, e)
            self._freeze(FREEZE_SCALE_STALL)
            c._no_place.discard(i)
            c._down.add(i)
            if c.router is not None:
                c.router.on_replica_down(i)
            if c.coordinator is not None:
                c.coordinator.set_health(i, False, clear=True)
            c._next_probe[i] = time.monotonic() + c._probe_interval
            self._journal_end(i)
            return
        c.clients[i] = newc
        c._no_place.discard(i)
        if c.router is not None:
            c.router.on_replica_down(i)  # old role's pages died
        if c.coordinator is not None:
            c.coordinator.set_health(i, True, clear=True)
        if c.disagg is not None:
            c.disagg.set_role(i, role)
        self._count_warm_start(i)
        self._mark_fresh(i)
        self._journal_end(i)
        self.events.record("", ev.FLEET_RESPLIT,
                           {"replica": i, "role": role})
        logger.info("fleet: replica %d re-entered rotation as %s "
                    "(pools now prefill=%s decode=%s)", i, role,
                    c.disagg.prefill_pool if c.disagg else None,
                    c.disagg.decode_pool if c.disagg else None)

    # -- Live pool re-split ---------------------------------------------
    def _pool_members(self, role: str,
                      within: Optional[list[int]] = None) -> list[int]:
        d = self.client.disagg
        pool = d.prefill_pool if role == "prefill" else d.decode_pool
        members = within if within is not None else self._active()
        return [i for i in pool if i in members]

    def _pool_occupancy(self, role: str) -> float:
        members = self._pool_members(role)
        return self._occupancy(members) if members else 0.0

    def _evaluate_resplit(self, now: float) -> None:
        c = self.client
        if c.disagg is None or self.resplit_ratio <= 0:
            return
        from vllm_distributed_tpu.engine.disagg import (DECODE_POOL,
                                                        PREFILL_POOL)
        pp = self._pool_occupancy(PREFILL_POOL)
        dp = self._pool_occupancy(DECODE_POOL)
        # The pressured pool must carry real load (>= the low
        # watermark) AND out-pressure the other pool by the ratio; the
        # DONOR pool must keep a member after the conversion.
        direction = None
        if (dp >= self.low_wm and dp > pp * self.resplit_ratio
                and len(self._pool_members(PREFILL_POOL)) > 1):
            direction = DECODE_POOL
        elif (pp >= self.low_wm and pp > dp * self.resplit_ratio
              and len(self._pool_members(DECODE_POOL)) > 1):
            direction = PREFILL_POOL
        if direction != self._resplit_dir:
            self._resplit_dir = direction
            self._resplit_ticks = 0
        if direction is None:
            return
        self._resplit_ticks += 1
        if self._resplit_ticks < self.eval_ticks:
            return
        self._resplit_ticks = 0
        if not c.disagg.symmetric_roles():
            # Asymmetric per-role TP: the convert would need a
            # different device footprint than the slot owns.
            self._freeze(FREEZE_ASYM_TP)
            return
        if not self._budget_ok():
            return
        donor_role = (PREFILL_POOL if direction == DECODE_POOL
                      else DECODE_POOL)
        donors = [i for i in self._pool_members(donor_role)
                  if i not in self._draining]
        if len(donors) <= 1:
            return
        if not self._fence("resplit"):
            return
        victim = min(donors, key=lambda i: (len(c._live[i]), -i))
        self._start_drain(victim, "convert", direction, now)
        logger.info(
            "fleet: re-splitting pools — converting replica %d "
            "%s -> %s (occupancy prefill=%.2f decode=%.2f)", victim,
            donor_role, direction, pp, dp)

    # -- Warm start ------------------------------------------------------
    def _count_warm_start(self, i: int) -> None:
        """Pages the fresh engine restored from the shared T2 spill
        namespace (core/kv_tier.py counts them at its disk scan)."""
        try:
            stats = self.client.clients[i].get_stats()
        except Exception:  # noqa: BLE001 - stats probe is best-effort
            return
        tier = stats.get("kv_tier")
        if isinstance(tier, dict):
            self.warm_start_pages += int(tier.get("warm_start_pages", 0))
        self.observe_stats(i, stats)

    # ------------------------------------------------------------------
    def reset(self) -> None:
        """Full-fleet restart: every surviving replica respawned with
        empty state; drains are moot (counters persist)."""
        self._draining.clear()
        self._snap.clear()
        self._step_marks.clear()
        self._quarantine_hints.clear()
        self._high_ticks = self._low_ticks = self._resplit_ticks = 0
        self._resplit_dir = None
        self._last_tick = float("-inf")

    def drain_events(self) -> list:
        return self.events.drain()

    def get_stats(self) -> dict:
        """The ``fleet`` entry of the DP stats aggregation, rendered as
        the vdt:fleet_* families."""
        c = self.client
        return {
            "replicas": len(self._active()),
            "draining": len(self._draining),
            "scale_outs": self.scale_outs,
            "scale_ins": self.scale_ins,
            "resplits": (c.disagg.resplits
                         if c.disagg is not None else 0),
            "wedge_cycles": self.wedge_cycles,
            "warm_start_pages": self.warm_start_pages,
            "quarantines": self.quarantines,
            "freezes": dict(self.freezes),
        }
