"""Incremental detokenization.

Reference: vllm/v1/engine/detokenizer.py (per-request incremental decode
with stable-prefix emission and stop-string scanning back-off).

The incremental algorithm keeps a small suffix window of token ids: a
token's text is only emitted once decoding a longer suffix no longer
changes it (byte-level BPE can merge with following tokens; multi-byte
unicode may be split across tokens).
"""

from typing import Optional

from vllm_distributed_tpu.sampling_params import SamplingParams


class IncrementalDetokenizer:
    """Per-request detokenizer state."""

    def __init__(self, tokenizer, params: SamplingParams,
                 prompt_token_ids: list[int]) -> None:
        self.tokenizer = tokenizer
        self.skip_special_tokens = params.skip_special_tokens
        self.stop_strings = params.stop or []
        # Longest stop string bounds how much emitted text we must retain
        # to detect a stop spanning an emission boundary.
        self._max_stop_len = max((len(s) for s in self.stop_strings),
                                 default=0)
        self.token_ids: list[int] = []
        self.output_text = ""
        # Prefix/read-offset incremental decode (reference:
        # detokenize_incrementally): text is emitted as the difference
        # between decoding [prefix:] and [prefix:read] — decoding tail
        # segments independently would drop the separators a tokenizer
        # inserts BETWEEN tokens (spaces in word-level/SentencePiece).
        self._prefix_offset = 0
        self._read_offset = 0

    def update(self, new_token_ids: list[int]) -> Optional[str]:
        """Append tokens; returns the stop string hit, if any."""
        if self.tokenizer is None:
            return None
        self.token_ids.extend(new_token_ids)
        prefix_text = self.tokenizer.decode(
            self.token_ids[self._prefix_offset:self._read_offset],
            skip_special_tokens=self.skip_special_tokens)
        full_text = self.tokenizer.decode(
            self.token_ids[self._prefix_offset:],
            skip_special_tokens=self.skip_special_tokens)
        # A window ending in the unicode replacement char may be a split
        # multi-byte sequence: hold it back until completed.
        if len(full_text) <= len(prefix_text) or full_text.endswith("�"):
            return None
        new_text = full_text[len(prefix_text):]
        self._prefix_offset = self._read_offset
        self._read_offset = len(self.token_ids)
        self.output_text += new_text

        if self.stop_strings:
            # Scan only the recently-produced region.
            window_start = max(
                0,
                len(self.output_text) - len(new_text) - self._max_stop_len)
            window = self.output_text[window_start:]
            for stop in self.stop_strings:
                idx = window.find(stop)
                if idx != -1:
                    # Truncate at the stop string (excluded from output).
                    self.output_text = \
                        self.output_text[:window_start + idx]
                    return stop
        return None

    def flush(self) -> None:
        """Emit any held-back tail at end of generation (text withheld by
        update() because it ended in a split multi-byte sequence)."""
        if self.tokenizer is None or self._read_offset >= len(
                self.token_ids):
            return
        prefix_text = self.tokenizer.decode(
            self.token_ids[self._prefix_offset:self._read_offset],
            skip_special_tokens=self.skip_special_tokens)
        full_text = self.tokenizer.decode(
            self.token_ids[self._prefix_offset:],
            skip_special_tokens=self.skip_special_tokens)
        if len(full_text) > len(prefix_text):
            self.output_text += full_text[len(prefix_text):]
        self._prefix_offset = self._read_offset = len(self.token_ids)

    def get_next_output_text(self, prev_len: int) -> str:
        """Delta since the caller's last read."""
        return self.output_text[prev_len:]
