"""HA fleet control plane: lease-fenced controller with leader failover.

ROADMAP item 2's named remainder — PR 16's ``FleetController`` lives
inside one DP client, so the front-end hosting it is a single point of
failure for the whole fleet's shape, and a second API server would run
a second, un-coordinated actuator. This module hoists the controller
behind the existing coordinator RPC socket (engine/coordinator.py grew
``lease``/``fence``/``lease_info`` ops) with three robustness
mechanisms, all behind ``VDT_FLEET_CONTROLLER`` (default off =
byte-identical in-process behavior):

* **Leader election + leases** — every front-end constructs an
  ``HAFleetController``; each tick it acquires/renews a TTL lease
  (monotonic coordinator clock) and only the current leaseholder runs
  the actuation half of the loop. Standbys keep feeding signals and
  serving; on leader death (``fleet.controller_die``) a standby's next
  acquire succeeds within the TTL.
* **Fencing epochs** — the coordinator bumps the lease epoch on every
  holder CHANGE. Each actuation (spawn/drain/retire/re-split/
  force-cycle, plus the drain-progress rungs) first runs a ``fence``
  check stamped with the epoch the controller last held; a
  paused-then-resumed ex-leader (``fleet.lease_expire``) fails it —
  the rejection is counted in ``vdt:fleet_fenced_actions_total``
  (never raised into serving) and the ex-leader demotes itself.
* **Crash-safe actuation journal** — multi-step actions write a JSON
  intent record (atomic tmp+rename) to the T2 spill namespace BEFORE
  each rung (``FleetController._journal_begin`` at drain start,
  ``_journal_end`` at retire/convert completion). A newly elected
  leader replays ``pending()`` records — re-entering the drain so the
  deadline/journal-migrate/retire machinery completes it with token
  parity — or safely aborts records that no longer apply.
* **Partition degradation** — a front-end whose coordinator RPCs fail
  (``coordinator.partition``) keeps serving and routing with frozen
  placement: lease/fence errors count a ``reason="partition"`` freeze
  and suppress actuation, mirroring the stale-stats freeze ladder,
  and the DP client's routing falls back to local least-loaded.

Satellite guard: with the control plane on, a standby front-end's tick
is a fenced no-op — in particular the legacy resurrection-probe
opportunity is counted (``action="resurrect"``) instead of actuated,
so a dead replica is only ever respawned by the leaseholder.
"""

import json
import os
import tempfile
import time
import uuid
from typing import Optional

from vllm_distributed_tpu.config import EngineConfig
from vllm_distributed_tpu.engine.fleet import (FREEZE_PARTITION,
                                               FleetController)
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.metrics import events as ev
from vllm_distributed_tpu.utils import fault_injection

logger = init_logger(__name__)


def journal_root() -> str:
    """Actuation-journal directory: ``VDT_FLEET_JOURNAL_DIR`` when set,
    else the T2 spill namespace (shared across front-ends exactly like
    warm-start pages), else a per-process tempdir (single front-end:
    still crash-safe across leader re-elections within the fleet)."""
    from vllm_distributed_tpu import envs
    root = envs.VDT_FLEET_JOURNAL_DIR
    if root:
        return root
    tier = envs.VDT_KV_TIER_DIR
    if tier:
        return os.path.join(tier, "fleet_journal")
    return tempfile.mkdtemp(prefix="vdt-fleet-journal-")


class ActuationJournal:
    """One JSON intent file per in-flight multi-step action, written
    atomically (tmp + rename) so a reader never sees a torn record.
    The key is the action's identity (``drain-<replica>``): a rung
    update overwrites, completion removes."""

    def __init__(self, root: str) -> None:
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, f"{key}.json")

    def begin(self, key: str, record: dict) -> None:
        tmp = self._path(key) + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(record, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._path(key))

    def end(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass

    def pending(self) -> dict:
        """All live intent records, key -> record (unreadable strays
        are skipped — atomic writes make them leftover tmp files or
        foreign junk, not half-written intents)."""
        out = {}
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            return out
        for name in names:
            if not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(self.root, name),
                          encoding="utf-8") as f:
                    out[name[:-len(".json")]] = json.load(f)
            except (OSError, ValueError):
                continue
        return out


class HAFleetController(FleetController):
    """Lease-fenced ``FleetController``: the decision/actuation loop is
    unchanged (inherited), but every tick first settles leadership and
    every actuation passes the coordinator's epoch fence. Multiple
    instances — one per front-end — safely share one fleet."""

    ha = True

    def __init__(self, client, config: EngineConfig,
                 holder: Optional[str] = None) -> None:
        super().__init__(client, config)
        from vllm_distributed_tpu import envs
        assert client.coordinator is not None, \
            "HA fleet controller needs the coordinator RPC plane"
        self.coord = client.coordinator
        self.holder = holder or f"fe-{uuid.uuid4().hex[:8]}"
        self.ttl_s = envs.VDT_FLEET_LEASE_TTL_S
        self.journal = ActuationJournal(journal_root())
        self.is_leader = False
        # The epoch of the lease we last HELD — actuations are stamped
        # with it, so after a takeover elsewhere our commands read as
        # stale to the coordinator no matter what we believe locally.
        self.epoch = 0
        self.leader_transitions = 0
        self.fenced_actions: dict[str, int] = {}
        self.journal_replays = 0
        # fleet.controller_die: the controller stops ticking/renewing
        # entirely, exactly as if its front-end process was killed.
        self.dead = False
        logger.info(
            "HA fleet controller %s: lease TTL %.1fs, journal at %s",
            self.holder, self.ttl_s, self.journal.root)

    # ------------------------------------------------------------------
    # Leadership
    # ------------------------------------------------------------------
    def _lease_tick(self) -> None:
        was = self.is_leader
        if was and fault_injection.should_fire("fleet.lease_expire"):
            # A paused-then-resumed leader: the renewal is skipped but
            # the controller still believes it leads — the next fenced
            # actuation is where reality catches up (epoch check).
            return
        try:
            rep = self.coord.acquire_lease(self.holder, self.ttl_s)
        except Exception as e:  # noqa: BLE001 - partitioned from the
            # control plane: keep serving with frozen placement, no
            # actuation — the stale-stats freeze ladder's idiom.
            self.is_leader = False
            self._freeze(FREEZE_PARTITION)
            logger.warning("fleet controller %s cannot reach the "
                           "control plane (%s); placement frozen",
                           self.holder, e)
            return
        self.is_leader = bool(rep.get("granted"))
        self.leader_transitions = int(rep.get("transitions", 0))
        if self.is_leader:
            self.epoch = int(rep.get("epoch", 0))
            if not was:
                self.events.record("", ev.FLEET_LEADER_TAKEOVER,
                                   {"holder": self.holder,
                                    "epoch": self.epoch})
                logger.info(
                    "fleet controller %s acquired the lease (epoch %d)",
                    self.holder, self.epoch)
                self._replay_journal()

    def tick(self) -> None:
        if self.dead:
            return
        if fault_injection.should_fire("fleet.controller_die"):
            self.dead = True
            self.is_leader = False
            self.events.record("", ev.FLEET_CONTROLLER_DOWN,
                               {"holder": self.holder})
            logger.error("fleet controller %s DIED (drill); lease "
                         "lapses within %.1fs", self.holder, self.ttl_s)
            return
        c = self.client
        with c._lock:
            self._lease_tick()
            if not self.is_leader:
                # Standby (or partitioned): never actuate. The legacy
                # resurrection-probe opportunity in particular is a
                # counted fenced no-op — only the leaseholder respawns
                # a dead replica (single-owner actuation guard).
                if c._down - c._retired:
                    self._count_fenced("resurrect")
                return
        super().tick()

    # ------------------------------------------------------------------
    # Fencing
    # ------------------------------------------------------------------
    def _count_fenced(self, action: str) -> None:
        self.fenced_actions[action] = \
            self.fenced_actions.get(action, 0) + 1
        self.events.record("", ev.FLEET_FENCED, {"action": action})

    def _fence(self, action: str) -> bool:
        try:
            ok = self.coord.fence(self.epoch, action)
        except Exception:  # noqa: BLE001 - partitioned mid-actuation:
            # fail safe (no actuation), counted on the freeze ladder.
            self._freeze(FREEZE_PARTITION)
            return False
        if not ok:
            # Stale epoch (or lapsed lease): we were deposed. Count the
            # rejection, demote, and let the next tick re-elect.
            self._count_fenced(action)
            self.is_leader = False
            logger.warning(
                "fleet controller %s: %s fenced off (stale epoch %d)",
                self.holder, action, self.epoch)
            return False
        return True

    # ------------------------------------------------------------------
    # Journal
    # ------------------------------------------------------------------
    def _journal_begin(self, i: int, mode: str,
                       role: Optional[str]) -> None:
        self.journal.begin(f"drain-{i}", {
            "action": mode, "replica": i, "role": role,
            "epoch": self.epoch, "holder": self.holder})

    def _journal_end(self, i: int) -> None:
        self.journal.end(f"drain-{i}")

    def _replay_journal(self) -> None:
        """Called on takeover (balancer lock held): complete or abort
        every half-done multi-step action the previous leader left.
        Completion re-enters the drain — ``_start_drain`` re-asserts
        out-of-placement state and a fresh deadline, and the normal
        journal-migrate machinery finishes the retire/convert with
        token parity."""
        c = self.client
        now = time.monotonic()
        for key, rec in self.journal.pending().items():
            i = rec.get("replica")
            mode = rec.get("action")
            role = rec.get("role")
            if (not isinstance(i, int) or not 0 <= i < len(c.clients)
                    or mode not in ("retire", "convert")
                    or i in c._retired):
                # No longer applies (slot already retired, or a record
                # from an incompatible fleet shape): safe abort.
                self.journal.end(key)
                continue
            self.journal_replays += 1
            self.events.record("", ev.FLEET_JOURNAL_REPLAY,
                               {"replica": i, "action": mode})
            logger.warning(
                "fleet controller %s: replaying journaled %s of "
                "replica %d left by %s", self.holder, mode, i,
                rec.get("holder"))
            self._start_drain(i, mode, role, now)

    # ------------------------------------------------------------------
    def close(self) -> None:
        if self.is_leader and not self.dead:
            try:
                self.coord.release_lease(self.holder)
            except Exception:  # noqa: BLE001 - coordinator already gone
                pass
        self.is_leader = False

    def get_stats(self) -> dict:
        stats = super().get_stats()
        stats["leader"] = int(self.is_leader and not self.dead)
        stats["lease_epoch"] = self.epoch
        stats["leader_transitions"] = self.leader_transitions
        stats["fenced_actions"] = dict(self.fenced_actions)
        stats["journal_replays"] = self.journal_replays
        return stats
