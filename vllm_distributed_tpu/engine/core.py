"""Engine core: the scheduler+executor busy loop.

Reference: vllm/v1/engine/core.py:55 (``EngineCore``: step:223,
_initialize_kv_caches:133; the multiprocess EngineCoreProc/DPEngineCoreProc
variants layer transport on top — here the in-process core comes first and
the ZMQ front-ends reuse it unchanged, mirroring InprocClient).
"""

from typing import Optional

from vllm_distributed_tpu.config import EngineConfig
from vllm_distributed_tpu.core.sched.scheduler import (EngineCoreOutput,
                                                       Scheduler)
from vllm_distributed_tpu.executor import Executor
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.request import (EngineCoreRequest, Request,
                                          RequestStatus)

logger = init_logger(__name__)


class EngineCore:

    def __init__(self, config: EngineConfig,
                 executor_class: Optional[type] = None) -> None:
        self.config = config
        # True when the most recent step() ran device work; busy loops
        # pace themselves when steps degenerate to host-only polls
        # (async KV transfers in flight, requests held on a pull).
        self.last_step_scheduled = False
        executor_class = executor_class or Executor.get_class(config)
        self.executor = executor_class(config)

        num_pages = self._initialize_kv_caches()
        config.cache_config.num_gpu_blocks = num_pages
        # Scheduler-side KV connector (disaggregated prefill; reference:
        # core.py constructs the connector beside the scheduler).
        from vllm_distributed_tpu.distributed.kv_transfer import (
            KVConnectorRole, create_kv_connector)
        kv_connector = create_kv_connector(config, KVConnectorRole.SCHEDULER)
        self.scheduler = Scheduler(config, num_blocks=num_pages,
                                   kv_connector=kv_connector)

    def _initialize_kv_caches(self) -> int:
        num_pages = self.executor.determine_num_available_blocks()
        logger.info("allocating %d KV pages (%d tokens)", num_pages,
                    num_pages * self.config.cache_config.block_size)
        self.executor.initialize_kv_cache(num_pages)
        return num_pages

    # ------------------------------------------------------------------
    def add_request(self, request: EngineCoreRequest) -> None:
        self.scheduler.add_request(Request.from_engine_core_request(request))

    def abort_requests(self, request_ids: list[str]) -> None:
        self.scheduler.finish_requests(request_ids,
                                       RequestStatus.FINISHED_ABORTED)

    def step(self) -> list[EngineCoreOutput]:
        """One scheduling iteration (reference: core.py:223)."""
        self.last_step_scheduled = False
        if not (self.scheduler.has_requests()
                or self.scheduler.has_kv_transfer_work()):
            return []
        scheduler_output = self.scheduler.schedule()
        self.last_step_scheduled = \
            scheduler_output.total_num_scheduled_tokens > 0
        runner_output = self.executor.execute_model(scheduler_output)
        return self.scheduler.update_from_output(scheduler_output,
                                                 runner_output)

    def has_unfinished_requests(self) -> bool:
        return self.scheduler.has_unfinished_requests()

    def has_kv_transfer_work(self) -> bool:
        """Async KV transfers needing step-polls even with no live
        requests (a producer's deferred frees)."""
        return self.scheduler.has_kv_transfer_work()

    def get_stats(self) -> dict:
        stats = self.scheduler.get_stats()
        stats.update(self.executor.get_stats())
        return stats

    def shutdown(self) -> None:
        self.executor.shutdown()
