"""Engine core: the scheduler+executor busy loop.

Reference: vllm/v1/engine/core.py:55 (``EngineCore``: step:223,
_initialize_kv_caches:133; the multiprocess EngineCoreProc/DPEngineCoreProc
variants layer transport on top — here the in-process core comes first and
the ZMQ front-ends reuse it unchanged, mirroring InprocClient).

The batch queue (reference: core.py:242 ``step_with_batch_queue``)
serves two overlap modes with one loop:

* **Pipeline parallelism** (depth = pipeline_parallel_size): up to one
  scheduler output per stage is dispatched before blocking on the
  oldest, so stage p of batch i+1 executes under stage p+1 of batch i;
  in-flight requests are skipped by the scheduler.
* **Async scheduling** (non-PP, depth 2; reference: the V1
  --async-scheduling path): the scheduler grants step N+1 — advancing
  each running decode request by one speculative position — while step
  N executes on device; the runner chains the unknown input token
  device-to-device and ``update_from_output`` reconciles when the
  sampled tokens land (stop/EOS detection lags one step).

On TPU the overlap itself comes from JAX async dispatch — the runner's
dispatch half enqueues programs without blocking; the queue's job is to
keep the host from blocking and the scheduler's grant state coherent.
"""

import os
import time
from collections import deque
from contextlib import nullcontext
from typing import Optional

from vllm_distributed_tpu.config import EngineConfig
from vllm_distributed_tpu.core.sched.scheduler import (EngineCoreOutput,
                                                       Scheduler)
from vllm_distributed_tpu.executor import Executor
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.metrics import events as ev
from vllm_distributed_tpu.metrics.stats import (HOST_GAP_BUCKETS,
                                                STEP_PHASE_BUCKETS,
                                                Histogram)
from vllm_distributed_tpu.request import (EngineCoreRequest, Request,
                                          RequestStatus)
from vllm_distributed_tpu.utils import fault_injection

logger = init_logger(__name__)

# Step phases the engine core times directly. prepare_inputs is timed
# inside the model runner (it happens under dispatch) and merged into
# the same family by get_stats. The sync (no batch queue) path folds
# dispatch+wait into "wait" — the device wait dominates it.
STEP_PHASES = ("schedule", "dispatch", "wait", "update")


class EngineCore:

    def __init__(self, config: EngineConfig,
                 executor_class: Optional[type] = None) -> None:
        self.config = config
        # True when the most recent step() ran device work; busy loops
        # pace themselves when steps degenerate to host-only polls
        # (async KV transfers in flight, requests held on a pull).
        self.last_step_scheduled = False
        # Transport telemetry (metrics/telemetry.py): ONE recorder per
        # engine core, installed for the construction window so every
        # connector / message queue / runner built below captures it.
        # In-process DP replicas therefore record into disjoint
        # recorders and the DP stats merge can sum per label exactly.
        from vllm_distributed_tpu.metrics import telemetry
        self.transport = telemetry.TransportRecorder()
        restore = telemetry.install_recorder(self.transport)
        try:
            executor_class = executor_class or Executor.get_class(config)
            self.executor = executor_class(config)

            num_pages = self._initialize_kv_caches()
            config.cache_config.num_gpu_blocks = num_pages
            # Scheduler-side KV connector (disaggregated prefill;
            # reference: core.py constructs the connector beside the
            # scheduler).
            from vllm_distributed_tpu.distributed.kv_transfer import (
                KVConnectorRole, create_kv_connector)
            kv_connector = create_kv_connector(config,
                                               KVConnectorRole.SCHEDULER)
            self.scheduler = Scheduler(config, num_blocks=num_pages,
                                       kv_connector=kv_connector)
            if self.scheduler.state_cache is not None:
                # The scheduler never touches device arrays; hand it the
                # runner's per-slot pool bytes so vdt:ssm_state_bytes_held
                # reports real HBM. A runner without a pool (executor
                # variants the gate excludes) leaves it at 0.
                runner = getattr(getattr(self.executor, "worker", None),
                                 "model_runner", None)
                if runner is not None:
                    self.scheduler.state_cache.bytes_per_slot = getattr(
                        runner, "state_pool_slot_bytes", lambda: 0)()
                    self.scheduler.state_cache.journal_fingerprint = \
                        getattr(runner, "_state_fingerprint",
                                lambda: b"")()
            if self.scheduler.kv_tier is not None:
                # Hierarchical KV tiering: the runner executes the
                # device legs against the scheduler's (in-proc) tier
                # manager, and the manager validates disk spill files
                # against this model's wire-layout page shapes before
                # admitting a tier hit. An executor variant without a
                # reachable flat runner cannot run the directives —
                # drop the tier (byte-identical untiered behavior).
                runner = getattr(getattr(self.executor, "worker", None),
                                 "model_runner", None)
                if runner is not None and hasattr(runner, "kv_caches"):
                    from vllm_distributed_tpu.distributed.kv_transfer \
                        import page_io
                    runner.kv_tier = self.scheduler.kv_tier
                    self.scheduler.kv_tier.wire_shapes = \
                        page_io.wire_page_shapes(runner)
                else:
                    logger.info("KV tiering: no flat runner reachable; "
                                "running untiered")
                    self.scheduler.kv_tier = None
                    self.scheduler.kv_cache_manager.tier = None
                    for pool in self.scheduler._block_pools():
                        pool.on_evict = None
        finally:
            restore()
        # Batch queue: in-flight (scheduler_output, handle) pairs,
        # newest first. Depth = max(pp, 2): the stage count under
        # pipeline parallelism (a deeper queue only adds latency once
        # every stage has work), 2 for async scheduling (one batch
        # executing while the next is scheduled/dispatched).
        pp = config.parallel_config.pipeline_parallel_size
        self.async_scheduling = config.scheduler_config.async_scheduling
        self.batch_queue_size = (max(pp, 2)
                                 if pp > 1 or self.async_scheduling else 1)
        self.batch_queue: Optional[deque] = (
            deque(maxlen=self.batch_queue_size)
            if self.batch_queue_size > 1 else None)
        # Peak in-flight depth (tests/metrics: proves overlap happened).
        self.max_concurrent_batches = 0
        # Overlap observability: dispatches issued while another batch
        # was already in flight, and the host gap between a wait_model
        # return and the next dispatch (the time the device sits idle
        # waiting on host scheduling/input prep — the async path exists
        # to drive this toward zero).
        self.steps_dispatched = 0
        self.steps_overlapped = 0
        self.step_host_gap = Histogram(HOST_GAP_BUCKETS)
        self._last_wait_done: Optional[float] = None
        # Step-phase profiler: where each engine iteration's wall time
        # goes (schedule / dispatch / device wait / update); rendered as
        # vdt:step_phase_seconds{phase=...} next to the host-gap
        # histogram. Always on — a perf_counter pair and one bisect per
        # phase per step.
        self.step_phases = {p: Histogram(STEP_PHASE_BUCKETS)
                            for p in STEP_PHASES}
        # Engine-level lifecycle events (batch dispatch/retire); the
        # scheduler keeps its own recorder for request transitions.
        self.events = ev.EventRecorder()
        # Opt-in TPU timeline annotation: wraps every dispatch in a
        # jax.profiler.StepTraceAnnotation so a trace captured via the
        # profile RPC shows per-step boundaries (trace dump dir:
        # VDT_PROFILER_DIR). Cached — the envs registry re-reads
        # os.environ per access.
        from vllm_distributed_tpu import envs
        self._profile_steps = envs.VDT_PROFILE_STEPS
        self._step_seq = 0
        # Hardened on-demand profiler capture (the profile RPC):
        # exactly one capture at a time, auto-named trace dirs, and a
        # monotonic deadline (VDT_PROFILE_MAX_S) after which the step
        # loop force-stops an unstopped trace — a wedged xprof client
        # (perf.capture_stall drill) can never wedge serving.
        self._profile_dir: Optional[str] = None
        self._profile_deadline = 0.0
        self._profile_seq = 0
        self._profile_stalled = False
        self._profile_stop_failures = 0
        # Structured output: the grammar layer needs a token-bytes table
        # (a tokenizer load + per-token decode sweep). Prefetch it off
        # the busy loop so the FIRST structured request doesn't stall
        # every in-flight stream for the load's duration.
        self._vocab_bytes_cache: Optional[list[bytes]] = None
        self._vocab_bytes_thread = None
        if (not config.model_config.skip_tokenizer_init
                and getattr(config.model_config,
                            "structured_vocab_bytes", None) is None
                and self._tokenizer_files_present()):
            # Cost/latency tradeoff: the background load burns one
            # duplicate tokenizer load per engine even if structured
            # output never arrives, but the FIRST structured request
            # then never stalls the busy loop for the load's duration.
            # The file check skips weights-only dirs (most tests).
            import threading
            self._vocab_bytes_thread = threading.Thread(
                target=self._prefetch_vocab_bytes, daemon=True,
                name="vocab-bytes-prefetch")
            self._vocab_bytes_thread.start()

    def _initialize_kv_caches(self) -> int:
        num_pages = self.executor.determine_num_available_blocks()
        logger.info("allocating %d KV pages (%d tokens)", num_pages,
                    num_pages * self.config.cache_config.block_size)
        self.executor.initialize_kv_cache(num_pages)
        return num_pages

    # ------------------------------------------------------------------
    def add_request(self, request: EngineCoreRequest) -> None:
        if request.sampling_params.structured is not None:
            self._register_structured(request)
        self.scheduler.add_request(Request.from_engine_core_request(request))

    def _register_structured(self, request: EngineCoreRequest) -> None:
        """Compile the request's grammar in the core, beside the
        scheduler (reference: v1/structured_output/__init__.py
        StructuredOutputManager). The manager (and its token-bytes
        table) is built on the first structured request."""
        if self.scheduler.structured_manager is None:
            from vllm_distributed_tpu.structured_output.manager import \
                StructuredOutputManager
            self.scheduler.structured_manager = \
                StructuredOutputManager(self._vocab_bytes())
        self.scheduler.structured_manager.add_request(
            request.request_id, request.sampling_params.structured,
            eos_token_id=request.eos_token_id)

    def _tokenizer_files_present(self) -> bool:
        import os
        path = (self.config.model_config.tokenizer
                or self.config.model_config.model)
        if not os.path.isdir(path):
            return False  # hub refs resolve lazily; don't prefetch
        return any(
            os.path.exists(os.path.join(path, f))
            for f in ("tokenizer.json", "tokenizer.model",
                      "tokenizer_config.json"))

    def _prefetch_vocab_bytes(self) -> None:
        try:
            self._vocab_bytes_cache = self._load_vocab_bytes()
        except Exception as e:  # noqa: BLE001 - surfaced on first use
            logger.debug("vocab-bytes prefetch failed (%s); structured "
                         "requests will retry inline", e)

    def _load_vocab_bytes(self) -> list[bytes]:
        from transformers import AutoTokenizer

        from vllm_distributed_tpu.structured_output.manager import \
            vocab_bytes_from_tokenizer
        tok = AutoTokenizer.from_pretrained(
            self.config.model_config.tokenizer
            or self.config.model_config.model)
        return vocab_bytes_from_tokenizer(tok)

    def _vocab_bytes(self) -> list[bytes]:
        """token id -> utf-8 bytes for grammar mask precomputation.
        Tests inject ``model_config.structured_vocab_bytes``; otherwise
        the prefetch thread's table (or an inline load as last resort)."""
        override = getattr(self.config.model_config,
                           "structured_vocab_bytes", None)
        if override is not None:
            return override
        if self._vocab_bytes_thread is not None:
            self._vocab_bytes_thread.join(timeout=120)
            self._vocab_bytes_thread = None
        if self._vocab_bytes_cache is None:
            self._vocab_bytes_cache = self._load_vocab_bytes()
        return self._vocab_bytes_cache

    def abort_requests(self, request_ids: list[str]) -> None:
        self.scheduler.finish_requests(request_ids,
                                       RequestStatus.FINISHED_ABORTED)

    def _observe_phase(self, phase: str, start: float) -> float:
        """Record one step-phase duration; returns the new timestamp so
        call sites chain them without extra clock reads."""
        now = time.perf_counter()
        self.step_phases[phase].observe(now - start)
        return now

    def _step_annotation(self):
        """jax.profiler.StepTraceAnnotation around a dispatch when
        VDT_PROFILE_STEPS is set (TPU timeline capture); no-op
        otherwise."""
        if not self._profile_steps:
            return nullcontext()
        import jax
        self._step_seq += 1
        return jax.profiler.StepTraceAnnotation("vdt_step",
                                                step_num=self._step_seq)

    # Bounded retries for a force-stop whose stop_trace itself fails
    # (full disk mid-export): retry once per window, then declare the
    # jax profiler state unknown and release the capture lane.
    _PROFILE_STOP_RETRIES = 3

    def _maybe_expire_profile(self) -> None:
        """Force-stop a profiler capture whose stop never arrived once
        its VDT_PROFILE_MAX_S window closes (checked per step and per
        stats poll — one None check on the hot path). State clears
        only AFTER stop_trace succeeds: clearing first would disarm
        this sweep while the jax trace kept running — exactly the
        wedged state the deadline exists to prevent. A stop_trace that
        itself fails re-arms the deadline for a bounded retry."""
        if (self._profile_dir is None
                or time.monotonic() < self._profile_deadline):
            return
        trace_dir = self._profile_dir
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001 - a broken trace must
            # never take the step loop down with it.
            self._profile_stop_failures += 1
            if self._profile_stop_failures < self._PROFILE_STOP_RETRIES:
                from vllm_distributed_tpu import envs
                self._profile_deadline = (time.monotonic() +
                                          envs.VDT_PROFILE_MAX_S)
                logger.warning(
                    "force-stopping overdue profiler capture failed "
                    "(%s); retrying next window", e)
                return
            logger.warning(
                "force-stopping overdue profiler capture failed %d "
                "times (%s); releasing the capture lane with the jax "
                "profiler state unknown", self._profile_stop_failures,
                e)
        else:
            logger.warning(
                "profiler capture exceeded its window; force-stopped "
                "-> %s", trace_dir)
        self._profile_dir = None
        self._profile_stalled = False
        self._profile_stop_failures = 0

    def step(self) -> list[EngineCoreOutput]:
        """One scheduling iteration (reference: core.py:223)."""
        if self._profile_dir is not None:
            self._maybe_expire_profile()
        if self.batch_queue is not None:
            return self.step_with_batch_queue()
        self.last_step_scheduled = False
        if not (self.scheduler.has_requests()
                or self.scheduler.has_kv_transfer_work()):
            return []
        t = time.perf_counter()
        scheduler_output = self.scheduler.schedule()
        t = self._observe_phase("schedule", t)
        self.last_step_scheduled = \
            scheduler_output.total_num_scheduled_tokens > 0
        with self._step_annotation():
            runner_output = self.executor.execute_model(scheduler_output)
        t = self._observe_phase("wait", t)
        outputs = self.scheduler.update_from_output(scheduler_output,
                                                    runner_output)
        self._observe_phase("update", t)
        return outputs

    def step_with_batch_queue(self) -> list[EngineCoreOutput]:
        """One iteration of the batch queue (PP microbatches or the
        async depth-2 pipeline; reference: core.py:242): dispatch a
        fresh batch whenever there is room and schedulable work;
        otherwise retire the oldest. Each call does at most one of the
        two, so dispatches outnumber waits until the pipeline fills."""
        self.last_step_scheduled = False
        if (len(self.batch_queue) < self.batch_queue_size
                and self.scheduler.has_schedulable_requests()):
            t = time.perf_counter()
            scheduler_output = self.scheduler.schedule()
            t = self._observe_phase("schedule", t)
            if scheduler_output.total_num_scheduled_tokens > 0:
                self.scheduler.mark_in_flight(
                    scheduler_output.num_scheduled_tokens)
                now = time.perf_counter()
                if self._last_wait_done is not None:
                    self.step_host_gap.observe(now - self._last_wait_done)
                    self._last_wait_done = None
                self.steps_dispatched += 1
                if self.batch_queue:
                    self.steps_overlapped += 1
                with self._step_annotation():
                    handle = self.executor.execute_model_async(
                        scheduler_output)
                self._observe_phase("dispatch", now)
                if self.events.enabled:
                    self.events.record("", ev.BATCH_DISPATCH, {
                        "reqs": len(
                            scheduler_output.num_scheduled_tokens),
                        "tokens":
                            scheduler_output.total_num_scheduled_tokens,
                        "depth": len(self.batch_queue) + 1,
                    })
                self.batch_queue.appendleft((scheduler_output, handle))
                self.last_step_scheduled = True
                self.max_concurrent_batches = max(
                    self.max_concurrent_batches, len(self.batch_queue))
                return []
            # An empty grant despite schedulable work (pool exhausted,
            # budget edge). The output still carries finished_req_ids
            # for worker-side row cleanup — run it through synchronously
            # rather than dropping it, then retire a batch to free
            # pages/slots for the next attempt. Safe to run while async
            # batches are in flight ONLY because a zero-token batch does
            # no device dispatch (dispatch_model's total==0 early return
            # — connector polls + row cleanup only; contract locked by
            # test_zero_token_dispatch_does_no_device_work).
            runner_output = self.executor.execute_model(scheduler_output)
            self.scheduler.update_from_output(scheduler_output,
                                              runner_output)
        if not self.batch_queue:
            if self.scheduler.has_kv_transfer_work():
                # No schedulable tokens and nothing in flight, but async
                # KV transfers still need the runner's get_finished poll
                # (PP + connector is rejected by PPModelRunner.__init__
                # today; this keeps the queue path honest when that gate
                # lifts).
                scheduler_output = self.scheduler.schedule()
                runner_output = self.executor.execute_model(
                    scheduler_output)
                return self.scheduler.update_from_output(
                    scheduler_output, runner_output)
            return []
        scheduler_output, handle = self.batch_queue.pop()
        if fault_injection.registry.active:
            # step.reconcile_stall: with delay_s it stalls the host
            # between device completion and reconciliation (the window
            # the async pipeline keeps covered); without a delay it
            # kills the core mid-pipeline so the crash-recovery ladder
            # is exercised with batches in flight.
            if fault_injection.registry.delay_of("step.reconcile_stall"):
                fault_injection.maybe_delay("step.reconcile_stall")
            else:
                fault_injection.fire_or_raise("step.reconcile_stall")
        t = time.perf_counter()
        runner_output = self.executor.wait_model(handle)
        self._last_wait_done = t = self._observe_phase("wait", t)
        if self.events.enabled:
            self.events.record("", ev.BATCH_RETIRE, {
                "reqs": len(scheduler_output.num_scheduled_tokens),
                "depth": len(self.batch_queue),
            })
        self.scheduler.unmark_in_flight(
            scheduler_output.num_scheduled_tokens)
        outputs = self.scheduler.update_from_output(scheduler_output,
                                                    runner_output)
        self._observe_phase("update", t)
        return outputs

    def has_unfinished_requests(self) -> bool:
        # A non-empty batch queue counts as work even when every live
        # request already finished: a trailing speculative batch must
        # still retire (its wait frees the pages parked on it).
        return (self.scheduler.has_unfinished_requests()
                or bool(self.batch_queue))

    def has_inflight_batches(self) -> bool:
        """Dispatched-but-unretired batches — busy loops must not pace
        (sleep) while a wait is pending, or the retire lags the device
        by the sleep quantum."""
        return bool(self.batch_queue)

    def has_kv_transfer_work(self) -> bool:
        """Async KV transfers needing step-polls even with no live
        requests (a producer's deferred frees)."""
        return self.scheduler.has_kv_transfer_work()

    def get_stats(self, include_events: bool = True) -> dict:
        if self._profile_dir is not None:
            # A wedged capture on an IDLE engine (no steps running the
            # sweep) still expires on the next stats poll / scrape.
            self._maybe_expire_profile()
        stats = self.scheduler.get_stats()
        stats.update(self.executor.get_stats())
        stats["inflight_batches"] = (len(self.batch_queue)
                                     if self.batch_queue is not None else 0)
        stats["max_concurrent_batches"] = self.max_concurrent_batches
        stats["steps_dispatched"] = self.steps_dispatched
        stats["steps_overlapped"] = self.steps_overlapped
        stats["decode_overlap_frac"] = (
            self.steps_overlapped / max(self.steps_dispatched, 1))
        stats["step_host_gap_seconds"] = self.step_host_gap.to_dict()
        # Step-phase profiler family. The runner times prepare_inputs
        # itself (it happens under dispatch); fold it into the family so
        # /metrics renders one labeled histogram.
        phases = {name: h.to_dict()
                  for name, h in self.step_phases.items()}
        prep = stats.pop("prepare_inputs_seconds", None)
        if isinstance(prep, dict):
            phases["prepare_inputs"] = prep
        stats["step_phase_seconds"] = phases
        # Transport telemetry: per-connector KV-transfer bytes/latency/
        # inflight and shm-ring wait/lag, recorded by everything built
        # inside this core's construction window. Multi-host follower
        # snapshots (the shm ring's read side lives in those
        # processes) arrive from the executor and merge per label —
        # the standard DP-merge shape, one level earlier.
        snap = self.transport.snapshot()
        followers = stats.pop("follower_transport", None)
        if followers:
            from vllm_distributed_tpu.metrics import telemetry
            merged = telemetry.merge_transport_snapshots(
                [snap] + list(followers))
            if merged is not None:
                snap = merged
        stats["transport"] = snap
        # Lifecycle timeline: drained per stats poll, shipped over the
        # stats RPC (DP-merged by the front-end client). The drain is
        # DESTRUCTIVE — callers that may abandon the response mid-RPC
        # (the admission gate's hard-timeout poll) pass
        # include_events=False so a cancelled poll can't discard a
        # batch of events.
        if include_events:
            stats["timeline_events"] = ev.merge_event_lists(
                self.scheduler.events.drain(), self.events.drain())
        stats["timeline_events_dropped"] = (
            self.scheduler.events.num_dropped + self.events.num_dropped)
        # Cross-process clock alignment (trace plane): this process's
        # monotonic reading at snapshot time. The front-end aggregator
        # pairs it with its own clock to estimate a per-replica offset
        # and re-base drained events into the front-end's epoch.
        stats["clock_mono"] = time.monotonic()
        # Process-local counter snapshots, pid-tagged so the front-end
        # merge can dedup in-process cores (which share the front-end's
        # registries) and sum only true follower processes — the fix
        # for the fleet-inexact vdt:fault_injections_total /
        # vdt:qcomm_* noted since PR 9.
        from vllm_distributed_tpu.parallel import collectives
        pid = os.getpid()
        counts = fault_injection.counters()
        if counts:
            stats["fault_injection_counts"] = {"pid": pid,
                                               "counts": counts}
        traced = collectives.traced_snapshot()
        if traced["bytes_saved"] or traced["fallbacks"]:
            stats["qcomm_traced"] = {"pid": pid, **traced}
        return stats

    def get_debug_state(self) -> dict:
        """Live engine-core introspection (the /debug endpoints and the
        SIGUSR1 dump): scheduler state plus the batch pipeline's
        occupancy. Read-only."""
        return {
            "scheduler": self.scheduler.get_debug_state(),
            "batch_queue_depth": (len(self.batch_queue)
                                  if self.batch_queue is not None else 0),
            "batch_queue_size": self.batch_queue_size,
            "async_scheduling": self.async_scheduling,
            "steps_dispatched": self.steps_dispatched,
            "max_concurrent_batches": self.max_concurrent_batches,
        }

    def save_sharded_state(self, path: str) -> None:
        """Persist the (sharded, post-quantization) weights for fast
        reload via load_format='sharded_state' (reference:
        EngineCore.save_sharded_state, core.py:336)."""
        self.executor.worker.model_runner.save_sharded_state(path)

    def sleep(self, level: int = 1) -> int:
        """Release device memory while idle (RLHF colocation;
        reference: EngineCore.sleep -> CuMemAllocator discard/offload,
        core.py:312-319 + cumem.py:106). Requires an idle engine —
        in-flight KV would be lost."""
        if self.scheduler.has_requests() or self.batch_queue:
            raise ValueError("cannot sleep with in-flight requests")
        if self.config.parallel_config.pipeline_parallel_size > 1:
            raise ValueError("sleep/wake under pipeline parallelism "
                             "needs per-stage restore; not wired yet")
        freed = self.executor.worker.model_runner.sleep(level)
        # The device pages are gone: cached prefix blocks must stop
        # advertising their contents or post-wake requests would "hit"
        # zeroed pages (reference: sleep implies reset_prefix_cache).
        if not self.scheduler.kv_cache_manager.reset_prefix_cache():
            logger.warning("prefix cache reset failed during sleep")
        if self.scheduler.state_cache is not None:
            # Same rule for SSM snapshots: the pool's HBM was released,
            # so the index must forget every slot.
            self.scheduler.state_cache.reset()
        return freed

    def wake_up(self) -> None:
        self.executor.worker.model_runner.wake_up()

    def profile(self, action: str = "start") -> str:
        """Start/stop a device trace (reference: EngineCore.profile RPC,
        core.py:297; TPU variant tpu_worker.py:246-256 — here
        jax.profiler, viewable in TensorBoard/XProf).

        Hardened for transient-tunnel use: each capture gets its own
        auto-named directory under VDT_PROFILER_DIR (captures never
        overwrite each other), a second concurrent start is rejected,
        and every capture carries a VDT_PROFILE_MAX_S deadline the step
        loop enforces — so one RPC pair always yields a self-contained
        xplane dump even if the client (or the tunnel) dies before the
        stop lands. Fault point ``perf.capture_stall`` simulates that
        wedged client: the stop RPC fails and the deadline is what ends
        the capture, counted in vdt:fault_injections_total."""
        import os

        import jax

        from vllm_distributed_tpu import envs
        if action == "start":
            if self._profile_dir is not None:
                raise ValueError(
                    f"profiler capture already active "
                    f"({self._profile_dir}); stop it first")
            self._profile_seq += 1
            trace_dir = os.path.join(
                envs.VDT_PROFILER_DIR,
                f"trace-{os.getpid()}-{self._profile_seq:03d}")
            jax.profiler.start_trace(trace_dir)
            self._profile_dir = trace_dir
            self._profile_deadline = (time.monotonic() +
                                      envs.VDT_PROFILE_MAX_S)
            self._profile_stalled = (
                fault_injection.registry.active
                and fault_injection.registry.should_fire(
                    "perf.capture_stall"))
            logger.info("profiling started -> %s (window %.0fs)",
                        trace_dir, envs.VDT_PROFILE_MAX_S)
            return trace_dir
        if self._profile_dir is None:
            raise ValueError("no profiler capture active")
        if self._profile_stalled:
            # Drill: the xprof session is wedged — the stop is lost and
            # only the capture-window deadline ends the trace.
            raise RuntimeError(
                "profiler capture is wedged (perf.capture_stall); the "
                "capture-window deadline will force-stop it")
        trace_dir = self._profile_dir
        # Stop FIRST, clear after: if stop_trace raises (full disk
        # mid-xplane-export), the capture stays armed so the deadline
        # sweep keeps owning the cleanup instead of orphaning a live
        # jax trace with the sweep disarmed.
        jax.profiler.stop_trace()
        self._profile_dir = None
        self._profile_stop_failures = 0
        logger.info("profiling stopped -> %s", trace_dir)
        return trace_dir

    def shutdown(self) -> None:
        self.scheduler.shutdown()
        self.executor.shutdown()
