"""Async engine for online serving.

Reference: vllm/v1/engine/async_llm.py:46 (``AsyncLLM``: generate :277
returning an async generator fed by per-request output queues, background
output handler :361, errored/dead_error :621). TPU-native differences:
the engine core runs either on a daemon thread (single process) or in an
EngineCoreProc subprocess (ZMQ); a pump thread marshals output batches
into the asyncio loop with call_soon_threadsafe — the GIL-friendly
equivalent of the reference's asyncio socket handler.
"""

import asyncio
import os
import threading
import time
from typing import AsyncGenerator, Optional, Union

from vllm_distributed_tpu.config import EngineConfig
from vllm_distributed_tpu.engine.core_client import (EngineDeadError,
                                                     RestartSupervisor,
                                                     SyncMPClient)
from vllm_distributed_tpu.engine.core_proc import BackgroundEngineCore
from vllm_distributed_tpu.engine.llm_engine import _load_tokenizer
from vllm_distributed_tpu.engine.output_processor import OutputProcessor
from vllm_distributed_tpu.engine.processor import Processor
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.metrics import events as ev
from vllm_distributed_tpu.outputs import RequestOutput
from vllm_distributed_tpu.sampling_params import SamplingParams

logger = init_logger(__name__)

# Sentinel delivered to a generate() consumer whose request was aborted
# out-of-band (AsyncLLM.abort): ends the stream without an error.
_ABORTED = object()


class AsyncLLM:

    def __init__(self, config: EngineConfig, tokenizer=None, *,
                 load_tokenizer: bool = True) -> None:
        self.config = config
        config.model_config.maybe_load_hf_config()
        if tokenizer is None and load_tokenizer:
            tokenizer = _load_tokenizer(config)
        self.tokenizer = tokenizer
        self.processor = Processor(config, tokenizer)
        self.output_processor = OutputProcessor(config, tokenizer)

        from vllm_distributed_tpu import envs
        pc = config.parallel_config
        if pc.data_parallel_size > 1 and pc.data_parallel_mode == "engine":
            # DP replicas under the async server always run as
            # subprocesses: the pump thread needs a non-blocking poll
            # surface and the replicas must overlap compute.
            from vllm_distributed_tpu.engine.dp_client import DPEngineClient
            self.core = DPEngineClient(config, force_mp=True)
        elif pc.multiprocess_engine_core or envs.VDT_ENABLE_MP_ENGINE:
            self.core = SyncMPClient(config)
        else:
            self.core = BackgroundEngineCore(config)

        self.request_queues: dict[str, asyncio.Queue] = {}
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._pump: Optional[threading.Thread] = None
        self._stopped = False
        # Event form of _stopped so recovery backoff sleeps wake
        # immediately on shutdown (a plain sleep would let the pump
        # respawn a core AFTER shutdown already tore the old one down,
        # leaking the fresh subprocess).
        self._stop_event = threading.Event()
        self._dead_error: Optional[Exception] = None

        # Crash-recovery state: the journal holds every unfinished
        # request's original EngineCoreRequest (tokens generated so far
        # live in output_processor.request_states); after a supervisor
        # restart each entry is resubmitted as a continuation prefill.
        # The core lock serializes submissions against restart+replay so
        # a request can never vanish into a dead incarnation unjournaled.
        self._journal: dict[str, "EngineCoreRequest"] = {}
        self._journal_lock = threading.Lock()
        self._core_lock = threading.Lock()
        self._supervisor = RestartSupervisor.from_config(config)

    @classmethod
    def from_engine_args(cls, engine_args) -> "AsyncLLM":
        return cls(engine_args.create_engine_config())

    # ------------------------------------------------------------------
    @property
    def engine_core(self):
        """The underlying engine client, under the attribute name the
        API server's introspection paths probe (health suspicion, the
        fleet goodput feed, /debug/correctness). The sync LLM engine
        exposes the same name directly."""
        return self.core

    @property
    def errored(self) -> bool:
        return self._dead_error is not None

    @property
    def dead_error(self) -> Exception:
        return self._dead_error or EngineDeadError("engine is dead")

    def _ensure_pump(self) -> None:
        loop = asyncio.get_running_loop()
        if self._loop is None or (self._loop is not loop
                                  and self._loop.is_closed()):
            # A recovered engine outlives asyncio.run() loops (the pump
            # thread survives core restarts): re-bind to the caller's
            # live loop once the old one is gone.
            self._loop = loop
        if self._pump is not None:
            return
        self._pump = threading.Thread(target=self._pump_outputs,
                                      daemon=True, name="output-pump")
        self._pump.start()

    def _post(self, callback, *args) -> bool:
        """Schedule a callback onto the bound event loop from the pump
        thread; False when the loop is closed (a new generate() call
        will re-bind before more work arrives)."""
        try:
            self._loop.call_soon_threadsafe(callback, *args)
            return True
        except RuntimeError:
            return False

    def _pump_outputs(self) -> None:
        """Blocking-side reader: ships each output batch into the event
        loop (reference: async_llm.py:361 _run_output_handler). A core
        death enters the recovery ladder (supervisor restart + journal
        replay) before giving up and failing pending requests."""
        while not self._stopped:
            try:
                outs = self._blocking_recv(timeout_s=0.2)
            except Exception as e:  # noqa: BLE001 - engine died
                if self._stopped:
                    return
                self.output_processor.stats.num_engine_deaths += 1
                if self._try_recover(e):
                    continue
                if not self._post(self._fail_all, e):
                    # Loop gone (consumer's asyncio.run ended): apply
                    # the terminal state inline so errored/dead_error
                    # reflect reality for the next caller.
                    self._fail_all(e)
                return
            if outs:
                while not self._post(self._process_batch, outs):
                    # Bound loop closed between asyncio.run() calls:
                    # wait for a new consumer to re-bind it.
                    if self._stopped:
                        return
                    time.sleep(0.05)

    # ------------------------------------------------------------------
    # Crash recovery: restart supervisor + in-flight request replay
    # ------------------------------------------------------------------
    def _try_recover(self, err: Exception) -> bool:
        """Respawn the dead core within the supervisor's restart budget
        and replay journaled requests as continuation prefills. Returns
        False once the budget circuit-breaks (the caller then fails
        pending requests with the terminal EngineDeadError)."""
        from vllm_distributed_tpu.utils import fault_injection
        # Timeline: the death reaches every journaled request's trace.
        # (Pump-thread appends race loop-thread reads only as GIL-atomic
        # list appends; the finish path sorts a copy.)
        if self.output_processor.timeline_enabled:
            with self._journal_lock:
                journaled = list(self._journal)
            for rid in journaled:
                self.output_processor.record_event(
                    rid, ev.ENGINE_DEATH, {"error": str(err)})
        while not self._stopped:
            delay = self._supervisor.next_delay()
            if delay is None:
                if self._supervisor.max_attempts > 0:
                    logger.error(
                        "engine core restart budget exhausted (%d in "
                        "%.0fs); circuit-breaking to EngineDeadError",
                        self._supervisor.max_attempts,
                        self._supervisor.window_s)
                return False
            logger.warning("engine core died (%s); restarting in %.2fs",
                           err, delay)
            if self._stop_event.wait(delay) or self._stopped:
                return False  # shutdown won the race: do NOT respawn
            # Make sure every output batch shipped BEFORE the death has
            # been applied to the output-processor state: the replay
            # prompt below embeds "tokens generated so far", and a
            # still-queued batch would otherwise be double-generated.
            if not self._drain_loop_callbacks():
                return False  # shutdown while waiting on the barrier
            with self._core_lock:
                if self._stopped:
                    return False
                storm = fault_injection.should_fire("restart.storm")
                if storm:
                    # Storm drill: the fresh core dies again immediately,
                    # burning through the restart budget. Armed both
                    # in-process (thread cores read this registry) and
                    # via the environment (a respawned SUBPROCESS core
                    # rebuilds its registry from VDT_FAULT_INJECT at
                    # start, not from the parent's memory).
                    fault_injection.inject("engine_core.die", max_fires=1)
                    prev_env = os.environ.get("VDT_FAULT_INJECT")
                    os.environ["VDT_FAULT_INJECT"] = (
                        (prev_env + "," if prev_env else "")
                        + "engine_core.die:1.0")
                try:
                    self.core.restart()
                except Exception as e:  # noqa: BLE001 - spawn failed
                    logger.error("engine core restart failed: %s", e)
                    err = e
                    continue
                finally:
                    if storm:
                        if prev_env is None:
                            os.environ.pop("VDT_FAULT_INJECT", None)
                        else:
                            os.environ["VDT_FAULT_INJECT"] = prev_env
                self._replay_journal()
            return True
        return False

    def _drain_loop_callbacks(self) -> bool:
        """Barrier: returns True once every callback already scheduled
        onto the event loop (queued _process_batch calls) has run —
        replaying before they land would double-generate their tokens.
        A closed loop counts as drained (its queued callbacks are
        discarded, so those tokens were never delivered and MUST be
        regenerated). Only a shutdown aborts the wait (False)."""
        done = threading.Event()
        if not self._post(done.set):
            return True  # loop closed: queued callbacks never run
        while not done.wait(timeout=10):
            if self._stopped:
                return False
            if self._loop.is_closed():
                # The loop accepted the barrier callback but closed
                # before running it (asyncio.run teardown): discarded
                # callbacks can never land, so the state IS drained.
                return True
            logger.warning("event loop has not drained its callback "
                           "queue in 10s; delaying the journal replay")
        return True

    def _replay_journal(self) -> None:
        """Resubmit every unfinished journaled request to the fresh core
        as a continuation prefill: prompt = original prompt + tokens
        already delivered, remaining token budget adjusted. With greedy
        sampling the resumed stream is token-identical to an
        uninterrupted run.

        Stateful (SSM) models: the fresh core's admission consults the
        state-cache checkpoint journal (core/state_cache.py,
        VDT_SSM_CKPT_DIR), so a replayed request resumes from its last
        checksummed checkpoint and re-prefills at most
        VDT_SSM_CKPT_INTERVAL tokens instead of the whole continuation
        prompt — O(1) recovery where re-prefill used to be O(prompt)."""
        with self._journal_lock:
            pending = list(self._journal.items())
        for rid, orig in pending:
            req = self._continuation_request(rid, orig)
            try:
                self.core.add_request(req)
            except Exception as e:  # noqa: BLE001 - fail THIS request
                # (leaving it journaled-but-unsubmitted would hang its
                # consumer forever while the fresh core serves others).
                logger.error("replay of %s failed: %s", rid, e)
                with self._journal_lock:
                    self._journal.pop(rid, None)
                replay_err = EngineDeadError(
                    f"request {rid} could not be replayed after an "
                    f"engine restart: {e}")
                if not self._post(self._fail_request, rid, replay_err):
                    self._fail_request(rid, replay_err)
                continue
            self.output_processor.stats.num_requests_replayed += 1
            delivered = (len(req.prompt_token_ids)
                         - len(orig.prompt_token_ids))
            self.output_processor.record_event(
                rid, ev.JOURNAL_REPLAY, {"delivered": delivered})
            logger.info("replayed request %s (%d tokens already "
                        "delivered)", rid, delivered)

    def _continuation_request(self, rid: str, orig):
        from vllm_distributed_tpu.request import continuation_request
        state = self.output_processor.request_states.get(rid)
        generated = (list(state.output_token_ids)
                     if state is not None else [])
        return continuation_request(orig, generated)

    def _blocking_recv(self, timeout_s: float):
        if isinstance(self.core, BackgroundEngineCore):
            import queue
            try:
                item = self.core.output_queue.get(timeout=timeout_s)
            except queue.Empty:
                # Nothing arrived: make sure that is "idle", not "the
                # core thread is dead/wedged" (health monitor raises
                # EngineDeadError; the pump then fails pending
                # requests instead of blocking forever).
                self.core.check_health()
                return None
            if isinstance(item, Exception):
                raise item
            return item
        return self.core.recv_outputs(timeout_ms=int(timeout_s * 1000))

    def _process_batch(self, core_outputs) -> None:
        processed = self.output_processor.process_outputs(core_outputs)
        self._abort_in_core(processed.reqs_to_abort)
        # Journal reaping keys off the RAW core outputs plus front-end
        # finishes (stop strings): even a request whose front-end state
        # is already gone (abort races, replayed ghosts) must leave the
        # journal once the core finishes it.
        done = [o.req_id for o in core_outputs if o.finished]
        done += processed.reqs_to_abort
        if done:
            with self._journal_lock:
                for rid in done:
                    self._journal.pop(rid, None)
        for ro in processed.request_outputs:
            q = self.request_queues.get(ro.request_id)
            if q is None:
                continue
            q.put_nowait(ro)
            if ro.finished:
                self.request_queues.pop(ro.request_id, None)

    def _fail_request(self, request_id: str, err: Exception) -> None:
        """Terminal error for ONE request (replay rejection) while the
        engine itself stays healthy."""
        self.output_processor.abort_requests([request_id])
        q = self.request_queues.pop(request_id, None)
        if q is not None:
            q.put_nowait(err)

    def _fail_all(self, err: Exception) -> None:
        # Pending requests always surface a STRUCTURED EngineDeadError
        # (the OpenAI server maps it to 503 + detail), whatever the
        # core's terminal exception actually was.
        if not isinstance(err, EngineDeadError):
            err = EngineDeadError(f"{type(err).__name__}: {err}")
        self._dead_error = err
        logger.error("engine core died: %s", err)
        with self._journal_lock:
            self._journal.clear()
        for q in self.request_queues.values():
            q.put_nowait(err)
        self.request_queues.clear()

    # ------------------------------------------------------------------
    async def generate(
        self,
        prompt: Union[str, list[int]],
        sampling_params: Optional[SamplingParams] = None,
        request_id: Optional[str] = None,
        priority: int = 0,
        tenant: Optional[str] = None,
        lora_request: Optional[dict] = None,
        pooling_params: Optional[dict] = None,
        multi_modal_data: Optional[dict] = None,
    ) -> AsyncGenerator[RequestOutput, None]:
        """Async stream of accumulated RequestOutputs for one request
        (reference: async_llm.py:277)."""
        if self._dead_error is not None:
            raise self._dead_error
        self._ensure_pump()
        if request_id is None:
            from vllm_distributed_tpu.utils import random_uuid
            request_id = random_uuid()
        sampling_params = sampling_params or SamplingParams()
        core_req = self.processor.process_inputs(
            request_id, prompt, sampling_params, priority=priority,
            tenant=tenant, lora_request=lora_request,
            pooling_params=pooling_params,
            multi_modal_data=multi_modal_data)
        queue: asyncio.Queue = asyncio.Queue()
        self.request_queues[request_id] = queue
        self.output_processor.add_request(
            core_req, prompt=prompt if isinstance(prompt, str) else None)
        try:
            # Submission runs off-loop: during a supervisor restart the
            # core lock is held for the respawn's duration, and the
            # event loop must stay responsive (health checks, other
            # consumers) while this add waits its turn.
            await asyncio.get_running_loop().run_in_executor(
                None, self._submit_to_core, core_req)
            while True:
                item = await queue.get()
                if item is _ABORTED:
                    return
                if isinstance(item, Exception):
                    raise item
                yield item
                if item.finished:
                    return
        finally:
            if self.request_queues.pop(request_id, None) is not None:
                # Consumer cancelled / errored mid-stream: abort upstream.
                with self._journal_lock:
                    self._journal.pop(request_id, None)
                self.output_processor.abort_requests([request_id])
                self._abort_in_core([request_id])

    def _submit_to_core(self, core_req) -> None:
        with self._core_lock:
            self.core.add_request(core_req)
            # Journaled only once the add landed in the CURRENT core
            # incarnation (both under the core lock): a restart+replay
            # can then never race this submission into a double add.
            with self._journal_lock:
                self._journal[core_req.request_id] = core_req

    def _abort_in_core(self, request_ids: list[str]) -> None:
        """Core-side abort from the event loop. The abort must never be
        DROPPED (a request left decoding to max_tokens holds KV pages
        for its whole budget), but the loop must also never stall on the
        core lock for a restart's duration — so the lock wait happens on
        an executor thread. Ordering with a concurrent restart is safe
        either way: the journal entries are already popped, so a replay
        skips these requests, and aborting an id the fresh core never
        saw is a scheduler no-op."""
        if not request_ids:
            return

        def _do() -> None:
            with self._core_lock:
                try:
                    self.core.abort_requests(request_ids)
                except Exception:  # noqa: BLE001 - dead/racing shutdown
                    pass

        try:
            asyncio.get_running_loop().run_in_executor(None, _do)
        except RuntimeError:
            # No running loop (teardown path): do it inline.
            _do()

    async def abort(self, request_id: str) -> None:
        q = self.request_queues.pop(request_id, None)
        if q is not None:
            # Wake any generate() consumer blocked on this queue.
            q.put_nowait(_ABORTED)
        with self._journal_lock:
            self._journal.pop(request_id, None)
        self.output_processor.abort_requests([request_id])
        self._abort_in_core([request_id])

    async def encode(self, prompt,
                     request_id: Optional[str] = None,
                     pooling_params: Optional[dict] = None):
        """Embedding request: returns the terminal PoolingOutput
        (reference: AsyncLLM.encode). The processor fills the pooling
        default per model kind (last for decoders, cls for encoders)."""
        async for out in self.generate(
                prompt, SamplingParams(temperature=0.0, max_tokens=1),
                request_id=request_id,
                pooling_params=pooling_params or {}):
            if getattr(out, "finished", True):
                return out
        raise RuntimeError("encode stream ended without a result")

    async def get_stats(self, include_events: bool = True) -> dict:
        """include_events=False skips the core-side event-ring drain —
        REQUIRED for callers that may cancel the await (wait_for
        timeouts): the drain is destructive, and an abandoned response
        silently discards the drained batch."""
        stats = await self._utility("get_stats", include_events)
        # Core-side lifecycle events are drained (destructively) per
        # stats poll; retain them front-side for /debug recent-events.
        events = stats.pop("timeline_events", None)
        if events:
            self.output_processor.core_events.absorb(events)
            if self.output_processor.assembler is not None:
                self.output_processor.assembler.feed(events)
        return stats

    async def get_debug_state(self) -> dict:
        """Live engine-core introspection (scheduler queues, per-request
        progress, batch-pipeline occupancy) for the /debug endpoints
        and the SIGUSR1 dump."""
        return await self._utility("get_debug_state")

    def supervisor_state(self) -> dict:
        """Restart-supervisor snapshot for /debug/engine. Uses the
        supervisor's read-only peek(): _expire()/exhausted REBUILD the
        attempts list, and this runs on the event-loop thread while the
        death handler may be inside next_delay() — a concurrent rebuild
        could discard a just-granted attempt and weaken the circuit
        breaker."""
        sup = self._supervisor
        in_window, exhausted = sup.peek()
        return {
            "max_attempts": sup.max_attempts,
            "window_s": sup.window_s,
            "attempts_in_window": in_window,
            "exhausted": exhausted,
            "engine_deaths": self.stats_engine_deaths(),
            "journal_depth": len(self._journal),
            "errored": self.errored,
            "dead_error": (str(self._dead_error)
                           if self._dead_error is not None else None),
            "core": type(self.core).__name__,
        }

    def stats_engine_deaths(self) -> int:
        return self.output_processor.stats.num_engine_deaths

    async def profile(self, action: str = "start"):
        """Start/stop a device trace on the core (reference:
        AsyncLLM.start_profile/stop_profile RPCs). Returns the trace
        dir, or a per-replica list under multiprocess DP."""
        return await self._utility("profile", action)

    def _send_utility_locked(self, method: str, args: tuple) -> int:
        # Same discipline as _submit_to_core: the zmq input socket is
        # not thread-safe, and submissions/aborts/restarts all touch it
        # under _core_lock from other threads.
        with self._core_lock:
            return self.core.send_utility(method, *args)

    async def _utility(self, method: str, *args):
        if isinstance(self.core, BackgroundEngineCore):
            return getattr(self.core.core, method)(*args)
        # MP core: the pump thread owns the output socket; poll for the
        # stashed result. The send runs off-loop under the core lock.
        call_id = await asyncio.get_running_loop().run_in_executor(
            None, self._send_utility_locked, method, args)
        sentinel = object()
        for _ in range(500):
            value = self.core.fetch_result(call_id, sentinel)
            if value is not sentinel:
                if isinstance(value, Exception):
                    raise value
                return value
            await asyncio.sleep(0.02)
        raise TimeoutError(f"{method} RPC timed out")

    def shutdown(self) -> None:
        self._stopped = True
        self._stop_event.set()
        if self._pump is not None:
            self._pump.join(timeout=5)
        # Under the core lock: a supervisor restart already in flight
        # must finish before teardown, or the freshly respawned core
        # would outlive this shutdown with no owner.
        with self._core_lock:
            self.core.shutdown()
