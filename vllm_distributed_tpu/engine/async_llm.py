"""Async engine for online serving.

Reference: vllm/v1/engine/async_llm.py:46 (``AsyncLLM``: generate :277
returning an async generator fed by per-request output queues, background
output handler :361, errored/dead_error :621). TPU-native differences:
the engine core runs either on a daemon thread (single process) or in an
EngineCoreProc subprocess (ZMQ); a pump thread marshals output batches
into the asyncio loop with call_soon_threadsafe — the GIL-friendly
equivalent of the reference's asyncio socket handler.
"""

import asyncio
import threading
from typing import AsyncGenerator, Optional, Union

from vllm_distributed_tpu.config import EngineConfig
from vllm_distributed_tpu.engine.core_client import (EngineDeadError,
                                                     SyncMPClient)
from vllm_distributed_tpu.engine.core_proc import BackgroundEngineCore
from vllm_distributed_tpu.engine.llm_engine import _load_tokenizer
from vllm_distributed_tpu.engine.output_processor import OutputProcessor
from vllm_distributed_tpu.engine.processor import Processor
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.outputs import RequestOutput
from vllm_distributed_tpu.sampling_params import SamplingParams

logger = init_logger(__name__)

# Sentinel delivered to a generate() consumer whose request was aborted
# out-of-band (AsyncLLM.abort): ends the stream without an error.
_ABORTED = object()


class AsyncLLM:

    def __init__(self, config: EngineConfig, tokenizer=None, *,
                 load_tokenizer: bool = True) -> None:
        self.config = config
        config.model_config.maybe_load_hf_config()
        if tokenizer is None and load_tokenizer:
            tokenizer = _load_tokenizer(config)
        self.tokenizer = tokenizer
        self.processor = Processor(config, tokenizer)
        self.output_processor = OutputProcessor(config, tokenizer)

        from vllm_distributed_tpu import envs
        pc = config.parallel_config
        if pc.data_parallel_size > 1 and pc.data_parallel_mode == "engine":
            # DP replicas under the async server always run as
            # subprocesses: the pump thread needs a non-blocking poll
            # surface and the replicas must overlap compute.
            from vllm_distributed_tpu.engine.dp_client import DPEngineClient
            self.core = DPEngineClient(config, force_mp=True)
        elif pc.multiprocess_engine_core or envs.VDT_ENABLE_MP_ENGINE:
            self.core = SyncMPClient(config)
        else:
            self.core = BackgroundEngineCore(config)

        self.request_queues: dict[str, asyncio.Queue] = {}
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._pump: Optional[threading.Thread] = None
        self._stopped = False
        self._dead_error: Optional[Exception] = None

    @classmethod
    def from_engine_args(cls, engine_args) -> "AsyncLLM":
        return cls(engine_args.create_engine_config())

    # ------------------------------------------------------------------
    @property
    def errored(self) -> bool:
        return self._dead_error is not None

    @property
    def dead_error(self) -> Exception:
        return self._dead_error or EngineDeadError("engine is dead")

    def _ensure_pump(self) -> None:
        if self._pump is not None:
            return
        self._loop = asyncio.get_running_loop()
        self._pump = threading.Thread(target=self._pump_outputs,
                                      daemon=True, name="output-pump")
        self._pump.start()

    def _pump_outputs(self) -> None:
        """Blocking-side reader: ships each output batch into the event
        loop (reference: async_llm.py:361 _run_output_handler)."""
        while not self._stopped:
            try:
                outs = self._blocking_recv(timeout_s=0.2)
            except Exception as e:  # noqa: BLE001 - engine died
                self._loop.call_soon_threadsafe(self._fail_all, e)
                return
            if outs:
                self._loop.call_soon_threadsafe(self._process_batch, outs)

    def _blocking_recv(self, timeout_s: float):
        if isinstance(self.core, BackgroundEngineCore):
            import queue
            try:
                item = self.core.output_queue.get(timeout=timeout_s)
            except queue.Empty:
                # Nothing arrived: make sure that is "idle", not "the
                # core thread is dead/wedged" (health monitor raises
                # EngineDeadError; the pump then fails pending
                # requests instead of blocking forever).
                self.core.check_health()
                return None
            if isinstance(item, Exception):
                raise item
            return item
        return self.core.recv_outputs(timeout_ms=int(timeout_s * 1000))

    def _process_batch(self, core_outputs) -> None:
        processed = self.output_processor.process_outputs(core_outputs)
        if processed.reqs_to_abort:
            try:
                self.core.abort_requests(processed.reqs_to_abort)
            except Exception:  # noqa: BLE001 - core racing shutdown
                pass
        for ro in processed.request_outputs:
            q = self.request_queues.get(ro.request_id)
            if q is None:
                continue
            q.put_nowait(ro)
            if ro.finished:
                self.request_queues.pop(ro.request_id, None)

    def _fail_all(self, err: Exception) -> None:
        # Pending requests always surface a STRUCTURED EngineDeadError
        # (the OpenAI server maps it to 503 + detail), whatever the
        # core's terminal exception actually was.
        if not isinstance(err, EngineDeadError):
            err = EngineDeadError(f"{type(err).__name__}: {err}")
        self._dead_error = err
        self.output_processor.stats.num_engine_deaths += 1
        logger.error("engine core died: %s", err)
        for q in self.request_queues.values():
            q.put_nowait(err)
        self.request_queues.clear()

    # ------------------------------------------------------------------
    async def generate(
        self,
        prompt: Union[str, list[int]],
        sampling_params: Optional[SamplingParams] = None,
        request_id: Optional[str] = None,
        priority: int = 0,
        lora_request: Optional[dict] = None,
        pooling_params: Optional[dict] = None,
        multi_modal_data: Optional[dict] = None,
    ) -> AsyncGenerator[RequestOutput, None]:
        """Async stream of accumulated RequestOutputs for one request
        (reference: async_llm.py:277)."""
        if self._dead_error is not None:
            raise self._dead_error
        self._ensure_pump()
        if request_id is None:
            from vllm_distributed_tpu.utils import random_uuid
            request_id = random_uuid()
        sampling_params = sampling_params or SamplingParams()
        core_req = self.processor.process_inputs(
            request_id, prompt, sampling_params, priority=priority,
            lora_request=lora_request, pooling_params=pooling_params,
            multi_modal_data=multi_modal_data)
        queue: asyncio.Queue = asyncio.Queue()
        self.request_queues[request_id] = queue
        self.output_processor.add_request(
            core_req, prompt=prompt if isinstance(prompt, str) else None)
        self.core.add_request(core_req)
        try:
            while True:
                item = await queue.get()
                if item is _ABORTED:
                    return
                if isinstance(item, Exception):
                    raise item
                yield item
                if item.finished:
                    return
        finally:
            if self.request_queues.pop(request_id, None) is not None:
                # Consumer cancelled / errored mid-stream: abort upstream.
                self.output_processor.abort_requests([request_id])
                try:
                    self.core.abort_requests([request_id])
                except Exception:  # noqa: BLE001
                    pass

    async def abort(self, request_id: str) -> None:
        q = self.request_queues.pop(request_id, None)
        if q is not None:
            # Wake any generate() consumer blocked on this queue.
            q.put_nowait(_ABORTED)
        self.output_processor.abort_requests([request_id])
        self.core.abort_requests([request_id])

    async def encode(self, prompt,
                     request_id: Optional[str] = None,
                     pooling_params: Optional[dict] = None):
        """Embedding request: returns the terminal PoolingOutput
        (reference: AsyncLLM.encode). The processor fills the pooling
        default per model kind (last for decoders, cls for encoders)."""
        async for out in self.generate(
                prompt, SamplingParams(temperature=0.0, max_tokens=1),
                request_id=request_id,
                pooling_params=pooling_params or {}):
            if getattr(out, "finished", True):
                return out
        raise RuntimeError("encode stream ended without a result")

    async def get_stats(self) -> dict:
        return await self._utility("get_stats")

    async def profile(self, action: str = "start"):
        """Start/stop a device trace on the core (reference:
        AsyncLLM.start_profile/stop_profile RPCs). Returns the trace
        dir, or a per-replica list under multiprocess DP."""
        return await self._utility("profile", action)

    async def _utility(self, method: str, *args):
        if isinstance(self.core, BackgroundEngineCore):
            return getattr(self.core.core, method)(*args)
        # MP core: the pump thread owns the output socket; poll for the
        # stashed result.
        call_id = self.core.send_utility(method, *args)
        sentinel = object()
        for _ in range(500):
            value = self.core.fetch_result(call_id, sentinel)
            if value is not sentinel:
                if isinstance(value, Exception):
                    raise value
                return value
            await asyncio.sleep(0.02)
        raise TimeoutError(f"{method} RPC timed out")

    def shutdown(self) -> None:
        self._stopped = True
        if self._pump is not None:
            self._pump.join(timeout=5)
        self.core.shutdown()
