"""Engine-core outputs -> user-facing RequestOutputs.

Reference: vllm/v1/engine/output_processor.py (per-request state in the
client process: detokenize, stop-string detection -> abort signal back to
the core, RequestOutput assembly).
"""

from dataclasses import dataclass, field
from typing import Optional

from vllm_distributed_tpu.config import EngineConfig
from vllm_distributed_tpu.core.sched.scheduler import EngineCoreOutput
from vllm_distributed_tpu.engine.detokenizer import IncrementalDetokenizer
from vllm_distributed_tpu.metrics.stats import RequestTimes
from vllm_distributed_tpu.outputs import (CompletionOutput,
                                          PoolingOutput,
                                          RequestOutput)
from vllm_distributed_tpu.request import EngineCoreRequest
from vllm_distributed_tpu.sampling_params import SamplingParams


@dataclass
class RequestState:
    request_id: str
    prompt: Optional[str]
    prompt_token_ids: list[int]
    params: SamplingParams
    detokenizer: Optional[IncrementalDetokenizer]
    output_token_ids: list[int] = field(default_factory=list)
    logprobs: list[dict[int, float]] = field(default_factory=list)
    num_cached_tokens: int = 0
    finished: bool = False
    finish_reason: Optional[str] = None
    stop_reason: Optional[int | str] = None
    kv_transfer_params: Optional[dict] = None
    # Per-prompt-token logprob dicts (entry 0 None), delivered once by
    # the core after the prompt completes.
    prompt_logprobs: Optional[list] = None
    times: Optional["RequestTimes"] = None


@dataclass
class ProcessedOutputs:
    request_outputs: list[RequestOutput]
    # Requests the front-end decided to finish (stop string hit): the
    # caller must abort them in the scheduler.
    reqs_to_abort: list[str]


class OutputProcessor:

    def __init__(self, config: EngineConfig, tokenizer) -> None:
        self.config = config
        self.tokenizer = tokenizer
        self.request_states: dict[str, RequestState] = {}
        # Front-end latency/throughput stats (reference:
        # v1/metrics/stats.py IterationStats maintained in the output
        # path); rendered into /metrics beside the core's stats.
        from vllm_distributed_tpu.metrics.stats import FrontendStats
        self.stats = FrontendStats()
        # Per-request spans (reference: tracing.py spans emitted from
        # the output path; gated by otlp_traces_endpoint).
        from vllm_distributed_tpu.tracing import init_tracer
        self.tracer = init_tracer(
            config.observability_config.otlp_traces_endpoint)

    def add_request(self, request: EngineCoreRequest,
                    prompt: Optional[str] = None) -> None:
        params = request.sampling_params
        detok = None
        if self.tokenizer is not None and params.detokenize:
            detok = IncrementalDetokenizer(self.tokenizer, params,
                                           request.prompt_token_ids)
        import time as _time
        self.request_states[request.request_id] = RequestState(
            request_id=request.request_id,
            prompt=prompt,
            prompt_token_ids=request.prompt_token_ids,
            params=params,
            detokenizer=detok,
            times=RequestTimes(arrival=_time.monotonic()),
        )

    def abort_requests(self, request_ids: list[str]) -> None:
        for req_id in request_ids:
            self.request_states.pop(req_id, None)

    def get_num_unfinished_requests(self) -> int:
        return len(self.request_states)

    def has_unfinished_requests(self) -> bool:
        return bool(self.request_states)

    # ------------------------------------------------------------------
    def process_outputs(
            self, core_outputs: list[EngineCoreOutput]) -> ProcessedOutputs:
        request_outputs: list[RequestOutput] = []
        reqs_to_abort: list[str] = []
        for out in core_outputs:
            state = self.request_states.get(out.req_id)
            if state is None:
                continue  # aborted while output was in flight
            if out.pooled is not None:
                # Embedding request: one terminal pooled result.
                self.stats.on_finished(state.times,
                                       len(state.prompt_token_ids))
                state.finished = True
                state.finish_reason = out.finish_reason
                if self.tracer is not None:
                    self._emit_span(state)
                request_outputs.append(PoolingOutput(
                    request_id=out.req_id, embedding=out.pooled,
                    num_prompt_tokens=len(state.prompt_token_ids)))
                del self.request_states[out.req_id]
                continue
            state.output_token_ids.extend(out.new_token_ids)
            if out.new_token_ids:
                self.stats.on_tokens(state.times, len(out.new_token_ids))
            if out.logprobs:
                state.logprobs.extend(out.logprobs)
            state.num_cached_tokens = out.num_cached_tokens

            stop_str = None
            if state.detokenizer is not None:
                stop_str = state.detokenizer.update(out.new_token_ids)

            finish_reason = out.finish_reason
            stop_reason = out.stop_reason
            if stop_str is not None and finish_reason is None:
                # Front-end stop: tell the core to abort the request.
                finish_reason = "stop"
                stop_reason = stop_str
                reqs_to_abort.append(out.req_id)

            finished = finish_reason is not None
            state.finished = finished
            state.finish_reason = finish_reason
            state.stop_reason = stop_reason
            if out.kv_transfer_params is not None:
                state.kv_transfer_params = out.kv_transfer_params
            if out.prompt_logprobs is not None:
                state.prompt_logprobs = [
                    ({int(k): float(v) for k, v in d.items()}
                     if d is not None else None)
                    for d in out.prompt_logprobs
                ]
            if finished:
                self.stats.on_finished(state.times,
                                       len(state.prompt_token_ids))
                if self.tracer is not None:
                    self._emit_span(state)
                if state.detokenizer is not None:
                    # Emit any text held back waiting for more context.
                    state.detokenizer.flush()

            request_outputs.append(self._make_request_output(state))
            if finished:
                del self.request_states[out.req_id]
        return ProcessedOutputs(request_outputs, reqs_to_abort)

    def _emit_span(self, state: RequestState) -> None:
        import time as _time

        from vllm_distributed_tpu.tracing import SpanAttributes as SA
        now = _time.monotonic()
        t = state.times
        self.tracer.emit({
            SA.GEN_AI_REQUEST_ID: state.request_id,
            SA.GEN_AI_REQUEST_MAX_TOKENS: state.params.max_tokens,
            SA.GEN_AI_REQUEST_TEMPERATURE: state.params.temperature,
            SA.GEN_AI_USAGE_PROMPT_TOKENS: len(state.prompt_token_ids),
            SA.GEN_AI_USAGE_COMPLETION_TOKENS:
                len(state.output_token_ids),
            SA.GEN_AI_LATENCY_TIME_TO_FIRST_TOKEN:
                (t.first_token - t.arrival
                 if t and t.first_token is not None else None),
            SA.GEN_AI_LATENCY_E2E: (now - t.arrival) if t else None,
            SA.GEN_AI_RESPONSE_FINISH_REASON: state.finish_reason,
        })

    def _make_request_output(self, state: RequestState) -> RequestOutput:
        text = (state.detokenizer.output_text
                if state.detokenizer is not None else "")
        completion = CompletionOutput(
            index=0,
            text=text,
            token_ids=list(state.output_token_ids),
            logprobs=list(state.logprobs) if state.logprobs else None,
            cumulative_logprob=(sum(
                next(iter(lp.values())) for lp in state.logprobs)
                                if state.logprobs else None),
            finish_reason=state.finish_reason,
            stop_reason=state.stop_reason,
        )
        return RequestOutput(
            request_id=state.request_id,
            prompt=state.prompt,
            prompt_token_ids=state.prompt_token_ids,
            outputs=[completion],
            finished=state.finished,
            num_cached_tokens=state.num_cached_tokens,
            kv_transfer_params=state.kv_transfer_params,
            prompt_logprobs=state.prompt_logprobs,
        )
