"""Engine-core outputs -> user-facing RequestOutputs.

Reference: vllm/v1/engine/output_processor.py (per-request state in the
client process: detokenize, stop-string detection -> abort signal back to
the core, RequestOutput assembly).
"""

from dataclasses import dataclass, field
from typing import Optional

from vllm_distributed_tpu.config import EngineConfig
from vllm_distributed_tpu.core.sched.scheduler import EngineCoreOutput
from vllm_distributed_tpu.engine.detokenizer import IncrementalDetokenizer
from vllm_distributed_tpu.metrics import events as ev
from vllm_distributed_tpu.metrics.stats import RequestTimes
from vllm_distributed_tpu.outputs import (CompletionOutput,
                                          PoolingOutput,
                                          RequestOutput)
from vllm_distributed_tpu.request import EngineCoreRequest
from vllm_distributed_tpu.sampling_params import SamplingParams

# Completed-phase duration samples kept per phase for percentile
# reporting (bench); oldest dropped beyond this.
_MAX_PHASE_SAMPLES = 8192


@dataclass
class RequestState:
    request_id: str
    prompt: Optional[str]
    prompt_token_ids: list[int]
    params: SamplingParams
    detokenizer: Optional[IncrementalDetokenizer]
    output_token_ids: list[int] = field(default_factory=list)
    logprobs: list[dict[int, float]] = field(default_factory=list)
    num_cached_tokens: int = 0
    finished: bool = False
    finish_reason: Optional[str] = None
    stop_reason: Optional[int | str] = None
    kv_transfer_params: Optional[dict] = None
    # Per-prompt-token logprob dicts (entry 0 None), delivered once by
    # the core after the prompt completes.
    prompt_logprobs: Optional[list] = None
    times: Optional["RequestTimes"] = None
    # Bucketed QoS tenant key (qos.bucket_tenant; None when the QoS
    # plane is off) for the per-tenant goodput family.
    tenant: Optional[str] = None
    # Merged lifecycle timeline: (monotonic_ts, event, detail) — the
    # front-end's own events (arrived/first_token/replay/finished) plus
    # the core-side events riding each EngineCoreOutput. Stitched into
    # phase child spans when the request finishes.
    timeline: list[tuple] = field(default_factory=list)
    # Trace context minted at admission (None when the trace plane is
    # off): stamped onto front-end events so the assembler can resolve
    # them even after its request-id map evicts.
    trace_ctx: Optional[dict] = None


@dataclass
class ProcessedOutputs:
    request_outputs: list[RequestOutput]
    # Requests the front-end decided to finish (stop string hit): the
    # caller must abort them in the scheduler.
    reqs_to_abort: list[str]


class OutputProcessor:

    def __init__(self, config: EngineConfig, tokenizer) -> None:
        self.config = config
        self.tokenizer = tokenizer
        self.request_states: dict[str, RequestState] = {}
        # Front-end latency/throughput stats (reference:
        # v1/metrics/stats.py IterationStats maintained in the output
        # path); rendered into /metrics beside the core's stats.
        from vllm_distributed_tpu import envs
        from vllm_distributed_tpu.metrics.stats import FrontendStats
        self.stats = FrontendStats()
        # SLO goodput targets, read ONCE at construction (the envs
        # registry re-reads os.environ per access; scoring runs per
        # finished request).
        self.stats.slo_ttft_ms = envs.VDT_SLO_TTFT_MS
        self.stats.slo_tpot_ms = envs.VDT_SLO_TPOT_MS
        # Burn-rate watchdog over the goodput plane: only meaningful
        # when at least one SLO target is set (otherwise every request
        # scores good and the burn rate is identically zero).
        if self.stats.slo_enabled:
            from vllm_distributed_tpu.metrics.stats import \
                BurnRateWatchdog
            self.stats.burn = BurnRateWatchdog(
                target=envs.VDT_SLO_TARGET,
                threshold=envs.VDT_SLO_BURN_THRESHOLD)
        # Per-tenant goodput accounting (vdt:tenant_goodput_frac) rides
        # the QoS plane: bucketing shares qos.bucket_tenant with the
        # scheduler so both label spaces stay bounded and agree. Read
        # once, like the SLO targets.
        self._qos_tenants = envs.VDT_QOS
        self._tenant_tracked: set = set()
        self._max_tracked_tenants = envs.VDT_QOS_MAX_TRACKED_TENANTS
        # Per-request spans (reference: tracing.py spans emitted from
        # the output path; gated by otlp_traces_endpoint).
        from vllm_distributed_tpu.tracing import init_tracer
        self.tracer = init_tracer(
            config.observability_config.otlp_traces_endpoint)
        # Front-end lifecycle ledger (arrivals, sheds, deaths, replays)
        # for the /debug endpoints; per-request timelines live on the
        # RequestState. Cached enable flag — envs re-reads os.environ.
        self.events = ev.EventRecorder()
        self.timeline_enabled = self.events.enabled
        # Core-side events (scheduler/engine rings) absorbed from the
        # stats RPC by the engine's get_stats — retained here so the
        # /debug recent-events view spans every component. Always
        # enabled: absorption only happens when recording was on.
        self.core_events = ev.EventRecorder(enabled=True)
        # Fleet-wide causal trace assembly (VDT_TRACE_PLANE): front-end
        # events feed it directly; core/router events arrive via the
        # get_stats drain (already clock-rebased and replica-tagged by
        # the DP client when running multi-replica).
        self.assembler = None
        if ev.trace_plane_enabled():
            from vllm_distributed_tpu.trace_plane import TraceAssembler
            self.assembler = TraceAssembler()
        # Completed per-phase durations (seconds) for percentile
        # reporting; bounded FIFO per phase.
        self.phase_durations: dict[str, list[float]] = {}

    def add_request(self, request: EngineCoreRequest,
                    prompt: Optional[str] = None) -> None:
        params = request.sampling_params
        detok = None
        if self.tokenizer is not None and params.detokenize:
            detok = IncrementalDetokenizer(self.tokenizer, params,
                                           request.prompt_token_ids)
        import time as _time
        arrival = _time.monotonic()
        tenant = None
        if self._qos_tenants:
            from vllm_distributed_tpu.core.sched.qos import bucket_tenant
            tenant = bucket_tenant(request.tenant, self._tenant_tracked,
                                   self._max_tracked_tenants)
        state = RequestState(
            request_id=request.request_id,
            prompt=prompt,
            prompt_token_ids=request.prompt_token_ids,
            params=params,
            detokenizer=detok,
            times=RequestTimes(arrival=arrival),
            tenant=tenant,
            trace_ctx=request.trace_ctx,
        )
        if self.assembler is not None and request.trace_ctx is not None:
            self.assembler.note_admission(request.request_id,
                                          request.trace_ctx)
        if self.timeline_enabled:
            state.timeline.append((arrival, ev.ARRIVED, None))
            detail = {"prompt_tokens": len(request.prompt_token_ids)}
            if state.trace_ctx is not None:
                detail = ev.stamp_trace(detail, state.trace_ctx)
            self.events.record(request.request_id, ev.ARRIVED, detail,
                               ts=arrival)
            if self.assembler is not None:
                self.assembler.add_event(arrival, request.request_id,
                                         ev.ARRIVED, detail)
        self.request_states[request.request_id] = state

    def abort_requests(self, request_ids: list[str]) -> None:
        for req_id in request_ids:
            state = self.request_states.pop(req_id, None)
            if state is not None and self.timeline_enabled:
                detail = ev.stamp_trace(None, state.trace_ctx)
                self.events.record(req_id, ev.ABORTED, detail)
                if self.assembler is not None:
                    import time as _time
                    self.assembler.add_event(_time.monotonic(), req_id,
                                             ev.ABORTED, detail)

    def record_event(self, request_id: str, event: str,
                     detail: Optional[dict] = None) -> None:
        """External lifecycle events (AsyncLLM's engine-death/replay,
        the admission gate's sheds) onto the request's timeline and the
        front-end ledger."""
        if not self.timeline_enabled:
            return
        import time as _time
        ts = _time.monotonic()
        state = self.request_states.get(request_id)
        if state is not None:
            state.timeline.append((ts, event, detail))
            if state.trace_ctx is not None:
                detail = ev.stamp_trace(detail, state.trace_ctx)
        self.events.record(request_id, event, detail, ts=ts)
        if self.assembler is not None:
            self.assembler.add_event(ts, request_id, event, detail)

    def _finish_timeline(self, state: RequestState,
                         event: str = ev.FINISHED
                         ) -> Optional[list[dict]]:
        """Close a request's timeline: append the terminal event,
        compute its phase intervals, and bank per-phase durations for
        percentile reporting. Returns the phases (None when the
        timeline is disabled)."""
        if not self.timeline_enabled:
            return None
        import time as _time
        now = _time.monotonic()
        detail = {"reason": state.finish_reason}
        state.timeline.append((now, event, detail))
        if self.assembler is not None:
            self.assembler.add_event(
                now, state.request_id, event,
                ev.stamp_trace(detail, state.trace_ctx))
        # Re-base BEFORE sorting: events absorbed from a restarted core
        # carry a fresh monotonic epoch (timestamps behind the old
        # core's by its whole uptime) — sorting raw would interleave
        # the replayed lifecycle into the pre-death one and phase math
        # would go negative. Then sort a COPY and swap it in
        # (_emit_span reuses it): the AsyncLLM pump thread may append
        # ENGINE_DEATH concurrently, and an in-place sort of a
        # mutating list raises ValueError.
        state.timeline = sorted(ev.rebase_epochs(state.timeline),
                                key=lambda e: e[0])
        phases = ev.phases_from_timeline(state.timeline, now=now)
        for name, dur in ev.phase_durations(phases).items():
            bank = self.phase_durations.setdefault(name, [])
            bank.append(dur)
            if len(bank) > _MAX_PHASE_SAMPLES:
                del bank[:len(bank) - _MAX_PHASE_SAMPLES]
        return phases

    def get_num_unfinished_requests(self) -> int:
        return len(self.request_states)

    def has_unfinished_requests(self) -> bool:
        return bool(self.request_states)

    # ------------------------------------------------------------------
    def process_outputs(
            self, core_outputs: list[EngineCoreOutput]) -> ProcessedOutputs:
        request_outputs: list[RequestOutput] = []
        reqs_to_abort: list[str] = []
        for out in core_outputs:
            state = self.request_states.get(out.req_id)
            if state is None:
                continue  # aborted while output was in flight
            if out.events and self.timeline_enabled:
                # Core-side lifecycle events riding this output.
                state.timeline.extend(out.events)
            if out.pooled is not None:
                # Embedding request: one terminal pooled result.
                self.stats.on_finished(state.times,
                                       len(state.prompt_token_ids))
                state.finished = True
                state.finish_reason = out.finish_reason
                phases = self._finish_timeline(state)
                if self.tracer is not None:
                    self._emit_span(state, phases)
                request_outputs.append(PoolingOutput(
                    request_id=out.req_id, embedding=out.pooled,
                    num_prompt_tokens=len(state.prompt_token_ids)))
                del self.request_states[out.req_id]
                continue
            state.output_token_ids.extend(out.new_token_ids)
            if out.new_token_ids:
                first = state.times.first_token is None
                self.stats.on_tokens(state.times, len(out.new_token_ids))
                if first and self.timeline_enabled:
                    state.timeline.append(
                        (state.times.first_token, ev.FIRST_TOKEN, None))
            if out.logprobs:
                state.logprobs.extend(out.logprobs)
            state.num_cached_tokens = out.num_cached_tokens

            stop_str = None
            if state.detokenizer is not None:
                stop_str = state.detokenizer.update(out.new_token_ids)

            finish_reason = out.finish_reason
            stop_reason = out.stop_reason
            if stop_str is not None and finish_reason is None:
                # Front-end stop: tell the core to abort the request.
                finish_reason = "stop"
                stop_reason = stop_str
                reqs_to_abort.append(out.req_id)

            finished = finish_reason is not None
            state.finished = finished
            state.finish_reason = finish_reason
            state.stop_reason = stop_reason
            if out.kv_transfer_params is not None:
                state.kv_transfer_params = out.kv_transfer_params
            if out.prompt_logprobs is not None:
                state.prompt_logprobs = [
                    ({int(k): float(v) for k, v in d.items()}
                     if d is not None else None)
                    for d in out.prompt_logprobs
                ]
            if finished:
                self.stats.on_finished(state.times,
                                       len(state.prompt_token_ids))
                self.stats.on_slo(state.times,
                                  len(state.output_token_ids),
                                  tenant=state.tenant)
                phases = self._finish_timeline(
                    state, ev.ABORTED if finish_reason == "abort"
                    else ev.FINISHED)
                if self.tracer is not None:
                    self._emit_span(state, phases)
                if state.detokenizer is not None:
                    # Emit any text held back waiting for more context.
                    state.detokenizer.flush()

            request_outputs.append(self._make_request_output(state))
            if finished:
                del self.request_states[out.req_id]
        return ProcessedOutputs(request_outputs, reqs_to_abort)

    def _emit_span(self, state: RequestState,
                   phases: Optional[list[dict]] = None) -> None:
        """One parent span per request; the lifecycle timeline's phase
        intervals (queue, kv_pull, prefill, decode, stalls) ride as
        child spans. A replayed continuation keeps its original request
        id, so the parent span survives an engine restart with the
        journal/replay events on its timeline."""
        import time as _time

        from vllm_distributed_tpu.tracing import SpanAttributes as SA
        now = _time.monotonic()
        t = state.times
        events = None
        if state.timeline:
            # _finish_timeline already sorted the timeline in place.
            t0 = state.timeline[0][0]
            events = [[round(ts - t0, 6), event, detail]
                      for ts, event, detail in state.timeline]
        attrs = {
            SA.GEN_AI_REQUEST_ID: state.request_id,
            SA.GEN_AI_REQUEST_MAX_TOKENS: state.params.max_tokens,
            SA.GEN_AI_REQUEST_TEMPERATURE: state.params.temperature,
            SA.GEN_AI_USAGE_PROMPT_TOKENS: len(state.prompt_token_ids),
            SA.GEN_AI_USAGE_COMPLETION_TOKENS:
                len(state.output_token_ids),
            SA.GEN_AI_LATENCY_TIME_TO_FIRST_TOKEN:
                (t.first_token - t.arrival
                 if t and t.first_token is not None else None),
            SA.GEN_AI_LATENCY_E2E: (now - t.arrival) if t else None,
            SA.GEN_AI_RESPONSE_FINISH_REASON: state.finish_reason,
        }
        if state.trace_ctx is not None:
            attrs[SA.GEN_AI_TRACE_ID] = state.trace_ctx.get("trace_id")
        self.tracer.emit(attrs, phases=phases, events=events)

    def _make_request_output(self, state: RequestState) -> RequestOutput:
        text = (state.detokenizer.output_text
                if state.detokenizer is not None else "")
        completion = CompletionOutput(
            index=0,
            text=text,
            token_ids=list(state.output_token_ids),
            logprobs=list(state.logprobs) if state.logprobs else None,
            cumulative_logprob=(sum(
                next(iter(lp.values())) for lp in state.logprobs)
                                if state.logprobs else None),
            finish_reason=state.finish_reason,
            stop_reason=state.stop_reason,
        )
        return RequestOutput(
            request_id=state.request_id,
            prompt=state.prompt,
            prompt_token_ids=state.prompt_token_ids,
            outputs=[completion],
            finished=state.finished,
            num_cached_tokens=state.num_cached_tokens,
            kv_transfer_params=state.kv_transfer_params,
            prompt_logprobs=state.prompt_logprobs,
        )
