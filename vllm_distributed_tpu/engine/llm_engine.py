"""Synchronous engine facade (reference: vllm/v1/engine/llm_engine.py:41 —
add_request -> step -> RequestOutput)."""

from typing import Optional, Union

from vllm_distributed_tpu.config import EngineConfig
from vllm_distributed_tpu.engine.core_client import EngineCoreClient
from vllm_distributed_tpu.engine.output_processor import OutputProcessor
from vllm_distributed_tpu.engine.processor import Processor
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.outputs import RequestOutput
from vllm_distributed_tpu.sampling_params import SamplingParams

logger = init_logger(__name__)


def _load_tokenizer(config: EngineConfig):
    from transformers import AutoTokenizer
    try:
        try:
            # Local path / cache first: avoids hub-retry backoff offline.
            return AutoTokenizer.from_pretrained(
                config.model_config.tokenizer,
                trust_remote_code=config.model_config.trust_remote_code,
                local_files_only=True)
        except Exception:
            return AutoTokenizer.from_pretrained(
                config.model_config.tokenizer,
                trust_remote_code=config.model_config.trust_remote_code)
    except Exception as e:
        logger.warning("could not load tokenizer %s (%s); token-id I/O only",
                       config.model_config.tokenizer, e)
        return None


class LLMEngine:

    def __init__(self, config: EngineConfig,
                 tokenizer=None, *, load_tokenizer: bool = True) -> None:
        self.config = config
        config.model_config.maybe_load_hf_config()
        if config.model_config.skip_tokenizer_init:
            load_tokenizer = False
        if tokenizer is None and load_tokenizer:
            tokenizer = _load_tokenizer(config)
        self.tokenizer = tokenizer
        self.processor = Processor(config, tokenizer)
        self.output_processor = OutputProcessor(config, tokenizer)
        self.engine_core = EngineCoreClient.make_client(config)

    @classmethod
    def from_engine_args(cls, engine_args) -> "LLMEngine":
        return cls(engine_args.create_engine_config())

    # ------------------------------------------------------------------
    def add_request(
        self,
        request_id: str,
        prompt: Union[str, list[int]],
        sampling_params: Optional[SamplingParams] = None,
        priority: int = 0,
        tenant: Optional[str] = None,
        kv_transfer_params: Optional[dict] = None,
        lora_request: Optional[dict] = None,
        pooling_params: Optional[dict] = None,
        multi_modal_data: Optional[dict] = None,
    ) -> None:
        sampling_params = sampling_params or SamplingParams()
        core_req = self.processor.process_inputs(
            request_id, prompt, sampling_params, priority=priority,
            tenant=tenant, kv_transfer_params=kv_transfer_params,
            lora_request=lora_request, pooling_params=pooling_params,
            multi_modal_data=multi_modal_data)
        self.output_processor.add_request(
            core_req, prompt=prompt if isinstance(prompt, str) else None)
        self.engine_core.add_request(core_req)

    def abort_request(self, request_ids: list[str]) -> None:
        self.output_processor.abort_requests(request_ids)
        self.engine_core.abort_requests(request_ids)

    def step(self) -> list[RequestOutput]:
        core_outputs = self.engine_core.get_output()
        processed = self.output_processor.process_outputs(core_outputs)
        if processed.reqs_to_abort:
            self.engine_core.abort_requests(processed.reqs_to_abort)
        return processed.request_outputs

    def has_unfinished_requests(self) -> bool:
        return (self.engine_core.has_unfinished_requests()
                or self.output_processor.has_unfinished_requests())

    def get_stats(self) -> dict:
        stats = self.engine_core.get_stats()
        # Same retention as AsyncLLM.get_stats: the core rings drain
        # destructively per poll; keep the events reachable front-side.
        events = stats.pop("timeline_events", None)
        if events:
            self.output_processor.core_events.absorb(events)
            if self.output_processor.assembler is not None:
                self.output_processor.assembler.feed(events)
        return stats

    def sleep(self, level: int = 1) -> int:
        """Release device memory while idle (RLHF colocation; see
        EngineCore.sleep). Returns approximate bytes released."""
        return self.engine_core.call_utility("sleep", level)

    def wake_up(self) -> None:
        self.engine_core.call_utility("wake_up")

    def shutdown(self) -> None:
        self.engine_core.shutdown()
