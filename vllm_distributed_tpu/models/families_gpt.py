"""GPT-lineage families on the learned-position / parallel-block knobs.

Reference: vllm/model_executor/models/{gpt2,gpt_j,gpt_bigcode,opt,
minicpm,exaone}.py — each is the canonical decoder with structural
twists now expressed as LlamaArchConfig knobs (learned absolute
positions, fused/packed QKV checkpoint layouts, Conv1D weight storage,
MQA, MUP-style multipliers); the subclasses set the knobs and map the
checkpoint tensor names onto the canonical layout models/llama.py
stacks."""

from types import SimpleNamespace

import numpy as np

from vllm_distributed_tpu.models.common import rename_tensors as _rename
from vllm_distributed_tpu.models.llama import (LlamaArchConfig,
                                               LlamaForCausalLM)

# Shared GPT-2-style transformer.h naming (also GPTBigCode).
_GPT2_RENAMES = [
    ("transformer.h.", "model.layers."),
    ("transformer.wte.", "model.embed_tokens."),
    ("transformer.wpe.", "model.embed_positions."),
    ("transformer.ln_f.", "model.norm."),
    (".ln_1.", ".input_layernorm."),
    (".ln_2.", ".post_attention_layernorm."),
    (".attn.c_proj.", ".self_attn.o_proj."),
    (".mlp.c_fc.", ".mlp.fc1."),
    (".mlp.c_proj.", ".mlp.fc2."),
]


def _attn_get(hf, key, default):
    """Read a key from MPT's attn_config (dict or sub-config object)."""
    from vllm_distributed_tpu.models.common import subconfig_get
    return subconfig_get(getattr(hf, "attn_config", None), key, default)


class GPT2LMHeadModel(LlamaForCausalLM):
    """GPT-2: learned positions (wpe), pre-LN LayerNorm+bias blocks,
    fused Conv1D c_attn split into q/k/v, gelu_new MLP, tied LM head
    (reference: models/gpt2.py incl. its Conv1D transpose and c_attn
    split in the weight loader)."""

    @classmethod
    def arch_config_source(cls, hf):
        return SimpleNamespace(
            vocab_size=hf.vocab_size,
            hidden_size=hf.hidden_size,
            intermediate_size=(getattr(hf, "n_inner", None)
                               or 4 * hf.hidden_size),
            num_hidden_layers=hf.num_hidden_layers,
            num_attention_heads=hf.num_attention_heads,
            num_key_value_heads=hf.num_attention_heads,
            head_dim=hf.hidden_size // hf.num_attention_heads,
            rms_norm_eps=float(getattr(hf, "layer_norm_epsilon", 1e-5)),
            tie_word_embeddings=True,
        )

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        arch.pos_embedding = "learned"
        arch.max_position_embeddings = int(hf.max_position_embeddings)
        arch.norm_type = "layernorm"
        arch.norm_bias = True
        arch.mlp_gated = False
        arch.mlp_bias = True
        arch.attention_bias = True
        arch.attention_out_bias = True
        arch.hidden_act = getattr(hf, "activation_function", "gelu_new")
        arch.tie_word_embeddings = True
        if getattr(hf, "scale_attn_by_inverse_layer_idx", False):
            raise ValueError(
                "GPT-2 scale_attn_by_inverse_layer_idx checkpoints are "
                "not supported")

    # Conv1D stores [in, out]; the canonical loader transposes torch
    # Linear [out, in] — so Conv1D mats are pre-transposed here.
    _CONV1D = (".attn.c_proj.weight", ".mlp.c_fc.weight",
               ".mlp.c_proj.weight")

    def params_from_hf_state_dict(self, tensors) -> dict:
        c = self.cfg
        H = c.hidden_size
        filtered = {}
        for name, t in tensors.items():
            if name.endswith((".attn.bias", ".attn.masked_bias")):
                continue  # causal-mask buffers
            t = np.asarray(t)
            if any(name.endswith(suf) for suf in self._CONV1D):
                t = t.T
            filtered[name] = t
        out = _rename(filtered, _GPT2_RENAMES)
        for i in range(c.num_layers):
            base = f"model.layers.{i}.attn.c_attn"
            w = np.asarray(out.pop(base + ".weight"))  # Conv1D [H, 3H]
            b = np.asarray(out.pop(base + ".bias"))
            A = f"model.layers.{i}.self_attn."
            # Canonical layout is torch-Linear [out, in].
            out[A + "q_proj.weight"] = w[:, :H].T
            out[A + "k_proj.weight"] = w[:, H:2 * H].T
            out[A + "v_proj.weight"] = w[:, 2 * H:].T
            out[A + "q_proj.bias"] = b[:H]
            out[A + "k_proj.bias"] = b[H:2 * H]
            out[A + "v_proj.bias"] = b[2 * H:]
        return super().params_from_hf_state_dict(out)


class GPTJForCausalLM(LlamaForCausalLM):
    """GPT-J: parallel residual from ONE shared ln_1, interleaved
    partial rotary, unbiased separate q/k/v, biased fc_in/fc_out MLP
    and a biased LM head (reference: models/gpt_j.py)."""

    LM_HEAD_BIAS = True

    @classmethod
    def arch_config_source(cls, hf):
        return SimpleNamespace(
            vocab_size=hf.vocab_size,
            hidden_size=hf.hidden_size,
            intermediate_size=(getattr(hf, "n_inner", None)
                               or 4 * hf.hidden_size),
            num_hidden_layers=hf.num_hidden_layers,
            num_attention_heads=hf.num_attention_heads,
            num_key_value_heads=hf.num_attention_heads,
            head_dim=hf.hidden_size // hf.num_attention_heads,
            rms_norm_eps=float(getattr(hf, "layer_norm_epsilon", 1e-5)),
            tie_word_embeddings=False,
        )

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        arch.norm_type = "layernorm"
        arch.norm_bias = True
        arch.parallel_block = True
        arch.shared_block_ln = True
        arch.mlp_gated = False
        arch.mlp_bias = True
        arch.rope_interleaved = True
        arch.rotary_dim = int(getattr(hf, "rotary_dim", None)
                              or arch.head_dim)
        arch.hidden_act = getattr(hf, "activation_function", "gelu_new")

    def params_from_hf_state_dict(self, tensors) -> dict:
        # lm_head.bias flows through the base LM_HEAD_BIAS hook.
        renamed = _rename(tensors, [
            ("transformer.h.", "model.layers."),
            ("transformer.wte.", "model.embed_tokens."),
            ("transformer.ln_f.", "model.norm."),
            (".ln_1.", ".input_layernorm."),
            (".attn.out_proj.", ".self_attn.o_proj."),
            (".attn.q_proj.", ".self_attn.q_proj."),
            (".attn.k_proj.", ".self_attn.k_proj."),
            (".attn.v_proj.", ".self_attn.v_proj."),
            (".mlp.fc_in.", ".mlp.fc1."),
            (".mlp.fc_out.", ".mlp.fc2."),
        ])
        renamed = {k: v for k, v in renamed.items()
                   if not k.endswith((".attn.bias", ".attn.masked_bias"))}
        return super().params_from_hf_state_dict(renamed)


class GPTBigCodeForCausalLM(LlamaForCausalLM):
    """GPTBigCode (StarCoder 1 / SantaCoder): multi-query attention
    (one KV head), learned positions, LayerNorm+bias, packed Linear
    c_attn [H + 2*head_dim rows] (reference: models/gpt_bigcode.py)."""

    @classmethod
    def arch_config_source(cls, hf):
        mq = bool(getattr(hf, "multi_query", True))
        return SimpleNamespace(
            vocab_size=hf.vocab_size,
            hidden_size=hf.hidden_size,
            intermediate_size=(getattr(hf, "n_inner", None)
                               or 4 * hf.hidden_size),
            num_hidden_layers=hf.num_hidden_layers,
            num_attention_heads=hf.num_attention_heads,
            num_key_value_heads=1 if mq else hf.num_attention_heads,
            head_dim=hf.hidden_size // hf.num_attention_heads,
            rms_norm_eps=float(getattr(hf, "layer_norm_epsilon", 1e-5)),
            tie_word_embeddings=True,
        )

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        arch.pos_embedding = "learned"
        arch.max_position_embeddings = int(hf.max_position_embeddings)
        arch.norm_type = "layernorm"
        arch.norm_bias = True
        arch.mlp_gated = False
        arch.mlp_bias = True
        arch.attention_bias = True
        arch.attention_out_bias = True
        arch.hidden_act = getattr(hf, "activation_function",
                                  "gelu_pytorch_tanh")
        arch.tie_word_embeddings = True

    def params_from_hf_state_dict(self, tensors) -> dict:
        c = self.cfg
        H = c.hidden_size
        kv = c.num_kv_heads * c.head_dim
        out = _rename({k: np.asarray(v) for k, v in tensors.items()},
                      _GPT2_RENAMES)
        for i in range(c.num_layers):
            base = f"model.layers.{i}.attn.c_attn"
            w = np.asarray(out.pop(base + ".weight"))  # [H + 2kv, H]
            b = np.asarray(out.pop(base + ".bias"))
            A = f"model.layers.{i}.self_attn."
            out[A + "q_proj.weight"] = w[:H]
            out[A + "k_proj.weight"] = w[H:H + kv]
            out[A + "v_proj.weight"] = w[H + kv:]
            out[A + "q_proj.bias"] = b[:H]
            out[A + "k_proj.bias"] = b[H:H + kv]
            out[A + "v_proj.bias"] = b[H + kv:]
        return super().params_from_hf_state_dict(out)


class OPTForCausalLM(LlamaForCausalLM):
    """OPT: learned positions written from offset 2, ReLU MLP,
    LayerNorm+bias, biased projections, tied embeddings (reference:
    models/opt.py incl. OPTLearnedPositionalEmbedding's offset)."""

    @classmethod
    def arch_config_source(cls, hf):
        return SimpleNamespace(
            vocab_size=hf.vocab_size,
            hidden_size=hf.hidden_size,
            intermediate_size=hf.ffn_dim,
            num_hidden_layers=hf.num_hidden_layers,
            num_attention_heads=hf.num_attention_heads,
            num_key_value_heads=hf.num_attention_heads,
            head_dim=hf.hidden_size // hf.num_attention_heads,
            rms_norm_eps=1e-5,
            tie_word_embeddings=bool(
                getattr(hf, "tie_word_embeddings", True)),
        )

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        if getattr(hf, "word_embed_proj_dim",
                   hf.hidden_size) != hf.hidden_size:
            raise ValueError(
                "OPT word_embed_proj_dim != hidden_size (opt-350m "
                "projection layout) is not supported")
        if not getattr(hf, "do_layer_norm_before", True):
            raise ValueError(
                "OPT post-norm (do_layer_norm_before=False) "
                "checkpoints are not supported")
        arch.pos_embedding = "learned"
        # The HF table physically holds offset + max positions.
        arch.pos_offset = 2
        arch.max_position_embeddings = int(
            hf.max_position_embeddings) + 2
        arch.norm_type = "layernorm"
        arch.norm_bias = True
        arch.mlp_gated = False
        arch.mlp_bias = True
        arch.attention_bias = True
        arch.attention_out_bias = True
        arch.hidden_act = getattr(hf, "activation_function", "relu")

    def params_from_hf_state_dict(self, tensors) -> dict:
        renamed = _rename(tensors, [
            ("model.decoder.layers.", "model.layers."),
            ("model.decoder.embed_tokens.", "model.embed_tokens."),
            ("model.decoder.embed_positions.",
             "model.embed_positions."),
            ("model.decoder.final_layer_norm.", "model.norm."),
            (".self_attn.out_proj.", ".self_attn.o_proj."),
            (".self_attn_layer_norm.", ".input_layernorm."),
            (".final_layer_norm.", ".post_attention_layernorm."),
            (".fc1.", ".mlp.fc1."),
            (".fc2.", ".mlp.fc2."),
        ])
        return super().params_from_hf_state_dict(renamed)


class BloomForCausalLM(LlamaForCausalLM):
    """Bloom: ALiBi (no position embeddings), post-embedding LayerNorm,
    per-head-interleaved fused QKV, gelu-tanh MLP, tied embeddings
    (reference: models/bloom.py incl. its _get_alibi_slopes and the
    query_key_value de-interleave)."""

    @classmethod
    def arch_config_source(cls, hf):
        return SimpleNamespace(
            vocab_size=hf.vocab_size,
            hidden_size=hf.hidden_size,
            intermediate_size=4 * hf.hidden_size,
            num_hidden_layers=hf.num_hidden_layers,
            num_attention_heads=hf.num_attention_heads,
            num_key_value_heads=hf.num_attention_heads,
            head_dim=hf.hidden_size // hf.num_attention_heads,
            rms_norm_eps=float(getattr(hf, "layer_norm_epsilon", 1e-5)),
            tie_word_embeddings=True,
        )

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        arch.alibi = True
        arch.pos_embedding = "none"
        arch.embed_ln = True
        arch.norm_type = "layernorm"
        arch.norm_bias = True
        arch.mlp_gated = False
        arch.mlp_bias = True
        arch.attention_bias = True
        arch.attention_out_bias = True
        arch.hidden_act = "gelu_tanh"  # BloomGelu = tanh approximation
        arch.tie_word_embeddings = True

    def params_from_hf_state_dict(self, tensors) -> dict:
        c = self.cfg
        N, D, H = c.num_q_heads, c.head_dim, c.hidden_size
        prefixed = {
            (k if k.startswith("transformer.") else "transformer." + k):
            np.asarray(v)  # some dumps drop the prefix
            for k, v in tensors.items()
        }
        out = _rename(prefixed, [
            ("transformer.h.", "model.layers."),
            ("transformer.word_embeddings_layernorm.",
             "model.embed_layernorm."),
            ("transformer.word_embeddings.", "model.embed_tokens."),
            ("transformer.ln_f.", "model.norm."),
            (".self_attention.dense.", ".self_attn.o_proj."),
            (".mlp.dense_h_to_4h.", ".mlp.fc1."),
            (".mlp.dense_4h_to_h.", ".mlp.fc2."),
        ])
        for i in range(c.num_layers):
            base = f"model.layers.{i}.self_attention.query_key_value"
            # Rows pack [h0_q, h0_k, h0_v, h1_q, ...] like GPT-NeoX.
            w = out.pop(base + ".weight").reshape(N, 3, D, H)
            b = out.pop(base + ".bias").reshape(N, 3, D)
            A = f"model.layers.{i}.self_attn."
            out[A + "q_proj.weight"] = w[:, 0].reshape(N * D, H)
            out[A + "k_proj.weight"] = w[:, 1].reshape(N * D, H)
            out[A + "v_proj.weight"] = w[:, 2].reshape(N * D, H)
            out[A + "q_proj.bias"] = b[:, 0].reshape(N * D)
            out[A + "k_proj.bias"] = b[:, 1].reshape(N * D)
            out[A + "v_proj.bias"] = b[:, 2].reshape(N * D)
        return super().params_from_hf_state_dict(out)


class MPTForCausalLM(LlamaForCausalLM):
    """MPT: ALiBi, fused straight-concat Wqkv, optional qkv clipping,
    bias-free norms/linears under no_bias, non-gated gelu FFN
    (reference: models/mpt.py)."""

    @classmethod
    def arch_config_source(cls, hf):
        heads = hf.n_heads
        return SimpleNamespace(
            vocab_size=hf.vocab_size,
            hidden_size=hf.d_model,
            intermediate_size=int(
                getattr(hf, "expansion_ratio", 4) * hf.d_model),
            num_hidden_layers=hf.n_layers,
            num_attention_heads=heads,
            num_key_value_heads=int(_attn_get(hf, "kv_n_heads", heads)),
            head_dim=hf.d_model // heads,
            rms_norm_eps=1e-5,
            tie_word_embeddings=True,
        )

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        if not _attn_get(hf, "alibi", True):
            raise ValueError(
                "MPT checkpoints without ALiBi (learned-position "
                "variants) are not supported")
        if _attn_get(hf, "qk_ln", False):
            raise ValueError("MPT qk_ln checkpoints are not supported")
        arch.alibi = True
        arch.pos_embedding = "none"
        arch.norm_type = "layernorm"
        no_bias = bool(getattr(hf, "no_bias", True))
        arch.norm_bias = not no_bias
        arch.mlp_gated = False
        arch.mlp_bias = not no_bias
        arch.attention_bias = not no_bias
        arch.attention_out_bias = not no_bias
        clip = _attn_get(hf, "clip_qkv", None)
        arch.qkv_clip = float(clip) if clip else None
        arch.hidden_act = "gelu"
        arch.tie_word_embeddings = True

    def params_from_hf_state_dict(self, tensors) -> dict:
        c = self.cfg
        H = c.hidden_size
        kv = c.num_kv_heads * c.head_dim
        out = {}
        for name, t in tensors.items():
            name = name.replace("transformer.blocks.", "model.layers.")
            name = name.replace("transformer.wte.", "model.embed_tokens.")
            name = name.replace("transformer.norm_f.", "model.norm.")
            name = name.replace(".norm_1.", ".input_layernorm.")
            name = name.replace(".norm_2.", ".post_attention_layernorm.")
            name = name.replace(".attn.out_proj.", ".self_attn.o_proj.")
            name = name.replace(".ffn.up_proj.", ".mlp.fc1.")
            name = name.replace(".ffn.down_proj.", ".mlp.fc2.")
            out[name] = np.asarray(t)
        for i in range(c.num_layers):
            base = f"model.layers.{i}.attn.Wqkv"
            w = out.pop(base + ".weight")  # [H + 2kv, H] straight concat
            A = f"model.layers.{i}.self_attn."
            out[A + "q_proj.weight"] = w[:H]
            out[A + "k_proj.weight"] = w[H:H + kv]
            out[A + "v_proj.weight"] = w[H + kv:]
            if base + ".bias" in out:
                b = out.pop(base + ".bias")
                out[A + "q_proj.bias"] = b[:H]
                out[A + "k_proj.bias"] = b[H:H + kv]
                out[A + "v_proj.bias"] = b[H + kv:]
        return super().params_from_hf_state_dict(out)


class MiniCPMForCausalLM(LlamaForCausalLM):
    """MiniCPM 1/2: Llama weights + MUP-style multipliers (scale_emb,
    depth-scaled residuals, logits over dim_model_base; reference:
    models/minicpm.py)."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        if getattr(hf, "num_experts", 0):
            raise ValueError("MiniCPM-MoE checkpoints are not supported")
        import math
        arch.embed_scale = float(getattr(hf, "scale_emb", 1.0))
        depth = float(getattr(hf, "scale_depth", 1.0))
        arch.residual_multiplier = depth / math.sqrt(arch.num_layers)
        base = float(getattr(hf, "dim_model_base", arch.hidden_size)
                     or arch.hidden_size)
        arch.logit_multiplier = base / arch.hidden_size


class Ernie45ForCausalLM(LlamaForCausalLM):
    """Baidu ERNIE 4.5 dense: Llama math; use_bias puts biases on
    EVERY projection — qkv, output, and the gated MLP (reference:
    models/ernie45.py)."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        bias = bool(getattr(hf, "use_bias", False))
        arch.attention_bias = bias
        arch.attention_out_bias = bias
        arch.mlp_bias = bias


class SeedOssForCausalLM(LlamaForCausalLM):
    """ByteDance Seed-OSS: Llama math; qkv / output / MLP biases each
    follow their own config flag (reference: models/seed_oss.py)."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        arch.attention_bias = bool(getattr(hf, "attention_bias", True))
        arch.attention_out_bias = bool(
            getattr(hf, "attention_out_bias", False))
        arch.mlp_bias = bool(getattr(hf, "mlp_bias", False))


class ArceeForCausalLM(LlamaForCausalLM):
    """Arcee AFM: Llama attention over a NON-gated relu^2 MLP
    (reference: models/arcee.py)."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        arch.mlp_gated = False
        arch.hidden_act = getattr(hf, "hidden_act", "relu2")
        bias = bool(getattr(hf, "attention_bias", False))
        arch.attention_bias = bias
        arch.attention_out_bias = bias
        arch.mlp_bias = bool(getattr(hf, "mlp_bias", False))

    def params_from_hf_state_dict(self, tensors) -> dict:
        return super().params_from_hf_state_dict(_rename(tensors, [
            (".mlp.up_proj.", ".mlp.fc1."),
            (".mlp.down_proj.", ".mlp.fc2."),
        ]))


class ExaoneForCausalLM(LlamaForCausalLM):
    """LG EXAONE 3: Llama block under transformer.h naming
    (reference: models/exaone.py)."""

    @classmethod
    def arch_config_source(cls, hf):
        return SimpleNamespace(
            vocab_size=hf.vocab_size,
            hidden_size=hf.hidden_size,
            intermediate_size=hf.intermediate_size,
            num_hidden_layers=hf.num_hidden_layers,
            num_attention_heads=hf.num_attention_heads,
            num_key_value_heads=getattr(hf, "num_key_value_heads",
                                        hf.num_attention_heads),
            head_dim=getattr(hf, "head_dim", None) or (
                hf.hidden_size // hf.num_attention_heads),
            rms_norm_eps=float(getattr(hf, "layer_norm_epsilon", 1e-5)),
            tie_word_embeddings=bool(
                getattr(hf, "tie_word_embeddings", False)),
            rope_theta=getattr(hf, "rope_theta", 10000.0),
            rope_scaling=getattr(hf, "rope_scaling", None),
        )

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        arch.hidden_act = getattr(hf, "activation_function", "silu")

    def params_from_hf_state_dict(self, tensors) -> dict:
        renamed = _rename(tensors, [
            ("transformer.h.", "model.layers."),
            ("transformer.wte.", "model.embed_tokens."),
            ("transformer.ln_f.", "model.norm."),
            (".ln_1.", ".input_layernorm."),
            (".ln_2.", ".post_attention_layernorm."),
            (".attn.attention.q_proj.", ".self_attn.q_proj."),
            (".attn.attention.k_proj.", ".self_attn.k_proj."),
            (".attn.attention.v_proj.", ".self_attn.v_proj."),
            (".attn.attention.out_proj.", ".self_attn.o_proj."),
            (".mlp.c_fc_0.", ".mlp.gate_proj."),
            (".mlp.c_fc_1.", ".mlp.up_proj."),
            (".mlp.c_proj.", ".mlp.down_proj."),
        ])
        return super().params_from_hf_state_dict(renamed)


class BioGptForCausalLM(OPTForCausalLM):
    """BioGPT (reference: the OPT-shaped decoder of models/biogpt
    support in HF): the OPT block — learned positions from offset 2,
    biased projections, LayerNorm — with gelu MLP, sqrt(H) embedding
    scaling, and ``biogpt.*`` checkpoint naming."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        import math
        arch.pos_embedding = "learned"
        arch.pos_offset = 2
        arch.max_position_embeddings = int(
            hf.max_position_embeddings) + 2
        arch.norm_type = "layernorm"
        arch.norm_bias = True
        arch.mlp_gated = False
        arch.mlp_bias = True
        arch.attention_bias = True
        arch.attention_out_bias = True
        arch.hidden_act = getattr(hf, "hidden_act", "gelu")
        arch.rms_norm_eps = float(getattr(hf, "layer_norm_eps", 1e-12))
        if bool(getattr(hf, "scale_embedding", True)):
            arch.embed_scale = math.sqrt(arch.hidden_size)

    @classmethod
    def arch_config_source(cls, hf):
        from types import SimpleNamespace
        return SimpleNamespace(
            vocab_size=hf.vocab_size,
            hidden_size=hf.hidden_size,
            intermediate_size=hf.intermediate_size,
            num_hidden_layers=hf.num_hidden_layers,
            num_attention_heads=hf.num_attention_heads,
            num_key_value_heads=hf.num_attention_heads,
            head_dim=hf.hidden_size // hf.num_attention_heads,
            rms_norm_eps=float(getattr(hf, "layer_norm_eps", 1e-12)),
            tie_word_embeddings=True,
        )

    def params_from_hf_state_dict(self, tensors) -> dict:
        renamed = _rename(tensors, [
            ("biogpt.layers.", "model.layers."),
            ("biogpt.embed_tokens.", "model.embed_tokens."),
            ("biogpt.embed_positions.", "model.embed_positions."),
            ("biogpt.layer_norm.", "model.norm."),
            ("output_projection.", "lm_head."),
            (".self_attn.out_proj.", ".self_attn.o_proj."),
            (".self_attn_layer_norm.", ".input_layernorm."),
            (".final_layer_norm.", ".post_attention_layernorm."),
            (".fc1.", ".mlp.fc1."),
            (".fc2.", ".mlp.fc2."),
        ])
        return LlamaForCausalLM.params_from_hf_state_dict(self, renamed)


class XGLMForCausalLM(OPTForCausalLM):
    """XGLM (reference: the OPT-shaped multilingual decoder): the OPT
    block with gelu MLP, sqrt(H) embedding scaling, and SINUSOIDAL
    positions — the fixed fairseq table (offset 2, half sin / half
    cos) materializes into the learned-position slot at load."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        import math
        arch.pos_embedding = "learned"
        arch.pos_offset = 2
        arch.max_position_embeddings = int(
            hf.max_position_embeddings) + 2
        arch.norm_type = "layernorm"
        arch.norm_bias = True
        arch.mlp_gated = False
        arch.mlp_bias = True
        arch.attention_bias = True
        arch.attention_out_bias = True
        arch.hidden_act = getattr(hf, "activation_function", "gelu")
        if bool(getattr(hf, "scale_embedding", True)):
            arch.embed_scale = math.sqrt(arch.hidden_size)

    @classmethod
    def arch_config_source(cls, hf):
        from types import SimpleNamespace
        return SimpleNamespace(
            vocab_size=hf.vocab_size,
            hidden_size=hf.d_model,
            intermediate_size=hf.ffn_dim,
            num_hidden_layers=hf.num_layers,
            num_attention_heads=hf.attention_heads,
            num_key_value_heads=hf.attention_heads,
            head_dim=hf.d_model // hf.attention_heads,
            rms_norm_eps=1e-5,
            tie_word_embeddings=False,
        )

    @staticmethod
    def _sinusoid_table(n_pos: int, dim: int,
                        padding_idx: int = 1) -> np.ndarray:
        """fairseq/XGLMSinusoidalPositionalEmbedding.get_embedding."""
        import math
        half = dim // 2
        freq = np.exp(np.arange(half, dtype=np.float64) *
                      -(math.log(10000.0) / (half - 1)))
        ang = np.arange(n_pos, dtype=np.float64)[:, None] * freq[None]
        emb = np.concatenate([np.sin(ang), np.cos(ang)], axis=1)
        if dim % 2:
            emb = np.concatenate([emb, np.zeros((n_pos, 1))], axis=1)
        emb[padding_idx] = 0.0
        return emb.astype(np.float32)

    def params_from_hf_state_dict(self, tensors) -> dict:
        c = self.cfg
        renamed = _rename(tensors, [
            ("model.layer_norm.", "model.norm."),
            (".self_attn.out_proj.", ".self_attn.o_proj."),
            (".self_attn_layer_norm.", ".input_layernorm."),
            (".final_layer_norm.", ".post_attention_layernorm."),
            (".fc1.", ".mlp.fc1."),
            (".fc2.", ".mlp.fc2."),
        ])
        renamed["model.embed_positions.weight"] = self._sinusoid_table(
            c.max_position_embeddings, c.hidden_size)
        return LlamaForCausalLM.params_from_hf_state_dict(self, renamed)
