"""Extended model families on the generic block knobs.

Reference: vllm/model_executor/models/{gpt_neox,phi,stablelm,
starcoder2,commandr,olmo2,granite,qwen3_moe,nemotron}.py — each family
is the Llama decoder with a structural twist now expressed as
LlamaArchConfig knobs (norm flavor, partial rotary, parallel residual,
non-gated MLP, multipliers); these subclasses set the knobs and map the
checkpoint tensor names onto the canonical layout."""

import numpy as np
from jax.sharding import PartitionSpec as P

from vllm_distributed_tpu.models.llama import (MODEL_AXIS,
                                               LlamaArchConfig,
                                               LlamaForCausalLM)
from vllm_distributed_tpu.models.mixtral import MixtralForCausalLM


def _alias_moe_experts(tensors: dict, num_layers: int,
                       num_experts: int) -> dict:
    """Map the HF mlp.experts.{e}.{gate,up,down}_proj / mlp.gate naming
    (Qwen3-MoE, OLMoE) onto the Mixtral layout the base loader stacks."""
    alias = dict(tensors)
    for i in range(num_layers):
        for e in range(num_experts):
            for src, dst in (("gate_proj", "w1"), ("down_proj", "w2"),
                             ("up_proj", "w3")):
                alias[f"model.layers.{i}.block_sparse_moe.experts."
                      f"{e}.{dst}.weight"] = tensors[
                          f"model.layers.{i}.mlp.experts.{e}."
                          f"{src}.weight"]
        alias[f"model.layers.{i}.block_sparse_moe.gate.weight"] = \
            tensors[f"model.layers.{i}.mlp.gate.weight"]
    return alias


from vllm_distributed_tpu.models.common import rename_tensors as _rename


class GraniteForCausalLM(LlamaForCausalLM):
    """IBM Granite: Llama weights + the four scale multipliers
    (reference: models/granite.py)."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        arch.embed_scale = float(getattr(hf, "embedding_multiplier", 1.0))
        arch.residual_multiplier = float(
            getattr(hf, "residual_multiplier", 1.0))
        arch.sm_scale_override = float(
            getattr(hf, "attention_multiplier", None)
            or arch.head_dim ** -0.5)
        ls = float(getattr(hf, "logits_scaling", 1.0) or 1.0)
        arch.logit_multiplier = 1.0 / ls
        arch.attention_bias = bool(getattr(hf, "attention_bias", False))


class Qwen3MoeForCausalLM(MixtralForCausalLM):
    """Qwen3-MoE: Mixtral-style routed experts (normalized top-k) +
    Qwen3 per-head qk norm, no shared expert (reference:
    models/qwen3_moe.py)."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        arch.num_experts = hf.num_experts
        arch.num_experts_per_tok = hf.num_experts_per_tok
        arch.norm_topk_prob = bool(getattr(hf, "norm_topk_prob", True))
        arch.moe_intermediate_size = hf.moe_intermediate_size
        arch.qk_norm = True
        if getattr(hf, "mlp_only_layers", None) or \
                getattr(hf, "decoder_sparse_step", 1) != 1:
            raise ValueError(
                "Qwen3-MoE layouts mixing dense and sparse layers are "
                "not supported; every layer must be sparse")

    def params_from_hf_state_dict(self, tensors) -> dict:
        return super().params_from_hf_state_dict(_alias_moe_experts(
            tensors, self.cfg.num_layers, self.cfg.num_experts))


class GraniteMoeForCausalLM(MixtralForCausalLM):
    """IBM Granite-MoE: Mixtral-style routed experts stored as FUSED
    per-expert tensors (input_linear packs [gate; up] rows) + the four
    Granite multipliers (reference: models/granitemoe.py). Its top-k-
    then-softmax gating equals Mixtral's softmax-then-renormalize, so
    norm_topk_prob=True reproduces it exactly."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        # The four multipliers + attention bias are exactly Granite's.
        GraniteForCausalLM.configure_arch(arch, hf)
        arch.num_experts = hf.num_local_experts
        arch.num_experts_per_tok = hf.num_experts_per_tok
        arch.norm_topk_prob = True
        arch.moe_intermediate_size = hf.intermediate_size

    def params_from_hf_state_dict(self, tensors) -> dict:
        c = self.cfg
        I = c.moe_intermediate_size
        alias = dict(tensors)
        for i in range(c.num_layers):
            pre = f"model.layers.{i}.block_sparse_moe."
            fused = np.asarray(alias.pop(pre + "input_linear.weight"))
            out_w = np.asarray(alias.pop(pre + "output_linear.weight"))
            alias[pre + "gate.weight"] = np.asarray(
                alias.pop(pre + "router.layer.weight"))
            for e in range(c.num_experts):
                # [2I, H] per expert: first I rows gate, rest up
                # (HF chunk(2, dim=-1) of the fused projection).
                alias[pre + f"experts.{e}.w1.weight"] = fused[e, :I]
                alias[pre + f"experts.{e}.w3.weight"] = fused[e, I:]
                alias[pre + f"experts.{e}.w2.weight"] = out_w[e]
        return super().params_from_hf_state_dict(alias)


class DbrxForCausalLM(MixtralForCausalLM):
    """Databricks DBRX: MoE with experts stored as FLAT stacked
    [E*ffn, H] tensors (w1 gate, v1 up, w2 down applied untransposed),
    fused Wqkv with clipping, bias-free LayerNorms (reference:
    models/dbrx.py incl. its expert unflatten in the weight loader)."""

    @classmethod
    def arch_config_source(cls, hf):
        from types import SimpleNamespace

        from vllm_distributed_tpu.models.common import subconfig_get \
            as get
        attn = getattr(hf, "attn_config", None)
        ffn = getattr(hf, "ffn_config", None)
        return SimpleNamespace(
            vocab_size=hf.vocab_size,
            hidden_size=hf.d_model,
            intermediate_size=int(get(ffn, "ffn_hidden_size",
                                      4 * hf.d_model)),
            num_hidden_layers=hf.n_layers,
            num_attention_heads=hf.n_heads,
            num_key_value_heads=int(get(attn, "kv_n_heads", hf.n_heads)),
            head_dim=hf.d_model // hf.n_heads,
            rms_norm_eps=1e-5,
            rope_theta=float(get(attn, "rope_theta", 10000.0)),
            tie_word_embeddings=False,
        )

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        from vllm_distributed_tpu.models.common import subconfig_get \
            as get
        ffn = getattr(hf, "ffn_config", None)
        attn = getattr(hf, "attn_config", None)
        arch.num_experts = int(get(ffn, "moe_num_experts", 8))
        arch.num_experts_per_tok = int(get(ffn, "moe_top_k", 2))
        # moe_normalize_expert_weights is a p-norm order; only the L1
        # renormalization (1 / None) maps onto the router — reject
        # other orders rather than silently approximating them.
        p_norm = get(ffn, "moe_normalize_expert_weights", 1)
        if p_norm not in (None, 0, 1, 1.0):
            raise ValueError(
                f"DBRX moe_normalize_expert_weights={p_norm} is not "
                f"supported (only L1 renormalization)")
        arch.norm_topk_prob = bool(p_norm)
        arch.moe_intermediate_size = arch.intermediate_size
        arch.norm_type = "layernorm"
        clip = get(attn, "clip_qkv", None)
        arch.qkv_clip = float(clip) if clip else None

    def params_from_hf_state_dict(self, tensors) -> dict:
        c = self.cfg
        H = c.hidden_size
        I = c.moe_intermediate_size
        kv = c.num_kv_heads * c.head_dim
        alias = {}
        for name, t in tensors.items():
            name = name.replace("transformer.blocks.", "model.layers.")
            name = name.replace("transformer.wte.", "model.embed_tokens.")
            name = name.replace("transformer.norm_f.", "model.norm.")
            name = name.replace(".norm_attn_norm.norm_1.",
                                ".input_layernorm.")
            name = name.replace(".norm_attn_norm.norm_2.",
                                ".post_attention_layernorm.")
            name = name.replace(".norm_attn_norm.attn.out_proj.",
                                ".self_attn.o_proj.")
            alias[name] = np.asarray(t)
        for i in range(c.num_layers):
            base = f"model.layers.{i}."
            w = alias.pop(base + "norm_attn_norm.attn.Wqkv.weight")
            A = base + "self_attn."
            alias[A + "q_proj.weight"] = w[:H]
            alias[A + "k_proj.weight"] = w[H:H + kv]
            alias[A + "v_proj.weight"] = w[H + kv:]
            moe = base + "block_sparse_moe."
            alias[moe + "gate.weight"] = alias.pop(
                base + "ffn.router.layer.weight")
            w1 = alias.pop(base + "ffn.experts.mlp.w1")  # [E*I, H]
            v1 = alias.pop(base + "ffn.experts.mlp.v1")
            w2 = alias.pop(base + "ffn.experts.mlp.w2")
            for e in range(c.num_experts):
                rows = slice(e * I, (e + 1) * I)
                alias[moe + f"experts.{e}.w1.weight"] = w1[rows]
                alias[moe + f"experts.{e}.w3.weight"] = v1[rows]
                # w2 chunks apply UNtransposed (h @ w2_e); canonical
                # w2.weight is torch [out, in], so hand over the
                # transpose.
                alias[moe + f"experts.{e}.w2.weight"] = w2[rows].T
        return super().params_from_hf_state_dict(alias)


class PhimoeForCausalLM(MixtralForCausalLM):
    """Phi-3.5-MoE: Mixtral expert layout + LayerNorm blocks, biased
    projections, and SPARSEMIXER routing — each of the two experts is
    the argmax over jitter-thresholded scores, weighted by a softmax
    over the surviving entries (reference: models/phimoe.py
    phimoe_routing_function; deterministic at inference)."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        if hf.num_experts_per_tok != 2:
            raise ValueError("sparsemixer routing requires top_k == 2")
        arch.num_experts = hf.num_local_experts
        arch.num_experts_per_tok = 2
        arch.moe_intermediate_size = hf.intermediate_size
        arch.norm_type = "layernorm"
        arch.norm_bias = True
        arch.attention_bias = bool(getattr(hf, "attention_bias", True))
        arch.attention_out_bias = arch.attention_bias
        arch.router_jitter_eps = float(
            getattr(hf, "router_jitter_eps", 0.01))
        if getattr(hf, "lm_head_bias", False):
            raise ValueError("Phimoe lm_head_bias checkpoints are not "
                             "supported yet")

    def _route(self, lp: dict, x):
        import jax
        import jax.numpy as jnp
        eps = self.cfg.router_jitter_eps
        scores = (x.astype(jnp.float32)
                  @ lp["router"].astype(jnp.float32))  # [T, E]
        neg = jnp.float32(-jnp.inf)

        def pick(cand):
            # cand: scores with already-taken experts at -inf. The
            # threshold compares against the ORIGINAL scores (HF
            # sparsemixer semantics).
            mx = cand.max(axis=-1, keepdims=True)
            factor = jnp.maximum(jnp.abs(scores), mx)
            drop = ((mx - scores) / factor) > (2 * eps)
            gates = jnp.where(drop, neg, cand)
            sel = jnp.argmax(cand, axis=-1)
            w = jnp.take_along_axis(jax.nn.softmax(gates, axis=-1),
                                    sel[:, None], axis=-1)[:, 0]
            return sel, w

        sel1, w1 = pick(scores)
        masked = scores.at[jnp.arange(scores.shape[0]), sel1].set(neg)
        sel2, w2 = pick(masked)
        return (jnp.stack([sel1, sel2], axis=-1),
                jnp.stack([w1, w2], axis=-1))


class GptOssForCausalLM(MixtralForCausalLM):
    """OpenAI gpt-oss: attention sinks, alternating sliding/full
    layers, biased projections, MoE with interleaved gate_up expert
    tensors, per-expert biases and the clamped (up+1)*glu activation
    (reference: models/gpt_oss.py)."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        arch.num_experts = hf.num_local_experts
        arch.num_experts_per_tok = hf.num_experts_per_tok
        arch.norm_topk_prob = True  # topk-then-softmax == renormalized
        arch.moe_intermediate_size = hf.intermediate_size
        arch.attention_bias = True
        arch.attention_out_bias = True
        arch.attn_sinks = True
        arch.moe_bias = True
        arch.router_bias = True
        # Clamped-GLU activation constants (HF GptOssExperts).
        arch.glu_alpha = 1.702
        arch.glu_limit = float(getattr(hf, "swiglu_limit", 7.0))

    def param_specs(self) -> dict:
        specs = super().param_specs()
        layer = specs["layers"]
        layer["sinks"] = P(None, MODEL_AXIS)
        layer["router_b"] = P(None, None)
        ax = layer["w_gate"]  # [L, E, H, I] spec; biases follow it
        layer["b_gate"] = P(ax[0], ax[1], ax[3])
        layer["b_up"] = P(ax[0], ax[1], ax[3])
        layer["b_down"] = P(ax[0], ax[1], None)
        return specs

    def init_params(self, rng, scale: float = 0.02) -> dict:
        import jax.numpy as jnp
        params = super().init_params(rng, scale)
        c = self.cfg
        L, E = c.num_layers, c.num_experts
        I = c.moe_intermediate_size
        layers = params["layers"]
        layers["sinks"] = jnp.zeros((L, c.num_q_heads), c.dtype)
        layers["router_b"] = jnp.zeros((L, E), c.dtype)
        layers["b_gate"] = jnp.zeros((L, E, I), c.dtype)
        layers["b_up"] = jnp.zeros((L, E, I), c.dtype)
        layers["b_down"] = jnp.zeros((L, E, c.hidden_size), c.dtype)
        return params

    def params_from_hf_state_dict(self, tensors) -> dict:
        import jax.numpy as jnp
        c = self.cfg
        L, E = c.num_layers, c.num_experts
        I = c.moe_intermediate_size
        alias = dict(tensors)
        gu = np.stack([np.asarray(
            alias.pop(f"model.layers.{i}.mlp.experts.gate_up_proj"))
            for i in range(L)])              # [L, E, H, 2I]
        gub = np.stack([np.asarray(
            alias.pop(f"model.layers.{i}.mlp.experts.gate_up_proj_bias"))
            for i in range(L)])              # [L, E, 2I]
        dn = np.stack([np.asarray(
            alias.pop(f"model.layers.{i}.mlp.experts.down_proj"))
            for i in range(L)])              # [L, E, I, H]
        dnb = np.stack([np.asarray(
            alias.pop(f"model.layers.{i}.mlp.experts.down_proj_bias"))
            for i in range(L)])              # [L, E, H]
        for i in range(L):
            # Base mapper wants dense-MLP + per-expert w1/w2/w3 names;
            # hand over torch-Linear [out, in] layouts (it transposes).
            pre = f"model.layers.{i}."
            alias[pre + "block_sparse_moe.gate.weight"] = np.asarray(
                alias.pop(pre + "mlp.router.weight"))
            for e in range(E):
                alias[pre + f"block_sparse_moe.experts.{e}.w1.weight"] \
                    = gu[i, e, :, ::2].T
                alias[pre + f"block_sparse_moe.experts.{e}.w3.weight"] \
                    = gu[i, e, :, 1::2].T
                alias[pre + f"block_sparse_moe.experts.{e}.w2.weight"] \
                    = dn[i, e].T
        params = super().params_from_hf_state_dict(alias)
        layers = params["layers"]
        layers["sinks"] = jnp.asarray(np.stack([
            np.asarray(tensors[f"model.layers.{i}.self_attn.sinks"])
            for i in range(L)]), c.dtype)
        layers["router_b"] = jnp.asarray(np.stack([
            np.asarray(tensors[f"model.layers.{i}.mlp.router.bias"])
            for i in range(L)]), c.dtype)
        layers["b_gate"] = jnp.asarray(gub[..., ::2], c.dtype)
        layers["b_up"] = jnp.asarray(gub[..., 1::2], c.dtype)
        layers["b_down"] = jnp.asarray(dnb, c.dtype)
        return params

    def _moe_dense(self, lp, x, top_idx, top_vals):
        raise ValueError(
            "VDT_MOE_BACKEND=dense is not wired for gpt-oss (its "
            "experts carry biases + a clamped GLU the dense einsum "
            "baseline lacks); unset the env var")

    def _route(self, lp: dict, x):
        import jax
        import jax.numpy as jnp
        c = self.cfg
        logits = (x.astype(jnp.float32)
                  @ lp["router"].astype(jnp.float32)
                  + lp["router_b"].astype(jnp.float32))
        # HF gpt-oss: top-k over logits, softmax over the selected k.
        top_logits, top_idx = jax.lax.top_k(logits,
                                            c.num_experts_per_tok)
        top_vals = jax.nn.softmax(top_logits, axis=-1)
        return top_idx, top_vals

    def _expert_ffn(self, lp: dict, xs, group_sizes):
        import jax
        import jax.numpy as jnp
        c = self.cfg
        rows = xs.shape[0]
        # Expert id per sorted row, for the per-expert biases.
        bounds = jnp.cumsum(group_sizes)
        row_e = jnp.searchsorted(bounds,
                                 jnp.arange(rows, dtype=jnp.int32),
                                 side="right")
        row_e = jnp.minimum(row_e, group_sizes.shape[0] - 1)
        g = (jax.lax.ragged_dot(xs, self._w(lp, "w_gate"), group_sizes)
             + lp["b_gate"][row_e])
        u = (jax.lax.ragged_dot(xs, self._w(lp, "w_up"), group_sizes)
             + lp["b_up"][row_e])
        # Clamped GLU (HF GptOssExperts): gate capped above, up capped
        # both ways, sigmoid(alpha * gate) gating, (up + 1) residual.
        limit, alpha = c.glu_limit, c.glu_alpha
        g = jnp.minimum(g, limit)
        u = jnp.clip(u, -limit, limit)
        glu = g * jax.nn.sigmoid(g * alpha)
        y = jax.lax.ragged_dot(((u + 1.0) * glu).astype(xs.dtype),
                               self._w(lp, "w_down"), group_sizes)
        return y + lp["b_down"][row_e]


class Starcoder2ForCausalLM(LlamaForCausalLM):
    """StarCoder2: LayerNorm(+bias), non-gated gelu MLP with biases,
    qkv + output biases (reference: models/starcoder2.py)."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        arch.norm_type = "layernorm"
        arch.norm_bias = True
        arch.mlp_gated = False
        arch.mlp_bias = bool(getattr(hf, "use_bias", True))
        arch.attention_bias = bool(getattr(hf, "use_bias", True))
        arch.attention_out_bias = bool(getattr(hf, "use_bias", True))
        arch.hidden_act = getattr(hf, "hidden_act", "gelu_pytorch_tanh")
        arch.rms_norm_eps = float(getattr(hf, "norm_epsilon", 1e-5))
        arch.tie_word_embeddings = bool(
            getattr(hf, "tie_word_embeddings", True))

    def params_from_hf_state_dict(self, tensors) -> dict:
        return super().params_from_hf_state_dict(_rename(tensors, [
            (".mlp.c_fc.", ".mlp.fc1."),
            (".mlp.c_proj.", ".mlp.fc2."),
        ]))


class StableLmForCausalLM(LlamaForCausalLM):
    """StableLM: partial rotary + LayerNorm(+bias) around a gated silu
    MLP (reference: models/stablelm.py)."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        arch.norm_type = "layernorm"
        arch.norm_bias = bool(getattr(hf, "layer_norm_bias", True))
        arch.rotary_dim = int(arch.head_dim *
                              float(getattr(hf, "partial_rotary_factor",
                                            0.25)))
        arch.attention_bias = bool(getattr(hf, "use_qkv_bias", False))
        arch.rms_norm_eps = float(getattr(hf, "layer_norm_eps", 1e-5))


class GPTNeoXForCausalLM(LlamaForCausalLM):
    """GPT-NeoX (Pythia): parallel residual with separate norms,
    LayerNorm(+bias), fused per-head-interleaved QKV, partial rotary,
    non-gated gelu MLP, every Linear biased (reference:
    models/gpt_neox.py incl. its fused-QKV de-interleave)."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        arch.norm_type = "layernorm"
        arch.norm_bias = True
        arch.parallel_block = bool(
            getattr(hf, "use_parallel_residual", True))
        arch.mlp_gated = False
        arch.mlp_bias = True
        arch.attention_bias = True
        arch.attention_out_bias = True
        arch.hidden_act = getattr(hf, "hidden_act", "gelu")
        arch.rotary_dim = int(arch.head_dim *
                              float(getattr(hf, "rotary_pct", 0.25)))
        arch.rms_norm_eps = float(getattr(hf, "layer_norm_eps", 1e-5))
        arch.tie_word_embeddings = False

    def params_from_hf_state_dict(self, tensors) -> dict:
        c = self.cfg
        D, H = c.head_dim, c.hidden_size
        N = c.num_q_heads
        out = {}
        for name, t in tensors.items():
            name = name.replace("gpt_neox.layers.", "model.layers.")
            name = name.replace("gpt_neox.final_layer_norm.",
                                "model.norm.")
            name = name.replace("gpt_neox.embed_in.",
                                "model.embed_tokens.")
            name = name.replace("embed_out.", "lm_head.")
            name = name.replace(".attention.dense.", ".self_attn.o_proj.")
            name = name.replace(".mlp.dense_h_to_4h.", ".mlp.fc1.")
            name = name.replace(".mlp.dense_4h_to_h.", ".mlp.fc2.")
            out[name] = t
        # De-interleave the fused QKV: rows pack [h0_q, h0_k, h0_v,
        # h1_q, ...] (reference: gpt_neox.py attention weight loader).
        for i in range(c.num_layers):
            base = f"model.layers.{i}.attention.query_key_value"
            w = np.asarray(out.pop(base + ".weight"))  # [3*N*D, H]
            b = np.asarray(out.pop(base + ".bias"))
            w = w.reshape(N, 3, D, H)
            b = b.reshape(N, 3, D)
            A = f"model.layers.{i}.self_attn."
            out[A + "q_proj.weight"] = w[:, 0].reshape(N * D, H)
            out[A + "k_proj.weight"] = w[:, 1].reshape(N * D, H)
            out[A + "v_proj.weight"] = w[:, 2].reshape(N * D, H)
            out[A + "q_proj.bias"] = b[:, 0].reshape(N * D)
            out[A + "k_proj.bias"] = b[:, 1].reshape(N * D)
            out[A + "v_proj.bias"] = b[:, 2].reshape(N * D)
        return super().params_from_hf_state_dict(out)


class PhiForCausalLM(LlamaForCausalLM):
    """Phi-1/1.5/2: parallel residual from ONE shared input norm,
    LayerNorm(+bias), partial rotary, non-gated gelu MLP with biases,
    biased LM head (reference: models/phi.py)."""

    LM_HEAD_BIAS = True

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        arch.norm_type = "layernorm"
        arch.norm_bias = True
        arch.parallel_block = True
        arch.shared_block_ln = True
        arch.mlp_gated = False
        arch.mlp_bias = True
        arch.attention_bias = True
        arch.attention_out_bias = True
        arch.hidden_act = getattr(hf, "hidden_act", "gelu_new")
        arch.rotary_dim = int(arch.head_dim *
                              float(getattr(hf, "partial_rotary_factor",
                                            0.5)))
        arch.rms_norm_eps = float(getattr(hf, "layer_norm_eps", 1e-5))

    def params_from_hf_state_dict(self, tensors) -> dict:
        # lm_head.bias flows through the base LM_HEAD_BIAS hook.
        return super().params_from_hf_state_dict(_rename(tensors, [
            (".self_attn.dense.", ".self_attn.o_proj."),
            ("model.final_layernorm.", "model.norm."),
        ]))


class CohereForCausalLM(LlamaForCausalLM):
    """Cohere Command-R: parallel residual from one shared LayerNorm
    (no bias), interleaved rope, logit_scale, tied embeddings
    (reference: models/commandr.py)."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        arch.norm_type = "layernorm"
        arch.parallel_block = True
        arch.shared_block_ln = True
        arch.rope_interleaved = True
        arch.logit_multiplier = float(getattr(hf, "logit_scale", 1.0))
        arch.tie_word_embeddings = True
        arch.attention_bias = bool(getattr(hf, "attention_bias", False))
        arch.rms_norm_eps = float(getattr(hf, "layer_norm_eps", 1e-5))
        if getattr(hf, "use_qk_norm", False):
            raise ValueError("Cohere use_qk_norm checkpoints are not "
                             "supported yet")


class Olmo2ForCausalLM(LlamaForCausalLM):
    """OLMo 2: post-norm block (sub-layers read the raw residual
    stream, outputs are RMS-normed before the add) + full-row q/k norms
    (reference: models/olmo2.py)."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        arch.pre_norm = False
        arch.extra_layer_norms = True
        arch.qk_norm_full = True

    # The base loader handles the post-norm layout directly: with
    # pre_norm=False it skips input_ln/post_ln and stacks only the two
    # output norms (post_attention/post_feedforward), which is exactly
    # olmo2's checkpoint naming — no override needed.


class Olmo3ForCausalLM(Olmo2ForCausalLM):
    """OLMo-3: the OLMo-2 post-norm block + per-layer sliding windows;
    rope SCALING applies only to full-attention layers — sliding
    layers keep the default unscaled rope (reference: models/olmo3.py
    building separate rotary tables per layer type)."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        Olmo2ForCausalLM.configure_arch(arch, hf)
        if arch.rope_scaling is not None:
            # Same base, no scaling, on the windowed layers (the
            # rope_theta_local table carries no scaling by design).
            arch.rope_theta_local = arch.rope_theta


class NemotronForCausalLM(LlamaForCausalLM):
    """Nemotron: LayerNorm1p (weight+1, folded at load), relu^2
    non-gated MLP, partial rotary (reference: models/nemotron.py)."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        arch.norm_type = "layernorm"
        arch.norm_bias = True
        arch.mlp_gated = False
        arch.hidden_act = "relu2"
        arch.rotary_dim = int(
            arch.head_dim * float(getattr(hf, "partial_rotary_factor",
                                          0.5)))
        arch.rms_norm_eps = float(getattr(hf, "norm_eps", 1e-5))

    def params_from_hf_state_dict(self, tensors) -> dict:
        params = super().params_from_hf_state_dict(_rename(tensors, [
            (".mlp.up_proj.", ".mlp.fc1."),
            (".mlp.down_proj.", ".mlp.fc2."),
        ]))
        # LayerNorm1p: (1 + w) * normed + b — fold the +1.
        layers = params["layers"]
        for key in ("input_ln", "post_ln"):
            layers[key] = layers[key] + 1.0
        params["final_ln"] = params["final_ln"] + 1.0
        return params


class OlmoForCausalLM(LlamaForCausalLM):
    """OLMo v1: NON-parametric LayerNorm (no weight/bias tensors in the
    checkpoint — synthesized as ones/zeros at load), optional qkv
    clamping (reference: models/olmo.py)."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        arch.norm_type = "layernorm"
        arch.rms_norm_eps = 1e-5  # OlmoLayerNorm's fixed eps
        clip = getattr(hf, "clip_qkv", None)
        arch.qkv_clip = float(clip) if clip else None

    def params_from_hf_state_dict(self, tensors) -> dict:
        c = self.cfg
        ones = np.ones((c.hidden_size, ), np.float32)
        alias = dict(tensors)
        for i in range(c.num_layers):
            alias[f"model.layers.{i}.input_layernorm.weight"] = ones
            alias[f"model.layers.{i}.post_attention_layernorm.weight"] \
                = ones
        alias["model.norm.weight"] = ones
        return super().params_from_hf_state_dict(alias)


class OlmoeForCausalLM(MixtralForCausalLM):
    """OLMoE: Mixtral-style routed experts (softmax, norm_topk_prob
    False) + full-row q/k RMSNorms (reference: models/olmoe.py)."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        arch.num_experts = hf.num_experts
        arch.num_experts_per_tok = hf.num_experts_per_tok
        arch.norm_topk_prob = bool(getattr(hf, "norm_topk_prob", False))
        arch.qk_norm_full = True

    def params_from_hf_state_dict(self, tensors) -> dict:
        return super().params_from_hf_state_dict(_alias_moe_experts(
            tensors, self.cfg.num_layers, self.cfg.num_experts))


class GlmForCausalLM(LlamaForCausalLM):
    """GLM-4 (hf-format): partial INTERLEAVED rotary on the first half
    of each head, qkv bias, standard pre-norm gated block (reference:
    models/glm.py)."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        arch.rope_interleaved = True
        arch.rotary_dim = int(arch.head_dim *
                              float(getattr(hf, "partial_rotary_factor",
                                            0.5)))
        arch.attention_bias = bool(getattr(hf, "attention_bias", True))
        # GLM's o_proj is bias-free even when attention_bias is set
        # (HF GlmAttention hardcodes bias=False on o_proj).
        arch.attention_out_bias = False

    def params_from_hf_state_dict(self, tensors) -> dict:
        # GLM fuses gate|up like Phi-3; split for the base layout.
        out = dict(tensors)
        for i in range(self.cfg.num_layers):
            gu = np.asarray(
                tensors[f"model.layers.{i}.mlp.gate_up_proj.weight"])
            half = gu.shape[0] // 2
            out[f"model.layers.{i}.mlp.gate_proj.weight"] = gu[:half]
            out[f"model.layers.{i}.mlp.up_proj.weight"] = gu[half:]
        return super().params_from_hf_state_dict(out)


class Glm4ForCausalLM(GlmForCausalLM):
    """GLM-4-0414: the GLM block plus Gemma2-style sandwich norms on
    each sub-block's output (post_self_attn / post_mlp layernorms;
    reference: models/glm4.py)."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        GlmForCausalLM.configure_arch(arch, hf)
        arch.extra_layer_norms = True

    def params_from_hf_state_dict(self, tensors) -> dict:
        # Role renames onto the Gemma2-style 4-norm canonical layout;
        # ORDER matters (the true pre-MLP norm carries the name the
        # attention-output norm must end up with).
        return super().params_from_hf_state_dict(_rename(tensors, [
            (".post_attention_layernorm.", ".pre_feedforward_layernorm."),
            (".post_self_attn_layernorm.", ".post_attention_layernorm."),
            (".post_mlp_layernorm.", ".post_feedforward_layernorm."),
        ]))


class FalconForCausalLM(LlamaForCausalLM):
    """Falcon (reference: models/falcon.py): parallel-residual block —
    one shared norm for 7B-style checkpoints, separate ln_attn/ln_mlp
    for the new decoder architecture (40B/180B) — non-gated gelu MLP,
    grouped fused QKV (q heads of each kv group packed with that
    group's k and v), multi-query or grouped kv."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        if getattr(hf, "alibi", False):
            raise ValueError("ALiBi Falcon checkpoints (falcon-rw) are "
                             "not supported (no rotary)")
        if not getattr(hf, "parallel_attn", True):
            raise ValueError("sequential-attention Falcon "
                             "(parallel_attn=false) is not supported")
        new = bool(getattr(hf, "new_decoder_architecture", False))
        arch.parallel_block = True
        # Falcon2-11B is new-arch but keeps ONE shared norm
        # (num_ln_in_parallel_attn=1 -> no ln_attn/ln_mlp tensors).
        arch.shared_block_ln = (not new or getattr(
            hf, "num_ln_in_parallel_attn", None) == 1)
        arch.norm_type = "layernorm"
        arch.norm_bias = True
        arch.mlp_gated = False
        bias = bool(getattr(hf, "bias", False))
        arch.mlp_bias = bias
        arch.attention_bias = bias
        arch.attention_out_bias = bias
        # HF FalconMLP honors config.activation; _act raises on
        # anything unmappable instead of silently running gelu.
        arch.hidden_act = getattr(hf, "activation", "gelu") or "gelu"
        arch.rms_norm_eps = float(getattr(hf, "layer_norm_epsilon",
                                          1e-5))
        if new:
            arch.num_kv_heads = int(hf.num_kv_heads)
        elif getattr(hf, "multi_query", True):
            arch.num_kv_heads = 1
        arch.tie_word_embeddings = False

    def params_from_hf_state_dict(self, tensors) -> dict:
        c = self.cfg
        D, H = c.head_dim, c.hidden_size
        G = c.num_kv_heads
        qpg = c.num_q_heads // G
        out = {}
        for name, t in tensors.items():
            name = name.replace("transformer.h.", "model.layers.")
            name = name.replace("transformer.ln_f.", "model.norm.")
            name = name.replace("transformer.word_embeddings.",
                                "model.embed_tokens.")
            name = name.replace(".self_attention.dense.",
                                ".self_attn.o_proj.")
            name = name.replace(".mlp.dense_h_to_4h.", ".mlp.fc1.")
            name = name.replace(".mlp.dense_4h_to_h.", ".mlp.fc2.")
            # ln_attn feeds attention (our input_ln); ln_mlp the MLP
            # (our post_ln); old-style shares input_layernorm.
            name = name.replace(".ln_attn.", ".input_layernorm.")
            name = name.replace(".ln_mlp.", ".post_attention_layernorm.")
            out[name] = t
        # Grouped fused QKV: per kv group, q_per_group q heads then that
        # group's k and v (reference: falcon.py _split_heads).
        from vllm_distributed_tpu.models.families import \
            split_grouped_qkv
        for i in range(c.num_layers):
            base = f"model.layers.{i}.self_attention.query_key_value"
            w = np.asarray(out.pop(base + ".weight"))
            A = f"model.layers.{i}.self_attn."
            (out[A + "q_proj.weight"], out[A + "k_proj.weight"],
             out[A + "v_proj.weight"]) = split_grouped_qkv(
                w, G, qpg, D)
            if base + ".bias" in out:
                b = np.asarray(out.pop(base + ".bias")).reshape(-1, 1)
                qb, kb, vb = split_grouped_qkv(b, G, qpg, D)
                out[A + "q_proj.bias"] = qb.reshape(-1)
                out[A + "k_proj.bias"] = kb.reshape(-1)
                out[A + "v_proj.bias"] = vb.reshape(-1)
        return super().params_from_hf_state_dict(out)


class PersimmonForCausalLM(LlamaForCausalLM):
    """Persimmon (Adept; reference: models/persimmon.py): LayerNorm
    block with biases, relu^2 non-gated MLP, partial rotary, per-head
    qk LayerNorms WITH biases, NeoX-style per-head-interleaved fused
    QKV."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        arch.norm_type = "layernorm"
        arch.norm_bias = True
        arch.mlp_gated = False
        arch.mlp_bias = True
        arch.attention_bias = True
        arch.attention_out_bias = True
        arch.hidden_act = getattr(hf, "hidden_act", "relu2")
        arch.rotary_dim = int(arch.head_dim *
                              float(getattr(hf, "partial_rotary_factor",
                                            0.5)))
        arch.rms_norm_eps = float(getattr(hf, "layer_norm_eps", 1e-5))
        if getattr(hf, "qk_layernorm", True):
            arch.qk_norm = True
            arch.qk_norm_bias = True

    def params_from_hf_state_dict(self, tensors) -> dict:
        c = self.cfg
        D, H = c.head_dim, c.hidden_size
        N = c.num_q_heads
        out = {}
        for name, t in tensors.items():
            name = name.replace(".self_attn.dense.", ".self_attn.o_proj.")
            name = name.replace(".self_attn.q_layernorm.",
                                ".self_attn.q_norm.")
            name = name.replace(".self_attn.k_layernorm.",
                                ".self_attn.k_norm.")
            name = name.replace("model.final_layernorm.", "model.norm.")
            name = name.replace(".mlp.dense_h_to_4h.", ".mlp.fc1.")
            name = name.replace(".mlp.dense_4h_to_h.", ".mlp.fc2.")
            out[name] = t
        from vllm_distributed_tpu.models.families import \
            split_grouped_qkv
        for i in range(c.num_layers):
            base = f"model.layers.{i}.self_attn.query_key_value"
            # NeoX-style per-head [q, k, v] triplets = the grouped
            # layout with one q head per "group".
            w = np.asarray(out.pop(base + ".weight")).reshape(
                N, 3, D, H).reshape(N * 3 * D, H)
            A = f"model.layers.{i}.self_attn."
            (out[A + "q_proj.weight"], out[A + "k_proj.weight"],
             out[A + "v_proj.weight"]) = split_grouped_qkv(w, N, 1, D)
            b = np.asarray(out.pop(base + ".bias")).reshape(-1, 1)
            qb, kb, vb = split_grouped_qkv(b, N, 1, D)
            out[A + "q_proj.bias"] = qb.reshape(-1)
            out[A + "k_proj.bias"] = kb.reshape(-1)
            out[A + "v_proj.bias"] = vb.reshape(-1)
        return super().params_from_hf_state_dict(out)


class Cohere2ForCausalLM(CohereForCausalLM):
    """Cohere2 / Command-R7B (reference: models/commandr.py Cohere2
    variant): the Cohere parallel block + 3:1 sliding/full interleave
    where the FULL-attention layers are NoPE — rotary applies only
    under the sliding window (modeling_cohere2.Cohere2Attention gates
    apply_rotary_pos_emb on sliding_window)."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        super().configure_arch(arch, hf)
        if arch.window_pattern is not None:
            arch.nope_layers = tuple(
                w == 0 for w in arch.window_pattern)


class SmolLM3ForCausalLM(LlamaForCausalLM):
    """SmolLM3 (reference: models/smollm3.py): llama block with every
    fourth layer NoPE (config.no_rope_layers, 0 = skip rotary)."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        nrl = getattr(hf, "no_rope_layers", None)
        if nrl:
            arch.nope_layers = tuple(not bool(v) for v in nrl)


class Exaone4ForCausalLM(LlamaForCausalLM):
    """EXAONE-4 (reference: models/exaone4.py): POST-norm block (the
    sublayer output is normed before the residual add — the Olmo2
    layout), per-head q/k RMSNorm ahead of rope, and a 3:1
    sliding/full hybrid whose full-attention layers are NoPE
    ("global NoPE", modeling_exaone4.Exaone4Attention)."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        arch.pre_norm = False
        arch.extra_layer_norms = True
        arch.qk_norm = True
        if arch.window_pattern is not None:
            arch.nope_layers = tuple(
                w == 0 for w in arch.window_pattern)


class VaultGemmaForCausalLM(LlamaForCausalLM):
    """VaultGemma (reference: models/vaultgemma.py): the Gemma block
    (scaled embeddings, gelu-tanh, +1-offset RMSNorm weights,
    query_pre_attn_scalar, attention + final logit soft-capping,
    alternating windows) but WITHOUT Gemma2's sandwich norms — the MLP
    pre-norm ships as ``pre_feedforward_layernorm``."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        import math
        arch.embed_scale = math.sqrt(arch.hidden_size)
        arch.hidden_act = "gelu_tanh"
        arch.tie_word_embeddings = True
        arch.attn_logit_softcap = float(
            getattr(hf, "attn_logit_softcapping", None) or 0.0)
        arch.final_logit_softcap = float(
            getattr(hf, "final_logit_softcapping", None) or 0.0)
        qpas = getattr(hf, "query_pre_attn_scalar", None)
        arch.query_pre_attn_scalar = float(qpas) if qpas else None

    def params_from_hf_state_dict(self, tensors) -> dict:
        renamed = {}
        for name, t in tensors.items():
            renamed[name.replace("pre_feedforward_layernorm",
                                 "post_attention_layernorm")] = t
        params = super().params_from_hf_state_dict(renamed)
        layers = params["layers"]
        for key in ("input_ln", "post_ln"):
            layers[key] = layers[key] + 1.0
        params["final_ln"] = params["final_ln"] + 1.0
        return params


class HunYuanDenseV1ForCausalLM(LlamaForCausalLM):
    """Tencent HunYuan dense v1 (reference: models/hunyuan_v1.py): the
    llama block + per-head q/k RMSNorm ahead of rope, shipped as
    ``query_layernorm``/``key_layernorm``."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        arch.qk_norm = True

    def params_from_hf_state_dict(self, tensors) -> dict:
        renamed = {}
        for name, t in tensors.items():
            name = name.replace(".query_layernorm.", ".q_norm.")
            name = name.replace(".key_layernorm.", ".k_norm.")
            renamed[name] = t
        return super().params_from_hf_state_dict(renamed)


class FlexOlmoForCausalLM(MixtralForCausalLM):
    """FlexOlmo (reference: models/flex_olmo.py): the OLMo-2 POST-norm
    block (output norms before the residual adds, full-row q/k norms)
    with OLMoE-style routed experts."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        arch.pre_norm = False
        arch.extra_layer_norms = True
        arch.qk_norm_full = True
        arch.num_experts = hf.num_experts
        arch.num_experts_per_tok = hf.num_experts_per_tok
        arch.norm_topk_prob = bool(getattr(hf, "norm_topk_prob", False))

    def params_from_hf_state_dict(self, tensors) -> dict:
        return super().params_from_hf_state_dict(_alias_moe_experts(
            tensors, self.cfg.num_layers, self.cfg.num_experts))


class GraniteMoeSharedForCausalLM(GraniteMoeForCausalLM):
    """GraniteMoeShared (reference: models/granitemoeshared.py): the
    GraniteMoe block plus an UNGATED dense shared MLP added to every
    token's routed output, shipped fused like the experts
    (shared_mlp.input_linear packs [gate; up])."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        super().configure_arch(arch, hf)
        arch.shared_expert_intermediate_size = int(
            getattr(hf, "shared_intermediate_size", 0) or 0)

    def param_specs(self) -> dict:
        specs = super().param_specs()
        if self.cfg.shared_expert_intermediate_size:
            specs["layers"].update({
                "shared_gate": P(None, None, MODEL_AXIS),
                "shared_up": P(None, None, MODEL_AXIS),
                "shared_down": P(None, MODEL_AXIS, None),
            })
        return specs

    def init_params(self, rng, scale: float = 0.02) -> dict:
        import jax
        import jax.numpy as jnp
        params = super().init_params(rng, scale)
        c = self.cfg
        Is = c.shared_expert_intermediate_size
        if Is:
            L, H = c.num_layers, c.hidden_size
            keys = iter(jax.random.split(jax.random.fold_in(rng, 29), 3))

            def norm(key, shape):
                return (scale * jax.random.normal(
                    key, shape, jnp.float32)).astype(c.dtype)

            params["layers"].update({
                "shared_gate": norm(next(keys), (L, H, Is)),
                "shared_up": norm(next(keys), (L, H, Is)),
                "shared_down": norm(next(keys), (L, Is, H)),
            })
        return params

    def params_from_hf_state_dict(self, tensors) -> dict:
        import jax.numpy as jnp
        c = self.cfg
        params = super().params_from_hf_state_dict(tensors)
        Is = c.shared_expert_intermediate_size
        if Is:
            gates, ups, downs = [], [], []
            for i in range(c.num_layers):
                pre = f"model.layers.{i}.shared_mlp."
                fused = np.asarray(tensors[pre + "input_linear.weight"])
                gates.append(fused[:Is].T)   # [H, Is]
                ups.append(fused[Is:].T)
                downs.append(np.asarray(
                    tensors[pre + "output_linear.weight"]).T)
            params["layers"].update({
                "shared_gate": jnp.asarray(np.stack(gates), c.dtype),
                "shared_up": jnp.asarray(np.stack(ups), c.dtype),
                "shared_down": jnp.asarray(np.stack(downs), c.dtype),
            })
        return params

    def mlp_block(self, lp: dict, x, lora_ctx=None):
        routed = super().mlp_block(lp, x, lora_ctx)
        if not self.cfg.shared_expert_intermediate_size:
            return routed
        from vllm_distributed_tpu.models.common import swiglu
        return routed + swiglu(x, lp["shared_gate"], lp["shared_up"],
                               lp["shared_down"], act=self._act)
