"""Llava-style image+text models — text-decoder side.

Reference: vllm/model_executor/models/llava.py. The engine serves the
TEXT decoder of a llava checkpoint; image inputs arrive as pre-computed
projector outputs (multimodal/__init__.py) and replace the placeholder
rows after embedding (models/llama.py forward). Running the CLIP vision
tower + projector in-engine is the follow-up slice; until then clients
compute features with the HF tower (the parity test does exactly that).
"""

import numpy as np

from vllm_distributed_tpu.models.llama import LlamaForCausalLM


class LlavaForConditionalGeneration(LlamaForCausalLM):

    @classmethod
    def arch_config_source(cls, hf):
        # Decoder dims live on the nested text_config.
        return hf.text_config

    @classmethod
    def configure_arch(cls, arch, hf) -> None:
        super().configure_arch(arch, hf.text_config)

    def params_from_hf_state_dict(self, tensors: dict[str, np.ndarray],
                                  ) -> dict:
        # Strip the language-model prefix (hub checkpoints say
        # "language_model.model.*", in-memory state dicts
        # "model.language_model.*"); the vision tower + projector are
        # not served (clients ship projector outputs).
        renamed = {}
        for name, t in tensors.items():
            if "vision_tower." in name or "multi_modal_projector." in name:
                continue
            name = name.replace("language_model.model.", "model.")
            name = name.replace("model.language_model.", "model.")
            name = name.replace("language_model.lm_head.", "lm_head.")
            renamed[name] = t
        return super().params_from_hf_state_dict(renamed)
