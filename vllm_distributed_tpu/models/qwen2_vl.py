"""Qwen2-VL: dynamic-resolution vision-language model with M-RoPE.

Reference: vllm/model_executor/models/qwen2_vl.py. The engine serves
the Qwen2 text decoder with MULTIMODAL rotary embeddings (3D
temporal/height/width position ids; models/common.py
compute_mrope_cos_sin) and runs the dynamic-resolution vision tower
(multimodal/qwen2_vision.py) at admission — images and VIDEOS become
pre-positioned embedding rows with an (t, h, w) grid that drives both
the placeholder expansion and the rotary id table
(multimodal/__init__.py compute_mrope_positions).
"""

import numpy as np

from vllm_distributed_tpu.models.llama import LlamaForCausalLM


class Qwen2VLForConditionalGeneration(LlamaForCausalLM):

    MROPE = True
    # Vision payload keys accepted by the processor for this family.
    VISION_STYLE = "qwen2_vl"

    @classmethod
    def arch_config_source(cls, hf):
        return hf.text_config

    @classmethod
    def configure_arch(cls, arch, hf) -> None:
        tc = hf.text_config
        super().configure_arch(arch, tc)
        rs = getattr(tc, "rope_scaling", None) or {}
        section = rs.get("mrope_section")
        if section:
            arch.mrope_section = tuple(int(s) for s in section)
        # The mrope dict is not a frequency-scaling rule; the plain
        # inv_freq table applies (reference: qwen2_vl.py uses default
        # rope frequencies under mrope).
        arch.rope_scaling = None

    def params_from_hf_state_dict(self, tensors: dict[str, np.ndarray],
                                  ) -> dict:
        renamed = {}
        for name, t in tensors.items():
            if ".visual." in name or name.startswith("visual."):
                continue  # the tower runs front-end side
            name = name.replace("model.language_model.", "model.")
            name = name.replace("language_model.model.", "model.")
            name = name.replace("language_model.lm_head.", "lm_head.")
            renamed[name] = t
        return super().params_from_hf_state_dict(renamed)
