"""Shared model-layer math: RMSNorm, rotary embeddings, attention batch
descriptor.

Equivalents of the reference's vllm/model_executor/layers/{layernorm.py,
rotary_embedding.py}; on TPU these are plain jnp expressions XLA fuses into
the surrounding matmuls (SURVEY.md §2.7: "XLA fuses this natively").
"""

import dataclasses
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp


@dataclass
class TknpAttentionBatch:
    """Per-token-parallel-rank attention metadata, stacked on a leading
    rank axis that is sharded over the ``token`` mesh axis inside
    shard_map — each rank reads only its own slab.

    TPU analogue of the fork's TokenParallelMetadata + _tknp_slicing
    (vllm/v1/worker/gpu_model_runner.py:334,392): the host slices the
    step's metadata per rank; page ids are LOCAL to the rank's shard of
    the page-sharded KV cache, and tokens of requests owned by other
    ranks appear as padding (slot -1), so each rank computes attention
    only for its own requests and a psum over the token axis merges the
    disjoint outputs.
    """

    # [K, T] int32 local flat slots; -1 where this rank does not own the
    # token's request.
    slot_mapping: jax.Array
    # [K, max_reqs, pages_per_req] int32 rank-local page tables (rows of
    # non-owned requests are garbage; never dereferenced).
    block_tables: jax.Array
    # [K, max_reqs, 4] / [K, 1]: per-rank compacted seq runs.
    seq_info: jax.Array
    num_seqs: jax.Array
    # [K, G, 4] / [K, 1]: per-rank KV-write runs with local page ids.
    kv_runs: jax.Array
    num_kv_runs: jax.Array
    # [K, P, 3] / [K, max_reqs]: per-rank mega-kernel partition
    # descriptors over the rank's compacted seq runs (None routes the
    # legacy per-composition kernels).
    desc: Optional[jax.Array] = None
    decode_list: Optional[jax.Array] = None


jax.tree_util.register_dataclass(
    TknpAttentionBatch,
    data_fields=[f.name for f in dataclasses.fields(TknpAttentionBatch)],
    meta_fields=[],
)


@dataclass
class AttentionBatch:
    """Flat ragged batch descriptor consumed by every attention layer.

    Built once per step by the model runner (equivalent of the reference's
    per-backend AttentionMetadata, v1/attention/backends/pallas.py
    PallasMetadata). Carries both token-centric metadata (XLA reference
    attention path) and sequence-centric run metadata (Pallas kernel path).
    """

    # [T] int32: owning request row for each token.
    req_idx: jax.Array
    # [T] int32: absolute sequence position of each token.
    positions: jax.Array
    # [T] int32: flat KV slot (page * page_size + offset), -1 for padding.
    slot_mapping: jax.Array
    # [max_reqs, pages_per_req] int32 page table.
    block_tables: jax.Array
    # [max_reqs] int32 total context length per request (0 = inactive).
    seq_lens: jax.Array
    # [max_reqs, 4] int32 per-sequence runs in batch order:
    # (q_start, q_len, kv_len_incl_new, batch_row). Rows >= num_seqs zero.
    seq_info: Optional[jax.Array] = None
    # [1] int32: number of active runs in seq_info.
    num_seqs: Optional[jax.Array] = None
    # [G, 4] int32 page-write runs for the Pallas KV-write kernel:
    # (page, off_start, window_start, run_len); see ops/pallas_kv_write.py.
    kv_runs: Optional[jax.Array] = None
    # [1] int32: number of active rows in kv_runs.
    num_kv_runs: Optional[jax.Array] = None
    # Per-rank stacked metadata when token parallelism is on (see
    # TknpAttentionBatch); None otherwise.
    tknp: Optional[TknpAttentionBatch] = None
    # Multi-LoRA token routing (None when LoRA is disabled): tokens
    # sorted by adapter slot, consumed by the grouped-GEMM LoRA apply
    # (models/lora.py; the TPU answer to the reference's punica SGMV).
    lora: Optional["LoraBatch"] = None
    # Cascade attention: page ids of the batch-wide shared prefix
    # ([S] int32, static S; None disables — see
    # ops/attention.cascade_ragged_paged_attention).
    cascade_shared_ids: Optional[jax.Array] = None
    # Multimodal: [T, H] embedding-override rows + [T] bool mask
    # (placeholder positions take the image rows; None on text-only
    # steps — a distinct pytree, so mm steps compile their own variant
    # like every other static flag).
    mm_embeds: Optional[jax.Array] = None
    mm_mask: Optional[jax.Array] = None
    # M-RoPE (Qwen2-VL): [T, 3] (temporal, height, width) rotary ids;
    # None = all three equal `positions` (plain rope — exact for
    # text-only requests). Reference: the mrope position ids of
    # model_executor/models/qwen2_vl.py get_rope_index.
    mrope_positions: Optional[jax.Array] = None
    # Mega-kernel partition descriptor ([P, 3] int32) + decode row list
    # ([max_reqs] int32): the host-built program partition consumed by
    # ops/pallas_attention.py's unified kernel (kv-write runs, prefill
    # q-tiles, SB decode groups — see the descriptor contract there).
    # None routes legacy per-composition dispatch (in-jit batches built
    # by the multi-step scan / EAGLE, and MLA models).
    attn_desc: Optional[jax.Array] = None
    decode_list: Optional[jax.Array] = None
    # Static: per-sequence query-length bucket (1 for pure decode);
    # changing it recompiles, like every other shape bucket. Ignored by
    # the unified kernel (pinned to 1 by the runner when a descriptor is
    # present), still consulted by the legacy dispatch and MLA.
    max_q: int = 1
    # Static mega-kernel tile parameters (prefill_tile_size /
    # decode_group_size): fixed per model+sharding, passed through the
    # batch so the host descriptor builder and the kernel can never
    # disagree. 0 when no descriptor is attached.
    attn_bq: int = 0
    attn_sb: int = 0
    # Fused transformer-block decode (ops/pallas_block.py): the runner
    # sets this STATIC flag on decode-only waves of an eligible model
    # under VDT_BLOCK_FUSION=1 — run_layers then executes each layer as
    # ONE Pallas call instead of the per-op path. A meta field like
    # max_q: flipping it selects a different (precompile-warmed) graph.
    block_fused: bool = False


@dataclasses.dataclass
class LoraBatch:
    """Token->adapter-slot grouping, built once per step and shared by
    every LoRA-wrapped matmul in the forward."""

    # [T] int32 permutation sorting tokens by adapter slot.
    order: jax.Array
    # [T] int32 inverse permutation (back to batch order).
    inv: jax.Array
    # [S] int32 tokens per slot in sorted order (S = max_loras + 1).
    group_sizes: jax.Array
    # [T] float32 per-token adapter scaling (alpha/r; 0 for slot 0), in
    # SORTED order.
    scaling: jax.Array


jax.tree_util.register_dataclass(
    LoraBatch,
    data_fields=[f.name for f in dataclasses.fields(LoraBatch)],
    meta_fields=[],
)


jax.tree_util.register_dataclass(
    AttentionBatch,
    data_fields=[
        f.name for f in dataclasses.fields(AttentionBatch)
        if f.name not in ("max_q", "attn_bq", "attn_sb", "block_fused")
    ],
    meta_fields=["max_q", "attn_bq", "attn_sb", "block_fused"],
)


def alibi_slopes(num_heads: int) -> tuple:
    """Standard ALiBi head slopes (geometric 2^(-8i/n) ladder, with the
    interleaved extension for non-power-of-two head counts; reference:
    the _get_alibi_slopes helpers of models/bloom.py / mpt.py — the
    published train-short-test-long recipe)."""
    import math

    def pow2(n):
        start = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
        return [start ** (i + 1) for i in range(n)]

    if math.log2(num_heads).is_integer():
        return tuple(pow2(num_heads))
    closest = 2 ** math.floor(math.log2(num_heads))
    return tuple(pow2(closest) +
                 pow2(2 * closest)[0::2][:num_heads - closest])


def subconfig_get(cfg, key, default):
    """Read a key from an HF sub-config that may be a dict or an
    attribute-style object (MPT attn_config, DBRX attn/ffn_config)."""
    if cfg is None:
        return default
    if isinstance(cfg, dict):
        return cfg.get(key, default)
    return getattr(cfg, key, default)


def rename_tensors(tensors: dict, table) -> dict:
    """Substring-rename checkpoint tensor names onto the canonical
    layout (shared by the family loaders; rules apply in order)."""
    out = {}
    for name, t in tensors.items():
        for old, new in table:
            if old in name:
                name = name.replace(old, new)
        out[name] = t
    return out


def rms_norm(x: jax.Array, weight: jax.Array,
             eps: float = 1e-6) -> jax.Array:
    """Llama RMSNorm; accumulate in fp32 regardless of activation dtype."""
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    normed = x32 * jax.lax.rsqrt(var + eps)
    return (normed * weight.astype(jnp.float32)).astype(x.dtype)


def make_inv_freq(head_dim: int, rope_theta: float,
                  rope_scaling: dict | None = None) -> jax.Array:
    """Rotary inverse frequencies, with Llama-3.1-style piecewise NTK
    scaling when ``rope_scaling["rope_type"] == "llama3"`` (reference:
    vllm/model_executor/layers/rotary_embedding.py Llama3RotaryEmbedding)."""
    inv_freq = 1.0 / (rope_theta ** (
        jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    rtype = (rope_scaling or {}).get(
        "rope_type", (rope_scaling or {}).get("type"))
    if rope_scaling and rtype == "linear":
        # Position-interpolation scaling (Gemma3 global layers).
        inv_freq = inv_freq / rope_scaling["factor"]
    if rope_scaling and rope_scaling.get("rope_type",
                                        rope_scaling.get("type")) == "llama3":
        factor = rope_scaling["factor"]
        low = rope_scaling["low_freq_factor"]
        high = rope_scaling["high_freq_factor"]
        orig = rope_scaling["original_max_position_embeddings"]
        wavelen = 2 * jnp.pi / inv_freq
        low_wavelen = orig / low
        high_wavelen = orig / high
        # Long wavelengths scaled down by factor; short kept; smooth ramp
        # in between.
        smooth = (orig / wavelen - low) / (high - low)
        scaled = jnp.where(
            wavelen > low_wavelen, inv_freq / factor,
            jnp.where(wavelen < high_wavelen, inv_freq,
                      (1 - smooth) * inv_freq / factor + smooth * inv_freq))
        inv_freq = scaled
    if rope_scaling and rtype == "yarn":
        # NTK-by-parts scaling (gpt-oss, Qwen long-context checkpoints);
        # the attention factor is applied by compute_rope_cos_sin.
        orig = rope_scaling.get("original_max_position_embeddings")
        if not orig:
            raise ValueError(
                "yarn rope_scaling needs original_max_position_"
                "embeddings")
        inv_freq, _ = yarn_inv_freq(head_dim, rope_theta, rope_scaling,
                                    orig)
    if rope_scaling and rtype == "longrope":
        # Phi-3 LongRoPE: per-dim rescale factors, long set active when
        # the serving window exceeds the pretraining window (reference:
        # modeling_rope_utils._compute_longrope_parameters; from_hf_
        # config folds the two window fields into the dict).
        orig = rope_scaling.get("original_max_position_embeddings")
        maxp = rope_scaling.get("max_position_embeddings")
        if not orig or not maxp:
            raise ValueError(
                "longrope rope_scaling needs original_ and "
                "max_position_embeddings (from_hf_config adds them)")
        ext = (rope_scaling["long_factor"] if maxp > orig
               else rope_scaling["short_factor"])
        inv_freq = 1.0 / (
            jnp.asarray(ext, jnp.float32) * rope_theta ** (
                jnp.arange(0, head_dim, 2, jnp.float32) / head_dim))
    return inv_freq


def _rope_attention_factor(rope_scaling: dict | None) -> float:
    """YaRN's mscale: multiplies cos/sin (reference: the
    attention_scaling of modeling_rope_utils._compute_yarn_parameters).
    Shares yarn_inv_freq's formula (yarn_attention_factor)."""
    import math
    rtype = (rope_scaling or {}).get(
        "rope_type", (rope_scaling or {}).get("type"))
    if rope_scaling and rtype == "yarn":
        return yarn_attention_factor(rope_scaling)
    if rope_scaling and rtype == "longrope":
        af = rope_scaling.get("attention_factor")
        if af is not None:
            return float(af)
        orig = rope_scaling["original_max_position_embeddings"]
        maxp = rope_scaling["max_position_embeddings"]
        # Phi-3.5-MoE carries explicit per-regime mscales.
        mscale = (rope_scaling.get("long_mscale") if maxp > orig
                  else rope_scaling.get("short_mscale"))
        if mscale:
            return float(mscale)
        factor = rope_scaling.get("factor") or maxp / orig
        if factor <= 1.0:
            return 1.0
        return math.sqrt(1 + math.log(factor) / math.log(orig))
    return 1.0


def compute_rope_cos_sin(positions: jax.Array, head_dim: int,
                         rope_theta: float,
                         rope_scaling: dict | None = None,
                         dtype=jnp.float32) -> tuple[jax.Array, jax.Array]:
    """cos/sin tables for the given positions, HF-llama layout: inv_freq
    over even dims, duplicated across both halves of the head."""
    inv_freq = make_inv_freq(head_dim, rope_theta, rope_scaling)
    att = _rope_attention_factor(rope_scaling)
    freqs = positions.astype(jnp.float32)[:, None] * inv_freq[None, :]
    emb = jnp.concatenate([freqs, freqs], axis=-1)  # [T, D]
    return (jnp.cos(emb).astype(dtype) * att,
            jnp.sin(emb).astype(dtype) * att)


def compute_mrope_cos_sin(mrope_positions: jax.Array,  # [T, 3]
                          head_dim: int, rope_theta: float,
                          sections: tuple,
                          dtype=jnp.float32) -> tuple[jax.Array,
                                                      jax.Array]:
    """Multimodal (3D) rotary tables, Qwen2-VL layout (reference:
    apply_multimodal_rotary_pos_emb of qwen2_vl.py): frequency index i
    reads its angle from the (temporal | height | width) position id
    its ``mrope_section`` assigns it; text-only ids (all three equal)
    reduce exactly to plain rope."""
    inv_freq = make_inv_freq(head_dim, rope_theta, None)
    half = inv_freq.shape[0]
    assert sum(sections) == half, (sections, half)
    # [3, T, half] angle per position stream.
    freqs = (mrope_positions.astype(jnp.float32).T[:, :, None] *
             inv_freq[None, None, :])
    parts = []
    start = 0
    for k, width in enumerate(sections):
        parts.append(freqs[k, :, start:start + width])
        start += width
    sel = jnp.concatenate(parts, axis=-1)  # [T, half]
    emb = jnp.concatenate([sel, sel], axis=-1)
    return jnp.cos(emb).astype(dtype), jnp.sin(emb).astype(dtype)


def _rotate_half(x: jax.Array) -> jax.Array:
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rope(q: jax.Array, k: jax.Array, cos: jax.Array,
               sin: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Apply rotary embedding; q/k are [T, heads, head_dim], cos/sin [T, D].
    Matches HF transformers' apply_rotary_pos_emb exactly (parity tests
    depend on bit-level agreement up to dtype rounding)."""
    cos = cos[:, None, :]
    sin = sin[:, None, :]
    q_out = q * cos + _rotate_half(q) * sin
    k_out = k * cos + _rotate_half(k) * sin
    return q_out.astype(q.dtype), k_out.astype(k.dtype)


def swiglu(x: jax.Array, gate_w: jax.Array, up_w: jax.Array,
           down_w: jax.Array, act=jax.nn.silu) -> jax.Array:
    """Gated MLP (reference: csrc/activation_kernels.cu fused silu-mul /
    gelu variants; XLA fuses the elementwise chain into the matmuls)."""
    gate = act(x @ gate_w)
    return (gate * (x @ up_w)) @ down_w


def yarn_attention_factor(scaling: dict) -> float:
    """YaRN's attention (mscale) factor — the cos/sin multiplier
    (reference: modeling_rope_utils._compute_yarn_parameters)."""
    import math
    factor = scaling["factor"]
    af = scaling.get("attention_factor")
    if af is not None:
        return float(af)

    def g(scale: float, m: float = 1.0) -> float:
        return 1.0 if scale <= 1 else 0.1 * m * math.log(scale) + 1.0

    mscale = scaling.get("mscale")
    msd = scaling.get("mscale_all_dim")
    if mscale and msd:
        return float(g(factor, mscale) / g(factor, msd))
    return float(g(factor))


def yarn_inv_freq(head_dim: int, rope_theta: float, scaling: dict,
                  max_position_embeddings: int) -> tuple[jax.Array, float]:
    """YaRN NTK-by-parts inverse frequencies -> (inv_freq, attention
    factor). Mirrors transformers' modeling_rope_utils.
    _compute_yarn_parameters (the init the reference's
    DeepseekScalingRotaryEmbedding shares, vllm/model_executor/layers/
    rotary_embedding.py yarn_* helpers); the attention factor multiplies
    cos/sin downstream."""
    import math
    factor = scaling["factor"]
    orig = (scaling.get("original_max_position_embeddings")
            or max_position_embeddings)
    attention_factor = yarn_attention_factor(scaling)
    beta_fast = scaling.get("beta_fast") or 32
    beta_slow = scaling.get("beta_slow") or 1

    def corr_dim(num_rotations: float) -> float:
        return (head_dim * math.log(orig / (num_rotations * 2 * math.pi))
                ) / (2 * math.log(rope_theta))

    low, high = corr_dim(beta_fast), corr_dim(beta_slow)
    if scaling.get("truncate", True):
        low, high = math.floor(low), math.ceil(high)
    low, high = max(low, 0), min(high, head_dim - 1)
    if low == high:
        high += 0.001  # avoid the ramp singularity
    pos_freqs = rope_theta ** (
        jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim)
    extrap = 1.0 / pos_freqs
    interp = 1.0 / (factor * pos_freqs)
    ramp = jnp.clip(
        (jnp.arange(head_dim // 2, dtype=jnp.float32) - low) /
        (high - low), 0, 1)
    inv_freq = interp * ramp + extrap * (1 - ramp)
    return inv_freq, float(attention_factor)


def compute_rope_cos_sin_pairwise(
        positions: jax.Array, head_dim: int, rope_theta: float,
        rope_scaling: dict | None = None,
        max_position_embeddings: int = 4096,
) -> tuple[jax.Array, jax.Array]:
    """cos/sin [T, head_dim//2] for PAIRWISE (complex) rotary — the form
    DeepSeek MLA applies to its decoupled rope dims (HF
    modeling_deepseek_v2.apply_rotary_emb on freqs_cis; V3's
    de-interleave variant is score-equivalent because the same
    permutation hits q and k). YaRN scaling folds its attention factor
    into the returned tables, matching HF's freqs_cis * scaling."""
    rtype = (rope_scaling or {}).get(
        "rope_type", (rope_scaling or {}).get("type"))
    if rope_scaling and rtype == "yarn":
        inv_freq, att = yarn_inv_freq(head_dim, rope_theta, rope_scaling,
                                      max_position_embeddings)
    else:
        inv_freq = make_inv_freq(head_dim, rope_theta, rope_scaling)
        att = 1.0
    freqs = positions.astype(jnp.float32)[:, None] * inv_freq[None, :]
    return jnp.cos(freqs) * att, jnp.sin(freqs) * att


def apply_rope_pairwise(x: jax.Array, cos: jax.Array,
                        sin: jax.Array) -> jax.Array:
    """Rotate adjacent pairs (x[2i], x[2i+1]) of [T, heads, D] by the
    i-th angle — HF DeepSeek's complex-multiply rope."""
    T, H, D = x.shape
    xr = x.astype(jnp.float32).reshape(T, H, D // 2, 2)
    x0, x1 = xr[..., 0], xr[..., 1]
    c = cos[:, None, :]
    s = sin[:, None, :]
    out = jnp.stack([x0 * c - x1 * s, x0 * s + x1 * c], axis=-1)
    return out.reshape(T, H, D).astype(x.dtype)


def apply_rope_single(x: jax.Array, cos: jax.Array,
                      sin: jax.Array) -> jax.Array:
    """Rotate-half rope on one [T, heads, D] tensor (partial-rotary
    callers rope q and k slices independently)."""
    c = cos[:, None, :]
    s = sin[:, None, :]
    return (x * c + _rotate_half(x) * s).astype(x.dtype)
