"""Bamba: hybrid Mamba-2 / attention decoder (IBM Bamba family).

Reference surface: vllm/model_executor/models/bamba.py — Mamba-2 (SSD)
mixers on most layers, GQA attention with PARTIAL rotary embeddings on
the layers named by attn_layer_indices, a dense SwiGLU FFN on every
layer, hybrid cache groups sizing attention pages separately from SSM
state.

TPU design mirrors models/jamba.py (per-kind stacked parameter
subtrees, unrolled heterogeneous layer walk) with the Mamba-2 mixer of
models/mamba.py (segmented SSD scan, split x / B-C depthwise convs)
and llama-style partial rotary on the attention layers.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from vllm_distributed_tpu.models.common import (apply_rope_single,
                                                compute_rope_cos_sin,
                                                rms_norm, swiglu)
from vllm_distributed_tpu.models.jamba import JambaForCausalLM
from vllm_distributed_tpu.models.llama import MODEL_AXIS
from vllm_distributed_tpu.models.mamba import Mamba2ForCausalLM
from vllm_distributed_tpu.ops.attention import (paged_attention,
                                                write_kv_cache)
from vllm_distributed_tpu.ops.mamba import build_segment_info


class BambaForCausalLM(JambaForCausalLM):
    """Hybrid Mamba-2 / partial-rotary-attention stack."""

    @classmethod
    def configure_arch(cls, arch, hf) -> None:
        arch.stateful = True
        # Mamba-2 mixer geometry (names shared with models/mamba.py
        # Mamba2ForCausalLM._mixer).
        arch.ssm_state_size = hf.mamba_d_state
        arch.conv_kernel = hf.mamba_d_conv
        arch.d_inner = hf.mamba_expand * hf.hidden_size
        arch.num_ssm_heads = hf.mamba_n_heads
        arch.ssm_head_dim = getattr(
            hf, "mamba_d_head", arch.d_inner // hf.mamba_n_heads)
        arch.n_groups = getattr(hf, "mamba_n_groups", 1)
        arch.time_step_limit = tuple(
            getattr(hf, "time_step_limit", None)
            or (0.0, float("inf")))
        arch.use_conv_bias = bool(getattr(hf, "mamba_conv_bias", True))
        if getattr(hf, "mamba_proj_bias", False):
            raise ValueError(
                "Bamba mamba_proj_bias checkpoints are not supported")
        arch.use_bias = False
        # Attention layer set + partial rotary.
        idx = getattr(hf, "attn_layer_indices", None) or []
        arch.attn_indices = tuple(idx)
        factor = getattr(hf, "partial_rotary_factor", None) or 1.0
        arch.rotary_dim = int(arch.head_dim * factor)
        arch.num_experts = 0
        if not hasattr(arch, "state_slots"):
            arch.state_slots = 0

    def _is_attn(self, i: int) -> bool:
        return i in self.cfg.attn_indices

    def _is_moe(self, i: int) -> bool:
        return False

    # ------------------------------------------------------------------
    def param_specs(self) -> dict:
        c = self.cfg
        col = P(None, None, MODEL_AXIS)
        row = P(None, MODEL_AXIS, None)
        layer = {
            "a_ln": P(None, None),
            "a_wq": col, "a_wk": col, "a_wv": col, "a_wo": row,
            "a_pre_ln": P(None, None),
            "a_gate": col, "a_up": col, "a_down": row,
            "m_norm": P(None, None),
            "m_gated_norm": P(None, MODEL_AXIS),
            "m_in_gate": col, "m_in_x": col,
            "m_in_bc": P(None, None, None),
            "m_in_dt": col,
            "m_conv_x_w": col,
            "m_conv_bc_w": P(None, None, None),
            "m_dt_bias": P(None, MODEL_AXIS),
            "m_A_log": P(None, MODEL_AXIS),
            "m_D": P(None, MODEL_AXIS),
            "m_out_proj": row,
            "m_pre_ln": P(None, None),
            "m_gate": col, "m_up": col, "m_down": row,
        }
        if c.use_conv_bias:
            layer["m_conv_x_b"] = P(None, MODEL_AXIS)
            layer["m_conv_bc_b"] = P(None, None)
        return {
            "embed": P(None, None),
            "layers": layer,
            "final_ln": P(None, ),
            "lm_head": P(None, MODEL_AXIS),
        }

    def init_params(self, rng: jax.Array, scale: float = 0.02) -> dict:
        c = self.cfg
        H, I = c.hidden_size, c.intermediate_size
        Di, N, K = c.d_inner, c.ssm_state_size, c.conv_kernel
        Hm, G = c.num_ssm_heads, c.n_groups
        La, Lm = len(self._attn_layers), len(self._mamba_layers)
        Dq = c.num_q_heads * c.head_dim
        Dkv = c.total_kv_heads * c.head_dim
        keys = iter(jax.random.split(rng, 24))

        def norm(key, shape):
            return (scale * jax.random.normal(key, shape,
                                              jnp.float32)).astype(c.dtype)

        layers = {
            "a_ln": jnp.ones((La, H), c.dtype),
            "a_wq": norm(next(keys), (La, H, Dq)),
            "a_wk": norm(next(keys), (La, H, Dkv)),
            "a_wv": norm(next(keys), (La, H, Dkv)),
            "a_wo": norm(next(keys), (La, Dq, H)),
            "a_pre_ln": jnp.ones((La, H), c.dtype),
            "a_gate": norm(next(keys), (La, H, I)),
            "a_up": norm(next(keys), (La, H, I)),
            "a_down": norm(next(keys), (La, I, H)),
            "m_norm": jnp.ones((Lm, H), c.dtype),
            "m_gated_norm": jnp.ones((Lm, Di), c.dtype),
            "m_in_gate": norm(next(keys), (Lm, H, Di)),
            "m_in_x": norm(next(keys), (Lm, H, Di)),
            "m_in_bc": norm(next(keys), (Lm, H, 2 * G * N)),
            "m_in_dt": norm(next(keys), (Lm, H, Hm)),
            "m_conv_x_w": norm(next(keys), (Lm, K, Di)),
            "m_conv_bc_w": norm(next(keys), (Lm, K, 2 * G * N)),
            "m_dt_bias": jnp.zeros((Lm, Hm), jnp.float32),
            "m_A_log": jnp.broadcast_to(
                jnp.log(jnp.arange(1, Hm + 1, dtype=jnp.float32)),
                (Lm, Hm)),
            "m_D": jnp.ones((Lm, Hm), jnp.float32),
            "m_out_proj": norm(next(keys), (Lm, Di, H)),
            "m_pre_ln": jnp.ones((Lm, H), c.dtype),
            "m_gate": norm(next(keys), (Lm, H, I)),
            "m_up": norm(next(keys), (Lm, H, I)),
            "m_down": norm(next(keys), (Lm, I, H)),
        }
        if c.use_conv_bias:
            layers["m_conv_x_b"] = jnp.zeros((Lm, Di), c.dtype)
            layers["m_conv_bc_b"] = jnp.zeros((Lm, 2 * G * N), c.dtype)
        embed = norm(next(keys), (c.vocab_size, H))
        return {
            "embed": embed,
            "layers": layers,
            "final_ln": jnp.ones((H, ), c.dtype),
            "lm_head": (embed.T if c.tie_word_embeddings else norm(
                next(keys), (H, c.vocab_size))),
        }

    def params_from_hf_state_dict(self, tensors: dict,
                                  prefix: str = "model") -> dict:
        c = self.cfg
        Di = c.d_inner
        GN2 = 2 * c.n_groups * c.ssm_state_size

        def t(name):
            return np.asarray(tensors[name])

        def stack(ids, fmt, f=lambda a: a, dtype=None):
            return jnp.asarray(np.stack(
                [f(t(fmt.format(i))) for i in ids])).astype(
                    dtype or c.dtype)

        A, M = self._attn_layers, self._mamba_layers
        ly = prefix + ".layers.{}."
        layers = {
            "a_ln": stack(A, ly + "input_layernorm.weight"),
            "a_wq": stack(A, ly + "self_attn.q_proj.weight",
                          lambda a: a.T),
            "a_wk": stack(A, ly + "self_attn.k_proj.weight",
                          lambda a: a.T),
            "a_wv": stack(A, ly + "self_attn.v_proj.weight",
                          lambda a: a.T),
            "a_wo": stack(A, ly + "self_attn.o_proj.weight",
                          lambda a: a.T),
            "a_pre_ln": stack(A, ly + "pre_ff_layernorm.weight"),
            "a_gate": stack(A, ly + "feed_forward.gate_proj.weight",
                            lambda a: a.T),
            "a_up": stack(A, ly + "feed_forward.up_proj.weight",
                          lambda a: a.T),
            "a_down": stack(A, ly + "feed_forward.down_proj.weight",
                            lambda a: a.T),
            "m_norm": stack(M, ly + "input_layernorm.weight"),
            "m_gated_norm": stack(M, ly + "mamba.norm.weight"),
            "m_in_gate": stack(M, ly + "mamba.in_proj.weight",
                               lambda a: a[:Di].T),
            "m_in_x": stack(M, ly + "mamba.in_proj.weight",
                            lambda a: a[Di:2 * Di].T),
            "m_in_bc": stack(M, ly + "mamba.in_proj.weight",
                             lambda a: a[2 * Di:2 * Di + GN2].T),
            "m_in_dt": stack(M, ly + "mamba.in_proj.weight",
                             lambda a: a[2 * Di + GN2:].T),
            "m_conv_x_w": stack(M, ly + "mamba.conv1d.weight",
                                lambda a: a[:Di, 0, :].T),
            "m_conv_bc_w": stack(M, ly + "mamba.conv1d.weight",
                                 lambda a: a[Di:, 0, :].T),
            "m_dt_bias": stack(M, ly + "mamba.dt_bias",
                               dtype=jnp.float32),
            "m_A_log": stack(M, ly + "mamba.A_log", dtype=jnp.float32),
            "m_D": stack(M, ly + "mamba.D", dtype=jnp.float32),
            "m_out_proj": stack(M, ly + "mamba.out_proj.weight",
                                lambda a: a.T),
            "m_pre_ln": stack(M, ly + "pre_ff_layernorm.weight"),
            "m_gate": stack(M, ly + "feed_forward.gate_proj.weight",
                            lambda a: a.T),
            "m_up": stack(M, ly + "feed_forward.up_proj.weight",
                          lambda a: a.T),
            "m_down": stack(M, ly + "feed_forward.down_proj.weight",
                            lambda a: a.T),
        }
        if c.use_conv_bias:
            layers["m_conv_x_b"] = stack(M, ly + "mamba.conv1d.bias",
                                         lambda a: a[:Di])
            layers["m_conv_bc_b"] = stack(M, ly + "mamba.conv1d.bias",
                                          lambda a: a[Di:])
        if c.num_kv_head_replicas > 1:
            from vllm_distributed_tpu.models.llama import \
                _replicate_kv_heads
            for name in ("a_wk", "a_wv"):
                layers[name] = _replicate_kv_heads(
                    layers[name], c.num_kv_heads, c.num_kv_head_replicas)
        embed = jnp.asarray(t(prefix + ".embed_tokens.weight")).astype(
            c.dtype)
        if c.tie_word_embeddings or "lm_head.weight" not in tensors:
            lm_head = embed.T
        else:
            lm_head = jnp.asarray(t("lm_head.weight")).T.astype(c.dtype)
        return {
            "embed": embed,
            "layers": layers,
            "final_ln": jnp.asarray(
                t(prefix + ".final_layernorm.weight")).astype(c.dtype),
            "lm_head": lm_head,
        }

    # ------------------------------------------------------------------
    # state_shapes() (snapshot-pool geometry for core/state_cache.py)
    # is inherited from Jamba: mamba-stack depth with THIS override's
    # Mamba-2 arrays (conv + conv_bc + ssm), so hybrid Mamba-2
    # checkpoints snapshot all three state tensors coherently.
    def _state_shapes(self, depth: int) -> dict:
        # Must match the Mamba-2 mixer's state layout exactly: delegate
        # to the single source of truth in models/mamba.py.
        return Mamba2ForCausalLM._state_shapes(self, depth)

    def kv_cache_specs(self) -> dict:
        # Paged K/V specs from the hybrid base + Mamba-2 state specs.
        return {**JambaForCausalLM.kv_cache_specs(self),
                **Mamba2ForCausalLM.kv_cache_specs(self)}

    # ------------------------------------------------------------------
    def run_layers(
        self,
        layer_params: dict,
        kv_caches: dict,
        hidden: jax.Array,
        batch,
        first_layer: int = 0,
    ) -> tuple[jax.Array, dict]:
        c = self.cfg
        T = hidden.shape[0]
        seg = build_segment_info(batch, kv_caches["ssm"].shape[1] - 1)
        sm_scale = c.head_dim**-0.5
        rd = c.rotary_dim or c.head_dim
        cos, sin = compute_rope_cos_sin(batch.positions, rd,
                                        c.rope_theta, c.rope_scaling,
                                        dtype=jnp.float32)

        def rope(x):
            x32 = x.astype(jnp.float32)
            rot = apply_rope_single(x32[..., :rd], cos, sin)
            if rd == c.head_dim:
                return rot.astype(c.dtype)
            return jnp.concatenate([rot, x32[..., rd:]],
                                   axis=-1).astype(c.dtype)

        def sub(prefix, j):
            return {
                k[len(prefix):]: v[j]
                for k, v in layer_params.items() if k.startswith(prefix)
            }

        h = hidden
        k_all, v_all = kv_caches["k"], kv_caches["v"]
        conv_all = kv_caches["conv"]
        conv_bc_all = kv_caches["conv_bc"]
        ssm_all = kv_caches["ssm"]
        ai = mi = 0
        for i in range(c.num_layers):
            if self._is_attn(i):
                lp = sub("a_", ai)
                x = rms_norm(h, lp["ln"], c.rms_norm_eps)
                q = rope((x @ lp["wq"]).reshape(T, c.num_q_heads,
                                                c.head_dim))
                k = rope((x @ lp["wk"]).reshape(T, c.total_kv_heads,
                                                c.head_dim))
                v = (x @ lp["wv"]).reshape(T, c.total_kv_heads,
                                           c.head_dim)
                li = jnp.full((1, ), ai, jnp.int32)
                k_all, v_all = write_kv_cache(k_all, v_all, k, v, batch,
                                              li)
                attn = paged_attention(q, k_all, v_all, batch,
                                       sm_scale=sm_scale, layer=li,
                                       window=0)
                h = h + attn.reshape(T, -1) @ lp["wo"]
                x2 = rms_norm(h, lp["pre_ln"], c.rms_norm_eps)
                h = h + swiglu(x2, lp["gate"], lp["up"], lp["down"])
                ai += 1
            else:
                lp = sub("m_", mi)
                x = rms_norm(h, lp["norm"], c.rms_norm_eps)
                out, conv_new, conv_bc_new, ssm_new = \
                    Mamba2ForCausalLM._mixer(
                        self, lp, x, conv_all[mi], conv_bc_all[mi],
                        ssm_all[mi], seg)
                conv_all = conv_all.at[mi].set(conv_new)
                conv_bc_all = conv_bc_all.at[mi].set(conv_bc_new)
                ssm_all = ssm_all.at[mi].set(ssm_new)
                h = h + out
                x2 = rms_norm(h, lp["pre_ln"], c.rms_norm_eps)
                h = h + swiglu(x2, lp["gate"], lp["up"], lp["down"])
                mi += 1
        return h, {"k": k_all, "v": v_all, "conv": conv_all,
                   "conv_bc": conv_bc_all, "ssm": ssm_all}
