"""GGUF checkpoint loading (reference:
vllm/model_executor/model_loader/gguf_loader.py — vLLM mounts GGUF
files through gguf-py and torch dequant kernels; here the format is
parsed directly and dequantized host-side into the standard fp load
path, like the GPTQ/AWQ loaders).

Scope: GGUF v3, llama-family architecture, tensor types F32 / F16 /
BF16 / Q8_0 (the lossless-ish formats; K-quants can be added as pure
numpy dequants later). The llama.cpp conversion permutes q/k
projection rows for GGML's interleaved-rope convention
(convert_hf_to_gguf.py ``permute``); loading inverts it so weights
match the HF layout the model code expects.

A minimal writer (``write_gguf``) exists for tests: it produces real
GGUF v3 bytes with llama.cpp tensor names and the q/k permute applied,
so the loader is exercised against the actual on-disk convention.
"""

import struct
from typing import Any, BinaryIO

import numpy as np

from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)

_MAGIC = b"GGUF"

# Metadata value types (ggml spec).
_U8, _I8, _U16, _I16, _U32, _I32, _F32, _BOOL, _STR, _ARR, _U64, _I64, \
    _F64 = range(13)
_SCALAR = {
    _U8: "<B", _I8: "<b", _U16: "<H", _I16: "<h", _U32: "<I",
    _I32: "<i", _F32: "<f", _BOOL: "<?", _U64: "<Q", _I64: "<q",
    _F64: "<d",
}

# Tensor dtypes.
_T_F32, _T_F16 = 0, 1
_T_Q8_0 = 8
_T_BF16 = 30


def _read(f: BinaryIO, fmt: str):
    size = struct.calcsize(fmt)
    return struct.unpack(fmt, f.read(size))[0]


def _read_str(f: BinaryIO) -> str:
    n = _read(f, "<Q")
    return f.read(n).decode("utf-8")


def _read_value(f: BinaryIO, vtype: int):
    if vtype in _SCALAR:
        return _read(f, _SCALAR[vtype])
    if vtype == _STR:
        return _read_str(f)
    if vtype == _ARR:
        etype = _read(f, "<I")
        count = _read(f, "<Q")
        return [_read_value(f, etype) for _ in range(count)]
    raise ValueError(f"unknown gguf metadata type {vtype}")


def _dequant(raw: bytes, dtype: int, shape: tuple[int, ...]) -> np.ndarray:
    n = int(np.prod(shape))
    if dtype == _T_F32:
        arr = np.frombuffer(raw, np.float32, n)
    elif dtype == _T_F16:
        arr = np.frombuffer(raw, np.float16, n).astype(np.float32)
    elif dtype == _T_BF16:
        import ml_dtypes
        arr = np.frombuffer(raw, ml_dtypes.bfloat16, n).astype(np.float32)
    elif dtype == _T_Q8_0:
        # Blocks of 32: f16 scale + 32 int8 payloads (34 bytes).
        nb = n // 32
        blocks = np.frombuffer(raw, np.uint8, nb * 34).reshape(nb, 34)
        scales = blocks[:, :2].copy().view(np.float16).astype(np.float32)
        q = blocks[:, 2:].view(np.int8).astype(np.float32)
        arr = (q * scales).reshape(-1)
    else:
        raise ValueError(f"unsupported gguf tensor type {dtype} "
                         "(supported: F32, F16, BF16, Q8_0)")
    return arr.reshape(shape)


def read_gguf(path: str) -> tuple[dict[str, Any], dict[str, np.ndarray]]:
    """(metadata, tensors). Tensor shapes come out numpy-style (GGML
    stores dims innermost-first; they are reversed here)."""
    with open(path, "rb") as f:
        if f.read(4) != _MAGIC:
            raise ValueError(f"{path} is not a GGUF file")
        version = _read(f, "<I")
        if version < 2:
            raise ValueError(f"GGUF v{version} is too old (need >= 2)")
        n_tensors = _read(f, "<Q")
        n_kv = _read(f, "<Q")
        meta: dict[str, Any] = {}
        for _ in range(n_kv):
            key = _read_str(f)
            vtype = _read(f, "<I")
            meta[key] = _read_value(f, vtype)
        infos = []
        for _ in range(n_tensors):
            name = _read_str(f)
            n_dims = _read(f, "<I")
            dims = [_read(f, "<Q") for _ in range(n_dims)]
            dtype = _read(f, "<I")
            offset = _read(f, "<Q")
            infos.append((name, tuple(reversed(dims)), dtype, offset))
        align = int(meta.get("general.alignment", 32))
        base = f.tell()
        base = (base + align - 1) // align * align
        tensors = {}
        for name, shape, dtype, offset in infos:
            f.seek(base + offset)
            nbytes = _tensor_nbytes(dtype, shape)
            tensors[name] = _dequant(f.read(nbytes), dtype, shape)
    return meta, tensors


def _tensor_nbytes(dtype: int, shape: tuple[int, ...]) -> int:
    n = int(np.prod(shape))
    if dtype == _T_F32:
        return n * 4
    if dtype in (_T_F16, _T_BF16):
        return n * 2
    if dtype == _T_Q8_0:
        return n // 32 * 34
    raise ValueError(f"unsupported gguf tensor type {dtype}")


def _permute_inv(w: np.ndarray, n_head: int) -> np.ndarray:
    """Invert llama.cpp's q/k row permute (convert_hf_to_gguf.py):
    forward = reshape(h, 2, d/2, in).swapaxes(1, 2).reshape."""
    out = w.shape[0]
    d = out // n_head
    return (w.reshape(n_head, d // 2, 2, *w.shape[1:])
            .swapaxes(1, 2).reshape(w.shape))


def permute_qk(w: np.ndarray, n_head: int) -> np.ndarray:
    """llama.cpp's forward permute (used by the test writer)."""
    out = w.shape[0]
    d = out // n_head
    return (w.reshape(n_head, 2, d // 2, *w.shape[1:])
            .swapaxes(1, 2).reshape(w.shape))


_DIRECT = {
    "token_embd.weight": "model.embed_tokens.weight",
    "output_norm.weight": "model.norm.weight",
    "output.weight": "lm_head.weight",
}
_LAYER = {
    "attn_norm.weight": "input_layernorm.weight",
    "attn_q.weight": "self_attn.q_proj.weight",
    "attn_k.weight": "self_attn.k_proj.weight",
    "attn_v.weight": "self_attn.v_proj.weight",
    "attn_output.weight": "self_attn.o_proj.weight",
    "ffn_gate.weight": "mlp.gate_proj.weight",
    "ffn_up.weight": "mlp.up_proj.weight",
    "ffn_down.weight": "mlp.down_proj.weight",
    "ffn_norm.weight": "post_attention_layernorm.weight",
}


def gguf_to_hf_state_dict(meta: dict,
                          tensors: dict[str, np.ndarray]) -> dict:
    """llama.cpp tensor names -> HF names, q/k permute inverted."""
    n_head = int(meta["llama.attention.head_count"])
    n_kv = int(meta.get("llama.attention.head_count_kv", n_head))
    out = {}
    for name, arr in tensors.items():
        if name in _DIRECT:
            out[_DIRECT[name]] = arr
            continue
        if not name.startswith("blk."):
            logger.warning("gguf: skipping unknown tensor %r", name)
            continue
        _, idx, rest = name.split(".", 2)
        hf_suffix = _LAYER.get(rest)
        if hf_suffix is None:
            logger.warning("gguf: skipping unknown tensor %r", name)
            continue
        if rest == "attn_q.weight":
            arr = _permute_inv(arr, n_head)
        elif rest == "attn_k.weight":
            arr = _permute_inv(arr, n_kv)
        out[f"model.layers.{idx}.{hf_suffix}"] = arr
    if "lm_head.weight" not in out and "model.embed_tokens.weight" in out:
        out["lm_head.weight"] = out["model.embed_tokens.weight"]
    return out


def hf_config_dict_from_gguf(meta: dict,
                             tensors: dict[str, np.ndarray]) -> dict:
    """LlamaConfig kwargs from GGUF metadata (reference: the config
    extraction of gguf_loader.py)."""
    H = int(meta["llama.embedding_length"])
    heads = int(meta["llama.attention.head_count"])
    return dict(
        architectures=["LlamaForCausalLM"],
        model_type="llama",
        vocab_size=int(tensors["token_embd.weight"].shape[0]),
        hidden_size=H,
        intermediate_size=int(meta["llama.feed_forward_length"]),
        num_hidden_layers=int(meta["llama.block_count"]),
        num_attention_heads=heads,
        num_key_value_heads=int(
            meta.get("llama.attention.head_count_kv", heads)),
        max_position_embeddings=int(
            meta.get("llama.context_length", 2048)),
        rms_norm_eps=float(
            meta.get("llama.attention.layer_norm_rms_epsilon", 1e-5)),
        rope_theta=float(meta.get("llama.rope.freq_base", 10000.0)),
        tie_word_embeddings="output.weight" not in tensors,
    )


# ---------------------------------------------------------------------------
# Minimal writer (tests): real GGUF v3 bytes from an HF llama state dict
# ---------------------------------------------------------------------------

def _write_str(f: BinaryIO, s: str) -> None:
    b = s.encode("utf-8")
    f.write(struct.pack("<Q", len(b)) + b)


def _kv(f: BinaryIO, key: str, vtype: int, value) -> None:
    _write_str(f, key)
    f.write(struct.pack("<I", vtype))
    if vtype in _SCALAR:
        f.write(struct.pack(_SCALAR[vtype], value))
    elif vtype == _STR:
        _write_str(f, value)
    else:
        raise ValueError(vtype)


def write_gguf(path: str, hf_config, state_dict: dict,
               quant: str = "f32") -> None:
    """HF llama tensors -> a GGUF v3 file with llama.cpp naming and the
    q/k permute applied (what convert_hf_to_gguf.py emits)."""
    inv_layer = {v: k for k, v in _LAYER.items()}
    inv_direct = {v: k for k, v in _DIRECT.items()}
    n_head = hf_config.num_attention_heads
    n_kv = hf_config.num_key_value_heads

    entries = []
    for name, w in state_dict.items():
        arr = np.asarray(w, np.float32)
        if name in inv_direct:
            gname = inv_direct[name]
        elif name.startswith("model.layers."):
            _m, _l, idx, rest = name.split(".", 3)
            suffix = inv_layer.get(rest)
            if suffix is None:
                continue
            if rest == "self_attn.q_proj.weight":
                arr = permute_qk(arr, n_head)
            elif rest == "self_attn.k_proj.weight":
                arr = permute_qk(arr, n_kv)
            gname = f"blk.{idx}.{suffix}"
        else:
            continue
        if quant == "q8_0" and arr.ndim == 2 and arr.size % 32 == 0:
            flat = arr.reshape(-1, 32)
            scale = (np.abs(flat).max(axis=1, keepdims=True) /
                     127.0).astype(np.float32)
            scale = np.maximum(scale, 1e-8)
            q = np.clip(np.round(flat / scale), -127,
                        127).astype(np.int8)
            payload = np.concatenate(
                [scale.astype(np.float16).view(np.uint8),
                 q.view(np.uint8)], axis=1).tobytes()
            entries.append((gname, arr.shape, _T_Q8_0, payload))
        else:
            entries.append((gname, arr.shape, _T_F32, arr.tobytes()))

    meta = [
        ("general.architecture", _STR, "llama"),
        ("llama.embedding_length", _U32, hf_config.hidden_size),
        ("llama.block_count", _U32, hf_config.num_hidden_layers),
        ("llama.feed_forward_length", _U32, hf_config.intermediate_size),
        ("llama.attention.head_count", _U32, n_head),
        ("llama.attention.head_count_kv", _U32, n_kv),
        ("llama.attention.layer_norm_rms_epsilon", _F32,
         hf_config.rms_norm_eps),
        ("llama.rope.freq_base", _F32,
         getattr(hf_config, "rope_theta", 10000.0)),
        ("llama.context_length", _U32,
         hf_config.max_position_embeddings),
        ("general.alignment", _U32, 32),
    ]

    with open(path, "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<I", 3))
        f.write(struct.pack("<Q", len(entries)))
        f.write(struct.pack("<Q", len(meta)))
        for key, vtype, value in meta:
            _kv(f, key, vtype, value)
        offset = 0
        for gname, shape, dtype, payload in entries:
            _write_str(f, gname)
            f.write(struct.pack("<I", len(shape)))
            for d in reversed(shape):
                f.write(struct.pack("<Q", d))
            f.write(struct.pack("<I", dtype))
            f.write(struct.pack("<Q", offset))
            offset += (len(payload) + 31) // 32 * 32
        pos = f.tell()
        f.write(b"\x00" * ((pos + 31) // 32 * 32 - pos))
        for _gname, _shape, _dtype, payload in entries:
            f.write(payload)
            pad = (len(payload) + 31) // 32 * 32 - len(payload)
            f.write(b"\x00" * pad)
