"""Weight loading (reference: vllm/model_executor/model_loader/ — default
safetensors streaming loader, dummy_loader.py for perf tests, tpu.py).

Loads HF checkpoints from a local directory (safetensors shards or a
pytorch_model.bin fallback) into the stacked JAX parameter tree, placing
shards directly with their NamedShardings so each device only materializes
its slice (the GSPMD analogue of the reference's per-rank weight_loader
callbacks on ColumnParallelLinear et al.).
"""

import glob
import os
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding

from vllm_distributed_tpu.config import EngineConfig
from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.models.llama import LlamaArchConfig
from vllm_distributed_tpu.models.registry import resolve_architecture

logger = init_logger(__name__)


def _dtype_from_str(name: str):
    return {
        "bfloat16": jnp.bfloat16,
        "float16": jnp.float16,
        "float32": jnp.float32,
    }[name]


def load_hf_state_dict(model_path: str,
                       prefixes: tuple = ()) -> dict[str, np.ndarray]:
    """Read a local HF checkpoint into numpy; with ``prefixes``, only
    tensors whose name starts with one of them (partial reads keep the
    vision-tower load off the full-checkpoint path)."""

    def want(name: str) -> bool:
        return not prefixes or name.startswith(prefixes)

    if model_path.endswith(".gguf"):
        # GGUF single-file checkpoints (reference: gguf_loader.py);
        # dequantized host-side into the standard fp path.
        from vllm_distributed_tpu.models.gguf import (
            gguf_to_hf_state_dict, read_gguf)
        meta, raw = read_gguf(model_path)
        return {k: v for k, v in
                gguf_to_hf_state_dict(meta, raw).items() if want(k)}

    st_files = sorted(glob.glob(os.path.join(model_path, "*.safetensors")))
    tensors: dict[str, np.ndarray] = {}
    if st_files:
        from safetensors import safe_open
        for path in st_files:
            with safe_open(path, framework="np") as f:
                for name in f.keys():
                    if want(name):
                        tensors[name] = f.get_tensor(name)
        return tensors
    bin_path = os.path.join(model_path, "pytorch_model.bin")
    if os.path.exists(bin_path):
        import torch
        sd = torch.load(bin_path, map_location="cpu", weights_only=True)
        return {k: v.float().numpy() for k, v in sd.items() if want(k)}
    raise FileNotFoundError(
        f"no safetensors/pytorch_model.bin under {model_path}")


def get_model(config: EngineConfig, mesh,
              shard: bool = True) -> tuple[Any, dict]:
    """Build the model class for the config and return (model, params) with
    params placed on the mesh according to the model's PartitionSpecs.
    ``shard=False`` returns host-resident params (the pipeline-parallel
    runner slices layers per stage and places each slice itself)."""
    hf_config = config.model_config.maybe_load_hf_config()
    model_cls = resolve_architecture(hf_config)
    dtype = _dtype_from_str(config.model_config.dtype)
    arch = LlamaArchConfig.from_hf_config(
        model_cls.arch_config_source(hf_config), dtype=dtype)
    model_cls.configure_arch(arch, hf_config)
    arch.expert_parallel = config.parallel_config.enable_expert_parallel
    if getattr(arch, "dense_prefix", 0):
        if config.parallel_config.pipeline_parallel_size > 1:
            raise ValueError(
                "mixed dense/sparse MoE layouts are not wired for "
                "pipeline parallelism (stage slicing assumes one "
                "uniform layer stack)")
        if config.lora_config.enable_lora:
            raise ValueError(
                "LoRA for mixed dense/sparse MoE layouts is not wired "
                "(adapter buffers assume one uniform layer stack)")
    if (config.parallel_config.enable_sequence_parallel
            and config.parallel_config.token_parallel_size > 1):
        raise ValueError(
            "sequence parallelism under token parallelism is not wired "
            "(the TKNP attention shard_maps assume token-replicated "
            "activations); disable one of the two")
    if (config.parallel_config.enable_sequence_parallel
            and config.parallel_config.pipeline_parallel_size > 1):
        raise ValueError(
            "sequence parallelism under pipeline parallelism is not "
            "wired (the SP constraint binds the full mesh, but PP "
            "stages jit over per-stage sub-meshes); disable one")
    arch.sequence_parallel = (
        config.parallel_config.enable_sequence_parallel
        and config.parallel_config.tensor_parallel_size > 1)
    arch.quantization = config.model_config.quantization
    qcfg = getattr(hf_config, "quantization_config", None)
    if qcfg is not None:
        get = (qcfg.get if isinstance(qcfg, dict)
               else lambda k, d=None: getattr(qcfg, k, d))
        gs = int(get("group_size", get("q_group_size", 0)) or 0)
        if gs > 0:
            # int4g reuses the checkpoint's own group lattice so the
            # re-quantization after the load-time dequant is lossless.
            arch.quant_group_size = gs
    kv_dtype = config.cache_config.cache_dtype
    if kv_dtype not in ("auto", None):
        if kv_dtype not in ("fp8", "fp8_e4m3", "fp8_e5m2"):
            raise ValueError(
                f"unsupported kv cache dtype {kv_dtype!r} "
                "(supported: auto, fp8, fp8_e4m3, fp8_e5m2)")
        if (getattr(model_cls, "STATEFUL", False)
                or getattr(model_cls, "ENCODER_ONLY", False)
                or getattr(arch, "mla", False)):
            raise ValueError(
                "--kv-cache-dtype fp8 is wired for standard paged K/V "
                "only (SSM state rows / MLA latent pages / encoder "
                "models keep the model dtype); drop the flag")
        if config.kv_transfer_config.kv_connector:
            raise ValueError(
                "--kv-cache-dtype fp8 with KV transfer is not wired "
                "(the connectors' wire layout carries model-dtype "
                "pages); drop one")
        if config.parallel_config.token_parallel_size > 1:
            raise ValueError(
                "--kv-cache-dtype fp8 under token parallelism is not "
                "wired (the per-rank attention path has no fp8 "
                "dequant); drop one")
        arch.kv_cache_dtype = (jnp.float8_e5m2
                               if kv_dtype == "fp8_e5m2"
                               else jnp.float8_e4m3fn)
        logger.warning(
            "fp8 KV cache: attention and cache writes run the XLA "
            "path (the Pallas kernels' fp8 dequant is a follow-up) — "
            "halved KV HBM, some per-step throughput cost on TPU")
    if arch.quantization == "w8a8" and getattr(arch, "num_experts", 0):
        # The MoE expert dots (the dominant FLOPs) run through
        # ragged_dot/shard_map paths that dequantize weights (w8a16);
        # serving "w8a8" there would silently not apply where its
        # benefit lies — refuse instead.
        raise ValueError(
            "w8a8 is not wired for MoE expert layers yet; use "
            "--quantization int8 (weight-only) for MoE models")
    if getattr(arch, "moe_bias", False) and (
            config.parallel_config.enable_expert_parallel
            or config.parallel_config.num_redundant_experts):
        # The EP all-to-all / EPLB paths run the plain SwiGLU expert
        # kernels without per-expert biases or the clamped GLU
        # (gpt-oss); serving through them would be silently wrong.
        raise ValueError(
            "expert parallelism / EPLB for biased-expert MoE (gpt-oss) "
            "is not wired yet; disable enable_expert_parallel / "
            "num_redundant_experts")
    if arch.num_experts and config.parallel_config.num_redundant_experts:
        arch.num_physical_experts = (
            arch.num_experts +
            config.parallel_config.num_redundant_experts)
    if arch.num_experts and arch.expert_parallel:
        ep = config.parallel_config.tensor_parallel_size
        arch.expert_parallel_ranks = ep
        physical = arch.num_physical_experts or arch.num_experts
        if physical % ep != 0:
            raise ValueError(
                f"expert parallelism needs the physical expert count "
                f"({physical} = {arch.num_experts} experts + "
                f"{physical - arch.num_experts} redundant) divisible by "
                f"tensor_parallel_size={ep}")
    if config.lora_config.enable_lora:
        arch.max_loras = config.lora_config.max_loras
        arch.max_lora_rank = config.lora_config.max_lora_rank
    if getattr(arch, "stateful", False):
        # Stateful (SSM) families: one state row per schedulable request
        # (the TPU form of the reference's MambaSpec one-block-per-
        # request cache, v1/kv_cache_interface.py).
        arch.state_slots = config.scheduler_config.max_num_seqs
        if config.speculative_config.num_speculative_tokens:
            # Draft rejection rolls num_computed_tokens back, but a
            # recurrence's state row cannot rewind past verified tokens.
            raise ValueError(
                "speculative decoding over stateful (SSM) models is not "
                "wired (rejected drafts cannot rewind recurrence state); "
                "disable speculative decoding")
        if config.kv_transfer_config.kv_connector:
            raise ValueError(
                "KV transfer for stateful (SSM) models is not wired "
                "(their state lives in per-request rows, not pages); "
                "drop the kv-transfer config")
        if config.parallel_config.token_parallel_size > 1:
            raise ValueError(
                "token parallelism over stateful (SSM) models is not "
                "wired (state rows are not partitioned per rank); "
                "disable one")
        if config.parallel_config.pipeline_parallel_size > 1:
            raise ValueError(
                "pipeline parallelism over stateful (SSM) models is "
                "not wired (hybrid per-kind stacks don't slice per "
                "stage); disable one")
        if config.parallel_config.enable_expert_parallel:
            raise ValueError(
                "expert parallelism over stateful hybrid models is not "
                "wired; disable enable_expert_parallel")
        if config.parallel_config.num_redundant_experts:
            raise ValueError(
                "EPLB redundant experts over stateful hybrid models "
                "are not wired; drop num_redundant_experts")
    if arch.pos_embedding == "learned":
        capacity = arch.max_position_embeddings - arch.pos_offset
        if config.scheduler_config.max_model_len > capacity:
            # A clip would silently reuse the last table row past the
            # window (degenerate output, no error) — refuse instead.
            raise ValueError(
                f"max_model_len={config.scheduler_config.max_model_len} "
                f"exceeds the model's learned-position capacity "
                f"({capacity}); lower --max-model-len")
    if getattr(arch, "encoder_only", False):
        pc = config.parallel_config
        bad = []
        if pc.pipeline_parallel_size > 1:
            bad.append("pipeline parallelism")
        if pc.token_parallel_size > 1:
            bad.append("token parallelism")
        if pc.enable_sequence_parallel:
            bad.append("sequence parallelism")
        if config.lora_config.enable_lora:
            bad.append("LoRA")
        if config.speculative_config.num_speculative_tokens:
            bad.append("speculative decoding")
        if config.kv_transfer_config.kv_connector:
            bad.append("KV transfer")
        if bad:
            raise ValueError(
                f"encoder-only models do not compose with "
                f"{', '.join(bad)} (no KV cache, no decode steps); "
                f"drop those options")
    if ((arch.sliding_window or arch.window_pattern
         or arch.attn_logit_softcap or arch.alibi or arch.attn_sinks)
            and config.parallel_config.token_parallel_size > 1):
        raise ValueError(
            "sliding-window attention / attention logit soft-capping / "
            "ALiBi under token parallelism is not wired yet (the "
            "per-rank attention path carries none of these); serve "
            "this model without token parallelism")
    if getattr(arch, "mla", False):
        # MLA family intersections not wired this round; reject with
        # clear errors instead of silently mis-serving.
        if config.parallel_config.token_parallel_size > 1:
            raise ValueError(
                "MLA (DeepSeek) under token parallelism is not wired "
                "yet (per-rank latent page pools); disable one")
        if config.parallel_config.num_redundant_experts:
            raise ValueError(
                "EPLB redundant experts are not wired for the DeepSeek "
                "family yet")
        # TPLA (ops/mla.py): shard the latent cache over the TP axis so
        # the per-rank latent pool is ~1/TP the bytes. Decided ONCE here
        # (weights, cache layout and attention all key on it); VDT_TPLA=0
        # reverts wholesale to the replicated layout.
        from vllm_distributed_tpu import envs as _envs
        from vllm_distributed_tpu.ops.mla import tpla_applicable
        mla_tp = config.parallel_config.tensor_parallel_size
        arch.tpla_shards = 1
        if _envs.VDT_TPLA and mla_tp > 1:
            if config.parallel_config.pipeline_parallel_size > 1:
                logger.info(
                    "TPLA disabled under pipeline parallelism (stage "
                    "sub-meshes don't carry the latent shard_map); "
                    "serving the replicated latent layout")
            elif not tpla_applicable(arch.kv_lora_rank, mla_tp):
                logger.warning(
                    "TPLA disabled: kv_lora_rank=%d does not divide "
                    "tensor_parallel_size=%d; serving the replicated "
                    "latent layout", arch.kv_lora_rank, mla_tp)
            else:
                arch.tpla_shards = mla_tp
                logger.info(
                    "TPLA: latent cache sharded %d ways over the TP "
                    "axis (%d lanes/rank of kv_lora_rank=%d + %d rope "
                    "lanes replicated)", mla_tp,
                    arch.kv_lora_rank // mla_tp, arch.kv_lora_rank,
                    arch.qk_rope_head_dim)
    # KV-head replication when TP exceeds the checkpoint's KV-head count
    # (reference: QKVParallelLinear kv replication, layers/linear.py):
    # repeat heads to the lcm so the kv-head dim divides the model axis.
    tp = config.parallel_config.tensor_parallel_size
    if getattr(arch, "mla", False):
        pass  # latent cache is MQA-shared; no KV-head replication
    elif arch.num_kv_heads % tp != 0:
        import math
        arch.num_kv_head_replicas = (
            math.lcm(arch.num_kv_heads, tp) // arch.num_kv_heads)
        logger.info(
            "replicating %d KV heads x%d to cover tensor_parallel_size=%d",
            arch.num_kv_heads, arch.num_kv_head_replicas, tp)
    # Fused transformer-block decode (ops/pallas_block.py): decided
    # ONCE here — the param tree (re-laid wqkv), the runner's dispatch
    # and the forward all key on it. VDT_BLOCK_FUSION=0 (the default)
    # reverts wholesale to the per-op mega-kernel path.
    arch.block_fusion = False
    from vllm_distributed_tpu import envs as _envs_bf
    if _envs_bf.VDT_BLOCK_FUSION:
        reason = block_fusion_ineligible_reason(arch, model_cls, config)
        if reason is None:
            arch.block_fusion = True
            logger.info(
                "block fusion ON: decode-only waves run one fused "
                "Pallas call per layer (VDT_BLOCK_FUSION=1)")
        else:
            logger.info(
                "block fusion requested but ineligible (%s); decode "
                "waves keep the per-op mega-kernel path", reason)
    model = model_cls(arch)

    # Performance-attribution plane (metrics/costmodel.py): the analytic
    # per-dispatch FLOP/byte model is priced ONCE here, from the final
    # arch shapes (post TPLA/fusion/quant decisions), and rides the arch
    # so every runner variant (single-program, PP stages) charges
    # dispatches against the same constants. VDT_PERF_ATTRIB=0 attaches
    # None — the runners' per-step charge degrades to one None check.
    from vllm_distributed_tpu.metrics.costmodel import resolve_cost_model
    arch.cost_model = resolve_cost_model(model, config, mesh=mesh)

    load_format = config.load_config.load_format
    model_path = config.model_config.model
    if load_format == "sharded_state":
        # Orbax tree written by save_sharded_state: already transposed,
        # stacked, replicated and quantized — restore host-side and let
        # the placement pass below shard it (reference:
        # model_loader/sharded_state_loader.py skipping the per-tensor
        # weight_loader work).
        import orbax.checkpoint as ocp
        ckpt_dir = config.load_config.sharded_state_path or model_path
        params = ocp.StandardCheckpointer().restore(
            os.path.abspath(ckpt_dir))
        logger.info("restored sharded state from %s", ckpt_dir)
        if (getattr(arch, "block_fusion", False)
                and "wqkv" not in params.get("layers", {})):
            # Tree saved under the per-op path: build the fused
            # projection now so VDT_BLOCK_FUSION=1 serves from any
            # sharded-state snapshot (the method also re-checks the
            # bias revoke).
            model._maybe_fuse_qkv(params["layers"])
        if not getattr(arch, "block_fusion", False):
            # The reverse direction: a snapshot SAVED under fusion,
            # reloaded with fusion off/revoked — drop the stale fused
            # weight so the tree matches param_specs() again.
            if isinstance(params.get("layers"), dict):
                params["layers"].pop("wqkv", None)
    elif load_format == "dummy" or (
            load_format == "auto" and not os.path.isdir(model_path)
            and not (model_path.endswith(".gguf")
                     and os.path.isfile(model_path))):
        if load_format != "dummy":
            logger.warning(
                "%s is not a local directory; using dummy weights "
                "(set load_format='safetensors' with a local path for "
                "real weights)", model_path)
        rng = jax.random.PRNGKey(config.model_config.seed)
        params = model.init_params(rng)
    else:
        tensors = load_hf_state_dict(model_path)
        from vllm_distributed_tpu.models.gptq import maybe_dequantize_gptq
        tensors = maybe_dequantize_gptq(tensors, hf_config,
                                        model_path)
        params = model.params_from_hf_state_dict(tensors)
        logger.info("loaded %d tensors from %s", len(tensors), model_path)

    # Quantize-on-load (reference: tpu_int8.py process_weights_after_
    # loading) before placement, so only int8 bytes hit device HBM.
    # Sharded-state trees were saved post-quantization already.
    if load_format != "sharded_state":
        params = model.quantize_params(params)

    if not shard:
        return model, params

    specs = model.param_specs()

    def place(x, spec):
        return jax.device_put(x, NamedSharding(mesh, spec))

    # Walk the params tree key-by-key so family-specific extras
    # (final_ln_b / lm_head_b biases, encoder embedding tables,
    # pooler/classifier heads) get their shardings too.
    def place_tree(p, s):
        if isinstance(p, dict):
            return {k: place_tree(v, s[k]) for k, v in p.items()}
        return place(p, s)

    params = place_tree(params, specs)
    return model, params


def block_fusion_ineligible_reason(arch, model_cls,
                                   config) -> Optional[str]:
    """Why the fused decode-block kernel (ops/pallas_block.py) cannot
    serve this (arch, parallel layout) — None when eligible. The kernel
    implements exactly the standard dense pre-norm gated Llama block
    (RMSNorm / fused QKV / full-head rope / paged KV / SwiGLU) on one
    chip; anything structurally different keeps the per-op path.
    Sliding window / softcap / ALiBi / sinks are NOT exclusions — they
    ride the kernel's per-layer statics + head-feature sidecar."""
    pc = config.parallel_config
    checks = (
        (pc.tensor_parallel_size > 1, "tensor parallelism"),
        (pc.pipeline_parallel_size > 1, "pipeline parallelism"),
        (pc.token_parallel_size > 1, "token parallelism"),
        (pc.enable_sequence_parallel, "sequence parallelism"),
        (getattr(model_cls, "ENCODER_ONLY", False), "encoder-only arch"),
        (getattr(model_cls, "CROSS_ATTENTION", False),
         "cross-attention arch"),
        (getattr(arch, "mla", False), "MLA latent cache"),
        (getattr(arch, "stateful", False), "stateful (SSM) layers"),
        (getattr(arch, "kv_cache_dtype", None) is not None,
         "fp8 KV cache"),
        (arch.num_experts > 0, "MoE layers"),
        (arch.dense_prefix > 0, "mixed dense/sparse stack"),
        (arch.quantization is not None, "weight quantization"),
        (arch.max_loras > 0, "LoRA adapters"),
        (not arch.pre_norm, "post-norm block"),
        (not arch.mlp_gated, "non-gated MLP"),
        (arch.norm_type != "rms", "non-RMS norms"),
        (arch.parallel_block, "parallel-residual block"),
        (arch.extra_layer_norms, "sandwich/post norms"),
        (arch.qk_norm or arch.qk_norm_full, "q/k norms"),
        (arch.attention_bias or arch.attention_out_bias
         or arch.mlp_bias, "projection biases"),
        (arch.qkv_clip is not None, "qkv clipping"),
        (arch.pos_embedding != "rope", "non-rope positions"),
        (arch.rotary_dim is not None
         and arch.rotary_dim != arch.head_dim, "partial rotary"),
        (arch.rope_interleaved, "pairwise rope"),
        (arch.mrope_section is not None, "M-RoPE"),
        (arch.nope_layers is not None, "NoPE layer mix"),
        (arch.rope_theta_local is not None, "per-layer rope bases"),
        (arch.residual_multiplier != 1.0, "residual multiplier"),
    )
    for bad, why in checks:
        if bad:
            return why
    from vllm_distributed_tpu.ops.attention import storage_head_dim
    if storage_head_dim(arch.head_dim) != arch.head_dim:
        return "lane-padded KV storage (head_dim % 128 != 0 on TPU)"
    return None


def resolve_encoder_only(model_config) -> bool:
    """True for encoder-only (BERT-family) archs: the worker swaps in
    the dense EncoderModelRunner and the scheduler disables chunked
    prefill + prefix caching (a bidirectional layer needs the whole
    sequence in one step; a cached page boundary is meaningless without
    causality). Reference: the pooling-model runner split of
    v1/worker/gpu_model_runner.py + models/bert.py."""
    try:
        hf_config = model_config.maybe_load_hf_config()
        model_cls = resolve_architecture(hf_config)
    except Exception:  # noqa: BLE001 - conservative
        return False
    return bool(getattr(model_cls, "ENCODER_ONLY", False))


def resolve_encoder_limits(model_config) -> "tuple[bool, Optional[int]]":
    """(is_cross_encoder, max_encodable_tokens) for encoder-only archs.

    Cross-encoder = checkpoint with a classification head ("score"
    pooling is only admissible there — a bad request must 400 at the
    front-end, never raise inside the engine step). The token bound is
    the position table minus the family's position offset (RoBERTa
    writes positions starting at padding_idx + 1 = 2, so a 514-row
    table only covers 512 tokens)."""
    try:
        hf_config = model_config.maybe_load_hf_config()
        model_cls = resolve_architecture(hf_config)
        if not getattr(model_cls, "ENCODER_ONLY", False):
            return False, None
        offset = int(getattr(model_cls, "POS_OFFSET", 0))
        max_pos = int(getattr(hf_config, "max_position_embeddings", 0))
    except Exception:  # noqa: BLE001 - conservative
        return False, None
    limit = max_pos - offset if max_pos else None
    return bool(getattr(model_cls, "CLASSIFY", False)), limit


def resolve_stateful(model_config) -> bool:
    """True when the model carries non-pageable per-request state (SSM
    layers): the scheduler must disable prefix caching — a cached page
    boundary is not a re-enterable point for a recurrence (the
    reference likewise disables prefix caching for mamba models)."""
    try:
        hf_config = model_config.maybe_load_hf_config()
        model_cls = resolve_architecture(hf_config)
    except Exception:  # noqa: BLE001 - conservative
        return False
    return bool(getattr(model_cls, "STATEFUL", False))


def resolve_state_snapshotable(model_config) -> bool:
    """True when the model's per-request state is SNAPSHOTABLE — it
    exposes ``state_shapes()`` (SSM conv/ssm rows, Mamba/Jamba/Bamba),
    so the state cache can checkpoint/restore it. STATEFUL alone is
    not enough: Whisper/BART are stateful (fixed cross-attention state
    rows, no prefix caching) but carry no re-enterable recurrence
    state — activating the snapshot pool for them crashes the runner
    at ``state_shapes`` and buys nothing."""
    try:
        hf_config = model_config.maybe_load_hf_config()
        model_cls = resolve_architecture(hf_config)
    except Exception:  # noqa: BLE001 - conservative
        return False
    return hasattr(model_cls, "state_shapes")


def resolve_state_only(model_config) -> bool:
    """True for pure-SSM stacks (Mamba family): pages carry no KV
    bytes, so a state snapshot alone is a complete resume point and the
    state cache skips the page-residency requirement hybrid stacks
    (Jamba/Bamba) need for coherent re-entry."""
    try:
        hf_config = model_config.maybe_load_hf_config()
        model_cls = resolve_architecture(hf_config)
    except Exception:  # noqa: BLE001 - conservative
        return False
    return bool(getattr(model_cls, "STATE_ONLY", False))


def resolve_free_window(model_config) -> Optional[int]:
    """Token window below which KV pages can be freed mid-request: the
    minimum layer window when EVERY attention layer is windowed, else
    None (any full-attention layer needs the whole history). Resolved
    through the same arch hooks get_model uses, so family overrides
    (Gemma2 alternating layouts, Qwen2 max_window_layers) are honored
    (reference: the per-group window specs of v1/kv_cache_interface.py
    SlidingWindowSpec)."""
    try:
        hf_config = model_config.maybe_load_hf_config()
        model_cls = resolve_architecture(hf_config)
        arch = LlamaArchConfig.from_hf_config(
            model_cls.arch_config_source(hf_config))
        model_cls.configure_arch(arch, hf_config)
    except Exception:  # noqa: BLE001 - conservative: no freeing
        return None
    if arch.window_pattern is not None:
        pattern = arch.window_pattern
        # Only a UNIFORM all-windowed pattern is safe to free against:
        # with unequal windows the larger-window layers still attend
        # pages the smaller window has left behind (freeing at
        # min(pattern) would hand live history to the pool). Mixed and
        # unequal layouts need per-group hybrid caches — not wired.
        if all(pattern) and len(set(pattern)) == 1:
            return pattern[0]
        return None
    return arch.sliding_window
