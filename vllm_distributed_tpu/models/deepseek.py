"""DeepSeek-V2/V3 family: Multi-head Latent Attention + DeepSeekMoE.

TPU-first re-design of the reference's DeepSeek support
(vllm/model_executor/models/deepseek_v2.py + the MLA backend family in
vllm/v1/attention/backends/mla/common.py and csrc/attention/mla/):

* **MLA** — the KV cache holds one compressed row per token (kv_c latent
  of width kv_lora_rank ++ the shared rope key k_pe) instead of per-head
  K/V. This implementation runs the ABSORBED form uniformly: W_UK folds
  into the query before attention and W_UV applies to the latent
  output after (common.py:96-120 `_forward_decode`), so attention is
  MQA over the latent cache (ops/mla.py) and the bucket lattice stays
  additive — no separate prefill/decode kernels.
* **DeepSeekMoE** — the Mixtral grouped-GEMM machinery (moe_dispatch)
  with DeepSeek gating on top: softmax scores with greedy or
  group-limited top-k and routed_scaling_factor (V2, HF 4.57 semantics),
  or sigmoid scores + e_score_correction_bias + top-2-sum group
  selection (V3 "noaux_tc"); plus ungated shared experts and the first
  ``first_k_dense_replace`` layers dense.

Parity target is transformers' DeepseekV2/V3 implementations (the V3
de-interleaved rope is score-equivalent to the V2 complex form because
the same permutation hits q and k; see models/common.py
apply_rope_pairwise).

Not wired in this round (rejected at load with clear errors): token
parallelism, LoRA, quantization, and EPLB redundancy for this family.
"""

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from vllm_distributed_tpu.models.common import (AttentionBatch,
                                                apply_rope_pairwise,
                                                compute_rope_cos_sin_pairwise,
                                                rms_norm)
from vllm_distributed_tpu.models.llama import (MODEL_AXIS, TOKEN_AXIS,
                                               LlamaForCausalLM)
from vllm_distributed_tpu.models.mixtral import MixtralForCausalLM
from vllm_distributed_tpu.ops.mla import (latent_attention,
                                          latent_shard_dim,
                                          latent_storage_dim,
                                          tpla_latent_attention,
                                          write_latent_cache,
                                          write_latent_cache_tpla)

_DENSE_KEYS = frozenset({"gate", "up", "down"})
_MOE_KEYS = frozenset({"router", "router_bias", "w_gate", "w_up", "w_down",
                       "shared_gate", "shared_up", "shared_down"})


class DeepseekV2ForCausalLM(MixtralForCausalLM):

    # Quantized / LoRA serving of the absorbed projections is follow-up
    # work; both are rejected at load for this family.
    QUANT_TARGETS = ()
    LORA_TARGETS = ()
    SCORING = "softmax"  # V3 overrides to sigmoid + correction bias

    @classmethod
    def configure_arch(cls, arch, hf) -> None:
        arch.mla = True
        arch.q_lora_rank = getattr(hf, "q_lora_rank", None)
        arch.kv_lora_rank = hf.kv_lora_rank
        arch.qk_nope_head_dim = hf.qk_nope_head_dim
        arch.qk_rope_head_dim = hf.qk_rope_head_dim
        arch.v_head_dim = hf.v_head_dim
        arch.max_position_embeddings = getattr(
            hf, "max_position_embeddings", 4096)
        arch.num_experts = getattr(hf, "n_routed_experts", 0) or 0
        arch.num_experts_per_tok = getattr(hf, "num_experts_per_tok", 1)
        arch.moe_intermediate_size = getattr(hf, "moe_intermediate_size",
                                             None)
        n_shared = getattr(hf, "n_shared_experts", None) or 0
        arch.shared_expert_intermediate_size = (
            n_shared * (arch.moe_intermediate_size or 0))
        arch.first_k_dense_replace = (
            getattr(hf, "first_k_dense_replace", 0)
            if arch.num_experts else arch.num_layers)
        arch.routed_scaling_factor = getattr(hf, "routed_scaling_factor",
                                             1.0)
        arch.topk_method = getattr(hf, "topk_method", "greedy")
        arch.n_group = getattr(hf, "n_group", 1) or 1
        arch.topk_group = getattr(hf, "topk_group", 1) or 1
        arch.norm_topk_prob = bool(getattr(hf, "norm_topk_prob", False))
        if getattr(hf, "moe_layer_freq", 1) not in (None, 1):
            raise ValueError("DeepSeek moe_layer_freq != 1 layouts are "
                             "not supported")
        if getattr(hf, "attention_bias", False):
            raise ValueError("DeepSeek attention_bias checkpoints are "
                             "not supported (no published model uses it)")

    # ------------------------------------------------------------------
    # Parameter layout
    # ------------------------------------------------------------------
    @property
    def tpla_shards(self) -> int:
        """TP shards of the latent cache (ops/mla.py TPLA layout); 1 =
        replicated (VDT_TPLA=0 / TP 1 / indivisible kv_lora_rank —
        models/loader.py decides once at load)."""
        return max(1, int(getattr(self.cfg, "tpla_shards", 1) or 1))

    @property
    def _n_dense(self) -> int:
        return min(self.cfg.first_k_dense_replace, self.cfg.num_layers)

    @property
    def _n_moe(self) -> int:
        return self.cfg.num_layers - self._n_dense

    def param_specs(self) -> dict:
        c = self.cfg
        if c.max_loras:
            raise ValueError("LoRA is not supported for the DeepSeek "
                             "family yet")
        specs = LlamaForCausalLM.param_specs(self)
        layer: dict = {
            "input_ln": P(None, None),
            "post_ln": P(None, None),
            # Latent projections: the down-projections and the shared
            # latent path are replicated (their outputs are per-token,
            # not per-head); the up-projections shard on the head dim —
            # or, under TPLA, on the LATENT dim (the paper's layout:
            # every rank runs all heads against its kv_lora_rank/TP
            # slice, so W_UK/W_UV shard where the cache does and the
            # absorbed ql comes out latent-sharded with no collective).
            "kv_a": P(None, None, None),
            "kv_a_ln": P(None, None),
        }
        if self.tpla_shards > 1:
            layer.update({
                "w_uk": P(None, MODEL_AXIS, None, None),
                "w_uv": P(None, MODEL_AXIS, None, None),
                # q projections and wo replicate under TPLA (all heads
                # on every rank; weight bytes are O(params), the latent
                # pool — the concurrency bottleneck — is what shards).
                "wo": P(None, None, None),
            })
        else:
            layer.update({
                "w_uk": P(None, None, MODEL_AXIS, None),
                "w_uv": P(None, None, MODEL_AXIS, None),
                "wo": P(None, MODEL_AXIS, None),
            })
        q_out = (P(None, None, None) if self.tpla_shards > 1
                 else P(None, None, MODEL_AXIS))
        if c.q_lora_rank:
            layer.update({
                "q_a": P(None, None, None),
                "q_a_ln": P(None, None),
                "q_b": q_out,
            })
        else:
            layer["wq"] = q_out
        if self._n_dense:
            layer.update({
                "gate": P(None, None, MODEL_AXIS),
                "up": P(None, None, MODEL_AXIS),
                "down": P(None, MODEL_AXIS, None),
            })
        if self._n_moe:
            layer["router"] = P(None, None, None)
            if self.SCORING == "sigmoid":
                layer["router_bias"] = P(None, None)
            if c.expert_parallel:
                ffn = P(None, MODEL_AXIS, None, None)
                layer.update({"w_gate": ffn, "w_up": ffn, "w_down": ffn})
            else:
                layer.update({
                    "w_gate": P(None, None, None, MODEL_AXIS),
                    "w_up": P(None, None, None, MODEL_AXIS),
                    "w_down": P(None, None, MODEL_AXIS, None),
                })
            if c.shared_expert_intermediate_size:
                layer.update({
                    "shared_gate": P(None, None, MODEL_AXIS),
                    "shared_up": P(None, None, MODEL_AXIS),
                    "shared_down": P(None, MODEL_AXIS, None),
                })
        specs["layers"] = layer
        return specs

    def slice_layer_params(self, layers: dict, start: int,
                           end: int) -> dict:
        """PP stage slicing with per-kind depths: attention tensors are
        stacked over all L layers, dense-MLP tensors over the first
        ``first_k_dense_replace`` and expert tensors over the rest."""
        fkd = self._n_dense
        ds, de = min(start, fkd), min(end, fkd)
        ms, me = max(start, fkd) - fkd, max(end, fkd) - fkd
        out = {}
        for k, v in layers.items():
            if k in _DENSE_KEYS:
                out[k] = v[ds:de]
            elif k in _MOE_KEYS:
                out[k] = v[ms:me]
            else:
                out[k] = v[start:end]
        return out

    def init_params(self, rng: jax.Array, scale: float = 0.02) -> dict:
        c = self.cfg
        L, H = c.num_layers, c.hidden_size
        N = c.num_q_heads
        Pn, R, V = c.qk_nope_head_dim, c.qk_rope_head_dim, c.v_head_dim
        Lkv = c.kv_lora_rank
        keys = iter(jax.random.split(rng, 24))

        def norm(shape):
            return (scale * jax.random.normal(next(keys), shape,
                                              jnp.float32)).astype(c.dtype)

        layer: dict = {
            "input_ln": jnp.ones((L, H), c.dtype),
            "post_ln": jnp.ones((L, H), c.dtype),
            "kv_a": norm((L, H, Lkv + R)),
            "kv_a_ln": jnp.ones((L, Lkv), c.dtype),
            "w_uk": norm((L, Lkv, N, Pn)),
            "w_uv": norm((L, Lkv, N, V)),
            "wo": norm((L, N * V, H)),
        }
        if c.q_lora_rank:
            layer.update({
                "q_a": norm((L, H, c.q_lora_rank)),
                "q_a_ln": jnp.ones((L, c.q_lora_rank), c.dtype),
                "q_b": norm((L, c.q_lora_rank, N * (Pn + R))),
            })
        else:
            layer["wq"] = norm((L, H, N * (Pn + R)))
        nd, nm = self._n_dense, self._n_moe
        if nd:
            I = c.intermediate_size
            layer.update({
                "gate": norm((nd, H, I)),
                "up": norm((nd, H, I)),
                "down": norm((nd, I, H)),
            })
        if nm:
            E = c.num_experts
            Im = c.moe_intermediate_size or c.intermediate_size
            layer.update({
                "router": norm((nm, H, E)),
                "w_gate": norm((nm, E, H, Im)),
                "w_up": norm((nm, E, H, Im)),
                "w_down": norm((nm, E, Im, H)),
            })
            if self.SCORING == "sigmoid":
                layer["router_bias"] = jnp.zeros((nm, E), jnp.float32)
            Is = c.shared_expert_intermediate_size
            if Is:
                layer.update({
                    "shared_gate": norm((nm, H, Is)),
                    "shared_up": norm((nm, H, Is)),
                    "shared_down": norm((nm, Is, H)),
                })
        embed = norm((c.vocab_size, H))
        return {
            "embed": embed,
            "layers": layer,
            "final_ln": jnp.ones((H, ), c.dtype),
            "lm_head": (embed.T if c.tie_word_embeddings else norm(
                (H, c.vocab_size))),
        }

    def params_from_hf_state_dict(self, tensors: dict[str, np.ndarray],
                                  ) -> dict:
        c = self.cfg
        L, H = c.num_layers, c.hidden_size
        N = c.num_q_heads
        Pn, R, V = c.qk_nope_head_dim, c.qk_rope_head_dim, c.v_head_dim
        Lkv = c.kv_lora_rank

        def t(name):
            return np.asarray(tensors[name])

        def stack(fmt, layers_range=range(L), transpose=True):
            mats = [t(fmt.format(i)) for i in layers_range]
            return jnp.asarray(
                np.stack([m.T if transpose else m for m in mats]),
                dtype=c.dtype)

        A = "model.layers.{}.self_attn."
        layer: dict = {
            "input_ln": stack("model.layers.{}.input_layernorm.weight",
                              transpose=False),
            "post_ln": stack(
                "model.layers.{}.post_attention_layernorm.weight",
                transpose=False),
            "kv_a": stack(A + "kv_a_proj_with_mqa.weight"),
            "kv_a_ln": stack(A + "kv_a_layernorm.weight",
                             transpose=False),
            "wo": stack(A + "o_proj.weight"),
        }
        # kv_b_proj [N*(P+V), Lkv] splits into the absorbed halves.
        uk, uv = [], []
        for i in range(L):
            kv_b = t(A.format(i) + "kv_b_proj.weight").reshape(
                N, Pn + V, Lkv)
            uk.append(kv_b[:, :Pn, :].transpose(2, 0, 1))  # [Lkv, N, P]
            uv.append(kv_b[:, Pn:, :].transpose(2, 0, 1))  # [Lkv, N, V]
        layer["w_uk"] = jnp.asarray(np.stack(uk), dtype=c.dtype)
        layer["w_uv"] = jnp.asarray(np.stack(uv), dtype=c.dtype)
        if c.q_lora_rank:
            layer.update({
                "q_a": stack(A + "q_a_proj.weight"),
                "q_a_ln": stack(A + "q_a_layernorm.weight",
                                transpose=False),
                "q_b": stack(A + "q_b_proj.weight"),
            })
        else:
            layer["wq"] = stack(A + "q_proj.weight")
        nd, nm = self._n_dense, self._n_moe
        M = "model.layers.{}.mlp."
        if nd:
            dense = range(nd)
            layer.update({
                "gate": stack(M + "gate_proj.weight", dense),
                "up": stack(M + "up_proj.weight", dense),
                "down": stack(M + "down_proj.weight", dense),
            })
        if nm:
            moe = range(nd, L)
            E = c.num_experts
            layer["router"] = stack(M + "gate.weight", moe)
            if self.SCORING == "sigmoid":
                layer["router_bias"] = jnp.asarray(np.stack([
                    t(M.format(i) + "gate.e_score_correction_bias")
                    for i in moe]), dtype=jnp.float32)

            def stack_experts(proj, transpose=True):
                per_layer = []
                for i in moe:
                    mats = [t(M.format(i) + f"experts.{e}.{proj}.weight")
                            for e in range(E)]
                    per_layer.append(np.stack(
                        [m.T if transpose else m for m in mats]))
                return jnp.asarray(np.stack(per_layer), dtype=c.dtype)

            layer["w_gate"] = stack_experts("gate_proj")
            layer["w_up"] = stack_experts("up_proj")
            layer["w_down"] = stack_experts("down_proj")
            if c.shared_expert_intermediate_size:
                layer.update({
                    "shared_gate": stack(
                        M + "shared_experts.gate_proj.weight", moe),
                    "shared_up": stack(
                        M + "shared_experts.up_proj.weight", moe),
                    "shared_down": stack(
                        M + "shared_experts.down_proj.weight", moe),
                })
        embed = jnp.asarray(t("model.embed_tokens.weight"), dtype=c.dtype)
        if c.tie_word_embeddings or "lm_head.weight" not in tensors:
            lm_head = embed.T
        else:
            lm_head = jnp.asarray(t("lm_head.weight").T, dtype=c.dtype)
        return {
            "embed": embed,
            "layers": layer,
            "final_ln": jnp.asarray(t("model.norm.weight"),
                                    dtype=c.dtype),
            "lm_head": lm_head,
        }

    # ------------------------------------------------------------------
    # KV cache: one latent row per token
    # ------------------------------------------------------------------
    def kv_cache_specs(self) -> dict:
        # Replicated layout: latent rows are shared by every head (MQA),
        # so the cache replicates over the model axis; pages shard over
        # the token axis like the standard cache. TPLA layout: the "c"
        # lanes shard over the model axis (each rank holds its
        # kv_lora_rank/TP slice of every row) and the rope sidecar "pe"
        # replicates.
        if self.tpla_shards > 1:
            return {"c": P(None, TOKEN_AXIS, None, MODEL_AXIS),
                    "pe": P(None, TOKEN_AXIS, None, None)}
        return {"c": P(None, TOKEN_AXIS, None, None)}

    def make_kv_caches(self, num_pages: int, page_size: int,
                       cache_dtype=None,
                       num_layers: Optional[int] = None) -> dict:
        c = self.cfg
        depth = num_layers if num_layers is not None else c.num_layers
        S = self.tpla_shards
        if S > 1:
            Cs = S * latent_shard_dim(c.kv_lora_rank, S)
            Rs = latent_storage_dim(c.qk_rope_head_dim, 0)
            dtype = cache_dtype or c.dtype
            return {
                "c": jnp.zeros((depth, num_pages, page_size, Cs), dtype),
                "pe": jnp.zeros((depth, num_pages, page_size, Rs), dtype),
            }
        Cs = latent_storage_dim(c.kv_lora_rank, c.qk_rope_head_dim)
        return {"c": jnp.zeros((depth, num_pages, page_size, Cs),
                               cache_dtype or c.dtype)}

    def kv_cache_page_bytes(self, page_size: int) -> int:
        """PER-RANK HBM bytes one page costs (what the worker divides a
        device's free HBM by). Replicated layout: the full latent row on
        every rank. TPLA: one kv_lora_rank/TP latent shard plus the
        replicated rope sidecar — ~1/TP the bytes, so ~TP x the pages
        fit the same per-device budget."""
        c = self.cfg
        S = self.tpla_shards
        if S > 1:
            lanes = (latent_shard_dim(c.kv_lora_rank, S) +
                     latent_storage_dim(c.qk_rope_head_dim, 0))
        else:
            lanes = latent_storage_dim(c.kv_lora_rank, c.qk_rope_head_dim)
        return (c.num_layers * page_size * lanes *
                jnp.dtype(c.dtype).itemsize)

    def quantize_params(self, params: dict) -> dict:
        if self.cfg.quantization:
            raise ValueError("quantization is not supported for the "
                             "DeepSeek family yet")
        return params

    # ------------------------------------------------------------------
    # Routing (overrides the Mixtral softmax+topk gate)
    # ------------------------------------------------------------------
    def _route(self, lp: dict, x: jax.Array) -> tuple[jax.Array, jax.Array]:
        c = self.cfg
        T = x.shape[0]
        k = c.num_experts_per_tok
        E = c.num_experts
        logits = (x.astype(jnp.float32)
                  @ lp["router"].astype(jnp.float32))  # [T, E]
        if self.SCORING == "sigmoid":
            # V3 "noaux_tc" (HF DeepseekV3TopkRouter): sigmoid scores,
            # group selection by sum of each group's top-2 biased
            # scores, weights gathered from the UNbiased scores.
            scores = jax.nn.sigmoid(logits)
            choice = scores + lp["router_bias"][None, :]
            G = c.n_group
            grp = choice.reshape(T, G, E // G)
            top2 = jax.lax.top_k(grp, min(2, E // G))[0].sum(axis=-1)
            sel = self._group_mask(top2, c.topk_group, G, E)
            masked = jnp.where(sel, choice, 0.0)
            top_idx = jax.lax.top_k(masked, k)[1]
            top_vals = jnp.take_along_axis(scores, top_idx, axis=-1)
            if c.norm_topk_prob:
                top_vals = top_vals / (
                    top_vals.sum(axis=-1, keepdims=True) + 1e-20)
        else:
            # V2 (HF 4.57 DeepseekV2MoEGate): softmax scores; greedy or
            # group-limited-greedy selection. NOTE: HF 4.57 never
            # applies norm_topk_prob for V2 — mirrored here for parity.
            scores = jax.nn.softmax(logits, axis=-1)
            if c.topk_method == "group_limited_greedy":
                G = c.n_group
                grp_max = scores.reshape(T, G, E // G).max(axis=-1)
                sel = self._group_mask(grp_max, c.topk_group, G, E)
                masked = jnp.where(sel, scores, 0.0)
                top_vals, top_idx = jax.lax.top_k(masked, k)
            else:
                top_vals, top_idx = jax.lax.top_k(scores, k)
        return top_idx, top_vals * c.routed_scaling_factor

    @staticmethod
    def _group_mask(group_scores: jax.Array, topk_group: int, G: int,
                    E: int) -> jax.Array:
        """[T, G] group scores -> [T, E] bool mask keeping the top
        ``topk_group`` groups' experts."""
        T = group_scores.shape[0]
        gidx = jax.lax.top_k(group_scores, topk_group)[1]  # [T, kg]
        gmask = jnp.zeros((T, G), bool).at[
            jnp.arange(T)[:, None], gidx].set(True)
        return jnp.repeat(gmask, E // G, axis=-1)

    def mlp_block(self, lp: dict, x: jax.Array,
                  lora_ctx=None) -> jax.Array:
        """MoE layer: routed experts + ungated shared experts (HF
        DeepseekV2MoE: shared output added on top, no gate — unlike
        Qwen2-MoE's sigmoid-gated shared expert)."""
        top_idx, top_vals = self._route(lp, x)
        out = self.moe_dispatch(lp, x, top_idx, top_vals)
        if self.cfg.shared_expert_intermediate_size:
            g = jax.nn.silu(x @ self._w(lp, "shared_gate"))
            u = x @ self._w(lp, "shared_up")
            out = out + (g * u) @ self._w(lp, "shared_down")
        return out.astype(x.dtype)

    def _sm_scale(self) -> float:
        """(P+R)^-0.5; V3 (HF DeepseekV3Attention) additionally folds
        the YaRN mscale^2 into the score scale when rope_scaling carries
        mscale_all_dim — real V3/R1 checkpoints all do. HF's V2 does
        NOT apply it (its yarn attention factor rides the cos/sin
        tables instead, models/common.py compute_rope_cos_sin_pairwise);
        each subclass mirrors its HF parity target exactly."""
        import math
        c = self.cfg
        scale = (c.qk_nope_head_dim + c.qk_rope_head_dim) ** -0.5
        if self.SCORING == "sigmoid" and c.rope_scaling:
            mscale_all_dim = c.rope_scaling.get("mscale_all_dim", 0)
            factor = c.rope_scaling.get("factor", 1.0)
            if mscale_all_dim and factor > 1:
                mscale = 0.1 * mscale_all_dim * math.log(factor) + 1.0
                scale = scale * mscale * mscale
        return scale

    # ------------------------------------------------------------------
    # Forward
    # ------------------------------------------------------------------
    def run_layers(
        self,
        layer_params: dict,
        kv_caches: dict,
        hidden: jax.Array,  # [T, H]
        batch: AttentionBatch,
        first_layer: int = 0,
    ) -> tuple[jax.Array, dict]:
        c = self.cfg
        T = hidden.shape[0]
        N = c.num_q_heads
        Pn, R, V = c.qk_nope_head_dim, c.qk_rope_head_dim, c.v_head_dim
        Lkv = c.kv_lora_rank
        sm_scale = self._sm_scale()
        num_layers = layer_params["input_ln"].shape[0]
        cos, sin = compute_rope_cos_sin_pairwise(
            batch.positions, R, c.rope_theta, c.rope_scaling,
            c.max_position_embeddings)

        tpla = self.tpla_shards

        def attn_block(lp, h, caches, layer_idx):
            x = rms_norm(h, lp["input_ln"], c.rms_norm_eps)
            if c.q_lora_rank:
                qc = rms_norm(x @ self._w(lp, "q_a"), lp["q_a_ln"],
                              c.rms_norm_eps)
                q = qc @ self._w(lp, "q_b")
            else:
                q = x @ self._w(lp, "wq")
            q = q.reshape(T, N, Pn + R)
            q_nope, q_pe = q[..., :Pn], q[..., Pn:]
            ckv = x @ self._w(lp, "kv_a")  # [T, Lkv + R]
            kv_c = rms_norm(ckv[..., :Lkv], lp["kv_a_ln"],
                            c.rms_norm_eps)
            k_pe = apply_rope_pairwise(
                ckv[..., Lkv:][:, None, :].astype(jnp.float32), cos,
                sin)[:, 0].astype(c.dtype)
            q_pe = apply_rope_pairwise(q_pe.astype(jnp.float32), cos,
                                       sin).astype(c.dtype)
            # Absorb W_UK into the query: MQA over the latent cache.
            # Under TPLA w_uk is latent-sharded, so ql comes out sharded
            # on its last dim — exactly the layout the sharded cache
            # attention consumes (no collective here).
            ql = jnp.einsum("tnp,knp->tnk", q_nope.astype(jnp.float32),
                            self._w(lp, "w_uk").astype(jnp.float32))
            if tpla > 1:
                c_all, pe_all = write_latent_cache_tpla(
                    caches["c"], caches["pe"], kv_c, k_pe, batch,
                    layer_idx, shards=tpla, kv_lora_rank=Lkv)
                caches = {"c": c_all, "pe": pe_all}
                v = tpla_latent_attention(
                    ql.astype(c.dtype), q_pe, c_all, pe_all, batch,
                    self._w(lp, "w_uv"), sm_scale=sm_scale,
                    kv_lora_rank=Lkv, rope_dim=R, shards=tpla,
                    layer=layer_idx).astype(jnp.float32)
            else:
                cache = write_latent_cache(
                    caches["c"], jnp.concatenate([kv_c, k_pe], axis=-1),
                    batch, layer_idx)
                caches = {"c": cache}
                out_l = latent_attention(
                    ql.astype(c.dtype), q_pe, cache, batch,
                    sm_scale=sm_scale, kv_lora_rank=Lkv, rope_dim=R,
                    layer=layer_idx)
                v = jnp.einsum("tnk,knv->tnv", out_l.astype(jnp.float32),
                               self._w(lp, "w_uv").astype(jnp.float32))
            o = v.reshape(T, N * V).astype(c.dtype) @ self._w(lp, "wo")
            return h + o, caches

        attn_keys = [k for k in layer_params
                     if k not in _DENSE_KEYS and k not in _MOE_KEYS]
        mlp_keys = {
            "dense": [k for k in layer_params if k in _DENSE_KEYS],
            "moe": [k for k in layer_params if k in _MOE_KEYS],
        }
        # Local segment split: stage covers global layers
        # [first_layer, first_layer + num_layers); the dense/MoE
        # boundary is first_k_dense_replace.
        nd_local = max(
            0, min(first_layer + num_layers, self._n_dense) - first_layer)

        def seg_scan(carry, seg_start, seg_len, kind):
            if seg_len == 0:
                return carry
            attn_lp = {k: layer_params[k][seg_start:seg_start + seg_len]
                       for k in attn_keys}
            # Dense/MoE stacks are indexed in their OWN depth space and
            # slice_layer_params already rebased them per stage, so each
            # kind's stack starts at 0 locally.
            mlp_lp = {k: layer_params[k][:seg_len]
                      for k in mlp_keys[kind]}
            ids = jnp.arange(seg_start, seg_start + seg_len,
                             dtype=jnp.int32)[:, None]

            def body(car, xs):
                h, caches = car
                a_lp, m_lp, layer_idx = xs
                h, caches = attn_block(a_lp, h, caches, layer_idx)
                x2 = rms_norm(h, a_lp["post_ln"], c.rms_norm_eps)
                if kind == "dense":
                    mlp_out = LlamaForCausalLM.mlp_block(self, m_lp, x2)
                else:
                    mlp_out = self.mlp_block(m_lp, x2)
                return (h + mlp_out, caches), None

            carry, _ = jax.lax.scan(body, carry, (attn_lp, mlp_lp, ids))
            return carry

        carry = (hidden, dict(kv_caches))
        carry = seg_scan(carry, 0, nd_local, "dense")
        carry = seg_scan(carry, nd_local, num_layers - nd_local, "moe")
        hidden, caches = carry
        return hidden, caches


class DeepseekV3ForCausalLM(DeepseekV2ForCausalLM):
    """DeepSeek-V3/R1: V2's MLA + MoE structure with sigmoid scoring,
    the aux-loss-free correction bias, and top-2-sum group selection
    (HF DeepseekV3TopkRouter; reference:
    vllm/model_executor/models/deepseek_v3.py)."""

    SCORING = "sigmoid"

    @classmethod
    def configure_arch(cls, arch, hf) -> None:
        super().configure_arch(arch, hf)
        arch.topk_method = "noaux_tc"
