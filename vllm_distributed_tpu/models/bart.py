"""BART encoder-decoder text generation (summarization/translation).

Reference surface: vllm/model_executor/models/bart.py
(BartForConditionalGeneration: the reference's encoder-decoder TEXT
family, registry.py:129). Rides the Whisper cross-attention machinery
(models/whisper.py): the text encoder runs front-end-side at admission
(multimodal/text_encoder.py) and its hidden states install into the
per-request cross-KV state rows with a valid-length mask (BART sources
vary, unlike Whisper's fixed audio frames). Structural deltas from
Whisper: POST-norm blocks, learned positions written from offset 2,
an embedding LayerNorm, k-projection biases, no final decoder norm,
and a final_logits_bias on the tied LM head.
"""

from __future__ import annotations

from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from vllm_distributed_tpu.models.llama import MODEL_AXIS
from vllm_distributed_tpu.models.whisper import \
    WhisperForConditionalGeneration


def _with_model_prefix(tensors: dict) -> dict:
    """``BartModel`` checkpoints store unprefixed keys (shared.weight,
    decoder.layers...); normalize onto the ForConditionalGeneration
    ``model.`` layout both loaders expect."""
    if any(k.startswith("model.") for k in tensors):
        return tensors
    return {("model." + k if not k.startswith(("final_logits_bias",
                                               "lm_head")) else k): v
            for k, v in tensors.items()}


class BartForConditionalGeneration(WhisperForConditionalGeneration):

    LM_HEAD_BIAS = True  # final_logits_bias
    CROSS_MODALITY = "text"

    @classmethod
    def arch_config_source(cls, hf):
        return SimpleNamespace(
            vocab_size=hf.vocab_size,
            hidden_size=hf.d_model,
            intermediate_size=hf.decoder_ffn_dim,
            num_hidden_layers=hf.decoder_layers,
            num_attention_heads=hf.decoder_attention_heads,
            num_key_value_heads=hf.decoder_attention_heads,
            head_dim=hf.d_model // hf.decoder_attention_heads,
            rms_norm_eps=1e-5,
            tie_word_embeddings=True,
        )

    @classmethod
    def configure_arch(cls, arch, hf) -> None:
        import math
        arch.stateful = True
        arch.pos_embedding = "learned"
        # HF's learned table physically holds offset + max positions.
        arch.pos_offset = 2
        arch.max_position_embeddings = int(hf.max_position_embeddings) + 2
        arch.norm_type = "layernorm"
        arch.norm_bias = True
        arch.mlp_gated = False
        arch.mlp_bias = True
        arch.attention_out_bias = True
        arch.pre_norm = False           # BART is post-norm
        arch.final_norm = False         # no final decoder LayerNorm
        arch.embed_ln = True            # layernorm_embedding
        arch.hidden_act = getattr(hf, "activation_function", "gelu")
        arch.embed_scale = (math.sqrt(hf.d_model)
                            if getattr(hf, "scale_embedding", False)
                            else 1.0)
        arch.tie_word_embeddings = True
        # Cross state holds up to the encoder's position capacity.
        arch.num_audio_frames = int(hf.max_position_embeddings)
        if not hasattr(arch, "state_slots"):
            arch.state_slots = 0

    # ------------------------------------------------------------------
    def param_specs(self) -> dict:
        specs = super().param_specs()
        colb = P(None, MODEL_AXIS)
        specs["layers"]["bk"] = colb
        specs["layers"]["cbk"] = colb
        specs["embed_ln_w"] = P(None)
        specs["embed_ln_b"] = P(None)
        specs["lm_head_b"] = P(MODEL_AXIS)
        del specs["final_ln"], specs["final_ln_b"]
        return specs

    def init_params(self, rng: jax.Array, scale: float = 0.02) -> dict:
        c = self.cfg
        params = super().init_params(rng, scale)
        L, H = c.num_layers, c.hidden_size
        params["layers"]["bk"] = jnp.zeros((L, H), c.dtype)
        params["layers"]["cbk"] = jnp.zeros((L, H), c.dtype)
        params["embed_ln_w"] = jnp.ones((H, ), c.dtype)
        params["embed_ln_b"] = jnp.zeros((H, ), c.dtype)
        params["lm_head_b"] = jnp.zeros((c.vocab_size, ), c.dtype)
        del params["final_ln"], params["final_ln_b"]
        return params

    def params_from_hf_state_dict(self, tensors, dtype=None) -> dict:
        c = self.cfg
        dt = dtype or c.dtype
        L = c.num_layers
        tensors = _with_model_prefix(tensors)

        def t(name):
            return np.asarray(tensors[name])

        def stack(fmt, transpose=True):
            mats = [t(fmt.format(i)) for i in range(L)]
            return jnp.asarray(
                np.stack([m.T if transpose else m for m in mats]), dt)

        D = "model.decoder.layers.{}."
        layer = {
            "ln1": stack(D + "self_attn_layer_norm.weight", False),
            "ln1_b": stack(D + "self_attn_layer_norm.bias", False),
            "wq": stack(D + "self_attn.q_proj.weight"),
            "bq": stack(D + "self_attn.q_proj.bias", False),
            "wk": stack(D + "self_attn.k_proj.weight"),
            "bk": stack(D + "self_attn.k_proj.bias", False),
            "wv": stack(D + "self_attn.v_proj.weight"),
            "bv": stack(D + "self_attn.v_proj.bias", False),
            "wo": stack(D + "self_attn.out_proj.weight"),
            "bo": stack(D + "self_attn.out_proj.bias", False),
            "ln2": stack(D + "encoder_attn_layer_norm.weight", False),
            "ln2_b": stack(D + "encoder_attn_layer_norm.bias", False),
            "cwq": stack(D + "encoder_attn.q_proj.weight"),
            "cbq": stack(D + "encoder_attn.q_proj.bias", False),
            "cwk": stack(D + "encoder_attn.k_proj.weight"),
            "cbk": stack(D + "encoder_attn.k_proj.bias", False),
            "cwv": stack(D + "encoder_attn.v_proj.weight"),
            "cbv": stack(D + "encoder_attn.v_proj.bias", False),
            "cwo": stack(D + "encoder_attn.out_proj.weight"),
            "cbo": stack(D + "encoder_attn.out_proj.bias", False),
            "ln3": stack(D + "final_layer_norm.weight", False),
            "ln3_b": stack(D + "final_layer_norm.bias", False),
            "fc1": stack(D + "fc1.weight"),
            "fc1_b": stack(D + "fc1.bias", False),
            "fc2": stack(D + "fc2.weight"),
            "fc2_b": stack(D + "fc2.bias", False),
        }
        embed = jnp.asarray(t("model.shared.weight"), dt)
        flb = tensors.get("final_logits_bias")
        return {
            "embed": embed,
            "embed_pos": jnp.asarray(
                t("model.decoder.embed_positions.weight"), dt),
            "embed_ln_w": jnp.asarray(
                t("model.decoder.layernorm_embedding.weight"), dt),
            "embed_ln_b": jnp.asarray(
                t("model.decoder.layernorm_embedding.bias"), dt),
            "layers": layer,
            "lm_head": embed.T,
            "lm_head_b": jnp.asarray(
                np.asarray(flb).reshape(-1) if flb is not None
                else np.zeros((c.vocab_size, ), np.float32), dt),
        }
