"""Llama-shaped model families beyond the base class.

Reference: vllm/model_executor/models/{gemma,qwen3,phi3}.py — each is the
Llama decoder with a small twist, so each maps to a thin subclass here
(the registry covers the long tail of HF ``architectures`` strings the
same way the reference's ~180-entry table does):

* Gemma: sqrt(H)-scaled embeddings, tanh-GELU gated MLP, RMSNorm with a
  +1 weight offset (folded into the stored weights at load so the
  forward stays branch-free), tied LM head.
* Qwen3: per-head RMSNorm on q/k ahead of RoPE.
* Phi-3: identical math to Llama with FUSED qkv_proj / gate_up_proj
  checkpoint tensors — a pure name-mapping subclass.
"""

import math

import numpy as np

from vllm_distributed_tpu.models.llama import (LlamaArchConfig,
                                               LlamaForCausalLM)


def split_grouped_qkv(w: "np.ndarray", num_kv_heads: int,
                      q_per_kv: int, head_dim: int):
    """Undo the grouped fused-QKV layout shared by InternLM2 and
    Falcon: per kv group the rows pack q_per_kv query heads, then that
    group's k head, then its v head. Returns (q, k, v) row blocks."""
    H = w.shape[-1]
    g = w.reshape(num_kv_heads, q_per_kv + 2, head_dim, H)
    return (g[:, :q_per_kv].reshape(-1, H),
            g[:, q_per_kv].reshape(-1, H),
            g[:, q_per_kv + 1].reshape(-1, H))


class GemmaForCausalLM(LlamaForCausalLM):

    # RMSNorm weights stored as offsets from 1 in Gemma checkpoints.
    _NORM_FOLD_KEYS = ("input_ln", "post_ln")

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        arch.embed_scale = math.sqrt(arch.hidden_size)
        arch.hidden_act = "gelu_tanh"
        arch.tie_word_embeddings = True

    def params_from_hf_state_dict(self, tensors) -> dict:
        params = super().params_from_hf_state_dict(tensors)
        # Gemma's RMSNorm computes x * (1 + w): fold the offset into the
        # stored weights so rms_norm needs no model-specific branch.
        layers = params["layers"]
        for key in self._NORM_FOLD_KEYS:
            layers[key] = layers[key] + 1.0
        params["final_ln"] = params["final_ln"] + 1.0
        return params

    def init_params(self, rng, scale: float = 0.02) -> dict:
        # Random init is already offset-free; nothing to fold.
        return super().init_params(rng, scale)


class Gemma2ForCausalLM(GemmaForCausalLM):
    """Gemma 2 (reference: vllm/model_executor/models/gemma2.py): the
    Gemma block plus sandwich norms around both sub-blocks, attention
    and final logit soft-capping, query_pre_attn_scalar score scaling,
    and alternating sliding/full attention layers. The alternating
    layout arrives via hf.layer_types through the generic
    window-pattern resolver; run_layers executes it as one lax.scan
    over layer PAIRS so every mask stays static."""

    _NORM_FOLD_KEYS = ("input_ln", "post_ln", "post_attn_ln",
                       "post_ffw_ln")

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        super().configure_arch(arch, hf)
        arch.extra_layer_norms = True
        arch.attn_logit_softcap = float(
            getattr(hf, "attn_logit_softcapping", None) or 0.0)
        arch.final_logit_softcap = float(
            getattr(hf, "final_logit_softcapping", None) or 0.0)
        qpas = getattr(hf, "query_pre_attn_scalar", None)
        arch.query_pre_attn_scalar = float(qpas) if qpas else None
        if arch.sliding_window and arch.window_pattern is None:
            # Older transformers Gemma2Configs predate layer_types, so
            # the generic resolver sees a uniform window — which would
            # silently window the full-attention layers too. Gemma2's
            # layout is fixed: even layers sliding, odd layers full.
            arch.window_pattern = tuple(
                arch.sliding_window if i % 2 == 0 else 0
                for i in range(arch.num_layers))


class Qwen3ForCausalLM(LlamaForCausalLM):

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        arch.qk_norm = True


class Phi3ForCausalLM(LlamaForCausalLM):

    def params_from_hf_state_dict(self, tensors) -> dict:
        """Split Phi-3's fused projections into the base layout."""
        c = self.cfg
        Dq = c.num_q_heads * c.head_dim
        Dkv = c.num_kv_heads * c.head_dim
        out = dict(tensors)
        for i in range(c.num_layers):
            qkv = np.asarray(
                tensors[f"model.layers.{i}.self_attn.qkv_proj.weight"])
            out[f"model.layers.{i}.self_attn.q_proj.weight"] = qkv[:Dq]
            out[f"model.layers.{i}.self_attn.k_proj.weight"] = \
                qkv[Dq:Dq + Dkv]
            out[f"model.layers.{i}.self_attn.v_proj.weight"] = \
                qkv[Dq + Dkv:]
            gu = np.asarray(
                tensors[f"model.layers.{i}.mlp.gate_up_proj.weight"])
            half = gu.shape[0] // 2
            out[f"model.layers.{i}.mlp.gate_proj.weight"] = gu[:half]
            out[f"model.layers.{i}.mlp.up_proj.weight"] = gu[half:]
        return super().params_from_hf_state_dict(out)


class InternLM2ForCausalLM(LlamaForCausalLM):
    """InternLM2 (reference: vllm/model_executor/models/internlm2.py):
    Llama math with renamed tensors and a GROUPED fused wqkv — per kv
    group the checkpoint packs q_per_kv query heads, then that group's
    k head, then its v head (the reference's split_qkv at
    internlm2.py:119 undoes the same layout per TP rank)."""

    def params_from_hf_state_dict(self, tensors) -> dict:
        c = self.cfg
        H = c.hidden_size
        q_per_kv = c.num_q_heads // c.num_kv_heads
        out = {}
        for i in range(c.num_layers):
            pre = f"model.layers.{i}."
            wqkv = np.asarray(tensors[f"{pre}attention.wqkv.weight"])
            (out[f"{pre}self_attn.q_proj.weight"],
             out[f"{pre}self_attn.k_proj.weight"],
             out[f"{pre}self_attn.v_proj.weight"]) = split_grouped_qkv(
                wqkv, c.num_kv_heads, q_per_kv, c.head_dim)
            out[f"{pre}self_attn.o_proj.weight"] = \
                tensors[f"{pre}attention.wo.weight"]
            out[f"{pre}mlp.gate_proj.weight"] = \
                tensors[f"{pre}feed_forward.w1.weight"]
            out[f"{pre}mlp.up_proj.weight"] = \
                tensors[f"{pre}feed_forward.w3.weight"]
            out[f"{pre}mlp.down_proj.weight"] = \
                tensors[f"{pre}feed_forward.w2.weight"]
            out[f"{pre}input_layernorm.weight"] = \
                tensors[f"{pre}attention_norm.weight"]
            out[f"{pre}post_attention_layernorm.weight"] = \
                tensors[f"{pre}ffn_norm.weight"]
        out["model.embed_tokens.weight"] = \
            tensors["model.tok_embeddings.weight"]
        out["model.norm.weight"] = tensors["model.norm.weight"]
        if "output.weight" in tensors:
            out["lm_head.weight"] = tensors["output.weight"]
        return super().params_from_hf_state_dict(out)


class BaichuanForCausalLM(LlamaForCausalLM):
    """Baichuan 7B/13B (reference: vllm/model_executor/models/
    baichuan.py): Llama math with a fused W_pack = [q; k; v]
    projection. The 13B variant replaces RoPE with ALiBi — keyed on
    hidden size like the reference keys position_embedding on the
    model name (baichuan.py:330)."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        if getattr(hf, "hidden_size", 0) >= 5120:
            arch.alibi = True
            arch.pos_embedding = "none"

    # Baichuan2's vocab size — its NormHead lm_head stores unnormalized
    # rows that the forward L2-normalizes (reference: baichuan.py keying
    # the row normalization on this constant).
    _BAICHUAN2_VOCAB = 125696

    def params_from_hf_state_dict(self, tensors) -> dict:
        c = self.cfg
        Dq = c.num_q_heads * c.head_dim
        Dkv = c.num_kv_heads * c.head_dim
        out = dict(tensors)
        for i in range(c.num_layers):
            pre = f"model.layers.{i}.self_attn."
            w = np.asarray(tensors[f"{pre}W_pack.weight"])
            out[f"{pre}q_proj.weight"] = w[:Dq]
            out[f"{pre}k_proj.weight"] = w[Dq:Dq + Dkv]
            out[f"{pre}v_proj.weight"] = w[Dq + Dkv:]
        if (c.vocab_size == self._BAICHUAN2_VOCAB
                and "lm_head.weight" in out):
            head = np.asarray(out["lm_head.weight"], np.float32)
            norms = np.linalg.norm(head, axis=-1, keepdims=True)
            out["lm_head.weight"] = head / np.maximum(norms, 1e-7)
        return super().params_from_hf_state_dict(out)


class Gemma3ForCausalLM(Gemma2ForCausalLM):
    """Gemma 3 text decoder (reference: vllm/model_executor/models/
    gemma3.py): the Gemma2 sandwich-norm block minus the softcaps, plus
    per-head qk RMSNorms (gemma-style 1+w weights) and a SEPARATE rope
    base for sliding layers (rope_local_base_freq) while full layers
    use the global theta with linear scaling."""

    _NORM_FOLD_KEYS = ("input_ln", "post_ln", "post_attn_ln",
                       "post_ffw_ln", "q_norm", "k_norm")

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        super().configure_arch(arch, hf)
        arch.qk_norm = True
        local = getattr(hf, "rope_local_base_freq", None)
        if local and any(w == 0 for w in (arch.window_pattern or ())):
            # Mixed layouts rope sliding layers with the local base.
            arch.rope_theta_local = float(local)
        elif local and arch.sliding_window:
            # All-sliding tiny configs: the local base IS the base.
            arch.rope_theta = float(local)
