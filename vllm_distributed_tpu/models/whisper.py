"""Whisper encoder-decoder (audio transcription).

Reference surface: vllm/model_executor/models/whisper.py
(WhisperForConditionalGeneration: a conv-subsampled audio encoder whose
output feeds per-layer cross-attention in the decoder; the decoder's
self-attention KV is paged while the cross-attention KV is computed
once per request) and the transcription serving path
(entrypoints/openai/serving_transcription.py).

TPU design: the AUDIO ENCODER runs front-end-side at admission (the
multimodal/audio.py module, mirroring the CLIP vision tower's
placement) and ships the [frames, H] hidden states on the request like
an image's embeddings. Worker-side, ``install_cross_states`` projects
them through the decoder's per-layer cross K/V weights ONCE and
scatters the result into fixed per-request state rows — the same
slot-indexed state-row machinery the SSM families use
(models/mamba.py), because cross KV is O(1) per request (every audio
clip encodes to the same static frame count) and paging buys nothing.
The decoder itself runs on the ordinary ragged paged engine: learned
positions, bias-carrying LayerNorm blocks, causal paged self-attention
(no rope), and a dense cross-attention over the request's state row.
"""

from __future__ import annotations

from types import SimpleNamespace
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from vllm_distributed_tpu.models.llama import (MODEL_AXIS, TOKEN_AXIS,
                                               LlamaForCausalLM)
from vllm_distributed_tpu.ops.attention import (paged_attention,
                                                storage_head_dim,
                                                write_kv_cache)


class WhisperForConditionalGeneration(LlamaForCausalLM):
    """Whisper decoder on the paged engine + cross-attention state."""

    STATEFUL = True        # fixed per-request rows; no prefix caching
    CROSS_ATTENTION = True
    CROSS_MODALITY = "audio"
    QUANT_TARGETS = ()
    LORA_TARGETS = ()

    # ------------------------------------------------------------------
    @classmethod
    def arch_config_source(cls, hf):
        return SimpleNamespace(
            vocab_size=hf.vocab_size,
            hidden_size=hf.d_model,
            intermediate_size=hf.decoder_ffn_dim,
            num_hidden_layers=hf.decoder_layers,
            num_attention_heads=hf.decoder_attention_heads,
            num_key_value_heads=hf.decoder_attention_heads,
            head_dim=hf.d_model // hf.decoder_attention_heads,
            rms_norm_eps=1e-5,
            tie_word_embeddings=True,
        )

    @classmethod
    def configure_arch(cls, arch, hf) -> None:
        arch.stateful = True
        arch.pos_embedding = "learned"
        arch.max_position_embeddings = int(hf.max_target_positions)
        arch.norm_type = "layernorm"
        arch.norm_bias = True
        arch.mlp_gated = False
        arch.mlp_bias = True
        arch.attention_out_bias = True
        arch.hidden_act = getattr(hf, "activation_function", "gelu")
        arch.tie_word_embeddings = True
        # Encoder frame count after the stride-2 conv subsampling.
        arch.num_audio_frames = int(hf.max_source_positions)
        if not hasattr(arch, "state_slots"):
            arch.state_slots = 0

    def quantize_params(self, params: dict) -> dict:
        if self.cfg.quantization:
            raise ValueError(
                "quantization for Whisper is not wired yet; drop "
                "--quantization")
        return params

    # ------------------------------------------------------------------
    # Parameters
    # ------------------------------------------------------------------
    def param_specs(self) -> dict:
        c = self.cfg
        col = P(None, None, MODEL_AXIS)
        colb = P(None, MODEL_AXIS)
        row = P(None, MODEL_AXIS, None)
        ln = P(None, None)
        layer = {}
        for pre in ("", "c"):
            layer.update({
                pre + "wq": col, pre + "bq": colb,
                pre + "wk": col,
                pre + "wv": col, pre + "bv": colb,
                pre + "wo": row, pre + "bo": ln,
            })
        layer.update({
            "ln1": ln, "ln1_b": ln,
            "ln2": ln, "ln2_b": ln,
            "ln3": ln, "ln3_b": ln,
            "fc1": col, "fc1_b": colb,
            "fc2": row, "fc2_b": ln,
        })
        return {
            "embed": P(None, None),
            "embed_pos": P(None, None),
            "layers": layer,
            "final_ln": P(None),
            "final_ln_b": P(None),
            "lm_head": P(None, MODEL_AXIS),
        }

    def init_params(self, rng: jax.Array, scale: float = 0.02) -> dict:
        c = self.cfg
        L, H, I = c.num_layers, c.hidden_size, c.intermediate_size
        keys = iter(jax.random.split(rng, 24))

        def rnd(shape):
            return (scale * jax.random.normal(next(keys), shape,
                                              jnp.float32)).astype(c.dtype)

        layer = {}
        for pre in ("", "c"):
            layer.update({
                pre + "wq": rnd((L, H, H)),
                pre + "bq": jnp.zeros((L, H), c.dtype),
                pre + "wk": rnd((L, H, H)),
                pre + "wv": rnd((L, H, H)),
                pre + "bv": jnp.zeros((L, H), c.dtype),
                pre + "wo": rnd((L, H, H)),
                pre + "bo": jnp.zeros((L, H), c.dtype),
            })
        layer.update({
            "ln1": jnp.ones((L, H), c.dtype),
            "ln1_b": jnp.zeros((L, H), c.dtype),
            "ln2": jnp.ones((L, H), c.dtype),
            "ln2_b": jnp.zeros((L, H), c.dtype),
            "ln3": jnp.ones((L, H), c.dtype),
            "ln3_b": jnp.zeros((L, H), c.dtype),
            "fc1": rnd((L, H, I)),
            "fc1_b": jnp.zeros((L, I), c.dtype),
            "fc2": rnd((L, I, H)),
            "fc2_b": jnp.zeros((L, H), c.dtype),
        })
        embed = rnd((c.vocab_size, H))
        return {
            "embed": embed,
            "embed_pos": rnd((c.max_position_embeddings, H)),
            "layers": layer,
            "final_ln": jnp.ones((H, ), c.dtype),
            "final_ln_b": jnp.zeros((H, ), c.dtype),
            "lm_head": embed.T,
        }

    def params_from_hf_state_dict(self, tensors, dtype=None) -> dict:
        c = self.cfg
        dt = dtype or c.dtype
        L = c.num_layers

        def t(name):
            return np.asarray(tensors[name])

        def stack(fmt, transpose=True):
            mats = [t(fmt.format(i)) for i in range(L)]
            return jnp.asarray(
                np.stack([m.T if transpose else m for m in mats]), dt)

        D = "model.decoder.layers.{}."
        layer = {
            "ln1": stack(D + "self_attn_layer_norm.weight", False),
            "ln1_b": stack(D + "self_attn_layer_norm.bias", False),
            "wq": stack(D + "self_attn.q_proj.weight"),
            "bq": stack(D + "self_attn.q_proj.bias", False),
            "wk": stack(D + "self_attn.k_proj.weight"),
            "wv": stack(D + "self_attn.v_proj.weight"),
            "bv": stack(D + "self_attn.v_proj.bias", False),
            "wo": stack(D + "self_attn.out_proj.weight"),
            "bo": stack(D + "self_attn.out_proj.bias", False),
            "ln2": stack(D + "encoder_attn_layer_norm.weight", False),
            "ln2_b": stack(D + "encoder_attn_layer_norm.bias", False),
            "cwq": stack(D + "encoder_attn.q_proj.weight"),
            "cbq": stack(D + "encoder_attn.q_proj.bias", False),
            "cwk": stack(D + "encoder_attn.k_proj.weight"),
            "cwv": stack(D + "encoder_attn.v_proj.weight"),
            "cbv": stack(D + "encoder_attn.v_proj.bias", False),
            "cwo": stack(D + "encoder_attn.out_proj.weight"),
            "cbo": stack(D + "encoder_attn.out_proj.bias", False),
            "ln3": stack(D + "final_layer_norm.weight", False),
            "ln3_b": stack(D + "final_layer_norm.bias", False),
            "fc1": stack(D + "fc1.weight"),
            "fc1_b": stack(D + "fc1.bias", False),
            "fc2": stack(D + "fc2.weight"),
            "fc2_b": stack(D + "fc2.bias", False),
        }
        embed = jnp.asarray(t("model.decoder.embed_tokens.weight"), dt)
        return {
            "embed": embed,
            "embed_pos": jnp.asarray(
                t("model.decoder.embed_positions.weight"), dt),
            "layers": layer,
            "final_ln": jnp.asarray(t("model.decoder.layer_norm.weight"),
                                    dt),
            "final_ln_b": jnp.asarray(
                t("model.decoder.layer_norm.bias"), dt),
            # proj_out is tied to the decoder embedding.
            "lm_head": embed.T,
        }

    # ------------------------------------------------------------------
    # Caches: paged decoder KV + fixed cross-KV state rows
    # ------------------------------------------------------------------
    def kv_cache_specs(self) -> dict:
        return {
            "k": P(None, TOKEN_AXIS, MODEL_AXIS, None, None),
            "v": P(None, TOKEN_AXIS, MODEL_AXIS, None, None),
            "xk": P(None, None, None, MODEL_AXIS, None),
            "xv": P(None, None, None, MODEL_AXIS, None),
            "xlen": P(None),
        }

    def _cross_shapes(self) -> dict:
        c = self.cfg
        S = (c.state_slots or 256) + 1  # +1 dump row
        shape = (c.num_layers, S, c.num_audio_frames, c.num_q_heads,
                 c.head_dim)
        return {"xk": (shape, c.dtype), "xv": (shape, c.dtype),
                # Valid source length per slot (whisper audio is always
                # full-frame; BART text varies).
                "xlen": ((S, ), jnp.int32)}

    def make_kv_caches(self, num_pages: int, page_size: int,
                       cache_dtype=None,
                       num_layers: Optional[int] = None) -> dict:
        c = self.cfg
        assert num_layers is None or num_layers == c.num_layers, \
            "whisper stacks are not sliceable per stage (no PP)"
        dtype = cache_dtype or c.dtype
        shape = (c.num_layers, num_pages, c.total_kv_heads, page_size,
                 storage_head_dim(c.head_dim))
        caches = {"k": jnp.zeros(shape, dtype),
                  "v": jnp.zeros(shape, dtype)}
        caches.update({
            name: jnp.zeros(s, dt)
            for name, (s, dt) in self._cross_shapes().items()
        })
        return caches

    def fixed_cache_bytes(self) -> int:
        return sum(int(np.prod(s)) * jnp.dtype(dt).itemsize
                   for s, dt in self._cross_shapes().values())

    # ------------------------------------------------------------------
    def install_cross_states(self, kv_caches: dict, slot: int,
                             enc_hidden: np.ndarray) -> dict:
        """Project the encoder hidden states through every decoder
        layer's cross K/V weights and write the request's state row
        (runs once at admission; donated in-place update)."""
        if self._install_fn is None:
            def project(layers, h):
                # h [F, H] -> k/v [L, F, NH, D]
                c = self.cfg
                k = jnp.einsum("fh,lhd->lfd", h, layers["cwk"])
                if "cbk" in layers:
                    k = k + layers["cbk"][:, None, :]
                v = jnp.einsum("fh,lhd->lfd", h, layers["cwv"])
                if "cbv" in layers:
                    v = v + layers["cbv"][:, None, :]
                L, F = k.shape[0], k.shape[1]
                return (k.reshape(L, F, c.num_q_heads, c.head_dim),
                        v.reshape(L, F, c.num_q_heads, c.head_dim))

            def scatter(xk, xv, xlen, k, v, n, slot):
                return (xk.at[:, slot].set(k.astype(xk.dtype)),
                        xv.at[:, slot].set(v.astype(xv.dtype)),
                        xlen.at[slot].set(n))

            self._install_fn = (jax.jit(project),
                                jax.jit(scatter,
                                        donate_argnums=(0, 1, 2)))
        project, scatter = self._install_fn
        h = np.asarray(enc_hidden)
        n = h.shape[0]
        F = self.cfg.num_audio_frames
        if n > F:
            raise ValueError(
                f"encoder output has {n} frames; this model's "
                f"cross-attention state holds {F}")
        if n < F:  # variable-length sources (BART text) pad; the
            h = np.concatenate(  # xlen mask hides the padding
                [h, np.zeros((F - n, h.shape[1]), h.dtype)])
        k, v = project(self.params_ref["layers"],
                       jnp.asarray(h, self.cfg.dtype))
        kv_caches["xk"], kv_caches["xv"], kv_caches["xlen"] = scatter(
            kv_caches["xk"], kv_caches["xv"], kv_caches["xlen"], k, v,
            jnp.asarray(n, jnp.int32), jnp.asarray(slot, jnp.int32))
        return kv_caches

    def clear_cross_states(self, kv_caches: dict, slot: int) -> dict:
        """Zero a row's valid-frame count so a reused batch row can
        never cross-attend to a previous occupant's installed states
        (defense in depth behind the Processor's admission check)."""
        if self._clear_fn is None:
            self._clear_fn = jax.jit(
                lambda xlen, slot: xlen.at[slot].set(0),
                donate_argnums=(0, ))
        kv_caches["xlen"] = self._clear_fn(
            kv_caches["xlen"], jnp.asarray(slot, jnp.int32))
        return kv_caches

    _install_fn = None
    _clear_fn = None
    params_ref: dict = None  # set by the runner after load

    # ------------------------------------------------------------------
    # Forward
    # ------------------------------------------------------------------
    def run_layers(self, layer_params, kv_caches, hidden, batch,
                   first_layer: int = 0):
        c = self.cfg
        T = hidden.shape[0]
        sm_scale = c.head_dim ** -0.5
        slots = batch.req_idx  # input-batch row == state slot

        def ln(x, w, b):
            xf = x.astype(jnp.float32)
            mu = jnp.mean(xf, axis=-1, keepdims=True)
            var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
            return ((xf - mu) * jax.lax.rsqrt(var + c.rms_norm_eps) *
                    w + b).astype(x.dtype)

        h = hidden
        k_all, v_all = kv_caches["k"], kv_caches["v"]
        xk_all, xv_all = kv_caches["xk"], kv_caches["xv"]
        xlen = kv_caches["xlen"][slots]  # [T] valid source frames
        F = xk_all.shape[2]
        frame_valid = (jnp.arange(F, dtype=jnp.int32)[None, :]
                       < xlen[:, None])  # [T, F]
        pre = c.pre_norm  # whisper pre-LN; BART post-LN

        for i in range(c.num_layers):
            lp = {k: v[i] for k, v in layer_params.items()}
            li = jnp.full((1, ), i, jnp.int32)
            # Self-attention (causal, paged, no rope).
            x = ln(h, lp["ln1"], lp["ln1_b"]) if pre else h
            q = (x @ lp["wq"] + lp["bq"]).reshape(T, c.num_q_heads,
                                                  c.head_dim)
            k = x @ lp["wk"]
            if "bk" in lp:
                k = k + lp["bk"]
            k = k.reshape(T, c.total_kv_heads, c.head_dim)
            v = (x @ lp["wv"] + lp["bv"]).reshape(T, c.total_kv_heads,
                                                  c.head_dim)
            k_all, v_all = write_kv_cache(k_all, v_all, k, v, batch, li)
            attn = paged_attention(q, k_all, v_all, batch,
                                   sm_scale=sm_scale, layer=li)
            h = h + attn.reshape(T, -1) @ lp["wo"] + lp["bo"]
            if not pre:
                h = ln(h, lp["ln1"], lp["ln1_b"])
            # Cross-attention over the request's encoder-state row;
            # frames past xlen are masked (whisper audio is always
            # full-frame, BART text varies).
            x = ln(h, lp["ln2"], lp["ln2_b"]) if pre else h
            q = ((x @ lp["cwq"] + lp["cbq"]) * sm_scale).reshape(
                T, c.num_q_heads, c.head_dim)
            xk = xk_all[i][slots]  # [T, F, NH, D]
            xv = xv_all[i][slots]
            scores = jnp.einsum("tnd,tfnd->tnf", q.astype(jnp.float32),
                                xk.astype(jnp.float32))
            scores = jnp.where(frame_valid[:, None, :], scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1)
            ctx = jnp.einsum("tnf,tfnd->tnd", probs.astype(h.dtype), xv)
            h = h + ctx.reshape(T, -1) @ lp["cwo"] + lp["cbo"]
            if not pre:
                h = ln(h, lp["ln2"], lp["ln2_b"])
            # MLP.
            x = ln(h, lp["ln3"], lp["ln3_b"]) if pre else h
            m = self._act(x @ lp["fc1"] + lp["fc1_b"])
            h = h + m @ lp["fc2"] + lp["fc2_b"]
            if not pre:
                h = ln(h, lp["ln3"], lp["ln3_b"])
        return h, {"k": k_all, "v": v_all, "xk": xk_all, "xv": xv_all,
                   "xlen": kv_caches["xlen"]}
