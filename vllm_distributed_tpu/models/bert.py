"""Encoder-only (BERT / RoBERTa) families: embeddings + cross-encoder
scoring.

Reference surface: vllm/model_executor/models/bert.py (BertModel /
BertEmbeddingModel with CLS-pooled embeddings, the ``_EMBEDDING_MODELS``
registry map), roberta.py (RobertaEmbeddingModel with its
padding-offset learned positions and the classification head of
RobertaForSequenceClassification), and the cross-encoder path of
vllm/entrypoints/llm.py ``LLM.score`` / serving_score.py.

TPU design: encoder inference is pure prefill — no KV cache, no paging,
no sampling. Instead of threading bidirectional masks through the
ragged paged-attention machinery, the whole batch runs as ONE dense
[R, L, H] program: padded row-major batches are exactly what the MXU
wants (large static matmuls), and the O(L^2) score tensor is tiny at
encoder lengths (<=512 tokens). A dedicated runner
(worker/encoder_runner.py) buckets (R, L) and jits a single forward
that returns every pooling variant at once; the scheduler runs
unchanged with chunked prefill disabled (a bidirectional layer needs
the full sequence in one step).

Architecture notes (post-LN transformer, HF ``BertModel`` semantics):
  x   = LN(word[ids] + pos[positions] + type[type_ids])
  h   = LN(h + Wo @ MHA(h))       (attention.output.LayerNorm)
  h   = LN(h + W2 @ gelu(W1 @ h)) (output.LayerNorm)
pooling: "cls" (default, matches the reference's BertEmbeddingModel),
"mean" (sentence-transformers style masked mean), or "last".
Cross-encoder checkpoints add dense+tanh (BERT pooler / Roberta head
dense) and a classifier projection; their score is the single logit
(num_labels == 1) or softmax[1] for 2-label heads, as in the
reference's cross-encoder scoring.
"""

from __future__ import annotations

from types import SimpleNamespace

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from vllm_distributed_tpu.models.llama import MODEL_AXIS, LlamaForCausalLM

_NEG = -1e9  # additive mask for padded keys (fp32 scores)


class BertEmbeddingModel(LlamaForCausalLM):
    """BERT encoder serving embedding requests (arch "BertModel")."""

    ENCODER_ONLY = True
    CLASSIFY = False
    QUANT_TARGETS = ()
    LORA_TARGETS = ()
    # Candidate HF checkpoint prefixes, tried in order.
    HF_PREFIXES = ("", "bert.")
    # RoBERTa writes positions starting at padding_idx + 1 == 2.
    POS_OFFSET = 0

    # ------------------------------------------------------------------
    @classmethod
    def arch_config_source(cls, hf):
        """BertConfig lacks the decoder fields from_hf_config reads;
        shim them (attention values are real, rope fields inert)."""
        return SimpleNamespace(
            vocab_size=hf.vocab_size,
            hidden_size=hf.hidden_size,
            intermediate_size=hf.intermediate_size,
            num_hidden_layers=hf.num_hidden_layers,
            num_attention_heads=hf.num_attention_heads,
            num_key_value_heads=hf.num_attention_heads,
            head_dim=hf.hidden_size // hf.num_attention_heads,
            rms_norm_eps=getattr(hf, "layer_norm_eps", 1e-12),
            tie_word_embeddings=False,
        )

    @classmethod
    def configure_arch(cls, arch, hf) -> None:
        arch.encoder_only = True
        arch.norm_type = "layernorm"
        arch.norm_bias = True
        arch.mlp_gated = False
        arch.hidden_act = getattr(hf, "hidden_act", "gelu")
        arch.max_position_embeddings = hf.max_position_embeddings
        arch.type_vocab_size = max(int(getattr(hf, "type_vocab_size", 0)),
                                   1)
        arch.pos_offset = cls.POS_OFFSET
        arch.classify = cls.CLASSIFY
        arch.num_labels = int(getattr(hf, "num_labels", 2))

    def quantize_params(self, params: dict) -> dict:
        if self.cfg.quantization:
            raise ValueError(
                "weight quantization for encoder models is not wired "
                "yet; drop --quantization")
        return params

    # ------------------------------------------------------------------
    # Parameters
    # ------------------------------------------------------------------
    def param_specs(self) -> dict:
        layer = {
            "wq": P(None, None, MODEL_AXIS),
            "wk": P(None, None, MODEL_AXIS),
            "wv": P(None, None, MODEL_AXIS),
            "bq": P(None, MODEL_AXIS),
            "bk": P(None, MODEL_AXIS),
            "bv": P(None, MODEL_AXIS),
            "wo": P(None, MODEL_AXIS, None),
            "bo": P(None, None),
            "ln_attn": P(None, None),
            "ln_attn_b": P(None, None),
            "fc1": P(None, None, MODEL_AXIS),
            "fc1_b": P(None, MODEL_AXIS),
            "fc2": P(None, MODEL_AXIS, None),
            "fc2_b": P(None, None),
            "ln_mlp": P(None, None),
            "ln_mlp_b": P(None, None),
        }
        specs = {
            "embed": P(None, None),
            "embed_pos": P(None, None),
            "embed_type": P(None, None),
            "embed_ln": P(None),
            "embed_ln_b": P(None),
            "layers": layer,
        }
        if self.cfg.classify:
            specs.update({
                "pooler_w": P(None, None),
                "pooler_b": P(None),
                "cls_w": P(None, None),
                "cls_b": P(None),
            })
        return specs

    def kv_cache_specs(self) -> dict:
        return {}

    def kv_cache_page_bytes(self, page_size: int) -> int:
        return 0

    def make_kv_caches(self, num_pages: int, page_size: int,
                       mesh=None) -> dict:
        return {}

    def init_params(self, rng: jax.Array, scale: float = 0.02) -> dict:
        c = self.cfg
        H, I, L = c.hidden_size, c.intermediate_size, c.num_layers
        keys = iter(jax.random.split(rng, 16))

        def rnd(shape):
            return (jax.random.normal(next(keys), shape, jnp.float32) *
                    scale).astype(c.dtype)

        layer = {
            "wq": rnd((L, H, H)),
            "wk": rnd((L, H, H)),
            "wv": rnd((L, H, H)),
            "bq": jnp.zeros((L, H), c.dtype),
            "bk": jnp.zeros((L, H), c.dtype),
            "bv": jnp.zeros((L, H), c.dtype),
            "wo": rnd((L, H, H)),
            "bo": jnp.zeros((L, H), c.dtype),
            "ln_attn": jnp.ones((L, H), c.dtype),
            "ln_attn_b": jnp.zeros((L, H), c.dtype),
            "fc1": rnd((L, H, I)),
            "fc1_b": jnp.zeros((L, I), c.dtype),
            "fc2": rnd((L, I, H)),
            "fc2_b": jnp.zeros((L, H), c.dtype),
            "ln_mlp": jnp.ones((L, H), c.dtype),
            "ln_mlp_b": jnp.zeros((L, H), c.dtype),
        }
        params = {
            "embed": rnd((c.vocab_size, H)),
            "embed_pos": rnd((c.max_position_embeddings, H)),
            "embed_type": rnd((c.type_vocab_size, H)),
            "embed_ln": jnp.ones((H, ), c.dtype),
            "embed_ln_b": jnp.zeros((H, ), c.dtype),
            "layers": layer,
        }
        if c.classify:
            params.update({
                "pooler_w": rnd((H, H)),
                "pooler_b": jnp.zeros((H, ), c.dtype),
                "cls_w": rnd((H, c.num_labels)),
                "cls_b": jnp.zeros((c.num_labels, ), c.dtype),
            })
        return params

    # ------------------------------------------------------------------
    def params_from_hf_state_dict(self, tensors: dict[str, np.ndarray],
                                  dtype=None) -> dict:
        c = self.cfg
        dtype = dtype or c.dtype
        prefix = ""
        for cand in self.HF_PREFIXES:
            if f"{cand}embeddings.word_embeddings.weight" in tensors:
                prefix = cand
                break

        def t(name, required=True):
            full = prefix + name
            if full not in tensors and not required:
                return None
            return tensors[full]

        def a(x):
            return jnp.asarray(np.ascontiguousarray(x), dtype)

        def stack(fmt, transpose=True):
            mats = [np.asarray(tensors[prefix + fmt.format(i=i)])
                    for i in range(c.num_layers)]
            if transpose:
                mats = [m.T for m in mats]
            return a(np.stack(mats))

        type_emb = t("embeddings.token_type_embeddings.weight",
                     required=False)
        if type_emb is None:
            type_emb = np.zeros((c.type_vocab_size, c.hidden_size),
                                np.float32)
        params = {
            "embed": a(t("embeddings.word_embeddings.weight")),
            "embed_pos": a(t("embeddings.position_embeddings.weight")),
            "embed_type": a(type_emb),
            "embed_ln": a(t("embeddings.LayerNorm.weight")),
            "embed_ln_b": a(t("embeddings.LayerNorm.bias")),
            "layers": {
                "wq": stack("encoder.layer.{i}.attention.self.query.weight"),
                "wk": stack("encoder.layer.{i}.attention.self.key.weight"),
                "wv": stack("encoder.layer.{i}.attention.self.value.weight"),
                "bq": stack("encoder.layer.{i}.attention.self.query.bias",
                            transpose=False),
                "bk": stack("encoder.layer.{i}.attention.self.key.bias",
                            transpose=False),
                "bv": stack("encoder.layer.{i}.attention.self.value.bias",
                            transpose=False),
                "wo": stack("encoder.layer.{i}.attention.output.dense.weight"),
                "bo": stack("encoder.layer.{i}.attention.output.dense.bias",
                            transpose=False),
                "ln_attn": stack(
                    "encoder.layer.{i}.attention.output.LayerNorm.weight",
                    transpose=False),
                "ln_attn_b": stack(
                    "encoder.layer.{i}.attention.output.LayerNorm.bias",
                    transpose=False),
                "fc1": stack("encoder.layer.{i}.intermediate.dense.weight"),
                "fc1_b": stack("encoder.layer.{i}.intermediate.dense.bias",
                               transpose=False),
                "fc2": stack("encoder.layer.{i}.output.dense.weight"),
                "fc2_b": stack("encoder.layer.{i}.output.dense.bias",
                               transpose=False),
                "ln_mlp": stack("encoder.layer.{i}.output.LayerNorm.weight",
                                transpose=False),
                "ln_mlp_b": stack("encoder.layer.{i}.output.LayerNorm.bias",
                                  transpose=False),
            },
        }
        if c.classify:
            self._load_head(tensors, params, a)
        return params

    def _load_head(self, tensors, params, a) -> None:
        """Classification head: BERT = pooler.dense + classifier;
        RoBERTa = classifier.dense + classifier.out_proj (both are
        dense -> tanh -> proj over the CLS position)."""
        if "classifier.dense.weight" in tensors:  # roberta-style head
            params["pooler_w"] = a(np.asarray(
                tensors["classifier.dense.weight"]).T)
            params["pooler_b"] = a(tensors["classifier.dense.bias"])
            params["cls_w"] = a(np.asarray(
                tensors["classifier.out_proj.weight"]).T)
            params["cls_b"] = a(tensors["classifier.out_proj.bias"])
            return
        prefix = self.HF_PREFIXES[-1]
        pooler_w = tensors.get(f"{prefix}pooler.dense.weight")
        if pooler_w is None:
            pooler_w = tensors.get("pooler.dense.weight")
            prefix = ""
        params["pooler_w"] = a(np.asarray(pooler_w).T)
        params["pooler_b"] = a(tensors[f"{prefix}pooler.dense.bias"])
        params["cls_w"] = a(np.asarray(tensors["classifier.weight"]).T)
        params["cls_b"] = a(tensors["classifier.bias"])

    # ------------------------------------------------------------------
    # Forward
    # ------------------------------------------------------------------
    def _ln(self, x: jax.Array, w: jax.Array, b: jax.Array) -> jax.Array:
        xf = x.astype(jnp.float32)
        mu = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
        y = (xf - mu) * jax.lax.rsqrt(var + self.cfg.rms_norm_eps)
        return (y * w + b).astype(x.dtype)

    def _gelu(self, x: jax.Array) -> jax.Array:
        act = self.cfg.hidden_act
        if act == "gelu":
            return jax.nn.gelu(x, approximate=False)
        if act in ("gelu_new", "gelu_tanh", "gelu_pytorch_tanh",
                   "gelu_fast"):
            return jax.nn.gelu(x, approximate=True)
        if act == "quick_gelu":
            return x * jax.nn.sigmoid(1.702 * x)
        if act == "relu":
            return jax.nn.relu(x)
        if act == "silu":
            return jax.nn.silu(x)
        # Fail fast: a silent fallback would serve numerically wrong
        # embeddings with no error.
        raise ValueError(f"unsupported encoder hidden_act {act!r}")

    def encode(self, params: dict, token_ids: jax.Array,
               type_ids: jax.Array, valid: jax.Array) -> jax.Array:
        """Dense padded forward.

        token_ids/type_ids: [R, L] int32; valid: [R, L] bool.
        Returns last_hidden_state [R, L, H] (matches HF ``BertModel``).
        """
        c = self.cfg
        R, L = token_ids.shape
        nh, hd = c.num_q_heads, c.head_dim
        positions = jnp.clip(
            jnp.arange(L, dtype=jnp.int32) + c.pos_offset,
            0, c.max_position_embeddings - 1)
        x = (params["embed"][token_ids] +
             params["embed_pos"][positions][None, :, :] +
             params["embed_type"][jnp.clip(type_ids, 0,
                                           c.type_vocab_size - 1)])
        h = self._ln(x, params["embed_ln"], params["embed_ln_b"])
        # Additive key mask, shared across layers/heads/queries.
        bias = jnp.where(valid[:, None, None, :], 0.0, _NEG)  # [R,1,1,L]
        scale = hd**-0.5

        def body(h, lp):
            q = (h @ lp["wq"] + lp["bq"]).reshape(R, L, nh, hd)
            k = (h @ lp["wk"] + lp["bk"]).reshape(R, L, nh, hd)
            v = (h @ lp["wv"] + lp["bv"]).reshape(R, L, nh, hd)
            scores = jnp.einsum("rinh,rjnh->rnij", q, k,
                                preferred_element_type=jnp.float32)
            probs = jax.nn.softmax(scores * scale + bias, axis=-1)
            ctx = jnp.einsum("rnij,rjnh->rinh",
                             probs.astype(h.dtype), v).reshape(R, L, -1)
            h = self._ln(h + ctx @ lp["wo"] + lp["bo"],
                         lp["ln_attn"], lp["ln_attn_b"])
            m = self._gelu(h @ lp["fc1"] + lp["fc1_b"]) @ lp["fc2"]
            h = self._ln(h + m + lp["fc2_b"], lp["ln_mlp"], lp["ln_mlp_b"])
            return h, None

        h, _ = jax.lax.scan(body, h, params["layers"])
        return h

    def pool(self, params: dict, hidden: jax.Array,
             valid: jax.Array) -> dict:
        """All pooling variants at once (cheap relative to the encode):
        cls / mean / last vectors [R, H] and, for cross-encoder
        checkpoints, the per-row relevance score [R]."""
        validf = valid.astype(jnp.float32)[:, :, None]
        hf32 = hidden.astype(jnp.float32)
        lengths = jnp.maximum(validf.sum(axis=1), 1.0)
        mean = (hf32 * validf).sum(axis=1) / lengths
        cls = hf32[:, 0, :]
        last_idx = jnp.maximum(
            valid.astype(jnp.int32).sum(axis=1) - 1, 0)
        last = jnp.take_along_axis(
            hf32, last_idx[:, None, None], axis=1)[:, 0, :]
        out = {"cls": cls, "mean": mean, "last": last}
        if self.cfg.classify:
            pooled = jnp.tanh(
                cls.astype(self.cfg.dtype) @ params["pooler_w"] +
                params["pooler_b"]).astype(jnp.float32)
            logits = (pooled @ params["cls_w"].astype(jnp.float32) +
                      params["cls_b"].astype(jnp.float32))
            if self.cfg.num_labels == 1:
                # HF's get_cross_encoder_activation_function returns
                # Sigmoid for single-logit heads (reference
                # transformers_utils/config.py:787) — scores land in [0,1].
                score = jax.nn.sigmoid(logits[:, 0])
            else:
                # Two-label heads: probability of the positive class
                # (index 1). Checkpoints with >2 labels are rejected at
                # admission (see Processor) — the "relevance" class is
                # undefined for them.
                score = jax.nn.softmax(logits, axis=-1)[:, 1]
            out["score"] = score
            out["logits"] = logits
        return out


class BertForSequenceClassification(BertEmbeddingModel):
    """Cross-encoder scoring (arch "BertForSequenceClassification")."""

    CLASSIFY = True


class RobertaEmbeddingModel(BertEmbeddingModel):
    """RoBERTa / XLM-R encoder: positions offset by padding_idx + 1."""

    HF_PREFIXES = ("", "roberta.")
    POS_OFFSET = 2


class RobertaForSequenceClassification(RobertaEmbeddingModel):
    CLASSIFY = True
