"""GPTQ checkpoint loading: dequantize-on-load.

Reference: vllm/model_executor/layers/quantization/gptq.py (runtime
4-bit CUDA kernels over the packed layout). TPU-first translation: the
MXU has no 4-bit datapath, so packed GPTQ tensors are unpacked and
dequantized HOST-SIDE into ordinary fp weights during load — after
which the standard pipeline applies (optionally re-quantizing to the
w8a16 int8/fp8 schemes via --quantization, which halves HBM again).

Layout handled (AutoGPTQ v1 safetensors, the format of the vast
majority of HF "-GPTQ" checkpoints):
  * ``qweight`` int32 [in/pack, out] — ``pack``=32/bits values per
    word along the INPUT dim, low bits first.
  * ``qzeros`` int32 [groups, out/pack] — packed along OUTPUT; stores
    zero-point MINUS ONE (the historical AutoGPTQ bias, re-added here).
  * ``scales`` fp16 [groups, out].
  * ``g_idx`` int32 [in] — input row -> group map (covers desc_act
    act-order checkpoints; absent means contiguous groups).
Dequant: W[i, o] = scales[g_idx[i], o] * (q[i, o] - (z[g_idx[i], o]+1)).
"""

import numpy as np

from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)


def _unpack(packed: np.ndarray, bits: int, axis: int) -> np.ndarray:
    """Unpack int32 words into ``32/bits`` unsigned values along
    ``axis`` (low bits first, matching AutoGPTQ's pack order)."""
    pack = 32 // bits
    mask = (1 << bits) - 1
    shifts = (np.arange(pack, dtype=np.uint32) * bits)
    words = packed.astype(np.uint32)
    expanded = (words[..., None] >> shifts) & mask  # [..., pack] last
    # Move the pack dim next to `axis` and merge.
    expanded = np.moveaxis(expanded, -1, axis + 1)
    shape = list(packed.shape)
    shape[axis] *= pack
    return expanded.reshape(shape)


def dequantize_gptq_layer(qweight: np.ndarray, qzeros: np.ndarray,
                          scales: np.ndarray, g_idx, bits: int,
                          group_size: int) -> np.ndarray:
    """One packed linear -> fp32 [out, in] (torch Linear orientation)."""
    q = _unpack(qweight, bits, axis=0)          # [in, out]
    z = _unpack(qzeros, bits, axis=1)           # [groups, out]
    in_dim = q.shape[0]
    if group_size <= 0:
        group_size = in_dim  # group_size=-1: one group spans the input
    if g_idx is None:
        g_idx = np.arange(in_dim, dtype=np.int64) // group_size
    else:
        g_idx = np.asarray(g_idx, np.int64)
    w = (scales.astype(np.float32)[g_idx]
         * (q.astype(np.float32) - (z.astype(np.float32) + 1.0)[g_idx]))
    # C-contiguous, not a transpose view: astype(order='K') keeps
    # F-order, and safetensors serializes raw buffers assuming C-order.
    return np.ascontiguousarray(w.T)  # [out, in]


def dequantize_gptq_state_dict(tensors: dict, bits: int,
                               group_size: int) -> dict:
    """Replace every packed GPTQ linear in an HF state dict with its
    dequantized ``.weight``; non-quantized tensors (embeddings, norms,
    lm_head) pass through."""
    return _dequantize_state_dict(
        tensors, "GPTQ", (".qzeros", ".scales", ".g_idx"),
        lambda base: dequantize_gptq_layer(
            np.asarray(tensors[base + ".qweight"]),
            np.asarray(tensors[base + ".qzeros"]),
            np.asarray(tensors[base + ".scales"]),
            tensors.get(base + ".g_idx"), bits, group_size))


def maybe_dequantize_gptq(tensors: dict, hf_config,
                          model_path: str = "") -> dict:
    """Apply GPTQ dequant when the HF config declares it; no-op
    otherwise. Raises for formats this loader does not handle.

    Older AutoGPTQ exports ship ``quantize_config.json`` beside the
    shards instead of a config.json quantization_config entry — read it
    as a fallback so those checkpoints load too."""
    qcfg = getattr(hf_config, "quantization_config", None)
    if qcfg is None and model_path:
        import json
        import os
        legacy = os.path.join(model_path, "quantize_config.json")
        if os.path.exists(legacy):
            with open(legacy) as f:
                qcfg = dict(json.load(f), quant_method="gptq")
    if qcfg is None:
        if any(name.endswith(".qweight") for name in tensors):
            raise ValueError(
                "checkpoint contains packed .qweight tensors but "
                "declares no quantization_config (and has no "
                "quantize_config.json); cannot identify the "
                "quantization format")
        return tensors
    get = (qcfg.get if isinstance(qcfg, dict)
           else lambda k, d=None: getattr(qcfg, k, d))
    method = get("quant_method")
    if method == "awq":
        if int(get("bits", get("w_bit", 4))) != 4:
            raise ValueError("only 4-bit AWQ checkpoints are supported")
        version = get("version", get("backend", "gemm"))
        if version is not None:
            version = str(version).lower().rsplit(".", 1)[-1]
        if version not in ("gemm", None):
            raise ValueError(
                f"only AWQ 'gemm'-format checkpoints are supported "
                f"(got version={version!r})")
        if get("zero_point", True) is False:
            raise ValueError("symmetric (zero_point=false) AWQ "
                             "checkpoints are not supported")
        gs = int(get("group_size", get("q_group_size", 128)))
        return dequantize_awq_state_dict(tensors, gs)
    if method != "gptq":
        raise ValueError(
            f"checkpoint declares quantization_config.quant_method="
            f"{method!r}; only 'gptq' and 'awq' checkpoints are "
            "supported")
    if get("checkpoint_format", "gptq") not in ("gptq", None):
        raise ValueError(
            "only the v1 'gptq' checkpoint_format is supported "
            f"(got {get('checkpoint_format')!r})")
    bits = int(get("bits", 4))
    if 32 % bits != 0:
        raise ValueError(f"unsupported GPTQ bits={bits}")
    group_size = int(get("group_size", 128))
    return dequantize_gptq_state_dict(tensors, bits, group_size)


# ---------------------------------------------------------------------------
# AWQ (AutoAWQ "gemm" format)
# ---------------------------------------------------------------------------

# AWQ packs 8 int4 values per word along the OUTPUT dim in the
# interleaved order [0, 2, 4, 6, 1, 3, 5, 7]; this is the inverse
# permutation restoring real column order after a low-bits-first unpack
# (the AWQ_REVERSE_ORDER constant of AutoAWQ / the reference's
# awq dequant kernels, csrc/quantization/awq/dequantize.cuh).
_AWQ_REVERSE_ORDER = np.array([0, 4, 1, 5, 2, 6, 3, 7])


def _awq_reorder(a: np.ndarray) -> np.ndarray:
    out = a.shape[1]
    idx = np.arange(out).reshape(-1, 8)[:, _AWQ_REVERSE_ORDER].reshape(-1)
    return a[:, idx]


def dequantize_awq_layer(qweight: np.ndarray, qzeros: np.ndarray,
                         scales: np.ndarray,
                         group_size: int) -> np.ndarray:
    """One packed AWQ linear -> fp32 [out, in].

    Layout (AutoAWQ gemm): ``qweight`` int32 [in, out/8] and ``qzeros``
    int32 [in/group, out/8], both packed along OUTPUT in AWQ order;
    ``scales`` fp16 [in/group, out].
    Dequant: W[i, o] = scales[g, o] * (q[i, o] - z[g, o])."""
    q = _awq_reorder(_unpack(np.asarray(qweight), 4, axis=1))
    z = _awq_reorder(_unpack(np.asarray(qzeros), 4, axis=1))
    in_dim = q.shape[0]
    gs = group_size if group_size > 0 else in_dim
    g_idx = np.arange(in_dim, dtype=np.int64) // gs
    w = (np.asarray(scales, np.float32)[g_idx]
         * (q.astype(np.float32) - z.astype(np.float32)[g_idx]))
    return np.ascontiguousarray(w.T)


def dequantize_awq_state_dict(tensors: dict, group_size: int) -> dict:
    return _dequantize_state_dict(
        tensors, "AWQ", (".qzeros", ".scales"),
        lambda base: dequantize_awq_layer(
            tensors[base + ".qweight"], tensors[base + ".qzeros"],
            tensors[base + ".scales"], group_size))


def _dequantize_state_dict(tensors: dict, tag: str,
                           companions: tuple, dequant_one) -> dict:
    """Shared packed-linear walker: every ``.qweight`` becomes a plain
    ``.weight`` via ``dequant_one(base)``; companion tensors of a packed
    linear are dropped; everything else passes through."""
    out = {}
    n = 0
    for name, val in tensors.items():
        if name.endswith(".qweight"):
            base = name[:-len(".qweight")]
            out[base + ".weight"] = dequant_one(base)
            n += 1
        elif name.endswith(companions) and (
                name.rsplit(".", 1)[0] + ".qweight") in tensors:
            continue
        else:
            out[name] = val
    logger.info("dequantized %d %s linears on load", n, tag)
    return out
