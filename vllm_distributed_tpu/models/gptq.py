"""GPTQ checkpoint loading: dequantize-on-load.

Reference: vllm/model_executor/layers/quantization/gptq.py (runtime
4-bit CUDA kernels over the packed layout). TPU-first translation: the
MXU has no 4-bit datapath, so packed GPTQ tensors are unpacked and
dequantized HOST-SIDE into ordinary fp weights during load — after
which the standard pipeline applies (optionally re-quantizing to the
w8a16 int8/fp8 schemes via --quantization, which halves HBM again).

Layout handled (AutoGPTQ v1 safetensors, the format of the vast
majority of HF "-GPTQ" checkpoints):
  * ``qweight`` int32 [in/pack, out] — ``pack``=32/bits values per
    word along the INPUT dim, low bits first.
  * ``qzeros`` int32 [groups, out/pack] — packed along OUTPUT; stores
    zero-point MINUS ONE (the historical AutoGPTQ bias, re-added here).
  * ``scales`` fp16 [groups, out].
  * ``g_idx`` int32 [in] — input row -> group map (covers desc_act
    act-order checkpoints; absent means contiguous groups).
Dequant: W[i, o] = scales[g_idx[i], o] * (q[i, o] - (z[g_idx[i], o]+1)).
"""

import numpy as np

from vllm_distributed_tpu.logger import init_logger

logger = init_logger(__name__)


def _unpack(packed: np.ndarray, bits: int, axis: int) -> np.ndarray:
    """Unpack int32 words into ``32/bits`` unsigned values along
    ``axis`` (low bits first, matching AutoGPTQ's pack order)."""
    pack = 32 // bits
    mask = (1 << bits) - 1
    shifts = (np.arange(pack, dtype=np.uint32) * bits)
    words = packed.astype(np.uint32)
    expanded = (words[..., None] >> shifts) & mask  # [..., pack] last
    # Move the pack dim next to `axis` and merge.
    expanded = np.moveaxis(expanded, -1, axis + 1)
    shape = list(packed.shape)
    shape[axis] *= pack
    return expanded.reshape(shape)


def dequantize_gptq_layer(qweight: np.ndarray, qzeros: np.ndarray,
                          scales: np.ndarray, g_idx, bits: int,
                          group_size: int) -> np.ndarray:
    """One packed linear -> fp32 [out, in] (torch Linear orientation)."""
    q = _unpack(qweight, bits, axis=0)          # [in, out]
    z = _unpack(qzeros, bits, axis=1)           # [groups, out]
    in_dim = q.shape[0]
    if group_size <= 0:
        group_size = in_dim  # group_size=-1: one group spans the input
    if g_idx is None:
        g_idx = np.arange(in_dim, dtype=np.int64) // group_size
    else:
        g_idx = np.asarray(g_idx, np.int64)
    w = (scales.astype(np.float32)[g_idx]
         * (q.astype(np.float32) - (z.astype(np.float32) + 1.0)[g_idx]))
    # C-contiguous, not a transpose view: astype(order='K') keeps
    # F-order, and safetensors serializes raw buffers assuming C-order.
    return np.ascontiguousarray(w.T)  # [out, in]


def dequantize_gptq_state_dict(tensors: dict, bits: int,
                               group_size: int) -> dict:
    """Replace every packed GPTQ linear in an HF state dict with its
    dequantized ``.weight``; non-quantized tensors (embeddings, norms,
    lm_head) pass through."""
    out = {}
    n = 0
    for name, val in tensors.items():
        if name.endswith(".qweight"):
            base = name[:-len(".qweight")]
            out[base + ".weight"] = dequantize_gptq_layer(
                np.asarray(val), np.asarray(tensors[base + ".qzeros"]),
                np.asarray(tensors[base + ".scales"]),
                tensors.get(base + ".g_idx"), bits, group_size)
            n += 1
        elif name.endswith((".qzeros", ".scales", ".g_idx")) and (
                name.rsplit(".", 1)[0] + ".qweight") in tensors:
            continue
        else:
            out[name] = val
    logger.info("dequantized %d GPTQ linears (%d-bit, group %d)", n,
                bits, group_size)
    return out


def maybe_dequantize_gptq(tensors: dict, hf_config,
                          model_path: str = "") -> dict:
    """Apply GPTQ dequant when the HF config declares it; no-op
    otherwise. Raises for formats this loader does not handle.

    Older AutoGPTQ exports ship ``quantize_config.json`` beside the
    shards instead of a config.json quantization_config entry — read it
    as a fallback so those checkpoints load too."""
    qcfg = getattr(hf_config, "quantization_config", None)
    if qcfg is None and model_path:
        import json
        import os
        legacy = os.path.join(model_path, "quantize_config.json")
        if os.path.exists(legacy):
            with open(legacy) as f:
                qcfg = dict(json.load(f), quant_method="gptq")
    if qcfg is None:
        if any(name.endswith(".qweight") for name in tensors):
            raise ValueError(
                "checkpoint contains packed .qweight tensors but "
                "declares no quantization_config (and has no "
                "quantize_config.json); cannot identify the "
                "quantization format")
        return tensors
    get = (qcfg.get if isinstance(qcfg, dict)
           else lambda k, d=None: getattr(qcfg, k, d))
    method = get("quant_method")
    if method != "gptq":
        raise ValueError(
            f"checkpoint declares quantization_config.quant_method="
            f"{method!r}; only 'gptq' checkpoints are supported "
            "(AWQ/others need their own unpackers)")
    if get("checkpoint_format", "gptq") not in ("gptq", None):
        raise ValueError(
            "only the v1 'gptq' checkpoint_format is supported "
            f"(got {get('checkpoint_format')!r})")
    bits = int(get("bits", 4))
    if 32 % bits != 0:
        raise ValueError(f"unsupported GPTQ bits={bits}")
    group_size = int(get("group_size", 128))
    return dequantize_gptq_state_dict(tensors, bits, group_size)
