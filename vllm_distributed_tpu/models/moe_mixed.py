"""Mixed dense/sparse MoE decoders (dense prefix + routed rest).

Reference: vllm/model_executor/models/ernie45_moe.py and glm4_moe.py —
modern MoE families run their first layer(s) as PLAIN dense decoder
blocks (first_k_dense_replace / moe_layer_start_index) before the
routed stack. TPU-first mechanism: the dense prefix is its own stacked
subtree (``layers_dense``) built by a throwaway dense submodel and run
through ``run_layers`` first; the sparse stack follows with
``cache_layer_offset`` shifting its KV rows past the prefix
(models/llama.py forward). No per-layer branching inside the scan —
each stack keeps uniform leaves.

Constraints: pipeline parallelism and LoRA are rejected for mixed
layouts (stage slicing and adapter buffers assume one uniform layer
stack); weight quantization applies to the sparse stack only.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from vllm_distributed_tpu.models.llama import (MODEL_AXIS,
                                               LlamaArchConfig,
                                               LlamaForCausalLM)
from vllm_distributed_tpu.models.mixtral import MixtralForCausalLM


class _DensePrefixMoe(MixtralForCausalLM):
    """Shared machinery: ``cfg.dense_prefix`` plain layers, then the
    Mixtral-style routed stack."""

    def _submodels(self):
        c = self.cfg
        k = c.dense_prefix
        dense_cfg = dataclasses.replace(
            c, num_layers=k, num_experts=0, dense_prefix=0)
        sparse_cfg = dataclasses.replace(
            c, num_layers=c.num_layers - k, dense_prefix=0)
        return (LlamaForCausalLM(dense_cfg),
                type(self)(sparse_cfg))

    @staticmethod
    def _shift_layer_names(tensors: dict, start: int,
                           count: int) -> dict:
        out = {}
        for name, t in tensors.items():
            if name.startswith("model.layers."):
                rest = name[len("model.layers."):]
                idx, _, tail = rest.partition(".")
                i = int(idx)
                if start <= i < start + count:
                    out[f"model.layers.{i - start}.{tail}"] = t
            else:
                out[name] = t
        return out

    # ------------------------------------------------------------------
    def param_specs(self) -> dict:
        if not self.cfg.dense_prefix:
            return super().param_specs()
        dense_m, sparse_m = self._submodels()
        specs = sparse_m.param_specs()
        specs["layers_dense"] = dense_m.param_specs()["layers"]
        return specs

    def init_params(self, rng, scale: float = 0.02) -> dict:
        if not self.cfg.dense_prefix:
            return super().init_params(rng, scale)
        dense_m, sparse_m = self._submodels()
        params = sparse_m.init_params(rng, scale)
        params["layers_dense"] = dense_m.init_params(
            jax.random.fold_in(rng, 31), scale)["layers"]
        return params

    def params_from_hf_state_dict(self, tensors) -> dict:
        if not self.cfg.dense_prefix:
            return super().params_from_hf_state_dict(tensors)
        c = self.cfg
        k = c.dense_prefix
        dense_m, sparse_m = self._submodels()
        params = sparse_m.params_from_hf_state_dict(
            self._shift_layer_names(tensors, k, c.num_layers - k))
        params["layers_dense"] = dense_m.params_from_hf_state_dict(
            self._shift_layer_names(tensors, 0, k))["layers"]
        return params

    def quantize_params(self, params: dict) -> dict:
        if self.cfg.quantization and self.cfg.dense_prefix:
            raise ValueError(
                "weight quantization for mixed dense/sparse MoE "
                "layouts is not wired; drop --quantization")
        return super().quantize_params(params)


class Ernie45MoeForCausalLM(_DensePrefixMoe):
    """Baidu ERNIE-4.5 MoE (reference: models/ernie45_moe.py): dense
    prefix (moe_layer_start_index), softmax routing with an
    e_score_correction_bias applied for SELECTION only (weights are the
    raw softmax probs of the selected experts, normalized with a
    moe_norm_min clamp), plus an ungated dense shared expert of width
    moe_intermediate_size * moe_num_shared_experts."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        L = arch.num_layers
        arch.num_experts = hf.moe_num_experts
        arch.num_experts_per_tok = hf.moe_k
        arch.moe_intermediate_size = hf.moe_intermediate_size
        arch.shared_expert_intermediate_size = (
            hf.moe_intermediate_size *
            int(getattr(hf, "moe_num_shared_experts", 0) or 0))
        start = int(getattr(hf, "moe_layer_start_index", 0) or 0)
        end = getattr(hf, "moe_layer_end_index", None)
        end = L - 1 if end is None else int(end)
        if (int(getattr(hf, "moe_layer_interval", 1) or 1) != 1
                or end != L - 1):
            raise ValueError(
                "only contiguous dense-prefix ERNIE MoE layouts are "
                "supported (moe_layer_interval=1, moe_layer_end_index "
                "= last layer)")
        arch.dense_prefix = start
        arch.moe_norm_min = float(getattr(hf, "moe_norm_min", 1e-12))
        if bool(getattr(hf, "use_bias", False)):
            raise ValueError("ERNIE use_bias checkpoints are not "
                             "supported")

    # ---- routing ------------------------------------------------------
    def _route(self, lp, x):
        c = self.cfg
        logits = (x.astype(jnp.float32)
                  @ lp["router"].astype(jnp.float32))  # [T, E]
        probs = jax.nn.softmax(logits, axis=-1)
        _, top_idx = jax.lax.top_k(
            probs + lp["router_bias"].astype(jnp.float32)[None, :],
            c.num_experts_per_tok)
        top_vals = jnp.take_along_axis(probs, top_idx, axis=-1)
        top_vals = top_vals / jnp.maximum(
            top_vals.sum(axis=-1, keepdims=True), c.moe_norm_min)
        return top_idx, top_vals

    def mlp_block(self, lp: dict, x, lora_ctx=None):
        if "router" not in lp:  # dense-prefix subtree
            return LlamaForCausalLM.mlp_block(self, lp, x, lora_ctx)
        routed = super().mlp_block(lp, x, lora_ctx)
        if not self.cfg.shared_expert_intermediate_size:
            return routed
        from vllm_distributed_tpu.models.common import swiglu
        return routed + swiglu(x, lp["shared_gate"], lp["shared_up"],
                               lp["shared_down"], act=self._act)

    # ---- params (the dense-prefix case delegates entirely to the
    # sparse submodel inside _DensePrefixMoe, which re-enters these
    # methods with dense_prefix == 0) -----------------------------------
    def param_specs(self) -> dict:
        specs = super().param_specs()
        if self.cfg.dense_prefix:
            return specs
        layer = specs["layers"]
        layer["router_bias"] = P(None, None)  # [L, E]
        if self.cfg.shared_expert_intermediate_size:
            layer.update({
                "shared_gate": P(None, None, MODEL_AXIS),
                "shared_up": P(None, None, MODEL_AXIS),
                "shared_down": P(None, MODEL_AXIS, None),
            })
        return specs

    def init_params(self, rng, scale: float = 0.02) -> dict:
        params = super().init_params(rng, scale)
        c = self.cfg
        if c.dense_prefix:
            return params
        Ls = c.num_layers
        layers = params["layers"]
        layers["router_bias"] = jnp.zeros((Ls, c.num_experts),
                                          jnp.float32)
        Is = c.shared_expert_intermediate_size
        if Is:
            keys = iter(jax.random.split(jax.random.fold_in(rng, 37), 3))

            def norm(key, shape):
                return (scale * jax.random.normal(
                    key, shape, jnp.float32)).astype(c.dtype)

            layers.update({
                "shared_gate": norm(next(keys), (Ls, c.hidden_size, Is)),
                "shared_up": norm(next(keys), (Ls, c.hidden_size, Is)),
                "shared_down": norm(next(keys), (Ls, Is, c.hidden_size)),
            })
        return params

    def params_from_hf_state_dict(self, tensors) -> dict:
        c = self.cfg
        if c.dense_prefix:
            return super().params_from_hf_state_dict(tensors)
        # Sparse stack (possibly the submodel for the sparse slice):
        # map ERNIE names onto the Mixtral layout + side tensors.
        from vllm_distributed_tpu.models.families_ext import \
            _alias_moe_experts
        L = c.num_layers
        params = MixtralForCausalLM.params_from_hf_state_dict(
            self, _alias_moe_experts(tensors, L, c.num_experts))
        layers = params["layers"]
        layers["router_bias"] = jnp.asarray(np.stack([
            np.asarray(tensors[f"model.layers.{i}.mlp.moe_statics."
                               f"e_score_correction_bias"]).reshape(-1)
            for i in range(L)
        ]), jnp.float32)
        Is = c.shared_expert_intermediate_size
        if Is:
            def stack(fmt):
                return jnp.asarray(np.stack([
                    np.asarray(tensors[fmt.format(i)]).T
                    for i in range(L)
                ]), c.dtype)

            layers.update({
                "shared_gate": stack("model.layers.{}.mlp."
                                     "shared_experts.gate_proj.weight"),
                "shared_up": stack("model.layers.{}.mlp."
                                   "shared_experts.up_proj.weight"),
                "shared_down": stack("model.layers.{}.mlp."
                                     "shared_experts.down_proj.weight"),
            })
        return params


class Glm4MoeForCausalLM(_DensePrefixMoe):
    """GLM-4-MoE (reference: models/glm4_moe.py): dense prefix
    (first_k_dense_replace), DeepSeek-V3-style routing (sigmoid scores,
    e_score_correction_bias for SELECTION with top-2-sum group
    limiting, weights from the raw sigmoid, optional renormalize,
    routed_scaling_factor), ungated shared experts, partial rotary and
    optional per-head qk norm on a standard-attention llama block."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        arch.num_experts = hf.n_routed_experts
        arch.num_experts_per_tok = hf.num_experts_per_tok
        arch.moe_intermediate_size = hf.moe_intermediate_size
        arch.shared_expert_intermediate_size = (
            hf.moe_intermediate_size *
            int(getattr(hf, "n_shared_experts", 0) or 0))
        arch.dense_prefix = int(
            getattr(hf, "first_k_dense_replace", 0) or 0)
        arch.norm_topk_prob = bool(getattr(hf, "norm_topk_prob", True))
        arch.routed_scaling_factor = float(
            getattr(hf, "routed_scaling_factor", 1.0) or 1.0)
        arch.n_group = int(getattr(hf, "n_group", 1) or 1)
        arch.topk_group = int(getattr(hf, "topk_group", 1) or 1)
        arch.qk_norm = bool(getattr(hf, "use_qk_norm", False))
        arch.attention_bias = bool(getattr(hf, "attention_bias", False))
        factor = float(getattr(hf, "partial_rotary_factor", 1.0) or 1.0)
        if factor != 1.0:
            arch.rotary_dim = int(arch.head_dim * factor)

    # ---- routing (DeepSeek-V3 noaux_tc on sigmoid scores) -------------
    def _route(self, lp, x):
        from vllm_distributed_tpu.models.deepseek import \
            DeepseekV2ForCausalLM
        c = self.cfg
        T = x.shape[0]
        E = c.num_experts
        logits = (x.astype(jnp.float32)
                  @ lp["router"].astype(jnp.float32))
        scores = jax.nn.sigmoid(logits)
        choice = scores + lp["router_bias"].astype(jnp.float32)[None, :]
        G = c.n_group
        grp = choice.reshape(T, G, E // G)
        top2 = jax.lax.top_k(grp, min(2, E // G))[0].sum(axis=-1)
        sel = DeepseekV2ForCausalLM._group_mask(top2, c.topk_group, G, E)
        masked = jnp.where(sel, choice, 0.0)
        top_idx = jax.lax.top_k(masked, c.num_experts_per_tok)[1]
        top_vals = jnp.take_along_axis(scores, top_idx, axis=-1)
        if c.norm_topk_prob:
            top_vals = top_vals / (
                top_vals.sum(axis=-1, keepdims=True) + 1e-20)
        return top_idx, top_vals * c.routed_scaling_factor

    def mlp_block(self, lp: dict, x, lora_ctx=None):
        if "router" not in lp:  # dense-prefix subtree
            return LlamaForCausalLM.mlp_block(self, lp, x, lora_ctx)
        routed = super().mlp_block(lp, x, lora_ctx)
        if not self.cfg.shared_expert_intermediate_size:
            return routed
        from vllm_distributed_tpu.models.common import swiglu
        return routed + swiglu(x, lp["shared_gate"], lp["shared_up"],
                               lp["shared_down"], act=self._act)

    # ---- params -------------------------------------------------------
    def param_specs(self) -> dict:
        specs = super().param_specs()
        if self.cfg.dense_prefix:
            return specs
        layer = specs["layers"]
        layer["router_bias"] = P(None, None)
        if self.cfg.shared_expert_intermediate_size:
            layer.update({
                "shared_gate": P(None, None, MODEL_AXIS),
                "shared_up": P(None, None, MODEL_AXIS),
                "shared_down": P(None, MODEL_AXIS, None),
            })
        return specs

    def init_params(self, rng, scale: float = 0.02) -> dict:
        params = super().init_params(rng, scale)
        c = self.cfg
        if c.dense_prefix:
            return params
        layers = params["layers"]
        layers["router_bias"] = jnp.zeros((c.num_layers, c.num_experts),
                                          jnp.float32)
        Is = c.shared_expert_intermediate_size
        if Is:
            keys = iter(jax.random.split(jax.random.fold_in(rng, 41), 3))

            def norm(key, shape):
                return (scale * jax.random.normal(
                    key, shape, jnp.float32)).astype(c.dtype)

            layers.update({
                "shared_gate": norm(next(keys),
                                    (c.num_layers, c.hidden_size, Is)),
                "shared_up": norm(next(keys),
                                  (c.num_layers, c.hidden_size, Is)),
                "shared_down": norm(next(keys),
                                    (c.num_layers, Is, c.hidden_size)),
            })
        return params

    def params_from_hf_state_dict(self, tensors) -> dict:
        c = self.cfg
        if c.dense_prefix:
            return super().params_from_hf_state_dict(tensors)
        from vllm_distributed_tpu.models.families_ext import \
            _alias_moe_experts
        L = c.num_layers
        params = MixtralForCausalLM.params_from_hf_state_dict(
            self, _alias_moe_experts(tensors, L, c.num_experts))
        layers = params["layers"]
        layers["router_bias"] = jnp.asarray(np.stack([
            np.asarray(tensors[f"model.layers.{i}.mlp.gate."
                               f"e_score_correction_bias"]).reshape(-1)
            for i in range(L)
        ]), jnp.float32)
        Is = c.shared_expert_intermediate_size
        if Is:
            def stack(fmt):
                return jnp.asarray(np.stack([
                    np.asarray(tensors[fmt.format(i)]).T
                    for i in range(L)
                ]), c.dtype)

            layers.update({
                "shared_gate": stack("model.layers.{}.mlp."
                                     "shared_experts.gate_proj.weight"),
                "shared_up": stack("model.layers.{}.mlp."
                                   "shared_experts.up_proj.weight"),
                "shared_down": stack("model.layers.{}.mlp."
                                     "shared_experts.down_proj.weight"),
            })
        return params


class Dots1ForCausalLM(Glm4MoeForCausalLM):
    """rednote dots.llm1 (reference: models/dots1.py): the GLM-4-MoE
    recipe — dense prefix, V3-style sigmoid/group routing with
    e_score_correction_bias, shared experts — with ALWAYS-on per-head
    q/k RMSNorm, full rotary, and optional sliding layer_types through
    the generic window resolver."""

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        super().configure_arch(arch, hf)
        arch.qk_norm = True
        arch.rotary_dim = None  # full rotary (no partial factor)
