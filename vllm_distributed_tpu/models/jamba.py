"""Jamba: hybrid Mamba-1 / attention decoder with interleaved MoE.

Reference surface: vllm/model_executor/models/jamba.py (layer pattern
from attn_layer_period/offset + expert_layer_period/offset, Mamba mixer
with learned dt/B/C layernorms, NoPE attention, Mixtral-style MoE
without top-k renormalization), with hybrid KV groups sizing attention
pages separately from mamba state
(vllm/v1/kv_cache_interface.py FullAttentionSpec + MambaSpec groups).

TPU design: this is the framework's hybrid-cache-group model — the
cache dict carries BOTH paged K/V stacked over the attention layers
only ([La, pages, ...]: kv_cache_page_bytes charges La, not L — a
4x page-memory saving at Jamba's 1:7 attention:mamba ratio) and
fixed-size per-request conv/ssm state rows stacked over the mamba
layers ([Lm, S, ...], charged via fixed_cache_bytes). The mamba mixers
run the segmented associative scan of ops/mamba.py on the flat ragged
batch; attention layers are plain paged attention without rotary
embeddings (Jamba uses none). MoE layers reuse the Mixtral grouped-GEMM
dispatch verbatim (models/mixtral.py moe_dispatch).

Layers are heterogeneous, so run_layers walks them as an unrolled
Python loop over the four block kinds (attn/mamba x dense/moe), each
kind's parameters stacked separately; at Jamba's scale (32 layers) the
unroll compiles once per token bucket like every other model.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from vllm_distributed_tpu.models.common import rms_norm, swiglu
from vllm_distributed_tpu.models.llama import MODEL_AXIS, TOKEN_AXIS
from vllm_distributed_tpu.models.mamba import MambaForCausalLM
from vllm_distributed_tpu.models.mixtral import MixtralForCausalLM
from vllm_distributed_tpu.ops.attention import (paged_attention,
                                                storage_head_dim,
                                                write_kv_cache)
from vllm_distributed_tpu.ops.mamba import build_segment_info


class JambaForCausalLM(MixtralForCausalLM):
    """Hybrid attention/Mamba stack with periodic MoE FFNs."""

    QUANT_TARGETS = ()
    LORA_TARGETS = ()
    STATEFUL = True
    # Hybrid stack: state restores must re-enter coherently with the
    # attention layers' cached KV pages (core/state_cache.py requires
    # every prefix page resident for a hit).
    STATE_ONLY = False

    def quantize_params(self, params: dict) -> dict:
        if self.cfg.quantization:
            raise ValueError(
                "weight quantization for hybrid SSM stacks is not "
                "wired yet; drop --quantization for Jamba")
        return params

    @classmethod
    def configure_arch(cls, arch, hf) -> None:
        arch.stateful = True
        # Mamba mixer geometry (names shared with models/mamba.py so
        # MambaForCausalLM._mixer runs unchanged).
        arch.ssm_state_size = hf.mamba_d_state
        arch.conv_kernel = hf.mamba_d_conv
        arch.d_inner = hf.mamba_expand * hf.hidden_size
        arch.dt_rank = (hf.mamba_dt_rank if hf.mamba_dt_rank != "auto"
                        else -(-hf.hidden_size // 16))
        arch.use_conv_bias = bool(getattr(hf, "mamba_conv_bias", True))
        if getattr(hf, "mamba_proj_bias", False):
            raise ValueError(
                "Jamba mamba_proj_bias checkpoints are not supported "
                "(no published model sets it)")
        arch.use_bias = False
        # Layer pattern.
        arch.attn_period = hf.attn_layer_period
        arch.attn_offset = hf.attn_layer_offset
        arch.expert_period = hf.expert_layer_period
        arch.expert_offset = hf.expert_layer_offset
        n_exp = getattr(hf, "num_experts", 1)
        arch.num_experts = n_exp if n_exp > 1 else 0
        arch.num_experts_per_tok = getattr(hf, "num_experts_per_tok", 2)
        arch.norm_topk_prob = False  # Jamba does not renormalize top-k
        if not hasattr(arch, "state_slots"):
            arch.state_slots = 0

    # ------------------------------------------------------------------
    # Layer pattern helpers (static python ints — part of the compiled
    # program structure, like the window segments of models/llama.py)
    # ------------------------------------------------------------------
    def _is_attn(self, i: int) -> bool:
        return i % self.cfg.attn_period == self.cfg.attn_offset

    def _is_moe(self, i: int) -> bool:
        return (self.cfg.num_experts > 0
                and i % self.cfg.expert_period == self.cfg.expert_offset)

    @property
    def _attn_layers(self) -> list:
        return [i for i in range(self.cfg.num_layers) if self._is_attn(i)]

    @property
    def _mamba_layers(self) -> list:
        return [i for i in range(self.cfg.num_layers)
                if not self._is_attn(i)]

    @property
    def _moe_layers(self) -> list:
        return [i for i in range(self.cfg.num_layers) if self._is_moe(i)]

    @property
    def _dense_layers(self) -> list:
        return [i for i in range(self.cfg.num_layers)
                if not self._is_moe(i)]

    # ------------------------------------------------------------------
    # Parameters: one stacked subtree per block kind, flat "a_/m_/d_/e_"
    # prefixed keys so the loader's per-key placement applies unchanged.
    # ------------------------------------------------------------------
    def param_specs(self) -> dict:
        c = self.cfg
        col = P(None, None, MODEL_AXIS)
        row = P(None, MODEL_AXIS, None)
        layer = {
            # attention stack [La, ...]
            "a_ln": P(None, None),
            "a_wq": col, "a_wk": col, "a_wv": col, "a_wo": row,
            # mamba stack [Lm, ...]
            "m_norm": P(None, None),
            "m_in_x": col, "m_in_z": col,
            "m_conv_w": col,
            "m_x_proj": row,
            "m_dt_w": col, "m_dt_b": P(None, MODEL_AXIS),
            "m_dt_ln": P(None, None), "m_b_ln": P(None, None),
            "m_c_ln": P(None, None),
            "m_A_log": P(None, MODEL_AXIS, None),
            "m_D": P(None, MODEL_AXIS),
            "m_out_proj": row,
            # dense-FFN stack [Ld, ...]
            "d_pre_ln": P(None, None),
            "d_gate": col, "d_up": col, "d_down": row,
        }
        if c.use_conv_bias:
            layer["m_conv_b"] = P(None, MODEL_AXIS)
        if c.num_experts:
            ffn = P(None, None, None, MODEL_AXIS)
            layer.update({
                "e_pre_ln": P(None, None),
                "e_router": P(None, None, None),
                "e_w_gate": ffn, "e_w_up": ffn,
                "e_w_down": P(None, None, MODEL_AXIS, None),
            })
        return {
            "embed": P(None, None),
            "layers": layer,
            "final_ln": P(None, ),
            "lm_head": P(None, MODEL_AXIS),
        }

    def init_params(self, rng: jax.Array, scale: float = 0.02) -> dict:
        c = self.cfg
        H, I = c.hidden_size, c.intermediate_size
        Di, N, K, R = c.d_inner, c.ssm_state_size, c.conv_kernel, c.dt_rank
        La, Lm = len(self._attn_layers), len(self._mamba_layers)
        Ld, Le = len(self._dense_layers), len(self._moe_layers)
        Dq = c.num_q_heads * c.head_dim
        Dkv = c.total_kv_heads * c.head_dim
        keys = iter(jax.random.split(rng, 20))

        def norm(key, shape):
            return (scale * jax.random.normal(key, shape,
                                              jnp.float32)).astype(c.dtype)

        layers = {
            "a_ln": jnp.ones((La, H), c.dtype),
            "a_wq": norm(next(keys), (La, H, Dq)),
            "a_wk": norm(next(keys), (La, H, Dkv)),
            "a_wv": norm(next(keys), (La, H, Dkv)),
            "a_wo": norm(next(keys), (La, Dq, H)),
            "m_norm": jnp.ones((Lm, H), c.dtype),
            "m_in_x": norm(next(keys), (Lm, H, Di)),
            "m_in_z": norm(next(keys), (Lm, H, Di)),
            "m_conv_w": norm(next(keys), (Lm, K, Di)),
            "m_x_proj": norm(next(keys), (Lm, Di, R + 2 * N)),
            "m_dt_w": norm(next(keys), (Lm, R, Di)),
            "m_dt_b": jnp.zeros((Lm, Di), jnp.float32),
            "m_dt_ln": jnp.ones((Lm, R), c.dtype),
            "m_b_ln": jnp.ones((Lm, N), c.dtype),
            "m_c_ln": jnp.ones((Lm, N), c.dtype),
            "m_A_log": jnp.broadcast_to(
                jnp.log(jnp.arange(1, N + 1, dtype=jnp.float32)),
                (Lm, Di, N)) * jnp.ones((Lm, Di, 1), jnp.float32),
            "m_D": jnp.ones((Lm, Di), jnp.float32),
            "m_out_proj": norm(next(keys), (Lm, Di, H)),
            "d_pre_ln": jnp.ones((Ld, H), c.dtype),
            "d_gate": norm(next(keys), (Ld, H, I)),
            "d_up": norm(next(keys), (Ld, H, I)),
            "d_down": norm(next(keys), (Ld, I, H)),
        }
        if c.use_conv_bias:
            layers["m_conv_b"] = jnp.zeros((Lm, Di), c.dtype)
        if c.num_experts:
            E = c.num_experts
            layers.update({
                "e_pre_ln": jnp.ones((Le, H), c.dtype),
                "e_router": norm(next(keys), (Le, H, E)),
                "e_w_gate": norm(next(keys), (Le, E, H, I)),
                "e_w_up": norm(next(keys), (Le, E, H, I)),
                "e_w_down": norm(next(keys), (Le, E, I, H)),
            })
        embed = norm(next(keys), (c.vocab_size, H))
        return {
            "embed": embed,
            "layers": layers,
            "final_ln": jnp.ones((H, ), c.dtype),
            "lm_head": (embed.T if c.tie_word_embeddings else norm(
                next(keys), (H, c.vocab_size))),
        }

    def params_from_hf_state_dict(self, tensors: dict,
                                  prefix: str = "model") -> dict:
        c = self.cfg
        Di = c.d_inner

        def t(name):
            return np.asarray(tensors[name])

        def stack(ids, fmt, f=lambda a: a, dtype=None):
            return jnp.asarray(np.stack(
                [f(t(fmt.format(i))) for i in ids])).astype(
                    dtype or c.dtype)

        A, M = self._attn_layers, self._mamba_layers
        D, E = self._dense_layers, self._moe_layers
        ly = prefix + ".layers.{}."
        layers = {
            "a_ln": stack(A, ly + "input_layernorm.weight"),
            "a_wq": stack(A, ly + "self_attn.q_proj.weight",
                          lambda a: a.T),
            "a_wk": stack(A, ly + "self_attn.k_proj.weight",
                          lambda a: a.T),
            "a_wv": stack(A, ly + "self_attn.v_proj.weight",
                          lambda a: a.T),
            "a_wo": stack(A, ly + "self_attn.o_proj.weight",
                          lambda a: a.T),
            "m_norm": stack(M, ly + "input_layernorm.weight"),
            "m_in_x": stack(M, ly + "mamba.in_proj.weight",
                            lambda a: a[:Di].T),
            "m_in_z": stack(M, ly + "mamba.in_proj.weight",
                            lambda a: a[Di:].T),
            "m_conv_w": stack(M, ly + "mamba.conv1d.weight",
                              lambda a: a[:, 0, :].T),
            "m_x_proj": stack(M, ly + "mamba.x_proj.weight",
                              lambda a: a.T),
            "m_dt_w": stack(M, ly + "mamba.dt_proj.weight",
                            lambda a: a.T),
            "m_dt_b": stack(M, ly + "mamba.dt_proj.bias",
                            dtype=jnp.float32),
            "m_dt_ln": stack(M, ly + "mamba.dt_layernorm.weight"),
            "m_b_ln": stack(M, ly + "mamba.b_layernorm.weight"),
            "m_c_ln": stack(M, ly + "mamba.c_layernorm.weight"),
            "m_A_log": stack(M, ly + "mamba.A_log", dtype=jnp.float32),
            "m_D": stack(M, ly + "mamba.D", dtype=jnp.float32),
            "m_out_proj": stack(M, ly + "mamba.out_proj.weight",
                                lambda a: a.T),
            "d_pre_ln": stack(D, ly + "pre_ff_layernorm.weight"),
            "d_gate": stack(D, ly + "feed_forward.gate_proj.weight",
                            lambda a: a.T),
            "d_up": stack(D, ly + "feed_forward.up_proj.weight",
                          lambda a: a.T),
            "d_down": stack(D, ly + "feed_forward.down_proj.weight",
                            lambda a: a.T),
        }
        if c.use_conv_bias:
            layers["m_conv_b"] = stack(M, ly + "mamba.conv1d.bias")
        if c.num_experts:
            ex = ly + "feed_forward.experts.{}.{}_proj.weight"

            def stack_experts(which):
                return jnp.asarray(np.stack([
                    np.stack([
                        t(ex.format(i, e_i, which)).T
                        for e_i in range(c.num_experts)
                    ]) for i in E
                ])).astype(c.dtype)

            layers.update({
                "e_pre_ln": stack(E, ly + "pre_ff_layernorm.weight"),
                "e_router": stack(E, ly + "feed_forward.router.weight",
                                  lambda a: a.T),
                "e_w_gate": stack_experts("gate"),
                "e_w_up": stack_experts("up"),
                "e_w_down": stack_experts("down"),
            })
        if c.num_kv_head_replicas > 1:
            # KV-head replication for tp > kv_heads (see
            # models/llama.py _maybe_replicate_kv).
            from vllm_distributed_tpu.models.llama import \
                _replicate_kv_heads
            for name in ("a_wk", "a_wv"):
                layers[name] = _replicate_kv_heads(
                    layers[name], c.num_kv_heads, c.num_kv_head_replicas)
        embed = jnp.asarray(t(prefix + ".embed_tokens.weight")).astype(
            c.dtype)
        if c.tie_word_embeddings or "lm_head.weight" not in tensors:
            lm_head = embed.T
        else:
            lm_head = jnp.asarray(t("lm_head.weight")).T.astype(c.dtype)
        return {
            "embed": embed,
            "layers": layers,
            "final_ln": jnp.asarray(
                t(prefix + ".final_layernorm.weight")).astype(c.dtype),
            "lm_head": lm_head,
        }

    # ------------------------------------------------------------------
    # Hybrid cache groups: paged K/V over attention layers + state rows
    # over mamba layers (reference: kv_cache_coordinator grouping,
    # v1/core/kv_cache_coordinator.py).
    # ------------------------------------------------------------------
    def kv_cache_specs(self) -> dict:
        return {
            "k": P(None, TOKEN_AXIS, MODEL_AXIS, None, None),
            "v": P(None, TOKEN_AXIS, MODEL_AXIS, None, None),
            "conv": P(None, None, None, MODEL_AXIS),
            "ssm": P(None, None, MODEL_AXIS, None),
        }

    def _state_shapes(self, depth: int) -> dict:
        """Single source of truth for the mamba-state arrays (same
        contract as models/mamba.py _state_shapes)."""
        c = self.cfg
        S = (c.state_slots or 256) + 1
        return {
            "conv": ((depth, S, c.conv_kernel - 1, c.d_inner), c.dtype),
            "ssm": ((depth, S, c.d_inner, c.ssm_state_size),
                    jnp.float32),
        }

    def state_shapes(self) -> dict:
        """Snapshot-pool geometry (core/state_cache.py): the mamba
        stack's state arrays only — paged K/V re-enters through the
        ordinary prefix cache."""
        return self._state_shapes(len(self._mamba_layers))

    def make_kv_caches(self, num_pages: int, page_size: int,
                       cache_dtype=None,
                       num_layers: Optional[int] = None) -> dict:
        c = self.cfg
        assert num_layers is None or num_layers == c.num_layers, \
            "hybrid stacks are not sliceable per stage (no PP)"
        La, Lm = len(self._attn_layers), len(self._mamba_layers)
        dtype = cache_dtype or c.dtype
        shape = (La, num_pages, c.total_kv_heads, page_size,
                 storage_head_dim(c.head_dim))
        caches = {
            "k": jnp.zeros(shape, dtype),
            "v": jnp.zeros(shape, dtype),
        }
        caches.update({
            name: jnp.zeros(s, d)
            for name, (s, d) in self._state_shapes(Lm).items()
        })
        return caches

    def kv_cache_page_bytes(self, page_size: int) -> int:
        c = self.cfg
        La = len(self._attn_layers)
        return (2 * La * page_size * c.total_kv_heads *
                storage_head_dim(c.head_dim) *
                jnp.dtype(c.dtype).itemsize)

    def fixed_cache_bytes(self) -> int:
        return sum(
            int(np.prod(shape)) * jnp.dtype(dtype).itemsize
            for shape, dtype in self._state_shapes(
                len(self._mamba_layers)).values())

    def slice_layer_params(self, layers: dict, start: int, end: int):
        raise ValueError(
            "pipeline parallelism over hybrid attention/mamba stacks "
            "is not wired (per-kind stack depths differ per stage)")

    # ------------------------------------------------------------------
    # Forward
    # ------------------------------------------------------------------
    def run_layers(
        self,
        layer_params: dict,
        kv_caches: dict,
        hidden: jax.Array,
        batch,
        first_layer: int = 0,
    ) -> tuple[jax.Array, dict]:
        c = self.cfg
        T = hidden.shape[0]
        seg = build_segment_info(batch, kv_caches["ssm"].shape[1] - 1)
        sm_scale = c.head_dim**-0.5

        def sub(prefix, j):
            return {
                k[len(prefix):]: v[j]
                for k, v in layer_params.items() if k.startswith(prefix)
            }

        h = hidden
        k_all, v_all = kv_caches["k"], kv_caches["v"]
        conv_all, ssm_all = kv_caches["conv"], kv_caches["ssm"]
        ai = mi = di = ei = 0
        for i in range(c.num_layers):
            if self._is_attn(i):
                lp = sub("a_", ai)
                x = rms_norm(h, lp["ln"], c.rms_norm_eps)
                q = (x @ lp["wq"]).reshape(T, c.num_q_heads, c.head_dim)
                k = (x @ lp["wk"]).reshape(T, c.total_kv_heads,
                                           c.head_dim)
                v = (x @ lp["wv"]).reshape(T, c.total_kv_heads,
                                           c.head_dim)
                # NoPE: Jamba attention applies no rotary embedding.
                li = jnp.full((1, ), ai, jnp.int32)
                k_all, v_all = write_kv_cache(k_all, v_all, k, v, batch,
                                              li)
                attn = paged_attention(q, k_all, v_all, batch,
                                       sm_scale=sm_scale, layer=li,
                                       window=0)
                h = h + attn.reshape(T, -1) @ lp["wo"]
                ai += 1
            else:
                lp = sub("m_", mi)
                x = rms_norm(h, lp["norm"], c.rms_norm_eps)
                out, conv_new, ssm_new = MambaForCausalLM._mixer(
                    self, lp, x, conv_all[mi], ssm_all[mi], seg)
                conv_all = conv_all.at[mi].set(conv_new)
                ssm_all = ssm_all.at[mi].set(ssm_new)
                h = h + out
                mi += 1
            if self._is_moe(i):
                # sub() yields exactly the router/w_gate/w_up/w_down
                # keys the Mixtral dispatch reads.
                lp = sub("e_", ei)
                x = rms_norm(h, lp["pre_ln"], c.rms_norm_eps)
                h = h + MixtralForCausalLM.mlp_block(self, lp, x)
                ei += 1
            else:
                lp = sub("d_", di)
                x = rms_norm(h, lp["pre_ln"], c.rms_norm_eps)
                h = h + swiglu(x, lp["gate"], lp["up"], lp["down"])
                di += 1
        return h, {"k": k_all, "v": v_all, "conv": conv_all,
                   "ssm": ssm_all}
