"""Multi-LoRA: slot-stacked adapter buffers + grouped-GEMM apply.

Reference: vllm/lora/ (~6.7k LoC — LoRA layer wrappers around every
parallel linear, punica SGMV/BGMV Triton kernels in lora/ops/, worker
adapter manager; the TPU punica wrapper is selected at
platforms/tpu.py:79). TPU-native redesign:

* ``max_loras`` adapter SLOTS of fixed ``max_lora_rank`` live in the
  param tree as stacked buffers — A: [L, S, in, r], B: [L, S, r, out]
  with slot 0 all-zero ("no adapter"). Loading an adapter WRITES a slot;
  shapes never change, so nothing recompiles (the same discipline the
  engine applies everywhere else).
* Per-token adapter routing reuses the MoE machinery: tokens sort by
  slot once per step and each LoRA-wrapped matmul adds two
  ``jax.lax.ragged_dot`` grouped GEMMs (x @ A)[slot-grouped] @ B — the
  XLA equivalent of punica's segmented SGMV.
* PEFT checkpoints (adapter_config.json + adapter safetensors) load
  directly; ranks below max_lora_rank zero-pad.
"""

import json
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from vllm_distributed_tpu.logger import init_logger
from vllm_distributed_tpu.models.common import LoraBatch

logger = init_logger(__name__)

# Target matrices and their PEFT module names ((proj name, fused slice)).
# Fused qkv/gate-up don't exist here — each projection is its own matmul,
# so the mapping is 1:1.
PEFT_TARGETS = {
    "wq": "q_proj",
    "wk": "k_proj",
    "wv": "v_proj",
    "wo": "o_proj",
    "gate": "gate_proj",
    "up": "up_proj",
    "down": "down_proj",
}


def init_lora_buffers(cfg, targets) -> dict:
    """Zero adapter buffers for the requested targets (host numpy; the
    loader's placement pass moves them to device). Slot 0 stays zero
    forever — requests without an adapter route there."""
    L = cfg.num_layers
    S = cfg.max_loras + 1
    r = cfg.max_lora_rank
    H = cfg.hidden_size
    I = cfg.intermediate_size
    Dq = cfg.num_q_heads * cfg.head_dim
    Dkv = cfg.total_kv_heads * cfg.head_dim
    dims = {
        "wq": (H, Dq), "wk": (H, Dkv), "wv": (H, Dkv), "wo": (Dq, H),
        "gate": (H, I), "up": (H, I), "down": (I, H),
    }
    out = {}
    for name in targets:
        if name not in dims:
            continue
        din, dout = dims[name]
        out[name + "_a"] = np.zeros((L, S, din, r), np.dtype(cfg.dtype))
        out[name + "_b"] = np.zeros((L, S, r, dout), np.dtype(cfg.dtype))
    return out


def lora_apply(x: jax.Array, a: jax.Array, b: jax.Array,
               ctx: LoraBatch) -> jax.Array:
    """delta = scaling * (x @ A[slot]) @ B[slot], token-grouped by slot.

    ``a``/``b`` are one layer's stacks ([S, in, r], [S, r, out]); slot
    0's zeros make un-adapted tokens free of numerical effect (they
    still ride the grouped GEMM — static shapes beat a gather-free
    special case)."""
    xs = x[ctx.order]
    t = jax.lax.ragged_dot(xs, a, ctx.group_sizes)
    d = jax.lax.ragged_dot(t, b, ctx.group_sizes)
    d = d * ctx.scaling[:, None].astype(d.dtype)
    return d[ctx.inv]


# ---------------------------------------------------------------------------
# Worker-side adapter slot manager
# ---------------------------------------------------------------------------


class LoRASlotManager:
    """Resolves adapter names to device slots, loading PEFT checkpoints
    on first use (reference: lora/worker_manager.py LRUCacheWorkerLoRA
    Manager). Slot weights are written with .at[].set — the buffers'
    shapes (and thus every compiled graph) never change."""

    def __init__(self, cfg, max_loras: int) -> None:
        self.cfg = cfg
        self.max_loras = max_loras
        self.name_to_slot: dict[str, int] = {}
        self.active_counts: dict[int, int] = {}
        self.scaling: np.ndarray = np.zeros(max_loras + 1, np.float32)

    # -- lifecycle -----------------------------------------------------
    def acquire(self, name: str, path: str, runner) -> int:
        slot = self.name_to_slot.get(name)
        if slot is None:
            slot = self._free_slot()
            self._load_into_slot(slot, path, runner)
            self.name_to_slot[name] = slot
        self.active_counts[slot] = self.active_counts.get(slot, 0) + 1
        return slot

    def release(self, slot: int) -> None:
        if slot in self.active_counts:
            self.active_counts[slot] -= 1
            if self.active_counts[slot] <= 0:
                del self.active_counts[slot]
                # Adapter stays resident (LRU-ish: evicted only when a
                # new adapter needs the slot).

    def _free_slot(self) -> int:
        used = set(self.name_to_slot.values())
        for slot in range(1, self.max_loras + 1):
            if slot not in used:
                return slot
        # All slots named; evict an inactive one.
        for name, slot in list(self.name_to_slot.items()):
            if slot not in self.active_counts:
                del self.name_to_slot[name]
                logger.info("evicting LoRA %r from slot %d", name, slot)
                return slot
        raise ValueError(
            f"all {self.max_loras} LoRA slots are serving active "
            "requests; raise max_loras")

    # -- loading -------------------------------------------------------
    def _load_into_slot(self, slot: int, path: str, runner) -> None:
        cfg_path = os.path.join(path, "adapter_config.json")
        with open(cfg_path) as f:
            acfg = json.load(f)
        rank = int(acfg["r"])
        alpha = float(acfg.get("lora_alpha", rank))
        r_max = self.cfg.max_lora_rank
        if rank > r_max:
            raise ValueError(
                f"adapter rank {rank} exceeds max_lora_rank {r_max}")
        tensors = _load_adapter_tensors(path)
        self.scaling[slot] = alpha / rank

        # One tree for the single-program runner; one per stage (with
        # its layer slice) under pipeline parallelism.
        for lora, (lo, hi) in runner.lora_buffer_trees():
            for name, proj in PEFT_TARGETS.items():
                a_key, b_key = name + "_a", name + "_b"
                if a_key not in lora:
                    continue  # target not LoRA-enabled for this model
                a_buf, b_buf = lora[a_key], lora[b_key]
                a_np = np.zeros((hi - lo, ) + a_buf.shape[2:], np.float32)
                b_np = np.zeros((hi - lo, ) + b_buf.shape[2:], np.float32)
                found = False
                for layer in range(lo, hi):
                    a_t = _find_tensor(tensors, layer, proj, "lora_A")
                    b_t = _find_tensor(tensors, layer, proj, "lora_B")
                    if a_t is None or b_t is None:
                        continue
                    found = True
                    # PEFT stores A [r, in] and B [out, r]; ours are
                    # right-multiply transposed.
                    a_np[layer - lo, :, :rank] = a_t.T
                    b_np[layer - lo, :rank, :] = b_t.T
                if found:
                    lora[a_key] = a_buf.at[:, slot].set(
                        jnp.asarray(a_np, a_buf.dtype))
                    lora[b_key] = b_buf.at[:, slot].set(
                        jnp.asarray(b_np, b_buf.dtype))
                else:
                    # Target not in this adapter: zero the slot.
                    lora[a_key] = a_buf.at[:, slot].set(0.0)
                    lora[b_key] = b_buf.at[:, slot].set(0.0)
        logger.info("loaded LoRA %s (rank %d, alpha %.1f) into slot %d",
                    path, rank, alpha, slot)


def _load_adapter_tensors(path: str) -> dict[str, np.ndarray]:
    from safetensors.numpy import load_file
    for fname in ("adapter_model.safetensors", "adapter_model.bin"):
        full = os.path.join(path, fname)
        if os.path.exists(full):
            if fname.endswith(".safetensors"):
                return load_file(full)
            import torch
            return {k: v.float().numpy()
                    for k, v in torch.load(full,
                                           map_location="cpu").items()}
    raise FileNotFoundError(f"no adapter weights under {path}")


def _find_tensor(tensors: dict, layer: int, proj: str,
                 kind: str) -> Optional[np.ndarray]:
    for key, val in tensors.items():
        if (f"layers.{layer}." in key and f"{proj}" in key
                and kind in key and key.endswith("weight")):
            return np.asarray(val, np.float32)
    return None
