"""Mamba (selective state-space) model family.

Reference surface: vllm/model_executor/models/mamba.py (pure Mamba-1
MambaForCausalLM) built on layers/mamba/mamba_mixer.py, with per-request
SSM state held in the KV cache as a MambaSpec "one block per request"
group (vllm/v1/kv_cache_interface.py) and chunk metadata from
v1/attention/backends/mamba_attn.py.

TPU design: the mixer runs directly on the engine's flat ragged token
batch via the segmented associative scan in ops/mamba.py — no
prefill/decode split, no chunk-index tables; decode, chunked prefill and
mixed batches are one compiled program per token bucket. State lives in
fixed-size per-request rows indexed by the runner's persistent
input-batch slots (state is O(1) per request, so paging buys nothing);
the page pool is sized to "free" (kv_cache_page_bytes == 0) and the
worker charges the fixed state bytes instead (fixed_cache_bytes).

Tensor parallelism shards the d_inner channel axis: in/out projections
column/row-parallel, conv + scan fully elementwise in the shard, B/C/dt
projections replicated (they are per-token vectors of rank << d_inner).
Prefix caching is disabled for stateful families at scheduler
construction (models/loader.resolve_stateful): SSM state cannot be
re-entered at an arbitrary page boundary — matching the reference,
which likewise rejects prefix caching for mamba models.
"""

from __future__ import annotations

import math
from types import SimpleNamespace
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from vllm_distributed_tpu.models.common import rms_norm
from vllm_distributed_tpu.models.llama import (MODEL_AXIS,
                                               LlamaForCausalLM)
from vllm_distributed_tpu.ops.mamba import (build_segment_info,
                                            causal_conv1d_ragged,
                                            selective_scan_ragged,
                                            ssd_scan_ragged)


def _softplus(x: jax.Array) -> jax.Array:
    return jax.nn.softplus(x)


class MambaForCausalLM(LlamaForCausalLM):
    """Pure Mamba-1 stack: L x (RMSNorm -> MambaMixer) + final norm.

    HF checkpoint layout: backbone.embeddings, backbone.layers.{i}.norm
    + .mixer.{in_proj,conv1d,x_proj,dt_proj,A_log,D,out_proj},
    backbone.norm_f, tied lm_head.
    """

    QUANT_TARGETS = ()  # weight quantization for SSM stacks: follow-up
    LORA_TARGETS = ()
    STATEFUL = True
    # Pure-SSM stack: pages carry no bytes, so a state snapshot alone is
    # a complete resume point (hybrid stacks set this False — their
    # restores must re-enter coherently with cached attention pages).
    STATE_ONLY = True

    @classmethod
    def arch_config_source(cls, hf):
        """MambaConfig lacks the attention fields from_hf_config reads;
        present a shim with inert attention values."""
        d_inner = getattr(hf, "intermediate_size", None) or (
            hf.expand * hf.hidden_size)
        return SimpleNamespace(
            vocab_size=hf.vocab_size,
            hidden_size=hf.hidden_size,
            intermediate_size=d_inner,
            num_hidden_layers=hf.num_hidden_layers,
            num_attention_heads=1,
            num_key_value_heads=1,
            head_dim=hf.hidden_size,
            rms_norm_eps=getattr(hf, "layer_norm_epsilon", 1e-5),
            tie_word_embeddings=getattr(hf, "tie_word_embeddings", True),
        )

    @classmethod
    def configure_arch(cls, arch, hf) -> None:
        arch.stateful = True
        arch.ssm_state_size = hf.state_size
        arch.conv_kernel = hf.conv_kernel
        arch.d_inner = arch.intermediate_size
        dt_rank = getattr(hf, "time_step_rank", None)
        if dt_rank is None or dt_rank == "auto":
            dt_rank = math.ceil(hf.hidden_size / 16)
        arch.dt_rank = int(dt_rank)
        arch.use_conv_bias = bool(getattr(hf, "use_conv_bias", True))
        arch.use_bias = bool(getattr(hf, "use_bias", False))
        # Filled by the loader from SchedulerConfig.max_num_seqs; tests
        # constructing the model directly set it on the arch first.
        if not hasattr(arch, "state_slots"):
            arch.state_slots = 0

    def quantize_params(self, params: dict) -> dict:
        if self.cfg.quantization:
            raise ValueError(
                "weight quantization for SSM stacks is not wired yet; "
                "drop --quantization for Mamba-family models")
        return params

    # ------------------------------------------------------------------
    # Parameters
    # ------------------------------------------------------------------
    def param_specs(self) -> dict:
        c = self.cfg
        layer = {
            "norm": P(None, None),
            "in_x": P(None, None, MODEL_AXIS),
            "in_z": P(None, None, MODEL_AXIS),
            "conv_w": P(None, None, MODEL_AXIS),
            "x_proj": P(None, MODEL_AXIS, None),
            "dt_w": P(None, None, MODEL_AXIS),
            "dt_b": P(None, MODEL_AXIS),
            "A_log": P(None, MODEL_AXIS, None),
            "D": P(None, MODEL_AXIS),
            "out_proj": P(None, MODEL_AXIS, None),
        }
        if c.use_conv_bias:
            layer["conv_b"] = P(None, MODEL_AXIS)
        if c.use_bias:
            layer["in_x_b"] = P(None, MODEL_AXIS)
            layer["in_z_b"] = P(None, MODEL_AXIS)
            layer["out_b"] = P(None, None)
        return {
            "embed": P(None, None),
            "layers": layer,
            "final_ln": P(None, ),
            "lm_head": P(None, MODEL_AXIS),
        }

    def init_params(self, rng: jax.Array, scale: float = 0.02) -> dict:
        c = self.cfg
        L, H = c.num_layers, c.hidden_size
        Di, N, K, R = c.d_inner, c.ssm_state_size, c.conv_kernel, c.dt_rank
        keys = iter(jax.random.split(rng, 10))

        def norm(key, shape):
            return (scale * jax.random.normal(key, shape,
                                              jnp.float32)).astype(c.dtype)

        layers = {
            "norm": jnp.ones((L, H), c.dtype),
            "in_x": norm(next(keys), (L, H, Di)),
            "in_z": norm(next(keys), (L, H, Di)),
            "conv_w": norm(next(keys), (L, K, Di)),
            "x_proj": norm(next(keys), (L, Di, R + 2 * N)),
            "dt_w": norm(next(keys), (L, R, Di)),
            "dt_b": jnp.zeros((L, Di), jnp.float32),
            # S4D-real init: A = -(1..N) per channel, like the published
            # Mamba initialization.
            "A_log": jnp.broadcast_to(
                jnp.log(jnp.arange(1, N + 1, dtype=jnp.float32)),
                (L, Di, N)) * jnp.ones((L, Di, 1), jnp.float32),
            "D": jnp.ones((L, Di), jnp.float32),
            "out_proj": norm(next(keys), (L, Di, H)),
        }
        if c.use_conv_bias:
            layers["conv_b"] = jnp.zeros((L, Di), c.dtype)
        if c.use_bias:
            layers["in_x_b"] = jnp.zeros((L, Di), c.dtype)
            layers["in_z_b"] = jnp.zeros((L, Di), c.dtype)
            layers["out_b"] = jnp.zeros((L, H), c.dtype)
        embed = norm(next(keys), (c.vocab_size, H))
        return {
            "embed": embed,
            "layers": layers,
            "final_ln": jnp.ones((H, ), c.dtype),
            "lm_head": (embed.T if c.tie_word_embeddings else norm(
                next(keys), (H, c.vocab_size))),
        }

    def _hf_stackers(self, tensors: dict):
        """(t, stack) helpers shared by the family's checkpoint maps."""
        L = self.cfg.num_layers

        def t(name):
            return np.asarray(tensors[name])

        def stack(fmt, f):
            return jnp.asarray(
                np.stack([f(t(fmt.format(i))) for i in range(L)]))

        return t, stack

    def _hf_tail(self, tensors: dict, layers: dict,
                 prefix: str) -> dict:
        """Assemble the param tree around a family's layers dict
        (embeddings / final norm / tied-or-separate lm_head)."""
        c = self.cfg
        t, _ = self._hf_stackers(tensors)
        embed = jnp.asarray(t(prefix + ".embeddings.weight")).astype(
            c.dtype)
        if c.tie_word_embeddings or "lm_head.weight" not in tensors:
            lm_head = embed.T
        else:
            lm_head = jnp.asarray(t("lm_head.weight")).T.astype(c.dtype)
        return {
            "embed": embed,
            "layers": layers,
            "final_ln":
            jnp.asarray(t(prefix + ".norm_f.weight")).astype(c.dtype),
            "lm_head": lm_head,
        }

    def params_from_hf_state_dict(self, tensors: dict,
                                  prefix: str = "backbone") -> dict:
        c = self.cfg
        Di = c.d_inner
        t, stack = self._hf_stackers(tensors)

        def lin(a):  # torch Linear weight [out, in] -> [in, out]
            return a.T

        mx = prefix + ".layers.{}.mixer."
        layers = {
            "norm":
            stack(prefix + ".layers.{}.norm.weight",
                  lambda a: a).astype(c.dtype),
            "in_x":
            stack(mx + "in_proj.weight",
                  lambda a: lin(a[:Di])).astype(c.dtype),
            "in_z":
            stack(mx + "in_proj.weight",
                  lambda a: lin(a[Di:])).astype(c.dtype),
            # conv1d depthwise weight [Di, 1, K] -> taps-major [K, Di].
            "conv_w":
            stack(mx + "conv1d.weight",
                  lambda a: a[:, 0, :].T).astype(c.dtype),
            "x_proj":
            stack(mx + "x_proj.weight", lin).astype(c.dtype),
            "dt_w":
            stack(mx + "dt_proj.weight", lin).astype(c.dtype),
            "dt_b":
            stack(mx + "dt_proj.bias", lambda a: a).astype(jnp.float32),
            "A_log":
            stack(mx + "A_log", lambda a: a).astype(jnp.float32),
            "D":
            stack(mx + "D", lambda a: a).astype(jnp.float32),
            "out_proj":
            stack(mx + "out_proj.weight", lin).astype(c.dtype),
        }
        if c.use_conv_bias:
            layers["conv_b"] = stack(mx + "conv1d.bias",
                                     lambda a: a).astype(c.dtype)
        if c.use_bias:
            layers["in_x_b"] = stack(mx + "in_proj.bias",
                                     lambda a: a[:Di]).astype(c.dtype)
            layers["in_z_b"] = stack(mx + "in_proj.bias",
                                     lambda a: a[Di:]).astype(c.dtype)
            layers["out_b"] = stack(mx + "out_proj.bias",
                                    lambda a: a).astype(c.dtype)
        return self._hf_tail(tensors, layers, prefix)

    # ------------------------------------------------------------------
    # State cache (replaces paged KV)
    # ------------------------------------------------------------------
    def kv_cache_specs(self) -> dict:
        return {
            "conv": P(None, None, None, MODEL_AXIS),
            "ssm": P(None, None, MODEL_AXIS, None),
        }

    def _state_shapes(self, depth: int) -> dict:
        """One source of truth for state-cache shapes/dtypes, shared by
        make_kv_caches and fixed_cache_bytes so the worker's memory
        accounting can never drift from the arrays it allocates."""
        c = self.cfg
        S = (c.state_slots or 256) + 1  # +1 dump row for padding writes
        return {
            "conv": ((depth, S, c.conv_kernel - 1, c.d_inner), c.dtype),
            "ssm": ((depth, S, c.d_inner, c.ssm_state_size),
                    jnp.float32),
        }

    def state_shapes(self) -> dict:
        """State-array geometry for the snapshot pool
        (core/state_cache.py): {name: ((depth, S+1, ...), dtype)} —
        axis 1 is the per-request slot axis the runner's snapshot
        copies gather/scatter along."""
        return self._state_shapes(self.cfg.num_layers)

    def make_kv_caches(self, num_pages: int, page_size: int,
                       cache_dtype=None,
                       num_layers: Optional[int] = None) -> dict:
        c = self.cfg
        depth = num_layers if num_layers is not None else c.num_layers
        return {
            name: jnp.zeros(shape, dtype)
            for name, (shape, dtype) in self._state_shapes(depth).items()
        }

    def kv_cache_page_bytes(self, page_size: int) -> int:
        # SSM state is per-request, not per-token: pages are free; the
        # worker charges fixed_cache_bytes instead.
        return 0

    def fixed_cache_bytes(self) -> int:
        return sum(
            int(np.prod(shape)) * jnp.dtype(dtype).itemsize
            for shape, dtype in self._state_shapes(
                self.cfg.num_layers).values())

    # ------------------------------------------------------------------
    # Forward
    # ------------------------------------------------------------------
    def _mixer(self, lp: dict, x: jax.Array, conv_state, ssm_state, seg):
        """One Mamba-1 mixer over flat tokens x [T, Di-projected]."""
        c = self.cfg
        N, R = c.ssm_state_size, c.dt_rank
        xin = x @ lp["in_x"]
        z = x @ lp["in_z"]
        if c.use_bias:
            xin = xin + lp["in_x_b"]
            z = z + lp["in_z_b"]
        xc, conv_state = causal_conv1d_ragged(
            xin, lp["conv_w"], lp.get("conv_b"), conv_state, seg)
        xc = jax.nn.silu(xc)
        ssm_p = xc @ lp["x_proj"]  # [T, R + 2N]
        dt_r = ssm_p[:, :R]
        B = ssm_p[:, R:R + N]
        C = ssm_p[:, R + N:]
        eps = getattr(c, "mixer_rms_eps", None)
        if eps is not None:
            # FalconMamba: weightless RMSNorm on dt/B/C before use.
            ones = jnp.ones((1, ), jnp.float32)
            dt_r = rms_norm(dt_r.astype(jnp.float32), ones, eps)
            B = rms_norm(B.astype(jnp.float32), ones, eps)
            C = rms_norm(C.astype(jnp.float32), ones, eps)
        if "dt_ln" in lp:
            # Jamba: learned RMSNorms on the selection vectors
            # (dt_layernorm/b_layernorm/c_layernorm).
            dt_r = rms_norm(dt_r, lp["dt_ln"], c.rms_norm_eps)
            B = rms_norm(B, lp["b_ln"], c.rms_norm_eps)
            C = rms_norm(C, lp["c_ln"], c.rms_norm_eps)
        dt = _softplus(
            dt_r @ lp["dt_w"] + lp["dt_b"])  # [T, Di] f32 bias
        A = -jnp.exp(lp["A_log"])  # [Di, N] f32
        y, ssm_state = selective_scan_ragged(
            xc.astype(jnp.float32), dt, A, B, C, lp["D"], ssm_state, seg)
        y = y * jax.nn.silu(z.astype(jnp.float32))
        out = y.astype(c.dtype) @ lp["out_proj"]
        if c.use_bias:
            out = out + lp["out_b"]
        return out, conv_state, ssm_state

    def run_layers(
        self,
        layer_params: dict,
        kv_caches: dict,
        hidden: jax.Array,  # [T, H]
        batch,
        first_layer: int = 0,
    ) -> tuple[jax.Array, dict]:
        c = self.cfg
        seg = build_segment_info(batch, kv_caches["ssm"].shape[1] - 1)
        num_layers = jax.tree_util.tree_leaves(layer_params)[0].shape[0]
        layer_ids = jnp.arange(num_layers, dtype=jnp.int32)

        def layer_body(carry, xs):
            h, conv_all, ssm_all = carry
            lp, li = xs
            x = rms_norm(h, lp["norm"], c.rms_norm_eps)
            out, conv_new, ssm_new = self._mixer(
                lp, x, conv_all[li], ssm_all[li], seg)
            conv_all = jax.lax.dynamic_update_index_in_dim(
                conv_all, conv_new, li, 0)
            ssm_all = jax.lax.dynamic_update_index_in_dim(
                ssm_all, ssm_new, li, 0)
            return (h + out, conv_all, ssm_all), None

        carry = (hidden, kv_caches["conv"], kv_caches["ssm"])
        carry, _ = jax.lax.scan(layer_body, carry,
                                (layer_params, layer_ids))
        hidden, conv_all, ssm_all = carry
        return hidden, {"conv": conv_all, "ssm": ssm_all}


class FalconMambaForCausalLM(MambaForCausalLM):
    """FalconMamba: Mamba-1 with a weightless RMSNorm applied to the
    dt/B/C selection vectors (reference:
    vllm/model_executor/models/falcon_mamba.py mixer_rms_eps)."""

    @classmethod
    def configure_arch(cls, arch, hf) -> None:
        MambaForCausalLM.configure_arch(arch, hf)
        arch.mixer_rms_eps = getattr(hf, "mixer_rms_eps", 1e-6)


class Mamba2ForCausalLM(MambaForCausalLM):
    """Mamba-2 (SSD) stack: scalar decay per head, grouped B/C, x/B/C
    convolved together, gated RMSNorm before out_proj.

    Reference: vllm/model_executor/models/mamba2.py on
    layers/mamba/mamba_mixer2.py (chunked-SSD CUDA kernels). Here the
    recurrence is the same segmented scan as Mamba-1 with head-major
    shapes (ops/mamba.ssd_scan_ragged); the conv splits into x and B/C
    halves (depthwise, so two convs == one) to keep x head-sharded and
    B/C replicated under TP.
    """

    @classmethod
    def arch_config_source(cls, hf):
        src = MambaForCausalLM.arch_config_source(hf)
        src.tie_word_embeddings = getattr(hf, "tie_word_embeddings",
                                          False)
        return src

    @classmethod
    def configure_arch(cls, arch, hf) -> None:
        arch.stateful = True
        arch.ssm_state_size = hf.state_size
        arch.conv_kernel = hf.conv_kernel
        arch.d_inner = arch.intermediate_size
        arch.num_ssm_heads = hf.num_heads
        arch.ssm_head_dim = getattr(hf, "head_dim",
                                    arch.d_inner // hf.num_heads)
        arch.n_groups = getattr(hf, "n_groups", 1)
        arch.time_step_limit = tuple(
            getattr(hf, "time_step_limit", (0.0, float("inf"))))
        arch.use_conv_bias = bool(getattr(hf, "use_conv_bias", True))
        arch.use_bias = bool(getattr(hf, "use_bias", False))
        if not hasattr(arch, "state_slots"):
            arch.state_slots = 0

    # ------------------------------------------------------------------
    def param_specs(self) -> dict:
        c = self.cfg
        layer = {
            "norm": P(None, None),
            "gated_norm": P(None, MODEL_AXIS),
            "in_gate": P(None, None, MODEL_AXIS),
            "in_x": P(None, None, MODEL_AXIS),
            "in_bc": P(None, None, None),
            "in_dt": P(None, None, MODEL_AXIS),
            "conv_x_w": P(None, None, MODEL_AXIS),
            "conv_bc_w": P(None, None, None),
            "dt_bias": P(None, MODEL_AXIS),
            "A_log": P(None, MODEL_AXIS),
            "D": P(None, MODEL_AXIS),
            "out_proj": P(None, MODEL_AXIS, None),
        }
        if c.use_conv_bias:
            layer["conv_x_b"] = P(None, MODEL_AXIS)
            layer["conv_bc_b"] = P(None, None)
        if c.use_bias:
            layer["in_b"] = P(None, None)
            layer["out_b"] = P(None, None)
        return {
            "embed": P(None, None),
            "layers": layer,
            "final_ln": P(None, ),
            "lm_head": P(None, MODEL_AXIS),
        }

    def init_params(self, rng: jax.Array, scale: float = 0.02) -> dict:
        c = self.cfg
        L, H = c.num_layers, c.hidden_size
        Di, N, K = c.d_inner, c.ssm_state_size, c.conv_kernel
        Hm, G = c.num_ssm_heads, c.n_groups
        keys = iter(jax.random.split(rng, 10))

        def norm(key, shape):
            return (scale * jax.random.normal(key, shape,
                                              jnp.float32)).astype(c.dtype)

        layers = {
            "norm": jnp.ones((L, H), c.dtype),
            "gated_norm": jnp.ones((L, Di), c.dtype),
            "in_gate": norm(next(keys), (L, H, Di)),
            "in_x": norm(next(keys), (L, H, Di)),
            "in_bc": norm(next(keys), (L, H, 2 * G * N)),
            "in_dt": norm(next(keys), (L, H, Hm)),
            "conv_x_w": norm(next(keys), (L, K, Di)),
            "conv_bc_w": norm(next(keys), (L, K, 2 * G * N)),
            "dt_bias": jnp.zeros((L, Hm), jnp.float32),
            "A_log": jnp.broadcast_to(
                jnp.log(jnp.arange(1, Hm + 1, dtype=jnp.float32)),
                (L, Hm)),
            "D": jnp.ones((L, Hm), jnp.float32),
            "out_proj": norm(next(keys), (L, Di, H)),
        }
        if c.use_conv_bias:
            layers["conv_x_b"] = jnp.zeros((L, Di), c.dtype)
            layers["conv_bc_b"] = jnp.zeros((L, 2 * G * N), c.dtype)
        if c.use_bias:
            layers["in_b"] = jnp.zeros((L, 2 * Di + 2 * G * N + Hm),
                                       c.dtype)
            layers["out_b"] = jnp.zeros((L, H), c.dtype)
        embed = norm(next(keys), (c.vocab_size, H))
        return {
            "embed": embed,
            "layers": layers,
            "final_ln": jnp.ones((H, ), c.dtype),
            "lm_head": (embed.T if c.tie_word_embeddings else norm(
                next(keys), (H, c.vocab_size))),
        }

    def params_from_hf_state_dict(self, tensors: dict,
                                  prefix: str = "backbone") -> dict:
        c = self.cfg
        Di = c.d_inner
        GN2 = 2 * c.n_groups * c.ssm_state_size
        t, stack = self._hf_stackers(tensors)
        mx = prefix + ".layers.{}.mixer."
        # in_proj rows: [gate(Di), x(Di), B/C(2GN), dt(Hm)] (d_mlp = 0
        # for the published Mamba-2 checkpoints).
        layers = {
            "norm":
            stack(prefix + ".layers.{}.norm.weight",
                  lambda a: a).astype(c.dtype),
            "gated_norm":
            stack(mx + "norm.weight", lambda a: a).astype(c.dtype),
            "in_gate":
            stack(mx + "in_proj.weight",
                  lambda a: a[:Di].T).astype(c.dtype),
            "in_x":
            stack(mx + "in_proj.weight",
                  lambda a: a[Di:2 * Di].T).astype(c.dtype),
            "in_bc":
            stack(mx + "in_proj.weight",
                  lambda a: a[2 * Di:2 * Di + GN2].T).astype(c.dtype),
            "in_dt":
            stack(mx + "in_proj.weight",
                  lambda a: a[2 * Di + GN2:].T).astype(c.dtype),
            "conv_x_w":
            stack(mx + "conv1d.weight",
                  lambda a: a[:Di, 0, :].T).astype(c.dtype),
            "conv_bc_w":
            stack(mx + "conv1d.weight",
                  lambda a: a[Di:, 0, :].T).astype(c.dtype),
            "dt_bias":
            stack(mx + "dt_bias", lambda a: a).astype(jnp.float32),
            "A_log":
            stack(mx + "A_log", lambda a: a).astype(jnp.float32),
            "D":
            stack(mx + "D", lambda a: a).astype(jnp.float32),
            "out_proj":
            stack(mx + "out_proj.weight", lambda a: a.T).astype(c.dtype),
        }
        if c.use_conv_bias:
            layers["conv_x_b"] = stack(mx + "conv1d.bias",
                                       lambda a: a[:Di]).astype(c.dtype)
            layers["conv_bc_b"] = stack(mx + "conv1d.bias",
                                        lambda a: a[Di:]).astype(c.dtype)
        if c.use_bias:
            layers["in_b"] = stack(mx + "in_proj.bias",
                                   lambda a: a).astype(c.dtype)
            layers["out_b"] = stack(mx + "out_proj.bias",
                                    lambda a: a).astype(c.dtype)
        return self._hf_tail(tensors, layers, prefix)

    # ------------------------------------------------------------------
    def kv_cache_specs(self) -> dict:
        return {
            "conv": P(None, None, None, MODEL_AXIS),
            "conv_bc": P(None, None, None, None),
            "ssm": P(None, None, MODEL_AXIS, None, None),
        }

    def _state_shapes(self, depth: int) -> dict:
        c = self.cfg
        S = (c.state_slots or 256) + 1
        GN2 = 2 * c.n_groups * c.ssm_state_size
        return {
            # x and B/C carry separate conv states (their convs split).
            "conv": ((depth, S, c.conv_kernel - 1, c.d_inner), c.dtype),
            "conv_bc": ((depth, S, c.conv_kernel - 1, GN2), c.dtype),
            "ssm": ((depth, S, c.num_ssm_heads, c.ssm_head_dim,
                     c.ssm_state_size), jnp.float32),
        }

    def _mixer(self, lp: dict, x: jax.Array, conv_state, conv_bc_state,
               ssm_state, seg):
        c = self.cfg
        Hm, Pd, N, G = (c.num_ssm_heads, c.ssm_head_dim,
                        c.ssm_state_size, c.n_groups)
        gate = x @ lp["in_gate"]
        xin = x @ lp["in_x"]
        bc = x @ lp["in_bc"]
        dt_r = x @ lp["in_dt"]  # [T, Hm]
        if c.use_bias:
            b = lp["in_b"]
            Di = c.d_inner
            gate = gate + b[:Di]
            xin = xin + b[Di:2 * Di]
            bc = bc + b[2 * Di:2 * Di + 2 * G * N]
            dt_r = dt_r + b[2 * Di + 2 * G * N:]
        xc, conv_state = causal_conv1d_ragged(
            xin, lp["conv_x_w"], lp.get("conv_x_b"), conv_state, seg)
        bcc, conv_bc_state = causal_conv1d_ragged(
            bc, lp["conv_bc_w"], lp.get("conv_bc_b"), conv_bc_state, seg)
        xc = jax.nn.silu(xc)
        bcc = jax.nn.silu(bcc)
        B = bcc[:, :G * N].reshape(-1, G, N)
        C = bcc[:, G * N:].reshape(-1, G, N)
        dt = _softplus(dt_r.astype(jnp.float32) + lp["dt_bias"])
        lo, hi = c.time_step_limit
        if lo > 0.0 or hi != float("inf"):
            dt = jnp.clip(dt, lo, hi)
        A = -jnp.exp(lp["A_log"])  # [Hm]
        xh = xc.astype(jnp.float32).reshape(-1, Hm, Pd)
        y, ssm_state = ssd_scan_ragged(xh, dt, A, B, C, lp["D"],
                                       ssm_state, seg)
        y = y.reshape(-1, Hm * Pd)
        # Gated RMSNorm (norm(y * silu(gate)) * weight), f32 like HF.
        y = y * jax.nn.silu(gate.astype(jnp.float32))
        y = rms_norm(y, lp["gated_norm"].astype(jnp.float32),
                     c.rms_norm_eps)
        out = y.astype(c.dtype) @ lp["out_proj"]
        if c.use_bias:
            out = out + lp["out_b"]
        return out, conv_state, conv_bc_state, ssm_state

    def run_layers(
        self,
        layer_params: dict,
        kv_caches: dict,
        hidden: jax.Array,
        batch,
        first_layer: int = 0,
    ) -> tuple[jax.Array, dict]:
        c = self.cfg
        seg = build_segment_info(batch, kv_caches["ssm"].shape[1] - 1)
        num_layers = jax.tree_util.tree_leaves(layer_params)[0].shape[0]
        layer_ids = jnp.arange(num_layers, dtype=jnp.int32)

        def layer_body(carry, xs):
            h, conv_all, conv_bc_all, ssm_all = carry
            lp, li = xs
            x = rms_norm(h, lp["norm"], c.rms_norm_eps)
            out, conv_new, conv_bc_new, ssm_new = self._mixer(
                lp, x, conv_all[li], conv_bc_all[li], ssm_all[li], seg)
            conv_all = jax.lax.dynamic_update_index_in_dim(
                conv_all, conv_new, li, 0)
            conv_bc_all = jax.lax.dynamic_update_index_in_dim(
                conv_bc_all, conv_bc_new, li, 0)
            ssm_all = jax.lax.dynamic_update_index_in_dim(
                ssm_all, ssm_new, li, 0)
            return (h + out, conv_all, conv_bc_all, ssm_all), None

        carry = (hidden, kv_caches["conv"], kv_caches["conv_bc"],
                 kv_caches["ssm"])
        carry, _ = jax.lax.scan(layer_body, carry,
                                (layer_params, layer_ids))
        hidden, conv_all, conv_bc_all, ssm_all = carry
        return hidden, {"conv": conv_all, "conv_bc": conv_bc_all,
                        "ssm": ssm_all}
