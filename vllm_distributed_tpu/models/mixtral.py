"""Mixtral-family sparse-MoE decoder (covers HF ``MixtralForCausalLM``).

TPU-first equivalent of the reference's vllm/model_executor/models/
mixtral.py + layers/fused_moe/layer.py:593 ``FusedMoE`` (CUDA grouped-GEMM
kernels with all-to-all dispatch over the EP group): here every expert's
FFN weights are STACKED on a leading expert axis and the whole MoE block
is three einsums — router top-k gates, batched expert FFNs, weighted
combine. Under expert parallelism the expert axis is sharded over the
``model`` mesh axis (EP spans the TP group, reference
parallel_state.py:1189-1204); GSPMD turns the combine contraction into
the psum that replaces the reference's all-to-all combine. Every selected
token is computed exactly (no capacity-factor drops), matching HF
numerics for parity tests.

The attention/embedding/norm stack is inherited from the Llama decoder
(Mixtral is architecturally Llama + MoE MLP).
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from vllm_distributed_tpu.models.llama import (MODEL_AXIS,
                                               LlamaForCausalLM)
from vllm_distributed_tpu.parallel.mesh import shard_map


class MixtralForCausalLM(LlamaForCausalLM):

    QUANT_TARGETS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")
    # LoRA on the attention projections only (per-expert adapters would
    # need expert-grouped LoRA GEMMs; the reference likewise restricts
    # MoE LoRA support).
    LORA_TARGETS = ("wq", "wk", "wv", "wo")
    _EXPERT_WEIGHTS = ("w_gate", "w_up", "w_down")

    @property
    def num_physical(self) -> int:
        """Physical expert slots (>= logical; extra slots hold EPLB
        replicas of hot experts, reference: distributed/eplb/)."""
        return self.cfg.num_physical_experts or self.cfg.num_experts

    @property
    def _replica_cap(self) -> int:
        # One expert could absorb every spare slot: static buffer bound.
        return self.num_physical - self.cfg.num_experts + 1

    # ------------------------------------------------------------------
    def param_specs(self) -> dict:
        specs = super().param_specs()
        layer = specs["layers"]
        for k in ("gate", "up", "down"):
            layer.pop(k)
        layer["router"] = P(None, None, None)  # [L, H, E] replicated
        if self.num_physical > self.cfg.num_experts:
            # EPLB routing buffers: replicated (tiny int tables).
            layer["expert_map"] = P(None, None, None)
            layer["expert_replicas"] = P(None, None)
        if self.cfg.expert_parallel:
            # Experts sharded over the model axis: each rank holds
            # E/ep_size whole experts (reference: FusedMoE EP path).
            ffn = P(None, MODEL_AXIS, None, None)
            layer.update({"w_gate": ffn, "w_up": ffn, "w_down": ffn})
        else:
            # TP inside each expert's FFN (Megatron layout per expert).
            layer.update({
                "w_gate": P(None, None, None, MODEL_AXIS),
                "w_up": P(None, None, None, MODEL_AXIS),
                "w_down": P(None, None, MODEL_AXIS, None),
            })
        self._add_scale_specs(layer)
        return specs

    def init_params(self, rng: jax.Array, scale: float = 0.02) -> dict:
        params = super().init_params(rng, scale)
        c = self.cfg
        L, H, E = c.num_layers, c.hidden_size, c.num_experts
        I = c.moe_intermediate_size or c.intermediate_size
        keys = iter(jax.random.split(jax.random.fold_in(rng, 17), 4))

        def norm(key, shape):
            return (scale * jax.random.normal(key, shape,
                                              jnp.float32)).astype(c.dtype)

        layers = params["layers"]
        for k in ("gate", "up", "down"):
            layers.pop(k)
        layers["router"] = norm(next(keys), (L, H, E))
        layers["w_gate"] = norm(next(keys), (L, E, H, I))
        layers["w_up"] = norm(next(keys), (L, E, H, I))
        layers["w_down"] = norm(next(keys), (L, E, I, H))
        self._install_physical_experts(layers)
        return params

    def params_from_hf_state_dict(self, tensors: dict[str, np.ndarray],
                                  ) -> dict:
        c = self.cfg
        L, E = c.num_layers, c.num_experts
        # The base mapper handles every non-MLP tensor but requires the
        # dense-MLP names; alias them to expert 0's weights (the dense
        # entries are dropped right after) and stack the real expert
        # tensors below.
        alias = dict(tensors)
        for i in range(L):
            alias[f"model.layers.{i}.mlp.gate_proj.weight"] = tensors[
                f"model.layers.{i}.block_sparse_moe.experts.0.w1.weight"]
            alias[f"model.layers.{i}.mlp.up_proj.weight"] = tensors[
                f"model.layers.{i}.block_sparse_moe.experts.0.w3.weight"]
            alias[f"model.layers.{i}.mlp.down_proj.weight"] = tensors[
                f"model.layers.{i}.block_sparse_moe.experts.0.w2.weight"]
        params = super().params_from_hf_state_dict(alias)
        layers = params["layers"]
        for k in ("gate", "up", "down"):
            layers.pop(k)

        def stack_experts(fmt, transpose=True):
            per_layer = []
            for i in range(L):
                mats = [np.asarray(tensors[fmt.format(i, e)])
                        for e in range(E)]
                per_layer.append(
                    np.stack([m.T if transpose else m for m in mats]))
            return jnp.asarray(np.stack(per_layer), dtype=c.dtype)

        layers["router"] = jnp.asarray(
            np.stack([
                np.asarray(
                    tensors[f"model.layers.{i}.block_sparse_moe"
                            f".gate.weight"]).T for i in range(L)
            ]), dtype=c.dtype)
        layers["w_gate"] = stack_experts(
            "model.layers.{}.block_sparse_moe.experts.{}.w1.weight")
        layers["w_up"] = stack_experts(
            "model.layers.{}.block_sparse_moe.experts.{}.w3.weight")
        layers["w_down"] = stack_experts(
            "model.layers.{}.block_sparse_moe.experts.{}.w2.weight")
        self._install_physical_experts(layers)
        return params

    # ------------------------------------------------------------------
    # EPLB: physical expert slots + logical->physical routing buffers
    # ------------------------------------------------------------------
    def _install_physical_experts(self, layers: dict) -> None:
        """Expand the logical [L, E, ...] expert stacks to the physical
        slot count with an initial balanced placement, and install the
        routing buffers the forward reads."""
        if self.num_physical == self.cfg.num_experts:
            return
        from vllm_distributed_tpu.parallel.eplb import rebalance_experts
        L, E = self.cfg.num_layers, self.cfg.num_experts
        placement = rebalance_experts(np.ones((L, E)), self.num_physical,
                                      self.cfg.expert_parallel_ranks)
        self._scatter_placement(layers, placement)

    def _scatter_placement(self, layers: dict, placement) -> None:
        """Gather expert weights into physical-slot order and refresh
        the routing buffers. The logical source for slot p is the
        CURRENT first replica of placement's logical id — so this works
        both at install time (logical order) and on a live rebalance."""
        L, E = self.cfg.num_layers, self.cfg.num_experts
        p2l = placement.phys_to_logical  # [L, P]
        have_map = "expert_map" in layers

        def logical_index(arr):
            if not have_map:
                return arr  # still in logical order
            cur_first = np.asarray(layers["expert_map"])[:, :, 0]  # [L, E]
            return np.stack([np.asarray(arr)[l][cur_first[l]]
                             for l in range(L)])

        for name in self._EXPERT_WEIGHTS:
            for key in (name, name + "_scale"):
                if key not in layers:
                    continue
                logical = logical_index(layers[key])
                layers[key] = jnp.asarray(
                    np.stack([logical[l][p2l[l]] for l in range(L)]))
        r_cap = self._replica_cap
        emap = np.zeros((L, E, r_cap), np.int32)
        for l in range(L):
            for e in range(E):
                ids = placement.logical_to_phys[l, e]
                ids = ids[ids >= 0]
                emap[l, e, :len(ids)] = ids
                emap[l, e, len(ids):] = ids[0]  # safe padding
        layers["expert_map"] = jnp.asarray(emap)
        layers["expert_replicas"] = jnp.asarray(
            placement.logical_replicas.astype(np.int32))

    def apply_rebalance(self, params: dict, placement) -> dict:
        """Live EPLB step: move expert weights to the new placement and
        swap the routing buffers (reference: rebalance_execute.py, done
        here as host gathers + re-placement; the runner re-places the
        returned tree with its param shardings)."""
        self._scatter_placement(params["layers"], placement)
        return params

    # ------------------------------------------------------------------
    def mlp_block(self, lp: dict, x: jax.Array,
                  lora_ctx=None) -> jax.Array:
        """Sparse-MoE FFN via grouped (ragged) matmuls, computed exactly
        for every selected token:

        router softmax -> top-k -> renormalize (HF Mixtral semantics,
        reference models/mixtral.py MixtralMoE.forward), then the TPU
        dispatch: flatten the T*k (token, expert) assignments, sort by
        expert, run ``jax.lax.ragged_dot`` against the expert-stacked
        weights (the XLA grouped-GEMM that replaces the reference's
        fused_moe CUDA kernels / moe_pallas.py seed), and segment-sum
        the weighted rows back. Cost is k/E of the dense all-expert
        form — only selected (token, expert) pairs hit the MXU.

        VDT_MOE_BACKEND=dense restores the all-expert einsum baseline
        (also used by the FLOP-reduction regression test)."""
        top_idx, top_vals = self._route(lp, x)
        return self.moe_dispatch(lp, x, top_idx, top_vals)

    def _route(self, lp: dict, x: jax.Array) -> tuple[jax.Array, jax.Array]:
        """Router softmax -> top-k -> optional renormalize (HF Mixtral
        semantics); subclasses override for other gating schemes."""
        c = self.cfg
        # Router in fp32 for parity with the HF reference.
        logits = (x.astype(jnp.float32)
                  @ lp["router"].astype(jnp.float32))  # [T, E]
        probs = jax.nn.softmax(logits, axis=-1)
        top_vals, top_idx = jax.lax.top_k(probs, c.num_experts_per_tok)
        if c.norm_topk_prob:
            top_vals = top_vals / top_vals.sum(axis=-1, keepdims=True)
        return top_idx, top_vals

    def moe_dispatch(self, lp: dict, x: jax.Array, top_idx: jax.Array,
                     top_vals: jax.Array) -> jax.Array:
        """Routing-agnostic grouped-GEMM dispatch of (token, expert)
        assignments (see mlp_block docstring for the mechanism)."""
        from vllm_distributed_tpu import envs
        c = self.cfg
        T = x.shape[0]
        k = top_idx.shape[-1]
        E = c.num_experts

        if envs.VDT_MOE_BACKEND == "dense":
            return self._moe_dense(lp, x, top_idx, top_vals)

        if c.expert_parallel and self._a2a_applicable(T):
            # True all-to-all dispatch: tokens shard over the EP axis,
            # rows travel to their expert-owner rank and back.
            return self._moe_ep_a2a(lp, x, top_idx, top_vals)

        # Flatten assignments and sort by expert id: each expert's rows
        # become contiguous, exactly what ragged_dot's group_sizes
        # describe (reference: moe_align_block_size kernels, csrc/moe/).
        flat_e = top_idx.astype(jnp.int32).reshape(-1)        # [T*k]
        flat_t = jnp.repeat(jnp.arange(T, dtype=jnp.int32), k)
        flat_w = top_vals.reshape(-1)
        Pn = self.num_physical
        if Pn > E:
            # EPLB indirection: each assignment picks one of its logical
            # expert's physical replicas, spread round-robin by token
            # row (reference: eplb_state.py replica selection).
            choice = flat_t % lp["expert_replicas"][flat_e]
            flat_e = lp["expert_map"][flat_e, choice]
        order = jnp.argsort(flat_e, stable=True)
        se, st = flat_e[order], flat_t[order]
        sw = flat_w[order]
        xs = x[st]                                            # [T*k, H]

        if c.expert_parallel:
            y = self._moe_ep_ragged(lp, xs, se, sw)
        else:
            group_sizes = jnp.bincount(se, length=Pn)
            y = self._expert_ffn(lp, xs, group_sizes)
            y = y * sw[:, None].astype(y.dtype)
        # Un-sort + combine the k expert outputs per token.
        out = jax.ops.segment_sum(y, st, num_segments=T)
        return out.astype(x.dtype)

    def _expert_ffn(self, lp: dict, xs: jax.Array,
                    group_sizes: jax.Array) -> jax.Array:
        """SwiGLU over expert-sorted rows: three grouped GEMMs. Rows
        beyond sum(group_sizes) come back zero (ragged_dot semantics) —
        the EP path exploits that for its padding."""
        g = jax.nn.silu(
            jax.lax.ragged_dot(xs, self._w(lp, "w_gate"), group_sizes))
        u = jax.lax.ragged_dot(xs, self._w(lp, "w_up"), group_sizes)
        return jax.lax.ragged_dot(g * u, self._w(lp, "w_down"),
                                  group_sizes)

    def _a2a_applicable(self, T: int) -> bool:
        """The all-to-all dispatch needs the token bucket divisible by
        the EP width (static per-rank slices) and the mode not forced
        off; EPLB redundancy composes (the physical-replica indirection
        runs per rank on its token slice with global token indices).
        Non-applicable cases fall back to the exact replicate+psum
        path."""
        from vllm_distributed_tpu import envs
        from vllm_distributed_tpu.parallel import mesh as mesh_state
        if envs.VDT_MOE_EP_MODE != "a2a":
            return False
        if not mesh_state.has_global_mesh():
            return False
        ep = mesh_state.get_global_mesh().shape[MODEL_AXIS]
        return (ep > 1 and T % ep == 0
                and self.num_physical % ep == 0)

    def _moe_ep_a2a(self, lp: dict, x: jax.Array, top_idx: jax.Array,
                    top_vals: jax.Array) -> jax.Array:
        """Expert-parallel MoE with TRUE all-to-all dispatch (reference:
        device_communicators/all2all.py NaiveAll2AllManager and the
        dispatch/combine hooks at parallel_state.py:790-803).

        Each rank of the ``model`` axis owns E/ep whole experts AND a
        T/ep slice of the token batch. A rank buckets its own (token,
        expert) assignments by owner rank into fixed-capacity send
        buffers (capacity = its full T/ep*k rows, so no assignment is
        ever dropped — static shapes, exact compute), `lax.all_to_all`s
        rows to their expert owners, runs the grouped GEMMs locally,
        `all_to_all`s the weighted outputs back, combines its own
        tokens' k rows, and one tiled all_gather re-replicates the
        output for the (activation-replicated) engine (all three
        shuffles ride the quantized plane under VDT_QCOMM_PATHS "ep").

        ICI volume per MoE layer is O(T*k*H) each way plus the [T, H]
        gather — vs the replicate+psum path's O(ep * T * k * H) psum.
        The worst-case capacity keeps this exact; a capacity-factor
        (dropping) variant would trade exactness for bandwidth, which
        the parity tests forbid."""
        from vllm_distributed_tpu.parallel import mesh as mesh_state
        mesh = mesh_state.get_global_mesh()
        ep = mesh.shape[MODEL_AXIS]
        E_local = self.num_physical // ep
        T = x.shape[0]
        k = top_idx.shape[-1]
        Tl = T // ep
        Rk = Tl * k  # send capacity per destination (worst case)
        H = x.shape[-1]
        eplb = self.num_physical > self.cfg.num_experts

        def rank_fn(w_gate, w_up, w_down, x_, ti_, tv_, emap_, erep_):
            r = jax.lax.axis_index(MODEL_AXIS)
            xs = jax.lax.dynamic_slice_in_dim(x_, r * Tl, Tl)
            til = jax.lax.dynamic_slice_in_dim(ti_, r * Tl, Tl)
            tvl = jax.lax.dynamic_slice_in_dim(tv_, r * Tl, Tl)
            flat_e = til.astype(jnp.int32).reshape(-1)       # [Rk]
            flat_w = tvl.reshape(-1)
            flat_tok = jnp.repeat(jnp.arange(Tl, dtype=jnp.int32), k)
            if eplb:
                # EPLB indirection with GLOBAL token indices so replica
                # spreading matches the replicate-path semantics
                # (dispatch docstring; eplb_state.py replica choice).
                gtok = r * Tl + flat_tok
                choice = gtok % erep_[flat_e]
                flat_e = emap_[flat_e, choice]
            dest = flat_e // E_local
            order = jnp.argsort(dest, stable=True)
            d_sorted = dest[order]
            # Position within the destination bucket: index minus the
            # bucket's first index in the sorted order.
            within = (jnp.arange(Rk, dtype=jnp.int32) -
                      jnp.searchsorted(d_sorted, d_sorted,
                                       side="left").astype(jnp.int32))
            slot = d_sorted * Rk + within                    # unique
            send_x = jnp.zeros((ep * Rk, H), x_.dtype).at[slot].set(
                xs[flat_tok[order]])
            send_e = jnp.full((ep * Rk, ), -1, jnp.int32).at[slot].set(
                flat_e[order] % E_local)
            send_w = jnp.zeros((ep * Rk, ), flat_w.dtype).at[slot].set(
                flat_w[order])
            # Rows travel to their expert owner... (the [ep, Rk, H]
            # activation shuffle is the dominant EP wire cost; VDT_QCOMM
            # ships it block-scaled int8 — routing ids/weights stay raw,
            # they are a K/H fraction of the volume).
            from vllm_distributed_tpu.parallel import collectives
            recv_x = collectives.all_to_all(
                send_x.reshape(ep, Rk, H), MODEL_AXIS, 0, 0, path="ep")
            recv_e = jax.lax.all_to_all(
                send_e.reshape(ep, Rk), MODEL_AXIS, 0, 0).reshape(-1)
            recv_w = jax.lax.all_to_all(
                send_w.reshape(ep, Rk), MODEL_AXIS, 0, 0).reshape(-1)
            # ...grouped GEMMs over the received rows (padding rows sort
            # into the dropped E_local bucket and come back zero)...
            eid = jnp.where(recv_e >= 0, recv_e, E_local)
            order2 = jnp.argsort(eid, stable=True)
            xs2 = recv_x.reshape(ep * Rk, H)[order2]
            gs = jnp.bincount(eid[order2], length=E_local + 1)[:-1]
            g = jax.nn.silu(jax.lax.ragged_dot(xs2, w_gate, gs))
            u = jax.lax.ragged_dot(xs2, w_up, gs)
            y = jax.lax.ragged_dot(g * u, w_down, gs)
            y = y * recv_w[order2][:, None].astype(y.dtype)
            y = y[jnp.argsort(order2)]                       # recv order
            # ...and back to their owner (all_to_all is positionally an
            # involution here: my receive slice j returns as slice j).
            back = collectives.all_to_all(
                y.reshape(ep, Rk, H), MODEL_AXIS, 0, 0,
                path="ep").reshape(ep * Rk, H)
            # Combine this rank's k rows per token; slot layout gives
            # each row's source token.
            src_tok = jnp.full((ep * Rk, ), Tl, jnp.int32).at[slot].set(
                flat_tok[order])
            out_local = jax.ops.segment_sum(back, src_tok,
                                            num_segments=Tl + 1)[:Tl]
            # Re-replicate for the activation-replicated engine — the
            # [T, H] gather is the EP path's remaining wire cost after
            # the quantized a2a legs; VDT_QCOMM ships it block-scaled
            # int8 under the same "ep" path.
            return collectives.all_gather(out_local, MODEL_AXIS,
                                          tiled=True, path="ep")

        emap = (lp["expert_map"] if eplb else
                jnp.zeros((1, 1), jnp.int32))
        erep = (lp["expert_replicas"] if eplb else
                jnp.ones((1, ), jnp.int32))
        out = shard_map(
            rank_fn, mesh=mesh,
            in_specs=(P(MODEL_AXIS, None, None), P(MODEL_AXIS, None, None),
                      P(MODEL_AXIS, None, None), P(), P(), P(), P(), P()),
            out_specs=P(),
            check_vma=False)(self._w(lp, "w_gate"), self._w(lp, "w_up"),
                             self._w(lp, "w_down"), x,
                             top_idx.astype(jnp.int32),
                             top_vals.astype(jnp.float32), emap, erep)
        return out.astype(x.dtype)

    def _moe_ep_ragged(self, lp: dict, xs: jax.Array, se: jax.Array,
                       sw: jax.Array) -> jax.Array:
        """Expert-parallel dispatch: each rank of the ``model`` axis
        holds E/ep whole experts (reference: FusedMoE EP + all2all
        managers, device_communicators/all2all.py). Activations are
        replicated across the axis, so "dispatch" is a local partition —
        every rank stable-partitions ITS experts' rows to the front,
        runs the grouped GEMMs on its local expert slab (ragged_dot
        zero-fills the foreign rows), and the combine is one psum over
        ICI. Exact compute: no capacity factor, no dropped tokens."""
        from vllm_distributed_tpu.parallel import mesh as mesh_state
        mesh = mesh_state.get_global_mesh()
        ep = mesh.shape[MODEL_AXIS]
        E_local = self.num_physical // ep

        def rank_fn(w_gate, w_up, w_down, xs_, se_, sw_):
            r = jax.lax.axis_index(MODEL_AXIS)
            lo = r * E_local
            is_local = (se_ >= lo) & (se_ < lo + E_local)
            part = jnp.argsort(~is_local, stable=True)  # local rows first
            xs_l = xs_[part]
            local_ids = jnp.where(is_local[part], se_[part] - lo, E_local)
            # Foreign rows bucket into a virtual group E_local that is
            # dropped from group_sizes; ragged_dot then zero-fills them.
            group_sizes = jnp.bincount(local_ids, length=E_local + 1)[:-1]
            w = jnp.where(is_local[part], sw_[part], 0.0)
            g = jax.nn.silu(jax.lax.ragged_dot(xs_l, w_gate, group_sizes))
            u = jax.lax.ragged_dot(xs_l, w_up, group_sizes)
            y = jax.lax.ragged_dot(g * u, w_down, group_sizes)
            y = y * w[:, None].astype(y.dtype)
            y = y[jnp.argsort(part)]  # back to expert-sorted order
            from vllm_distributed_tpu.parallel import collectives
            return collectives.psum(y, MODEL_AXIS, path="ep")

        return shard_map(
            rank_fn, mesh=mesh,
            in_specs=(P(MODEL_AXIS, None, None), P(MODEL_AXIS, None, None),
                      P(MODEL_AXIS, None, None), P(), P(), P()),
            out_specs=P(),
            check_vma=False)(self._w(lp, "w_gate"),
                             self._w(lp, "w_up"),
                             self._w(lp, "w_down"), xs, se, sw)

    def _moe_dense(self, lp: dict, x: jax.Array, top_idx: jax.Array,
                   top_vals: jax.Array) -> jax.Array:
        """All-expert einsum baseline (E/k x the needed FLOPs); kept for
        A/B testing and the FLOP-reduction regression test."""
        c = self.cfg
        T = x.shape[0]
        rows = jnp.broadcast_to(
            jnp.arange(T, dtype=jnp.int32)[:, None],
            (T, c.num_experts_per_tok))
        if self.num_physical > c.num_experts:
            # EPLB: address each logical expert's first physical replica
            # (the dense baseline doesn't spread load).
            top_idx = lp["expert_map"][top_idx, 0]
        gates = jnp.zeros((T, self.num_physical), jnp.float32).at[
            rows, top_idx].set(top_vals)
        g = jax.nn.silu(
            jnp.einsum("th,ehi->eti", x, self._w(lp, "w_gate")))
        u = jnp.einsum("th,ehi->eti", x, self._w(lp, "w_up"))
        y = jnp.einsum("eti,eih->eth", g * u, self._w(lp, "w_down"))
        out = jnp.einsum("te,eth->th", gates.astype(y.dtype), y)
        return out.astype(x.dtype)


class Qwen2MoeForCausalLM(MixtralForCausalLM):
    """Qwen2-MoE (reference: vllm/model_executor/models/qwen2_moe.py):
    the Mixtral routed-expert block plus a sigmoid-gated SHARED expert
    that runs densely for every token, qkv bias, non-renormalized top-k
    routing weights, and a narrower per-expert FFN
    (moe_intermediate_size). Checkpoint names map onto the Mixtral
    layout; the shared expert adds three stacked dense tensors and the
    [H, 1] gate."""

    @classmethod
    def configure_arch(cls, arch, hf) -> None:
        arch.num_experts = hf.num_experts
        arch.num_experts_per_tok = hf.num_experts_per_tok
        arch.attention_bias = True  # Qwen2-style qkv bias, always on
        arch.norm_topk_prob = bool(getattr(hf, "norm_topk_prob", False))
        arch.moe_intermediate_size = hf.moe_intermediate_size
        arch.shared_expert_intermediate_size = \
            hf.shared_expert_intermediate_size
        if (getattr(hf, "mlp_only_layers", None)
                or getattr(hf, "decoder_sparse_step", 1) != 1):
            raise ValueError(
                "Qwen2-MoE layouts mixing dense and sparse MLP layers "
                "(mlp_only_layers / decoder_sparse_step != 1) are not "
                "supported; every layer must be sparse")

    # ------------------------------------------------------------------
    def param_specs(self) -> dict:
        specs = super().param_specs()
        layer = specs["layers"]
        # Shared expert: Megatron dense-MLP layout; the tiny sigmoid
        # gate is replicated.
        layer.update({
            "shared_gate": P(None, None, MODEL_AXIS),
            "shared_up": P(None, None, MODEL_AXIS),
            "shared_down": P(None, MODEL_AXIS, None),
            "shared_egate": P(None, None, None),
        })
        return specs

    def init_params(self, rng: jax.Array, scale: float = 0.02) -> dict:
        params = super().init_params(rng, scale)
        c = self.cfg
        L, H = c.num_layers, c.hidden_size
        Is = c.shared_expert_intermediate_size or c.intermediate_size
        keys = iter(jax.random.split(jax.random.fold_in(rng, 23), 4))

        def norm(key, shape):
            return (scale * jax.random.normal(key, shape,
                                              jnp.float32)).astype(c.dtype)

        params["layers"].update({
            "shared_gate": norm(next(keys), (L, H, Is)),
            "shared_up": norm(next(keys), (L, H, Is)),
            "shared_down": norm(next(keys), (L, Is, H)),
            "shared_egate": norm(next(keys), (L, H, 1)),
        })
        return params

    def params_from_hf_state_dict(self, tensors: dict[str, np.ndarray],
                                  ) -> dict:
        c = self.cfg
        L, E = c.num_layers, c.num_experts
        # Rename onto the Mixtral checkpoint layout, then stack the
        # shared-expert tensors on top.
        alias = dict(tensors)
        for i in range(L):
            src = f"model.layers.{i}.mlp"
            dst = f"model.layers.{i}.block_sparse_moe"
            alias[f"{dst}.gate.weight"] = tensors[f"{src}.gate.weight"]
            for e in range(E):
                for a, b in (("gate_proj", "w1"), ("down_proj", "w2"),
                             ("up_proj", "w3")):
                    alias[f"{dst}.experts.{e}.{b}.weight"] = \
                        tensors[f"{src}.experts.{e}.{a}.weight"]
        params = super().params_from_hf_state_dict(alias)

        def stack(fmt):
            return jnp.asarray(
                np.stack([np.asarray(tensors[fmt.format(i)]).T
                          for i in range(L)]), dtype=c.dtype)

        params["layers"].update({
            "shared_gate": stack(
                "model.layers.{}.mlp.shared_expert.gate_proj.weight"),
            "shared_up": stack(
                "model.layers.{}.mlp.shared_expert.up_proj.weight"),
            "shared_down": stack(
                "model.layers.{}.mlp.shared_expert.down_proj.weight"),
            "shared_egate": stack(
                "model.layers.{}.mlp.shared_expert_gate.weight"),
        })
        return params

    # ------------------------------------------------------------------
    def mlp_block(self, lp: dict, x: jax.Array,
                  lora_ctx=None) -> jax.Array:
        routed = super().mlp_block(lp, x, lora_ctx)
        from vllm_distributed_tpu.models.common import swiglu
        shared = swiglu(x, lp["shared_gate"], lp["shared_up"],
                        lp["shared_down"], act=self._act)
        # Sigmoid gate in fp32 (HF computes the gate on fp hidden).
        gate = jax.nn.sigmoid(x.astype(jnp.float32)
                              @ lp["shared_egate"].astype(jnp.float32))
        return routed + gate.astype(x.dtype) * shared
