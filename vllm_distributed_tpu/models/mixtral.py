"""Mixtral-family sparse-MoE decoder (covers HF ``MixtralForCausalLM``).

TPU-first equivalent of the reference's vllm/model_executor/models/
mixtral.py + layers/fused_moe/layer.py:593 ``FusedMoE`` (CUDA grouped-GEMM
kernels with all-to-all dispatch over the EP group): here every expert's
FFN weights are STACKED on a leading expert axis and the whole MoE block
is three einsums — router top-k gates, batched expert FFNs, weighted
combine. Under expert parallelism the expert axis is sharded over the
``model`` mesh axis (EP spans the TP group, reference
parallel_state.py:1189-1204); GSPMD turns the combine contraction into
the psum that replaces the reference's all-to-all combine. Every selected
token is computed exactly (no capacity-factor drops), matching HF
numerics for parity tests.

The attention/embedding/norm stack is inherited from the Llama decoder
(Mixtral is architecturally Llama + MoE MLP).
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from vllm_distributed_tpu.models.llama import (MODEL_AXIS,
                                               LlamaForCausalLM)


class MixtralForCausalLM(LlamaForCausalLM):

    # ------------------------------------------------------------------
    def param_specs(self) -> dict:
        specs = super().param_specs()
        layer = specs["layers"]
        for k in ("gate", "up", "down"):
            layer.pop(k)
        layer["router"] = P(None, None, None)  # [L, H, E] replicated
        if self.cfg.expert_parallel:
            # Experts sharded over the model axis: each rank holds
            # E/ep_size whole experts (reference: FusedMoE EP path).
            ffn = P(None, MODEL_AXIS, None, None)
            layer.update({"w_gate": ffn, "w_up": ffn, "w_down": ffn})
        else:
            # TP inside each expert's FFN (Megatron layout per expert).
            layer.update({
                "w_gate": P(None, None, None, MODEL_AXIS),
                "w_up": P(None, None, None, MODEL_AXIS),
                "w_down": P(None, None, MODEL_AXIS, None),
            })
        return specs

    def init_params(self, rng: jax.Array, scale: float = 0.02) -> dict:
        params = super().init_params(rng, scale)
        c = self.cfg
        L, H, I, E = (c.num_layers, c.hidden_size, c.intermediate_size,
                      c.num_experts)
        keys = iter(jax.random.split(jax.random.fold_in(rng, 17), 4))

        def norm(key, shape):
            return (scale * jax.random.normal(key, shape,
                                              jnp.float32)).astype(c.dtype)

        layers = params["layers"]
        for k in ("gate", "up", "down"):
            layers.pop(k)
        layers["router"] = norm(next(keys), (L, H, E))
        layers["w_gate"] = norm(next(keys), (L, E, H, I))
        layers["w_up"] = norm(next(keys), (L, E, H, I))
        layers["w_down"] = norm(next(keys), (L, E, I, H))
        return params

    def params_from_hf_state_dict(self, tensors: dict[str, np.ndarray],
                                  ) -> dict:
        c = self.cfg
        L, E = c.num_layers, c.num_experts
        # The base mapper handles every non-MLP tensor but requires the
        # dense-MLP names; alias them to expert 0's weights (the dense
        # entries are dropped right after) and stack the real expert
        # tensors below.
        alias = dict(tensors)
        for i in range(L):
            alias[f"model.layers.{i}.mlp.gate_proj.weight"] = tensors[
                f"model.layers.{i}.block_sparse_moe.experts.0.w1.weight"]
            alias[f"model.layers.{i}.mlp.up_proj.weight"] = tensors[
                f"model.layers.{i}.block_sparse_moe.experts.0.w3.weight"]
            alias[f"model.layers.{i}.mlp.down_proj.weight"] = tensors[
                f"model.layers.{i}.block_sparse_moe.experts.0.w2.weight"]
        params = super().params_from_hf_state_dict(alias)
        layers = params["layers"]
        for k in ("gate", "up", "down"):
            layers.pop(k)

        def stack_experts(fmt, transpose=True):
            per_layer = []
            for i in range(L):
                mats = [np.asarray(tensors[fmt.format(i, e)])
                        for e in range(E)]
                per_layer.append(
                    np.stack([m.T if transpose else m for m in mats]))
            return jnp.asarray(np.stack(per_layer), dtype=c.dtype)

        layers["router"] = jnp.asarray(
            np.stack([
                np.asarray(
                    tensors[f"model.layers.{i}.block_sparse_moe"
                            f".gate.weight"]).T for i in range(L)
            ]), dtype=c.dtype)
        layers["w_gate"] = stack_experts(
            "model.layers.{}.block_sparse_moe.experts.{}.w1.weight")
        layers["w_up"] = stack_experts(
            "model.layers.{}.block_sparse_moe.experts.{}.w3.weight")
        layers["w_down"] = stack_experts(
            "model.layers.{}.block_sparse_moe.experts.{}.w2.weight")
        return params

    # ------------------------------------------------------------------
    def mlp_block(self, lp: dict, x: jax.Array) -> jax.Array:
        """Sparse-MoE FFN, computed exactly (every selected token):

        router softmax -> top-k -> renormalize (HF Mixtral semantics,
        reference models/mixtral.py MixtralMoE.forward), then a dense
        gate matrix [T, E] weights batched all-expert FFN outputs. Cost
        is E/k times the active FLOPs — the all-to-all dispatch kernel
        (fused_moe) replaces this when token counts grow; the einsum
        form is the compiler-friendly baseline and the combine
        contraction IS the EP psum under GSPMD."""
        c = self.cfg
        T = x.shape[0]
        k = c.num_experts_per_tok
        # Router in fp32 for parity with the HF reference.
        logits = (x.astype(jnp.float32)
                  @ lp["router"].astype(jnp.float32))  # [T, E]
        probs = jax.nn.softmax(logits, axis=-1)
        top_vals, top_idx = jax.lax.top_k(probs, k)
        top_vals = top_vals / top_vals.sum(axis=-1, keepdims=True)
        rows = jnp.broadcast_to(
            jnp.arange(T, dtype=jnp.int32)[:, None], (T, k))
        gates = jnp.zeros((T, c.num_experts), jnp.float32).at[
            rows, top_idx].set(top_vals)

        # Batched all-expert FFN: [E, T, I] intermediates.
        g = jax.nn.silu(jnp.einsum("th,ehi->eti", x, lp["w_gate"]))
        u = jnp.einsum("th,ehi->eti", x, lp["w_up"])
        y = jnp.einsum("eti,eih->eth", g * u, lp["w_down"])
        # Weighted combine; contraction over e lowers to the EP psum.
        out = jnp.einsum("te,eth->th", gates.astype(y.dtype), y)
        return out.astype(x.dtype)
