"""Llama-family decoder in functional JAX (covers Llama 2/3, Mistral,
Qwen2 — any HF ``LlamaForCausalLM``-shaped config, incl. attention bias).

TPU-first equivalent of the reference's vllm/model_executor/models/llama.py
(which composes ColumnParallelLinear/RowParallelLinear with explicit NCCL
allreduce): here weights are one pytree with ``PartitionSpec`` annotations;
``jit`` + GSPMD insert the TP collectives over ICI. Layers execute under
``lax.scan`` over a stacked [L, ...] parameter tree, which keeps compile
time O(1) in depth — the TPU answer to the reference's CUDA-graph capture
per shape.

Weight layout mirrors HF checkpoint tensors transposed to right-multiply
form (x @ W), stacked on a leading layer axis.
"""

from dataclasses import dataclass, field
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from vllm_distributed_tpu.models.common import (AttentionBatch,
                                                compute_rope_cos_sin,
                                                rms_norm)
from vllm_distributed_tpu.ops.attention import write_kv_and_attend

MODEL_AXIS = "model"
TOKEN_AXIS = "token"


def _replicate_kv_heads(w: jax.Array, num_kv_heads: int,
                        replicas: int) -> jax.Array:
    """Repeat each KV head's slice of the last axis ``replicas`` times.

    Per-head repetition (not tiling) keeps the q-head→kv-head map of GQA
    intact: q heads grouped onto checkpoint head h land on one of h's
    replicas, so attention output is bit-identical to the un-replicated
    model."""
    if replicas == 1:
        return w
    *lead, dkv = w.shape
    head_dim = dkv // num_kv_heads
    w = w.reshape(*lead, num_kv_heads, head_dim)
    w = jnp.repeat(w, replicas, axis=-2)
    return w.reshape(*lead, num_kv_heads * replicas * head_dim)


@dataclass
class LlamaArchConfig:
    """Subset of the HF config the forward pass needs (static)."""

    vocab_size: int
    hidden_size: int
    intermediate_size: int
    num_layers: int
    num_q_heads: int
    num_kv_heads: int
    head_dim: int
    rope_theta: float = 10000.0
    rope_scaling: Optional[dict] = None
    rms_norm_eps: float = 1e-6
    tie_word_embeddings: bool = False
    attention_bias: bool = False  # Qwen2-style qkv bias
    # Mixture-of-experts (Mixtral-style); 0 experts = dense MLP.
    num_experts: int = 0
    num_experts_per_tok: int = 2
    # Renormalize top-k routing weights (Mixtral yes, Qwen2-MoE no —
    # reference: the renormalize flag of FusedMoE).
    norm_topk_prob: bool = True
    # Per-expert FFN width when it differs from intermediate_size
    # (Qwen2-MoE moe_intermediate_size); None = intermediate_size.
    moe_intermediate_size: Optional[int] = None
    # Qwen2-MoE shared expert: a dense SwiGLU of this width runs for
    # every token, sigmoid-gated, added to the routed output. 0 = none.
    shared_expert_intermediate_size: int = 0
    # Physical expert slots for EPLB (reference: distributed/eplb/):
    # 0 means = num_experts (no redundancy). Extra slots host replicas
    # of hot experts; the router maps logical -> physical through a
    # param-tree buffer so rebalances never recompile.
    num_physical_experts: int = 0
    # Shard experts over the "model" mesh axis (EP spans the TP group,
    # reference: parallel_state.py:1189-1204) instead of TP inside each
    # expert's FFN.
    expert_parallel: bool = False
    # Rank count of the expert-sharding axis (loader sets = tp under
    # expert_parallel); EPLB packs replicas rank-aware with it.
    expert_parallel_ranks: int = 1
    # KV-head replication factor for tp > num_kv_heads (reference:
    # QKVParallelLinear kv-head replication in
    # vllm/model_executor/layers/linear.py — each rank holds one whole
    # KV head when TP exceeds the head count). Each checkpoint KV head's
    # weights and cache rows are repeated this many times so the kv-head
    # dimension divides the model mesh axis; repeat-per-head preserves
    # GQA grouping exactly.
    num_kv_head_replicas: int = 1
    # Weight quantization scheme (None | "int8" | "fp8"); see
    # quantize_params.
    quantization: Optional[str] = None
    # int4g group width along the input dim; set from the checkpoint's
    # quantization_config.group_size so a GPTQ/AWQ re-quantization
    # reuses the original group lattice (lossless).
    quant_group_size: int = 128
    # M-RoPE (Qwen2-VL): per-frequency (temporal, height, width) section
    # widths over the half head dim; None = plain rope (reference:
    # rope_scaling.mrope_section of qwen2_vl.py).
    mrope_section: Optional[tuple] = None
    # Per-layer NoPE mask (True = this layer skips rotary): SmolLM3's
    # no_rope_layers, and the hybrid families whose FULL-attention
    # layers are position-free while sliding layers rope (Cohere2,
    # EXAONE-4). None = rotary everywhere.
    nope_layers: Optional[tuple] = None
    # Mixed MoE layouts (ERNIE-4.5 / GLM-4-MoE): this many leading
    # layers are PLAIN dense decoder blocks in their own stacked
    # subtree (models/moe_mixed.py); 0 = uniform stack.
    dense_prefix: int = 0
    # ERNIE routing-weight normalizer clamp (moe_norm_min).
    moe_norm_min: float = 1e-12
    # Multi-LoRA slots (0 disables; see models/lora.py).
    max_loras: int = 0
    max_lora_rank: int = 16
    # Sliding-window attention size (Mistral-style); None = full
    # causal. Compute-level only: pages outside the window stay
    # allocated (freeing them is a kv-cache-manager extension).
    sliding_window: Optional[int] = None
    # Per-layer window layout for models mixing sliding and full-causal
    # layers: entry i is layer i's window (0 = full causal). None means
    # ``sliding_window`` applies uniformly. Gemma2 alternates
    # sliding/full; Qwen2's max_window_layers keeps the first N layers
    # full (reference: per-layer sliding_window in models/gemma2.py and
    # models/qwen2.py attention construction).
    window_pattern: Optional[tuple] = None
    # Logit soft-capping, cap*tanh(x/cap); 0 disables (Gemma2,
    # reference: attn_logit_softcapping/final_logit_softcapping in
    # models/gemma2.py).
    attn_logit_softcap: float = 0.0
    final_logit_softcap: float = 0.0
    # Attention score scale override: scores use this value**-0.5
    # instead of head_dim**-0.5 (Gemma2 query_pre_attn_scalar).
    query_pre_attn_scalar: Optional[float] = None
    # Gemma2-style sandwich norms: an extra RMSNorm on each sub-block's
    # OUTPUT (attention and MLP) before the residual add.
    extra_layer_norms: bool = False
    # Sequence parallelism: constrain the residual stream token-sharded
    # on the model axis between blocks (see ParallelConfig.
    # enable_sequence_parallel).
    sequence_parallel: bool = False
    # Fused transformer-block decode (ops/pallas_block.py): set ONCE by
    # models/loader.py when VDT_BLOCK_FUSION=1 and the arch/parallel
    # layout qualifies (standard dense pre-norm gated block, TP=1).
    # Adds the re-laid "wqkv" fused projection to the param tree and
    # unlocks the block_fused batch path in run_layers.
    block_fusion: bool = False
    # Family knobs reused by Llama-shaped variants: embedding scale
    # (Gemma multiplies by sqrt(H)), MLP activation, per-head q/k
    # RMSNorm (Qwen3).
    embed_scale: float = 1.0
    hidden_act: str = "silu"  # silu | gelu_tanh | gelu | relu2
    qk_norm: bool = False
    # ---- generic block-structure knobs (GPT-NeoX / Phi / StableLM /
    # Starcoder2 / Cohere / Olmo2 / Granite families) ----
    # Norm flavor: "rms" or mean-centering "layernorm" (+ optional beta).
    norm_type: str = "rms"
    norm_bias: bool = False
    # Partial rotary: rope covers only the first rotary_dim lanes of
    # each head (GPT-NeoX rotary_pct, Phi partial_rotary_factor);
    # None = full head_dim.
    rotary_dim: Optional[int] = None
    # Pairwise (complex) rope instead of rotate-half (Cohere, GLM).
    rope_interleaved: bool = False
    # Parallel residual: h += attn(ln1(h)) + mlp(ln2(h)) (GPT-NeoX);
    # shared_block_ln feeds BOTH sub-blocks from ln1 (Phi, Cohere).
    parallel_block: bool = False
    shared_block_ln: bool = False
    # False + extra_layer_norms: post-norm block (Olmo2 — sublayer
    # inputs un-normed, outputs normed before the residual add).
    pre_norm: bool = True
    # Non-gated MLP: fc1 -> act -> fc2 (GPT-NeoX/Phi/Starcoder2).
    mlp_gated: bool = True
    mlp_bias: bool = False
    attention_out_bias: bool = False
    # Full-row q/k RMSNorm before the head reshape (Olmo2) — distinct
    # from the per-head qk_norm.
    qk_norm_full: bool = False
    # Per-head qk norms carry a bias (Persimmon's LayerNorm flavor;
    # the norm kind follows norm_type).
    qk_norm_bias: bool = False
    # Clamp q/k/v projections to [-clip, clip] (OLMo clip_qkv).
    qkv_clip: Optional[float] = None
    # Separate rope base for SLIDING-window layers (Gemma3: local
    # theta 10k on sliding layers, global theta 1M + scaling on full
    # layers). None = one table for every layer.
    rope_theta_local: Optional[float] = None
    # Score scale as a direct multiplier (Granite attention_multiplier);
    # overrides the head-dim rule and query_pre_attn_scalar.
    sm_scale_override: Optional[float] = None
    # Position encoding: "rope" (default) or "learned" absolute tables
    # added at embed time (GPT-2 / OPT / GPTBigCode lineage; reference:
    # the get_position_embeddings path of models/gpt2.py, opt.py). The
    # table is params["embed_pos"] [max_position_embeddings, H];
    # pos_offset shifts lookups (OPT writes positions starting at 2).
    pos_embedding: str = "rope"
    max_position_embeddings: int = 0
    pos_offset: int = 0
    # ALiBi attention bias (Bloom/MPT; usually with pos_embedding =
    # "none"): slope * (kv_pos - q_pos) added per head before masking.
    alibi: bool = False
    # Apply the final norm before the LM head (False for post-norm
    # encoder-decoder stacks like BART, whose last sublayer already
    # normalized).
    final_norm: bool = True
    # Learned per-head attention-sink logits joining each softmax
    # denominator (gpt-oss; params carry layers["sinks"] [L, heads]).
    attn_sinks: bool = False
    # LayerNorm directly after the embedding lookup (Bloom's
    # word_embeddings_layernorm).
    embed_ln: bool = False
    # Residual-branch multiplier (Granite residual_multiplier).
    residual_multiplier: float = 1.0
    # Final-logit multiplier (Cohere logit_scale; Granite
    # 1/logits_scaling).
    logit_multiplier: float = 1.0
    dtype: Any = jnp.bfloat16

    @property
    def total_kv_heads(self) -> int:
        """KV heads actually materialized (checkpoint heads × replicas)."""
        return self.num_kv_heads * self.num_kv_head_replicas

    @staticmethod
    def _resolve_sliding_window(hf):
        """HF sliding-window semantics -> (window, per_layer_pattern).

        Uniform layouts (Mistral) give (window, None); mixed layouts
        give a per-layer pattern — preferably from ``hf.layer_types``
        ("sliding_attention"/"full_attention" per layer: Gemma2
        alternates, Qwen2 marks layers >= max_window_layers), falling
        back to max_window_layers arithmetic for configs without it.
        Returns (None, None) when no layer is windowed."""
        window = getattr(hf, "sliding_window", None)
        if not window or not getattr(hf, "use_sliding_window", True):
            return None, None
        window = int(window)
        L = hf.num_hidden_layers
        layer_types = getattr(hf, "layer_types", None)
        if layer_types:
            pattern = tuple(window if t == "sliding_attention" else 0
                            for t in layer_types)
            if not any(pattern):
                return None, None
            if all(pattern):
                return window, None
            return window, pattern
        mwl = getattr(hf, "max_window_layers", None)
        if mwl is None or mwl <= 0:
            return window, None
        if mwl >= L:
            return None, None  # every layer full attention
        # First mwl layers full-causal, the rest windowed (Qwen2).
        return window, (0, ) * mwl + (window, ) * (L - mwl)

    @classmethod
    def from_hf_config(cls, hf, dtype=jnp.bfloat16) -> "LlamaArchConfig":
        head_dim = getattr(hf, "head_dim", None) or (
            hf.hidden_size // hf.num_attention_heads)
        sliding_window, window_pattern = cls._resolve_sliding_window(hf)
        rope_scaling = getattr(hf, "rope_scaling", None)
        rtype = (rope_scaling or {}).get(
            "rope_type", (rope_scaling or {}).get("type"))
        if rtype == "longrope":
            # LongRoPE selects long/short factors by the serving window
            # vs the pretraining window; fold both config-level fields
            # into the dict so the rope math stays self-contained.
            rope_scaling = dict(
                rope_scaling,
                original_max_position_embeddings=getattr(
                    hf, "original_max_position_embeddings",
                    hf.max_position_embeddings),
                max_position_embeddings=hf.max_position_embeddings)
        return cls(
            vocab_size=hf.vocab_size,
            hidden_size=hf.hidden_size,
            intermediate_size=(
                getattr(hf, "intermediate_size", None)
                or getattr(hf, "ffn_hidden_size", None)  # Falcon
                or 4 * hf.hidden_size),
            num_layers=hf.num_hidden_layers,
            num_q_heads=hf.num_attention_heads,
            num_kv_heads=getattr(hf, "num_key_value_heads",
                                 hf.num_attention_heads),
            head_dim=head_dim,
            rope_theta=getattr(hf, "rope_theta", 10000.0),
            rope_scaling=rope_scaling,
            rms_norm_eps=getattr(hf, "rms_norm_eps", 1e-6),
            tie_word_embeddings=getattr(hf, "tie_word_embeddings", False),
            attention_bias=getattr(hf, "attention_bias", False),
            sliding_window=sliding_window,
            window_pattern=window_pattern,
            num_experts=getattr(hf, "num_local_experts", 0),
            num_experts_per_tok=getattr(hf, "num_experts_per_tok", 2),
            # HF Llama semantics: attention_bias also biases o_proj and
            # mlp_bias biases the gated MLP (families whose HF code
            # deviates override in configure_arch).
            attention_out_bias=bool(getattr(hf, "attention_bias",
                                            False)),
            mlp_bias=bool(getattr(hf, "mlp_bias", False)),
            dtype=dtype,
        )


class LlamaForCausalLM:
    """Stateless model: holds config + param specs; params live outside."""

    # Matrix weights eligible for int8 quantize-on-load (reference:
    # quantization/tpu_int8.py quantizes the linear layers; embed stays
    # fp for the gather, lm_head for logit fidelity).
    QUANT_TARGETS = ("wq", "wk", "wv", "wo", "gate", "up", "down")
    # Matrices that accept LoRA adapters (reference: lora/layers.py
    # wrapping every parallel linear; MoE models restrict to attention).
    LORA_TARGETS = ("wq", "wk", "wv", "wo", "gate", "up", "down")
    # Families with a biased LM head (Phi, GPT-J): specs/init/load key
    # on this; the forward applies params["lm_head_b"] when present.
    LM_HEAD_BIAS = False

    def __init__(self, cfg: LlamaArchConfig) -> None:
        self.cfg = cfg

    @classmethod
    def arch_config_source(cls, hf):
        """The HF (sub-)config carrying the decoder dims (wrapper
        configs like llava point at text_config)."""
        return hf

    @classmethod
    def configure_arch(cls, arch: LlamaArchConfig, hf) -> None:
        """Family-specific arch-config tweaks, applied by the loader
        after the generic from_hf_config mapping (subclass hook)."""

    # ------------------------------------------------------------------
    # Quantization (w8a16)
    # ------------------------------------------------------------------
    def quantize_params(self, params: dict) -> dict:
        """Weight-only quantization of the listed layer matrices, w8a16
        style (reference: quantization/tpu_int8.py + the fp8 configs):

        * "int8": symmetric per-output-channel, scale = absmax/127.
        * "fp8": float8_e4m3fn payloads with the same per-channel
          scaling (absmax mapped to the e4m3 max of 448).
        * "int4": symmetric per-channel absmax/7 in jnp.int4 — XLA
          packs int4 two-per-byte in TPU HBM, a native 4-bit weight
          datapath (a "-GPTQ"/"-AWQ" checkpoint + --quantization int4
          keeps the 4-bit HBM footprint after the load-time dequant;
          reference: the W4A16 serving path of quantization/gptq.py).

        * "w8a8": int8 weights (same per-channel scaling) AND dynamic
          per-token int8 activations — the dot runs int8 x int8 on the
          MXU with an int32 accumulator, rescaled by the product of
          scales (reference: the w8a8 schemes of
          quantization/compressed_tensors + csrc int8 quant kernels).

        Matmuls dequantize at read (XLA fuses convert*scale into the
        dot's operand load); w8a8 instead quantizes the activation at
        the dot via _mm."""
        scheme = self.cfg.quantization
        if scheme == "w8a8":
            scheme = "int8"  # same weight payloads; _mm changes the dot
        if scheme == "int4g":
            return self._quantize_groupwise(params)
        if scheme not in ("int4", "int8", "fp8"):
            return params
        layers = params["layers"]
        for name in self.QUANT_TARGETS:
            w = layers.get(name)
            if w is None:
                continue
            w32 = np.asarray(w, np.float32)
            absmax = np.max(np.abs(w32), axis=-2, keepdims=True)
            if scheme == "int8":
                scale = np.maximum(absmax / 127.0, 1e-8)
                q = jnp.asarray(
                    np.clip(np.round(w32 / scale), -127,
                            127).astype(np.int8))
            elif scheme == "int4":
                import ml_dtypes
                scale = np.maximum(absmax / 7.0, 1e-8)
                q = jnp.asarray(
                    np.clip(np.round(w32 / scale), -8,
                            7).astype(ml_dtypes.int4))
            else:
                import ml_dtypes
                scale = np.maximum(absmax / 448.0, 1e-8)
                # Cast HOST-side so only fp8 bytes ever hit device HBM
                # (same contract as the int8 branch).
                q = jnp.asarray(
                    (w32 / scale).astype(ml_dtypes.float8_e4m3fn))
            layers[name] = q
            layers[name + "_scale"] = jnp.asarray(scale, jnp.float32)
        return params

    GROUP_SIZE = 128  # int4g quantization group along the input dim

    def _quantize_groupwise(self, params: dict) -> dict:
        """Group-wise asymmetric uint4 ("int4g"): per (128-input-row
        group, output channel) scale/min. A GPTQ/AWQ checkpoint's
        load-time fp reconstruction lies exactly on each group's
        4-bit lattice, so this re-quantization recovers the original
        packed values bit-exactly (up to fp rounding) — the 4-bit HBM
        footprint and group fidelity survive into serving (reference:
        the gptq_marlin W4A16 serving path)."""
        import ml_dtypes
        layers = params["layers"]
        for name in self.QUANT_TARGETS:
            w = layers.get(name)
            if w is None:
                continue
            w32 = np.asarray(w, np.float32)  # [L, K, N]
            K = w32.shape[-2]
            g = self.cfg.quant_group_size
            if K % g:
                g = self.GROUP_SIZE if K % self.GROUP_SIZE == 0 else K
            shp = w32.shape[:-2] + (K // g, g) + w32.shape[-1:]
            wg = w32.reshape(shp)
            wmin = wg.min(axis=-2)  # [L, G, N]
            wmax = wg.max(axis=-2)
            scale = np.maximum((wmax - wmin) / 15.0, 1e-8)
            q = np.clip(
                np.round((wg - wmin[..., None, :]) / scale[..., None, :]),
                0, 15).astype(ml_dtypes.uint4)
            layers[name] = jnp.asarray(q.reshape(w32.shape))
            layers[name + "_gscale"] = jnp.asarray(scale, jnp.float32)
            layers[name + "_gmin"] = jnp.asarray(wmin, jnp.float32)
        return params

    _QUANT_DTYPES = (jnp.int8, jnp.float8_e4m3fn, jnp.int4)
    # Row-parallel projections whose combining all-reduce the quantized
    # communication plane may take over (see _mm).
    _ROW_PARALLEL = ("wo", "down", "fc2")

    def _use_quant_kernel(self) -> bool:
        """Fused dequant-GEMM eligibility: pallas backend on one chip
        (pallas_call is opaque to GSPMD — sharded dots keep the XLA
        dequant-in-dot path, whose convert fuses into the operand
        load)."""
        from vllm_distributed_tpu.ops.attention import \
            resolve_attention_backend
        from vllm_distributed_tpu.parallel import mesh as mesh_state
        if resolve_attention_backend() != "pallas":
            return False
        return (not mesh_state.has_global_mesh()
                or mesh_state.tp_size() == 1)

    def _w(self, lp: dict, name: str) -> jax.Array:
        """Dequantizing weight accessor: identity for fp weights."""
        w = lp[name]
        if w.dtype == jnp.uint4:
            # int4g group-wise: w = q * scale[g] + min[g] along the
            # input dim (XLA fuses the reshape/broadcast into the dot).
            K, N = w.shape[-2], w.shape[-1]
            G = lp[name + "_gscale"].shape[-2]
            g = K // G
            wq = w.astype(jnp.float32).reshape(*w.shape[:-2], G, g, N)
            wf = (wq * lp[name + "_gscale"][..., :, None, :] +
                  lp[name + "_gmin"][..., :, None, :])
            return wf.reshape(w.shape).astype(self.cfg.dtype)
        if w.dtype in self._QUANT_DTYPES:
            return (w.astype(self.cfg.dtype) *
                    lp[name + "_scale"].astype(self.cfg.dtype))
        return w

    def _mm(self, lp: dict, name: str, x: jax.Array) -> jax.Array:
        """Quantization-aware matmul ``x @ w``: under w8a8 the
        activation is dynamically quantized per token (absmax/127) and
        the dot runs int8 x int8 -> int32 on the MXU, rescaled by
        act_scale * weight_scale; every other scheme dequantizes the
        weight into a normal fp dot (reference: the per-token dynamic
        activation quant of csrc/quantization/ int8 kernels). Small
        (decode-sized) weight-only dots on a single chip take the fused
        Pallas dequant-GEMM so only packed bytes stream from HBM
        (ops/pallas_quant_matmul.py; reference capability:
        csrc/quantization/gptq_marlin).

        Row-parallel output projections (wo / down / fc2: input dim
        sharded over the model axis, the dot's combining all-reduce is
        the dense-TP wire cost) route through the explicit quantized
        reduce when VDT_QCOMM enables the "tp" path — shard_map makes
        GSPMD's implicit psum OURS to quantize
        (parallel/collectives.row_parallel_dot). Quantized-weight
        layouts and sequence parallelism (whose reduce is already
        rewritten to reduce_scatter + all_gather) keep the GSPMD
        path."""
        w = lp[name]
        if (name in self._ROW_PARALLEL and w.ndim == 2 and x.ndim == 2
                and not self.cfg.sequence_parallel
                and w.dtype not in self._QUANT_DTYPES
                and w.dtype != jnp.uint4):
            from vllm_distributed_tpu.parallel import collectives
            if collectives.tp_reduce_applicable():
                return collectives.row_parallel_dot(x, w)
        if (w.dtype == jnp.uint4 and x.ndim == 2 and x.shape[0] <= 64
                and self._use_quant_kernel()):
            from vllm_distributed_tpu import envs
            from vllm_distributed_tpu.ops.pallas_quant_matmul import \
                quant_matmul_grouped
            return quant_matmul_grouped(
                x, w, lp[name + "_gscale"], lp[name + "_gmin"],
                interpret=envs.VDT_PALLAS_INTERPRET)
        if (w.dtype in self._QUANT_DTYPES
                and self.cfg.quantization != "w8a8"
                and x.ndim == 2 and x.shape[0] <= 64
                and self._use_quant_kernel()):
            from vllm_distributed_tpu import envs
            from vllm_distributed_tpu.ops.pallas_quant_matmul import \
                quant_matmul
            return quant_matmul(x, w, lp[name + "_scale"],
                                interpret=envs.VDT_PALLAS_INTERPRET)
        if self.cfg.quantization == "w8a8" and w.dtype == jnp.int8:
            amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1,
                           keepdims=True)
            xs = jnp.maximum(amax / 127.0, 1e-8)
            xq = jnp.clip(jnp.round(x.astype(jnp.float32) / xs),
                          -127, 127).astype(jnp.int8)
            acc = jax.lax.dot_general(
                xq, w, (((x.ndim - 1, ), (0, )), ((), ())),
                preferred_element_type=jnp.int32)
            out = (acc.astype(jnp.float32) * xs *
                   lp[name + "_scale"].astype(jnp.float32))
            return out.astype(x.dtype)
        return x @ self._w(lp, name)

    # ------------------------------------------------------------------
    # Parameter tree
    # ------------------------------------------------------------------
    def param_specs(self) -> dict:
        """PartitionSpecs matching self.init_params' tree: TP shards the
        head/ffn dimension on the "model" mesh axis (Megatron layout:
        column-parallel up-projections, row-parallel down-projections —
        reference vllm/model_executor/layers/linear.py, re-expressed as
        GSPMD shardings)."""
        c = self.cfg
        layer = {
            "input_ln": P(None, None),
            "wq": P(None, None, MODEL_AXIS),
            "wk": P(None, None, MODEL_AXIS),
            "wv": P(None, None, MODEL_AXIS),
            "wo": P(None, MODEL_AXIS, None),
            "post_ln": P(None, None),
        }
        if c.mlp_gated:
            layer.update({
                "gate": P(None, None, MODEL_AXIS),
                "up": P(None, None, MODEL_AXIS),
                "down": P(None, MODEL_AXIS, None),
            })
            if c.mlp_bias:
                layer.update({"gate_bias": P(None, MODEL_AXIS),
                              "up_bias": P(None, MODEL_AXIS),
                              "down_bias": P(None, None)})
        else:
            layer.update({
                "fc1": P(None, None, MODEL_AXIS),
                "fc2": P(None, MODEL_AXIS, None),
            })
            if c.mlp_bias:
                layer.update({"fc1_b": P(None, MODEL_AXIS),
                              "fc2_b": P(None, None)})
        if c.norm_bias:
            layer.update({"input_ln_b": P(None, None),
                          "post_ln_b": P(None, None)})
        if c.attention_out_bias:
            layer["bo"] = P(None, None)
        if c.parallel_block and c.shared_block_ln:
            # Single shared pre-norm: no post_ln in the block.
            layer.pop("post_ln")
            layer.pop("post_ln_b", None)
        if not c.pre_norm:
            layer.pop("input_ln")
            layer.pop("post_ln", None)
            layer.pop("input_ln_b", None)
            layer.pop("post_ln_b", None)
        if c.attention_bias:
            layer.update({
                "bq": P(None, MODEL_AXIS),
                "bk": P(None, MODEL_AXIS),
                "bv": P(None, MODEL_AXIS),
            })
        if c.qk_norm or c.qk_norm_full:
            layer.update({
                "q_norm": P(None, None),
                "k_norm": P(None, None),
            })
            if c.qk_norm_bias:
                layer.update({"q_norm_b": P(None, None),
                              "k_norm_b": P(None, None)})
        if c.extra_layer_norms:
            layer.update({
                "post_attn_ln": P(None, None),
                "post_ffw_ln": P(None, None),
            })
        if c.block_fusion:
            layer["wqkv"] = P(None, None, MODEL_AXIS)
        self._add_scale_specs(layer)
        self._add_lora_specs(layer)
        specs = {
            "embed": P(None, None),
            "layers": layer,
            "final_ln": P(None),
            "lm_head": P(None, MODEL_AXIS),
        }
        if c.pos_embedding == "learned":
            specs["embed_pos"] = P(None, None)
        if c.embed_ln:
            specs["embed_ln_w"] = P(None)
            if c.norm_bias:
                specs["embed_ln_b"] = P(None)
        if self.LM_HEAD_BIAS:
            specs["lm_head_b"] = P(MODEL_AXIS)
        if c.norm_bias:
            specs["final_ln_b"] = P(None)
        return specs

    def _add_lora_specs(self, layer: dict) -> None:
        """Adapter-buffer shardings: B follows the base weight's output
        sharding, A its input sharding; rank never shards."""
        if self.cfg.max_loras == 0:
            return
        for name in self.LORA_TARGETS:
            wspec = layer.get(name)
            if wspec is None:
                continue
            entries = list(wspec)  # [L, in, out]
            layer[name + "_a"] = P(None, None, entries[1], None)
            layer[name + "_b"] = P(None, None, None, entries[2])

    def _install_lora_buffers(self, layers: dict) -> None:
        if self.cfg.max_loras == 0:
            return
        from vllm_distributed_tpu.models.lora import init_lora_buffers
        targets = [t for t in self.LORA_TARGETS if t in layers]
        layers.update(init_lora_buffers(self.cfg, targets))

    def _lora_delta(self, lp: dict, name: str, x, ctx):
        """Adapter contribution for one matmul; zero-cost branch when
        LoRA is disabled (static)."""
        if ctx is None or (name + "_a") not in lp:
            return 0
        from vllm_distributed_tpu.models.lora import lora_apply
        return lora_apply(x, lp[name + "_a"], lp[name + "_b"], ctx)

    def _add_scale_specs(self, layer: dict) -> None:
        """Per-channel scale specs mirror their weight's spec with the
        reduced (input) axis unsharded — scales broadcast over it. All
        weight specs here are written at full rank, so the scale keeps
        the spec with only the second-to-last entry cleared."""
        for name in list(layer):
            if name.endswith(("_scale", "_gscale", "_gmin")):
                del layer[name]
        if self.cfg.quantization not in ("int4", "int8", "fp8", "w8a8",
                                         "int4g"):
            return
        for name in self.QUANT_TARGETS:
            spec = layer.get(name)
            if spec is None:
                continue
            if self.cfg.quantization == "int4g":
                # The group dim subdivides the input dim, so it shards
                # exactly as the weight's input axis does.
                layer[name + "_gscale"] = spec
                layer[name + "_gmin"] = spec
            else:
                entries = list(spec)
                entries[-2] = None
                layer[name + "_scale"] = P(*entries)

    def kv_cache_specs(self) -> dict:
        # [L, pages, kv_heads, page_size, head_dim]: pages shard on the
        # token-parallel axis (each rank's page-pool partition is its
        # shard; no-op when the axis is 1) and kv heads on the TP axis
        # (head-major page layout; see ops/attention.write_kv_pages).
        return {
            "k": P(None, TOKEN_AXIS, MODEL_AXIS, None, None),
            "v": P(None, TOKEN_AXIS, MODEL_AXIS, None, None),
        }

    def init_params(self, rng: jax.Array, scale: float = 0.02) -> dict:
        """Random (dummy-loader) initialization, HF-shaped."""
        c = self.cfg
        L, H, I = c.num_layers, c.hidden_size, c.intermediate_size
        Dq = c.num_q_heads * c.head_dim
        Dkv = c.num_kv_heads * c.head_dim
        keys = iter(jax.random.split(rng, 12))

        def norm(key, shape):
            return (scale * jax.random.normal(key, shape,
                                              jnp.float32)).astype(c.dtype)

        layers = {
            "input_ln": jnp.ones((L, H), c.dtype),
            "wq": norm(next(keys), (L, H, Dq)),
            "wk": norm(next(keys), (L, H, Dkv)),
            "wv": norm(next(keys), (L, H, Dkv)),
            "wo": norm(next(keys), (L, Dq, H)),
            "post_ln": jnp.ones((L, H), c.dtype),
        }
        if c.mlp_gated:
            layers.update({
                "gate": norm(next(keys), (L, H, I)),
                "up": norm(next(keys), (L, H, I)),
                "down": norm(next(keys), (L, I, H)),
            })
            if c.mlp_bias:
                layers.update({
                    "gate_bias": jnp.zeros((L, I), c.dtype),
                    "up_bias": jnp.zeros((L, I), c.dtype),
                    "down_bias": jnp.zeros((L, H), c.dtype),
                })
        else:
            layers.update({
                "fc1": norm(next(keys), (L, H, I)),
                "fc2": norm(next(keys), (L, I, H)),
            })
            if c.mlp_bias:
                layers.update({"fc1_b": jnp.zeros((L, I), c.dtype),
                               "fc2_b": jnp.zeros((L, H), c.dtype)})
        if c.norm_bias:
            layers.update({"input_ln_b": jnp.zeros((L, H), c.dtype),
                           "post_ln_b": jnp.zeros((L, H), c.dtype)})
        if c.attention_out_bias:
            layers["bo"] = jnp.zeros((L, H), c.dtype)
        if c.parallel_block and c.shared_block_ln:
            layers.pop("post_ln")
            layers.pop("post_ln_b", None)
        if not c.pre_norm:
            for k in ("input_ln", "post_ln", "input_ln_b", "post_ln_b"):
                layers.pop(k, None)
        if c.attention_bias:
            layers.update({
                "bq": jnp.zeros((L, Dq), c.dtype),
                "bk": jnp.zeros((L, Dkv), c.dtype),
                "bv": jnp.zeros((L, Dkv), c.dtype),
            })
        if c.qk_norm:
            layers.update({
                "q_norm": jnp.ones((L, c.head_dim), c.dtype),
                "k_norm": jnp.ones((L, c.head_dim), c.dtype),
            })
            if c.qk_norm_bias:
                layers.update({
                    "q_norm_b": jnp.zeros((L, c.head_dim), c.dtype),
                    "k_norm_b": jnp.zeros((L, c.head_dim), c.dtype),
                })
        if c.qk_norm_full:
            layers.update({
                "q_norm": jnp.ones((L, Dq), c.dtype),
                "k_norm": jnp.ones((L, Dkv), c.dtype),
            })
        if c.extra_layer_norms:
            layers.update({
                "post_attn_ln": jnp.ones((L, H), c.dtype),
                "post_ffw_ln": jnp.ones((L, H), c.dtype),
            })
        self._maybe_replicate_kv(layers)
        self._maybe_fuse_qkv(layers)
        self._install_lora_buffers(layers)
        embed = norm(next(keys), (c.vocab_size, H))
        out = {
            "embed": embed,
            "layers": layers,
            "final_ln": jnp.ones((H, ), c.dtype),
            "lm_head": (embed.T if c.tie_word_embeddings else norm(
                next(keys), (H, c.vocab_size))),
        }
        if c.pos_embedding == "learned":
            out["embed_pos"] = norm(next(keys),
                                    (c.max_position_embeddings, H))
        if c.embed_ln:
            out["embed_ln_w"] = jnp.ones((H, ), c.dtype)
            if c.norm_bias:
                out["embed_ln_b"] = jnp.zeros((H, ), c.dtype)
        if self.LM_HEAD_BIAS:
            out["lm_head_b"] = jnp.zeros((c.vocab_size, ), c.dtype)
        if c.norm_bias:
            out["final_ln_b"] = jnp.zeros((H, ), c.dtype)
        return out

    def _maybe_fuse_qkv(self, layers: dict) -> None:
        """Re-lay the QKV projections for the fused decode block: one
        stacked [L, H, Dq + 2*Dkv] concat that ops/pallas_block.py
        streams as a SINGLE weight (no per-projection kernel
        boundaries). Built only when the loader enabled block fusion
        (VDT_BLOCK_FUSION, default off): the canonical wq/wk/wv stay
        for the prefill/mixed per-op path, so fusion trades one extra
        HBM copy of the QKV weights for the fused decode stream."""
        if not getattr(self.cfg, "block_fusion", False):
            return
        if (self.cfg.attention_bias or self.cfg.attention_out_bias
                or self.cfg.mlp_bias):
            # Checkpoint auto-detection (undeclared qkv biases, Qwen2
            # style) can flip bias flags AFTER the loader's eligibility
            # decision; the fused kernel carries no biases, so revoke
            # fusion rather than silently dropping them.
            from vllm_distributed_tpu.logger import init_logger
            init_logger(__name__).warning(
                "block fusion revoked: checkpoint carries projection "
                "biases the fused kernel does not; decode waves keep "
                "the per-op mega-kernel path")
            self.cfg.block_fusion = False
            return
        layers["wqkv"] = jnp.concatenate(
            [layers["wq"], layers["wk"], layers["wv"]], axis=-1)

    def _maybe_replicate_kv(self, layers: dict) -> None:
        """Expand K/V projection weights in place when KV heads are
        replicated for tp > num_kv_heads."""
        c = self.cfg
        if c.num_kv_head_replicas == 1:
            return
        names = ["wk", "wv", "bk", "bv"]
        if c.qk_norm_full:
            # Olmo2's full-row k norm is per-lane; widen with the heads.
            names.append("k_norm")
        for name in names:
            if name in layers:
                layers[name] = _replicate_kv_heads(
                    layers[name], c.num_kv_heads, c.num_kv_head_replicas)

    def cache_dtype(self):
        """KV-page dtype: the model dtype, or fp8 under
        --kv-cache-dtype fp8 (reference: the kv_cache_dtype flag and
        csrc fp8 cache kernels; values dequantize at the attention
        read, scale 1.0 like the reference default)."""
        return getattr(self.cfg, "kv_cache_dtype", None) or self.cfg.dtype

    def kv_cache_page_bytes(self, page_size: int) -> int:
        """HBM bytes one page costs across all layers (the worker sizes
        the pool from this; models with non-K/V cache layouts override)."""
        from vllm_distributed_tpu.ops.attention import storage_head_dim
        c = self.cfg
        return (2 * c.num_layers * page_size * c.total_kv_heads *
                storage_head_dim(c.head_dim) *
                jnp.dtype(self.cache_dtype()).itemsize)

    def slice_layer_params(self, layers: dict, start: int,
                           end: int) -> dict:
        """A pipeline stage's slice of the stacked per-layer params;
        models whose stacks have per-kind depths override (deepseek)."""
        return {k: v[start:end] for k, v in layers.items()}

    def make_kv_caches(self, num_pages: int, page_size: int,
                       cache_dtype=None,
                       num_layers: Optional[int] = None) -> dict:
        """Stacked [L, ...] cache; ``num_layers`` overrides the depth for
        a pipeline stage's local slice."""
        from vllm_distributed_tpu.ops.attention import storage_head_dim
        c = self.cfg
        depth = num_layers if num_layers is not None else c.num_layers
        shape = (depth, num_pages, c.total_kv_heads,
                 page_size, storage_head_dim(c.head_dim))
        dtype = cache_dtype or self.cache_dtype()
        return {
            "k": jnp.zeros(shape, dtype),
            "v": jnp.zeros(shape, dtype),
        }

    # ------------------------------------------------------------------
    # Weight loading from an HF checkpoint state dict
    # ------------------------------------------------------------------
    def params_from_hf_state_dict(self, tensors: dict[str, np.ndarray],
                                  ) -> dict:
        """Map HF LlamaForCausalLM tensor names to the stacked tree.

        ``tensors`` maps HF names to numpy arrays (loaded by the
        model_loader from safetensors shards). Torch Linear stores
        [out, in]; we transpose to right-multiply layout.
        """
        c = self.cfg
        L = c.num_layers
        # Auto-detect bias tensors the config did not declare (Qwen2
        # hardcodes qkv biases with no attention_bias attr; dropping
        # them silently would mis-serve real checkpoints). cfg flags
        # are trace-time statics, so flipping them before param_specs
        # keeps specs/forward consistent.
        if (not c.attention_bias
                and "model.layers.0.self_attn.q_proj.bias" in tensors):
            c.attention_bias = True
        if (not c.attention_out_bias
                and "model.layers.0.self_attn.o_proj.bias" in tensors):
            c.attention_out_bias = True
        if (not c.mlp_bias and c.mlp_gated
                and "model.layers.0.mlp.gate_proj.bias" in tensors):
            c.mlp_bias = True

        def t(name):
            return np.asarray(tensors[name])

        def stack(fmt, transpose=True):
            mats = [t(fmt.format(i)) for i in range(L)]
            arr = np.stack([m.T if transpose else m for m in mats])
            return jnp.asarray(arr, dtype=c.dtype)

        layers = {
            "wq": stack("model.layers.{}.self_attn.q_proj.weight"),
            "wk": stack("model.layers.{}.self_attn.k_proj.weight"),
            "wv": stack("model.layers.{}.self_attn.v_proj.weight"),
            "wo": stack("model.layers.{}.self_attn.o_proj.weight"),
        }
        if c.pre_norm:
            layers["input_ln"] = stack(
                "model.layers.{}.input_layernorm.weight",
                transpose=False)
            if not (c.parallel_block and c.shared_block_ln):
                layers["post_ln"] = stack(
                    "model.layers.{}.post_attention_layernorm.weight",
                    transpose=False)
            if c.norm_bias:
                layers["input_ln_b"] = stack(
                    "model.layers.{}.input_layernorm.bias",
                    transpose=False)
                if "post_ln" in layers:
                    layers["post_ln_b"] = stack(
                        "model.layers.{}.post_attention_layernorm.bias",
                        transpose=False)
        if c.mlp_gated:
            layers.update({
                "gate": stack("model.layers.{}.mlp.gate_proj.weight"),
                "up": stack("model.layers.{}.mlp.up_proj.weight"),
                "down": stack("model.layers.{}.mlp.down_proj.weight"),
            })
            if c.mlp_bias:
                layers.update({
                    "gate_bias": stack(
                        "model.layers.{}.mlp.gate_proj.bias", False),
                    "up_bias": stack(
                        "model.layers.{}.mlp.up_proj.bias", False),
                    "down_bias": stack(
                        "model.layers.{}.mlp.down_proj.bias", False),
                })
        else:
            # Canonical plain-MLP names; family subclasses rename their
            # checkpoint tensors (dense_h_to_4h, c_fc, ...) onto these.
            layers.update({
                "fc1": stack("model.layers.{}.mlp.fc1.weight"),
                "fc2": stack("model.layers.{}.mlp.fc2.weight"),
            })
            if c.mlp_bias:
                layers.update({
                    "fc1_b": stack("model.layers.{}.mlp.fc1.bias",
                                   transpose=False),
                    "fc2_b": stack("model.layers.{}.mlp.fc2.bias",
                                   transpose=False),
                })
        if c.attention_out_bias:
            layers["bo"] = stack(
                "model.layers.{}.self_attn.o_proj.bias",
                transpose=False)
        if c.attention_bias:
            layers.update({
                "bq": stack("model.layers.{}.self_attn.q_proj.bias",
                            transpose=False),
                "bk": stack("model.layers.{}.self_attn.k_proj.bias",
                            transpose=False),
                "bv": stack("model.layers.{}.self_attn.v_proj.bias",
                            transpose=False),
            })
        if c.qk_norm or c.qk_norm_full:
            layers.update({
                "q_norm": stack("model.layers.{}.self_attn.q_norm.weight",
                                transpose=False),
                "k_norm": stack("model.layers.{}.self_attn.k_norm.weight",
                                transpose=False),
            })
            if c.qk_norm_bias:
                layers.update({
                    "q_norm_b": stack(
                        "model.layers.{}.self_attn.q_norm.bias",
                        transpose=False),
                    "k_norm_b": stack(
                        "model.layers.{}.self_attn.k_norm.bias",
                        transpose=False),
                })
        if c.extra_layer_norms:
            # Gemma2's 4-norm block renames the roles: HF
            # post_attention_layernorm norms the attention OUTPUT (our
            # post_attn_ln) and pre_feedforward_layernorm is the
            # pre-MLP norm (our post_ln). Post-norm blocks (Olmo2,
            # pre_norm=False) have only the two output norms.
            layers.update({
                "post_attn_ln": stack(
                    "model.layers.{}.post_attention_layernorm.weight",
                    transpose=False),
                "post_ffw_ln": stack(
                    "model.layers.{}.post_feedforward_layernorm.weight",
                    transpose=False),
            })
            if c.pre_norm:
                layers["post_ln"] = stack(
                    "model.layers.{}.pre_feedforward_layernorm.weight",
                    transpose=False)
        self._maybe_replicate_kv(layers)
        self._maybe_fuse_qkv(layers)
        embed = jnp.asarray(t("model.embed_tokens.weight"), dtype=c.dtype)
        if c.tie_word_embeddings or "lm_head.weight" not in tensors:
            lm_head = embed.T
        else:
            lm_head = jnp.asarray(t("lm_head.weight").T, dtype=c.dtype)
        self._install_lora_buffers(layers)
        out = {
            "embed": embed,
            "layers": layers,
            "final_ln": jnp.asarray(t("model.norm.weight"), dtype=c.dtype),
            "lm_head": lm_head,
        }
        if c.pos_embedding == "learned":
            # Families rename their table to this canonical name.
            out["embed_pos"] = jnp.asarray(
                t("model.embed_positions.weight"), dtype=c.dtype)
        if c.embed_ln:
            out["embed_ln_w"] = jnp.asarray(
                t("model.embed_layernorm.weight"), dtype=c.dtype)
            if c.norm_bias:
                out["embed_ln_b"] = jnp.asarray(
                    t("model.embed_layernorm.bias"), dtype=c.dtype)
        if self.LM_HEAD_BIAS:
            out["lm_head_b"] = jnp.asarray(
                np.asarray(tensors.get(
                    "lm_head.bias",
                    np.zeros((c.vocab_size, ), np.float32))),
                dtype=c.dtype)
        if c.norm_bias and "model.norm.bias" in tensors:
            out["final_ln_b"] = jnp.asarray(t("model.norm.bias"),
                                            dtype=c.dtype)
        return out

    # ------------------------------------------------------------------
    # Forward
    # ------------------------------------------------------------------
    def _act(self, x: jax.Array) -> jax.Array:
        act = self.cfg.hidden_act
        if act in ("gelu_tanh", "gelu_new", "gelu_pytorch_tanh"):
            return jax.nn.gelu(x, approximate=True)
        if act == "gelu":
            return jax.nn.gelu(x, approximate=False)
        if act == "relu2":
            r = jax.nn.relu(x)
            return r * r
        if act == "relu":
            return jax.nn.relu(x)
        if act == "quick_gelu":
            return x * jax.nn.sigmoid(1.702 * x)
        if act in ("silu", "swish", None):
            return jax.nn.silu(x)
        raise ValueError(
            f"unsupported hidden_act {act!r} (add it to _act rather "
            "than silently running the wrong activation)")

    def _norm(self, x: jax.Array, w: jax.Array,
              b: Optional[jax.Array] = None) -> jax.Array:
        """RMSNorm or mean-centering LayerNorm per cfg.norm_type."""
        c = self.cfg
        if c.norm_type == "rms":
            return rms_norm(x, w, c.rms_norm_eps)
        x32 = x.astype(jnp.float32)
        mu = x32.mean(axis=-1, keepdims=True)
        var = ((x32 - mu) ** 2).mean(axis=-1, keepdims=True)
        out = (x32 - mu) * jax.lax.rsqrt(var + c.rms_norm_eps)
        out = out * w.astype(jnp.float32)
        if b is not None:
            out = out + b.astype(jnp.float32)
        return out.astype(x.dtype)

    def mlp_block(self, lp: dict, x: jax.Array,
                  lora_ctx=None) -> jax.Array:
        """Per-layer feed-forward; MoE models override this (the MLP is
        the only structural difference in the decoder block)."""
        c = self.cfg
        if not c.mlp_gated:
            h = self._mm(lp, "fc1", x)
            if c.mlp_bias:
                h = h + lp["fc1_b"]
            h = self._mm(lp, "fc2", self._act(h))
            if c.mlp_bias:
                h = h + lp["fc2_b"]
            return h
        gb = lp["gate_bias"] if c.mlp_bias else 0
        ub = lp["up_bias"] if c.mlp_bias else 0
        db = lp["down_bias"] if c.mlp_bias else 0
        if lora_ctx is None or ("gate_a") not in lp:
            g = self._act(self._mm(lp, "gate", x) + gb)
            return self._mm(lp, "down",
                            g * (self._mm(lp, "up", x) + ub)) + db
        g = self._act(self._mm(lp, "gate", x) + gb +
                      self._lora_delta(lp, "gate", x, lora_ctx))
        u = (self._mm(lp, "up", x) + ub +
             self._lora_delta(lp, "up", x, lora_ctx))
        gu = g * u
        return (self._mm(lp, "down", gu) + db +
                self._lora_delta(lp, "down", gu, lora_ctx))

    def embed(self, params: dict, token_ids: jax.Array,
              positions: jax.Array = None) -> jax.Array:
        """Token embedding (pipeline stage 0 front; reference: the
        VocabParallelEmbedding layer; learned-position families add
        their absolute table here like GPT2Model.wpe)."""
        h = params["embed"][token_ids]
        if self.cfg.embed_scale != 1.0:
            # Gemma normalizer semantics: the scale is cast to the
            # activation dtype before multiplying (HF parity).
            h = h * jnp.asarray(self.cfg.embed_scale, h.dtype)
        if self.cfg.pos_embedding == "learned":
            assert positions is not None, \
                "learned-position models need positions at embed time"
            idx = jnp.clip(positions + self.cfg.pos_offset, 0,
                           self.cfg.max_position_embeddings - 1)
            h = h + params["embed_pos"][idx]
        if self.cfg.embed_ln:
            h = self._norm(h, params["embed_ln_w"],
                           params.get("embed_ln_b"))
        return h

    @staticmethod
    def _plan_window_segments(windows: tuple) -> list:
        """Split a per-layer window tuple into scan segments.

        Returns [(start, count, pattern)]: layers [start, start+count)
        repeat ``pattern``. A short repeating period (Gemma2 alternates
        sliding/full -> period 2) becomes ONE lax.scan whose step
        unrolls the period with a static window each; otherwise runs of
        constant window (Qwen2's first-N-full layouts -> 2 runs) each
        get their own scan. Every attention mask stays STATIC per scan
        step — the XLA-friendly alternative to a traced window bound.

        Odd-length slices of a periodic layout (a Gemma2 PP stage with
        21 of 42 layers) keep the periodic bulk and peel only the
        remainder — two scans, not a per-layer unroll."""
        n = len(windows)
        for period in range(1, min(8, n) + 1):
            bulk = period * (n // period)
            # Require >= 2 repetitions: any period trivially "matches" a
            # bulk of itself, which would mis-plan run layouts.
            if n // period >= 2 and all(windows[i] == windows[i % period]
                                        for i in range(bulk)):
                segments = [(0, bulk, tuple(windows[:period]))]
                if bulk < n:
                    segments.append(
                        (bulk, n - bulk, tuple(windows[bulk:])))
                return segments
        segments = []
        i = 0
        while i < n:
            j = i
            while j < n and windows[j] == windows[i]:
                j += 1
            segments.append((i, j - i, (windows[i], )))
            i = j
        return segments

    def _layer_windows(self, first_layer: int, num_layers: int) -> tuple:
        """Static window per layer for a [first_layer, +num_layers)
        slice of the model."""
        c = self.cfg
        if c.window_pattern is not None:
            return tuple(
                c.window_pattern[first_layer:first_layer + num_layers])
        return (c.sliding_window or 0, ) * num_layers

    def run_layers(
        self,
        layer_params: dict,
        kv_caches: dict,
        hidden: jax.Array,  # [T, H]
        batch: AttentionBatch,
        first_layer: int = 0,
        cache_layer_offset: int = 0,
    ) -> tuple[jax.Array, dict]:
        """Run a contiguous slice of decoder layers over the hidden
        states. ``layer_params`` is a stacked [Ls, ...] subtree and
        ``kv_caches`` that slice's own [Ls, ...] cache — under pipeline
        parallelism each stage calls this with its local slice
        (reference: the per-stage module list built by get_pp_indices,
        distributed/utils.py:89). ``first_layer`` is the slice's global
        offset, selecting the right rows of mixed window layouts
        (static — PP keys its stage jit on it for patterned models).
        ``cache_layer_offset`` shifts KV reads/writes into deeper rows
        of a taller stacked cache — the EAGLE drafter's layers append
        to the target's cache stack and index past its depth."""
        c = self.cfg
        T = hidden.shape[0]
        if c.sm_scale_override is not None:
            sm_scale = c.sm_scale_override
        else:
            sm_scale = (c.query_pre_attn_scalar or c.head_dim) ** -0.5
        num_layers = jax.tree_util.tree_leaves(layer_params)[0].shape[0]
        rd = c.rotary_dim or c.head_dim
        if c.pos_embedding != "rope":
            cos = sin = cos_l = sin_l = None
        elif (c.mrope_section is not None
              and getattr(batch, "mrope_positions", None) is not None):
            from vllm_distributed_tpu.models.common import \
                compute_mrope_cos_sin
            cos, sin = compute_mrope_cos_sin(
                batch.mrope_positions, rd, c.rope_theta,
                tuple(c.mrope_section))
        elif c.rope_interleaved:
            from vllm_distributed_tpu.models.common import \
                compute_rope_cos_sin_pairwise
            cos, sin = compute_rope_cos_sin_pairwise(
                batch.positions, rd, c.rope_theta, c.rope_scaling)
        else:
            cos, sin = compute_rope_cos_sin(batch.positions, rd,
                                            c.rope_theta, c.rope_scaling,
                                            dtype=jnp.float32)
        if c.rope_theta_local is not None:
            # Gemma3: sliding layers rope with the LOCAL base and no
            # scaling; full layers keep the global table above.
            cos_l, sin_l = compute_rope_cos_sin(
                batch.positions, rd, c.rope_theta_local, None,
                dtype=jnp.float32)
        else:
            cos_l, sin_l = cos, sin

        has_bias = c.attention_bias
        if c.alibi:
            from vllm_distributed_tpu.models.common import alibi_slopes
            slopes = alibi_slopes(c.num_q_heads)
        else:
            slopes = None

        # The stacked caches thread through the layer scan as CARRIES and
        # every cache op indexes [layer, ...] internally: slicing the
        # cache per layer (scan xs/ys) would copy the entire cache through
        # HBM every step — the Pallas write kernel updates pages in place
        # via input/output aliasing instead (reference analogue:
        # v1/attention/backends/pallas.py:282 aliased kv_cache_update).
        lora_ctx = batch.lora

        # Sequence parallelism (reference: the sequence_parallelism
        # compile pass rewriting allreduce -> reduce_scatter +
        # all_gather): pin the residual stream token-sharded on the
        # model axis at block boundaries; GSPMD then scatters the
        # row-parallel matmul reductions and gathers before the next
        # column-parallel one, and norms/adds run on T/tp tokens. The
        # sharding binds to the registered engine mesh so the constraint
        # works under jit without an ambient mesh context.
        # The token dim shards over data x model jointly so mesh-mode DP
        # keeps its batch split (equivalent to model-only when the data
        # axis is 1, i.e. the serving engine path).
        sp_spec = P(("data", MODEL_AXIS), None)
        sp_sharding = None
        if c.sequence_parallel:
            from jax.sharding import NamedSharding

            from vllm_distributed_tpu.parallel import mesh as mesh_state
            if mesh_state.has_global_mesh():
                sp_sharding = NamedSharding(
                    mesh_state.get_global_mesh(), sp_spec)

        def sp(h):
            if not c.sequence_parallel:
                return h
            return jax.lax.with_sharding_constraint(
                h, sp_sharding if sp_sharding is not None else sp_spec)

        def apply_rotary(x, local=False):
            """Rope on the first ``rd`` lanes (fp32; partial rotary
            passes the tail through — GPT-NeoX rotary_pct semantics);
            ``local`` picks the sliding-layer table (Gemma3). Learned-
            position families skip rotation entirely."""
            if c.pos_embedding != "rope":
                return x
            from vllm_distributed_tpu.models.common import (
                apply_rope_pairwise, apply_rope_single)
            cs, sn = (cos_l, sin_l) if local else (cos, sin)
            x32 = x.astype(jnp.float32)
            rot = x32[..., :rd]
            rot = (apply_rope_pairwise(rot, cs, sn)
                   if c.rope_interleaved else
                   apply_rope_single(rot, cs, sn))
            if rd == c.head_dim:
                return rot.astype(c.dtype)
            return jnp.concatenate([rot, x32[..., rd:]],
                                   axis=-1).astype(c.dtype)

        rm = c.residual_multiplier

        # Fused transformer-block decode (ops/pallas_block.py): the
        # runner flags decode-only waves of an eligible model (see the
        # loader's block-fusion decision) and each layer collapses to
        # ONE Pallas call — RMSNorm -> fused QKV -> rope + KV write +
        # attention -> O-proj -> RMSNorm -> gated MLP, activations in
        # VMEM throughout. Eligibility guarantees the standard dense
        # pre-norm gated block (no biases/qk-norms/LoRA/quant/MoE), full
        # head-dim rope and TP=1, so the per-op features below are
        # structurally absent; window/softcap/ALiBi/sinks ride kernel
        # statics + the head-feature sidecar like the mega-kernel.
        use_fused = bool(getattr(batch, "block_fused", False)
                         and c.block_fusion)
        if use_fused:
            from vllm_distributed_tpu.ops.pallas_block import \
                fused_block_decode_pallas
            rope_tab = jnp.stack([cos, sin])

        def fused_body(h, k_all, v_all, lp, layer_idx, window):
            from vllm_distributed_tpu.ops.attention import build_head_feat
            ln_w = jnp.stack([lp["input_ln"], lp["post_ln"]])
            feat = build_head_feat(
                c.num_q_heads, slopes,
                lp["sinks"] if c.attn_sinks else None)
            return fused_block_decode_pallas(
                h, k_all, v_all, lp["wqkv"], lp["wo"], lp["gate"],
                lp["up"], lp["down"], ln_w, rope_tab, feat,
                batch.seq_info, batch.num_seqs, batch.block_tables,
                layer_idx, sm_scale=sm_scale, eps=c.rms_norm_eps,
                num_q_heads=c.num_q_heads, head_dim=c.head_dim,
                window=window, logit_cap=c.attn_logit_softcap,
                has_alibi=c.alibi, has_sinks=c.attn_sinks)

        def layer_body(h, k_all, v_all, lp, layer_idx, window,
                       nope=False):
            if use_fused:
                return fused_body(h, k_all, v_all, lp, layer_idx,
                                  window)
            if c.pre_norm:
                x = self._norm(h, lp["input_ln"], lp.get("input_ln_b"))
            else:
                x = h  # Olmo2 post-norm block: sub-layers see raw h
            q = self._mm(lp, "wq", x) + self._lora_delta(lp, "wq", x,
                                                         lora_ctx)
            k = self._mm(lp, "wk", x) + self._lora_delta(lp, "wk", x,
                                                         lora_ctx)
            v = self._mm(lp, "wv", x) + self._lora_delta(lp, "wv", x,
                                                         lora_ctx)
            if has_bias:
                q = q + lp["bq"]
                k = k + lp["bk"]
                v = v + lp["bv"]
            if c.qkv_clip is not None:
                q = jnp.clip(q, -c.qkv_clip, c.qkv_clip)
                k = jnp.clip(k, -c.qkv_clip, c.qkv_clip)
                v = jnp.clip(v, -c.qkv_clip, c.qkv_clip)
            if c.qk_norm_full:
                # Olmo2: RMSNorm over the whole projection row, before
                # the head reshape.
                q = rms_norm(q, lp["q_norm"], c.rms_norm_eps)
                k = rms_norm(k, lp["k_norm"], c.rms_norm_eps)
            q = q.reshape(T, c.num_q_heads, c.head_dim)
            k = k.reshape(T, c.total_kv_heads, c.head_dim)
            if c.qk_norm:
                # Per-head norm ahead of RoPE (Qwen3 RMS; Persimmon
                # LayerNorm+bias via norm_type/qk_norm_bias).
                q = self._norm(q, lp["q_norm"], lp.get("q_norm_b"))
                k = self._norm(k, lp["k_norm"], lp.get("k_norm_b"))
            v = v.reshape(T, c.total_kv_heads, c.head_dim)
            local_rope = bool(window) and c.rope_theta_local is not None
            if not nope:
                q = apply_rotary(q, local=local_rope)
                k = apply_rotary(k, local=local_rope)
            # One fused Pallas pass writes the step's K/V pages and
            # attends in the same kernel call where the layout permits
            # (mega-kernel descriptor batches); otherwise this is the
            # classic write-then-attend pair.
            k_all, v_all, attn = write_kv_and_attend(
                q, k_all, v_all, k, v, batch, sm_scale=sm_scale,
                layer=layer_idx, window=window,
                logit_cap=c.attn_logit_softcap, alibi_slopes=slopes,
                sinks=(lp["sinks"] if c.attn_sinks else None))
            attn2d = attn.reshape(T, -1)
            attn_out = (self._mm(lp, "wo", attn2d) +
                        self._lora_delta(lp, "wo", attn2d, lora_ctx))
            if c.attention_out_bias:
                attn_out = attn_out + lp["bo"]
            if "post_attn_ln" in lp:
                # Sandwich/post norm on the attention output (Gemma2,
                # Olmo2).
                attn_out = self._norm(attn_out, lp["post_attn_ln"],
                                      lp.get("post_attn_ln_b"))
            if c.parallel_block:
                # GPT-NeoX/Phi/Cohere: both sub-blocks read the same
                # input state; one residual add.
                x2 = (x if c.shared_block_ln else
                      self._norm(h, lp["post_ln"], lp.get("post_ln_b")))
                mlp_out = self.mlp_block(lp, x2, lora_ctx)
                h = sp(h + rm * (attn_out + mlp_out))
                return h, k_all, v_all
            h = sp(h + rm * attn_out)
            x2 = (self._norm(h, lp["post_ln"], lp.get("post_ln_b"))
                  if c.pre_norm else h)
            mlp_out = self.mlp_block(lp, x2, lora_ctx)
            if "post_ffw_ln" in lp:
                mlp_out = self._norm(mlp_out, lp["post_ffw_ln"],
                                     lp.get("post_ffw_ln_b"))
            h = sp(h + rm * mlp_out)
            return h, k_all, v_all

        windows = self._layer_windows(first_layer, num_layers)
        # Per-layer static attributes segment TOGETHER: the scan
        # pattern keys on (window, nope) pairs so a NoPE/rope layout
        # (SmolLM3, Cohere2, EXAONE-4 hybrids) plans like a window
        # layout.
        if c.nope_layers is not None:
            nope = tuple(bool(c.nope_layers[first_layer + i])
                         for i in range(num_layers))
        else:
            nope = (False, ) * num_layers
        layer_keys = tuple(zip(windows, nope))
        segments = self._plan_window_segments(layer_keys)
        layer_ids = (jnp.arange(num_layers, dtype=jnp.int32)[:, None]
                     + cache_layer_offset)
        carry = (sp(hidden), kv_caches["k"], kv_caches["v"])
        for start, count, pattern in segments:
            if len(segments) == 1:
                lp_seg, ids_seg = layer_params, layer_ids
            else:
                lp_seg = jax.tree.map(lambda a: a[start:start + count],
                                      layer_params)
                ids_seg = layer_ids[start:start + count]
            period = len(pattern)
            steps = count // period
            lp_seg = jax.tree.map(
                lambda a: a.reshape(steps, period, *a.shape[1:]), lp_seg)
            ids_seg = ids_seg.reshape(steps, period, 1)

            def scan_fn(car, xs, pattern=pattern):
                h, k_all, v_all = car
                lp_grp, id_grp = xs
                for j, (w, np_) in enumerate(pattern):
                    lp_j = jax.tree.map(lambda a: a[j], lp_grp)
                    h, k_all, v_all = layer_body(h, k_all, v_all, lp_j,
                                                 id_grp[j], w,
                                                 nope=np_)
                return (h, k_all, v_all), None

            carry, _ = jax.lax.scan(scan_fn, carry, (lp_seg, ids_seg))
        hidden, k_all, v_all = carry
        return hidden, {"k": k_all, "v": v_all}

    def forward(
        self,
        params: dict,
        kv_caches: dict,
        token_ids: jax.Array,  # [T] int32
        batch: AttentionBatch,
    ) -> tuple[jax.Array, dict]:
        """Run the decoder over a flat ragged token batch; returns final
        hidden states [T, H] and the updated KV caches."""
        hidden = self.embed(params, token_ids, batch.positions)
        if getattr(batch, "mm_embeds", None) is not None:
            # Image placeholder positions take their pre-computed
            # encoder rows (reference: the inputs_embeds merge of
            # llava-style models, get_input_embeddings + masked_scatter
            # in vllm/model_executor/models/llava.py). The override
            # rows arrive post-projector, so no embed scaling applies.
            hidden = jnp.where(batch.mm_mask[:, None],
                               batch.mm_embeds.astype(hidden.dtype),
                               hidden)
        dense = params.get("layers_dense")
        if dense is not None:
            # Mixed layouts (Ernie-4.5-MoE / GLM-4-MoE style): a dense
            # PREFIX of plain decoder layers runs first from its own
            # stacked subtree, then the sparse stack continues with its
            # cache rows offset past the prefix.
            k = jax.tree_util.tree_leaves(dense)[0].shape[0]
            hidden, kv_caches = self.run_layers(dense, kv_caches,
                                                hidden, batch)
            return self.run_layers(params["layers"], kv_caches, hidden,
                                   batch, first_layer=k,
                                   cache_layer_offset=k)
        return self.run_layers(params["layers"], kv_caches, hidden, batch)

    def compute_logits(self, params: dict,
                       hidden: jax.Array) -> jax.Array:
        """Final norm + LM head on selected rows; fp32 logits."""
        if self.cfg.final_norm:
            x = self._norm(hidden, params["final_ln"],
                           params.get("final_ln_b"))
        else:
            x = hidden
        logits = jnp.dot(x, params["lm_head"],
                         preferred_element_type=jnp.float32)
        if "lm_head_b" in params:
            logits = logits + params["lm_head_b"].astype(jnp.float32)
        if self.cfg.logit_multiplier != 1.0:
            # Cohere logit_scale / Granite 1/logits_scaling.
            logits = logits * self.cfg.logit_multiplier
        cap = self.cfg.final_logit_softcap
        if cap:
            # Gemma2 final soft-capping (monotone: greedy order kept,
            # logprobs match HF).
            logits = cap * jnp.tanh(logits / cap)
        return logits
