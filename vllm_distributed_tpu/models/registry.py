"""Architecture registry (reference: vllm/model_executor/models/registry.py:32
maps HF ``architectures`` strings to model classes; ~180 entries there).

The Llama-family functional model covers every config that is structurally
a pre-norm RoPE decoder with SwiGLU MLP and optional QKV bias — which is
Llama 2/3, Mistral, Qwen2, and friends.
"""

from vllm_distributed_tpu.models.families import (BaichuanForCausalLM,
                                                  Gemma2ForCausalLM,
                                                  Gemma3ForCausalLM,
                                                  GemmaForCausalLM,
                                                  InternLM2ForCausalLM,
                                                  Phi3ForCausalLM,
                                                  Qwen3ForCausalLM)
from vllm_distributed_tpu.models.deepseek import (DeepseekV2ForCausalLM,
                                                  DeepseekV3ForCausalLM)
from vllm_distributed_tpu.models.llama import (LlamaArchConfig,
                                               LlamaForCausalLM)
from vllm_distributed_tpu.models.families_ext import (Cohere2ForCausalLM,
                                                      FlexOlmoForCausalLM,
                                                      GraniteMoeSharedForCausalLM,
                                                      HunYuanDenseV1ForCausalLM,
                                                      VaultGemmaForCausalLM,
                                                      CohereForCausalLM,
                                                      DbrxForCausalLM,
                                                      Exaone4ForCausalLM,
                                                      SmolLM3ForCausalLM,
                                                      FalconForCausalLM,
                                                      Glm4ForCausalLM,
                                                      GlmForCausalLM,
                                                      GptOssForCausalLM,
                                                      GraniteMoeForCausalLM,
                                                      OlmoeForCausalLM,
                                                      OlmoForCausalLM,
                                                      GPTNeoXForCausalLM,
                                                      GraniteForCausalLM,
                                                      NemotronForCausalLM,
                                                      Olmo2ForCausalLM,
                                                      Olmo3ForCausalLM,
                                                      PersimmonForCausalLM,
                                                      PhiForCausalLM,
                                                      PhimoeForCausalLM,
                                                      Qwen3MoeForCausalLM,
                                                      StableLmForCausalLM,
                                                      Starcoder2ForCausalLM)
from vllm_distributed_tpu.models.families_gpt import (ArceeForCausalLM,
                                                      BioGptForCausalLM,
                                                      XGLMForCausalLM,
                                                      BloomForCausalLM,
                                                      Ernie45ForCausalLM,
                                                      ExaoneForCausalLM,
                                                      SeedOssForCausalLM,
                                                      GPT2LMHeadModel,
                                                      GPTBigCodeForCausalLM,
                                                      GPTJForCausalLM,
                                                      MiniCPMForCausalLM,
                                                      MPTForCausalLM,
                                                      OPTForCausalLM)
from vllm_distributed_tpu.models.bert import (BertEmbeddingModel,
                                              BertForSequenceClassification,
                                              RobertaEmbeddingModel,
                                              RobertaForSequenceClassification)
from vllm_distributed_tpu.models.llava import LlavaForConditionalGeneration
from vllm_distributed_tpu.models.qwen2_vl import \
    Qwen2VLForConditionalGeneration
from vllm_distributed_tpu.models.bart import BartForConditionalGeneration
from vllm_distributed_tpu.models.whisper import \
    WhisperForConditionalGeneration
from vllm_distributed_tpu.models.bamba import BambaForCausalLM
from vllm_distributed_tpu.models.jamba import JambaForCausalLM
from vllm_distributed_tpu.models.mamba import (FalconMambaForCausalLM,
                                               Mamba2ForCausalLM,
                                               MambaForCausalLM)
from vllm_distributed_tpu.models.moe_mixed import (Dots1ForCausalLM,
                                                   Ernie45MoeForCausalLM,
                                                   Glm4MoeForCausalLM)
from vllm_distributed_tpu.models.mixtral import (MixtralForCausalLM,
                                                 Qwen2MoeForCausalLM)

_REGISTRY: dict[str, type] = {
    "LlamaForCausalLM": LlamaForCausalLM,
    "MistralForCausalLM": LlamaForCausalLM,
    # Ministral: llama block + uniform sliding window via layer_types
    # (the generic window resolver covers it).
    "MinistralForCausalLM": LlamaForCausalLM,
    "Qwen2ForCausalLM": LlamaForCausalLM,
    # Llama-weight-compatible forks (identical tensor naming + math).
    "AquilaForCausalLM": LlamaForCausalLM,
    "YiForCausalLM": LlamaForCausalLM,
    "MixtralForCausalLM": MixtralForCausalLM,
    "Qwen2MoeForCausalLM": Qwen2MoeForCausalLM,
    "GemmaForCausalLM": GemmaForCausalLM,
    "Gemma2ForCausalLM": Gemma2ForCausalLM,
    "Gemma3ForCausalLM": Gemma3ForCausalLM,
    "Qwen3ForCausalLM": Qwen3ForCausalLM,
    "Phi3ForCausalLM": Phi3ForCausalLM,
    "InternLM2ForCausalLM": InternLM2ForCausalLM,
    # Both checkpoint spellings; 13B (ALiBi) is rejected at load.
    "BaichuanForCausalLM": BaichuanForCausalLM,
    "BaiChuanForCausalLM": BaichuanForCausalLM,
    # MLA + DeepSeekMoE family (latent KV cache; models/deepseek.py).
    "DeepseekV2ForCausalLM": DeepseekV2ForCausalLM,
    "DeepseekV3ForCausalLM": DeepseekV3ForCausalLM,
    # Image+text (pre-computed projector embeddings; models/llava.py).
    "LlavaForConditionalGeneration": LlavaForConditionalGeneration,
    # Qwen2-VL family: M-RoPE decoder + dynamic-resolution tower with
    # video inputs (models/qwen2_vl.py).
    "Qwen2VLForConditionalGeneration": Qwen2VLForConditionalGeneration,
    # Families on the generic block knobs (models/families_ext.py).
    "GraniteForCausalLM": GraniteForCausalLM,
    "GraniteMoeForCausalLM": GraniteMoeForCausalLM,
    # GraniteMoe + ungated dense shared MLP (families_ext.py).
    "GraniteMoeSharedForCausalLM": GraniteMoeSharedForCausalLM,
    # Tencent HunYuan dense: llama + per-head qk RMSNorm.
    "HunYuanDenseV1ForCausalLM": HunYuanDenseV1ForCausalLM,
    # FlexOlmo: OLMo-2 post-norm block + OLMoE routed experts.
    "FlexOlmoForCausalLM": FlexOlmoForCausalLM,
    # ERNIE-4.5 MoE: dense prefix + bias-selected softmax routing +
    # ungated shared experts (models/moe_mixed.py).
    "Ernie4_5_MoeForCausalLM": Ernie45MoeForCausalLM,
    # GLM-4-MoE: dense prefix + DeepSeek-V3-style sigmoid routing +
    # shared experts on a standard-attention block (moe_mixed.py).
    "Glm4MoeForCausalLM": Glm4MoeForCausalLM,
    # dots.llm1: the GLM-4-MoE recipe + always-on per-head qk norm.
    "Dots1ForCausalLM": Dots1ForCausalLM,
    "DbrxForCausalLM": DbrxForCausalLM,
    # Attention sinks + clamped-GLU MoE (models/families_ext.py).
    "GptOssForCausalLM": GptOssForCausalLM,
    # Sparsemixer routing (models/families_ext.py PhimoeForCausalLM).
    "PhimoeForCausalLM": PhimoeForCausalLM,
    "Qwen3MoeForCausalLM": Qwen3MoeForCausalLM,
    "Starcoder2ForCausalLM": Starcoder2ForCausalLM,
    "StableLmForCausalLM": StableLmForCausalLM,
    "GPTNeoXForCausalLM": GPTNeoXForCausalLM,
    "PhiForCausalLM": PhiForCausalLM,
    "CohereForCausalLM": CohereForCausalLM,
    # Cohere2 / Command-R7B: sliding/full interleave, full layers NoPE.
    "Cohere2ForCausalLM": Cohere2ForCausalLM,
    # SmolLM3: llama block, every fourth layer NoPE.
    "SmolLM3ForCausalLM": SmolLM3ForCausalLM,
    # EXAONE-4: post-norm + per-head qk norm + hybrid global-NoPE.
    "Exaone4ForCausalLM": Exaone4ForCausalLM,
    # VaultGemma: Gemma block + softcaps/query scaling, no sandwich
    # norms (families_ext.py).
    "VaultGemmaForCausalLM": VaultGemmaForCausalLM,
    "Olmo2ForCausalLM": Olmo2ForCausalLM,
    "NemotronForCausalLM": NemotronForCausalLM,
    "OlmoForCausalLM": OlmoForCausalLM,
    "OlmoeForCausalLM": OlmoeForCausalLM,
    "GlmForCausalLM": GlmForCausalLM,
    "Glm4ForCausalLM": Glm4ForCausalLM,
    # OLMo-3: OLMo-2 post-norm block + windows + rope scaling only on
    # full-attention layers (models/families_ext.py).
    "Olmo3ForCausalLM": Olmo3ForCausalLM,
    "FalconForCausalLM": FalconForCausalLM,
    "PersimmonForCausalLM": PersimmonForCausalLM,
    # Selective state-space family (segmented-scan SSM; models/mamba.py).
    "MambaForCausalLM": MambaForCausalLM,
    "Mamba2ForCausalLM": Mamba2ForCausalLM,
    "FalconMambaForCausalLM": FalconMambaForCausalLM,
    # Hybrid attention/mamba/MoE (hybrid cache groups; models/jamba.py).
    "JambaForCausalLM": JambaForCausalLM,
    # Hybrid Mamba-2/attention (models/bamba.py).
    "BambaForCausalLM": BambaForCausalLM,
    # GPT lineage: learned positions / parallel blocks / packed QKV
    # (models/families_gpt.py).
    "GPT2LMHeadModel": GPT2LMHeadModel,
    "GPTJForCausalLM": GPTJForCausalLM,
    "GPTBigCodeForCausalLM": GPTBigCodeForCausalLM,
    "OPTForCausalLM": OPTForCausalLM,
    # OPT-shaped decoders: BioGPT (learned positions, gelu, scaled
    # embeddings) and XGLM (fixed sinusoidal positions materialized at
    # load) — models/families_gpt.py.
    "BioGptForCausalLM": BioGptForCausalLM,
    "XGLMForCausalLM": XGLMForCausalLM,
    "MiniCPMForCausalLM": MiniCPMForCausalLM,
    "ExaoneForCausalLM": ExaoneForCausalLM,
    # Llama-math forks with bias/MLP twists (models/families_gpt.py).
    "HeliumForCausalLM": LlamaForCausalLM,
    "Ernie4_5ForCausalLM": Ernie45ForCausalLM,
    "SeedOssForCausalLM": SeedOssForCausalLM,
    "ArceeForCausalLM": ArceeForCausalLM,
    # ALiBi families (slope bias in ops/attention.py).
    "BloomForCausalLM": BloomForCausalLM,
    "MptForCausalLM": MPTForCausalLM,
    "MPTForCausalLM": MPTForCausalLM,
    # Encoder-decoder audio (cross-attention state rows;
    # models/whisper.py + multimodal/audio.py).
    "WhisperForConditionalGeneration": WhisperForConditionalGeneration,
    # Encoder-decoder text (models/bart.py + multimodal/text_encoder.py).
    "BartForConditionalGeneration": BartForConditionalGeneration,
    "BartModel": BartForConditionalGeneration,
    # Encoder-only embedding + cross-encoder families (models/bert.py;
    # reference: the _EMBEDDING_MODELS / _CROSS_ENCODER_MODELS maps of
    # model_executor/models/registry.py).
    "BertModel": BertEmbeddingModel,
    "BertForSequenceClassification": BertForSequenceClassification,
    "RobertaModel": RobertaEmbeddingModel,
    "XLMRobertaModel": RobertaEmbeddingModel,
    "RobertaForSequenceClassification": RobertaForSequenceClassification,
    "XLMRobertaForSequenceClassification": RobertaForSequenceClassification,
}


def resolve_architecture(hf_config) -> type:
    for arch in getattr(hf_config, "architectures", None) or []:
        if arch in _REGISTRY:
            return _REGISTRY[arch]
    # Config-shape fallback (tiny test configs may lack architectures).
    if hasattr(hf_config, "num_hidden_layers"):
        return LlamaForCausalLM
    raise ValueError(
        f"no supported architecture in {getattr(hf_config, 'architectures', None)}")


def supported_architectures() -> list[str]:
    return sorted(_REGISTRY)


__all__ = [
    "resolve_architecture",
    "supported_architectures",
    "LlamaArchConfig",
    "LlamaForCausalLM",
]
