"""Token sampler: greedy / temperature / top-k / top-p / min-p.

Reference: vllm/v1/sample/sampler.py:18 and
v1/sample/ops/topk_topp_sampler.py:296. TPU-native design: one fused
static-shape computation over the padded request batch, built around
what the TPU is fast at (elementwise O(V) scans, small-k top_k) and
avoiding what it is slow at (a full vocab sort per step):

* Greedy-only batches (the common serving case) short-circuit to a
  single argmax under ``lax.cond`` — no sort, no Gumbel.
* Sampled batches derive top-k / top-p / min-p as per-row THRESHOLD
  VALUES from a top-``_K_CAP`` partial top_k, mask the full vocab with
  one compare, and sample by Gumbel-argmax (no [R, V] sort or gather).
* Rows the prefix cannot resolve exactly (top_k > _K_CAP, or a top-p
  whose nucleus spills past the prefix) flip a ``lax.cond`` to a
  full-sort path that computes the SAME thresholds exactly, so the
  sampled distribution never degrades — it only costs more on the
  rare batch that needs it. Ties at a threshold keep all tied tokens
  (the sorted formulation split them by sort order); with float32
  logits exact ties are measure-zero.
"""

from functools import partial

import jax
import jax.numpy as jnp

from vllm_distributed_tpu.sample.metadata import (ExtendedSamplingMetadata,
                                                  SamplingMetadata)

_NEG_INF = float("-inf")

# OpenAI-compatible cap on `logprobs=k`; the extended sampler always
# computes this many so K adds no compile-lattice dimension.
MAX_LOGPROBS = 20

# Truncation prefix width: top-k/top-p thresholds resolve from a
# top-_K_CAP partial top_k when the request's filters fit inside it
# (virtually always in practice); wider filters take the exact
# full-sort fallback branch.
_K_CAP = 128


def _truncation_thresholds(scaled, topv, top_k, top_p, kcap):
    """Per-row keep-threshold in scaled-logit space from the descending
    prefix ``topv`` [R, kcap] (kcap == V makes this exact for any
    filter). A token survives iff scaled >= threshold.

    top-k: threshold is the k-th largest value. top-p (nucleus): the
    value of the last entry of the smallest prefix whose mass reaches
    top_p; computed with the full-vocab softmax normalizer so prefix
    masses are true probabilities."""
    R = scaled.shape[0]
    V = scaled.shape[1]
    rows = jnp.arange(R, dtype=jnp.int32)
    # -- top-k threshold (top_k <= 0 or >= V disables).
    k_on = (top_k > 0) & (top_k < V)
    k_idx = jnp.clip(top_k - 1, 0, kcap - 1)
    kth = jnp.where(k_on & (top_k <= kcap), topv[rows, k_idx], _NEG_INF)
    # -- top-p threshold over true probabilities.
    logz = jax.nn.logsumexp(scaled, axis=-1, keepdims=True)
    p_pref = jnp.exp(topv - logz)  # [R, kcap] true probs of the prefix
    cum_before = jnp.cumsum(p_pref, axis=-1) - p_pref
    keep_sorted = cum_before < top_p[:, None]
    # Value of the last kept prefix entry = min over kept values.
    cut_p = jnp.min(jnp.where(keep_sorted, topv, jnp.inf), axis=-1)
    covered = (cum_before[:, -1] + p_pref[:, -1]) >= top_p
    cut_p = jnp.where((top_p < 1.0) & covered, cut_p, _NEG_INF)
    resolved = ((~k_on | (top_k <= kcap)) &
                ((top_p >= 1.0) | covered))
    return jnp.maximum(kth, cut_p), resolved


def _apply_truncation(scaled: jax.Array, top_k: jax.Array,
                      top_p: jax.Array, min_p: jax.Array) -> jax.Array:
    """Mask temperature-scaled logits [N, V] to each row's top-k/top-p/
    min-p support (-inf outside). Thresholds resolve from a top-_K_CAP
    prefix with an exact full-sort fallback (see module docstring);
    shared by the plain sampler and the spec-decode verifier so both
    truncate identically."""
    V = scaled.shape[1]
    kcap = min(_K_CAP, V)
    topv, _idx = jax.lax.top_k(scaled, kcap)
    thr, resolved = _truncation_thresholds(scaled, topv, top_k, top_p,
                                           kcap)
    if kcap < V:
        def exact(_):
            full, _i = jax.lax.top_k(scaled, V)
            t, _r = _truncation_thresholds(scaled, full, top_k, top_p, V)
            return t

        thr = jax.lax.cond(jnp.all(resolved), lambda _: thr, exact, None)
    # min-p in scaled space: p_i >= min_p * p_max  <=>
    # scaled_i >= log(min_p) + scaled_max (min_p = 0 -> -inf).
    cut_m = jnp.log(jnp.maximum(min_p, 0.0)) + scaled.max(axis=-1)
    thr = jnp.maximum(thr, cut_m)
    return jnp.where(scaled >= thr[:, None], scaled, _NEG_INF)


def _sample_from_logits(
    logits: jax.Array,  # [R, V] float32
    md: SamplingMetadata,
) -> tuple[jax.Array, jax.Array]:
    """Core fused sampler: returns (sampled token ids [R] int32, logprob of
    the sampled token [R] float32 under the RAW untempered distribution —
    the reference's semantics: v1/sample/sampler.py computes logprobs from
    the unprocessed logits, so reported values do not depend on
    temperature or batch composition)."""
    R, V = logits.shape

    greedy_ids = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def sampled_branch(_):
        # Temperature scale (guard greedy rows against /0; their result
        # is discarded by the final where()).
        temp = jnp.maximum(md.temperature, 1e-6)[:, None]
        scaled = logits / temp
        masked = _apply_truncation(scaled, md.top_k, md.top_p, md.min_p)

        # Gumbel-argmax over the masked vocab; per-request keys.
        base = jax.random.PRNGKey(0)
        keys = jax.vmap(lambda s: jax.random.fold_in(base, s))(
            md.seeds.astype(jnp.uint32))
        gumbel = jax.vmap(
            lambda key: jax.random.gumbel(key, (V, )))(keys)
        sampled_ids = jnp.argmax(masked + gumbel,
                                 axis=-1).astype(jnp.int32)
        return jnp.where(md.temperature < 1e-5, greedy_ids, sampled_ids)

    # Greedy-only batches (temperature 0 everywhere) skip the whole
    # truncation/Gumbel pipeline — one argmax.
    token_ids = jax.lax.cond(jnp.any(md.temperature >= 1e-5),
                             sampled_branch, lambda _: greedy_ids, None)

    # Logprob of the chosen token under the raw (untempered, untruncated)
    # distribution.
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    chosen_logprob = jnp.take_along_axis(logprobs, token_ids[:, None],
                                         axis=1)[:, 0]
    return token_ids, chosen_logprob


@partial(jax.jit, static_argnames=())
def sample_tokens(
    logits: jax.Array,  # [R, V] float32
    md: SamplingMetadata,
) -> tuple[jax.Array, jax.Array]:
    return _sample_from_logits(logits, md)


def apply_logits_processors(
    logits: jax.Array,  # [R, V] float32
    ext: ExtendedSamplingMetadata,
) -> jax.Array:
    """Penalties + sparse bias/mask, fused and static-shape.

    Reference semantics (vllm/v1/sample/ops/penalties.py):
    * repetition_penalty: tokens seen in prompt OR output — positive
      logits divided by rp, negative multiplied by rp.
    * frequency_penalty: logits -= fp * count-in-output.
    * presence_penalty: logits -= pp * (appeared-in-output).
    Then the sparse row mask: ``logits + base_fill`` with ``bias_vals``
    set() at ``bias_ids`` (carries logit_bias, allowed_token_ids and
    min-tokens stop suppression; see ExtendedSamplingMetadata).
    """
    R, V = logits.shape
    L = ext.hist_tokens.shape[1]
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    in_output = ((pos >= ext.prompt_len[:, None]) &
                 (pos < ext.total_len[:, None]))
    in_any = pos < ext.total_len[:, None]
    rows = jnp.broadcast_to(jnp.arange(R, dtype=jnp.int32)[:, None], (R, L))

    out_counts = jnp.zeros((R, V), jnp.float32).at[
        rows, ext.hist_tokens].add(in_output.astype(jnp.float32),
                                   mode="drop")
    seen = jnp.zeros((R, V), jnp.bool_).at[
        rows, ext.hist_tokens].max(in_any, mode="drop")

    rp = ext.repetition_penalty[:, None]
    logits = jnp.where(seen,
                       jnp.where(logits > 0, logits / rp, logits * rp),
                       logits)
    logits = logits - ext.frequency_penalty[:, None] * out_counts
    logits = logits - ext.presence_penalty[:, None] * (out_counts > 0)

    B = ext.bias_ids.shape[1]
    brows = jnp.broadcast_to(jnp.arange(R, dtype=jnp.int32)[:, None], (R, B))
    mask = jnp.broadcast_to(ext.base_fill[:, None], (R, V))
    mask = mask.at[brows, ext.bias_ids].set(ext.bias_vals, mode="drop")
    return logits + mask


def sample_tokens_extended(
    logits: jax.Array,  # [R, V] float32
    md: SamplingMetadata,
    ext: ExtendedSamplingMetadata,
    want_topk: bool = True,
    vocab_mask: jax.Array = None,  # [R, V] bool; True = token allowed
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Extended path: logits processors + sampling (+ top-K logprobs when
    ``want_topk``) in one graph. Returns (token ids [R], chosen logprob
    [R], topk logprob values [R, K], topk ids [R, K]); the topk pair is
    None when ``want_topk`` is False (penalties-only batches skip the
    vocab-wide top_k and its transfer).

    Logprobs (chosen and top-k) are reported under the RAW untempered
    pre-processor distribution — the reference's V1 semantics
    (v1/sample/sampler.py computes logprobs from the unprocessed logits),
    so a request's reported logprobs never depend on which other requests
    share its batch.
    """
    raw_logprobs = jax.nn.log_softmax(logits, axis=-1)
    logits = apply_logits_processors(logits, ext)
    if vocab_mask is not None:
        # Structured-output grammar bitmask (reference: bitmask applied
        # to the logits at gpu_model_runner.py:1433). Reported logprobs
        # stay raw, matching the unmasked-logprob semantics above.
        logits = jnp.where(vocab_mask, logits, jnp.float32(-jnp.inf))
    token_ids, _ = _sample_from_logits(logits, md)
    chosen_logprob = jnp.take_along_axis(raw_logprobs, token_ids[:, None],
                                         axis=1)[:, 0]
    if not want_topk:
        return token_ids, chosen_logprob, None, None
    k = min(MAX_LOGPROBS, logits.shape[-1])
    top_vals, top_ids = jax.lax.top_k(raw_logprobs, k)
    return token_ids, chosen_logprob, top_vals, top_ids.astype(jnp.int32)


@partial(jax.jit, static_argnames=("truncate", ))
def spec_verify_rejection(
    logits: jax.Array,  # [R, S1, V] target logits (S1 = S drafts + 1)
    drafts: jax.Array,  # [R, S] int32 proposed tokens (-1 = no draft)
    q_ids: jax.Array,  # [R, S, K] int32 draft support token ids
    q_probs: jax.Array,  # [R, S, K] f32 draft probs on the support
    md: SamplingMetadata,  # per-row (R); seeds [R, S1] per position
    truncate: bool = True,  # static: any row has top-k/top-p/min-p on
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """True stochastic rejection sampling for learned drafters
    (reference: v1/sample/rejection_sampler.py:23).

    The drafter samples from a truncated distribution q with support
    ``q_ids`` (K tokens) and probabilities ``q_probs``; position s of a
    row is accepted with prob min(1, p(d)/q(d)) under the TEMPERED
    target p, and the first rejected position resamples from the exact
    residual max(p - q, 0)/Z — together emitting tokens distributed
    exactly as p (Leviathan et al.; the reference kernel implements the
    same test). Greedy rows (temperature < 1e-5) accept iff the target
    argmax equals the draft — the deterministic limit of the same rule.

    Returns (accept [R, S] bool, residual [R, S] int32, bonus [R] int32,
    lp_cand [R, S, 2] raw logprobs of (draft, residual) per position,
    lp_bonus [R]) — everything the host needs to assemble the emitted
    prefix, with only O(R*S) transferred.
    """
    R, S1, V = logits.shape
    S = S1 - 1
    temp = jnp.maximum(md.temperature, 1e-6)[:, None, None]
    # Tempered target TRUNCATED to each request's top-k/top-p/min-p
    # support (ADVICE r5 high; reference: rejection_sampler
    # compute_probs applies top-k/top-p to target logits before the
    # accept test): the accept probability, exact residual, and bonus
    # sample all derive from the truncated p, so spec decode can never
    # emit a token the non-spec sampler would have masked. ``truncate``
    # is a STATIC flag the runner sets only when some batch row has a
    # filter active — the default-sampling case (where the thresholds
    # would resolve to -inf and mask nothing) skips the top_k pass.
    scaled = logits / temp
    if truncate:
        scaled = _apply_truncation(
            scaled.reshape(R * S1, V),
            jnp.repeat(md.top_k, S1),
            jnp.repeat(md.top_p, S1),
            jnp.repeat(md.min_p, S1)).reshape(R, S1, V)
    logp = jax.nn.log_softmax(scaled, axis=-1)  # tempered target
    p = jnp.exp(logp)

    rowsR = jnp.arange(R, dtype=jnp.int32)[:, None]
    sidx = jnp.arange(S, dtype=jnp.int32)[None, :]
    d_safe = jnp.maximum(drafts, 0)
    p_d = p[rowsR, sidx, d_safe]  # [R, S] target prob of each draft
    # Draft prob of its own sample: find d in the support row.
    match = q_ids == drafts[..., None]  # [R, S, K]
    q_d = jnp.where(match, q_probs, 0.0).sum(-1)  # [R, S]

    base = jax.random.PRNGKey(1)
    seeds = md.seeds.reshape(R, S1).astype(jnp.uint32)
    ukeys = jax.vmap(jax.vmap(lambda s: jax.random.fold_in(base, s)))(
        seeds)
    u = jax.vmap(jax.vmap(
        lambda k: jax.random.uniform(k, ())))(ukeys)  # [R, S1]

    greedy = md.temperature < 1e-5
    argmax_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [R,S1]
    # u < p/q without the divide; q_d == 0 (draft outside its own
    # support — impossible for a well-formed proposer) never accepts.
    accept_stoch = jnp.logical_and(u[:, :S] * q_d < p_d, q_d > 0)
    accept_greedy = argmax_tok[:, :S] == drafts
    accept = jnp.where(greedy[:, None], accept_greedy, accept_stoch)
    accept = jnp.logical_and(accept, drafts >= 0)

    # Exact residual: scatter q onto the vocab, r = max(p - q, 0).
    q_full = jnp.zeros((R, S, V), p.dtype).at[
        rowsR[..., None], sidx[..., None], q_ids].add(
            q_probs, mode="drop")
    resid = jnp.maximum(p[:, :S] - q_full, 0.0)
    # Gumbel over log-residual; per-(row, pos) keys derived from the
    # same seeds with a distinct stream constant.
    rbase = jax.random.PRNGKey(2)
    rkeys = jax.vmap(jax.vmap(lambda s: jax.random.fold_in(rbase, s)))(
        seeds[:, :S])
    g = jax.vmap(jax.vmap(
        lambda k: jax.random.gumbel(k, (V, ))))(rkeys)
    log_resid = jnp.where(resid > 0, jnp.log(jnp.maximum(resid, 1e-30)),
                          _NEG_INF)
    residual = jnp.argmax(log_resid + g, axis=-1).astype(jnp.int32)
    # Degenerate rows (p <= q everywhere numerically): fall back to the
    # tempered target sample so an emit is always valid.
    any_resid = (resid > 0).any(axis=-1)
    fallback = jnp.argmax(
        logp[:, :S] + g, axis=-1).astype(jnp.int32)
    residual = jnp.where(any_resid, residual, fallback)
    residual = jnp.where(greedy[:, None], argmax_tok[:, :S], residual)

    # Bonus token (all drafts accepted): regular sample at position S.
    bkeys = jax.vmap(lambda s: jax.random.fold_in(base, s))(
        seeds[:, S])
    bg = jax.vmap(lambda k: jax.random.gumbel(k, (V, )))(bkeys)
    bonus = jnp.argmax(logp[:, S] + bg, axis=-1).astype(jnp.int32)
    bonus = jnp.where(greedy, argmax_tok[:, S], bonus)

    # Raw (untempered) logprobs of every candidate emit: drafts,
    # residuals, bonus — the host assembles the emitted prefix.
    raw_lp = jax.nn.log_softmax(logits, axis=-1)
    lp_draft = raw_lp[rowsR, sidx, d_safe]
    lp_resid = raw_lp[rowsR, sidx, residual]
    lp_bonus = raw_lp[jnp.arange(R), S, bonus]
    return (accept, residual, bonus,
            jnp.stack([lp_draft, lp_resid], axis=-1), lp_bonus)


def compute_topk_logprobs(logits: jax.Array,
                          num_logprobs: int) -> tuple[jax.Array, jax.Array]:
    """Top-k logprobs for API `logprobs=k` requests (reference:
    v1/sample/logits_processor.py logprobs path)."""
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    top_vals, top_ids = jax.lax.top_k(logprobs, num_logprobs)
    return top_vals, top_ids.astype(jnp.int32)
