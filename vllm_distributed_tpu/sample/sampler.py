"""Token sampler: greedy / temperature / top-k / top-p / min-p.

Reference: vllm/v1/sample/sampler.py:18 and
v1/sample/ops/topk_topp_sampler.py:296. TPU-native design: one fused
static-shape computation over the padded request batch — a single
descending sort serves top-k, top-p and min-p masking, and sampling is
Gumbel-argmax over the masked, sorted logits (no host sync, no dynamic
shapes, vmapped per-request PRNG via fold_in).
"""

from functools import partial

import jax
import jax.numpy as jnp

from vllm_distributed_tpu.sample.metadata import (ExtendedSamplingMetadata,
                                                  SamplingMetadata)

_NEG_INF = float("-inf")

# OpenAI-compatible cap on `logprobs=k`; the extended sampler always
# computes this many so K adds no compile-lattice dimension.
MAX_LOGPROBS = 20


def _sample_from_logits(
    logits: jax.Array,  # [R, V] float32
    md: SamplingMetadata,
) -> tuple[jax.Array, jax.Array]:
    """Core fused sampler: returns (sampled token ids [R] int32, logprob of
    the sampled token [R] float32 under the RAW untempered distribution —
    the reference's semantics: v1/sample/sampler.py computes logprobs from
    the unprocessed logits, so reported values do not depend on
    temperature or batch composition)."""
    R, V = logits.shape

    greedy_ids = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    # Temperature scale (guard greedy rows against /0; their result is
    # discarded by the final where()).
    temp = jnp.maximum(md.temperature, 1e-6)[:, None]
    scaled = logits / temp

    # One descending sort powers all three truncations.
    sorted_logits, sorted_idx = jax.lax.top_k(scaled, V)

    ranks = jnp.arange(V, dtype=jnp.int32)[None, :]
    # top-k: keep the first k sorted entries (k=0 -> keep all).
    k = jnp.where(md.top_k > 0, md.top_k, V)[:, None]
    keep = ranks < k

    probs = jax.nn.softmax(sorted_logits, axis=-1)
    # top-p: keep the smallest prefix with cumulative prob >= top_p.
    # (cumsum - prob) is the mass strictly before each entry; once that
    # reaches top_p the entry is dropped.
    cum_before = jnp.cumsum(probs, axis=-1) - probs
    keep &= cum_before < md.top_p[:, None]
    # min-p: drop tokens below min_p * max_prob.
    keep &= probs >= (md.min_p[:, None] * probs[:, 0:1])

    masked = jnp.where(keep, sorted_logits, _NEG_INF)

    # Gumbel-argmax over the masked sorted logits; per-request keys.
    base = jax.random.PRNGKey(0)
    keys = jax.vmap(lambda s: jax.random.fold_in(base, s))(
        md.seeds.astype(jnp.uint32))
    gumbel = jax.vmap(
        lambda key, row: jax.random.gumbel(key, row.shape))(keys, masked)
    choice_rank = jnp.argmax(masked + gumbel, axis=-1)
    sampled_ids = jnp.take_along_axis(sorted_idx, choice_rank[:, None],
                                      axis=1)[:, 0].astype(jnp.int32)

    token_ids = jnp.where(md.temperature < 1e-5, greedy_ids, sampled_ids)

    # Logprob of the chosen token under the raw (untempered, untruncated)
    # distribution.
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    chosen_logprob = jnp.take_along_axis(logprobs, token_ids[:, None],
                                         axis=1)[:, 0]
    return token_ids, chosen_logprob


@partial(jax.jit, static_argnames=())
def sample_tokens(
    logits: jax.Array,  # [R, V] float32
    md: SamplingMetadata,
) -> tuple[jax.Array, jax.Array]:
    return _sample_from_logits(logits, md)


def apply_logits_processors(
    logits: jax.Array,  # [R, V] float32
    ext: ExtendedSamplingMetadata,
) -> jax.Array:
    """Penalties + sparse bias/mask, fused and static-shape.

    Reference semantics (vllm/v1/sample/ops/penalties.py):
    * repetition_penalty: tokens seen in prompt OR output — positive
      logits divided by rp, negative multiplied by rp.
    * frequency_penalty: logits -= fp * count-in-output.
    * presence_penalty: logits -= pp * (appeared-in-output).
    Then the sparse row mask: ``logits + base_fill`` with ``bias_vals``
    set() at ``bias_ids`` (carries logit_bias, allowed_token_ids and
    min-tokens stop suppression; see ExtendedSamplingMetadata).
    """
    R, V = logits.shape
    L = ext.hist_tokens.shape[1]
    pos = jnp.arange(L, dtype=jnp.int32)[None, :]
    in_output = ((pos >= ext.prompt_len[:, None]) &
                 (pos < ext.total_len[:, None]))
    in_any = pos < ext.total_len[:, None]
    rows = jnp.broadcast_to(jnp.arange(R, dtype=jnp.int32)[:, None], (R, L))

    out_counts = jnp.zeros((R, V), jnp.float32).at[
        rows, ext.hist_tokens].add(in_output.astype(jnp.float32),
                                   mode="drop")
    seen = jnp.zeros((R, V), jnp.bool_).at[
        rows, ext.hist_tokens].max(in_any, mode="drop")

    rp = ext.repetition_penalty[:, None]
    logits = jnp.where(seen,
                       jnp.where(logits > 0, logits / rp, logits * rp),
                       logits)
    logits = logits - ext.frequency_penalty[:, None] * out_counts
    logits = logits - ext.presence_penalty[:, None] * (out_counts > 0)

    B = ext.bias_ids.shape[1]
    brows = jnp.broadcast_to(jnp.arange(R, dtype=jnp.int32)[:, None], (R, B))
    mask = jnp.broadcast_to(ext.base_fill[:, None], (R, V))
    mask = mask.at[brows, ext.bias_ids].set(ext.bias_vals, mode="drop")
    return logits + mask


def sample_tokens_extended(
    logits: jax.Array,  # [R, V] float32
    md: SamplingMetadata,
    ext: ExtendedSamplingMetadata,
    want_topk: bool = True,
    vocab_mask: jax.Array = None,  # [R, V] bool; True = token allowed
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Extended path: logits processors + sampling (+ top-K logprobs when
    ``want_topk``) in one graph. Returns (token ids [R], chosen logprob
    [R], topk logprob values [R, K], topk ids [R, K]); the topk pair is
    None when ``want_topk`` is False (penalties-only batches skip the
    vocab-wide top_k and its transfer).

    Logprobs (chosen and top-k) are reported under the RAW untempered
    pre-processor distribution — the reference's V1 semantics
    (v1/sample/sampler.py computes logprobs from the unprocessed logits),
    so a request's reported logprobs never depend on which other requests
    share its batch.
    """
    raw_logprobs = jax.nn.log_softmax(logits, axis=-1)
    logits = apply_logits_processors(logits, ext)
    if vocab_mask is not None:
        # Structured-output grammar bitmask (reference: bitmask applied
        # to the logits at gpu_model_runner.py:1433). Reported logprobs
        # stay raw, matching the unmasked-logprob semantics above.
        logits = jnp.where(vocab_mask, logits, jnp.float32(-jnp.inf))
    token_ids, _ = _sample_from_logits(logits, md)
    chosen_logprob = jnp.take_along_axis(raw_logprobs, token_ids[:, None],
                                         axis=1)[:, 0]
    if not want_topk:
        return token_ids, chosen_logprob, None, None
    k = min(MAX_LOGPROBS, logits.shape[-1])
    top_vals, top_ids = jax.lax.top_k(raw_logprobs, k)
    return token_ids, chosen_logprob, top_vals, top_ids.astype(jnp.int32)


def compute_topk_logprobs(logits: jax.Array,
                          num_logprobs: int) -> tuple[jax.Array, jax.Array]:
    """Top-k logprobs for API `logprobs=k` requests (reference:
    v1/sample/logits_processor.py logprobs path)."""
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    top_vals, top_ids = jax.lax.top_k(logprobs, num_logprobs)
    return top_vals, top_ids.astype(jnp.int32)
