"""Token sampler: greedy / temperature / top-k / top-p / min-p.

Reference: vllm/v1/sample/sampler.py:18 and
v1/sample/ops/topk_topp_sampler.py:296. TPU-native design: one fused
static-shape computation over the padded request batch — a single
descending sort serves top-k, top-p and min-p masking, and sampling is
Gumbel-argmax over the masked, sorted logits (no host sync, no dynamic
shapes, vmapped per-request PRNG via fold_in).
"""

from functools import partial

import jax
import jax.numpy as jnp

from vllm_distributed_tpu.sample.metadata import SamplingMetadata

_NEG_INF = float("-inf")


@partial(jax.jit, static_argnames=())
def sample_tokens(
    logits: jax.Array,  # [R, V] float32
    md: SamplingMetadata,
) -> tuple[jax.Array, jax.Array]:
    """Returns (sampled token ids [R] int32, logprob of the sampled token
    [R] float32 under the *unmasked* temperature-scaled distribution —
    matching the reference's sampled-logprob semantics)."""
    R, V = logits.shape

    greedy_ids = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    # Temperature scale (guard greedy rows against /0; their result is
    # discarded by the final where()).
    temp = jnp.maximum(md.temperature, 1e-6)[:, None]
    scaled = logits / temp

    # One descending sort powers all three truncations.
    sorted_logits, sorted_idx = jax.lax.top_k(scaled, V)

    ranks = jnp.arange(V, dtype=jnp.int32)[None, :]
    # top-k: keep the first k sorted entries (k=0 -> keep all).
    k = jnp.where(md.top_k > 0, md.top_k, V)[:, None]
    keep = ranks < k

    probs = jax.nn.softmax(sorted_logits, axis=-1)
    # top-p: keep the smallest prefix with cumulative prob >= top_p.
    # (cumsum - prob) is the mass strictly before each entry; once that
    # reaches top_p the entry is dropped.
    cum_before = jnp.cumsum(probs, axis=-1) - probs
    keep &= cum_before < md.top_p[:, None]
    # min-p: drop tokens below min_p * max_prob.
    keep &= probs >= (md.min_p[:, None] * probs[:, 0:1])

    masked = jnp.where(keep, sorted_logits, _NEG_INF)

    # Gumbel-argmax over the masked sorted logits; per-request keys.
    base = jax.random.PRNGKey(0)
    keys = jax.vmap(lambda s: jax.random.fold_in(base, s))(
        md.seeds.astype(jnp.uint32))
    gumbel = jax.vmap(
        lambda key, row: jax.random.gumbel(key, row.shape))(keys, masked)
    choice_rank = jnp.argmax(masked + gumbel, axis=-1)
    sampled_ids = jnp.take_along_axis(sorted_idx, choice_rank[:, None],
                                      axis=1)[:, 0].astype(jnp.int32)

    token_ids = jnp.where(md.temperature < 1e-5, greedy_ids, sampled_ids)

    # Logprob of the chosen token under the temperature-scaled (but
    # untruncated) distribution; greedy rows report the raw distribution.
    report_scale = jnp.where(md.temperature[:, None] < 1e-5,
                             logits, scaled)
    logprobs = jax.nn.log_softmax(report_scale, axis=-1)
    chosen_logprob = jnp.take_along_axis(logprobs, token_ids[:, None],
                                         axis=1)[:, 0]
    return token_ids, chosen_logprob


def compute_topk_logprobs(logits: jax.Array,
                          num_logprobs: int) -> tuple[jax.Array, jax.Array]:
    """Top-k logprobs for API `logprobs=k` requests (reference:
    v1/sample/logits_processor.py logprobs path)."""
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    top_vals, top_ids = jax.lax.top_k(logprobs, num_logprobs)
    return top_vals, top_ids.astype(jnp.int32)
