"""Per-step sampling tensors (reference: vllm/v1/sample/metadata.py
``SamplingMetadata`` + the TPU variant in v1/sample/tpu/).

Every field is a dense [R] array so any mix of per-request parameters
lowers to the same compiled graph — adding a request never recompiles.
"""

from dataclasses import dataclass

import jax


@jax.tree_util.register_dataclass
@dataclass
class SamplingMetadata:
    # [R] float32; 0.0 means greedy.
    temperature: jax.Array
    # [R] int32; 0 disables top-k.
    top_k: jax.Array
    # [R] float32; 1.0 disables top-p.
    top_p: jax.Array
    # [R] float32; 0.0 disables min-p.
    min_p: jax.Array
    # [R] int64 per-step fold-in values: derived from (user seed, step) for
    # seeded requests or (engine rng, step) otherwise, built on the host.
    seeds: jax.Array
