"""Per-step sampling tensors (reference: vllm/v1/sample/metadata.py
``SamplingMetadata`` + the TPU variant in v1/sample/tpu/).

Every field is a dense [R] array so any mix of per-request parameters
lowers to the same compiled graph — adding a request never recompiles.
"""

from dataclasses import dataclass

import jax


@jax.tree_util.register_dataclass
@dataclass
class SamplingMetadata:
    # [R] float32; 0.0 means greedy.
    temperature: jax.Array
    # [R] int32; 0 disables top-k.
    top_k: jax.Array
    # [R] float32; 1.0 disables top-p.
    top_p: jax.Array
    # [R] float32; 0.0 disables min-p.
    min_p: jax.Array
    # [R] int64 per-step fold-in values: derived from (user seed, step) for
    # seeded requests or (engine rng, step) otherwise, built on the host.
    seeds: jax.Array


@jax.tree_util.register_dataclass
@dataclass
class ExtendedSamplingMetadata:
    """Logits-processor inputs for the extended sampling path (penalties,
    logit bias, allowed-token masks, min-tokens stop suppression;
    reference: vllm/v1/sample/sampler.py:18 apply_penalties +
    logits_processor.py:517). Static shapes: the history buffer is always
    [R, max_model_len] and the sparse bias buffer is a fixed [R, B] so the
    extended graph is keyed only by R.
    """

    # [R, L] int32 token history (prompt + generated). Entries past
    # total_len may hold ANY id (the input batch pads with 0): the
    # penalty scatters weight them by the in-window masks, so padding
    # contributes zero regardless of its value.
    hist_tokens: jax.Array
    # [R] int32 prompt length (presence/frequency penalize output only).
    prompt_len: jax.Array
    # [R] int32 total tokens so far (prompt + output).
    total_len: jax.Array
    # [R] float32 penalties; 0 / 0 / 1 disable.
    presence_penalty: jax.Array
    frequency_penalty: jax.Array
    repetition_penalty: jax.Array
    # Sparse additive bias applied with set(): [R, B] token ids (pad: out of
    # vocab, dropped) and values. Carries user logit_bias, min-tokens stop
    # suppression (-inf at stop ids), and allowed_token_ids (base_fill=-inf
    # with 0-valued entries at the allowed ids).
    bias_ids: jax.Array
    bias_vals: jax.Array
    # [R] float32 fill applied to the whole row before the sparse set():
    # 0.0 normally, -inf for allowed_token_ids rows.
    base_fill: jax.Array
