"""Ragged paged attention over a paged KV cache — XLA implementation.

This is the TPU-native equivalent of the reference's unified attention path
(vllm/attention/layer.py:398 ``unified_attention`` dispatching to the CUDA
paged-attention kernels in csrc/attention/ and, on its TPU backend, to
torch.ops.xla.ragged_paged_attention — v1/attention/backends/pallas.py:232).

Two ops:

* ``write_kv_pages`` — scatter newly-computed K/V for a flat batch of
  tokens into the paged cache via a precomputed slot mapping (equivalent of
  csrc/cache_kernels.cu reshape_and_cache, pallas_kv_cache_update.py).
  On TPU this lowers to a dynamic-update-scatter XLA handles well.

* ``ragged_paged_attention`` — token-centric unified prefill/decode
  attention: every query token attends to its request's pages up to its own
  position. Implemented as a lax.scan over page indices with an online
  (flash-style) softmax so peak memory is O(T * page_size) instead of
  O(T * max_model_len). Handles GQA, mixed prefill+decode in one batch,
  and same-step prefix sharing (KV must be written before calling).

A Pallas kernel (ops/pallas/) replaces the scan for performance; this XLA
version is the correctness reference and the CPU/interpret fallback
(selected via VDT_ATTENTION_BACKEND).
"""

from functools import partial

import jax
import jax.numpy as jnp

from vllm_distributed_tpu import envs
from vllm_distributed_tpu.parallel.mesh import shard_map

# Set to a large negative number rather than -inf so fully-masked rows
# produce 0-weight rows instead of NaNs.
_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)

# fp8 cache payload dtypes (--kv-cache-dtype): these route the XLA
# attention/write paths (Pallas fp8 dequant is a follow-up).
_FP8_DTYPES = (jnp.float8_e4m3fn, jnp.float8_e5m2)


def storage_head_dim(head_dim: int) -> int:
    """Head dim used for KV-cache storage: padded to the 128-lane tile on
    TPU (reference: v1/attention/backends/pallas.py:25 pads head size to
    128; Mosaic cannot DMA sub-tile lane slices). Zero-padding K and V
    leaves attention numerics unchanged."""
    if jax.default_backend() == "tpu":
        return -(-head_dim // 128) * 128
    return head_dim


def _pad_last_dim(x: jax.Array, target: int) -> jax.Array:
    if x.shape[-1] == target:
        return x
    pad = [(0, 0)] * (x.ndim - 1) + [(0, target - x.shape[-1])]
    return jnp.pad(x, pad)


def build_head_feat(num_q_heads: int, alibi_slopes, sinks) -> jax.Array:
    """The mega-kernel's per-head feature sidecar: [2, QH] f32 with
    ALiBi slopes in row 0 and attention-sink logits in row 1 (zeros for
    disabled features — the has_alibi/has_sinks statics gate the math,
    so the zero rows are never read). An ARRAY rather than statics so
    learned sinks stay traced and TP shard_maps slice per-rank head
    ranges naturally."""
    zeros = jnp.zeros((num_q_heads, ), jnp.float32)
    return jnp.stack([
        (jnp.asarray(alibi_slopes, jnp.float32)
         if alibi_slopes is not None else zeros),
        (sinks.astype(jnp.float32) if sinks is not None else zeros),
    ])


def write_kv_pages(
    k_pages: jax.Array,  # [num_pages, num_kv_heads, page_size, head_dim]
    v_pages: jax.Array,  # [num_pages, num_kv_heads, page_size, head_dim]
    k_new: jax.Array,  # [T, num_kv_heads, head_dim]
    v_new: jax.Array,  # [T, num_kv_heads, head_dim]
    slot_mapping: jax.Array,  # [T] int32 flat slot = page*page_size + off
) -> tuple[jax.Array, jax.Array]:
    """Scatter new K/V rows into a single-layer paged cache (XLA path).

    The cache page layout is head-major [page, kv_head, page_size, head_dim]
    so the Pallas attention kernel can DMA each page directly into
    head-leading VMEM blocks (Mosaic wants batch/head dims leading). The
    scatter is expressed as contiguous [1, head_dim] row updates on the
    flattened cache — the only scatter shape XLA lowers efficiently.
    Padded tokens must carry an out-of-range slot (e.g. -1): scatter mode
    'drop' discards them.
    """
    num_pages, num_kv_heads, page_size, head_dim = k_pages.shape
    T = k_new.shape[0]
    k_new = _pad_last_dim(k_new, head_dim)
    v_new = _pad_last_dim(v_new, head_dim)
    if k_pages.dtype in _FP8_DTYPES:
        # Saturate like the reference fp8 cache kernels: a bare astype
        # maps overflow to NaN, and one NaN row poisons its page.
        lim = float(jnp.finfo(k_pages.dtype).max)
        k_new = jnp.clip(k_new.astype(jnp.float32), -lim, lim)
        v_new = jnp.clip(v_new.astype(jnp.float32), -lim, lim)
    page = slot_mapping // page_size
    off = slot_mapping % page_size
    # Flat row per (token, head): ((page * KVH) + h) * PS + off.
    rows = ((page[:, None] * num_kv_heads +
             jnp.arange(num_kv_heads, dtype=jnp.int32)[None, :]) *
            page_size + off[:, None])
    total = num_pages * num_kv_heads * page_size
    rows = jnp.where(slot_mapping[:, None] < 0, total, rows).reshape(-1)
    k_flat = k_pages.reshape(total, head_dim)
    v_flat = v_pages.reshape(total, head_dim)
    k_flat = k_flat.at[rows].set(
        k_new.reshape(T * num_kv_heads, head_dim).astype(k_flat.dtype),
        mode="drop")
    v_flat = v_flat.at[rows].set(
        v_new.reshape(T * num_kv_heads, head_dim).astype(v_flat.dtype),
        mode="drop")
    return (k_flat.reshape(k_pages.shape), v_flat.reshape(v_pages.shape))


@partial(jax.jit, static_argnames=("sm_scale", "window", "logit_cap",
                                   "alibi_slopes"))
def ragged_paged_attention(
    q: jax.Array,  # [T, num_q_heads, head_dim]
    k_pages: jax.Array,  # [num_pages, num_kv_heads, page_size, head_dim]
    v_pages: jax.Array,  # [num_pages, num_kv_heads, page_size, head_dim]
    block_tables: jax.Array,  # [max_reqs, pages_per_req] int32
    req_idx: jax.Array,  # [T] int32: owning request row per token
    q_pos: jax.Array,  # [T] int32: absolute position of each query token
    *,
    sm_scale: float,
    window: int = 0,  # sliding window size; 0 = full causal
    logit_cap: float = 0.0,  # Gemma2 attn soft-capping; 0 = off
    alibi_slopes: tuple = None,  # per-q-head ALiBi slopes; None = off
    sinks: jax.Array = None,  # [num_q_heads] attention-sink logits
) -> jax.Array:  # [T, num_q_heads, head_dim]
    """Unified ragged attention: token t attends to kv positions
    0..q_pos[t] of request req_idx[t] (causal over the paged cache);
    a positive ``window`` restricts to the last ``window`` positions
    (Mistral-style sliding window, reference: sliding_window plumbed
    through the attention backends); a positive ``logit_cap`` bounds
    scores with cap*tanh(s/cap) before masking (Gemma2 soft-capping,
    reference: the softcap arg of the attention backends);
    ``alibi_slopes`` adds slope * (kv_pos - q_pos) per head before
    masking (Bloom/MPT ALiBi, reference: the alibi_slopes arg of the
    attention backends / csrc attention kernels)."""
    T, num_q_heads, head_dim = q.shape
    num_pages, num_kv_heads, page_size, _ = k_pages.shape
    assert num_q_heads % num_kv_heads == 0
    group = num_q_heads // num_kv_heads
    pages_per_req = block_tables.shape[1]

    # [T, Hkv, G, D] queries grouped by kv head.
    qg = q.reshape(T, num_kv_heads, group, head_dim).astype(jnp.float32)
    qg = qg * sm_scale
    # Per-token page lists: [T, pages_per_req].
    token_pages = block_tables[req_idx]

    def body(carry, page_i):
        m, l, acc = carry  # [T,Hkv,G,1], [T,Hkv,G,1], [T,Hkv,G,D]
        page_ids = token_pages[:, page_i]  # [T]
        k_blk = k_pages[page_ids, ..., :head_dim].astype(jnp.float32)
        v_blk = v_pages[page_ids, ..., :head_dim].astype(jnp.float32)
        # scores [T, Hkv, G, ps]
        scores = jnp.einsum("thgd,thpd->thgp", qg, k_blk)
        if logit_cap > 0:
            scores = logit_cap * jnp.tanh(scores / logit_cap)
        kv_pos = page_i * page_size + jnp.arange(page_size, dtype=jnp.int32)
        if alibi_slopes is not None:
            slopes = jnp.asarray(alibi_slopes, jnp.float32).reshape(
                num_kv_heads, group)
            dist = (kv_pos[None, :] - q_pos[:, None]).astype(jnp.float32)
            scores = scores + (slopes[None, :, :, None] *
                               dist[:, None, None, :])
        valid = kv_pos[None, :] <= q_pos[:, None]  # [T, ps] causal
        if window > 0:
            valid &= kv_pos[None, :] > (q_pos[:, None] - window)
        scores = jnp.where(valid[:, None, None, :], scores, _MASK_VALUE)

        m_new = jnp.maximum(m, scores.max(axis=-1, keepdims=True))
        p = jnp.exp(scores - m_new)  # [T,Hkv,G,ps]
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum("thgp,thpd->thgd", p, v_blk)
        return (m_new, l_new, acc_new), None

    m0 = jnp.full((T, num_kv_heads, group, 1), _MASK_VALUE, jnp.float32)
    l0 = jnp.zeros((T, num_kv_heads, group, 1), jnp.float32)
    acc0 = jnp.zeros((T, num_kv_heads, group, head_dim), jnp.float32)
    (m, l, acc), _ = jax.lax.scan(body, (m0, l0, acc0),
                                  jnp.arange(pages_per_req,
                                             dtype=jnp.int32))
    if sinks is not None:
        # A learned per-head virtual key joins the softmax denominator
        # only (gpt-oss attention sinks; softmax shift-invariance makes
        # the running max of the REAL scores a valid reference point).
        sk = sinks.astype(jnp.float32).reshape(num_kv_heads, group)
        l = l + jnp.exp(sk[None, :, :, None] - m)
    out = acc / jnp.maximum(l, 1e-20)
    return out.reshape(T, num_q_heads, head_dim).astype(q.dtype)


def _shared_prefix_state(q, k_pages, v_pages, shared_page_ids, q_pos,
                         sm_scale):
    """Dense online-softmax partial state of all T query tokens against
    the batch-wide shared-prefix pages: one gather + MXU matmuls,
    loaded once for the whole batch. Returns (m, l, acc) shaped
    [T, QH, 1/1/D] for merging with a suffix phase."""
    T, num_q_heads, head_dim = q.shape
    num_kv_heads, page_size = k_pages.shape[1], k_pages.shape[2]
    group = num_q_heads // num_kv_heads
    S = shared_page_ids.shape[0]
    qg = (q.reshape(T, num_kv_heads, group, head_dim)
          .astype(jnp.float32) * sm_scale)
    k_sh = k_pages[shared_page_ids, ..., :head_dim].astype(jnp.float32)
    v_sh = v_pages[shared_page_ids, ..., :head_dim].astype(jnp.float32)
    scores = jnp.einsum("thgd,shpd->thgsp", qg, k_sh)
    kv_pos = (jnp.arange(S, dtype=jnp.int32)[:, None] * page_size +
              jnp.arange(page_size, dtype=jnp.int32)[None, :])
    valid = kv_pos.reshape(-1)[None, :] <= q_pos[:, None]  # [T, S*ps]
    scores = scores.reshape(T, num_kv_heads, group, S * page_size)
    scores = jnp.where(valid[:, None, None, :], scores, _MASK_VALUE)
    m = scores.max(axis=-1, keepdims=True)
    p = jnp.exp(scores - m)
    l = p.sum(axis=-1, keepdims=True)
    acc = jnp.einsum(
        "thgj,thjd->thgd", p,
        jnp.broadcast_to(
            v_sh.swapaxes(0, 1).reshape(1, num_kv_heads,
                                        S * page_size, head_dim),
            (T, num_kv_heads, S * page_size, head_dim)))
    return (m.reshape(T, num_q_heads, 1), l.reshape(T, num_q_heads, 1),
            acc.reshape(T, num_q_heads, head_dim))


def merge_attention_states(state_a, state_b):
    """Combine two online-softmax partial states (m, l, acc) over
    disjoint KV ranges — the XLA equivalent of the reference's
    csrc/attention/merge_attn_states.cu (used there for cascade and
    split-KV attention)."""
    m_a, l_a, acc_a = state_a
    m_b, l_b, acc_b = state_b
    m = jnp.maximum(m_a, m_b)
    alpha_a = jnp.exp(m_a - m)
    alpha_b = jnp.exp(m_b - m)
    l = l_a * alpha_a + l_b * alpha_b
    acc = acc_a * alpha_a + acc_b * alpha_b
    return m, l, acc


def cascade_ragged_paged_attention(
    q: jax.Array,  # [T, num_q_heads, head_dim]
    k_pages: jax.Array,
    v_pages: jax.Array,
    block_tables: jax.Array,  # [max_reqs, pages_per_req]
    req_idx: jax.Array,  # [T]
    q_pos: jax.Array,  # [T]
    shared_page_ids: jax.Array,  # [S] int32: batch-wide common prefix
    *,
    sm_scale: float,
) -> jax.Array:
    """Shared-prefix (cascade) attention: every scheduled request's
    first S page-table slots hold the SAME pages (prefix-cache hits), so
    their KV is loaded ONCE and attended as a dense block for all T
    query tokens — one gather and one MXU-friendly matmul instead of T
    per-token page gathers (reference: the cascade path of
    v1/attention/backends/flash_attn.py + merge_attn_states.cu). The
    remaining per-request suffix runs the normal online-softmax page
    scan over a STATICALLY shortened slot range, and the two partial
    states merge exactly."""
    T, num_q_heads, head_dim = q.shape
    num_pages, num_kv_heads, page_size, _ = k_pages.shape
    group = num_q_heads // num_kv_heads
    S = shared_page_ids.shape[0]
    pages_per_req = block_tables.shape[1]

    qg = (q.reshape(T, num_kv_heads, group, head_dim)
          .astype(jnp.float32) * sm_scale)

    # ---- shared phase: dense attention over the common S pages ----
    m_sh, l_sh, acc_sh = _shared_prefix_state(q, k_pages, v_pages,
                                              shared_page_ids, q_pos,
                                              sm_scale)
    m_sh = m_sh.reshape(T, num_kv_heads, group, 1)
    l_sh = l_sh.reshape(T, num_kv_heads, group, 1)
    acc_sh = acc_sh.reshape(T, num_kv_heads, group, head_dim)

    # ---- suffix phase: the usual scan, slots [S, pages_per_req) ----
    token_pages = block_tables[req_idx]

    def body(carry, page_i):
        m, l, acc = carry
        page_ids = token_pages[:, page_i]
        k_blk = k_pages[page_ids, ..., :head_dim].astype(jnp.float32)
        v_blk = v_pages[page_ids, ..., :head_dim].astype(jnp.float32)
        s = jnp.einsum("thgd,thpd->thgp", qg, k_blk)
        pos = page_i * page_size + jnp.arange(page_size, dtype=jnp.int32)
        ok = pos[None, :] <= q_pos[:, None]
        s = jnp.where(ok[:, None, None, :], s, _MASK_VALUE)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        pj = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + pj.sum(axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum("thgp,thpd->thgd", pj, v_blk)
        return (m_new, l_new, acc_new), None

    m0 = jnp.full((T, num_kv_heads, group, 1), _MASK_VALUE, jnp.float32)
    l0 = jnp.zeros((T, num_kv_heads, group, 1), jnp.float32)
    acc0 = jnp.zeros((T, num_kv_heads, group, head_dim), jnp.float32)
    (m_sf, l_sf, acc_sf), _ = jax.lax.scan(
        body, (m0, l0, acc0),
        jnp.arange(S, pages_per_req, dtype=jnp.int32))

    _, l, acc = merge_attention_states((m_sh, l_sh, acc_sh),
                                       (m_sf, l_sf, acc_sf))
    out = acc / jnp.maximum(l, 1e-20)
    return out.reshape(T, num_q_heads, head_dim).astype(q.dtype)


def naive_ragged_attention(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    block_tables: jax.Array,
    req_idx: jax.Array,
    q_pos: jax.Array,
    *,
    sm_scale: float,
    window: int = 0,
    logit_cap: float = 0.0,
    alibi_slopes: tuple = None,
    sinks: jax.Array = None,
) -> jax.Array:
    """O(T * max_kv) dense-gather reference used only by unit tests."""
    T, num_q_heads, head_dim = q.shape
    num_pages, num_kv_heads, page_size, _ = k_pages.shape
    group = num_q_heads // num_kv_heads
    pages_per_req = block_tables.shape[1]
    max_kv = pages_per_req * page_size

    token_pages = block_tables[req_idx]  # [T, P]
    # Gather each token's full KV run: [T, P, Hkv, ps, D] -> [T, Hkv, max_kv, D]
    k_all = jnp.moveaxis(k_pages[token_pages, ..., :head_dim], 2,
                         1).reshape(T, num_kv_heads, max_kv, head_dim)
    v_all = jnp.moveaxis(v_pages[token_pages, ..., :head_dim], 2,
                         1).reshape(T, num_kv_heads, max_kv, head_dim)
    qg = q.reshape(T, num_kv_heads, group, head_dim).astype(jnp.float32)
    scores = jnp.einsum("thgd,thjd->thgj", qg * sm_scale,
                        k_all.astype(jnp.float32))
    if logit_cap > 0:
        scores = logit_cap * jnp.tanh(scores / logit_cap)
    kv_pos = jnp.arange(max_kv, dtype=jnp.int32)
    if alibi_slopes is not None:
        slopes = jnp.asarray(alibi_slopes, jnp.float32).reshape(
            num_kv_heads, group)
        dist = (kv_pos[None, :] - q_pos[:, None]).astype(jnp.float32)
        scores = scores + slopes[None, :, :, None] * dist[:, None, None, :]
    valid = kv_pos[None, :] <= q_pos[:, None]
    if window > 0:
        valid &= kv_pos[None, :] > (q_pos[:, None] - window)
    scores = jnp.where(valid[:, None, None, :], scores, _MASK_VALUE)
    if sinks is not None:
        sk = sinks.astype(jnp.float32).reshape(num_kv_heads, group)
        m = scores.max(axis=-1, keepdims=True)
        p = jnp.exp(scores - m)
        denom = p.sum(axis=-1, keepdims=True) + jnp.exp(
            sk[None, :, :, None] - m)
        weights = p / denom
    else:
        weights = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("thgj,thjd->thgd", weights, v_all.astype(jnp.float32))
    return out.reshape(T, num_q_heads, head_dim).astype(q.dtype)


def resolve_attention_backend() -> str:
    """Pick the attention implementation: 'pallas' on TPU (or when
    interpret-mode testing requests it), 'xla' otherwise (reference:
    vllm/attention/selector.py:109 get_attn_backend / platforms/tpu.py:45).

    The platform is taken from the engine mesh when one is set (the
    process default backend can be TPU while a test mesh runs on virtual
    CPU devices), else from the default backend."""
    backend = envs.VDT_ATTENTION_BACKEND
    if backend == "auto":
        from vllm_distributed_tpu.parallel import mesh as mesh_state
        if mesh_state.has_global_mesh():
            platform = next(iter(
                mesh_state.get_global_mesh().devices.flat)).platform
        else:
            platform = jax.default_backend()
        return "pallas" if platform == "tpu" else "xla"
    return backend


def _scatter_kv_flat(k_all, v_all, k_new, v_new, slot, layer, PS):
    """Contiguous-row scatter of new K/V into the flattened stacked cache
    (XLA path; slots < 0 drop)."""
    L, N, KVH, _, D = k_all.shape
    T = k_new.shape[0]
    k_new = _pad_last_dim(k_new, D)
    v_new = _pad_last_dim(v_new, D)
    page = slot // PS
    off = slot % PS
    rows = (((layer[0] * N + page[:, None]) * KVH +
             jnp.arange(KVH, dtype=jnp.int32)[None, :]) * PS +
            off[:, None])
    total = L * N * KVH * PS
    rows = jnp.where(slot[:, None] < 0, total, rows).reshape(-1)
    k_flat = k_all.reshape(total, D)
    v_flat = v_all.reshape(total, D)
    k_flat = k_flat.at[rows].set(
        k_new.reshape(T * KVH, D).astype(k_flat.dtype), mode="drop")
    v_flat = v_flat.at[rows].set(
        v_new.reshape(T * KVH, D).astype(v_flat.dtype), mode="drop")
    return k_flat.reshape(k_all.shape), v_flat.reshape(v_all.shape)


def _tknp_cache_specs():
    from jax.sharding import PartitionSpec as P

    from vllm_distributed_tpu.config import (MESH_AXIS_MODEL,
                                             MESH_AXIS_TOKEN)
    cache = P(None, MESH_AXIS_TOKEN, MESH_AXIS_MODEL, None, None)
    heads = P(None, MESH_AXIS_MODEL, None)
    return cache, heads, MESH_AXIS_TOKEN


def _tknp_apply_new_kv(k_all_, v_all_, k_new_, v_new_, kv_runs_,
                       n_runs_, slot_, layer, use_pallas):
    """Apply one rank's KV-write runs/slots to its cache shard — the
    per-rank body shared by the raw and quantized (tknp_kv) shuffle
    paths, so the halo-pad layout and the pallas-vs-scatter branch
    can never diverge between them."""
    PS, D = k_all_.shape[3], k_all_.shape[4]
    if use_pallas:
        from vllm_distributed_tpu.ops.pallas_kv_write import (
            write_kv_pages_pallas)
        pad = [(0, 0), (PS, 2 * PS), (0, 0)]
        k_hl = jnp.pad(_pad_last_dim(k_new_, D).swapaxes(0, 1), pad)
        v_hl = jnp.pad(_pad_last_dim(v_new_, D).swapaxes(0, 1), pad)
        return write_kv_pages_pallas(
            k_all_, v_all_, k_hl.astype(k_all_.dtype),
            v_hl.astype(v_all_.dtype), kv_runs_, n_runs_, layer)
    return _scatter_kv_flat(k_all_, v_all_, k_new_, v_new_, slot_,
                            layer, PS)


def _write_kv_cache_tknp(k_all, v_all, k_new, v_new, batch, layer):
    """Token-parallel KV write: the cache page axis is sharded over the
    ``token`` mesh axis; each rank applies only its own KV-write runs /
    slots (local page ids, prepared by the runner — TPU analogue of the
    fork's per-rank KV write path).

    The KV-write SHUFFLE — the step's new K/V rows crossing the
    shard_map boundary to the page-owning rank — is the last raw
    collective of ROADMAP item 5: under VDT_QCOMM_PATHS "tknp_kv" the
    payload crosses as block-scaled int8 + fp32 scales (quantized
    BEFORE the boundary, dequantized per-rank after), with the standard
    no-win fallback counting."""
    from vllm_distributed_tpu.parallel import collectives
    from vllm_distributed_tpu.parallel import mesh as mesh_state
    tk = batch.tknp
    use_pallas = resolve_attention_backend() == "pallas"
    cache_spec, new_spec, token_axis = _tknp_cache_specs()
    from jax.sharding import PartitionSpec as P

    q_pack = collectives.kv_shuffle_quantize(
        k_new, v_new, mesh_state.tknp_size())
    if q_pack is not None:
        return _write_kv_cache_tknp_quant(k_all, v_all, q_pack,
                                          k_new.dtype, batch, layer,
                                          use_pallas)

    def call(k_all_, v_all_, k_new_, v_new_, kv_runs_, n_runs_, slot_):
        return _tknp_apply_new_kv(k_all_, v_all_, k_new_, v_new_,
                                  kv_runs_[0], n_runs_[0], slot_[0],
                                  layer, use_pallas)

    return shard_map(
        call, mesh=mesh_state.get_global_mesh(),
        in_specs=(cache_spec, cache_spec, new_spec, new_spec,
                  P(token_axis, None, None), P(token_axis, None),
                  P(token_axis, None)),
        out_specs=(cache_spec, cache_spec),
        check_vma=False)(k_all, v_all, k_new, v_new, tk.kv_runs,
                         tk.num_kv_runs, tk.slot_mapping)


def _write_kv_cache_tknp_quant(k_all, v_all, q_pack, new_dtype, batch,
                               layer, use_pallas):
    """Quantized TKNP KV-write shuffle: the int8 payload + fp32 scales
    cross the token-axis shard_map boundary instead of the model-dtype
    K/V rows; each rank dequantizes and applies its own page runs.
    Cache writes land the quantized round-trip of the new rows — the
    same bounded per-block divergence the other VDT_QCOMM paths carry
    (tests/ops/test_quantized_comms.py pins the bound)."""
    from vllm_distributed_tpu.config import MESH_AXIS_MODEL
    from vllm_distributed_tpu.parallel import collectives
    from vllm_distributed_tpu.parallel import mesh as mesh_state
    tk = batch.tknp
    cache_spec, _new_spec, token_axis = _tknp_cache_specs()
    from jax.sharding import PartitionSpec as P
    k_q, k_s, v_q, v_s = q_pack
    # Payload [T, KVH, D/b, b] + scales [T, KVH, D/b, 1]: kv heads stay
    # sharded over the model axis, replication over the token axis is
    # the (now int8) shuffle leg.
    pay_spec = P(None, MESH_AXIS_MODEL, None, None)

    def call(k_all_, v_all_, k_q_, k_s_, v_q_, v_s_, kv_runs_, n_runs_,
             slot_):
        k_new_, v_new_ = collectives.kv_shuffle_dequantize(
            k_q_, k_s_, v_q_, v_s_, new_dtype)
        return _tknp_apply_new_kv(k_all_, v_all_, k_new_, v_new_,
                                  kv_runs_[0], n_runs_[0], slot_[0],
                                  layer, use_pallas)

    return shard_map(
        call, mesh=mesh_state.get_global_mesh(),
        in_specs=(cache_spec, cache_spec, pay_spec, pay_spec, pay_spec,
                  pay_spec, P(token_axis, None, None),
                  P(token_axis, None), P(token_axis, None)),
        out_specs=(cache_spec, cache_spec),
        check_vma=False)(k_all, v_all, k_q, k_s, v_q, v_s, tk.kv_runs,
                         tk.num_kv_runs, tk.slot_mapping)


def write_kv_cache(
    k_all: jax.Array,  # [L, N, KVH, PS, D]
    v_all: jax.Array,
    k_new: jax.Array,  # [T, KVH, d_model]
    v_new: jax.Array,
    batch,  # AttentionBatch
    layer: jax.Array,  # [1] int32
) -> tuple[jax.Array, jax.Array]:
    """Write the step's K/V into layer ``layer`` of the stacked cache.

    Pallas path: in-place aliased page RMW kernel (no cache copy; see
    ops/pallas_kv_write.py). XLA path: flat row scatter with a layer
    offset (CPU tests / debugging). Token-parallel batches route to the
    page-sharded per-rank write.
    """
    if getattr(batch, "tknp", None) is not None:
        return _write_kv_cache_tknp(k_all, v_all, k_new, v_new, batch,
                                    layer)
    L, N, KVH, PS, D = k_all.shape
    if (resolve_attention_backend() == "pallas"
            and k_all.dtype not in _FP8_DTYPES
            and getattr(batch, "kv_runs", None) is not None):
        from vllm_distributed_tpu.ops.pallas_kv_write import (
            write_kv_pages_pallas)

        def call(k_all_, v_all_, k_new_, v_new_):
            pad = [(0, 0), (PS, 2 * PS), (0, 0)]
            k_hl = jnp.pad(
                _pad_last_dim(k_new_, D).swapaxes(0, 1), pad)
            v_hl = jnp.pad(
                _pad_last_dim(v_new_, D).swapaxes(0, 1), pad)
            return write_kv_pages_pallas(
                k_all_, v_all_, k_hl.astype(k_all_.dtype),
                v_hl.astype(v_all_.dtype), batch.kv_runs,
                batch.num_kv_runs, layer)

        from vllm_distributed_tpu.config import MESH_AXIS_MODEL
        from vllm_distributed_tpu.parallel import mesh as mesh_state
        if mesh_state.has_global_mesh() and mesh_state.tp_size() > 1:
            from jax.sharding import PartitionSpec as P
            cache_spec = P(None, None, MESH_AXIS_MODEL, None, None)
            new_spec = P(None, MESH_AXIS_MODEL, None)
            return shard_map(
                call, mesh=mesh_state.get_global_mesh(),
                in_specs=(cache_spec, cache_spec, new_spec, new_spec),
                out_specs=(cache_spec, cache_spec),
                check_vma=False)(k_all, v_all, k_new, v_new)
        return call(k_all, v_all, k_new, v_new)

    # XLA fallback: contiguous-row scatter over the flattened cache.
    return _scatter_kv_flat(k_all, v_all, k_new, v_new,
                            batch.slot_mapping, layer, PS)


def _paged_attention_tknp(q, k_pages, v_pages, batch, *, sm_scale, layer):
    """Token-parallel attention: each ``token``-axis rank computes
    attention only for the requests whose KV pages live in its shard
    (per-rank compacted seq lists / local page tables built by the
    runner), zeroes the rows it does not own, and a psum over the token
    axis merges the disjoint per-rank outputs.

    This is the SPMD re-expression of the fork's TKNP decode-attention
    scaling (token_parallel_linear.py:39 scatter -> per-rank attention on
    local KV -> gather): activations stay replicated over the token axis
    (no scatter/gather), while KV memory and attention FLOPs/bandwidth
    split K ways.
    """
    from vllm_distributed_tpu.parallel import mesh as mesh_state
    tk = batch.tknp
    head_dim = q.shape[-1]
    use_pallas = (resolve_attention_backend() == "pallas"
                  and batch.seq_info is not None)
    cache_spec, head_spec, token_axis = _tknp_cache_specs()
    from jax.sharding import PartitionSpec as P

    unified = use_pallas and getattr(tk, "desc", None) is not None

    def call(q_, k_, v_, seq_info_, num_seqs_, bt_, slot_, desc_, dl_):
        seq_info_ = seq_info_[0]
        num_seqs_ = num_seqs_[0]
        bt_ = bt_[0]
        slot_ = slot_[0]
        if unified:
            from vllm_distributed_tpu.ops.pallas_attention import (
                unified_ragged_paged_attention_pallas)
            q_p = _pad_last_dim(q_, k_.shape[-1])
            out = unified_ragged_paged_attention_pallas(
                q_p, k_, v_, desc_[0], seq_info_, dl_[0], bt_, layer,
                sm_scale=sm_scale, bq=batch.attn_bq,
                sb=batch.attn_sb)[..., :head_dim]
        elif use_pallas:
            from vllm_distributed_tpu.ops.pallas_attention import (
                ragged_paged_attention_pallas)
            q_p = _pad_last_dim(q_, k_.shape[-1])
            out = ragged_paged_attention_pallas(
                q_p, k_, v_, seq_info_, num_seqs_, bt_, layer,
                sm_scale=sm_scale, max_q=batch.max_q)[..., :head_dim]
        else:
            out = ragged_paged_attention(
                q_, k_[layer[0]], v_[layer[0]], bt_, batch.req_idx,
                batch.positions, sm_scale=sm_scale)
        # Zero rows this rank does not own (incl. padding / kernel spill),
        # then merge the disjoint rank outputs. The psum is the decode
        # hot path's dominant wire cost; VDT_QCOMM ships it block-scaled
        # int8 (parallel/collectives.py).
        out = jnp.where((slot_ >= 0)[:, None, None], out, 0)
        from vllm_distributed_tpu.parallel import collectives
        return collectives.psum(out, token_axis, path="tknp")

    K = tk.seq_info.shape[0]
    desc = tk.desc if unified else jnp.zeros((K, 1, 3), jnp.int32)
    dl = (tk.decode_list if unified
          else jnp.zeros((K, tk.seq_info.shape[1]), jnp.int32))
    return shard_map(
        call, mesh=mesh_state.get_global_mesh(),
        in_specs=(head_spec, cache_spec, cache_spec,
                  P(token_axis, None, None), P(token_axis, None),
                  P(token_axis, None, None), P(token_axis, None),
                  P(token_axis, None, None), P(token_axis, None)),
        out_specs=head_spec,
        check_vma=False)(q, k_pages, v_pages, tk.seq_info, tk.num_seqs,
                         tk.block_tables, tk.slot_mapping, desc, dl)


def _pallas_cascade(q, q_p, k_all, v_all, batch, layer, sm_scale,
                    head_dim):
    """Cascade attention on the Pallas backend: the batch-wide shared
    prefix runs as ONE dense XLA phase (a single gather + MXU matmuls —
    there is nothing a kernel would add over XLA's own fusion here),
    the per-request suffix runs the Pallas kernel over a block table
    with the shared slots stripped and kv_len shifted (relative
    causality is preserved), and the kernel's exported (m, l) state
    merges the two exactly (reference: flash_attn.py cascade +
    merge_attn_states.cu). With a partition descriptor on the batch the
    suffix phase runs the mega-kernel (decode rows keep SB batching and
    export their state too); the descriptor is reused verbatim — only
    kv_len shifts, which the kernel reads dynamically."""
    shared = batch.cascade_shared_ids
    S = shared.shape[0]
    page_size = k_all.shape[3]
    D = k_all.shape[-1]
    k_layer = k_all[layer[0]]
    v_layer = v_all[layer[0]]
    m_sh, l_sh, acc_sh = _shared_prefix_state(
        q, k_layer, v_layer, shared, batch.positions, sm_scale)

    shift = S * page_size
    si = batch.seq_info
    si_sfx = si.at[:, 2].set(jnp.maximum(si[:, 2] - shift, 0))
    if getattr(batch, "attn_desc", None) is not None:
        from vllm_distributed_tpu.ops.pallas_attention import (
            unified_ragged_paged_attention_pallas)
        out_sf, st_sf = unified_ragged_paged_attention_pallas(
            q_p, k_all, v_all, batch.attn_desc, si_sfx,
            batch.decode_list, batch.block_tables[:, S:], layer,
            sm_scale=sm_scale, bq=batch.attn_bq, sb=batch.attn_sb,
            emit_state=True)
    else:
        from vllm_distributed_tpu.ops.pallas_attention import (
            ragged_paged_attention_pallas)
        out_sf, st_sf = ragged_paged_attention_pallas(
            q_p, k_all, v_all, si_sfx, batch.num_seqs,
            batch.block_tables[:, S:], layer, sm_scale=sm_scale,
            max_q=batch.max_q, emit_state=True)
    m_sf = st_sf[..., 0:1]                      # [T, QH, 1] f32
    l_sf = st_sf[..., D // 2:D // 2 + 1]
    acc_sf = out_sf[..., :head_dim].astype(jnp.float32) * l_sf

    _, l, acc = merge_attention_states((m_sh, l_sh, acc_sh),
                                       (m_sf, l_sf, acc_sf))
    return (acc / jnp.maximum(l, 1e-20)).astype(q.dtype)


def paged_attention(
    q: jax.Array,  # [T, num_q_heads, head_dim]
    k_pages: jax.Array,  # [L, N, KVH, PS, D] stacked cache
    v_pages: jax.Array,
    batch,  # AttentionBatch
    *,
    sm_scale: float,
    layer: jax.Array | None = None,  # [1] int32
    window: int = 0,  # sliding window; 0 = full causal
    logit_cap: float = 0.0,  # attn logit soft-capping; 0 = off
    alibi_slopes: tuple = None,  # Bloom/MPT ALiBi; None = off
    sinks: jax.Array = None,  # gpt-oss attention sinks; None = off
) -> jax.Array:
    """Unified entry used by every model's attention layer; dispatches to
    the Pallas kernel or the XLA reference path per backend selection.
    Sliding-window models take the XLA path (the Pallas kernel's
    per-sequence runs don't carry a window bound yet).

    On a >1-wide tensor-parallel mesh the Pallas call is wrapped in
    shard_map over the "model" (head) axis — pallas_call is opaque to
    GSPMD, so the kernel must be launched per-shard with local head counts
    (the TPU analogue of the reference's per-rank attention backends).
    """
    if layer is None:
        layer = jnp.zeros((1, ), jnp.int32)
    if getattr(batch, "tknp", None) is not None:
        if (window or logit_cap or alibi_slopes or sinks is not None
                or k_pages.dtype in _FP8_DTYPES):
            raise NotImplementedError(
                "sliding window / softcap / ALiBi / sinks / fp8 KV under token "
                "parallelism (the per-rank attention path carries none "
                "of these; models/loader.py get_model rejects the "
                "combinations at admission — this trace-time guard is "
                "the backstop)")
        return _paged_attention_tknp(q, k_pages, v_pages, batch,
                                     sm_scale=sm_scale, layer=layer)
    # Sliding window / softcap / ALiBi / sinks fold into the unified
    # mega-kernel (per-layer statics + the [2, QH] head-feature sidecar)
    # — Gemma/Mistral/Bloom/gpt-oss-class models reach the Pallas path
    # whenever the batch carries a partition descriptor. Feature waves
    # WITHOUT a descriptor (in-jit multi-step/EAGLE batches) and fp8 KV
    # keep the XLA reference below.
    features = bool(window or logit_cap or alibi_slopes is not None
                    or sinks is not None)
    if (k_pages.dtype not in _FP8_DTYPES
            and resolve_attention_backend() == "pallas"
            and batch.seq_info is not None
            and (not features
                 or (getattr(batch, "attn_desc", None) is not None
                     and getattr(batch, "cascade_shared_ids", None)
                     is None))):
        head_dim = q.shape[-1]
        feat = build_head_feat(q.shape[1], alibi_slopes, sinks)

        def call(q_, k_, v_, feat_):
            # Cache storage may be lane-padded (storage_head_dim); pad q to
            # match and slice the padding back off the output.
            q_p = _pad_last_dim(q_, k_.shape[-1])
            shared = getattr(batch, "cascade_shared_ids", None)
            if shared is not None:
                out = _pallas_cascade(q_, q_p, k_, v_, batch, layer,
                                      sm_scale, head_dim)
            elif getattr(batch, "attn_desc", None) is not None:
                # Mixed-batch mega-kernel: one call, prefill q-tiles +
                # SB decode groups partitioned by the host descriptor —
                # decode rows keep MXU-filling batching even when a
                # chunked-prefill chunk shares the wave, and no kernel
                # static depends on the batch composition.
                from vllm_distributed_tpu.ops.pallas_attention import (
                    unified_ragged_paged_attention_pallas)
                out = unified_ragged_paged_attention_pallas(
                    q_p, k_, v_, batch.attn_desc, batch.seq_info,
                    batch.decode_list, batch.block_tables, layer, feat_,
                    sm_scale=sm_scale, bq=batch.attn_bq,
                    sb=batch.attn_sb, window=window,
                    logit_cap=logit_cap,
                    has_alibi=alibi_slopes is not None,
                    has_sinks=sinks is not None)[..., :head_dim]
            else:
                from vllm_distributed_tpu.ops.pallas_attention import (
                    ragged_paged_attention_pallas)
                out = ragged_paged_attention_pallas(
                    q_p, k_, v_, batch.seq_info, batch.num_seqs,
                    batch.block_tables, layer, sm_scale=sm_scale,
                    max_q=batch.max_q)[..., :head_dim]
            # Rows the kernel never writes (padding tokens, tile spill past
            # the last sequence) are uninitialized HBM — possibly NaN/Inf
            # bit patterns. Zero them so garbage can't propagate through
            # later layers' projections (padding tokens have slot -1).
            valid = (batch.slot_mapping >= 0)[:, None, None]
            return jnp.where(valid, out, 0)

        from vllm_distributed_tpu.config import MESH_AXIS_MODEL
        from vllm_distributed_tpu.parallel import mesh as mesh_state
        if (mesh_state.has_global_mesh()
                and mesh_state.tp_size() > 1):
            from jax.sharding import PartitionSpec as P
            head_spec = P(None, MESH_AXIS_MODEL, None)
            kv_spec = P(None, None, MESH_AXIS_MODEL, None, None)
            return shard_map(
                call, mesh=mesh_state.get_global_mesh(),
                in_specs=(head_spec, kv_spec, kv_spec,
                          P(None, MESH_AXIS_MODEL)),
                out_specs=head_spec, check_vma=False)(q, k_pages,
                                                      v_pages, feat)
        return call(q, k_pages, v_pages, feat)
    if k_pages.ndim == 5:
        k_layer = k_pages[layer[0]]
        v_layer = v_pages[layer[0]]
    else:
        k_layer, v_layer = k_pages, v_pages
    if (window == 0 and logit_cap == 0 and alibi_slopes is None
            and sinks is None
            and getattr(batch, "cascade_shared_ids", None) is not None):
        return cascade_ragged_paged_attention(
            q, k_layer, v_layer, batch.block_tables, batch.req_idx,
            batch.positions, batch.cascade_shared_ids,
            sm_scale=sm_scale)
    return ragged_paged_attention(q, k_layer, v_layer, batch.block_tables,
                                  batch.req_idx, batch.positions,
                                  sm_scale=sm_scale, window=window,
                                  logit_cap=logit_cap,
                                  alibi_slopes=alibi_slopes, sinks=sinks)


def write_kv_and_attend(
    q: jax.Array,  # [T, num_q_heads, head_dim]
    k_pages: jax.Array,  # [L, N, KVH, PS, D] stacked cache
    v_pages: jax.Array,
    k_new: jax.Array,  # [T, KVH, head_dim]
    v_new: jax.Array,
    batch,  # AttentionBatch
    *,
    sm_scale: float,
    layer: jax.Array,  # [1] int32
    window: int = 0,
    logit_cap: float = 0.0,
    alibi_slopes: tuple = None,
    sinks: jax.Array = None,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """KV-page write + attention for one layer, fused into ONE Pallas
    pass over the cache when the layout permits: the mega-kernel's
    kind-3 programs land the step's new K/V pages in place (aliased),
    and the attention programs that follow in the sequential grid read
    them — a mixed step makes one pass over the KV cache instead of two.
    Returns (k_pages, v_pages, attn_out).

    Falls back to write_kv_cache + paged_attention whenever the layout
    rules the fused kernel out (fp8 KV / token parallelism / cascade),
    when the batch has no partition descriptor (in-jit batches from the
    multi-step scan or EAGLE), or when VDT_FUSED_KV_WRITE=0. Sliding
    window / softcap / ALiBi / sinks ride the kernel's per-layer
    statics + head-feature sidecar and no longer force the XLA path."""
    fused = (envs.VDT_FUSED_KV_WRITE
             and k_pages.dtype not in _FP8_DTYPES
             and getattr(batch, "tknp", None) is None
             and getattr(batch, "cascade_shared_ids", None) is None
             and getattr(batch, "attn_desc", None) is not None
             and getattr(batch, "kv_runs", None) is not None
             and resolve_attention_backend() == "pallas")
    if not fused:
        k_pages, v_pages = write_kv_cache(k_pages, v_pages, k_new, v_new,
                                          batch, layer)
        out = paged_attention(q, k_pages, v_pages, batch,
                              sm_scale=sm_scale, layer=layer,
                              window=window, logit_cap=logit_cap,
                              alibi_slopes=alibi_slopes, sinks=sinks)
        return k_pages, v_pages, out

    from vllm_distributed_tpu.ops.pallas_attention import (
        unified_write_attend_pallas)
    L, N, KVH, PS, D = k_pages.shape
    head_dim = q.shape[-1]
    feat = build_head_feat(q.shape[1], alibi_slopes, sinks)

    def call(q_, k_, v_, kn_, vn_, feat_):
        pad = [(0, 0), (PS, 2 * PS), (0, 0)]
        k_hl = jnp.pad(_pad_last_dim(kn_, D).swapaxes(0, 1),
                       pad).astype(k_.dtype)
        v_hl = jnp.pad(_pad_last_dim(vn_, D).swapaxes(0, 1),
                       pad).astype(v_.dtype)
        q_p = _pad_last_dim(q_, D)
        out, k2, v2 = unified_write_attend_pallas(
            q_p, k_, v_, k_hl, v_hl, batch.attn_desc, batch.seq_info,
            batch.decode_list, batch.kv_runs, batch.block_tables, layer,
            feat_, sm_scale=sm_scale, bq=batch.attn_bq,
            sb=batch.attn_sb, window=window, logit_cap=logit_cap,
            has_alibi=alibi_slopes is not None,
            has_sinks=sinks is not None)
        out = out[..., :head_dim]
        # Rows no program wrote (padding tokens) are uninitialized HBM;
        # zero them so garbage can't reach later layers' projections.
        valid = (batch.slot_mapping >= 0)[:, None, None]
        return k2, v2, jnp.where(valid, out, 0)

    from vllm_distributed_tpu.config import MESH_AXIS_MODEL
    from vllm_distributed_tpu.parallel import mesh as mesh_state
    if mesh_state.has_global_mesh() and mesh_state.tp_size() > 1:
        from jax.sharding import PartitionSpec as P
        head_spec = P(None, MESH_AXIS_MODEL, None)
        cache_spec = P(None, None, MESH_AXIS_MODEL, None, None)
        return shard_map(
            call, mesh=mesh_state.get_global_mesh(),
            in_specs=(head_spec, cache_spec, cache_spec, head_spec,
                      head_spec, P(None, MESH_AXIS_MODEL)),
            out_specs=(cache_spec, cache_spec, head_spec),
            check_vma=False)(q, k_pages, v_pages, k_new, v_new, feat)
    return call(q, k_pages, v_pages, k_new, v_new, feat)
