"""Ragged paged attention over a paged KV cache — XLA implementation.

This is the TPU-native equivalent of the reference's unified attention path
(vllm/attention/layer.py:398 ``unified_attention`` dispatching to the CUDA
paged-attention kernels in csrc/attention/ and, on its TPU backend, to
torch.ops.xla.ragged_paged_attention — v1/attention/backends/pallas.py:232).

Two ops:

* ``write_kv_pages`` — scatter newly-computed K/V for a flat batch of
  tokens into the paged cache via a precomputed slot mapping (equivalent of
  csrc/cache_kernels.cu reshape_and_cache, pallas_kv_cache_update.py).
  On TPU this lowers to a dynamic-update-scatter XLA handles well.

* ``ragged_paged_attention`` — token-centric unified prefill/decode
  attention: every query token attends to its request's pages up to its own
  position. Implemented as a lax.scan over page indices with an online
  (flash-style) softmax so peak memory is O(T * page_size) instead of
  O(T * max_model_len). Handles GQA, mixed prefill+decode in one batch,
  and same-step prefix sharing (KV must be written before calling).

A Pallas kernel (ops/pallas/) replaces the scan for performance; this XLA
version is the correctness reference and the CPU/interpret fallback
(selected via VDT_ATTENTION_BACKEND).
"""

from functools import partial

import jax
import jax.numpy as jnp

# Set to a large negative number rather than -inf so fully-masked rows
# produce 0-weight rows instead of NaNs.
_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def write_kv_pages(
    k_pages: jax.Array,  # [num_pages, page_size, num_kv_heads, head_dim]
    v_pages: jax.Array,  # [num_pages, page_size, num_kv_heads, head_dim]
    k_new: jax.Array,  # [T, num_kv_heads, head_dim]
    v_new: jax.Array,  # [T, num_kv_heads, head_dim]
    slot_mapping: jax.Array,  # [T] int32 flat slot = page*page_size + off
) -> tuple[jax.Array, jax.Array]:
    """Scatter new K/V rows into the paged cache.

    Padded tokens must carry an out-of-range slot (e.g. -1): scatter mode
    'drop' discards them.
    """
    num_pages, page_size, num_kv_heads, head_dim = k_pages.shape
    total_slots = num_pages * page_size
    flat_shape = (total_slots, num_kv_heads, head_dim)
    # JAX wraps negative indices; remap them out of range so mode='drop'
    # actually discards padding slots.
    slots = jnp.where(slot_mapping < 0, total_slots, slot_mapping)
    k_flat = k_pages.reshape(flat_shape)
    v_flat = v_pages.reshape(flat_shape)
    k_flat = k_flat.at[slots].set(k_new.astype(k_flat.dtype), mode="drop")
    v_flat = v_flat.at[slots].set(v_new.astype(v_flat.dtype), mode="drop")
    return (k_flat.reshape(k_pages.shape), v_flat.reshape(v_pages.shape))


@partial(jax.jit, static_argnames=("sm_scale", ))
def ragged_paged_attention(
    q: jax.Array,  # [T, num_q_heads, head_dim]
    k_pages: jax.Array,  # [num_pages, page_size, num_kv_heads, head_dim]
    v_pages: jax.Array,  # [num_pages, page_size, num_kv_heads, head_dim]
    block_tables: jax.Array,  # [max_reqs, pages_per_req] int32
    req_idx: jax.Array,  # [T] int32: owning request row per token
    q_pos: jax.Array,  # [T] int32: absolute position of each query token
    *,
    sm_scale: float,
) -> jax.Array:  # [T, num_q_heads, head_dim]
    """Unified ragged attention: token t attends to kv positions
    0..q_pos[t] of request req_idx[t] (causal over the paged cache)."""
    T, num_q_heads, head_dim = q.shape
    num_pages, page_size, num_kv_heads, _ = k_pages.shape
    assert num_q_heads % num_kv_heads == 0
    group = num_q_heads // num_kv_heads
    pages_per_req = block_tables.shape[1]

    # [T, Hkv, G, D] queries grouped by kv head.
    qg = q.reshape(T, num_kv_heads, group, head_dim).astype(jnp.float32)
    qg = qg * sm_scale
    # Per-token page lists: [T, pages_per_req].
    token_pages = block_tables[req_idx]

    def body(carry, page_i):
        m, l, acc = carry  # [T,Hkv,G,1], [T,Hkv,G,1], [T,Hkv,G,D]
        page_ids = token_pages[:, page_i]  # [T]
        k_blk = k_pages[page_ids].astype(jnp.float32)  # [T,ps,Hkv,D]
        v_blk = v_pages[page_ids].astype(jnp.float32)
        # scores [T, Hkv, G, ps]
        scores = jnp.einsum("thgd,tphd->thgp", qg, k_blk)
        kv_pos = page_i * page_size + jnp.arange(page_size, dtype=jnp.int32)
        valid = kv_pos[None, :] <= q_pos[:, None]  # [T, ps] causal
        scores = jnp.where(valid[:, None, None, :], scores, _MASK_VALUE)

        m_new = jnp.maximum(m, scores.max(axis=-1, keepdims=True))
        p = jnp.exp(scores - m_new)  # [T,Hkv,G,ps]
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum("thgp,tphd->thgd", p, v_blk)
        return (m_new, l_new, acc_new), None

    m0 = jnp.full((T, num_kv_heads, group, 1), _MASK_VALUE, jnp.float32)
    l0 = jnp.zeros((T, num_kv_heads, group, 1), jnp.float32)
    acc0 = jnp.zeros((T, num_kv_heads, group, head_dim), jnp.float32)
    (m, l, acc), _ = jax.lax.scan(body, (m0, l0, acc0),
                                  jnp.arange(pages_per_req,
                                             dtype=jnp.int32))
    out = acc / jnp.maximum(l, 1e-20)
    return out.reshape(T, num_q_heads, head_dim).astype(q.dtype)


def naive_ragged_attention(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    block_tables: jax.Array,
    req_idx: jax.Array,
    q_pos: jax.Array,
    *,
    sm_scale: float,
) -> jax.Array:
    """O(T * max_kv) dense-gather reference used only by unit tests."""
    T, num_q_heads, head_dim = q.shape
    num_pages, page_size, num_kv_heads, _ = k_pages.shape
    group = num_q_heads // num_kv_heads
    pages_per_req = block_tables.shape[1]
    max_kv = pages_per_req * page_size

    token_pages = block_tables[req_idx]  # [T, P]
    # Gather each token's full KV run: [T, P, ps, Hkv, D] -> [T, max_kv, ...]
    k_all = k_pages[token_pages].reshape(T, max_kv, num_kv_heads, head_dim)
    v_all = v_pages[token_pages].reshape(T, max_kv, num_kv_heads, head_dim)
    qg = q.reshape(T, num_kv_heads, group, head_dim).astype(jnp.float32)
    scores = jnp.einsum("thgd,tjhd->thgj", qg * sm_scale,
                        k_all.astype(jnp.float32))
    kv_pos = jnp.arange(max_kv, dtype=jnp.int32)
    valid = kv_pos[None, :] <= q_pos[:, None]
    scores = jnp.where(valid[:, None, None, :], scores, _MASK_VALUE)
    weights = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("thgj,tjhd->thgd", weights, v_all.astype(jnp.float32))
    return out.reshape(T, num_q_heads, head_dim).astype(q.dtype)
