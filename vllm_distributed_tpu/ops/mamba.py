"""Selective-state-space (Mamba) ops over the flat ragged token batch.

The reference implements Mamba with CUDA kernels operating on a
[batch, seq] layout plus per-request state tensors indexed through the
block table (csrc/mamba/mamba_ssm/ selective scan;
vllm/v1/attention/backends/mamba_attn.py builds chunk metadata so
varlen prefills can share one kernel launch).

The TPU design takes a different route: the engine's native batch layout
is already a FLAT ragged token array [T] (each request's scheduled chunk
occupies a contiguous run — see worker/model_runner._prepare_inputs), so
the recurrence runs directly on it as a SEGMENTED associative scan:

    h_t = a_t * h_{t-1} + b_t          (elementwise in [Di, N])

with a reset flag raised at each request's first token. The combine

    (a1, b1, f1) ∘ (a2, b2, f2) =
        f2 ? (a2, b2, f2) : (a1*a2, a2*b1 + b2, f1|f2)

is associative, so ``jax.lax.associative_scan`` evaluates every
request's recurrence in O(log T) depth with no [R, max_q] dense buffer
(which would be quadratic in the worst case: R and max_q both scale
with the token bucket). Chunk-resumed prefills fold their carried state
into the drive term of the chunk's first token; the final state of each
request is scattered back from its last token. Decode, chunked prefill,
and mixed batches are all the same code path — one compiled program per
token bucket, exactly like the attention layers.

State tensors are indexed by INPUT-BATCH ROW (the runner's persistent
request slots), not through the page pool: SSM state is fixed-size per
request, so paging buys nothing — this is the TPU form of the
reference's MambaSpec "one block per request" cache
(vllm/v1/kv_cache_interface.py MambaSpec, block_size = max_model_len).
Row S (= max_reqs) is a dump slot for padding writes.

The state cache (core/state_cache.py) re-enters the scan mid-sequence
through exactly this machinery: a restore fills the request's state
rows before the forward, and because the restored request is admitted
as a continuation (chunk_pos0 > 0), ``build_segment_info`` raises its
``has_init`` flag and the scan folds the restored carry into the
chunk's first token — no scan-side special case exists or is needed.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp


@dataclasses.dataclass
class SegmentInfo:
    """Per-token segment metadata for stateful (scan) layers, built once
    per forward from the AttentionBatch (ops/mamba.build_segment_info).

    All fields are device arrays with static shapes; ``row`` routes
    padding tokens to the dump slot S so every scatter stays masked.
    """

    # [T] int32: state-slot row per token (== input-batch row; S for
    # padding tokens).
    row: jax.Array
    # [T] bool: real (non-padding) token.
    valid: jax.Array
    # [T] int32: offset of the token within its request's scheduled
    # chunk (garbage at padding).
    off: jax.Array
    # [T] bool: first / last token of its request's chunk.
    start: jax.Array
    end: jax.Array
    # [T] bool: the token's request carries resumable state (its chunk
    # does not begin at position 0).
    has_init: jax.Array
    # [S+1] int32: scheduled chunk length per state row (0 = inactive).
    q_len_by_row: jax.Array
    # [S+1] int32: flat index of the chunk's first token per state row
    # (garbage where inactive).
    q_start_by_row: jax.Array
    # [S+1] bool: the row's chunk resumes carried state.
    has_init_by_row: jax.Array


jax.tree_util.register_dataclass(
    SegmentInfo,
    data_fields=[f.name for f in dataclasses.fields(SegmentInfo)],
    meta_fields=[],
)


def build_segment_info(batch, num_state_rows: int) -> SegmentInfo:
    """Derive SegmentInfo from an AttentionBatch.

    ``num_state_rows`` is S (the runner's max_num_reqs); tokens of
    inactive rows and padding scatter to dump row S.
    """
    T = batch.req_idx.shape[0]
    S = num_state_rows
    valid = batch.slot_mapping >= 0
    row = jnp.where(valid, batch.req_idx, S)

    # Per-row chunk geometry from seq_info (active rows only; inactive
    # seq_info rows are zero and must not clobber row 0).
    si = batch.seq_info  # [max_reqs, 4] = (q_start, q_len, kv_len, row)
    n_active = batch.num_seqs[0]
    idx = jnp.where(
        jnp.arange(si.shape[0]) < n_active, si[:, 3], S)
    q_start_by_row = jnp.zeros((S + 1, ), jnp.int32).at[idx].set(si[:, 0])
    q_len_by_row = jnp.zeros((S + 1, ), jnp.int32).at[idx].set(si[:, 1])
    # Position of the chunk's first token = kv_len - q_len.
    chunk_pos0 = jnp.zeros((S + 1, ), jnp.int32).at[idx].set(
        si[:, 2] - si[:, 1])

    off = batch.positions - chunk_pos0[row]
    q_len_tok = q_len_by_row[row]
    start = valid & (off == 0)
    end = valid & (off == q_len_tok - 1)
    has_init = valid & (chunk_pos0[row] > 0)
    return SegmentInfo(row=row, valid=valid, off=off, start=start,
                       end=end, has_init=has_init,
                       q_len_by_row=q_len_by_row,
                       q_start_by_row=q_start_by_row,
                       has_init_by_row=(q_len_by_row > 0)
                       & (chunk_pos0 > 0))


def _bshape(flag: jax.Array, like: jax.Array) -> jax.Array:
    """Reshape a [T] flag for broadcasting against [T, ...]."""
    return flag.reshape(flag.shape + (1, ) * (like.ndim - 1))


def segmented_linear_scan(a: jax.Array, b: jax.Array,
                          reset: jax.Array) -> jax.Array:
    """h_t = a_t * h_{t-1} + b_t with h reset to 0 where ``reset``.

    a, b: [T, ...] (elementwise recurrence), reset: [T] bool.
    Returns h: [T, ...]. O(log T) depth via associative_scan.
    """
    # The flag leaf stays [T, 1, ...] — associative_scan only requires
    # equal length along the scanned axis, and a broadcastable flag
    # keeps the combine's bookkeeping O(T) instead of O(T * state).
    f = _bshape(reset, a)

    def combine(left, right):
        a1, b1, f1 = left
        a2, b2, f2 = right
        return (jnp.where(f2, a2, a1 * a2),
                jnp.where(f2, b2, a2 * b1 + b2),
                f1 | f2)

    _, h, _ = jax.lax.associative_scan(combine, (a, b, f), axis=0)
    return h


def causal_conv1d_ragged(
    x: jax.Array,  # [T, Di] pre-activation conv inputs
    weight: jax.Array,  # [K, Di] depthwise taps (tap 0 = oldest)
    bias: Optional[jax.Array],  # [Di] or None
    conv_state: jax.Array,  # [S+1, K-1, Di] carried inputs per row
    seg: SegmentInfo,
) -> tuple[jax.Array, jax.Array]:
    """Depthwise causal conv over the ragged batch with carried state.

    Within a chunk, tap j reads x[t - j]; reads that cross the chunk
    start come from ``conv_state`` (the last K-1 inputs before the
    chunk), or zero when the request starts at position 0 — the same
    left-pad the reference's causal_conv1d kernel applies.
    Returns (y [T, Di], new_conv_state).
    """
    T, Di = x.shape
    K = weight.shape[0]
    xz = jnp.where(_bshape(seg.valid, x), x, 0.0)

    y = jnp.broadcast_to(weight[K - 1] * xz, xz.shape)  # tap at lag 0
    for j in range(1, K):
        # In-chunk read: x[t - j] when the token is >= j deep.
        shifted = jnp.concatenate([jnp.zeros((j, Di), x.dtype),
                                   xz[:T - j]], axis=0)
        in_chunk = seg.off >= j
        # Carried read: conv_state[row, K-1 + off - j].
        cs_idx = jnp.clip(K - 1 + seg.off - j, 0, K - 2)
        carried = conv_state[seg.row, cs_idx]
        carried = jnp.where(_bshape(seg.has_init, carried), carried, 0.0)
        tap = jnp.where(_bshape(in_chunk, shifted), shifted, carried)
        y = y + weight[K - 1 - j] * tap
    if bias is not None:
        y = y + bias

    # New carried state per row: the last K-1 inputs of the chunk,
    # reaching back into the old state when the chunk is shorter.
    q_len = seg.q_len_by_row  # [S+1]
    q_start = seg.q_start_by_row
    i = jnp.arange(K - 1)
    # Wanted input offset within the chunk: q_len - (K-1) + i.
    want = q_len[:, None] - (K - 1) + i[None, :]  # [S+1, K-1]
    from_chunk = want >= 0
    flat_idx = jnp.clip(q_start[:, None] + want, 0, T - 1)
    chunk_vals = xz[flat_idx]  # [S+1, K-1, Di]
    old_idx = jnp.clip(q_len[:, None] + i[None, :], 0, K - 2)
    old_vals = jnp.take_along_axis(
        conv_state, jnp.broadcast_to(
            old_idx[:, :, None], (conv_state.shape[0], K - 1, 1)), axis=1)
    # Fresh chunks (position 0) left-pad with zeros, not stale state.
    old_vals = jnp.where(seg.has_init_by_row[:, None, None], old_vals,
                         0.0)
    new_state = jnp.where(from_chunk[:, :, None], chunk_vals, old_vals)
    # Inactive rows keep their state verbatim.
    active = (q_len > 0)[:, None, None]
    new_state = jnp.where(active, new_state, conv_state)
    return y, new_state


def selective_scan_ragged(
    x: jax.Array,  # [T, Di] activated conv output (f32 recommended)
    dt: jax.Array,  # [T, Di] softplus'd step sizes
    A: jax.Array,  # [Di, N] negative reals
    B: jax.Array,  # [T, N]
    C: jax.Array,  # [T, N]
    D: jax.Array,  # [Di]
    ssm_state: jax.Array,  # [S+1, Di, N] carried state (f32)
    seg: SegmentInfo,
) -> tuple[jax.Array, jax.Array]:
    """Mamba-1 selective scan over the ragged batch.

    Discretization follows the published recurrence (and the
    reference's selective_scan_fwd semantics): a_t = exp(dt ⊙ A),
    b_t = dt ⊙ B_t ⊙ x_t; y_t = C_t · h_t + D ⊙ x_t.
    Returns (y [T, Di] f32, new_ssm_state [S+1, Di, N] f32).
    """
    x32 = x.astype(jnp.float32)
    # Zero dt at padding -> identity transition (dt_proj bias would
    # otherwise give padding steps a real decay).
    dt32 = jnp.where(_bshape(seg.valid, dt), dt.astype(jnp.float32), 0.0)
    a = jnp.exp(dt32[:, :, None] * A[None, :, :])  # [T, Di, N]
    b = (dt32 * x32)[:, :, None] * B[:, None, :].astype(jnp.float32)

    # Fold carried state into the first token of resumed chunks:
    # h_t0 = a_t0 * h_carry + b_t0.
    h_carry = ssm_state[seg.row]  # [T, Di, N]
    inject = _bshape(seg.start & seg.has_init, h_carry)
    b = b + jnp.where(inject, a * h_carry, 0.0)

    h = segmented_linear_scan(a, b, seg.start)
    y = jnp.einsum("tdn,tn->td", h,
                   C.astype(jnp.float32)) + D[None, :] * x32

    # Scatter each request's final state back to its row.
    dump = ssm_state.shape[0] - 1
    wrow = jnp.where(seg.end, seg.row, dump)
    new_state = ssm_state.at[wrow].set(h)
    # Repair the dump row to a fixed value so donation stays clean.
    new_state = new_state.at[dump].set(0.0)
    return y, new_state


def ssd_scan_ragged(
    x: jax.Array,  # [T, Hm, P] activated conv output
    dt: jax.Array,  # [T, Hm] softplus'd step sizes
    A: jax.Array,  # [Hm] negative reals (scalar per head)
    B: jax.Array,  # [T, G, N]
    C: jax.Array,  # [T, G, N]
    D: jax.Array,  # [Hm]
    ssm_state: jax.Array,  # [S+1, Hm, P, N] carried state (f32)
    seg: SegmentInfo,
) -> tuple[jax.Array, jax.Array]:
    """Mamba-2 (SSD) scan over the ragged batch: scalar decay per head,
    B/C shared across ``Hm // G`` heads per group (GQA-style).

    h_t = exp(dt_t A_h) h_{t-1} + dt_t * x_t ⊗ B_t ; y = h · C + D x.
    Same segmented scan as Mamba-1 with the head-major shapes of the
    reference's mamba_mixer2 (vllm/model_executor/layers/mamba/
    mamba_mixer2.py); the scalar-per-head decay keeps the scan elements
    rank-4 instead of materializing per-channel decays.
    Returns (y [T, Hm, P] f32, new state).
    """
    T, Hm, P = x.shape
    G = B.shape[1]
    rep = Hm // G
    x32 = x.astype(jnp.float32)
    dt32 = jnp.where(_bshape(seg.valid, dt), dt.astype(jnp.float32), 0.0)
    Bh = jnp.repeat(B.astype(jnp.float32), rep, axis=1)  # [T, Hm, N]
    Ch = jnp.repeat(C.astype(jnp.float32), rep, axis=1)
    # Decay stays a broadcastable [T, Hm, 1, 1] leaf through the scan
    # (the combine a1*a2 preserves it; a2*b1+b2 broadcasts), so the
    # scalar-per-head structure costs 1/(P*N) of the drive's traffic —
    # the same trick segmented_linear_scan applies to the reset flag.
    a4 = jnp.exp(dt32 * A[None, :])[:, :, None, None]  # [T, Hm, 1, 1]
    b = (dt32[:, :, None] * x32)[..., None] * Bh[:, :, None, :]

    h_carry = ssm_state[seg.row]
    inject = _bshape(seg.start & seg.has_init, h_carry)
    b = b + jnp.where(inject, a4 * h_carry, 0.0)

    h = segmented_linear_scan(a4, b, seg.start)  # [T, Hm, P, N]
    y = jnp.einsum("thpn,thn->thp", h, Ch) + D[None, :, None] * x32

    dump = ssm_state.shape[0] - 1
    wrow = jnp.where(seg.end, seg.row, dump)
    new_state = ssm_state.at[wrow].set(h)
    new_state = new_state.at[dump].set(0.0)
    return y, new_state
