"""Pallas MLA (latent MQA) attention kernel for TPU.

TPU-native counterpart of the reference's MLA decode kernels
(csrc/attention/mla/cutlass_mla_kernels.cu, sm100 FlashMLA): attention
over the paged LATENT cache — one [kv_lora_rank + rope] row per token,
shared by every head (MQA). Mirrors ops/pallas_attention.py's design
(grid (seq, q_tile), scalar-prefetched per-sequence runs, async page
DMA, online-softmax carries) with the MLA twists:

* ONE kv "head": all q heads fold into the score-matrix rows, so the
  per-block compute is two plain MXU matmuls — [rows, kdim] x
  [kdim, BLK] scores and [rows, BLK] x [BLK, Lkv] accumulate.
* The value matrix IS the key latent slice (absorbed form): the
  accumulator carries [rows, Lkv] and the caller applies W_UV after.

Layout contract matches the base kernel (flat ragged q, seq_info runs,
padded q tiles); the cache is [L, num_pages, PS, Cs] with the latent in
lanes [0, Lkv) and the rope key in [Lkv, Lkv + R).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from vllm_distributed_tpu import envs

_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def _kernel(
    # scalar prefetch
    seq_info_ref,  # [R, 4] int32: q_start, q_len, kv_len, batch_row
    num_seqs_ref,  # [1] int32
    layer_ref,  # [1] int32
    block_tables_ref,  # [max_reqs, pages_per_req] int32
    # tensor inputs (HBM)
    q_hbm,  # [T_pad, N, kdim_pad]
    c_hbm,  # [L, num_pages, PS, Cs]
    # output (HBM)
    out_hbm,  # [T_pad, N, Lkv_pad]
    # scratch
    q_vmem,  # [BQ, N, kdim_pad]
    c_vmem,  # [BLK, Cs]
    out_stage,  # [BQ, N, Lkv_pad]
    q_sem,
    c_sems,  # [PPB]
    out_sem,
    *,
    sm_scale: float,
    bq: int,
    ppb: int,
    page_size: int,
    lkv: int,
    kdim: int,
):
    r = pl.program_id(0)
    qt = pl.program_id(1)
    q_start = seq_info_ref[r, 0]
    q_len = seq_info_ref[r, 1]
    kv_len = seq_info_ref[r, 2]
    row = seq_info_ref[r, 3]
    num_seqs = num_seqs_ref[0]
    layer = layer_ref[0]
    N = q_vmem.shape[1]
    lkv_pad = out_stage.shape[2]

    blk = ppb * page_size
    tile_start = qt * bq
    q_pos_max = kv_len - q_len + jnp.minimum(tile_start + bq, q_len) - 1
    active = jnp.logical_and(
        r < num_seqs,
        jnp.logical_and(tile_start < q_len, kv_len > 0))

    @pl.when(active)
    def _run():
        q_dma = pltpu.make_async_copy(
            q_hbm.at[pl.ds(q_start + tile_start, bq)], q_vmem, q_sem)
        q_dma.start()
        num_blocks = q_pos_max // blk + 1
        q_dma.wait()

        rows = bq * N
        q_tile = (q_vmem[...].astype(jnp.float32)
                  .reshape(rows, -1)[:, :kdim] * sm_scale)

        row_pos = (kv_len - q_len + tile_start +
                   jax.lax.broadcasted_iota(jnp.int32, (rows, blk), 0) //
                   N)
        col_base = jax.lax.broadcasted_iota(jnp.int32, (rows, blk), 1)
        row_valid = (jax.lax.broadcasted_iota(jnp.int32, (rows, blk), 0) //
                     N + tile_start) < q_len

        def body(b, carry):
            m_prev, l_prev, acc_prev = carry
            for i in range(ppb):
                page_id = block_tables_ref[row, b * ppb + i]
                pltpu.make_async_copy(
                    c_hbm.at[layer, page_id],
                    c_vmem.at[pl.ds(i * page_size, page_size)],
                    c_sems.at[i]).start()
            for i in range(ppb):
                pltpu.make_async_copy(
                    c_hbm.at[0, 0],
                    c_vmem.at[pl.ds(i * page_size, page_size)],
                    c_sems.at[i]).wait()

            kv_pos = b * blk + col_base
            mask = jnp.logical_and(kv_pos <= row_pos, row_valid)

            c_blk = c_vmem[...].astype(jnp.float32)  # [BLK, Cs]
            s = jax.lax.dot_general(
                q_tile, c_blk[:, :kdim],
                dimension_numbers=(((1, ), (1, )), ((), ())),
                preferred_element_type=jnp.float32)  # [rows, BLK]
            s = jnp.where(mask, s, _MASK_VALUE)
            m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m_prev - m_new)
            l_new = l_prev * alpha + p.sum(axis=-1, keepdims=True)
            pv = jax.lax.dot_general(
                p, c_blk[:, :lkv],
                dimension_numbers=(((1, ), (0, )), ((), ())),
                preferred_element_type=jnp.float32)  # [rows, Lkv]
            acc_new = acc_prev * alpha + pv
            return m_new, l_new, acc_new

        init = (jnp.full((rows, 1), _MASK_VALUE, jnp.float32),
                jnp.zeros((rows, 1), jnp.float32),
                jnp.zeros((rows, lkv), jnp.float32))
        m, l, acc = jax.lax.fori_loop(0, num_blocks, body, init)

        o = acc / jnp.maximum(l, 1e-20)  # [rows, Lkv]
        if lkv_pad > lkv:
            o = jnp.pad(o, ((0, 0), (0, lkv_pad - lkv)))
        out_stage[...] = o.reshape(bq, N, lkv_pad).astype(
            out_stage.dtype)
        out_dma = pltpu.make_async_copy(
            out_stage, out_hbm.at[pl.ds(q_start + tile_start, bq)],
            out_sem)
        out_dma.start()
        out_dma.wait()


@functools.partial(
    jax.jit,
    static_argnames=("sm_scale", "max_q", "kv_lora_rank", "rope_dim",
                     "interpret"))
def ragged_latent_attention_pallas(
    q: jax.Array,  # [T_pad, N, kdim_pad] (ql ++ q_pe, lane-padded)
    c_pages: jax.Array,  # [L, num_pages, PS, Cs]
    seq_info: jax.Array,  # [R, 4] int32
    num_seqs: jax.Array,  # [1] int32
    block_tables: jax.Array,  # [max_reqs, pages_per_req] int32
    layer: jax.Array | None = None,  # [1] int32
    *,
    sm_scale: float,
    max_q: int,
    kv_lora_rank: int,
    rope_dim: int,
    interpret: bool | None = None,
) -> jax.Array:
    """MLA attention over the latent cache; returns [T_pad, N, Lkv_pad]
    (lanes past kv_lora_rank are zero; caller slices). Rows past each
    sequence's q_len are garbage, like the base kernel."""
    if interpret is None:
        interpret = envs.VDT_PALLAS_INTERPRET
    if layer is None:
        layer = jnp.zeros((1, ), jnp.int32)
    T_pad, N, kdim_pad = q.shape
    _, num_pages, page_size, Cs = c_pages.shape
    kdim = kv_lora_rank + rope_dim
    R = seq_info.shape[0]
    pages_per_req = block_tables.shape[1]
    from vllm_distributed_tpu.ops.mla import latent_storage_dim
    lkv_pad = latent_storage_dim(kv_lora_rank, 0)

    bq = min(max_q, 32)
    # VMEM: q tile + f32 accumulators over Lkv lanes per row.
    while bq > 1 and bq * N * (kdim_pad + 3 * kv_lora_rank) * 4 > \
            10 * 1024**2:
        bq //= 2
    num_q_tiles = pl.cdiv(max_q, bq)
    assert T_pad >= bq, "q must be padded to at least one tile"
    ppb = max(1, min(128 // page_size, pages_per_req))
    while pages_per_req % ppb:
        ppb -= 1
    blk = ppb * page_size

    kernel = functools.partial(
        _kernel, sm_scale=sm_scale, bq=bq, ppb=ppb,
        page_size=page_size, lkv=kv_lora_rank, kdim=kdim)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(R, num_q_tiles),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),  # q
            pl.BlockSpec(memory_space=pltpu.ANY),  # c_pages
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[
            pltpu.VMEM((bq, N, kdim_pad), q.dtype),
            pltpu.VMEM((blk, Cs), c_pages.dtype),
            pltpu.VMEM((bq, N, lkv_pad), q.dtype),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((ppb, )),
            pltpu.SemaphoreType.DMA(()),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((T_pad, N, lkv_pad), q.dtype),
        interpret=interpret,
    )(seq_info, num_seqs, layer, block_tables, q, c_pages)
