"""Multi-head Latent Attention (MLA) ops over a paged latent cache.

TPU-native equivalent of the reference's MLA backend family
(vllm/v1/attention/backends/mla/common.py, csrc/attention/mla/): the KV
cache stores ONE compressed row per token — the kv_lora_rank latent
``kv_c`` concatenated with the shared rope key ``k_pe`` — instead of
per-head K and V, cutting KV memory by ~an order of magnitude for
DeepSeek-shaped models.

Design choice: this implementation uses the reference's "data-movement
friendly" ABSORBED form (common.py:96-120 `_forward_decode`) uniformly
for prefill and decode. The model absorbs W_UK into the query
(`ql = q_nope · W_UK`, done once per step outside this op) so attention
is MQA with qk dim = Lkv + R and v dim = Lkv; W_UV is applied to the
output afterwards. One uniform path keeps the jit bucket lattice
additive (the reference keeps separate prefill/decode MLA kernels and
pays a dispatch split); the compute overhead vs the "compute friendly"
prefill form is bounded by (Lkv+R)/(P+R) on the score matmul, which the
MXU absorbs at these widths. A Pallas kernel can later replace the page
scan without changing this interface.

Sharding — two layouts, selected at load (models/loader.py sets
``arch.tpla_shards``):

* **Replicated** (``VDT_TPLA=0`` or TP == 1): kv_c/k_pe are shared by
  all heads (that is the point of MLA), so each TP rank attends with
  its local head shard against the full cache, and GSPMD needs no
  collective inside the op. This is the pre-TPLA layout, byte-identical
  under the kill switch.
* **TPLA** (PAPERS.md "TPLA: Tensor Parallel Latent Attention"): the
  latent dimension of every cache row shards over the model (TP) axis —
  rank r stores lanes [r*Lkv/TP, (r+1)*Lkv/TP) of kv_c in the "c" pages
  while the rope key k_pe lives in a small replicated "pe" sidecar (the
  paper's layout: latent sharded, rope broadcast). The per-rank latent
  pool is ~1/TP the bytes, so max concurrent MLA requests scales
  ~TP-fold at fixed HBM. Attention runs EXACTLY (token-identical to the
  replicated layout): inside a shard_map each rank computes partial
  scores ql_shard·kv_c_shard per page block, a psum over the model axis
  plus the locally-computed q_pe·k_pe reassembles the full scores, the
  per-block (m, l, acc) state merges through the cascade emit-state
  machinery (ops/attention.merge_attention_states) with the value
  accumulator carrying only the rank's latent slice, and the absorbed
  W_UV output projection contracts each rank's slice with its W_UV
  shard — that final [T, N, V] combine is the layer's one reduced
  collective and rides the quantized plane under VDT_QCOMM_PATHS
  "tpla" (parallel/collectives.py). The score psum itself stays exact
  (lax.psum): pre-softmax logits are the one tensor a block-scaled
  round-trip can visibly move.

Pages still shard over the token-parallel axis like the standard cache
(not yet wired: the loader rejects MLA x TKNP). A TPLA-aware Pallas
latent kernel needs the score psum between its two MXU matmuls
(a two-kernel split); until then the TPLA path runs this module's
blockwise scan on every backend and the Pallas kernel keeps serving the
replicated layout.
"""

import jax
import jax.numpy as jnp

from vllm_distributed_tpu.ops.attention import (_MASK_VALUE,
                                                _pad_last_dim,
                                                merge_attention_states)
from vllm_distributed_tpu.parallel.mesh import shard_map


def latent_storage_dim(kv_lora_rank: int, rope_dim: int) -> int:
    """Last-dim storage size for the latent cache: Lkv + R padded to the
    128-lane tile on TPU (see ops/attention.storage_head_dim)."""
    c = kv_lora_rank + rope_dim
    if jax.default_backend() == "tpu":
        return -(-c // 128) * 128
    return c


def latent_shard_dim(kv_lora_rank: int, shards: int) -> int:
    """Per-rank storage lanes of one TPLA latent shard: Lkv/shards,
    padded to the 128-lane tile on TPU so each rank's slice DMAs whole
    tiles. The global "c" last dim is ``shards *`` this."""
    assert kv_lora_rank % shards == 0, (kv_lora_rank, shards)
    return latent_storage_dim(kv_lora_rank // shards, 0)


def tpla_applicable(kv_lora_rank: int, shards: int) -> bool:
    """Can the latent dim split evenly over ``shards`` ranks? The loader
    falls back to the replicated layout (with a log) when not."""
    return shards > 1 and kv_lora_rank % shards == 0


def write_latent_cache(
    c_all: jax.Array,  # [L, num_pages, page_size, Cs] stacked cache
    c_new: jax.Array,  # [T, Lkv + R] new latent rows (kv_c ++ k_pe)
    batch,  # AttentionBatch
    layer: jax.Array,  # [1] int32
) -> jax.Array:
    """Scatter the step's latent rows into layer ``layer`` of the stacked
    cache (XLA contiguous-row scatter; slots < 0 drop). Equivalent of
    the reference's concat_and_cache_mla (csrc/cache_kernels.cu)."""
    L, NP, PS, Cs = c_all.shape
    c_new = _pad_last_dim(c_new, Cs)
    slot = batch.slot_mapping
    total = L * NP * PS
    rows = layer[0] * (NP * PS) + slot
    rows = jnp.where(slot < 0, total, rows)
    flat = c_all.reshape(total, Cs)
    flat = flat.at[rows].set(c_new.astype(flat.dtype), mode="drop")
    return flat.reshape(c_all.shape)


def ragged_latent_attention(
    ql: jax.Array,  # [T, N, Lkv] absorbed no-rope queries (q_nope · W_UK)
    q_pe: jax.Array,  # [T, N, R] rope queries
    c_pages: jax.Array,  # [num_pages, page_size, Cs] one layer's cache
    block_tables: jax.Array,  # [max_reqs, pages_per_req] int32
    req_idx: jax.Array,  # [T] int32 owning request row per token
    q_pos: jax.Array,  # [T] int32 absolute position per query token
    *,
    sm_scale: float,
    kv_lora_rank: int,
    rope_dim: int,
) -> jax.Array:  # [T, N, Lkv] latent-space attention output
    """Unified ragged MQA over the latent cache: token t attends to
    latent rows 0..q_pos[t] of its request. Scores are
    ql·kv_c + q_pe·k_pe (the absorbed form); the value accumulated is
    kv_c itself, so the caller applies W_UV to the [T, N, Lkv] result.
    Online-softmax scan over pages, like ops/attention.
    ragged_paged_attention."""
    T, N, Lkv = ql.shape
    PS = c_pages.shape[1]
    pages_per_req = block_tables.shape[1]
    # [T, N, Lkv + R] combined queries, pre-scaled.
    qc = jnp.concatenate([ql.astype(jnp.float32),
                          q_pe.astype(jnp.float32)], axis=-1) * sm_scale
    token_pages = block_tables[req_idx]  # [T, pages_per_req]
    kdim = kv_lora_rank + rope_dim

    def body(carry, page_i):
        m, l, acc = carry  # [T,N,1], [T,N,1], [T,N,Lkv]
        page_ids = token_pages[:, page_i]  # [T]
        blk = c_pages[page_ids, :, :kdim].astype(jnp.float32)  # [T,PS,kd]
        scores = jnp.einsum("tnc,tpc->tnp", qc, blk)  # [T, N, PS]
        kv_pos = page_i * PS + jnp.arange(PS, dtype=jnp.int32)
        valid = kv_pos[None, :] <= q_pos[:, None]  # [T, PS] causal
        scores = jnp.where(valid[:, None, :], scores, _MASK_VALUE)
        m_new = jnp.maximum(m, scores.max(axis=-1, keepdims=True))
        p = jnp.exp(scores - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum("tnp,tpl->tnl", p,
                                           blk[..., :Lkv])
        return (m_new, l_new, acc_new), None

    m0 = jnp.full((T, N, 1), _MASK_VALUE, jnp.float32)
    l0 = jnp.zeros((T, N, 1), jnp.float32)
    acc0 = jnp.zeros((T, N, Lkv), jnp.float32)
    (m, l, acc), _ = jax.lax.scan(
        body, (m0, l0, acc0),
        jnp.arange(pages_per_req, dtype=jnp.int32))
    out = acc / jnp.maximum(l, 1e-20)
    return out.astype(ql.dtype)


def write_latent_cache_tpla(
    c_all: jax.Array,  # [L, NP, PS, shards * shard_pad] latent-sharded
    pe_all: jax.Array,  # [L, NP, PS, R_pad] replicated rope sidecar
    kv_c: jax.Array,  # [T, Lkv] new latent rows
    k_pe: jax.Array,  # [T, R] new rope keys
    batch,  # AttentionBatch
    layer: jax.Array,  # [1] int32
    *,
    shards: int,
    kv_lora_rank: int,
) -> tuple[jax.Array, jax.Array]:
    """TPLA cache write: scatter each rank's latent slice into its "c"
    shard and the shared rope key into the replicated "pe" sidecar. The
    new rows are re-laid out [T, shards, Lkv/shards] -> per-shard lane
    padding -> [T, shards * shard_pad], so the (elementwise on the lane
    dim) scatter writes every rank's slice locally — GSPMD moves no
    data."""
    shard_pad = c_all.shape[-1] // shards
    lkv_local = kv_lora_rank // shards
    T = kv_c.shape[0]
    rows = kv_c.reshape(T, shards, lkv_local)
    if shard_pad > lkv_local:
        rows = jnp.pad(rows, ((0, 0), (0, 0), (0, shard_pad - lkv_local)))
    c_new = rows.reshape(T, shards * shard_pad)
    c_all = write_latent_cache(c_all, c_new, batch, layer)
    pe_all = write_latent_cache(pe_all, k_pe, batch, layer)
    return c_all, pe_all


def tpla_latent_attention(
    ql: jax.Array,  # [T, N, Lkv] absorbed queries, latent-dim sharded
    q_pe: jax.Array,  # [T, N, R] rope queries, replicated
    c_all: jax.Array,  # [L, NP, PS, shards * shard_pad] latent-sharded
    pe_all: jax.Array,  # [L, NP, PS, R_pad] replicated rope sidecar
    batch,  # AttentionBatch
    w_uv: jax.Array,  # [Lkv, N, V] this layer's W_UV, latent-dim sharded
    *,
    sm_scale: float,
    kv_lora_rank: int,
    rope_dim: int,
    shards: int,
    layer: jax.Array,  # [1] int32
) -> jax.Array:  # [T, N, V] replicated value-space output
    """TPLA ragged latent attention + absorbed W_UV, exact (see module
    docstring): per-block partial scores psum over the model axis, the
    rope term computed locally from the replicated sidecar, online
    softmax carried through merge_attention_states, per-rank latent
    value slices contracted against the rank's W_UV shard and combined
    with ONE psum (quantized plane path "tpla")."""
    from jax.sharding import PartitionSpec as P

    from vllm_distributed_tpu.config import MESH_AXIS_MODEL
    from vllm_distributed_tpu.parallel import collectives
    from vllm_distributed_tpu.parallel import mesh as mesh_state
    if getattr(batch, "tknp", None) is not None:
        raise NotImplementedError(
            "MLA under token parallelism is not wired (per-rank latent "
            "page pools); models/loader.py rejects the combination")
    lkv_local = kv_lora_rank // shards
    shard_pad = c_all.shape[-1] // shards
    PS = c_all.shape[2]
    pages_per_req = batch.block_tables.shape[1]

    def rank_fn(ql_, qpe_, c_, pe_, bt_, req_idx_, q_pos_, wuv_, layer_):
        # ql_ [T, N, lkv_local]; c_ [L, NP, PS, shard_pad] (this rank's
        # latent lanes); pe_ replicated; wuv_ [lkv_local, N, V].
        c_layer = c_[layer_[0]]
        pe_layer = pe_[layer_[0]]
        ql32 = ql_.astype(jnp.float32) * sm_scale
        qpe32 = qpe_.astype(jnp.float32) * sm_scale
        token_pages = bt_[req_idx_]  # [T, pages_per_req]
        T, N = ql_.shape[0], ql_.shape[1]

        def body(carry, page_i):
            page_ids = token_pages[:, page_i]  # [T]
            c_blk = c_layer[page_ids, :, :lkv_local].astype(jnp.float32)
            pe_blk = pe_layer[page_ids, :, :rope_dim].astype(jnp.float32)
            # Partial scores from this rank's latent slice; the psum
            # over the model axis reassembles the full ql·kv_c term so
            # every rank softmaxes the EXACT scores (identical m/l).
            part = jnp.einsum("tnc,tpc->tnp", ql32, c_blk)
            s = jax.lax.psum(part, MESH_AXIS_MODEL)
            s = s + jnp.einsum("tnr,tpr->tnp", qpe32, pe_blk)
            kv_pos = page_i * PS + jnp.arange(PS, dtype=jnp.int32)
            valid = kv_pos[None, :] <= q_pos_[:, None]  # [T, PS] causal
            s = jnp.where(valid[:, None, :], s, _MASK_VALUE)
            # Per-block dense state, folded into the carry through the
            # cascade m/l emit-state merge (a fully-masked block's
            # m = _MASK_VALUE, so its alpha underflows to exactly 0).
            m_b = s.max(axis=-1, keepdims=True)
            p = jnp.exp(s - m_b)
            l_b = p.sum(axis=-1, keepdims=True)
            acc_b = jnp.einsum("tnp,tpl->tnl", p, c_blk)
            return merge_attention_states(carry, (m_b, l_b, acc_b)), None

        init = (jnp.full((T, N, 1), _MASK_VALUE, jnp.float32),
                jnp.zeros((T, N, 1), jnp.float32),
                jnp.zeros((T, N, lkv_local), jnp.float32))
        (m, l, acc), _ = jax.lax.scan(
            body, init, jnp.arange(pages_per_req, dtype=jnp.int32))
        out_r = acc / jnp.maximum(l, 1e-20)  # [T, N, lkv_local]
        # Absorbed W_UV on the rank's slice; the combine is the layer's
        # one reduced collective — quantized plane path "tpla".
        v_r = jnp.einsum("tnk,knv->tnv", out_r,
                         wuv_.astype(jnp.float32))
        return collectives.psum(v_r, MESH_AXIS_MODEL, path="tpla")

    M = MESH_AXIS_MODEL
    out = shard_map(
        rank_fn, mesh=mesh_state.get_global_mesh(),
        in_specs=(P(None, None, M), P(), P(None, None, None, M), P(),
                  P(), P(), P(), P(M, None, None), P()),
        out_specs=P(), check_vma=False)(
            ql, q_pe, c_all, pe_all, batch.block_tables, batch.req_idx,
            batch.positions, w_uv, layer)
    return out.astype(ql.dtype)


def latent_attention(q_absorbed, q_pe, c_all, batch, *, sm_scale,
                     kv_lora_rank, rope_dim, layer=None):
    """Model-facing entry: select the layer's page slab and run the
    ragged latent attention (the MLA analogue of ops/attention.
    paged_attention). Dispatches to the Pallas latent kernel
    (ops/pallas_mla.py) on the pallas backend; the XLA scan is the
    correctness reference and CPU fallback. Token parallelism is
    rejected upstream by the loader."""
    from vllm_distributed_tpu.ops.attention import \
        resolve_attention_backend
    if getattr(batch, "tknp", None) is not None:
        raise NotImplementedError(
            "MLA under token parallelism (per-rank latent page pools "
            "are not wired; models/loader.py rejects the combination "
            "at admission — this trace-time guard is the backstop)")
    if layer is None:
        layer = jnp.zeros((1, ), jnp.int32)
    if (resolve_attention_backend() == "pallas"
            and getattr(batch, "seq_info", None) is not None
            and c_all.ndim == 4):
        from vllm_distributed_tpu.ops.pallas_mla import \
            ragged_latent_attention_pallas
        qc = jnp.concatenate([q_absorbed, q_pe], axis=-1)
        Cs = c_all.shape[-1]
        qc = _pad_last_dim(qc, Cs)

        def call(q_):
            out = ragged_latent_attention_pallas(
                q_, c_all, batch.seq_info, batch.num_seqs,
                batch.block_tables, layer, sm_scale=sm_scale,
                max_q=batch.max_q, kv_lora_rank=kv_lora_rank,
                rope_dim=rope_dim)
            # Rows the kernel never writes are uninitialized HBM; zero
            # them (padding tokens carry slot -1).
            valid = (batch.slot_mapping >= 0)[:, None, None]
            return jnp.where(valid, out[..., :kv_lora_rank], 0)

        from vllm_distributed_tpu.parallel import mesh as mesh_state
        if mesh_state.has_global_mesh() and mesh_state.tp_size() > 1:
            from jax.sharding import PartitionSpec as P

            from vllm_distributed_tpu.config import MESH_AXIS_MODEL

            # q heads shard; the latent cache is MQA-shared and
            # replicated, so each rank runs the kernel on its head
            # slice against the full cache.
            head_spec = P(None, MESH_AXIS_MODEL, None)
            return shard_map(
                call, mesh=mesh_state.get_global_mesh(),
                in_specs=(head_spec, ),
                out_specs=head_spec, check_vma=False)(qc)
        return call(qc)
    c_layer = c_all[layer[0]] if c_all.ndim == 4 else c_all
    return ragged_latent_attention(
        q_absorbed, q_pe, c_layer, batch.block_tables, batch.req_idx,
        batch.positions, sm_scale=sm_scale, kv_lora_rank=kv_lora_rank,
        rope_dim=rope_dim)
