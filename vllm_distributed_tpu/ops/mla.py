"""Multi-head Latent Attention (MLA) ops over a paged latent cache.

TPU-native equivalent of the reference's MLA backend family
(vllm/v1/attention/backends/mla/common.py, csrc/attention/mla/): the KV
cache stores ONE compressed row per token — the kv_lora_rank latent
``kv_c`` concatenated with the shared rope key ``k_pe`` — instead of
per-head K and V, cutting KV memory by ~an order of magnitude for
DeepSeek-shaped models.

Design choice: this implementation uses the reference's "data-movement
friendly" ABSORBED form (common.py:96-120 `_forward_decode`) uniformly
for prefill and decode. The model absorbs W_UK into the query
(`ql = q_nope · W_UK`, done once per step outside this op) so attention
is MQA with qk dim = Lkv + R and v dim = Lkv; W_UV is applied to the
output afterwards. One uniform path keeps the jit bucket lattice
additive (the reference keeps separate prefill/decode MLA kernels and
pays a dispatch split); the compute overhead vs the "compute friendly"
prefill form is bounded by (Lkv+R)/(P+R) on the score matmul, which the
MXU absorbs at these widths. A Pallas kernel can later replace the page
scan without changing this interface.

Sharding: the latent cache is REPLICATED over the model (TP) axis —
kv_c/k_pe are shared by all heads (that is the point of MLA), so each
TP rank attends with its local head shard against the full cache, and
GSPMD needs no collective inside the op. Pages still shard over the
token-parallel axis like the standard cache (not yet wired: the loader
rejects MLA x TKNP).
"""

import jax
import jax.numpy as jnp

from vllm_distributed_tpu.ops.attention import _MASK_VALUE, _pad_last_dim
from vllm_distributed_tpu.parallel.mesh import shard_map


def latent_storage_dim(kv_lora_rank: int, rope_dim: int) -> int:
    """Last-dim storage size for the latent cache: Lkv + R padded to the
    128-lane tile on TPU (see ops/attention.storage_head_dim)."""
    c = kv_lora_rank + rope_dim
    if jax.default_backend() == "tpu":
        return -(-c // 128) * 128
    return c


def write_latent_cache(
    c_all: jax.Array,  # [L, num_pages, page_size, Cs] stacked cache
    c_new: jax.Array,  # [T, Lkv + R] new latent rows (kv_c ++ k_pe)
    batch,  # AttentionBatch
    layer: jax.Array,  # [1] int32
) -> jax.Array:
    """Scatter the step's latent rows into layer ``layer`` of the stacked
    cache (XLA contiguous-row scatter; slots < 0 drop). Equivalent of
    the reference's concat_and_cache_mla (csrc/cache_kernels.cu)."""
    L, NP, PS, Cs = c_all.shape
    c_new = _pad_last_dim(c_new, Cs)
    slot = batch.slot_mapping
    total = L * NP * PS
    rows = layer[0] * (NP * PS) + slot
    rows = jnp.where(slot < 0, total, rows)
    flat = c_all.reshape(total, Cs)
    flat = flat.at[rows].set(c_new.astype(flat.dtype), mode="drop")
    return flat.reshape(c_all.shape)


def ragged_latent_attention(
    ql: jax.Array,  # [T, N, Lkv] absorbed no-rope queries (q_nope · W_UK)
    q_pe: jax.Array,  # [T, N, R] rope queries
    c_pages: jax.Array,  # [num_pages, page_size, Cs] one layer's cache
    block_tables: jax.Array,  # [max_reqs, pages_per_req] int32
    req_idx: jax.Array,  # [T] int32 owning request row per token
    q_pos: jax.Array,  # [T] int32 absolute position per query token
    *,
    sm_scale: float,
    kv_lora_rank: int,
    rope_dim: int,
) -> jax.Array:  # [T, N, Lkv] latent-space attention output
    """Unified ragged MQA over the latent cache: token t attends to
    latent rows 0..q_pos[t] of its request. Scores are
    ql·kv_c + q_pe·k_pe (the absorbed form); the value accumulated is
    kv_c itself, so the caller applies W_UV to the [T, N, Lkv] result.
    Online-softmax scan over pages, like ops/attention.
    ragged_paged_attention."""
    T, N, Lkv = ql.shape
    PS = c_pages.shape[1]
    pages_per_req = block_tables.shape[1]
    # [T, N, Lkv + R] combined queries, pre-scaled.
    qc = jnp.concatenate([ql.astype(jnp.float32),
                          q_pe.astype(jnp.float32)], axis=-1) * sm_scale
    token_pages = block_tables[req_idx]  # [T, pages_per_req]
    kdim = kv_lora_rank + rope_dim

    def body(carry, page_i):
        m, l, acc = carry  # [T,N,1], [T,N,1], [T,N,Lkv]
        page_ids = token_pages[:, page_i]  # [T]
        blk = c_pages[page_ids, :, :kdim].astype(jnp.float32)  # [T,PS,kd]
        scores = jnp.einsum("tnc,tpc->tnp", qc, blk)  # [T, N, PS]
        kv_pos = page_i * PS + jnp.arange(PS, dtype=jnp.int32)
        valid = kv_pos[None, :] <= q_pos[:, None]  # [T, PS] causal
        scores = jnp.where(valid[:, None, :], scores, _MASK_VALUE)
        m_new = jnp.maximum(m, scores.max(axis=-1, keepdims=True))
        p = jnp.exp(scores - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum("tnp,tpl->tnl", p,
                                           blk[..., :Lkv])
        return (m_new, l_new, acc_new), None

    m0 = jnp.full((T, N, 1), _MASK_VALUE, jnp.float32)
    l0 = jnp.zeros((T, N, 1), jnp.float32)
    acc0 = jnp.zeros((T, N, Lkv), jnp.float32)
    (m, l, acc), _ = jax.lax.scan(
        body, (m0, l0, acc0),
        jnp.arange(pages_per_req, dtype=jnp.int32))
    out = acc / jnp.maximum(l, 1e-20)
    return out.astype(ql.dtype)


def latent_attention(q_absorbed, q_pe, c_all, batch, *, sm_scale,
                     kv_lora_rank, rope_dim, layer=None):
    """Model-facing entry: select the layer's page slab and run the
    ragged latent attention (the MLA analogue of ops/attention.
    paged_attention). Dispatches to the Pallas latent kernel
    (ops/pallas_mla.py) on the pallas backend; the XLA scan is the
    correctness reference and CPU fallback. Token parallelism is
    rejected upstream by the loader."""
    from vllm_distributed_tpu.ops.attention import \
        resolve_attention_backend
    if getattr(batch, "tknp", None) is not None:
        raise NotImplementedError(
            "MLA under token parallelism (per-rank latent page pools "
            "are not wired; models/loader.py rejects the combination "
            "at admission — this trace-time guard is the backstop)")
    if layer is None:
        layer = jnp.zeros((1, ), jnp.int32)
    if (resolve_attention_backend() == "pallas"
            and getattr(batch, "seq_info", None) is not None
            and c_all.ndim == 4):
        from vllm_distributed_tpu.ops.pallas_mla import \
            ragged_latent_attention_pallas
        qc = jnp.concatenate([q_absorbed, q_pe], axis=-1)
        Cs = c_all.shape[-1]
        qc = _pad_last_dim(qc, Cs)

        def call(q_):
            out = ragged_latent_attention_pallas(
                q_, c_all, batch.seq_info, batch.num_seqs,
                batch.block_tables, layer, sm_scale=sm_scale,
                max_q=batch.max_q, kv_lora_rank=kv_lora_rank,
                rope_dim=rope_dim)
            # Rows the kernel never writes are uninitialized HBM; zero
            # them (padding tokens carry slot -1).
            valid = (batch.slot_mapping >= 0)[:, None, None]
            return jnp.where(valid, out[..., :kv_lora_rank], 0)

        from vllm_distributed_tpu.parallel import mesh as mesh_state
        if mesh_state.has_global_mesh() and mesh_state.tp_size() > 1:
            from jax.sharding import PartitionSpec as P

            from vllm_distributed_tpu.config import MESH_AXIS_MODEL

            # q heads shard; the latent cache is MQA-shared and
            # replicated, so each rank runs the kernel on its head
            # slice against the full cache.
            head_spec = P(None, MESH_AXIS_MODEL, None)
            return shard_map(
                call, mesh=mesh_state.get_global_mesh(),
                in_specs=(head_spec, ),
                out_specs=head_spec, check_vma=False)(qc)
        return call(qc)
    c_layer = c_all[layer[0]] if c_all.ndim == 4 else c_all
    return ragged_latent_attention(
        q_absorbed, q_pe, c_layer, batch.block_tables, batch.req_idx,
        batch.positions, sm_scale=sm_scale, kv_lora_rank=kv_lora_rank,
        rope_dim=rope_dim)
