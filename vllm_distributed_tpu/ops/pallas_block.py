"""Fused transformer-block decode: ONE Pallas call per layer.

ROADMAP item 1 (PAPERS.md "ClusterFusion++"): the mega-kernel collapsed
attention to one Pallas call per layer, but a decode step still bounced
through XLA op boundaries — RMSNorm, three projection matmuls, rope, the
KV write, attention, the output projection and the gated MLP each
round-tripped the [T, H] activations through HBM. At decode batch sizes
those activations are tiny next to the weights, so every boundary costs
a kernel launch plus an HBM write+read of the residual stream for no
reason. This kernel chains the WHOLE layer for decode-only waves:

    RMSNorm -> fused QKV projection (one re-laid [H, Dq+2*Dkv] weight)
      -> rope -> KV-page write (in-place RMW) -> paged attention
      -> O-projection -> residual add -> RMSNorm -> gated MLP
      -> residual add

with the activations living in VMEM across the entire layer. Weights
stream through VMEM in column/row tiles (decode is weight-bandwidth
bound; the stream is the same HBM traffic the separate matmuls paid,
minus all the activation round-trips). The gated MLP is tile-fused too:
gate/up/down consume one intermediate tile at a time, so the [T, I]
intermediate never materializes anywhere.

Design notes:

* Grid = decode groups of ``sb`` sequences (the decode_group_size
  batching of ops/pallas_attention.py); each program runs the full
  layer for its group. Sequences address their token row through
  seq_info's q_start, so the runner's decode layout works unchanged.
* The current token's K/V contribution folds into the online softmax
  IN REGISTER (one extra score column per head): attention walks only
  the kv_len - 1 CACHED positions, so there is no write-then-read
  hazard on the cache page the program itself just updated. The page
  write is still performed (future steps read it) as an in-place RMW
  aliased on the cache refs, like ops/pallas_kv_write.py.
* Sliding window / softcap / ALiBi / sinks ride the same per-layer
  statics + [2, QH] head-feature sidecar as the mega-kernel, so
  feature models that pass the block-shape eligibility keep the fused
  path.
* Weight tile streams are DOUBLE-BUFFERED: every stream (fused-QKV
  columns, O-proj rows, the gate/up/down MLP trio) prefetches tile
  i+1 into its second VMEM slot while tile i multiplies, so the HBM
  weight read — the bandwidth bound of decode — overlaps the MXU
  work instead of serializing with it. Two slots per stream keep the
  VMEM footprint flat by halving the per-tile cap (weight_tile cap
  256 vs the single-buffered 512). Eligibility (decided once in
  models/loader.py) pins TP=1 and the standard dense block, so no
  shard_map wrapping is needed here.

``fused_block_decode_xla`` is the XLA-composed correctness reference:
the same math built from the reference ops (rms_norm, rope helpers,
the flat-scatter KV write and the XLA ragged attention), used by the
parity suite and as the non-Pallas fallback.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from vllm_distributed_tpu import envs

_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def weight_tile(n: int, cap: int = 256) -> int:
    """Streaming tile width along a weight dimension: the largest
    divisor of ``n`` that is <= cap and lane-aligned (multiple of 128)
    when one exists, else the largest divisor <= cap. Small dims (CPU
    tests) stream as one tile. The cap is half the single-buffered
    512: each stream now holds TWO tiles in VMEM (double buffering),
    so the finer tile keeps the footprint flat and pipelines the HBM
    read behind the previous tile's matmul."""
    if n <= cap:
        return n
    for t in range(cap, 0, -128):
        if t % 128 == 0 and n % t == 0:
            return t
    for t in range(cap, 0, -1):
        if n % t == 0:
            return t
    return n


def fused_block_group_size(num_q_heads: int, num_kv_heads: int,
                           num_reqs: int) -> int:
    """Sequences per fused-block program: the decode-group width of the
    mega-kernel (virtual-head batching keeps the score dot MXU-filling),
    re-derived here so the two kernels can diverge independently."""
    from vllm_distributed_tpu.ops.pallas_attention import decode_group_size
    return max(1, min(decode_group_size(num_q_heads, num_kv_heads),
                      num_reqs))


def _rot_half_matrix(hd: int):
    """[hd, hd] f32 permutation: x @ P == rotate_half(x). Built from
    iotas in-kernel (Mosaic has no lane-dim dynamic slicing on values;
    a 0/-1/+1 matmul keeps the rotation exact and MXU-friendly)."""
    r = jax.lax.broadcasted_iota(jnp.int32, (hd, hd), 0)
    c = jax.lax.broadcasted_iota(jnp.int32, (hd, hd), 1)
    half = hd // 2
    return (jnp.where(r == c + half, -1.0, 0.0) +
            jnp.where(r + half == c, 1.0, 0.0)).astype(jnp.float32)


def _kernel(
    # scalar prefetch
    seq_info_ref,  # [R, 4] int32: q_start, q_len, kv_len, batch_row
    num_seqs_ref,  # [1] int32
    layer_ref,  # [1] int32
    block_tables_ref,  # [max_reqs, pages_per_req] int32
    # tensor inputs
    hidden_hbm,  # [T_pad, H] (aliased -> out)
    wqkv_hbm,  # [H, Dq + 2*Dkv]
    wo_hbm,  # [Dq, H]
    wg_hbm,  # [H, I]
    wu_hbm,  # [H, I]
    wd_hbm,  # [I, H]
    lnw_ref,  # [2, H] VMEM: input_ln, post_ln
    rope_hbm,  # [2, T_pad, hd] f32: cos, sin
    feat_ref,  # [2, QH] f32 VMEM: ALiBi slopes, sink logits
    _k_in,  # aliased cache inputs
    _v_in,
    # outputs
    out_hbm,  # [T_pad, H] (aliased to hidden)
    k_cache,  # [L, N, KVH, PS, D] (aliased)
    v_cache,
    # scratch
    x_vmem,  # [sb, H] io dtype
    rope_buf,  # [2, sb, hd] f32
    col_buf,  # [2, H, TQ] weight dtype (QKV column tiles, 2 slots)
    row_buf,  # [2, TO, H] weight dtype (O-proj row tiles, 2 slots)
    wg_buf,  # [2, H, TI] (double-buffered MLP streams)
    wu_buf,  # [2, H, TI]
    wd_buf,  # [2, TI, H]
    kbuf,  # [2, sb, KVH, blk, D] cache dtype
    vbuf,
    kpage,  # [KVH, PS, D]
    vpage,
    out_stage,  # [sb, H] io dtype
    x_sems,  # DMA [sb]
    rope_sems,  # DMA [2, sb]
    w_sems,  # DMA [5, 2] (per weight stream x buffer slot)
    kv_sems,  # DMA [2, 2, sb, ppb]
    page_sems,  # DMA [2]
    out_sems,  # DMA [sb]
    *,
    sm_scale: float,
    eps: float,
    sb: int,
    ppb: int,
    page_size: int,
    group: int,
    tq: int,
    to: int,
    ti: int,
    window: int,
    logit_cap: float,
    has_alibi: bool,
    has_sinks: bool,
):
    p = pl.program_id(0)
    num_seqs = num_seqs_ref[0]
    layer = layer_ref[0]
    H = x_vmem.shape[1]
    QH = feat_ref.shape[1]
    KVH = kbuf.shape[2]
    hd = rope_buf.shape[2]
    Dq = QH * hd
    Dkv = KVH * hd
    Dtot = Dq + 2 * Dkv
    I = wg_hbm.shape[1]
    blk = ppb * page_size
    base = p * sb
    ROWS = sb * QH
    C = sb * KVH * blk

    # Per-sequence scalars (static unroll over sb slots; inactive slots
    # clamp to row 0's metadata and mask everything via kv_len = 0).
    idx = [jnp.minimum(base + i, seq_info_ref.shape[0] - 1)
           for i in range(sb)]
    kv_lens = [
        jnp.where(base + i < num_seqs, seq_info_ref[idx[i], 2], 0)
        for i in range(sb)
    ]
    rows_ = [seq_info_ref[idx[i], 3] for i in range(sb)]
    q_starts = [seq_info_ref[idx[i], 0] for i in range(sb)]
    cached = [jnp.maximum(kv_lens[i] - 1, 0) for i in range(sb)]

    @pl.when(base < num_seqs)
    def _run():
        # ---- stage the group's hidden rows + rope rows --------------
        for i in range(sb):
            pltpu.make_async_copy(
                hidden_hbm.at[pl.ds(q_starts[i], 1)],
                x_vmem.at[pl.ds(i, 1)], x_sems.at[i]).start()
            for rr in range(2):
                pltpu.make_async_copy(
                    rope_hbm.at[rr, pl.ds(q_starts[i], 1)],
                    rope_buf.at[rr, pl.ds(i, 1)],
                    rope_sems.at[rr, i]).start()
        for i in range(sb):
            pltpu.make_async_copy(
                hidden_hbm.at[pl.ds(0, 1)], x_vmem.at[pl.ds(i, 1)],
                x_sems.at[i]).wait()
            for rr in range(2):
                pltpu.make_async_copy(
                    rope_hbm.at[0, pl.ds(0, 1)],
                    rope_buf.at[rr, pl.ds(i, 1)],
                    rope_sems.at[rr, i]).wait()

        h0 = x_vmem[...].astype(jnp.float32)  # [sb, H] residual stream
        io_dtype = x_vmem.dtype
        w_dtype = col_buf.dtype
        lnw = lnw_ref[...].astype(jnp.float32)

        def rms(x32, w_row):
            var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
            return ((x32 * jax.lax.rsqrt(var + eps)) *
                    lnw[w_row][None, :]).astype(io_dtype)

        # ---- RMSNorm -> fused QKV (double-buffered column tiles) ----
        # Tile t+1's DMA streams into the other VMEM slot while tile
        # t multiplies; the wait() below re-constructs the matching
        # copy descriptor (only the semaphore/slot identity matters).
        xn = rms(h0, 0).astype(w_dtype)
        nq = Dtot // tq

        def qkv_copy(t):
            return pltpu.make_async_copy(
                wqkv_hbm.at[:, pl.ds(t * tq, tq)], col_buf.at[t % 2],
                w_sems.at[0, t % 2])

        qkv_copy(0).start()
        parts = []
        for t in range(nq):
            if t + 1 < nq:
                qkv_copy(t + 1).start()
            qkv_copy(t).wait()
            parts.append(
                jax.lax.dot_general(
                    xn, col_buf[t % 2],
                    dimension_numbers=(((1, ), (0, )), ((), ())),
                    preferred_element_type=jnp.float32))
        qkv = jnp.concatenate(parts, axis=-1).astype(io_dtype)
        q = qkv[:, :Dq].reshape(sb, QH, hd)
        k = qkv[:, Dq:Dq + Dkv].reshape(sb, KVH, hd)
        v = qkv[:, Dq + Dkv:].reshape(sb, KVH, hd)

        # ---- rope (rotate-half as an exact 0/±1 matmul) -------------
        rot = _rot_half_matrix(hd)
        cos = rope_buf[0][:, None, :]  # [sb, 1, hd]
        sin = rope_buf[1][:, None, :]

        def rope_apply(x):
            x32 = x.astype(jnp.float32)
            xr = jax.lax.dot_general(
                x32.reshape(sb * x.shape[1], hd), rot,
                dimension_numbers=(((1, ), (0, )), ((), ())),
                preferred_element_type=jnp.float32).reshape(x32.shape)
            return (x32 * cos + xr * sin).astype(io_dtype)

        q = rope_apply(q)
        k = rope_apply(k)

        # ---- KV-page write: in-place RMW of each slot's page --------
        # One new row per sequence at position kv_len - 1; sequences
        # own distinct pages, so the RMWs are race-free. Attention
        # below reads only CACHED positions (< kv_len - 1), so program
        # order vs this write is irrelevant within the program.
        for i in range(sb):
            @pl.when(jnp.logical_and(base + i < num_seqs,
                                     kv_lens[i] > 0))
            def _write(i=i):
                pos = kv_lens[i] - 1
                page = block_tables_ref[rows_[i],
                                        jax.lax.div(pos, page_size)]
                off = jax.lax.rem(pos, page_size)
                kp = pltpu.make_async_copy(k_cache.at[layer, page],
                                           kpage, page_sems.at[0])
                vp = pltpu.make_async_copy(v_cache.at[layer, page],
                                           vpage, page_sems.at[1])
                kp.start()
                vp.start()
                kp.wait()
                vp.wait()
                row_sel = (jax.lax.broadcasted_iota(
                    jnp.int32, (1, page_size, 1), 1) == off)
                kpage[...] = jnp.where(
                    row_sel, k[i].astype(kpage.dtype)[:, None, :],
                    kpage[...])
                vpage[...] = jnp.where(
                    row_sel, v[i].astype(vpage.dtype)[:, None, :],
                    vpage[...])
                kb = pltpu.make_async_copy(kpage,
                                           k_cache.at[layer, page],
                                           page_sems.at[0])
                vb = pltpu.make_async_copy(vpage,
                                           v_cache.at[layer, page],
                                           page_sems.at[1])
                kb.start()
                vb.start()
                kb.wait()
                vb.wait()

        # ---- paged attention over the CACHED positions --------------
        max_cached = cached[0]
        for i in range(1, sb):
            max_cached = jnp.maximum(max_cached, cached[i])
        num_blocks = jnp.where(
            max_cached > 0, jax.lax.div(max_cached - 1, blk) + 1, 0)

        def fetch(bi, slot):
            for i in range(sb):
                ci = jnp.clip(bi, 0,
                              jnp.maximum(
                                  jax.lax.div(cached[i] - 1, blk), 0))
                for j in range(ppb):
                    page_id = block_tables_ref[rows_[i], ci * ppb + j]
                    pltpu.make_async_copy(
                        k_cache.at[layer, page_id],
                        kbuf.at[slot, i, :,
                                pl.ds(j * page_size, page_size)],
                        kv_sems.at[slot, 0, i, j]).start()
                    pltpu.make_async_copy(
                        v_cache.at[layer, page_id],
                        vbuf.at[slot, i, :,
                                pl.ds(j * page_size, page_size)],
                        kv_sems.at[slot, 1, i, j]).start()

        # Warm-up fetch only when the loop will run: with zero cached
        # blocks (every slot at kv_len <= 1) nothing ever waits the kv
        # semaphores, and a started-but-unwaited DMA is a Mosaic error.
        @pl.when(num_blocks > 0)
        def _warmup():
            fetch(0, 0)

        q_all = (q.astype(jnp.float32) * sm_scale).reshape(ROWS, hd)
        vh_r = jax.lax.broadcasted_iota(jnp.int32, (ROWS, C), 0) // group
        vh_c = jax.lax.broadcasted_iota(jnp.int32, (ROWS, C), 1) // blk
        diag = vh_r == vh_c
        col_off = jax.lax.broadcasted_iota(jnp.int32, (ROWS, C), 1) % blk
        cached_rows = jnp.concatenate(
            [jnp.full((QH, ), cached[i], jnp.int32) for i in range(sb)])
        feat_val = (feat_ref[...].astype(jnp.float32)
                    if (has_alibi or has_sinks) else None)
        if has_alibi:
            slope_rows = jnp.tile(feat_val[0], (sb, ))[:, None]

        def body(bi, carry):
            m_prev, l_prev, acc_prev = carry
            slot = jax.lax.rem(bi, 2)

            @pl.when(bi + 1 < num_blocks)
            def _prefetch():
                fetch(bi + 1, jax.lax.rem(bi + 1, 2))

            for i in range(sb):
                for j in range(ppb):
                    pltpu.make_async_copy(
                        k_cache.at[0, 0],
                        kbuf.at[slot, i, :,
                                pl.ds(j * page_size, page_size)],
                        kv_sems.at[slot, 0, i, j]).wait()
                    pltpu.make_async_copy(
                        v_cache.at[0, 0],
                        vbuf.at[slot, i, :,
                                pl.ds(j * page_size, page_size)],
                        kv_sems.at[slot, 1, i, j]).wait()
            k_all = kbuf[slot].reshape(C, hd)
            v_all = vbuf[slot].reshape(C, hd)
            s = jax.lax.dot_general(
                q_all, k_all.astype(jnp.float32),
                dimension_numbers=(((1, ), (1, )), ((), ())),
                preferred_element_type=jnp.float32)
            if logit_cap > 0:
                s = logit_cap * jnp.tanh(s / logit_cap)
            if has_alibi:
                s = s + slope_rows * (
                    bi * blk + col_off -
                    cached_rows[:, None]).astype(jnp.float32)
            mask = jnp.logical_and(
                diag, bi * blk + col_off < cached_rows[:, None])
            if window > 0:
                # q position is cached (== kv_len - 1) per sequence.
                mask = jnp.logical_and(
                    mask,
                    bi * blk + col_off > cached_rows[:, None] - window)
            s = jnp.where(mask, s, _MASK_VALUE)
            m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
            pr = jnp.exp(s - m_new)
            pr = jnp.where(mask, pr, 0.0)
            alpha = jnp.exp(m_prev - m_new)
            l_new = l_prev * alpha + pr.sum(axis=-1, keepdims=True)
            pv = jax.lax.dot_general(
                pr.astype(v_all.dtype), v_all,
                dimension_numbers=(((1, ), (0, )), ((), ())),
                preferred_element_type=jnp.float32)
            return m_new, l_new, acc_prev * alpha + pv

        init = (
            jnp.full((ROWS, 1), _MASK_VALUE, jnp.float32),
            jnp.zeros((ROWS, 1), jnp.float32),
            jnp.zeros((ROWS, hd), jnp.float32),
        )
        m_fin, l_fin, acc = jax.lax.fori_loop(0, num_blocks, body, init)

        # Fold the CURRENT token in register: one extra score column
        # per row against this program's freshly computed K/V rows.
        kexp = jnp.repeat(k.astype(jnp.float32), group,
                          axis=1).reshape(ROWS, hd)
        vexp = jnp.repeat(v.astype(jnp.float32), group,
                          axis=1).reshape(ROWS, hd)
        s_cur = jnp.sum(q_all * kexp, axis=-1, keepdims=True)
        if logit_cap > 0:
            s_cur = logit_cap * jnp.tanh(s_cur / logit_cap)
        # ALiBi distance is 0 for the current token; window always
        # admits it. Inactive slots mask to _MASK_VALUE.
        active_rows = jnp.concatenate([
            jnp.full((QH, ), base + i < num_seqs, jnp.bool_)
            for i in range(sb)
        ])[:, None]
        s_cur = jnp.where(active_rows, s_cur, _MASK_VALUE)
        m2 = jnp.maximum(m_fin, s_cur)
        alpha = jnp.exp(m_fin - m2)
        p_cur = jnp.where(active_rows, jnp.exp(s_cur - m2), 0.0)
        l2 = l_fin * alpha + p_cur
        acc2 = acc * alpha + p_cur * vexp
        if has_sinks:
            l2 = l2 + jnp.exp(jnp.tile(feat_val[1], (sb, ))[:, None] - m2)
        attn = (acc2 / jnp.maximum(l2, 1e-20)).astype(io_dtype)
        attn2d = attn.reshape(sb, Dq).astype(w_dtype)

        # ---- O-projection (double-buffered contraction tiles) -------
        acc_h = jnp.zeros((sb, H), jnp.float32)
        no = Dq // to

        def o_copy(t):
            return pltpu.make_async_copy(
                wo_hbm.at[pl.ds(t * to, to)], row_buf.at[t % 2],
                w_sems.at[1, t % 2])

        o_copy(0).start()
        for t in range(no):
            if t + 1 < no:
                o_copy(t + 1).start()
            o_copy(t).wait()
            acc_h = acc_h + jax.lax.dot_general(
                attn2d[:, t * to:(t + 1) * to], row_buf[t % 2],
                dimension_numbers=(((1, ), (0, )), ((), ())),
                preferred_element_type=jnp.float32)
        h1 = h0 + acc_h

        # ---- RMSNorm -> tile-fused gated MLP + residual -------------
        # gate/up/down consume ONE intermediate tile at a time; the
        # [sb, I] intermediate never exists outside this loop body.
        x2 = rms(h1, 1).astype(w_dtype)
        acc_mlp = jnp.zeros((sb, H), jnp.float32)
        ni = I // ti

        def mlp_copies(t):
            s = t % 2
            return (
                pltpu.make_async_copy(
                    wg_hbm.at[:, pl.ds(t * ti, ti)], wg_buf.at[s],
                    w_sems.at[2, s]),
                pltpu.make_async_copy(
                    wu_hbm.at[:, pl.ds(t * ti, ti)], wu_buf.at[s],
                    w_sems.at[3, s]),
                pltpu.make_async_copy(
                    wd_hbm.at[pl.ds(t * ti, ti)], wd_buf.at[s],
                    w_sems.at[4, s]),
            )

        for cp in mlp_copies(0):
            cp.start()
        for t in range(ni):
            if t + 1 < ni:
                # Prefetch the NEXT tile's gate/up/down trio into the
                # other slot while this tile's three matmuls run.
                for cp in mlp_copies(t + 1):
                    cp.start()
            s = t % 2
            cg, cu, cd = mlp_copies(t)
            cg.wait()
            cu.wait()
            g_t = jax.lax.dot_general(
                x2, wg_buf[s],
                dimension_numbers=(((1, ), (0, )), ((), ())),
                preferred_element_type=jnp.float32)
            u_t = jax.lax.dot_general(
                x2, wu_buf[s],
                dimension_numbers=(((1, ), (0, )), ((), ())),
                preferred_element_type=jnp.float32)
            gu_t = (jax.nn.silu(g_t) * u_t).astype(io_dtype)
            cd.wait()
            acc_mlp = acc_mlp + jax.lax.dot_general(
                gu_t.astype(w_dtype), wd_buf[s],
                dimension_numbers=(((1, ), (0, )), ((), ())),
                preferred_element_type=jnp.float32)
        h2 = h1 + acc_mlp

        # ---- writeback (active rows only; inactive rows keep their
        # aliased input values) ---------------------------------------
        out_stage[...] = h2.astype(io_dtype)
        for i in range(sb):
            @pl.when(base + i < num_seqs)
            def _wb(i=i):
                pltpu.make_async_copy(
                    out_stage.at[pl.ds(i, 1)],
                    out_hbm.at[pl.ds(q_starts[i], 1)],
                    out_sems.at[i]).start()
        for i in range(sb):
            @pl.when(base + i < num_seqs)
            def _wbw(i=i):
                pltpu.make_async_copy(
                    out_stage.at[pl.ds(i, 1)],
                    out_hbm.at[pl.ds(0, 1)], out_sems.at[i]).wait()


@functools.partial(
    jax.jit,
    static_argnames=("sm_scale", "eps", "num_q_heads", "head_dim",
                     "interpret", "window", "logit_cap", "has_alibi",
                     "has_sinks"))
def fused_block_decode_pallas(
    hidden: jax.Array,  # [T_pad, H]
    k_pages: jax.Array,  # [L, N, KVH, PS, D] stacked cache (aliased)
    v_pages: jax.Array,
    wqkv: jax.Array,  # [H, Dq + 2*Dkv] re-laid fused projection
    wo: jax.Array,  # [Dq, H]
    w_gate: jax.Array,  # [H, I]
    w_up: jax.Array,  # [H, I]
    w_down: jax.Array,  # [I, H]
    ln_w: jax.Array,  # [2, H]: input_ln, post_ln
    rope: jax.Array,  # [2, T_pad, head_dim] f32: cos, sin
    feat: jax.Array,  # [2, QH] f32: ALiBi slopes, sink logits
    seq_info: jax.Array,  # [R, 4] int32
    num_seqs: jax.Array,  # [1] int32
    block_tables: jax.Array,  # [max_reqs, pages_per_req] int32
    layer: jax.Array,  # [1] int32
    *,
    sm_scale: float,
    eps: float,
    num_q_heads: int,
    head_dim: int,
    interpret: bool | None = None,
    window: int = 0,
    logit_cap: float = 0.0,
    has_alibi: bool = False,
    has_sinks: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One fused transformer-block decode layer; returns
    (hidden, k_pages, v_pages) with all three updated in place via
    input/output aliasing. Decode-only contract: every active seq_info
    row has q_len == 1 and kv_len counting this step's token."""
    if interpret is None:
        interpret = envs.VDT_PALLAS_INTERPRET
    T_pad, H = hidden.shape
    L, N, KVH, PS, D = k_pages.shape
    assert D == head_dim, "lane-padded caches need the XLA path"
    QH = num_q_heads
    assert QH % KVH == 0
    group = QH // KVH
    Dq = QH * head_dim
    Dtot = Dq + 2 * KVH * head_dim
    I = w_gate.shape[1]
    R = seq_info.shape[0]
    pages_per_req = block_tables.shape[1]
    ppb = max(1, min(128 // PS, pages_per_req))
    while pages_per_req % ppb:
        ppb -= 1
    blk = ppb * PS

    sb = fused_block_group_size(QH, KVH, R)
    tq = weight_tile(Dtot)
    to = weight_tile(Dq)
    ti = weight_tile(I)
    grid = (pl.cdiv(R, sb), )

    kernel = functools.partial(
        _kernel, sm_scale=sm_scale, eps=eps, sb=sb, ppb=ppb,
        page_size=PS, group=group, tq=tq, to=to, ti=ti, window=window,
        logit_cap=logit_cap, has_alibi=has_alibi, has_sinks=has_sinks)

    any_spec = pl.BlockSpec(memory_space=pltpu.ANY)
    vmem_spec = pl.BlockSpec(memory_space=pltpu.VMEM)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=grid,
        in_specs=[
            any_spec,  # hidden
            any_spec,  # wqkv
            any_spec,  # wo
            any_spec,  # w_gate
            any_spec,  # w_up
            any_spec,  # w_down
            vmem_spec,  # ln_w
            any_spec,  # rope
            vmem_spec,  # feat
            any_spec,  # k_pages
            any_spec,  # v_pages
        ],
        out_specs=[any_spec, any_spec, any_spec],
        scratch_shapes=[
            pltpu.VMEM((sb, H), hidden.dtype),
            pltpu.VMEM((2, sb, head_dim), jnp.float32),
            # Weight streams carry TWO tile slots each (double
            # buffering): tile t+1 DMAs into slot (t+1)%2 while tile
            # t multiplies out of slot t%2.
            pltpu.VMEM((2, H, tq), wqkv.dtype),
            pltpu.VMEM((2, to, H), wo.dtype),
            pltpu.VMEM((2, H, ti), w_gate.dtype),
            pltpu.VMEM((2, H, ti), w_up.dtype),
            pltpu.VMEM((2, ti, H), w_down.dtype),
            pltpu.VMEM((2, sb, KVH, blk, D), k_pages.dtype),
            pltpu.VMEM((2, sb, KVH, blk, D), v_pages.dtype),
            pltpu.VMEM((KVH, PS, D), k_pages.dtype),
            pltpu.VMEM((KVH, PS, D), v_pages.dtype),
            pltpu.VMEM((sb, H), hidden.dtype),
            pltpu.SemaphoreType.DMA((sb, )),
            pltpu.SemaphoreType.DMA((2, sb)),
            pltpu.SemaphoreType.DMA((5, 2)),
            pltpu.SemaphoreType.DMA((2, 2, sb, ppb)),
            pltpu.SemaphoreType.DMA((2, )),
            pltpu.SemaphoreType.DMA((sb, )),
        ],
    )
    # Flat operand indices: 4 scalar-prefetch args, then hidden (4) ...
    # k_pages (13), v_pages (14) alias outputs 0, 1, 2.
    out, k2, v2 = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct(hidden.shape, hidden.dtype),
            jax.ShapeDtypeStruct(k_pages.shape, k_pages.dtype),
            jax.ShapeDtypeStruct(v_pages.shape, v_pages.dtype),
        ],
        input_output_aliases={4: 0, 13: 1, 14: 2},
        interpret=interpret,
    )(seq_info, num_seqs, layer, block_tables, hidden, wqkv, wo,
      w_gate, w_up, w_down, ln_w, rope, feat, k_pages, v_pages)
    return out, k2, v2


def fused_block_decode_xla(
    hidden, k_pages, v_pages, wqkv, wo, w_gate, w_up, w_down, ln_w,
    rope, feat, seq_info, num_seqs, block_tables, layer, *, sm_scale,
    eps, num_q_heads, head_dim, window=0, logit_cap=0.0,
    has_alibi=False, has_sinks=False,
):
    """XLA-composed correctness reference / non-Pallas fallback for the
    fused decode block: the same math built from the reference ops (the
    flat-scatter KV write and the XLA ragged attention), driven purely
    by seq_info. Used by the parity suite; the serving path only
    dispatches the fused block on the Pallas backend."""
    from vllm_distributed_tpu.models.common import rms_norm
    from vllm_distributed_tpu.ops.attention import (_scatter_kv_flat,
                                                    ragged_paged_attention)
    L, N, KVH, PS, D = k_pages.shape
    QH = num_q_heads
    R = seq_info.shape[0]
    io_dtype = hidden.dtype
    active = jnp.arange(R, dtype=jnp.int32) < num_seqs[0]
    kv_len = seq_info[:, 2]
    row = seq_info[:, 3]
    q_start = seq_info[:, 0]
    pos = jnp.maximum(kv_len - 1, 0)

    x = hidden[q_start]  # [R, H]
    xn = rms_norm(x, ln_w[0], eps)
    qkv = xn @ wqkv
    Dq = QH * head_dim
    Dkv = KVH * head_dim
    q = qkv[:, :Dq].reshape(R, QH, head_dim)
    k = qkv[:, Dq:Dq + Dkv].reshape(R, KVH, head_dim)
    v = qkv[:, Dq + Dkv:].reshape(R, KVH, head_dim)

    from vllm_distributed_tpu.models.common import apply_rope_single
    cos = rope[0][q_start]
    sin = rope[1][q_start]
    q = apply_rope_single(q.astype(jnp.float32), cos, sin).astype(io_dtype)
    k = apply_rope_single(k.astype(jnp.float32), cos, sin).astype(io_dtype)

    page = jnp.take_along_axis(block_tables[row],
                               (pos // PS)[:, None], axis=1)[:, 0]
    slot = jnp.where(active, page * PS + pos % PS, -1)
    k_pages, v_pages = _scatter_kv_flat(k_pages, v_pages, k, v, slot,
                                        layer, PS)

    slopes = tuple(
        float(s) for s in jax.device_get(feat[0])) if has_alibi else None
    sinks = feat[1].astype(jnp.float32) if has_sinks else None
    attn = ragged_paged_attention(
        q, k_pages[layer[0]], v_pages[layer[0]], block_tables, row, pos,
        sm_scale=sm_scale, window=window, logit_cap=logit_cap,
        alibi_slopes=slopes, sinks=sinks)
    attn = jnp.where(active[:, None, None], attn, 0)

    h1 = x.astype(jnp.float32) + (attn.reshape(R, Dq) @ wo).astype(
        jnp.float32)
    h1 = h1.astype(io_dtype)
    x2 = rms_norm(h1, ln_w[1], eps)
    gu = jax.nn.silu(x2 @ w_gate) * (x2 @ w_up)
    h2 = h1 + (gu @ w_down)

    hidden = hidden.at[jnp.where(active, q_start,
                                 hidden.shape[0])].set(h2, mode="drop")
    return hidden, k_pages, v_pages
