"""Fused dequantize-matmul Pallas kernel (w4a16 / w8a16 / fp8-w8a16).

Reference capability: csrc/quantization/gptq_marlin/ (marlin-class fused
dequant GEMM — the reference streams packed 4-bit weights from HBM and
dequantizes inside the GEMM pipeline so quantized decode beats fp16).
TPU-native re-design rather than a port:

* Decode matmuls are HBM-bound on the weight stream: [T<=64, K] x
  [K, N] reads K*N weight bytes once. Streaming int4 instead of bf16
  is a 4x traffic cut — IF the dequant never materializes a bf16 copy
  of the weight in HBM. This kernel keeps the packed payload all the
  way into VMEM and dequantizes tile-by-tile into the MXU:
  grid over N tiles; the K loop double-buffers packed weight blocks
  (DMA block k+1 while block k computes), converts int4/int8/fp8 ->
  bf16 in VMEM registers, applies the per-output-channel scale on the
  f32 accumulator once at the end.
* The activation tile [T, K] rides whole in VMEM (decode T is tiny).
* GSPMD cannot see through pallas_call, so the kernel serves the
  tp == 1 path; multi-chip keeps XLA's dequant-in-dot (the convert
  fuses into the sharded dot's operand load).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(x_vmem, scale_vmem, w_hbm, out_vmem, w_vmem, sems,
            *, bk: int, bn: int, dtype):
    """One N tile: out[:, n*bn:(n+1)*bn] = x @ dequant(w[:, tile])."""
    n = pl.program_id(0)
    K = x_vmem.shape[1]
    num_k = K // bk

    def fetch(k, slot):
        pltpu.make_async_copy(
            w_hbm.at[pl.ds(k * bk, bk), pl.ds(n * bn, bn)],
            w_vmem.at[slot], sems.at[slot]).start()

    fetch(0, 0)

    def body(k, acc):
        slot = jax.lax.rem(k, 2)

        @pl.when(k + 1 < num_k)
        def _prefetch():
            fetch(k + 1, jax.lax.rem(k + 1, 2))

        pltpu.make_async_copy(
            w_hbm.at[pl.ds(0, bk), pl.ds(0, bn)], w_vmem.at[slot],
            sems.at[slot]).wait()
        w_blk = w_vmem[slot].astype(dtype)  # dequant in VMEM regs
        x_blk = x_vmem[:, pl.ds(k * bk, bk)].astype(dtype)
        return acc + jax.lax.dot_general(
            x_blk, w_blk, (((1, ), (0, )), ((), ())),
            preferred_element_type=jnp.float32)

    acc = jax.lax.fori_loop(
        0, num_k, body,
        jnp.zeros((x_vmem.shape[0], bn), jnp.float32))
    out_vmem[...] = (acc * scale_vmem[0, pl.ds(n * bn, bn)][None, :]
                     ).astype(out_vmem.dtype)


def _gkernel(x_vmem, gs_vmem, gm_vmem, w_hbm, out_vmem, w_vmem, sems,
             *, bk: int, bn: int, dtype, g: int):
    """Group-wise variant: w = q * scale[group] + min[group] (uint4
    int4g payloads, GPTQ/AWQ group structure preserved)."""
    n = pl.program_id(0)
    K = x_vmem.shape[1]
    num_k = K // bk

    def fetch(k, slot):
        pltpu.make_async_copy(
            w_hbm.at[pl.ds(k * bk, bk), pl.ds(n * bn, bn)],
            w_vmem.at[slot], sems.at[slot]).start()

    fetch(0, 0)
    ng = bk // g

    def body(k, acc):
        slot = jax.lax.rem(k, 2)

        @pl.when(k + 1 < num_k)
        def _prefetch():
            fetch(k + 1, jax.lax.rem(k + 1, 2))

        pltpu.make_async_copy(
            w_hbm.at[pl.ds(0, bk), pl.ds(0, bn)], w_vmem.at[slot],
            sems.at[slot]).wait()
        w_blk = w_vmem[slot].astype(jnp.float32)  # [bk, bn]
        gs = gs_vmem[pl.ds(k * ng, ng), :]  # [ng, bn]
        gm = gm_vmem[pl.ds(k * ng, ng), :]
        wf = (w_blk.reshape(ng, g, bn) * gs[:, None, :] +
              gm[:, None, :]).reshape(bk, bn)
        x_blk = x_vmem[:, pl.ds(k * bk, bk)].astype(jnp.float32)
        return acc + jax.lax.dot_general(
            x_blk, wf, (((1, ), (0, )), ((), ())),
            preferred_element_type=jnp.float32)

    acc = jax.lax.fori_loop(
        0, num_k, body,
        jnp.zeros((x_vmem.shape[0], bn), jnp.float32))
    out_vmem[...] = acc.astype(out_vmem.dtype)


@functools.partial(jax.jit, static_argnames=("interpret", ))
def quant_matmul_grouped(x: jax.Array,  # [T, K]
                         w_q: jax.Array,  # [K, N] uint4
                         gscale: jax.Array,  # [G, N] f32
                         gmin: jax.Array,  # [G, N] f32
                         *, interpret: bool = False) -> jax.Array:
    """x @ (w_q * gscale[group] + gmin[group]); packed-bytes streaming
    with per-group dequant inside the pipeline."""
    T, K = x.shape
    _, N = w_q.shape
    G = gscale.shape[0]
    g = K // G
    bn = 128 if N % 128 == 0 else N
    bk = K
    for cand in (2048, 1024, 512, 256, 128):
        if K % cand == 0 and cand % g == 0:
            bk = cand
            break
    kernel = functools.partial(_gkernel, bk=bk, bn=bn, dtype=x.dtype,
                               g=g)
    grid = (N // bn, )
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=grid,
            in_specs=[
                pl.BlockSpec((T, K), lambda n: (0, 0)),
                pl.BlockSpec((G, bn), lambda n: (0, n)),
                pl.BlockSpec((G, bn), lambda n: (0, n)),
                pl.BlockSpec(memory_space=pltpu.ANY),
            ],
            out_specs=pl.BlockSpec((T, bn), lambda n: (0, n)),
            scratch_shapes=[
                pltpu.VMEM((2, bk, bn), w_q.dtype),
                pltpu.SemaphoreType.DMA((2, )),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((T, N), x.dtype),
        interpret=interpret,
    )(x, gscale, gmin, w_q)


@functools.partial(jax.jit, static_argnames=("interpret", ))
def quant_matmul(x: jax.Array,  # [T, K] activations (bf16/f32)
                 w_q: jax.Array,  # [K, N] int4 | int8 | float8_e4m3fn
                 scale: jax.Array,  # [1, N] f32 per-output-channel
                 *, interpret: bool = False) -> jax.Array:
    """x @ (w_q * scale) streaming only packed weight bytes from HBM."""
    T, K = x.shape
    _, N = w_q.shape
    bn = 128 if N % 128 == 0 else N
    # K block: big enough to amortize DMA latency, small enough that two
    # slots of packed payload + the bf16 dequant tile stay comfortably
    # in VMEM.
    bk = K
    for cand in (2048, 1024, 512, 256, 128):
        if K % cand == 0:
            bk = cand
            break
    kernel = functools.partial(_kernel, bk=bk, bn=bn, dtype=x.dtype)
    grid = (N // bn, )
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0,
            grid=grid,
            in_specs=[
                pl.BlockSpec((T, K), lambda n: (0, 0)),  # x in VMEM
                pl.BlockSpec((1, N), lambda n: (0, 0)),  # scales
                pl.BlockSpec(memory_space=pltpu.ANY),  # packed weights
            ],
            out_specs=pl.BlockSpec((T, bn), lambda n: (0, n)),
            scratch_shapes=[
                pltpu.VMEM((2, bk, bn), w_q.dtype),
                pltpu.SemaphoreType.DMA((2, )),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((T, N), x.dtype),
        interpret=interpret,
    )(x, scale, w_q)
