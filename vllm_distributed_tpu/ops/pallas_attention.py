"""Pallas ragged paged attention kernel for TPU.

TPU-native replacement for the reference's paged-attention CUDA kernels
(csrc/attention/paged_attention_v{1,2}.cu) and the torch_xla
ragged_paged_attention op its TPU backend calls
(vllm/v1/attention/backends/pallas.py:232). Re-designed for Pallas rather
than translated:

* Grid ``(seq, q_tile)``; each program runs the whole flash-attention
  loop over that sequence's KV pages as a dynamic-trip-count
  ``fori_loop`` (decode cost is O(kv_len), not O(max_model_len)), with
  online-softmax accumulators as loop carries.
* Per-sequence metadata (q_start, q_len, kv_len, batch row) is
  scalar-prefetched into SMEM; KV pages are gathered from HBM by manual
  async DMA using page ids read from the prefetched block table (the
  paging side of csrc/attention is pure DMA here).
* Mixed prefill/decode in one call: each sequence brings q_len query rows
  (1 for decode, up to max_q for a chunked-prefill step).
* Mosaic-friendly compute: the KV cache page layout is head-major
  [page, kv_head, page_size, head_dim] so each page DMAs into a
  contiguous [kv_head, block, head_dim] VMEM block; scores are 2-D
  matmuls per kv head (GQA queries of a group fold into rows), avoiding
  batched dots and sub-tile DMA slices entirely.

Layout contract with the model runner:

* Token arrays are the flat ragged batch; each sequence's q rows are
  contiguous, sequence runs are back-to-back in run order r = 0..num_seqs.
* ``q`` and the returned output have at least ``q_tile`` padding rows at
  the end: a sequence's final tile may spill past its q_len; spilled rows
  of sequence r are garbage but are rewritten by sequence r+1's own tile
  flush (the TPU grid executes sequentially in order), and the last
  sequence spills into the padding rows.
* ``seq_info[r] = (q_start, q_len, kv_len, batch_row)``; ``kv_len``
  includes tokens written this step. ``block_tables[batch_row]`` holds the
  page ids (rows are input-batch rows, indirected through batch_row).
* ``page_size`` must be a multiple of 8 (sublane tiling of the DMA
  destination slices).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from vllm_distributed_tpu import envs

_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


def _kernel(
    # scalar prefetch
    seq_info_ref,  # [R, 4] int32: q_start, q_len, kv_len, batch_row
    num_seqs_ref,  # [1] int32
    layer_ref,  # [1] int32
    block_tables_ref,  # [max_reqs, pages_per_req] int32
    # tensor inputs (HBM)
    q_hbm,  # [T_pad, QH, D]
    k_hbm,  # [L, num_pages, KVH, PS, D] (full stacked cache)
    v_hbm,
    # outputs (HBM): out_hbm, then state_hbm when emit_state
    *refs,
    sm_scale: float,
    bq: int,
    ppb: int,
    page_size: int,
    group: int,
    emit_state: bool,
):
    if emit_state:
        (out_hbm, state_hbm, q_vmem, k_vmem, v_vmem, out_stage,
         state_stage, q_sem, kv_sems, out_sem, state_sem) = refs
    else:
        (out_hbm, q_vmem, k_vmem, v_vmem, out_stage, q_sem, kv_sems,
         out_sem) = refs
        state_hbm = state_stage = state_sem = None
    r = pl.program_id(0)
    qt = pl.program_id(1)

    q_start = seq_info_ref[r, 0]
    q_len = seq_info_ref[r, 1]
    kv_len = seq_info_ref[r, 2]
    row = seq_info_ref[r, 3]
    num_seqs = num_seqs_ref[0]
    layer = layer_ref[0]
    num_q_heads = q_vmem.shape[1]
    num_kv_heads = k_vmem.shape[0]
    head_dim = q_vmem.shape[2]

    blk = ppb * page_size
    tile_start = qt * bq
    # Absolute position of the last query row in this tile; kv blocks past
    # it are causally invisible and never fetched.
    q_pos_max = kv_len - q_len + jnp.minimum(tile_start + bq, q_len) - 1
    active = jnp.logical_and(
        r < num_seqs,
        jnp.logical_and(tile_start < q_len, kv_len > 0))

    @pl.when(active)
    def _run():
        # Whole q tile in one leading-dim DMA (token rows are the major
        # axis; head/lane dims stay intact — Mosaic constrains sub-tile
        # slicing of the minor two dims).
        q_dma = pltpu.make_async_copy(
            q_hbm.at[pl.ds(q_start + tile_start, bq)], q_vmem, q_sem)
        q_dma.start()
        num_blocks = q_pos_max // blk + 1
        q_dma.wait()

        q_tile = q_vmem[...].astype(jnp.float32) * sm_scale  # [BQ, QH, D]
        if bq == 1:
            # Decode: rows are heads; group slices are leading-dim slices.
            q_flat = q_tile.reshape(num_q_heads, head_dim)
            q_heads = [
                q_flat[h * group:(h + 1) * group]
                for h in range(num_kv_heads)
            ]
        else:
            q_heads = [
                q_tile[:, h * group:(h + 1) * group, :].reshape(
                    bq * group, head_dim) for h in range(num_kv_heads)
            ]
        rows = bq * group

        row_pos = (kv_len - q_len + tile_start +
                   jax.lax.broadcasted_iota(jnp.int32, (rows, blk), 0) //
                   group)
        col_base = jax.lax.broadcasted_iota(jnp.int32, (rows, blk), 1)
        row_valid = (jax.lax.broadcasted_iota(jnp.int32, (rows, blk), 0) //
                     group + tile_start) < q_len

        def body(b, carry):
            ms, ls, accs = carry
            kv_start = b * blk
            for i in range(ppb):
                page_id = block_tables_ref[row, b * ppb + i]
                pltpu.make_async_copy(
                    k_hbm.at[layer, page_id],
                    k_vmem.at[:, pl.ds(i * page_size, page_size)],
                    kv_sems.at[0, i]).start()
                pltpu.make_async_copy(
                    v_hbm.at[layer, page_id],
                    v_vmem.at[:, pl.ds(i * page_size, page_size)],
                    kv_sems.at[1, i]).start()
            for i in range(ppb):
                pltpu.make_async_copy(
                    k_hbm.at[0, 0],
                    k_vmem.at[:, pl.ds(i * page_size, page_size)],
                    kv_sems.at[0, i]).wait()
                pltpu.make_async_copy(
                    v_hbm.at[0, 0],
                    v_vmem.at[:, pl.ds(i * page_size, page_size)],
                    kv_sems.at[1, i]).wait()

            kv_pos = kv_start + col_base
            mask = jnp.logical_and(kv_pos <= row_pos, row_valid)

            new_ms, new_ls, new_accs = [], [], []
            for h in range(num_kv_heads):
                k_h = k_vmem[h]  # [BLK, D]
                v_h = v_vmem[h]
                s = jax.lax.dot_general(
                    q_heads[h], k_h.astype(jnp.float32),
                    dimension_numbers=(((1, ), (1, )), ((), ())),
                    preferred_element_type=jnp.float32)  # [rows, BLK]
                s = jnp.where(mask, s, _MASK_VALUE)
                m_prev, l_prev, acc_prev = ms[h], ls[h], accs[h]
                m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
                p = jnp.exp(s - m_new)
                alpha = jnp.exp(m_prev - m_new)
                l_new = l_prev * alpha + p.sum(axis=-1, keepdims=True)
                pv = jax.lax.dot_general(
                    p.astype(v_h.dtype), v_h,
                    dimension_numbers=(((1, ), (0, )), ((), ())),
                    preferred_element_type=jnp.float32)  # [rows, D]
                acc_new = acc_prev * alpha + pv
                new_ms.append(m_new)
                new_ls.append(l_new)
                new_accs.append(acc_new)
            return tuple(new_ms), tuple(new_ls), tuple(new_accs)

        init = (
            tuple(
                jnp.full((rows, 1), _MASK_VALUE, jnp.float32)
                for _ in range(num_kv_heads)),
            tuple(
                jnp.zeros((rows, 1), jnp.float32)
                for _ in range(num_kv_heads)),
            tuple(
                jnp.zeros((rows, head_dim), jnp.float32)
                for _ in range(num_kv_heads)),
        )
        ms, ls, accs = jax.lax.fori_loop(0, num_blocks, body, init)

        half = head_dim // 2
        for h in range(num_kv_heads):
            o_h = accs[h] / jnp.maximum(ls[h], 1e-20)  # [rows, D]
            if bq == 1:
                out_stage[0, h * group:(h + 1) * group, :] = (
                    o_h.astype(out_stage.dtype))
            else:
                out_stage[:, h * group:(h + 1) * group, :] = (
                    o_h.reshape(bq, group, head_dim).astype(
                        out_stage.dtype))
            if emit_state:
                # Online-softmax partial state for exact merging with
                # another KV range (cascade): m broadcast over the low
                # lanes, l over the high — lane-sliced out by the
                # caller. Full-D staging keeps the DMA tile-aligned.
                st = jnp.concatenate([
                    jnp.broadcast_to(ms[h], (rows, half)),
                    jnp.broadcast_to(ls[h], (rows, head_dim - half)),
                ], axis=-1)
                if bq == 1:
                    state_stage[0, h * group:(h + 1) * group, :] = st
                else:
                    state_stage[:, h * group:(h + 1) * group, :] = (
                        st.reshape(bq, group, head_dim))
        out_dma = pltpu.make_async_copy(
            out_stage, out_hbm.at[pl.ds(q_start + tile_start, bq)],
            out_sem)
        out_dma.start()
        if emit_state:
            st_dma = pltpu.make_async_copy(
                state_stage,
                state_hbm.at[pl.ds(q_start + tile_start, bq)], state_sem)
            st_dma.start()
            st_dma.wait()
        out_dma.wait()


@functools.partial(
    jax.jit,
    static_argnames=("sm_scale", "max_q", "interpret", "emit_state"))
def ragged_paged_attention_pallas(
    q: jax.Array,  # [T_pad, QH, D]; T_pad >= T + q_tile padding
    k_pages: jax.Array,  # [L, num_pages, KVH, PS, D] full stacked cache
    v_pages: jax.Array,
    seq_info: jax.Array,  # [R, 4] int32 (q_start, q_len, kv_len, row)
    num_seqs: jax.Array,  # [1] int32
    block_tables: jax.Array,  # [max_reqs, pages_per_req] int32
    layer: jax.Array | None = None,  # [1] int32
    *,
    sm_scale: float,
    max_q: int,
    interpret: bool | None = None,
    emit_state: bool = False,
):
    """Unified prefill/decode attention over the paged KV cache.

    ``max_q`` is the static per-sequence query bucket (1 for pure decode).
    The cache keeps its stacked layer dim; ``layer`` selects the slice to
    read (pages are DMA'd as [layer, page] — no layer copy materializes).
    Returns [T_pad, QH, D]; rows past each sequence's q_len are garbage.

    ``emit_state=True`` additionally returns the online-softmax partial
    state as an f32 [T_pad, QH, D] array with the row max broadcast over
    lanes [0, D/2) and the exp-sum over [D/2, D) — what cascade needs to
    merge this call's KV range with a shared-prefix phase exactly
    (reference: csrc/attention/merge_attn_states.cu exports the same
    (max, sumexp) pair).
    """
    if interpret is None:
        interpret = envs.VDT_PALLAS_INTERPRET
    if k_pages.ndim == 4:
        # Single-layer convenience form (tests).
        k_pages = k_pages[None]
        v_pages = v_pages[None]
    if layer is None:
        layer = jnp.zeros((1, ), jnp.int32)
    T_pad, num_q_heads, head_dim = q.shape
    _, num_pages, num_kv_heads, page_size, _ = k_pages.shape
    assert num_q_heads % num_kv_heads == 0
    group = num_q_heads // num_kv_heads
    R = seq_info.shape[0]
    pages_per_req = block_tables.shape[1]

    bq = min(max_q, 128)
    # Keep the per-program footprint (q/out staging, f32 accumulators and
    # their loop-carry double buffers, per-head score tiles) inside the
    # ~16MB VMEM budget: shrink the q tile for wide-head models.
    while bq > 8 and bq * num_q_heads * head_dim * 32 > 12 * 1024**2:
        bq //= 2
    num_q_tiles = pl.cdiv(max_q, bq)
    assert T_pad >= bq, "q must be padded to at least one tile"
    # ~128 kv positions per block, at least one page.
    ppb = max(1, min(128 // page_size, pages_per_req))
    while pages_per_req % ppb:
        ppb -= 1
    blk = ppb * page_size

    grid = (R, num_q_tiles)
    kernel = functools.partial(
        _kernel, sm_scale=sm_scale, bq=bq, ppb=ppb, page_size=page_size,
        group=group, emit_state=emit_state)

    scratch = [
        pltpu.VMEM((bq, num_q_heads, head_dim), q.dtype),
        pltpu.VMEM((num_kv_heads, blk, head_dim), k_pages.dtype),
        pltpu.VMEM((num_kv_heads, blk, head_dim), v_pages.dtype),
        pltpu.VMEM((bq, num_q_heads, head_dim), q.dtype),
    ]
    out_shape = [jax.ShapeDtypeStruct(q.shape, q.dtype)]
    out_specs = [pl.BlockSpec(memory_space=pltpu.ANY)]
    if emit_state:
        scratch.append(
            pltpu.VMEM((bq, num_q_heads, head_dim), jnp.float32))
        out_shape.append(
            jax.ShapeDtypeStruct(q.shape, jnp.float32))
        out_specs.append(pl.BlockSpec(memory_space=pltpu.ANY))
    scratch += [
        pltpu.SemaphoreType.DMA(()),
        pltpu.SemaphoreType.DMA((2, ppb)),
        pltpu.SemaphoreType.DMA(()),
    ]
    if emit_state:
        scratch.append(pltpu.SemaphoreType.DMA(()))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.ANY),  # q
            pl.BlockSpec(memory_space=pltpu.ANY),  # k_pages
            pl.BlockSpec(memory_space=pltpu.ANY),  # v_pages
        ],
        out_specs=out_specs,
        scratch_shapes=scratch,
    )
    result = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(seq_info, num_seqs, layer, block_tables, q, k_pages, v_pages)
    if emit_state:
        return tuple(result)
    return result[0]
